"""Benchmark harness. Default config: DeepFM CTR end-to-end (driver metric).

Measures the FULL training path the way production runs it — native text
parse -> columnar load -> per-batch host key map -> fused device step
(pull / fwd-bwd / dense+sparse update / AUC) — streaming DISTINCT batches
drawn from a >=50M-feature store, and prints ONE json line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

vs_baseline is measured against this repo's own previously-recorded number
for the same metric on the same hardware (BASELINE.md "measured" table —
the reference publishes no numbers, so the baseline is our prior round;
>1.0 means this round is faster). Extra keys break the e2e number down
(load / host-map / device) and report the device-only upper bound.

Other configs (BASELINE.md configs 1-3): `python bench.py resnet50`,
`python bench.py bert_dp`, `python bench.py gpt`.
"""

import json
import os
import sys
import tempfile
import time
from functools import partial

import numpy as np

# PBX_BENCH_SCALE=small = CPU smoke run of the full harness path (never
# for recorded numbers): pin the CPU platform BEFORE jax initializes a
# backend (the axon sitecustomize imports jax at startup, so the env var
# alone is not enough — same workaround as tests/conftest.py) and shrink
# every config below.
_SMALL = os.environ.get("PBX_BENCH_SCALE") == "small"

# ---------------------------------------------------------------------------
# Stall watchdog. The axon TPU tunnel can wedge mid-run (observed
# 2026-07-31: a device call blocked on the tunnel socket for 30+ min with
# zero progress) — and a bench that hangs forever records NOTHING for the
# round. The heartbeat machinery lives in core/watchdog.py (the library
# version the day loop also arms); bench keeps only its own stall
# POLICY: a parseable failure JSON + hard exit, and the two-tier limit —
# a DEAD tunnel shows up in the very first device round-trip, so until
# one _sync succeeds the limit is short (PBX_BENCH_WATCHDOG_EARLY_S, 240
# — a dead-tunnel run fails structured in <5 min); after the backend has
# proven alive it relaxes (PBX_BENCH_WATCHDOG_S, 900) so a long mid-run
# compile is not a false positive. The monitor also emits a stderr
# heartbeat every 30 s naming the current phase. Armed before the jax
# import: backend init itself can hang. (core.watchdog imports no jax.)
# ---------------------------------------------------------------------------

# Importing the library watchdog pulls in the package __init__ (which
# imports jax) — cover THAT window with a bare-threading import guard so
# a hung jax import still fails structured, as the pre-library watchdog
# did.
_IMPORT_GUARD = {"done": False}


def _import_guard() -> None:
    t0 = time.monotonic()
    limit = float(os.environ.get("PBX_BENCH_WATCHDOG_EARLY_S", "240"))
    while not _IMPORT_GUARD["done"]:
        if time.monotonic() - t0 > limit:
            name = sys.argv[1] if len(sys.argv) > 1 else "deepfm"
            print(json.dumps({
                "metric": f"{name}_FAILED", "value": 0.0, "unit": "none",
                "vs_baseline": None,
                "error": f"watchdog: package/jax import hung for "
                         f"{limit:.0f}s"}), flush=True)
            os._exit(3)
        time.sleep(5)


if os.environ.get("PBX_BENCH_WATCHDOG", "1") != "0":
    import threading
    threading.Thread(target=_import_guard, daemon=True).start()

from paddlebox_tpu.core.watchdog import Watchdog  # noqa: E402

_IMPORT_GUARD["done"] = True
_WD = {"device_alive": False, "trace": None, "wd": None}


def _on_bench_stall(phase: str, idle: float) -> None:
    name = sys.argv[1] if len(sys.argv) > 1 else "deepfm"
    # Stall forensics (the r05 lesson: "no progress in phase
    # 'device-probe'" with nothing else is undiagnosable): every
    # thread's Python stack + the trace ring tail ride in the failure
    # JSON, so the post-mortem names the frame blocked on the tunnel,
    # not just the phase.
    try:
        from paddlebox_tpu.core.trace import stall_forensics
        tail = stall_forensics()
    except Exception as e:  # noqa: BLE001 - keep the record
        tail = {"error": f"forensics unavailable: {e!r}"}
    print(json.dumps({
        "metric": f"{name}_FAILED",
        "value": 0.0,
        "unit": "none",
        "vs_baseline": None,
        "error": (f"watchdog: no progress in phase {phase!r} for "
                  f"{idle:.0f}s — device backend stall (axon tunnel?)"),
        "tail": tail,
    }, default=str), flush=True)
    os._exit(3)


def _tick(phase: str) -> None:
    wd = _WD["wd"]
    if wd is not None:
        wd.beat(phase)
    tr = _WD["trace"]
    if tr is not None and tr.enabled:
        # Phase transitions land in the span-tracer ring, so a stall
        # dump's trace_tail shows the path INTO the hung phase.
        tr.instant("bench/" + phase)


if os.environ.get("PBX_BENCH_WATCHDOG", "1") != "0":
    _WD["wd"] = Watchdog(
        float(os.environ.get("PBX_BENCH_WATCHDOG_EARLY_S", "240")),
        name="bench", on_stall=_on_bench_stall, poll_s=5.0,
        heartbeat_s=30.0)
    _WD["wd"].arm(phase="import-jax")

# Persistent compilation cache: a bench retry (the recorder retries once,
# and the driver may run multiple configs) must not re-pay multi-minute
# compiles over the flaky tunnel — cached executables make every attempt
# after the first cheap. (core.flags imports no jax; safe pre-import.)
from paddlebox_tpu.core import flags
from paddlebox_tpu.core import report as _report
from paddlebox_tpu.core import trace as _trace
from paddlebox_tpu.core.flags import enable_compilation_cache

_CACHE_DIR = enable_compilation_cache()

# Telemetry: arm the flag-configured sinks (FLAGS_trace_path /
# FLAGS_metrics_path), then ALWAYS keep the span-tracer ring on for the
# bench — phases and pass spans cost ~1 µs each here, and they are the
# watchdog's stall-forensics timeline (ring-only: no file is written
# unless FLAGS_trace_path asks for one).
_report.init_telemetry_from_flags()
_trace.GLOBAL.enable()
_WD["trace"] = _trace.GLOBAL

import jax

if _SMALL:
    jax.config.update("jax_platforms", "cpu")
# The axon sitecustomize imports jax before this file runs, so the env
# default above can land after jax froze its config — set it through the
# config API too (no-op when the env already took effect).
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
_tick("post-import")


def _sync(x) -> float:
    """Force completion by fetching the value — on the axon remote-TPU
    platform jax.block_until_ready returns before the dispatched chain
    finishes, so timing loops MUST fetch a concrete value."""
    v = float(np.asarray(x).ravel()[0])
    _tick("sync")
    if not _WD["device_alive"]:
        # Backend proven alive: relax the watchdog to the late tier.
        _WD["device_alive"] = True
        if _WD["wd"] is not None:
            _WD["wd"].set_timeout(
                float(os.environ.get("PBX_BENCH_WATCHDOG_S", "900")))
    return v


# Previously recorded numbers for vs_baseline ratios (BASELINE.md
# "measured" table; update when a new round records a number on the same
# hardware).
SELF_BASELINE = {
    # Round-2 honest E2E measurement (v5e single chip via axon),
    # BENCH_r02.json @ commit fb99701.
    "deepfm_e2e": 8587.0,          # samples/s/chip
    # Not yet recorded on the bench chip -> vs_baseline reports null.
    "resnet50": None,
    "bert_dp": None,
    "gpt": None,
    "wide_deep": None,
    "graph_walk": None,
    "serving": None,
    "online": None,
}

# First-recorded numbers (tools/record_baselines.py writes them as soon
# as a bench config lands on the real chip) fill metrics that have no
# hand-recorded baseline yet — never overriding an existing prior-round
# value, so vs_baseline stays a cross-round ratio where one exists.
try:
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BASELINE_MEASURED.json")) as _f:
        for _k, _v in json.load(_f).items():
            if SELF_BASELINE.get(_k) is None:
                SELF_BASELINE[_k] = _v
except (OSError, ValueError):
    pass


def _vs(metric: str, value: float):
    """Ratio vs our prior recorded number; None (JSON null) when no
    baseline exists yet — 1.0 would misread as 'exactly at baseline'."""
    base = SELF_BASELINE.get(metric)
    return round(value / base, 4) if base else None


# Per-chip peak for MFU accounting: TPU v5e bf16 = 197 TFLOP/s
# (PALLAS_AXON_TPU_GEN=v5e on this rig). Override for other parts.
PEAK_FLOPS = float(os.environ.get("PBX_TPU_PEAK_FLOPS", 197e12))


def _mfu(model_flops_per_s: float) -> float:
    """Model-FLOPs utilization vs the bf16 peak — the
    analytically-required FLOPs (not hardware-counter FLOPs), so remat
    recompute does not inflate it."""
    return round(model_flops_per_s / PEAK_FLOPS, 4)


# ---------------------------------------------------------------------------
# DeepFM CTR end-to-end (BASELINE.md config 4; the driver's default metric)
# ---------------------------------------------------------------------------

NUM_SLOTS = 26
EMB_DIM = 16
# Wide&Deep (bench_wide_deep) shape constants — module-level so the
# scatter preflight probes the SAME shapes the bench will compile.
WIDE_DEEP_EMB_DIM = 8
WIDE_DEEP_SLOTS = 20
WIDE_DEEP_BATCH = 8192
WIDE_DEEP_PASS_KEYS = 1_000_000
DENSE_DIM = 13
BATCH = 16384
STORE_KEYS = 50_000_000       # resident feature store size
PASS_KEYS = 4_000_000         # working set one pass touches
# Distinct timed batches: a real online pass trains minutes of traffic
# against one table build + write-back, so the per-pass fixed costs
# (feed_pass build, end_pass write-back) must amortize over a realistic
# batch count or the bench mis-states steady-state throughput.
N_BATCHES = 64

if _SMALL:
    BATCH = 1024
    STORE_KEYS = 1_000_000
    PASS_KEYS = 100_000
    N_BATCHES = 4
    # Ratios vs full-scale recordings would be meaningless noise.
    for _k in SELF_BASELINE:
        SELF_BASELINE[_k] = None


def _prepopulate_store(trainer, n_keys: int, chunk: int = 10_000_000) -> float:
    """Fill the backing store with n_keys initialized features (setup for a
    realistic pull: the pass working set hits a populated store). Returns
    build throughput in keys/s (index insert + value init — the
    PreBuildTask/BuildGPUTask role)."""
    eng = trainer.engine.groups[0].engine
    t0 = time.perf_counter()
    if hasattr(eng.store, "ensure_rows"):
        # Device tier: host index insert + on-device init; values never
        # cross the host boundary.
        for lo in range(1, n_keys + 1, chunk):
            keys = np.arange(lo, min(lo + chunk, n_keys + 1),
                             dtype=np.uint64)
            eng.store.ensure_rows(keys)
            _tick(f"prepopulate:{lo}")
        # Include device completion in the timing.
        jax.block_until_ready(eng.store._parts)
        np.asarray(eng.store._parts[0][:1, :1])
        _tick("prepopulate:done")
    else:
        for lo in range(1, n_keys + 1, chunk):
            keys = np.arange(lo, min(lo + chunk, n_keys + 1),
                             dtype=np.uint64)
            vals = eng.store.pull_for_pass(keys)  # materializes init
            eng.store.push_from_pass(keys, vals)
            _tick(f"prepopulate:{lo}")
    return n_keys / (time.perf_counter() - t0)


def _bench_host_index(n_keys: int) -> float:
    """Pure host-side pass-build throughput: fresh upsert of n_keys into
    the native incremental index. Separate from _prepopulate_store,
    whose number includes on-device row init; the measurement itself is
    the SHARED bench_index_build (one methodology with
    tools/bench_native_store.py)."""
    from paddlebox_tpu.native.store_py import bench_index_build
    return bench_index_build(n_keys,
                             tick=lambda lo: _tick(f"host_index:{lo}"))


def _native_available() -> bool:
    from paddlebox_tpu.native.build import native_available
    return bool(native_available())


def _bench_host_index_bulk(n_keys: int) -> float:
    """Sorted-run store build (round 13): per-chunk dedup → run merge →
    KeyIndex.bulk_build, same keys/chunking/tick as _bench_host_index so
    the two rates stay methodology-comparable (the r02 number was the
    incremental upsert walk)."""
    from paddlebox_tpu.native.store_py import bench_index_build
    return bench_index_build(n_keys, mode="bulk",
                             tick=lambda lo: _tick(f"host_index_bulk:{lo}"))


def _planted_labels(rng, hot_ids: np.ndarray, *, target_rate: float = 0.25,
                    strength: float = 2.0) -> np.ndarray:
    """Labels from a PLANTED sparse signal: each hot key carries a latent
    ±1 weight (a hash of the key), the sample logit is that weight scaled
    by ``strength`` plus the base-rate offset, and labels are Bernoulli
    in that logit. A learner that recovers per-key weights (exactly what
    the sparse w/embedding path trains) must pull AUC well above 0.5
    within a pass — random labels would mask sign/aliasing bugs that
    parity tests can't see (an embedding served to the wrong row still
    produces 0.5 AUC on random labels, never on planted ones). Role of
    the AUC discipline around metrics.cc:286-355."""
    h = (hot_ids * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(32)
    sign = (h & np.uint64(1)).astype(np.float32) * 2.0 - 1.0   # ±1 per key
    logit = sign * strength + np.log(target_rate / (1.0 - target_rate))
    p = 1.0 / (1.0 + np.exp(-logit))
    return (rng.random(hot_ids.shape[0]) < p).astype(np.int32)


def _gen_pass_files(tmpdir: str, rng, pass_keys: np.ndarray,
                    n_batches: int, *, batch: int = None,
                    n_slots: int = None, dense_dim: int = None,
                    label_rate: float = 0.25,
                    planted_hot: int = 1000,
                    zipf_a: float = None) -> list:
    """Write n_batches*batch svm-format lines across part files (one per
    batch). Slot 0 draws from a HOT head of ``planted_hot`` keys (the
    Zipf head every real CTR stream has — each hot key repeats
    batch*n_batches/planted_hot times, enough for the in-pass optimizer
    to recover its planted weight); the label carries that key's planted
    signal (_planted_labels). Remaining slots draw uniformly from the
    full working set when ``zipf_a`` is None, else Zipf(zipf_a)-ranked
    over it (head-heavy, duplication 2-5x at a~1.2) — the cold tail
    that sizes the store/pass machinery.
    Vectorized string assembly (np.char): a per-line Python loop takes
    minutes at 1M+ lines on one core."""
    batch = BATCH if batch is None else batch
    n_slots = NUM_SLOTS if n_slots is None else n_slots
    dense_dim = DENSE_DIM if dense_dim is None else dense_dim
    hot = pass_keys[:min(planted_hot, pass_keys.size)]
    files = []
    for b in range(n_batches):
        if zipf_a is not None:
            # Zipf-ranked draws over the working set — the head-heavy
            # key distribution every real CTR stream has (and what makes
            # dedup + measured capacity pay: duplication is 2-5x at
            # a~1.2 instead of the uniform draw's ~1.0).
            ranks = (rng.zipf(zipf_a, (batch, n_slots)).astype(np.int64)
                     - 1) % pass_keys.size
            ids = pass_keys[ranks]
        else:
            ids = rng.choice(pass_keys, (batch, n_slots))
        ids[:, 0] = rng.choice(hot, batch)
        labels = _planted_labels(rng, ids[:, 0], target_rate=label_rate)
        line = labels.astype("U1")
        for j in range(n_slots):
            line = np.char.add(line, f" s{j}:")
            line = np.char.add(line, ids[:, j].astype("U20"))
        if dense_dim:
            dense = (rng.random((batch, dense_dim)) * 10000).astype(np.int32)
            line = np.char.add(line, " d:0.")
            line = np.char.add(line, dense[:, 0].astype("U5"))
            for j in range(1, dense_dim):
                line = np.char.add(line, ",0.")
                line = np.char.add(line, dense[:, j].astype("U5"))
        path = os.path.join(tmpdir, f"part-{b:05d}")
        with open(path, "w") as f:
            f.write("\n".join(line.tolist()) + "\n")
        files.append(path)
    return files


def _bench_pull_push(trainer, tables, rows, iters=10):
    """Isolated (pull_ms, push_ms) for width group 0 on the live pass
    tables: jitted shard_map'd pull_local / push_local at the bench's
    real shapes. pull_ms is the op FLAGS_sparse_gather_kernel attacks
    (the last XLA gather of the CTR step), push_ms the one
    FLAGS_sparse_scatter_kernel already converted — recording both keys
    keeps the pull-side win visible in the artifact even when only CPU
    smoke runs are possible. Standalone (unshared-layout) timings: each
    side pays its own bucketing/sort here, so the fused step's total is
    below pull_ms + push_ms."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlebox_tpu.embedding.lookup import make_pull_fn, push_local

    table0, r0 = tables[0], rows[0]
    d = table0.dim
    n = int(r0.shape[0])
    axis = trainer.axis
    sh = NamedSharding(trainer.mesh, P(axis))

    def timed(thunk):
        out = thunk()                       # compile + warm
        _sync(jax.tree_util.tree_leaves(out)[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = thunk()
        _sync(jax.tree_util.tree_leaves(out)[0])
        return (time.perf_counter() - t0) / iters * 1e3

    _tick("deepfm:pull_push_breakdown")
    pull_fn = make_pull_fn(trainer.mesh, axis)
    pull_ms = timed(lambda: pull_fn(table0, r0))

    opt = trainer.sparse_opt

    # Deliberately NOT donating the table: the timed pass still trains
    # on these buffers; the copy is the price of a non-destructive probe.
    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=trainer.mesh,
        in_specs=(P(axis),) * 6, out_specs=P(axis), check_vma=False)
    def push_fn(table, dev_rows, ge, gw, sh_, ck):
        return push_local(table, dev_rows, ge, gw, sh_, ck, axis=axis,
                          opt=opt)

    ge = jax.device_put(np.zeros((n, d), np.float32), sh)
    gs = jax.device_put(np.zeros((n,), np.float32), sh)
    push_ms = timed(lambda: push_fn(table0, r0, ge, gs, gs, gs))
    return pull_ms, push_ms


def bench_deepfm() -> dict:
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))
    # Criteo-style fixed-length slots: exactly one feasign per slot per
    # sample, so capacity slack is 1.0 (no ragged headroom) — every byte
    # of the per-batch id arrays is real. AMP bf16 compute (master
    # params/optimizer/loss stay f32 — TrainerConfig.compute_dtype).
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(NUM_SLOTS))
    slots += (SlotConf("d", is_dense=True, dim=DENSE_DIM),)
    feed = DataFeedConfig(slots=slots, batch_size=BATCH,
                          slot_capacity_slack=1.0)
    table_cfg = TableConfig(dim=EMB_DIM, learning_rate=0.05)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(NUM_SLOTS)),
                   emb_dim=EMB_DIM, dense_dim=DENSE_DIM,
                   hidden=(400, 400, 400))
    from paddlebox_tpu.embedding import DeviceFeatureStore
    trainer = CTRTrainer(
        model, feed, table_cfg, mesh=mesh,
        config=TrainerConfig(auc_num_buckets=1 << 16,
                             compute_dtype="bfloat16"),
        store_factory=lambda cfg: DeviceFeatureStore(
            cfg, mesh=mesh, capacity_hint=STORE_KEYS + PASS_KEYS))
    trainer.init(seed=0)

    rng = np.random.default_rng(0)
    build_keys_per_s = _prepopulate_store(trainer, STORE_KEYS)
    host_index_keys_per_s = _bench_host_index(STORE_KEYS)
    host_index_bulk_keys_per_s = _bench_host_index_bulk(STORE_KEYS)
    # Multi-process ingest: enable on real multi-core hosts when the
    # operator left the flag at its default — the bench measures the
    # shipped fast path; on 1-2 core boxes spawn overhead would swamp
    # the parse and the thread path stays honest.
    if int(flags.flag("ingest_workers")) == 0 and (os.cpu_count() or 1) >= 4:
        flags.set_flags({"ingest_workers": min(8, os.cpu_count() - 1)})
    pass_keys = rng.choice(np.arange(1, STORE_KEYS, dtype=np.uint64),
                           size=PASS_KEYS, replace=False)

    with tempfile.TemporaryDirectory() as tmpdir:
        # Untimed setup: generate text data.
        files = _gen_pass_files(tmpdir, rng, pass_keys, N_BATCHES)

        # Start the timed pass's data preload NOW: it overlaps the
        # device-only warmup below exactly as a production day loop
        # overlaps pass k+1's read with pass k's training
        # (PreLoadIntoMemory role, box_wrapper.h:1140).
        dataset = Dataset(feed, num_reader_threads=4)
        dataset.set_filelist(files)
        t_preload0 = time.perf_counter()
        dataset.preload_into_memory()

        # Device-only upper bound: repeat the jitted step on one fixed
        # batch (no host work in the loop). Feeding the FULL pass key set
        # here puts the table in the same power-of-two size bucket as the
        # timed pass below, so this phase also serves as the compile
        # warmup and the timed pass runs with zero recompilation.
        ds_dev = Dataset(feed, num_reader_threads=2)
        ds_dev.set_filelist(files[:1])
        ds_dev.load_into_memory()
        batch = next(ds_dev.batches_sharded(ndev))
        eng = trainer.engine
        eng.feed_pass([np.sort(pass_keys) for _ in eng.groups])
        tables = eng.begin_pass()
        rows = trainer._map_batch_rows(batch)
        segs = {n: jnp.asarray(batch.segments[n]) for n in batch.ids}
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        import ml_dtypes
        # Same dtype the timed pass's prefetch will feed (bf16 under AMP)
        # or the warmup would compile a different signature and the first
        # timed step would retrace.
        dense_j = jnp.asarray(
            _concat_dense_host(batch).astype(ml_dtypes.bfloat16))
        labels_j = jnp.asarray(batch.labels)
        valid_j = jnp.asarray(batch.valid)
        if trainer._step_fn is None:
            trainer._step_fn = trainer._build_step()
        step = trainer._step_fn
        params, opt_state, auc = (trainer.params, trainer.opt_state,
                                  trainer.auc_state)
        sync0 = jnp.zeros((), jnp.int32)
        for _ in range(3):
            tables, params, opt_state, auc, loss, _of = step(
                tables, params, opt_state, auc, rows, segs, labels_j,
                valid_j, dense_j, sync0)
        _sync(loss)
        t0 = time.perf_counter()
        dev_steps = 20
        for _ in range(dev_steps):
            tables, params, opt_state, auc, loss, _of = step(
                tables, params, opt_state, auc, rows, segs, labels_j,
                valid_j, dense_j, sync0)
        _sync(loss)
        dev_dt = time.perf_counter() - t0
        pull_ms, push_ms = _bench_pull_push(trainer, tables, rows)
        trainer.params, trainer.opt_state, trainer.auc_state = (
            params, opt_state, auc)
        eng.update_tables(tables)
        eng.end_pass()
        device_only = dev_steps * BATCH / dev_dt

        # Timed E2E: the steady-state pass — data was preloaded during the
        # previous phase (as a day loop hides pass k+1's read under pass
        # k's training), so the timed region is wait-remainder + the real
        # pass loop (feed_pass build -> per-batch host map + device step
        # -> end_pass write-back) over distinct batches.
        t0 = time.perf_counter()
        dataset.wait_preload_done()
        t_load = time.perf_counter() - t0          # exposed remainder
        preload_wall = time.perf_counter() - t_preload0
        t0 = time.perf_counter()
        stats = trainer.train_pass(dataset)
        t_pass = time.perf_counter() - t0

        # Opt-in slot-importance block (--slot-auc[=s0,s1,...]): the
        # AUC-runner slot-replacement eval on the freshly trained
        # model over the timed pass's (still-loaded) data — per-slot
        # AUC degradation becomes a recorded artifact + quality/
        # slot_auc gauges instead of a print. Untimed by construction:
        # every perf number above is already captured.
        slot_auc_block = None
        if SLOT_AUC is not None:
            from paddlebox_tpu.train.auc_runner import \
                slot_replacement_eval
            names = SLOT_AUC or [f"s{i}"
                                 for i in range(min(4, NUM_SLOTS))]
            _tick("deepfm:slot_auc")
            sa = slot_replacement_eval(trainer, dataset, slots=names)
            slot_auc_block = {
                "base_auc": round(float(sa["base_auc"]), 5),
                "ranking": sa["ranking"],
                "slots": {n: {"auc": round(v["auc"], 5),
                              "drop": round(v["auc_drop"], 5)}
                          for n, v in sa["slots"].items()}}

    n_samples = N_BATCHES * BATCH
    e2e = n_samples / (t_load + t_pass)
    tm = trainer.timers
    host_map_s = tm["host_map"].elapsed_sec
    device_step_s = tm["device_step"].elapsed_sec
    # Analytic model FLOPs/sample (MLP fwd 2*in*out, bwd ~2x fwd).
    dims = [NUM_SLOTS * EMB_DIM + DENSE_DIM, 400, 400, 400, 1]
    mults = sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    flops_per_sample = 3 * 2 * mults
    per_chip = e2e / ndev
    # HBM residency (ZeRO-sharded dense state + slot-column offload):
    # measured bytes from the live arrays, not an asserted formula —
    # *_hbm_bytes keys gate lower-better in perf_gate through the
    # "_bytes" suffix; the placement strings are provenance (ungated).
    dense_mem = trainer.dense_memory_stats()
    store_mem = trainer.engine.groups[0].engine.store.memory_stats()
    return {
        "metric": "deepfm_ctr_e2e_samples_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "samples/s/chip",
        "vs_baseline": _vs("deepfm_e2e", per_chip),
        "device_only_per_chip": round(device_only / ndev, 1),
        "e2e_over_device_only": round(e2e / device_only, 4),
        "pull_ms": round(pull_ms, 3),
        "push_ms": round(push_ms, 3),
        "sparse_gather_kernel": flags.flag("sparse_gather_kernel"),
        "sparse_scatter_kernel": flags.flag("sparse_scatter_kernel"),
        # Dispatch amortization (FLAGS_trainer_steps_per_dispatch):
        # dispatch_ms is the host-side enqueue wall per BLOCK (the
        # device_step scope records async dispatch, not completion) —
        # at K>1 the same number covers K steps.
        "steps_per_dispatch": int(stats["steps_per_dispatch"]),
        "dispatch_blocks": int(stats["dispatch_blocks"]),
        "dispatch_ms": round(
            device_step_s / max(int(stats["dispatch_blocks"]), 1) * 1e3,
            3),
        "embedding_exchange_dtype": flags.flag("embedding_exchange_dtype"),
        # Pass-boundary breakdown (round 8): end_pass write-back ms and
        # the pass build's total vs blocked ms, so the split-build /
        # fused-boundary path is visible in the artifact even on CPU
        # smoke runs. This bench feeds with no pass active (feed_wait~0);
        # the pipelined day loop is where feed_wait vs build_ms shows
        # the real contention and overlap_frac its hidden fraction.
        "end_ms": (stats.get("boundary") or {}).get("end_ms"),
        "build_ms": (stats.get("boundary") or {}).get("build_ms"),
        "feed_wait_ms": (stats.get("boundary") or {}).get("feed_wait_ms"),
        "overlap_frac": (stats.get("boundary") or {}).get("overlap_frac"),
        # Critical-path attribution (round 11): the pass's bottleneck
        # verdict (bounding stage + device idle fraction + per-stage
        # busy/blocked shares + queue depths) and the dispatch-latency
        # quantiles — what tools/perf_gate.py gates across rounds, so
        # "store_build is the wall" is a machine-checked field, not a
        # post-hoc bench analysis.
        "bottleneck": stats.get("bottleneck"),
        "dispatch_ms_quantiles": stats.get("dispatch_ms_quantiles"),
        "pass_split_build": bool(flags.flag("pass_split_build")),
        "pass_boundary_fuse": flags.flag("pass_boundary_fuse"),
        "load_s": round(t_load, 3),
        "preload_wall_s": round(preload_wall, 3),
        "pass_s": round(t_pass, 3),
        "host_map_s": round(host_map_s, 3),
        "device_step_dispatch_s": round(device_step_s, 3),
        "achieved_gflops_per_chip": round(
            per_chip * flops_per_sample / 1e9, 2),
        "store_build_keys_per_s": round(build_keys_per_s, 0),
        "host_index_build_keys_per_s": round(host_index_keys_per_s, 0),
        # Round 13: the sorted-run build rate (dedup-as-chunks-arrive →
        # k-way merge → bulk_build) next to the incremental walk above,
        # plus ingest provenance — which reader produced the pass data
        # and how fast the bytes became ColumnarChunks (preload wall is
        # the in-situ rate: it overlaps device warmup like a day loop).
        "host_index_bulk_build_keys_per_s": round(
            host_index_bulk_keys_per_s, 0),
        "ingest_rows_per_s": round(n_samples / max(preload_wall, 1e-9), 0),
        "ingest_workers": int(flags.flag("ingest_workers")),
        "store_build_native": _native_available(),
        "store_keys": STORE_KEYS,
        "pass_keys": PASS_KEYS,
        "auc": round(float(stats["auc"]), 5),
        "auc_floor": _auc_floor(stats["auc"]),
        "lookup_overflow": _overflow_guard(stats),
        "lookup_exchange_bytes": int(stats["lookup_exchange_bytes"]),
        "scale_sparse_grad_by_batch": stats["scale_sparse_grad_by_batch"],
        **({"slot_auc": slot_auc_block}
           if slot_auc_block is not None else {}),
        "dense/params_hbm_bytes": int(dense_mem["params_hbm_bytes"]),
        "dense/opt_state_hbm_bytes": int(
            dense_mem["opt_state_hbm_bytes"]),
        "table/hot_hbm_bytes": int(store_mem["hot_hbm_bytes"]),
        "table/slot_hbm_bytes": int(store_mem["slot_hbm_bytes"]),
        "dense_zero": str(dense_mem["dense_zero"]),
        "table_slot_placement": str(store_mem["placement"]),
        "n_devices": ndev,
    }


def _overflow_guard(stats: dict) -> int:
    """VERDICT-r04 #8: dropped grads must never hide inside a throughput
    number. Any bucket-overflowed lookup during the TIMED pass fails the
    bench record outright — with dedup-before-exchange on (default),
    even planted hot-key skew must not overflow at default slack."""
    n = int(stats.get("lookup_overflow", 0))
    if n:
        raise RuntimeError(
            f"{n} sparse lookups overflowed their shard bucket during the "
            f"timed pass (dropped pull+grad) — the throughput number would "
            f"be measuring dropped work; raise FLAGS_embedding_shard_slack "
            f"or FLAGS_embedding_unique_frac")
    return 0


def _auc_floor(auc: float, floor: float = 0.7):
    """Learning proof on the planted-signal labels: a full-scale pass
    must pull AUC past the floor; below it the sparse path is broken
    (sign/aliasing/routing), and the record says so. Small smoke runs
    see each key ~once — the floor doesn't apply."""
    if _SMALL:
        return None
    ok = float(auc) > floor
    if not ok:
        print(f"[bench] AUC {auc:.4f} <= {floor} on planted-signal "
              f"labels — sparse path is NOT learning", file=sys.stderr)
    return {"floor": floor, "passed": ok}


# ---------------------------------------------------------------------------
# ResNet-50 (BASELINE.md config 1): single-chip fwd+bwd images/s
# ---------------------------------------------------------------------------

def bench_resnet50() -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.models.resnet import ResNet

    from paddlebox_tpu.amp import cast_compute_except_stats as cast_compute
    from paddlebox_tpu.amp import merge_bn_stats as merge_bn

    model = ResNet(depth=50, num_classes=1000)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)
    bs = 8 if _SMALL else 128

    def loss_fn(p, x, y):
        # bf16 compute (MXU path), f32 master params; BN statistics stay
        # f32 end-to-end (cast_compute skips them, batchnorm_apply
        # computes in f32, merge_bn writes them back to the master).
        logits, p_new = model.apply(cast_compute(p), x, train=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y).mean()
        return loss, p_new

    @jax.jit
    def step(p, s, x, y):
        (loss, p_new), g = jax.value_and_grad(loss_fn, has_aux=True)(
            p, x, y)
        updates, s = opt.update(g, s, p)
        return merge_bn(optax.apply_updates(p, updates), p_new), s, loss

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(bs, 224, 224, 3)), jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, bs), jnp.int32)
    for _ in range(1 if _SMALL else 3):
        params, opt_state, loss = step(params, opt_state, x, y)
    _sync(loss)
    t0 = time.perf_counter()
    n = 2 if _SMALL else 20
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, x, y)
    _sync(loss)
    dt = time.perf_counter() - t0
    ips = n * bs / dt
    # ResNet-50 @224: ~4.09 GFLOP forward/image (standard conv+fc
    # multiply-add count x2); train step ~3x forward (bwd ~2x fwd).
    flops_per_image = 3 * 4.09e9
    return {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/s/chip",
        "vs_baseline": _vs("resnet50", ips),
        "batch_size": bs,
        "achieved_mfu": _mfu(ips * flops_per_image),
    }


# ---------------------------------------------------------------------------
# BERT-base DP (BASELINE.md config 2): tokens/s over the dp mesh
# ---------------------------------------------------------------------------

def bench_bert_dp() -> dict:
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlebox_tpu.models.bert import (BertConfig, bert_mlm_loss,
                                           init_bert)
    from paddlebox_tpu.parallel import HybridTopology, build_mesh

    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))
    if _SMALL:
        cfg = BertConfig(d_model=128, n_layers=2, n_heads=2, d_ff=256)
    else:
        cfg = BertConfig()  # BERT-base defaults
    params = init_bert(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-4)
    bs, seq = (2 * ndev, 64) if _SMALL else (8 * ndev, 128)

    data_sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    params = jax.device_put(params, rep)

    def loss_fn(p, tokens, targets, mask):
        return bert_mlm_loss(p, cfg, tokens, targets, mask)

    # FLAGS_dense_zero applies to the dense workloads exactly as to the
    # CTR trainer: "shard" places adamw moments ZeRO-1 over dp (each
    # chip stores 1/dp of every large leaf; params output pinned
    # replicated so the sharded state can't leak into p+u), "offload"
    # keeps them in host memory between steps via OffloadedOptimizer.
    from paddlebox_tpu.parallel import zero as zero_lib
    dense_zero = str(flags.flag("dense_zero"))
    zero_min = int(flags.flag("dense_zero_min_size"))
    if dense_zero == "offload":
        off_tx = zero_lib.OffloadedOptimizer(
            opt, mesh, axis="dp", min_size=zero_min)
        opt_state = off_tx.init(params)
        grad_step = jax.jit(jax.value_and_grad(loss_fn))

        def step(p, s, tokens, targets, mask):
            loss, g = grad_step(p, tokens, targets, mask)
            p, s = off_tx.update_apply(g, s, p)
            return p, s, loss
    else:
        opt_state = opt.init(params)
        if dense_zero == "shard":
            opt_sh = zero_lib.zero_shardings(
                opt_state, mesh, axis="dp", min_size=zero_min)
            opt_state = jax.device_put(opt_state, opt_sh)
            jit_kw = {"out_shardings": (
                jax.tree.map(lambda _: rep, params), opt_sh, rep)}
        else:
            opt_state = jax.device_put(opt_state, rep)
            jit_kw = {}

        @partial(jax.jit, **jit_kw)
        def step(p, s, tokens, targets, mask):
            loss, g = jax.value_and_grad(loss_fn)(p, tokens, targets,
                                                  mask)
            updates, s = opt.update(g, s, p)
            return optax.apply_updates(p, updates), s, loss

    rng = np.random.default_rng(0)
    tokens = jax.device_put(jnp.asarray(
        rng.integers(0, cfg.vocab_size, (bs, seq)), jnp.int32), data_sh)
    targets = jax.device_put(jnp.asarray(
        rng.integers(0, cfg.vocab_size, (bs, seq)), jnp.int32), data_sh)
    # Standard MLM masking rate: predict ~15% of positions.
    mask = jax.device_put(jnp.asarray(
        rng.random((bs, seq)) < 0.15, jnp.float32), data_sh)
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens,
                                       targets, mask)
    _sync(loss)
    t0 = time.perf_counter()
    n = 2 if _SMALL else 10
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, tokens,
                                       targets, mask)
    _sync(loss)
    dt = time.perf_counter() - t0
    tps = n * bs * seq / dt
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    # Measured per-device HBM residency of the dense state (what
    # FLAGS_dense_zero exists to shrink) — not a formula.
    params_hbm = zero_lib.tree_hbm_bytes_per_device(params)
    opt_hbm = zero_lib.tree_hbm_bytes_per_device(opt_state)
    return {
        "metric": "bert_base_dp_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": _vs("bert_dp", tps),
        "n_devices": ndev,
        "batch_size": bs,
        "seq_len": seq,
        "n_params": n_params,
        "dense/params_hbm_bytes": int(params_hbm),
        "dense/opt_state_hbm_bytes": int(opt_hbm),
        "dense_zero": dense_zero,
        # 6ND estimate over ALL chips -> divide by ndev for per-chip MFU.
        "achieved_mfu": _mfu(6.0 * n_params * tps / ndev),
    }


# ---------------------------------------------------------------------------
# GPT (BASELINE.md config 3, scaled to available chips): tokens/s + MFU-ish
# ---------------------------------------------------------------------------

def bench_gpt() -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.models.gpt import (GPTConfig, init_gpt,
                                          make_gpt_train_step)
    from paddlebox_tpu.parallel import HybridTopology, build_mesh

    ndev = len(jax.devices())
    # GPT-350M-class on one chip; hybrid axes engage when chips allow.
    if _SMALL:
        cfg = GPTConfig(vocab_size=1024, d_model=128, n_heads=4,
                        n_layers=2, d_ff=256, max_seq_len=128)
    else:
        cfg = GPTConfig(vocab_size=50304, d_model=1024, n_heads=16,
                        n_layers=24, d_ff=4096, max_seq_len=1024)
    mesh = build_mesh(HybridTopology(dp=ndev))
    params, specs = init_gpt(jax.random.PRNGKey(0), cfg, pp_stages=1)
    opt = optax.adafactor(1e-3)
    opt_state = opt.init(params)

    # Same FLAGS_dense_zero wiring as bert_dp: "shard" ZeRO-1-places the
    # adafactor state over dp (params pinned replicated through the
    # step's out_shardings), "offload" keeps it host-resident between
    # steps; "off" is the replicated baseline.
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddlebox_tpu.parallel import zero as zero_lib
    dense_zero = str(flags.flag("dense_zero"))
    zero_min = int(flags.flag("dense_zero_min_size"))
    rep = NamedSharding(mesh, P())
    if dense_zero == "offload":
        from paddlebox_tpu.models.gpt import gpt_loss_fn
        params = jax.device_put(params, rep)
        off_tx = zero_lib.OffloadedOptimizer(
            opt, mesh, axis="dp", min_size=zero_min)
        opt_state = off_tx.init(params)
        vg = jax.jit(jax.value_and_grad(
            gpt_loss_fn(cfg, mesh, specs, num_microbatches=1)))

        def step(p, s, tokens, targets):
            loss, g = vg(p, tokens, targets)
            p, s = off_tx.update_apply(g, s, p)
            return p, s, loss
    elif dense_zero == "shard":
        params = jax.device_put(params, rep)
        opt_sh = zero_lib.zero_shardings(
            opt_state, mesh, axis="dp", min_size=zero_min)
        opt_state = jax.device_put(opt_state, opt_sh)
        step = make_gpt_train_step(
            cfg, mesh, specs, opt, num_microbatches=1,
            out_shardings=(jax.tree.map(lambda _: rep, params),
                           opt_sh, rep))
    else:
        step = make_gpt_train_step(cfg, mesh, specs, opt,
                                   num_microbatches=1)

    bs, seq = (2 * ndev, 128) if _SMALL else (4 * ndev, 1024)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, seq)),
                         jnp.int32)
    targets = jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, seq)),
                          jnp.int32)
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    _sync(loss)
    t0 = time.perf_counter()
    n = 2 if _SMALL else 5
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    _sync(loss)
    dt = time.perf_counter() - t0
    tps = n * bs * seq / dt
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    flops = 6.0 * n_params * tps  # standard 6ND estimate
    params_hbm = zero_lib.tree_hbm_bytes_per_device(params)
    opt_hbm = zero_lib.tree_hbm_bytes_per_device(opt_state)
    return {
        "metric": "gpt_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": _vs("gpt", tps),
        "n_devices": ndev,
        "n_params": n_params,
        "dense/params_hbm_bytes": int(params_hbm),
        "dense/opt_state_hbm_bytes": int(opt_hbm),
        "dense_zero": dense_zero,
        "achieved_tflops": round(flops / 1e12, 2),
        "achieved_mfu": _mfu(flops / ndev),
    }


# ---------------------------------------------------------------------------
# Wide&Deep CTR (BASELINE.md config 5): the HeterPS-style path — CVM
# (show/click) features flowing through the pull, device-resident store.
# ---------------------------------------------------------------------------

def bench_wide_deep() -> dict:
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
    from paddlebox_tpu.models.wide_deep import WideDeep
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))
    n_slots, emb_dim, batch = (WIDE_DEEP_SLOTS, WIDE_DEEP_EMB_DIM,
                               WIDE_DEEP_BATCH)
    store_keys, pass_keys_n, n_batches = (10_000_000,
                                          WIDE_DEEP_PASS_KEYS, 32)
    if _SMALL:
        batch, store_keys, pass_keys_n, n_batches = 512, 200_000, 20_000, 4
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(n_slots))
    feed = DataFeedConfig(slots=slots, batch_size=batch,
                          slot_capacity_slack=1.0)
    model = WideDeep(slot_names=tuple(f"s{i}" for i in range(n_slots)),
                     emb_dim=emb_dim, hidden=(256, 128))
    trainer = CTRTrainer(
        model, feed, TableConfig(dim=emb_dim, learning_rate=0.05),
        mesh=mesh,
        config=TrainerConfig(auc_num_buckets=1 << 16,
                             compute_dtype="bfloat16"),
        store_factory=lambda cfg: DeviceFeatureStore(
            cfg, mesh=mesh, capacity_hint=store_keys + pass_keys_n))
    trainer.init(seed=0)
    build_keys_per_s = _prepopulate_store(trainer, store_keys)
    rng = np.random.default_rng(0)
    pass_keys = rng.choice(np.arange(1, store_keys, dtype=np.uint64),
                           size=pass_keys_n, replace=False)
    # Zipf key stream + measured bucket capacity: the HeterPS-style
    # config is the duplicate-heavy one, so it carries the dedup
    # demonstration — capacity sizes to measured unique ids and the
    # record's lookup_exchange_bytes shows the reduction (overflow
    # still hard-fails via _overflow_guard). The flag itself is only
    # needed around train_pass (the warmup seeds _step_caps directly),
    # so it is set there under try/finally — a failure anywhere in this
    # function cannot leak it into a same-process deepfm run.
    from paddlebox_tpu.core import flags as flagmod
    with tempfile.TemporaryDirectory() as tmpdir:
        files = _gen_pass_files(tmpdir, rng, pass_keys, n_batches,
                                batch=batch, n_slots=n_slots, dense_dim=0,
                                label_rate=0.2, zipf_a=1.2)
        dataset = Dataset(feed, num_reader_threads=4)
        dataset.set_filelist(files)
        dataset.preload_into_memory()
        # Compile warmup at the TIMED pass's table size: feed the full
        # pass key set (same pow2 bucket), run the jitted step twice on
        # one batch, close the pass — the timed pass then reuses the
        # compiled program (same discipline as bench_deepfm).
        ds_warm = Dataset(feed, num_reader_threads=2)
        ds_warm.set_filelist(files[:1])
        ds_warm.load_into_memory()
        batch0 = next(ds_warm.batches_sharded(ndev))
        eng = trainer.engine
        eng.feed_pass([np.sort(pass_keys) for _ in eng.groups])
        tables = eng.begin_pass()
        rows = trainer._map_batch_rows(batch0)
        # Warm the MEASURED-capacity step (auto-capacity is on for this
        # config): the timed pass measures the same Zipf distribution
        # into the same pow2 bucket and reuses this compile.
        trainer._step_caps = tuple(trainer._measure_caps(tables, rows))
        trainer._step_fn = trainer._build_step(caps=trainer._step_caps)
        segs = {n: jnp.asarray(batch0.segments[n]) for n in batch0.ids}
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        import ml_dtypes
        dense_j = jnp.asarray(
            _concat_dense_host(batch0).astype(ml_dtypes.bfloat16))
        params, opt_state, auc = (trainer.params, trainer.opt_state,
                                  trainer.auc_state)
        sync0 = jnp.zeros((), jnp.int32)
        for _ in range(2):
            tables, params, opt_state, auc, loss, _of = trainer._step_fn(
                tables, params, opt_state, auc, rows, segs,
                jnp.asarray(batch0.labels), jnp.asarray(batch0.valid),
                dense_j, sync0)
        _sync(loss)
        trainer.params, trainer.opt_state, trainer.auc_state = (
            params, opt_state, auc)
        eng.update_tables(tables)
        eng.end_pass()

        dataset.wait_preload_done()
        t0 = time.perf_counter()
        _prev_autocap = flagmod.flag("embedding_auto_capacity")
        flagmod.set_flags({"embedding_auto_capacity": True})
        try:
            stats = trainer.train_pass(dataset)
        finally:
            flagmod.set_flags(
                {"embedding_auto_capacity": _prev_autocap})
        t_pass = time.perf_counter() - t0
    per_chip = n_batches * batch / t_pass / ndev
    return {
        "metric": "wide_deep_ctr_e2e_samples_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "samples/s/chip",
        "vs_baseline": _vs("wide_deep", per_chip),
        "store_build_keys_per_s": round(build_keys_per_s, 0),
        "auc": round(float(stats["auc"]), 5),
        "auc_floor": _auc_floor(stats["auc"]),
        "lookup_overflow": _overflow_guard(stats),
        "lookup_exchange_bytes": int(stats["lookup_exchange_bytes"]),
        "scale_sparse_grad_by_batch": stats["scale_sparse_grad_by_batch"],
        "n_devices": ndev,
    }


# ---------------------------------------------------------------------------
# Graph engine at non-toy scale (SURVEY §2.3): 10M-edge weighted build +
# sharded deepwalk throughput — the roles of GraphGpuWrapper::load_edge_file
# + upload_batch and GraphDataGenerator's walk loop
# (graph_gpu_ps_table_inl.cu), measured instead of merely covered.
# ---------------------------------------------------------------------------

GRAPH_EDGES = 10_000_000
GRAPH_NODES = 1_000_000
GRAPH_MAX_DEGREE = 64
GRAPH_WALK_LEN = 24
GRAPH_WALK_BATCH = 65_536
if _SMALL:
    GRAPH_EDGES, GRAPH_NODES = 1_000_000, 100_000
    GRAPH_WALK_BATCH = 8_192


def bench_graph() -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddlebox_tpu.graph import DeviceGraph, build_csr
    from paddlebox_tpu.graph.sampler import (random_walk,
                                             random_walk_weighted)
    from paddlebox_tpu.parallel import HybridTopology, build_mesh

    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))
    rng = np.random.default_rng(0)

    # Power-law-ish destinations (Zipf hubs — the degree skew real graphs
    # have, which is exactly what stresses the hub truncation path) with
    # integer weights.
    _tick("graph:gen")
    src = rng.integers(0, GRAPH_NODES, GRAPH_EDGES).astype(np.int64)
    dst = (rng.zipf(1.3, GRAPH_EDGES) % GRAPH_NODES).astype(np.int64)
    w = rng.integers(1, 10, GRAPH_EDGES).astype(np.float32)

    _tick("graph:build")
    t0 = time.perf_counter()
    g = build_csr(src, dst, num_nodes=GRAPH_NODES, weights=w)
    build_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    dg = DeviceGraph.from_csr(g, max_degree=GRAPH_MAX_DEGREE)
    pad_s = time.perf_counter() - t0

    _tick("graph:upload")
    rep = NamedSharding(mesh, P())
    shd = NamedSharding(mesh, P("dp"))
    nbrs = jax.device_put(jnp.asarray(dg.nbrs), rep)
    degree = jax.device_put(jnp.asarray(dg.degree), rep)
    cdf = jax.device_put(jnp.asarray(dg.nbr_cdf), rep)
    starts = jax.device_put(
        jnp.asarray(rng.integers(0, GRAPH_NODES, GRAPH_WALK_BATCH),
                    jnp.int32), shd)

    def timed_walks(fn, *arrays):
        # jitted fns shard the start batch over dp; the adjacency is
        # device-resident and replicated (each GPU holds its graph shard
        # in the reference; one chip holds the whole padded table here).
        _tick("graph:walk-compile")
        out = fn(*arrays, starts, jax.random.key(0), GRAPH_WALK_LEN)
        _sync(out[-1, -1])
        t0 = time.perf_counter()
        iters = 10
        for i in range(iters):
            out = fn(*arrays, starts, jax.random.key(i + 1),
                     GRAPH_WALK_LEN)
        _sync(out[-1, -1])
        dt = time.perf_counter() - t0
        return iters * GRAPH_WALK_BATCH * GRAPH_WALK_LEN / dt

    uniform_sps = timed_walks(random_walk, nbrs, degree)
    weighted_sps = timed_walks(random_walk_weighted, nbrs, cdf)

    return {
        "metric": "graph_walk_steps_per_sec",
        "value": round(uniform_sps, 0),
        "unit": "walk steps/s",
        "vs_baseline": _vs("graph_walk", uniform_sps),
        "weighted_walk_steps_per_sec": round(weighted_sps, 0),
        "build_edges_per_sec": round(GRAPH_EDGES / build_s, 0),
        "build_s": round(build_s, 3),
        "pad_s": round(pad_s, 3),
        "edges": GRAPH_EDGES,
        "nodes": GRAPH_NODES,
        "max_degree": GRAPH_MAX_DEGREE,
        "walk_len": GRAPH_WALK_LEN,
        "walk_batch": GRAPH_WALK_BATCH,
        "n_devices": ndev,
    }


# ---------------------------------------------------------------------------
# Online serving (SURVEY L12): xbox-style sparse model + jitted bf16
# predictor — the inference half of the CTR production loop, measured.
# ---------------------------------------------------------------------------

SERVING_KEYS = 2_000_000
SERVING_BATCH = 2048
SERVING_QUERY_BATCHES = 50
SERVE_REQ_ROWS = 64          # rows per client request in --clients mode
SERVE_CLIENT_SECONDS = 3.0   # timed window per client count
if _SMALL:
    SERVING_KEYS = 100_000
    SERVING_BATCH = 512
    SERVING_QUERY_BATCHES = 10
    SERVE_CLIENT_SECONDS = 1.0

# Parsed from --clients by main(): comma-separated client counts for the
# concurrent wire-mode serving bench ("" = skip the wire section).
# `bench.py deepfm --slot-auc[=s0,s1,...]` opt-in: run the AUC-runner
# slot-replacement eval on the trained model after the timed pass and
# record per-slot AUC degradation (None = off; [] = default first-4
# slots; a list = exactly those slots). Untimed — it runs after every
# perf number is captured.
SLOT_AUC = None
SERVE_CLIENTS = ""
# `bench.py serve --replicas 1,2` fleet axis ("" = skip): fresh fleet
# (R PredictServers + FleetRouter) per count over ONE shared predictor
# (the CPU-honest stand-in for R hosts: per-replica batchers/sockets/
# stats are real, the device table is shared so the axis measures the
# routing+coalescing overhead, not R copies of HBM).
SERVE_REPLICAS = ""
SERVE_FLEET_CLIENTS_PER_REPLICA = 4


def _serve_client_lines(rng, n_requests: int):
    """Vectorized svm-line assembly for the wire clients (per-line
    python f-strings would dominate the client threads' CPU budget and
    measure the bench, not the server)."""
    out = []
    for _ in range(n_requests):
        ids = rng.integers(1, SERVING_KEYS + 1,
                           (SERVE_REQ_ROWS, NUM_SLOTS))
        ids[:, 0] = rng.integers(1, 1001, SERVE_REQ_ROWS)
        line = np.full((SERVE_REQ_ROWS,), "0", dtype="U16")
        for j in range(NUM_SLOTS):
            line = np.char.add(line, f" s{j}:")
            line = np.char.add(line, ids[:, j].astype("U20"))
        out.append(line.tolist())
    return out


def _bench_serve_clients(pred, clients: list) -> dict:
    """Concurrent-client wire mode: N PredictClients hammer one
    PredictServer (micro-batcher on) for a fixed window; records
    throughput_rps / rows_per_s / p50/p99 predict latency /
    batch_fill_frac per client count. One fresh server per count so the
    latency digest and fill gauge belong to that run alone."""
    import threading

    from paddlebox_tpu.core import flags as flagmod, monitor
    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.serving.batcher import pack_bucketed, pow2_bucket
    from paddlebox_tpu.serving.service import PredictClient, PredictServer

    # Compile the pow2 row-bucket ladder BEFORE any timed window: a
    # coalesced batch of k requests lands in the pow2_bucket(k * rows)
    # trace, and an in-window XLA compile would be measured as a
    # multi-second p99.
    _tick("serving:bucket-warmup")
    wrng = np.random.default_rng(7)
    max_rows = min(max(clients) * SERVE_REQ_ROWS,
                   int(flagmod.flag("serving_batch_max_rows")))
    warm_lines = _serve_client_lines(wrng, 1)[0]
    b = pow2_bucket(SERVE_REQ_ROWS)
    while True:
        ins = parse_lines(warm_lines * (b // SERVE_REQ_ROWS), pred.feed)
        pred.predict(pack_bucketed(ins, pred.feed))
        if b >= pow2_bucket(max_rows):
            break
        b *= 2

    out = {}
    for n_cli in clients:
        _tick(f"serving:clients{n_cli}")
        monitor.reset()
        server = PredictServer("127.0.0.1:0", pred)
        rng = np.random.default_rng(1234 + n_cli)
        lines = [_serve_client_lines(rng, 8) for _ in range(n_cli)]
        done = [0] * n_cli
        stop = threading.Event()
        start = threading.Barrier(n_cli + 1)

        def run(i):
            cli = PredictClient(server.endpoint)
            ok = True
            try:
                cli.predict(lines[i][0])  # warm (compile outside window)
            except Exception as e:
                ok = False
                print(f"serve client {i} warmup failed: {e!r}",
                      file=sys.stderr)
            start.wait()  # always reached: a dead client must not
            try:          # wedge the barrier and stall the recording
                j = 0
                while ok and not stop.is_set():
                    cli.predict(lines[i][j % len(lines[i])])
                    done[i] += 1
                    j += 1
            finally:
                cli.close()

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(n_cli)]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        time.sleep(SERVE_CLIENT_SECONDS)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        dt = time.perf_counter() - t0
        stats_cli = PredictClient(server.endpoint)
        st = stats_cli.stats()
        stats_cli.close()
        server.stop()
        n_req = sum(done)
        out[f"c{n_cli}"] = {
            "throughput_rps": round(n_req / dt, 1),
            "rows_per_s": round(n_req * SERVE_REQ_ROWS / dt, 1),
            "predict_p50_ms": st["latency_ms"]["p50"],
            "predict_p99_ms": st["latency_ms"]["p99"],
            "batch_fill_frac": round(st["batch_fill_frac"], 4),
            "batches": st["batches"],
            "requests": n_req,
        }
    return out


def _bench_serve_telemetry_overhead(pred, *, n_requests: int = 200
                                    ) -> dict:
    """Tracing + live-scrape overhead on the serving path: the same
    single-replica router loop timed with telemetry OFF, then with the
    span ring ON and a concurrent fleet_top-style scrape loop hitting
    metrics_snapshot on router+replica — `telemetry_overhead_frac` is
    the rps delta, gated lower-better by tools/perf_gate.py (the
    observability layer must stay ~free, or it gets turned off exactly
    when it is needed)."""
    import threading

    from paddlebox_tpu.core import telemetry_scrape, trace
    from paddlebox_tpu.serving.router import FleetRouter
    from paddlebox_tpu.serving.service import PredictClient, PredictServer

    server = PredictServer("127.0.0.1:0", pred, replica_id="bench-tel")
    router = FleetRouter("127.0.0.1:0", replicas=[server.endpoint],
                         start_health=False)
    rng = np.random.default_rng(999)
    lines = _serve_client_lines(rng, 8)
    cli = PredictClient(router.endpoint)
    cli.predict(lines[0])  # warm the forward + conns

    def timed_loop() -> float:
        t0 = time.perf_counter()
        for j in range(n_requests):
            cli.predict(lines[j % len(lines)])
        return n_requests / (time.perf_counter() - t0)

    trace.disable()
    rps_off = timed_loop()
    trace.enable()   # ring-only: no file unless FLAGS_trace_path is set
    targets = {"router": router.endpoint, "replica": server.endpoint}
    stop = threading.Event()
    scrapes = [0]

    def scrape_loop():
        while not stop.is_set():
            telemetry_scrape.scrape_cluster(targets, with_stats=False)
            scrapes[0] += 1
            stop.wait(0.1)

    t = threading.Thread(target=scrape_loop, daemon=True)
    t.start()
    try:
        try:
            rps_on = timed_loop()
        finally:
            stop.set()
            t.join(timeout=10)
            trace.disable()
            trace.clear()
        # Health plane (fleet health PR): history sampler + alert
        # engine ON at a deliberately hot 100ms cadence — every
        # registered registry (global + router + replica instance
        # rings) is sampled and the burn-rate rule pack evaluated per
        # tick. history_overhead_frac is the additional rps cost vs
        # telemetry-off; alerts_firing must be 0 on a healthy bench
        # (both gated by tools/perf_gate.py).
        from paddlebox_tpu.core import alerts, timeseries
        prev = {k: flags.flag(k)
                for k in ("history_interval_s", "alerts_enable")}
        flags.set_flags({"history_interval_s": 0.1,
                         "alerts_enable": True})
        try:
            timeseries.init_from_flags()
            alerts.init_from_flags()
            rps_health = timed_loop()
            firing = alerts.firing_count()
        finally:
            alerts.shutdown()
            timeseries.GLOBAL_SAMPLER.stop()
            flags.set_flags(prev)
    finally:
        cli.close()
        router.stop()
        server.stop()
    return {
        "trace_off_rps": round(rps_off, 1),
        "trace_on_rps": round(rps_on, 1),
        "telemetry_overhead_frac": round(
            max(0.0, 1.0 - rps_on / max(rps_off, 1e-9)), 4),
        "history_on_rps": round(rps_health, 1),
        "history_overhead_frac": round(
            max(0.0, 1.0 - rps_health / max(rps_off, 1e-9)), 4),
        "alerts_firing": int(firing),
        "scrapes": int(scrapes[0]),
    }


def _bench_serve_fleet(pred, replicas: list) -> dict:
    """Fleet axis: R replica servers behind one FleetRouter, hammered
    by 4 clients per replica for a fixed window. Fresh fleet per count
    (per-replica instance registries + a fresh router latency digest
    belong to that run alone); records aggregate throughput_rps,
    per-replica batch fill, router route_ms p50/p99, and the
    degraded-path share — the keys tools/perf_gate.py gates."""
    import threading

    from paddlebox_tpu.serving.router import FleetRouter
    from paddlebox_tpu.serving.service import PredictClient, PredictServer

    out = {}
    for n_rep in replicas:
        _tick(f"serving:replicas{n_rep}")
        n_cli = max(int(SERVE_FLEET_CLIENTS_PER_REPLICA) * n_rep, 1)
        servers = [PredictServer("127.0.0.1:0", pred,
                                 replica_id=f"bench-r{i}")
                   for i in range(n_rep)]
        router = FleetRouter("127.0.0.1:0",
                             replicas=[s.endpoint for s in servers],
                             start_health=False)
        rng = np.random.default_rng(4321 + n_rep)
        lines = [_serve_client_lines(rng, 8) for _ in range(n_cli)]
        done = [0] * n_cli
        stop = threading.Event()
        start = threading.Barrier(n_cli + 1)

        def run(i):
            cli = PredictClient(router.endpoint)
            ok = True
            try:
                cli.predict(lines[i][0])  # warm outside the window
            except Exception as e:
                ok = False
                print(f"fleet client {i} warmup failed: {e!r}",
                      file=sys.stderr)
            start.wait()
            try:
                j = 0
                while ok and not stop.is_set():
                    cli.predict(lines[i][j % len(lines[i])])
                    done[i] += 1
                    j += 1
            finally:
                cli.close()

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(n_cli)]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        time.sleep(SERVE_CLIENT_SECONDS)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        dt = time.perf_counter() - t0
        stats_cli = PredictClient(router.endpoint)
        st = stats_cli.stats()
        stats_cli.close()
        router.stop()
        for s in servers:
            s.stop()
        n_req = sum(done)
        fills = [b["stats"]["batch_fill_frac"]
                 for b in st["replicas"].values()]
        out[f"r{n_rep}"] = {
            "throughput_rps": round(n_req / dt, 1),
            "rows_per_s": round(n_req * SERVE_REQ_ROWS / dt, 1),
            "route_ms_quantiles": {"p50": st["route_ms"]["p50"],
                                   "p99": st["route_ms"]["p99"]},
            "batch_fill_frac": round(
                sum(fills) / max(len(fills), 1), 4),
            "degraded_frac": round(
                st["degraded_rpcs"] / max(st["predict_rpcs"], 1), 4),
            "clients": n_cli,
            "requests": n_req,
        }
    _tick("serving:telemetry-overhead")
    out["telemetry"] = _bench_serve_telemetry_overhead(pred)
    return out


def bench_serving() -> dict:
    import jax

    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.serving.predictor import CTRPredictor

    rng = np.random.default_rng(0)
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(NUM_SLOTS))
    feed = DataFeedConfig(slots=slots, batch_size=SERVING_BATCH,
                          slot_capacity_slack=1.0)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(NUM_SLOTS)),
                   emb_dim=EMB_DIM, hidden=(400, 400, 400))
    dense_params = model.init(jax.random.PRNGKey(0))

    # Trained-model stand-in: the serving table's cost profile depends on
    # key count and width, not the values.
    _tick("serving:table")
    keys = np.arange(1, SERVING_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(SERVING_KEYS, EMB_DIM)).astype(np.float32) * 0.01
    w = rng.normal(size=(SERVING_KEYS,)).astype(np.float32) * 0.01
    t0 = time.perf_counter()
    pred = CTRPredictor(model, feed, keys, emb, w, dense_params)
    # Force the table upload before stopping the clock (the axon
    # platform returns from dispatch before the H2D lands — see _sync).
    float(np.asarray(pred._table[0, 0]))
    load_s = time.perf_counter() - t0

    # Query stream: hot head + uniform tail, same shape discipline as the
    # training benches. One batch shape -> one cached jitted forward.
    # Vectorized line assembly (np.char) — the per-line loop this file
    # warns about in _gen_pass_files would burn tunnel-window seconds
    # in untimed setup.
    def query_batch():
        ids = rng.integers(1, SERVING_KEYS + 1,
                           (SERVING_BATCH, NUM_SLOTS))
        ids[:, 0] = rng.integers(1, 1001, SERVING_BATCH)
        line = np.full((SERVING_BATCH,), "0", dtype="U16")
        for j in range(NUM_SLOTS):
            line = np.char.add(line, f" s{j}:")
            line = np.char.add(line, ids[:, j].astype("U20"))
        return SlotBatch.pack(parse_lines(line.tolist(), feed), feed)

    batches = [query_batch() for _ in range(SERVING_QUERY_BATCHES)]
    _tick("serving:warmup")
    probs = pred.predict(batches[0])          # compile
    assert probs.shape == (SERVING_BATCH,)
    _tick("serving:timed")
    t0 = time.perf_counter()
    for b in batches:
        probs = pred.predict(b)
    float(probs[0])
    dt = time.perf_counter() - t0
    qps = SERVING_QUERY_BATCHES * SERVING_BATCH / dt

    # Per-request latency digest (the SLO view, recorded beside the
    # pipelined-throughput headline — NOT inside its timed loop, which
    # must stay async to remain comparable with prior rounds): each
    # predict here is synced so a sample is a real request latency.
    _tick("serving:latency")
    from paddlebox_tpu.core.quantiles import LogQuantileDigest
    lat = LogQuantileDigest()
    for b in batches:
        tq = time.perf_counter()
        float(pred.predict(b)[0])
        lat.observe((time.perf_counter() - tq) * 1e3)
    lat_q = {k: (round(v, 3) if v is not None else None)
             for k, v in lat.quantiles().items()}

    out = {
        "metric": "serving_predict_samples_per_sec",
        "value": round(qps, 1),
        "unit": "samples/s",
        "vs_baseline": _vs("serving", qps),
        "table_load_s": round(load_s, 3),
        "predict_ms_quantiles": lat_q,
        "serving_slo_p99_ms": float(flags.flag("serving_slo_p99_ms")),
        "serving_keys": SERVING_KEYS,
        "batch_size": SERVING_BATCH,
        "serving_batch_window_ms": float(
            flags.flag("serving_batch_window_ms")),
        "n_devices": len(jax.devices()),
    }
    if SERVE_CLIENTS:
        clients = [int(c) for c in SERVE_CLIENTS.split(",") if c.strip()]
        out["clients"] = _bench_serve_clients(pred, clients)
    if SERVE_REPLICAS:
        # The --clients warmup above (when present) already compiled
        # the pow2 ladder; compile it here if fleet mode runs alone.
        replicas = [int(r) for r in SERVE_REPLICAS.split(",")
                    if r.strip()]
        if not SERVE_CLIENTS:
            from paddlebox_tpu.core import flags as flagmod
            from paddlebox_tpu.data.parser import parse_lines as _pl
            from paddlebox_tpu.serving.batcher import (pack_bucketed,
                                                       pow2_bucket)
            wrng = np.random.default_rng(7)
            max_rows = min(
                max(replicas) * SERVE_FLEET_CLIENTS_PER_REPLICA
                * SERVE_REQ_ROWS,
                int(flagmod.flag("serving_batch_max_rows")))
            warm_lines = _serve_client_lines(wrng, 1)[0]
            b = pow2_bucket(SERVE_REQ_ROWS)
            while True:
                ins = _pl(warm_lines * (b // SERVE_REQ_ROWS), pred.feed)
                pred.predict(pack_bucketed(ins, pred.feed))
                if b >= pow2_bucket(max_rows):
                    break
                b *= 2
        out["replicas"] = _bench_serve_fleet(pred, replicas)
    return out


MULTIHOST_HOSTS = 2          # `bench.py multihost --hosts N` overrides
MULTIHOST_KEYS = 20_000 if _SMALL else 2_000_000
MULTIHOST_DIM = 16
MULTIHOST_ROUNDS = 3


def bench_multihost() -> dict:
    """Loopback-process mode of the multi-host embedding exchange tier
    (MULTIHOST.md): N shard servers on 127.0.0.1 — the sockets, wire
    codec, fan-out threading, and reshard machinery are all real; only
    the DCN propagation delay is absent. Records the cross-host
    exchange rate per wire dtype plus a grow-by-one reshard
    (minimal-transfer audit included), gated by tools/perf_gate.py."""
    from paddlebox_tpu.core import monitor
    from paddlebox_tpu.embedding.table import TableConfig
    from paddlebox_tpu.multihost import (MultiHostStore, ShardRangeTable,
                                         execute_reshard,
                                         rows_moved_minimal,
                                         start_local_shards, stop_shards)

    hosts = MULTIHOST_HOSTS
    cfg = TableConfig(name="emb", dim=MULTIHOST_DIM, learning_rate=0.1)
    rng = np.random.default_rng(0)
    keys = np.unique(rng.integers(
        1, 1 << 50, size=int(MULTIHOST_KEYS * 1.01) + 64,
        dtype=np.uint64))[:MULTIHOST_KEYS]

    _tick("multihost:cluster")
    servers, eps = start_local_shards(hosts, cfg)
    store = MultiHostStore(cfg, eps)
    # Populate: one untimed pull+push round inserts every key.
    rows = store.pull_for_pass(keys)
    store.push_from_pass(keys, rows)

    def timed_round():
        t0 = time.perf_counter()
        r = store.pull_for_pass(keys)
        pull_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        store.push_from_pass(keys, r)
        return pull_s, time.perf_counter() - t1

    out_wire = {}
    prev = flags.flag("multihost_wire_dtype")
    try:
        for wire in ("f32", "int8"):
            _tick(f"multihost:wire-{wire}")
            flags.set_flags({"multihost_wire_dtype": wire})
            timed_round()  # warm the plan cache + connections
            b0 = (monitor.GLOBAL.get("multihost/pull_bytes")
                  + monitor.GLOBAL.get("multihost/push_bytes"))
            t0 = time.perf_counter()
            pull_s = push_s = 0.0
            for _ in range(MULTIHOST_ROUNDS):
                p, q = timed_round()
                pull_s += p
                push_s += q
            dt = time.perf_counter() - t0
            moved = (monitor.GLOBAL.get("multihost/pull_bytes")
                     + monitor.GLOBAL.get("multihost/push_bytes") - b0)
            out_wire[wire] = {
                "cross_host_exchange_bytes_per_s": round(moved / dt, 1),
                "exchange_keys_per_s": round(
                    MULTIHOST_ROUNDS * keys.size * 2 / dt, 1),
                "pull_ms": round(pull_s / MULTIHOST_ROUNDS * 1e3, 2),
                "push_ms": round(push_s / MULTIHOST_ROUNDS * 1e3, 2),
                "wire_bytes_per_round": int(moved // MULTIHOST_ROUNDS),
                # One pass boundary = one pull + one push of the pass's
                # working set: the DCN byte bill the quantized wire
                # shrinks. Gated lower-better ("_bytes_").
                "cross_host_bytes_per_pass": int(
                    moved // MULTIHOST_ROUNDS),
            }
    finally:
        flags.set_flags({"multihost_wire_dtype": prev})
    assert (out_wire["int8"]["cross_host_bytes_per_pass"] * 2
            <= out_wire["f32"]["cross_host_bytes_per_pass"]), out_wire

    # Overlapped boundary exchange (the split-build early pulls + this
    # round's background exchange worker): each round writes the pass
    # back with push_from_pass_async — the 50% shared window pushes
    # synchronously, the bulk drains on the worker while the "trainer"
    # computes — then the next pass pulls its shared window
    # barrier-free at the boundary. exchange_overlap_frac = 1 -
    # wait/busy over the phase; gated higher-better ("overlap_frac").
    _tick("multihost:overlap")
    from paddlebox_tpu.embedding.table import shared_key_mask
    half = np.zeros(keys.size, bool)
    half[::2] = True
    rows = store.pull_for_pass(keys, pass_id=1000)
    xs0 = store.exchange_stats()
    ov_t0 = time.perf_counter()
    for r in range(MULTIHOST_ROUNDS):
        pid = 1000 + r
        job = store.push_from_pass_async(keys, rows,
                                         priority_select=half,
                                         pass_id=pid)
        while not job.done:          # the pass's training compute
            np.multiply(rows["emb"], np.float32(1.0))
        store.pull_for_pass(keys, half, pass_id=pid + 1,
                            barrier=False, boundary=True)
        rows = store.pull_for_pass(keys, pass_id=pid + 1)
    ov_s = time.perf_counter() - ov_t0
    xs1 = store.exchange_stats()
    xbusy = xs1["exchange_busy_ms"] - xs0["exchange_busy_ms"]
    xwait = xs1["exchange_wait_ms"] - xs0["exchange_wait_ms"]
    overlap = {
        "exchange_overlap_frac": round(
            max(0.0, min(1.0, 1.0 - xwait / max(xbusy, 1e-9))), 4),
        "exchange_busy_ms": round(xbusy, 2),
        "exchange_wait_ms": round(xwait, 2),
        "overlap_round_ms": round(ov_s / MULTIHOST_ROUNDS * 1e3, 2),
    }

    # Tracing + scrape overhead on the exchange path (f32 wire): the
    # same pull+push rounds with the span ring ON — every RPC then
    # carries a trace context and client/server spans — plus one
    # metrics_snapshot scrape of every shard per round. The keys/s
    # delta is `telemetry_overhead_frac`, gated lower-better by
    # tools/perf_gate.py.
    _tick("multihost:telemetry-overhead")
    from paddlebox_tpu.core import telemetry_scrape, trace
    off_t0 = time.perf_counter()
    for _ in range(MULTIHOST_ROUNDS):
        timed_round()
    off_s = time.perf_counter() - off_t0
    trace.enable()
    try:
        targets = {f"shard{i}": ep for i, ep in enumerate(eps)}
        on_t0 = time.perf_counter()
        for _ in range(MULTIHOST_ROUNDS):
            timed_round()
            telemetry_scrape.scrape_cluster(targets, with_stats=False)
        on_s = time.perf_counter() - on_t0
    finally:
        trace.disable()
        trace.clear()
    keys_off = MULTIHOST_ROUNDS * keys.size * 2 / off_s
    keys_on = MULTIHOST_ROUNDS * keys.size * 2 / on_s
    # Health plane: history sampler + alert engine ON (100ms cadence
    # over the global + per-shard instance rings, burn-rate pack
    # evaluated per tick) for the same rounds — the additional keys/s
    # cost is history_overhead_frac; alerts_firing must be 0 on a
    # healthy bench. Both gated by tools/perf_gate.py.
    from paddlebox_tpu.core import alerts as _alerts
    from paddlebox_tpu.core import timeseries as _timeseries
    _prev_hp = {k: flags.flag(k)
                for k in ("history_interval_s", "alerts_enable")}
    flags.set_flags({"history_interval_s": 0.1, "alerts_enable": True})
    try:
        _timeseries.init_from_flags()
        _alerts.init_from_flags()
        hp_t0 = time.perf_counter()
        for _ in range(MULTIHOST_ROUNDS):
            timed_round()
        hp_s = time.perf_counter() - hp_t0
        hp_firing = _alerts.firing_count()
    finally:
        _alerts.shutdown()
        _timeseries.GLOBAL_SAMPLER.stop()
        flags.set_flags(_prev_hp)
    keys_health = MULTIHOST_ROUNDS * keys.size * 2 / hp_s
    telemetry = {
        "trace_off_keys_per_s": round(keys_off, 1),
        "trace_on_keys_per_s": round(keys_on, 1),
        "telemetry_overhead_frac": round(
            max(0.0, 1.0 - keys_on / max(keys_off, 1e-9)), 4),
        "history_on_keys_per_s": round(keys_health, 1),
        "history_overhead_frac": round(
            max(0.0, 1.0 - keys_health / max(keys_off, 1e-9)), 4),
        "alerts_firing": int(hp_firing),
    }

    # Grow-by-one reshard at the measured table size, audited against
    # the minimal-transfer bound.
    _tick("multihost:reshard")
    grown, geps = start_local_shards(hosts + 1, cfg)
    joiner, jep = grown[hosts], geps[hosts]
    stop_shards(grown[:hosts])
    rec = execute_reshard(eps, eps + [jep])
    minimal = rows_moved_minimal(ShardRangeTable.for_world(hosts),
                                 ShardRangeTable.for_world(hosts + 1),
                                 keys)
    assert rec["moved_rows"] == minimal, (rec["moved_rows"], minimal)
    stop_shards(servers)
    joiner.stop()

    # Replicated-tier failover: a replicas=2 cluster under a pull loop
    # takes a scripted primary kill — pull p99 across the kill is the
    # failover blip (reads fail over to the surviving backup), the
    # promote+re-replicate repair restores R, and the journal catch-up
    # rate is measured by re-syncing a lagged backup.
    _tick("multihost:failover")
    fo = _bench_multihost_failover(cfg, keys)

    f32 = out_wire["f32"]
    return {
        "metric": f"multihost_{hosts}host_exchange_keys_per_sec",
        "value": f32["exchange_keys_per_s"],
        "unit": "keys/s",
        "hosts": hosts,
        "pass_keys": int(keys.size),
        "dim": MULTIHOST_DIM,
        "wire": out_wire,
        "reshard_ms": round(rec["reshard_ms"], 2),
        "reshard_moved_rows": int(rec["moved_rows"]),
        "reshard_rows_per_s": round(
            rec["moved_rows"] / max(rec["reshard_ms"], 1e-6) * 1e3, 1),
        "reshard_minimal_frac": round(
            rec["moved_rows"] / max(minimal, 1), 4),
        "failover_blip_ms": fo["failover_blip_ms"],
        "failover_pull_p50_ms": fo["pull_p50_ms"],
        "repair_ms": fo["repair_ms"],
        "journal_catchup_rows_per_s": fo["journal_catchup_rows_per_s"],
        "failover_failed_pulls": fo["failed_pulls"],  # provenance: 0
        "overlap": overlap,
        "telemetry": telemetry,
        "embedding_quant_block": int(flags.flag("embedding_quant_block")),
    }


def _bench_multihost_failover(cfg, keys) -> dict:
    """Scripted primary kill under a pull loop (MULTIHOST.md
    "replicated tier"): records the pull p99 across the kill
    (failover_blip_ms — the read-failover cost of losing a shard
    host), the promote + re-replicate repair wall time (repair_ms),
    and the journal catch-up throughput for a briefly-lagged backup
    (journal_catchup_rows_per_s)."""
    import numpy as np

    from paddlebox_tpu.core import monitor
    from paddlebox_tpu.multihost import (MultiHostStore, ReplicaMap,
                                         start_local_shards, stop_shards)
    from paddlebox_tpu.multihost.shard_service import ShardServer

    sub = keys[: max(1, keys.size // 8)]   # a serving-sized working set
    servers, eps = start_local_shards(2, cfg, replicas=2)
    store = MultiHostStore(cfg, eps, replicas=2)
    rows = store.pull_for_pass(sub)
    store.push_from_pass(sub, rows)

    # Journal catch-up rate: sever the backup's conns so one push lags,
    # then time the forced re-sync (delta replay of the missed rows).
    servers[1].close_connections()
    rows["show"] += 1.0
    t0 = time.perf_counter()
    store.push_from_pass(sub, rows)        # in-line catch-up fires here
    store.sync_replicas()
    catchup_s = time.perf_counter() - t0
    catchup_rows_per_s = sub.size / max(catchup_s, 1e-9)

    # The scripted kill under a pull loop.
    lat_ms, failed = [], 0
    kill_at = 10
    fresh = None
    try:
        for i in range(30):
            if i == kill_at:
                servers[1].kill()          # the primary of ~half the keys
            t1 = time.perf_counter()
            try:
                store.pull_for_pass(sub)
            except Exception:
                failed += 1
                continue
            lat_ms.append((time.perf_counter() - t1) * 1e3)
        lat = np.sort(np.asarray(lat_ms))
        blip_ms = float(lat[min(len(lat) - 1,
                                int(0.99 * len(lat)))])
        p50_ms = float(lat[len(lat) // 2])

        # Repair: promote the survivor, re-replicate to a fresh host.
        from paddlebox_tpu.multihost.reshard import \
            ElasticReshardController
        ctl = ElasticReshardController(store, None)
        t2 = time.perf_counter()
        rec = ctl.repair(reason="bench scripted kill")
        assert rec is not None
        fresh = ShardServer("127.0.0.1:0", 0, store.ranges, cfg)
        new_map = store.replica_map
        for slot in range(new_map.world):
            new_map = new_map.add_backup(slot, fresh.endpoint)
        ctl._adopt_map(new_map)
        store.sync_replicas()
        repair_ms = (time.perf_counter() - t2) * 1e3
        assert store.replica_map.replication == 2
        monitor.set_gauge("multihost/repair_ms", repair_ms)
    finally:
        store.close()
        stop_shards(servers + ([fresh] if fresh else []))
    return {"failover_blip_ms": round(blip_ms, 2),
            "pull_p50_ms": round(p50_ms, 2),
            "repair_ms": round(repair_ms, 2),
            "journal_catchup_rows_per_s": round(catchup_rows_per_s, 1),
            "failed_pulls": failed}


ONLINE_DAYS = 3                  # replayed log days (TTL needs >= 3)
ONLINE_PASS_FILES = 2            # files per carved incremental pass
# ---------------------------------------------------------------------------
# RPC plane microbench (`bench.py rpc`): the event-loop/mux wire (RPC.md)
# ---------------------------------------------------------------------------

RPC_DEPTHS = (1, 4, 16)
RPC_PAYLOAD_F32 = ({"64b": 16, "64kb": 16384} if _SMALL
                   else {"64b": 16, "64kb": 16384, "1mb": 262144})
RPC_WINDOWS = 60 if _SMALL else 400


def bench_rpc() -> dict:
    """Echo RTT ladder over one loopback FramedRPCServer: payload size
    × outstanding depth × wire plane ({legacy: v1 frames, one call per
    RTT (depth > 1 = the old thread-per-call fan-out); mux: v2
    request-id multiplexing, ``call_async`` pipelining on ONE socket;
    sg: mux + zero-copy scatter/gather array frames}). Per cell:
    calls_per_s, the window-completion p50/p99, and payload bytes/s —
    all pinned by tools/perf_gate.py. The headline is mux calls_per_s
    at depth ≥ 2; ``mux_over_legacy_at_o4`` records the pipelining win
    that motivated the mux wire (provenance, not gated)."""
    from paddlebox_tpu.core import monitor
    from paddlebox_tpu.distributed import rpc

    class _EchoServer(rpc.FramedRPCServer):
        service_name = "rpc-bench"

        def handle_echo(self, req):
            return {"a": req["a"]}

    modes = {
        "legacy": {"rpc_mux": False, "rpc_sg_min_bytes": -1},
        "mux": {"rpc_mux": True, "rpc_sg_min_bytes": -1},
        "sg": {"rpc_mux": True, "rpc_sg_min_bytes": 4096},
    }
    prev = {k: flags.flag(k) for k in ("rpc_mux", "rpc_sg_min_bytes")}
    out_modes = {}
    sg0 = monitor.GLOBAL.get("rpc/sg_frames")
    try:
        for mode, fl in modes.items():
            _tick(f"rpc:{mode}")
            flags.set_flags(fl)
            srv = _EchoServer("127.0.0.1:0")
            conn = rpc.FramedRPCConn(srv.endpoint, timeout=60.0,
                                     service_name="rpc-bench",
                                     idempotent=("echo",))
            cells = {}
            try:
                for pname, n in RPC_PAYLOAD_F32.items():
                    a = np.arange(n, dtype=np.float32)
                    per_call = a.nbytes * 2  # request + echoed reply
                    windows = max(20, RPC_WINDOWS // max(1, n // 4096))
                    conn.call("echo", a=a)  # warm connect + caps
                    for depth in RPC_DEPTHS:
                        walls = []
                        t0 = time.perf_counter()
                        for _ in range(windows):
                            w0 = time.perf_counter()
                            if depth == 1:
                                conn.call("echo", a=a)
                            else:
                                futs = [conn.call_async("echo", a=a)
                                        for _ in range(depth)]
                                for f in futs:
                                    f.result()
                            walls.append(time.perf_counter() - w0)
                        dt = time.perf_counter() - t0
                        calls = windows * depth
                        cells[f"{pname}_o{depth}"] = {
                            "calls_per_s": round(calls / dt, 1),
                            "p50_ms": round(float(
                                np.percentile(walls, 50)) * 1e3, 3),
                            "p99_ms": round(float(
                                np.percentile(walls, 99)) * 1e3, 3),
                            "bytes_per_s": round(
                                calls * per_call / dt, 1),
                        }
            finally:
                conn.close()
                srv.stop()
                srv.close_connections()
            out_modes[mode] = cells
    finally:
        flags.set_flags(prev)
    mux_r = out_modes["mux"]["64b_o4"]["calls_per_s"]
    leg_r = out_modes["legacy"]["64b_o4"]["calls_per_s"]
    return {
        "metric": "rpc_echo_mux_calls_per_sec",
        "value": mux_r,
        "unit": "calls/s",
        "windows": RPC_WINDOWS,                       # provenance
        "mux_over_legacy_at_o4": round(
            mux_r / max(leg_r, 1e-9), 3),             # provenance
        "sg_frames": int(monitor.GLOBAL.get("rpc/sg_frames") - sg0),
        "modes": out_modes,
    }


ONLINE_FILES_PER_DAY = 4 if _SMALL else 8
ONLINE_BATCH = 128 if _SMALL else 512
ONLINE_ROWS_PER_FILE = ONLINE_BATCH * (2 if _SMALL else 4)
ONLINE_SLOTS = 4
ONLINE_KEYS_PER_DAY = 2_000 if _SMALL else 20_000


def bench_online() -> dict:
    """Streaming online-learning mode (ONLINE.md): replay a fixed
    multi-day event log as a stream through StreamRunner — every carved
    incremental pass trains and publishes a delta through the donefile
    path serving tails — and record the freshness/lifecycle numbers the
    roadmap asked for: event→servable latency quantiles, passes/hour,
    and the post-shrink store row count that proves TTL/decay bounds
    the table under infinite traffic (each day's keys churn, so without
    the lifecycle the store would grow ~linearly in days)."""
    import jax

    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.stream import StreamRunner
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    rng = np.random.default_rng(0)
    slot_names = tuple(f"s{i}" for i in range(ONLINE_SLOTS))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in slot_names),
        batch_size=ONLINE_BATCH)
    model = DeepFM(slot_names=slot_names, emb_dim=8, hidden=(32,))
    mesh = build_mesh(HybridTopology(dp=len(jax.devices())))
    trainer = CTRTrainer(model, feed,
                         TableConfig(name="emb", dim=8,
                                     learning_rate=0.05),
                         mesh=mesh,
                         config=TrainerConfig(auc_num_buckets=1 << 10))
    trainer.init(seed=0)

    def write_day_files(log_dir, day_idx):
        """One day of events: keys drawn from a per-day sliding window
        (half the window carries over, half churns) so TTL has real
        unseen traffic to expire."""
        lo = 1 + day_idx * ONLINE_KEYS_PER_DAY // 2
        keys = np.arange(lo, lo + ONLINE_KEYS_PER_DAY, dtype=np.uint64)
        files = []
        for i in range(ONLINE_FILES_PER_DAY):
            ids = rng.choice(keys, (ONLINE_ROWS_PER_FILE, ONLINE_SLOTS))
            labels = _planted_labels(rng, ids[:, 0])
            line = labels.astype("U1")
            for j in range(ONLINE_SLOTS):
                line = np.char.add(line, f" s{j}:")
                line = np.char.add(line, ids[:, j].astype("U20"))
            # Atomic appearance (write-tmp-then-rename), the tailer's
            # documented arrival convention.
            name = f"day{day_idx}-{i:04d}.log"
            tmp = os.path.join(log_dir, "." + name + ".tmp")
            with open(tmp, "w") as f:
                f.write("\n".join(line.tolist()) + "\n")
            final = os.path.join(log_dir, name)
            os.replace(tmp, final)
            files.append(final)
        return files

    from paddlebox_tpu.core import flags as flagmod
    prev = {k: flagmod.flag(k) for k in
            ("stream_pass_events", "table_ttl_days", "quality_collect")}
    out_rows = {}
    with tempfile.TemporaryDirectory() as tmpdir:
        log_dir = os.path.join(tmpdir, "events")
        os.makedirs(log_dir)
        runner = StreamRunner(
            trainer, feed, os.path.join(tmpdir, "out"), log_dir=log_dir,
            day_of=lambda p: os.path.basename(p).split("-")[0],
            shuffle=False, num_reader_threads=2)
        try:
            flagmod.set_flags({
                "stream_pass_events":
                    ONLINE_PASS_FILES * ONLINE_ROWS_PER_FILE,
                "table_ttl_days": 1,
                # Model-quality plane ON for the streamed run: per-pass
                # COPC/calibration + slot health + drift alarms ride
                # the same replay (the "quality" record block below).
                "quality_collect": True})
            _tick("online:stream")
            t0 = time.perf_counter()
            passes = 0
            for d in range(ONLINE_DAYS):
                write_day_files(log_dir, d)
                passes += runner.poll_once(flush=True)
                runner.end_day()
                out_rows[f"day{d}"] = int(
                    trainer.engine.store.num_features)
                _tick(f"online:day{d}")
            wall = time.perf_counter() - t0
        finally:
            flagmod.set_flags(prev)
        store_rows = int(trainer.engine.store.num_features)

    events = ONLINE_DAYS * ONLINE_FILES_PER_DAY * ONLINE_ROWS_PER_FILE
    fresh = runner.freshness_quantiles() or {}
    eps = events / wall
    # Model-quality record (core/quality.py, collected per carved
    # pass): headline COPC + the per-pass calibration-error p99 from
    # the registry digest, total drift alarms, the worst slot's
    # example coverage, and the data-shape provenance (skew/churn —
    # recorded, never gated).
    from paddlebox_tpu.core import monitor as _mon
    snap = _mon.snapshot()
    cal_d = _mon.GLOBAL.quantile_digest("quality/calibration_error")
    slot_covs = [v for k, v in snap.items()
                 if k.startswith("quality/slot_coverage/")]
    quality_block = {
        "copc": round(float(snap.get("quality/copc", float("nan"))), 4),
        "calibration_error": (
            {"p99": round(cal_d.quantile(0.99), 5)}
            if cal_d is not None and cal_d.count else None),
        "quality_alarms": int(sum(
            v for k, v in snap.items()
            if k.startswith("quality/alarms/"))),
        "slot_coverage": (round(min(slot_covs), 4) if slot_covs
                          else None),
        "skew_top_share": round(float(
            snap.get("quality/skew_top_share", 0.0)), 4),
        "key_churn": round(float(
            snap.get("quality/key_churn", 0.0)), 4),
    }
    return {
        "metric": "online_stream_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/s",
        "vs_baseline": _vs("online", eps),
        "event_to_servable_ms": {
            k: (round(v, 1) if v is not None else None)
            for k, v in fresh.items() if k in ("p50", "p99")},
        "passes_per_hour": round(passes / wall * 3600.0, 1),
        "post_shrink_store_rows": store_rows,
        "day1_rows": out_rows.get("day0"),
        "day3_over_day1_rows": (
            round(out_rows["day%d" % (ONLINE_DAYS - 1)]
                  / max(out_rows["day0"], 1), 4)
            if "day0" in out_rows else None),
        "stream_passes": passes,
        "events": events,
        "table_ttl_days": 1,
        "quality": quality_block,
        "n_devices": len(jax.devices()),
    }


FLEET_TRACE = ""   # `bench.py fleet --trace seed[,duration_s[,rps]]`


def bench_fleet() -> dict:
    """Autopilot soak: replay a seeded, diurnal, hot-set-skewed trace
    (serving/traceload.py — replay-pure, so two runs of one spec are
    the same trace) against a small in-process fleet with the full
    control loop armed: history sampler + alert engine (PR 18 plane),
    FleetAutopilot scaling on the merged stats, and the COPC-gated
    canary controller watching a live donefile. The chaos script rides
    the trace: a 10x spike, a replica kill, and a calibration-poisoned
    BASE publish that must be confined to the canary subset and rolled
    back on the real sampled-label join. Records the soak/* keys
    tools/perf_gate.py gates: failed_rpcs and predict_p99_ms lower-
    better, action counts as provenance."""
    import dataclasses
    import shutil

    import jax

    from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
    from paddlebox_tpu.core import (alerts, flags as flagmod, monitor,
                                    telemetry_scrape, timeseries)
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.serving import traceload
    from paddlebox_tpu.serving.autopilot import FleetAutopilot
    from paddlebox_tpu.serving.predictor import (CTRPredictor,
                                                 load_xbox_model)
    from paddlebox_tpu.serving.router import FleetRouter
    from paddlebox_tpu.serving.service import PredictClient, PredictServer

    spec = [s for s in FLEET_TRACE.split(",") if s.strip()]
    seed = int(spec[0]) if len(spec) > 0 else 0
    duration = float(spec[1]) if len(spec) > 1 else (6.0 if _SMALL
                                                    else 20.0)
    rps = float(spec[2]) if len(spec) > 2 else 30.0

    slots = ("u", "i")
    dim = 8
    n_keys = 2000
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in slots),
        batch_size=64)
    model = DeepFM(slot_names=slots, emb_dim=dim, hidden=())
    dense = model.init(jax.random.PRNGKey(0))
    mrng = np.random.default_rng(3)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    emb = mrng.normal(size=(n_keys, dim)).astype(np.float32) * 0.02
    w = mrng.normal(size=(n_keys,)).astype(np.float32) * 0.02

    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    root = os.path.join(tmp, "publish")
    proto = CheckpointProtocol(root)

    def write_base(day, e, ww):
        d = proto.model_dir(day, 0)
        os.makedirs(d, exist_ok=True)
        np.savez(os.path.join(d, "embedding.xbox.npz"),
                 keys=keys, emb=e, w=ww)
        return d

    base_dir = write_base("20260801", emb, w)
    proto.publish("20260801")
    # The poisoned base: weights shifted so every prediction saturates
    # toward 1.0 — served COPC (label_sum/pred_sum) collapses to ~0.5
    # against the alternating labels below, a textbook calibration
    # break the canary gate must catch.
    write_base("20260802", emb + 5.0, w + 5.0)

    prev = {k: flagmod.flag(k) for k in (
        "quality_sample_rate", "quality_min_events",
        "serving_slo_p99_ms", "autopilot_cooldown_s",
        "autopilot_min_replicas", "autopilot_max_replicas",
        "autopilot_poll_s", "autopilot_canary_replicas",
        "autopilot_canary_min_labels", "autopilot_canary_copc_margin",
        "autopilot_canary_timeout_s", "history_interval_s",
        "alerts_enable", "fleet_health_interval_s")}
    flagmod.set_flags({
        "quality_sample_rate": 1.0, "quality_min_events": 8,
        "serving_slo_p99_ms": 2000.0,   # generous CPU bound: the soak
        # asserts p99 stays UNDER it, scale-out triggers on the kill
        "autopilot_cooldown_s": 1.0, "autopilot_min_replicas": 2,
        "autopilot_max_replicas": 4, "autopilot_poll_s": 0.2,
        "autopilot_canary_replicas": 1,
        "autopilot_canary_min_labels": 24,
        "autopilot_canary_copc_margin": 0.2,
        "autopilot_canary_timeout_s": 30.0,
        "history_interval_s": 0.2, "alerts_enable": True,
        "fleet_health_interval_s": 0.2})
    monitor.reset()
    _tick("fleet:setup")

    def make_server(rid):
        k, e, ww = load_xbox_model(base_dir, "embedding")
        pred = CTRPredictor(model, feed, k, e, ww, dense,
                            compute_dtype="float32")
        return PredictServer("127.0.0.1:0", pred, replica_id=rid)

    servers = {f"replica-{i}": make_server(f"replica-{i}")
               for i in range(2)}
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint
                                   for s in servers.values()])
    timeseries.init_from_flags()
    alerts.init_from_flags()

    spawn_n = [0]

    def spawn():
        rid = f"auto-{spawn_n[0]}"
        spawn_n[0] += 1
        s = make_server(rid)
        servers[rid] = s
        router.fleet.add_replica(rid, s.endpoint, ready=True)
        return rid

    def retire(rid):
        s = servers.pop(rid, None)
        if s is not None:
            s.stop()

    # registry=router.metrics: action counters land in the router's
    # instance registry too, so ONE telemetry_scrape sweep over the
    # fleet shows every action the autopilot took.
    autopilot = FleetAutopilot(
        router.fleet, lambda: router.handle_stats({}),
        donefile_root=root, spawn=spawn, retire=retire,
        registry=router.metrics,
        state_path=os.path.join(tmp, "autopilot.json"))
    autopilot.start()

    # Trace skew calibrated from the live observatory when it has
    # reported (quality/slot_top_share gauges in a replica snapshot);
    # falls back to the config default on a cold start.
    snap = next(iter(servers.values())).metrics.snapshot_all()
    cfg = traceload.TraceConfig.from_quality(
        snap.get("gauges") or {}, seed=seed, duration_s=duration,
        base_rps=rps, n_keys=n_keys, slots=slots, rows_per_request=2,
        chaos=(
            traceload.ChaosEvent(at_s=0.30 * duration, kind="spike",
                                 duration_s=0.15 * duration,
                                 factor=10.0),
            traceload.ChaosEvent(at_s=0.40 * duration,
                                 kind="kill_replica", arg="replica-1"),
            traceload.ChaosEvent(at_s=0.50 * duration,
                                 kind="poison_delta", arg="20260802"),
        ))
    gen = traceload.TraceGenerator(cfg)

    cli = PredictClient(router.endpoint)
    failed = [0]
    lines0 = next(iter(gen.requests())).lines
    cli.predict(list(lines0))  # compile outside the soak window

    def send(req):
        try:
            cli.predict(list(req.lines), rid=req.rid)
            cli.send_labels(
                req.rid,
                [(int(req.rid.rsplit("-", 1)[1]) + r) % 2
                 for r in range(len(req.lines))])
        except Exception as e:  # noqa: BLE001 - every failure counts
            failed[0] += 1
            print(f"[bench fleet] rpc failed: {e!r}", file=sys.stderr)

    def kill_replica(ev):
        s = servers.pop(ev.arg, None)
        if s is not None:
            # Kill-like teardown: refuse new connects AND sever the
            # router's pooled conns (a graceful stop would keep
            # draining them and the fleet would never notice).
            s.stop()
            s.close_connections()

    def poison(ev):
        proto.publish(ev.arg)

    _tick("fleet:replay")
    t0 = time.perf_counter()
    replayed = traceload.replay(
        gen, send, handlers={"kill_replica": kill_replica,
                             "poison_delta": poison})
    replay_wall = time.perf_counter() - t0
    # Drain the canary: the verdict needs joined labels on BOTH sides
    # after the poisoned base staged — keep the labeled trace flowing
    # (fresh seed: content no longer asserted) until it resolves.
    _tick("fleet:canary-drain")
    t_end = time.perf_counter() + 30.0
    extra = 1
    while autopilot.canary.state.data.get("canary") is not None \
            and time.perf_counter() < t_end:
        drain = traceload.TraceGenerator(dataclasses.replace(
            cfg, seed=seed + extra, chaos=()))
        extra += 1
        for req in drain.requests():
            if autopilot.canary.state.data.get("canary") is None \
                    or time.perf_counter() > t_end:
                break
            send(req)

    st = router.handle_stats({})
    snap_all = monitor.snapshot()
    # One cluster sweep must show every action the autopilot took.
    targets = {"router": router.endpoint}
    targets.update({rid: s.endpoint for rid, s in servers.items()})
    sweep = telemetry_scrape.scrape_cluster(targets, with_stats=False)
    sweep_counters = (sweep.get("merged") or {}).get("counters") or {}
    reports = list(autopilot.canary.reports)

    autopilot.stop()
    alerts.shutdown()
    timeseries.GLOBAL_SAMPLER.stop()
    cli.close()
    router.stop()
    for s in servers.values():
        s.stop()
    flagmod.set_flags(prev)
    shutil.rmtree(tmp, ignore_errors=True)

    scale_out = int(snap_all.get("autopilot/actions/scale_out", 0))
    scale_in = int(snap_all.get("autopilot/actions/scale_in", 0))
    rollbacks = [r for r in reports if r.get("verdict") == "rollback"]
    return {
        # Headline follows the bench convention (value = throughput,
        # higher-better): replayed requests per wall second THROUGH the
        # chaos. The robustness keys gate under soak/*.
        "metric": "fleet_soak_requests_per_s",
        "value": round(replayed["sent"] / max(replay_wall, 1e-9), 1),
        "unit": "req/s",
        "soak": {
            "failed_rpcs": int(failed[0]),
            "predict_p99_ms": (st.get("latency_ms") or {}).get("p99"),
            "degraded_frac": round(
                st.get("degraded_rpcs", 0)
                / max(st.get("predict_rpcs", 1), 1), 4),
            "scale_actions": scale_out + scale_in,
            "canary_blocked": len(rollbacks),
        },
        "trace": {"seed": seed, "duration_s": duration,
                  "base_rps": rps, "hot_share": cfg.hot_share,
                  "requests": int(replayed["sent"]),
                  "events_fired": int(replayed["events_fired"])},
        "actions": {k.rsplit("/", 1)[1]: int(v)
                    for k, v in snap_all.items()
                    if k.startswith("autopilot/actions/")},
        "canary_reports": reports,
        "scrape_shows_actions": any(
            k.startswith("autopilot/actions/")
            for k in sweep_counters),
        "slo_p99_ms_flag": 2000.0,
        "n_devices": len(jax.devices()),
    }


CONFIGS = {
    "deepfm": bench_deepfm,
    "resnet50": bench_resnet50,
    "bert_dp": bench_bert_dp,
    "gpt": bench_gpt,
    "wide_deep": bench_wide_deep,
    "graph": bench_graph,
    "serving": bench_serving,
    "serve": bench_serving,  # alias: `bench.py serve --clients 1,8,32`
    "multihost": bench_multihost,  # `bench.py multihost --hosts N`
    "online": bench_online,        # streaming freshness/lifecycle mode
    "rpc": bench_rpc,              # event-loop/mux wire echo ladder
    "fleet": bench_fleet,  # autopilot soak: `bench.py fleet --trace`
}


def _preflight_scatter_kernel(n: int, aw: int, pass_keys: int) -> None:
    """Run the push scatter-accumulate once on the real backend at the
    EXACT shape the selected bench will compile — same update count,
    payload width, and pass-table block (jit/Mosaic treat each shape as
    a fresh compile, so any other shape would not predict the real one)
    — through the same ``_accumulate`` wrapper the jitted step uses. If
    it fails to compile/execute or returns wrong values (an untested /
    miscompiling toolchain), pin the flag to the XLA scatter so the
    recorded run never dies (or silently corrupts) inside the jitted
    step."""
    from paddlebox_tpu.core import flags as flagmod
    if flagmod.flag("sparse_scatter_kernel") == "xla":
        # Operator already pinned the fallback (e.g. because the kernel
        # hard-crashes the runtime, which no try/except catches) —
        # honor it; running the kernel anyway would defeat the pin.
        return
    try:
        from paddlebox_tpu.embedding.lookup import _accumulate
        from paddlebox_tpu.embedding.table import plan_shards
        import jax.numpy as jnp
        # Mirror make_push_fn at the bench's actual device count: the
        # jitted step compiles PER-SHARD shapes (block =
        # rows_per_shard + 1, n/ndev updates inside shard_map) — a
        # single-shard probe on a multi-chip bench would validate a
        # shape the step never compiles.
        ndev = len(jax.devices())
        block = plan_shards(pass_keys, ndev) + 1
        n = n // ndev
        rng = np.random.default_rng(0)
        rows = jnp.asarray(
            rng.integers(0, block - 1, n).astype(np.int32))
        pay = jnp.asarray(
            rng.standard_normal((n, aw)).astype(np.float32))
        out = _accumulate(rows, pay, block)
        ref = jnp.zeros((block, aw), jnp.float32).at[rows].add(pay)
        err = float(jnp.max(jnp.abs(out - ref)))
        # Value check, not just liveness: a miscompiling toolchain that
        # returns garbage must also route to the fallback. Explicit
        # raise (not assert) — python -O must not strip it.
        if not err < 1e-3:
            raise RuntimeError(f"kernel/xla mismatch: max err {err}")
    except Exception as e:  # noqa: BLE001 - any failure means fallback
        print(f"[bench] pallas scatter preflight failed ({e!r}); "
              f"using XLA scatter", file=sys.stderr)
        flagmod.set_flags({"sparse_scatter_kernel": "xla"})


def _preflight_gather_kernel(n: int, dim: int, pass_keys: int) -> None:
    """The pull-side twin of _preflight_scatter_kernel: run the Pallas
    sorted-stream gather once on the real backend at the EXACT per-shard
    shape the selected bench will compile (fused record width from the
    table config's optimizer, pull width dim+3) — through the same
    ``_gather_rows`` wrapper the jitted step uses. Any compile/execute
    failure or value mismatch pins the flag to the XLA gather so the
    recorded run never dies (or silently corrupts) inside the step."""
    from paddlebox_tpu.core import flags as flagmod
    if flagmod.flag("sparse_gather_kernel") == "xla":
        return
    try:
        import jax.numpy as jnp

        from paddlebox_tpu.embedding import (TableConfig,
                                             make_sparse_optimizer)
        from paddlebox_tpu.embedding.lookup import _gather_rows
        from paddlebox_tpu.embedding.table import plan_shards
        opt = make_sparse_optimizer(TableConfig(dim=dim))
        w = dim + 3 + opt.emb_state_width(dim) + opt.w_state_width()
        pw = dim + 3
        ndev = len(jax.devices())
        block = plan_shards(pass_keys, ndev) + 1
        n = n // ndev
        rng = np.random.default_rng(1)
        # block - 1 is the trash row: the kernel path DROPS it to zeros
        # by contract, so the probe keys stay below it.
        rows = jnp.asarray(rng.integers(0, block - 1, n).astype(np.int32))
        vals = jnp.asarray(
            rng.standard_normal((block, w)).astype(np.float32))
        out = _gather_rows(vals, rows, pw, block)
        err = float(jnp.max(jnp.abs(out - vals[rows, :pw])))
        if not err == 0.0:
            raise RuntimeError(f"kernel/xla mismatch: max err {err}")
    except Exception as e:  # noqa: BLE001 - any failure means fallback
        print(f"[bench] pallas gather preflight failed ({e!r}); "
              f"using XLA gather", file=sys.stderr)
        flagmod.set_flags({"sparse_gather_kernel": "xla"})


def main() -> None:
    global SERVE_CLIENTS, SERVE_REPLICAS, MULTIHOST_HOSTS, SLOT_AUC
    global FLEET_TRACE
    argv = list(sys.argv[1:])
    if "--slot-auc" in argv:
        i = argv.index("--slot-auc")
        SLOT_AUC = []
        del argv[i]
    for i, a in enumerate(argv):
        if a.startswith("--slot-auc="):
            SLOT_AUC = [s for s in a.split("=", 1)[1].split(",") if s]
            del argv[i]
            break
    if "--clients" in argv:
        i = argv.index("--clients")
        SERVE_CLIENTS = argv[i + 1] if i + 1 < len(argv) else "1,8,32"
        del argv[i:i + 2]
    if "--replicas" in argv:
        i = argv.index("--replicas")
        SERVE_REPLICAS = argv[i + 1] if i + 1 < len(argv) else "1,2"
        del argv[i:i + 2]
    if "--hosts" in argv:
        i = argv.index("--hosts")
        MULTIHOST_HOSTS = int(argv[i + 1]) if i + 1 < len(argv) else 2
        del argv[i:i + 2]
    if "--trace" in argv:
        # `bench.py fleet --trace [seed[,duration_s[,rps]]]` — the spec
        # is optional (defaults in bench_fleet); a bare --trace keeps
        # the seeded defaults.
        i = argv.index("--trace")
        if i + 1 < len(argv) and not argv[i + 1].startswith("-") \
                and argv[i + 1] not in CONFIGS:
            FLEET_TRACE = argv[i + 1]
            del argv[i:i + 2]
        else:
            FLEET_TRACE = ""
            del argv[i]
    name = argv[0] if argv else "deepfm"
    # Liveness probe: one tiny device round-trip. A dead tunnel hangs
    # HERE, inside the short early-watchdog tier, producing a structured
    # failure in <5 min; once it answers, the watchdog relaxes so a long
    # (legitimate) compile later in the run can't false-positive.
    _tick("device-probe")
    import jax.numpy as jnp
    _sync(jnp.ones((8,), jnp.float32).sum())
    if name in ("deepfm", "wide_deep") and not _SMALL:
        # (updates/step, payload width, pass keys) of the selected CTR
        # config — aw = emb_dim + 4 ([g_emb | g_w | show | click |
        # count]). Small/CPU mode never selects the Pallas path (flag
        # "auto" gates on the tpu backend), so no preflight.
        _tick("preflight")
        if name == "deepfm":
            _preflight_scatter_kernel(BATCH * NUM_SLOTS, EMB_DIM + 4,
                                      PASS_KEYS)
            _preflight_gather_kernel(BATCH * NUM_SLOTS, EMB_DIM,
                                     PASS_KEYS)
        else:
            _preflight_scatter_kernel(WIDE_DEEP_BATCH * WIDE_DEEP_SLOTS,
                                      WIDE_DEEP_EMB_DIM + 4,
                                      WIDE_DEEP_PASS_KEYS)
            _preflight_gather_kernel(WIDE_DEEP_BATCH * WIDE_DEEP_SLOTS,
                                     WIDE_DEEP_EMB_DIM,
                                     WIDE_DEEP_PASS_KEYS)
    _tick(f"bench:{name}")
    out = CONFIGS[name]()
    # Recorded artifacts must be attributable to hardware: the recorder
    # refuses to treat non-tpu numbers as baselines.
    out["platform"] = jax.default_backend()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
