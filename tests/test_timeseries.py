"""The bounded metric-history ring (core/timeseries.py).

Pins the fleet-health-plane sensor contracts OBSERVABILITY.md
documents: the ring is bounded (retention = FLAGS_history_points),
counters land as per-window deltas that ``rate()`` turns into
events/second, quantile digests land as exact ``delta()`` window
sketches so ``window_quantiles`` answers for the WINDOW (not process
lifetime), ``merge_history`` is associative across hosts like
``monitor.merge_snapshots``, ``to_dict``/``from_dict`` round-trips
through JSON (the ``metrics_history`` RPC payload), and every clock is
injected — a planted-timestamp test never reads wall time, which is
the same property graftlint's replay-purity pass relies on.

No jax import: the history plane is pure stdlib.
"""

import json

import pytest

from paddlebox_tpu.core import monitor
from paddlebox_tpu.core.timeseries import (HistorySampler, MetricHistory,
                                           merge_history)


def _planted(reg, t0=1000.0, n=6, step=10.0, per_window=50,
             lat=lambda i: 5.0):
    """Drive ``n`` sample windows over ``reg``: ``per_window`` counter
    events and quantile observations per window, gauge = window index.
    Returns the history (ring of n+1 points: base + n windows)."""
    h = MetricHistory(reg, points=64, label="planted",
                      clock=lambda: 0.0)
    h.sample(now=t0)  # delta base
    for i in range(n):
        reg.add("req/count", per_window)
        reg.set_gauge("load/gauge", float(i))
        for _ in range(per_window):
            reg.observe_quantile("req/ms", lat(i))
        h.sample(now=t0 + (i + 1) * step)
    return h


# -- ring bound ---------------------------------------------------------------


def test_ring_bound_drops_oldest():
    reg = monitor.Monitor()
    h = MetricHistory(reg, points=4, label="bound",
                      clock=lambda: 0.0)
    for i in range(10):
        reg.add("c", 1)
        h.sample(now=100.0 + i)
    assert len(h) == 4
    pts = h.points()
    assert [p["ts"] for p in pts] == [106.0, 107.0, 108.0, 109.0]
    # Every retained point carries the one-event delta.
    assert all(p["counters"]["c"] == 1 for p in pts)


# -- counters → deltas → rate -------------------------------------------------


def test_counter_deltas_and_rate():
    reg = monitor.Monitor()
    h = _planted(reg, per_window=50, step=10.0)
    # Each point stores the per-window delta, not the cumulative value.
    assert [v for _, v in h.series("req/count")][1:] == [50] * 6
    # 50 events per 10s window → 5/s, over any window that spans >= 2
    # points.
    assert h.rate("req/count") == pytest.approx(5.0)
    assert h.rate("req/count", window_s=20.0) == pytest.approx(5.0)
    # delta() sums the window's events; the first in-window point is
    # the delta base, so a 25s window covers two 10s deltas.
    assert h.delta("req/count") == pytest.approx(300)
    assert h.delta("req/count", window_s=25.0) == pytest.approx(100)
    # Gauges are last-value: latest wins, series carries each sample.
    assert h.latest("load/gauge") == 5.0
    assert h.rate("absent") is None or h.rate("absent") == 0.0


def test_rate_needs_two_points():
    reg = monitor.Monitor()
    h = MetricHistory(reg, points=8, clock=lambda: 0.0)
    reg.add("c", 7)
    h.sample(now=50.0)
    assert h.rate("c") is None  # single point = no span


# -- digest windows -----------------------------------------------------------


def test_window_quantiles_answer_for_the_window():
    """Lifetime digest says ~5ms (300 fast + 50 slow); the LAST window
    contains only the slow observations — window p50 must see 100ms,
    proving the per-point sketches are delta() windows."""
    reg = monitor.Monitor()
    h = _planted(reg, n=7, lat=lambda i: 100.0 if i == 6 else 5.0)
    last = h.window_quantiles("req/ms", window_s=10.0)
    assert last["count"] == 50
    assert last["p50"] == pytest.approx(100.0, rel=0.2)
    whole = h.window_quantiles("req/ms")
    assert whole["count"] == 350
    assert whole["p50"] == pytest.approx(5.0, rel=0.2)
    assert h.window_quantiles("never/observed") == {}


# -- serialization ------------------------------------------------------------


def test_to_dict_from_dict_round_trip_through_json():
    reg = monitor.Monitor()
    h = _planted(reg)
    wire = json.loads(json.dumps(h.to_dict()))  # the RPC payload path
    back = MetricHistory.from_dict(wire)
    assert len(back) == len(h)
    assert back.rate("req/count") == h.rate("req/count")
    assert back.delta("req/count") == h.delta("req/count")
    assert (back.window_quantiles("req/ms")["p99"]
            == h.window_quantiles("req/ms")["p99"])
    # window_s / last_n trims the payload without touching the ring.
    assert len(h.to_dict(last_n=2)["points"]) == 2
    assert len(h.to_dict(window_s=10.0)["points"]) < len(h)
    assert len(h) == 7


# -- merge across hosts -------------------------------------------------------


def _host(seed, t0, lat):
    reg = monitor.Monitor()
    return _planted(reg, t0=t0, n=4, per_window=10 + seed,
                    lat=lambda i: lat).to_dict()


def test_merge_history_sums_counters_means_gauges_merges_digests():
    a = _host(0, 1000.0, 5.0)
    b = _host(5, 1000.0, 50.0)
    m = merge_history([a, b], bucket_s=10.0)
    back = MetricHistory.from_dict(m)
    # Aligned buckets: counter deltas SUM (10 + 15 per window).
    assert back.delta("req/count") == pytest.approx(4 * 25)
    # Gauges MEAN within a bucket (both hosts report the same i).
    assert back.latest("load/gauge") == pytest.approx(3.0)
    # Digest windows MERGE: the cluster p99 sees the slow host.
    assert back.window_quantiles("req/ms")["p99"] >= 40.0


def test_merge_history_is_associative():
    hosts = [_host(i, 1000.0, 5.0 * (i + 1)) for i in range(3)]
    left = merge_history(
        [merge_history(hosts[:2], bucket_s=10.0), hosts[2]],
        bucket_s=10.0)
    flat = merge_history(hosts, bucket_s=10.0)
    la, fa = MetricHistory.from_dict(left), MetricHistory.from_dict(flat)
    assert la.delta("req/count") == pytest.approx(fa.delta("req/count"))
    assert (la.window_quantiles("req/ms")["p99"]
            == pytest.approx(fa.window_quantiles("req/ms")["p99"]))
    assert merge_history([])["points"] == []


# -- injected-clock purity ----------------------------------------------------


def test_injected_clock_means_no_wall_reads():
    """Sampling AND querying with a planted clock must be wall-time
    independent: two runs with identical planted timestamps produce
    identical rings even though real time passed between them — the
    replay-purity property graftlint walks StreamRunner for."""
    def run():
        reg = monitor.Monitor()
        h = _planted(reg, t0=123456.0)
        return (h.to_dict(), h.rate("req/count", window_s=30.0),
                h.window_quantiles("req/ms", window_s=30.0))
    assert run() == run()

    # A sentinel clock that fails on ANY call proves query paths never
    # consult the clock once planted `now` timestamps drive sample().
    def boom():  # pragma: no cover - must never run
        raise AssertionError("history read wall clock")

    reg = monitor.Monitor()
    h = MetricHistory(reg, points=8, clock=boom)
    reg.add("c", 3)
    h.sample(now=10.0)
    reg.add("c", 3)
    h.sample(now=20.0)
    assert h.rate("c") == pytest.approx(0.3)
    assert h.points(window_s=100.0)


# -- the sampler --------------------------------------------------------------


def test_sampler_ticks_all_histories_and_contains_callbacks():
    regs = [monitor.Monitor() for _ in range(2)]
    s = HistorySampler(clock=lambda: 0.0)
    hs = [s.register(MetricHistory(r, points=8, clock=lambda: 0.0))
          for r in regs]
    seen = []
    s.add_callback("ok", seen.append)
    s.add_callback("boom", lambda ts: 1 / 0)  # contained, never raises
    errs0 = monitor.GLOBAL.get("history/callback_errors")
    assert s.tick(now=100.0) == 2
    assert s.tick(now=110.0) == 2
    assert all(len(h) == 2 for h in hs)
    assert seen == [100.0, 110.0]
    assert monitor.GLOBAL.get("history/callback_errors") == errs0 + 2
    s.remove_callback("boom")
    s.tick(now=120.0)
    assert monitor.GLOBAL.get("history/callback_errors") == errs0 + 2
    assert not s.running  # never started a thread: hand-driven ticks
