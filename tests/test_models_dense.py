"""Dense model zoo + checkpoint + AMP + optimizer tests."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu import amp
from paddlebox_tpu.checkpoint import (CheckpointProtocol,
                                      get_online_pass_interval, load_pytree,
                                      save_pytree)
from paddlebox_tpu.models.bert import BertConfig, bert_mlm_loss, init_bert
from paddlebox_tpu.models.resnet import ResNet
from paddlebox_tpu.optimizers import make_optimizer, warmup_cosine
from paddlebox_tpu.parallel import HybridTopology, build_mesh


# -- ResNet ------------------------------------------------------------------

def test_resnet18_forward_and_train_step():
    model = ResNet(depth=18, num_classes=10, width=8)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits, new_params = jax.jit(
        lambda p, x: model.apply(p, x, train=True))(params, x)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()
    # BN stats updated
    assert not np.allclose(np.asarray(new_params["stem_bn"]["mean"]),
                           np.asarray(params["stem_bn"]["mean"]))
    # eval mode: stats unchanged
    logits_eval, p_eval = model.apply(params, x, train=False)
    np.testing.assert_array_equal(np.asarray(p_eval["stem_bn"]["mean"]),
                                  np.asarray(params["stem_bn"]["mean"]))


def test_resnet50_shapes():
    model = ResNet(depth=50, num_classes=10, width=8)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.zeros((1, 64, 64, 3))
    logits, _ = jax.jit(lambda p, x: model.apply(p, x, train=False))(params, x)
    assert logits.shape == (1, 10)


def test_resnet_learns():
    model = ResNet(depth=18, num_classes=2, width=8)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    # Two classes separated by channel mean.
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    x, y = jnp.asarray(x), jnp.asarray(y)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits, newp = model.apply(p, x, train=True)
            logz = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
            return jnp.mean(logz - tgt), newp
        (loss, newp), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(g, opt_state)
        # newp carries the updated BN stats; apply the grad step on top.
        params = optax.apply_updates(newp, updates)
        return params, opt_state, loss

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# -- BERT --------------------------------------------------------------------

BCFG = BertConfig(vocab_size=100, d_model=32, n_heads=4, n_layers=2,
                  d_ff=64, max_seq_len=32)


def test_bert_mlm_dp_parity(devices8):
    """dp-sharded MLM loss == single-device loss (role of the reference's
    dist parity tests, test_dist_base.py)."""
    params = init_bert(jax.random.PRNGKey(0), BCFG)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 100, (8, 16)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, 100, (8, 16)), jnp.int32)
    mask = jnp.asarray(rng.random((8, 16)) < 0.15, jnp.float32)

    single = bert_mlm_loss(params, BCFG, tokens, targets, mask)

    mesh = build_mesh(HybridTopology(dp=8))
    f = jax.shard_map(
        lambda p, t, tg, m: bert_mlm_loss(p, BCFG, t, tg, m,
                                          axis_name="dp"),
        mesh=mesh, in_specs=(P(), P("dp"), P("dp"), P("dp")),
        out_specs=P(), check_vma=False)
    dist = f(params, tokens, targets, mask)
    np.testing.assert_allclose(float(dist), float(single), rtol=1e-5)


def test_bert_train_step_learns():
    params = init_bert(jax.random.PRNGKey(0), BCFG)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 100, (8, 16)), jnp.int32)
    mask = jnp.asarray(np.ones((8, 16)), jnp.float32)

    @jax.jit
    def step(params, opt_state):
        loss, g = jax.value_and_grad(
            lambda p: bert_mlm_loss(p, BCFG, tokens, tokens, mask))(params)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


# -- checkpoint --------------------------------------------------------------

def test_dense_checkpoint_roundtrip(tmp_path):
    tree = {"a": jnp.arange(6.0).reshape(2, 3),
            "nested": {"b": jnp.ones((4,)), "c": [jnp.zeros((2,)),
                                                 jnp.full((1,), 7.0)]}}
    path = str(tmp_path / "ckpt" / "model.npz")
    save_pytree(tree, path, step=42)
    template = jax.tree.map(jnp.zeros_like, tree)
    restored, step = load_pytree(template, path)
    assert step == 42
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), tree, restored)


def test_checkpoint_missing_key_raises(tmp_path):
    path = str(tmp_path / "m.npz")
    save_pytree({"a": jnp.ones(2)}, path)
    with pytest.raises(KeyError):
        load_pytree({"a": jnp.zeros(2), "b": jnp.zeros(3)}, path)


def test_protocol_publish_and_recover(tmp_path):
    proto = CheckpointProtocol(str(tmp_path / "out"))
    assert proto.last_published() is None
    # Day base then two pass deltas, then next day's base.
    assert proto.publish("20260729", -1, key=111)
    assert proto.publish("20260729", 1)
    assert proto.publish("20260729", 2)
    # Duplicate publication is refused (donefile idempotence).
    assert not proto.publish("20260729", 2)
    last = proto.last_published()
    assert last.pass_id == 2 and last.day == "20260729"
    base, deltas = proto.recovery_chain()
    assert base.pass_id == 0
    assert [d.pass_id for d in deltas] == [1, 2]
    # New day base resets the chain.
    proto.publish("20260730", -1)
    base, deltas = proto.recovery_chain()
    assert base.day == "20260730" and deltas == []


def test_online_pass_interval():
    passes = get_online_pass_interval(list(range(24)), split_interval=60,
                                      split_per_pass=4)
    assert len(passes) == 6
    assert passes[0] == ["0000", "0100", "0200", "0300"]
    hourly = get_online_pass_interval([0, 1, 2, 3], split_interval=60,
                                      split_per_pass=2,
                                      is_data_hourly_placed=True)
    assert hourly[0] == ["00", "01"]


# -- AMP ---------------------------------------------------------------------

def test_amp_policy_cast():
    pol = amp.bf16_policy()
    tree = {"w": jnp.ones((2, 2), jnp.float32), "step": jnp.int32(3)}
    lo = pol.cast_to_compute(tree)
    assert lo["w"].dtype == jnp.bfloat16
    assert lo["step"].dtype == jnp.int32  # non-float untouched
    hi = pol.cast_to_param(lo)
    assert hi["w"].dtype == jnp.float32


def test_loss_scaling_dynamics():
    state = amp.loss_scale_init(1024.0, growth_interval=2)
    grads = {"g": jnp.ones((3,)) * 1024.0}
    # finite step: grads unscaled, tracker++
    g1, finite, state = amp.unscale_and_check(state, grads)
    assert bool(finite)
    np.testing.assert_allclose(np.asarray(g1["g"]), 1.0)
    assert float(state.scale) == 1024.0
    # second finite step hits growth_interval: scale doubles
    _, _, state = amp.unscale_and_check(state, grads)
    assert float(state.scale) == 2048.0
    # non-finite: backoff, skip
    bad = {"g": jnp.array([jnp.inf, 1.0, 1.0])}
    _, finite, state = amp.unscale_and_check(state, bad)
    assert not bool(finite)
    assert float(state.scale) == 1024.0
    # masked_update keeps old params on bad step
    old = {"w": jnp.zeros(2)}
    new = {"w": jnp.ones(2)}
    sel = amp.masked_update(finite, new, old)
    np.testing.assert_array_equal(np.asarray(sel["w"]), [0.0, 0.0])


# -- optimizers --------------------------------------------------------------

@pytest.mark.parametrize("name", ["sgd", "momentum", "adam", "adamw",
                                  "lars", "lamb"])
def test_optimizer_factory(name):
    tx = make_optimizer(name, 1e-2, weight_decay=0.01, clip_norm=1.0)
    params = {"w": jnp.ones((4, 4))}
    state = tx.init(params)
    grads = {"w": jnp.full((4, 4), 0.1)}
    updates, state = tx.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    assert not np.allclose(np.asarray(new["w"]), np.asarray(params["w"]))


def test_unknown_optimizer_raises():
    with pytest.raises(ValueError):
        make_optimizer("adagrad2000", 1e-3)


def test_warmup_cosine_schedule():
    sched = warmup_cosine(1e-3, warmup_steps=10, total_steps=100)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
    assert float(sched(100)) < 1e-4
