"""Test fixture: run the suite on a virtual 8-device CPU mesh.

Role of the reference's localhost fake-cluster test mechanism
(``test_dist_base.py:1041`` spawning trainers with env-injected topology):
instead of subprocesses, JAX gives us N virtual devices in one process via
``--xla_force_host_platform_device_count``, so every multi-chip sharding test
runs single-process on CPU. Real-TPU behavior is exercised by bench.py and
the driver's dryrun on actual hardware.

This file must set the env vars BEFORE jax is imported anywhere.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# Force CPU for tests even when the session env points at a TPU platform
# (e.g. JAX_PLATFORMS=axon): sharding tests need 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
# Keep CPU feature autotuning quiet/deterministic in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# sitecustomize may have imported jax before this conftest ran, freezing the
# platform choice from the original env — override via the config API too.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]
