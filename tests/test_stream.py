"""Streaming online-learning tier (ONLINE.md): source carving, durable
cursor resume, streamed-vs-batch bit-parity, the event→servable
freshness digest, and decay/TTL lifecycle parity across every store
variant's shrink()."""

import json
import os

import numpy as np
import pytest

from paddlebox_tpu.core import flags, monitor
from paddlebox_tpu.data import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.stream import StreamCursor, StreamRunner, StreamSource
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item")
BS = 32


@pytest.fixture
def flagset():
    """Set flags for one test; restore previous values afterwards."""
    prev = {}

    def set_(**kw):
        for k in kw:
            prev.setdefault(k, flags.flag(k))
        flags.set_flags(kw)

    yield set_
    flags.set_flags(prev)


def _write_event_file(log_dir, name, rows, rng, lo=1, hi=200,
                      mtime=None):
    """One atomically-appearing log segment of ``rows`` events."""
    os.makedirs(log_dir, exist_ok=True)
    tmp = os.path.join(log_dir, "." + name + ".tmp")
    with open(tmp, "w") as f:
        for _ in range(rows):
            toks = " ".join(f"{s}:{rng.integers(lo, hi)}" for s in SLOTS)
            f.write(f"{int(rng.random() < 0.3)} {toks}\n")
    path = os.path.join(log_dir, name)
    os.replace(tmp, path)
    if mtime is not None:
        os.utime(path, (mtime, mtime))
    return path


def _make_trainer():
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=BS)
    tr = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10))
    tr.init(seed=0)
    return tr, feed


def _digests(trainer):
    import hashlib

    import jax
    store = trainer.engine.store
    keys = np.sort(store.key_stats()[0]) if hasattr(store, "key_stats") \
        else np.sort(store.dirty_keys())
    vals = store.pull_for_pass(keys)
    h = hashlib.sha256()
    h.update(keys.tobytes())
    for f in sorted(vals):
        h.update(np.ascontiguousarray(vals[f]).tobytes())
    hd = hashlib.sha256()
    for x in jax.tree.leaves(jax.device_get(trainer.params)):
        hd.update(np.ascontiguousarray(x).tobytes())
    for x in jax.tree.leaves(jax.device_get(trainer.opt_state)):
        hd.update(np.ascontiguousarray(x).tobytes())
    return h.hexdigest(), hd.hexdigest(), int(store.num_features)


# ---------------------------------------------------------------------------
# source + cursor (no trainer)
# ---------------------------------------------------------------------------

def test_carve_by_event_count(tmp_path, flagset):
    rng = np.random.default_rng(0)
    log = str(tmp_path / "log")
    for i in range(5):
        _write_event_file(log, f"f{i:03d}.log", 10, rng)
    flagset(stream_pass_events=20, stream_pass_window_s=0.0)
    src = StreamSource(log, clock=lambda: 0.0)
    src.poll()
    protos = src.carve()
    # 10+10 closes a pass twice; the 10-event tail stays pending.
    assert [(len(fs), ev) for _d, fs, ev, _t in protos] == [(2, 20),
                                                           (2, 20)]
    assert len(src.pending()) == 1
    tail = src.carve(flush=True)
    assert [(len(fs), ev) for _d, fs, ev, _t in tail] == [(1, 10)]
    assert src.pending() == []


def test_carve_by_time_window(tmp_path, flagset):
    rng = np.random.default_rng(1)
    log = str(tmp_path / "log")
    _write_event_file(log, "a.log", 4, rng, mtime=1000.0)
    _write_event_file(log, "b.log", 4, rng, mtime=1030.0)
    flagset(stream_pass_events=0, stream_pass_window_s=60.0)
    clock = {"now": 1040.0}
    src = StreamSource(log, clock=lambda: clock["now"])
    src.poll()
    assert src.carve() == []          # oldest event only 40s old
    clock["now"] = 1061.0
    protos = src.carve()
    assert len(protos) == 1
    day, files, events, oldest = protos[0]
    assert events == 8 and oldest == 1000.0 and len(files) == 2


def test_carve_closes_at_day_change(tmp_path, flagset):
    rng = np.random.default_rng(2)
    log = str(tmp_path / "log")
    _write_event_file(log, "d0-a.log", 3, rng)
    _write_event_file(log, "d0-b.log", 3, rng)
    _write_event_file(log, "d1-a.log", 3, rng)
    flagset(stream_pass_events=100, stream_pass_window_s=0.0)
    src = StreamSource(log, clock=lambda: 0.0,
                       day_of=lambda p: os.path.basename(p).split("-")[0])
    src.poll()
    protos = src.carve(flush=True)
    assert [(d, len(fs)) for d, fs, _e, _t in protos] == [("d0", 2),
                                                          ("d1", 1)]


def test_cursor_durable_and_ordered(tmp_path):
    path = str(tmp_path / "cursor.json")
    c = StreamCursor(path)
    m1 = c.append("d0", ["/x/a", "/x/b"], 64, 123.0)
    m2 = c.append("d0", ["/x/c"], 32, 456.0)
    m3 = c.append("d1", ["/x/d"], 16, 789.0)
    assert (m1.pass_id, m2.pass_id, m3.pass_id) == (1, 2, 1)
    # A fresh reader sees the identical committed assignment.
    c2 = StreamCursor(path)
    assert [m.to_dict() for m in c2.manifests] == \
        [m.to_dict() for m in c.manifests]
    assert c2.consumed_files() == {"/x/a", "/x/b", "/x/c", "/x/d"}
    assert c2.next_pass_id("d0") == 3 and c2.next_pass_id("d2") == 1
    # The cursor file is valid JSON (operators read it in incidents).
    with open(path) as f:
        data = json.load(f)
    assert data["version"] == 1 and len(data["manifests"]) == 3


def test_source_skips_consumed_files(tmp_path, flagset):
    rng = np.random.default_rng(3)
    log = str(tmp_path / "log")
    a = _write_event_file(log, "a.log", 4, rng)
    flagset(stream_pass_events=1, stream_pass_window_s=0.0)
    src = StreamSource(log, clock=lambda: 0.0, consumed={a})
    src.poll()
    assert src.carve(flush=True) == []
    _write_event_file(log, "b.log", 4, rng)
    src.poll()
    protos = src.carve(flush=True)
    assert len(protos) == 1 and os.path.basename(protos[0][1][0]) == "b.log"


# ---------------------------------------------------------------------------
# streamed day == batch day (bit parity)
# ---------------------------------------------------------------------------

def test_streamed_day_bit_identical_to_batch_day(tmp_path, flagset):
    """A full day consumed as 4 streamed incremental passes yields
    BIT-identical dense params, optimizer state and store to the same
    data trained as ONE batch pass at the same data order (lifecycle
    flags off). File sizes are batch-aligned so the batch sequence is
    identical; shuffle off on both sides."""
    rng = np.random.default_rng(7)
    log = str(tmp_path / "log")
    files = [_write_event_file(log, f"p{i}.log", BS, rng)
             for i in range(4)]

    # Batch side: one pass over all four files, then the day boundary.
    # ONE reader thread: with several files per pass and no shuffle,
    # multi-threaded chunk arrival order IS the data order — "same data
    # order" (the parity contract) needs the deterministic reader.
    tr_b, feed = _make_trainer()
    batch = StreamRunner(tr_b, feed, str(tmp_path / "out_b"),
                         log_dir=str(tmp_path / "nolog"),
                         shuffle=False, num_reader_threads=1)
    batch.train_pass("stream", 1, files)
    batch.day_end("stream")
    dig_b = _digests(tr_b)

    # Stream side: the same files as four carved single-file passes.
    flagset(stream_pass_events=BS, stream_pass_window_s=0.0)
    tr_s, feed = _make_trainer()
    stream = StreamRunner(tr_s, feed, str(tmp_path / "out_s"),
                          log_dir=log, shuffle=False,
                          num_reader_threads=1)
    n = stream.poll_once(flush=True)
    assert n == 4
    stream.end_day()
    dig_s = _digests(tr_s)

    assert dig_s == dig_b  # (store sha, dense sha, num_features)
    # And the stream side published one delta per pass + the day base.
    recs = [(r.day, r.pass_id) for r in stream.ckpt.records()]
    assert recs == [("stream", 1), ("stream", 2), ("stream", 3),
                    ("stream", 4), ("stream", 0)]


# ---------------------------------------------------------------------------
# resume semantics + freshness
# ---------------------------------------------------------------------------

def test_resume_trains_unpublished_manifest(tmp_path, flagset):
    """Crash-after-cursor-commit, simulated in-process: a manifest is
    durable but its pass never published — resume() must train exactly
    that file set."""
    rng = np.random.default_rng(11)
    log = str(tmp_path / "log")
    f = _write_event_file(log, "a.log", BS, rng)
    out = str(tmp_path / "out")
    os.makedirs(out, exist_ok=True)
    StreamCursor(os.path.join(out, "stream_cursor.json")).append(
        "stream", [f], BS, os.path.getmtime(f))

    tr, feed = _make_trainer()
    runner = StreamRunner(tr, feed, out, log_dir=log, shuffle=False,
                          num_reader_threads=2)
    runner.resume()
    assert [(r.day, r.pass_id) for r in runner.ckpt.records()] == \
        [("stream", 1)]
    # The file is consumed: a poll carves nothing new.
    assert runner.poll_once(flush=True) == 0


def test_resume_skips_published_and_continues(tmp_path, flagset):
    rng = np.random.default_rng(13)
    log = str(tmp_path / "log")
    _write_event_file(log, "a.log", BS, rng)
    flagset(stream_pass_events=BS, stream_pass_window_s=0.0)
    out = str(tmp_path / "out")

    tr1, feed = _make_trainer()
    r1 = StreamRunner(tr1, feed, out, log_dir=log, shuffle=False,
                      num_reader_threads=2)
    assert r1.poll_once(flush=True) == 1
    n_feat = tr1.engine.store.num_features

    # "Restart": fresh trainer + runner over the same output root.
    tr2, feed = _make_trainer()
    r2 = StreamRunner(tr2, feed, out, log_dir=log, shuffle=False,
                      num_reader_threads=2)
    r2.resume()
    assert tr2.engine.store.num_features == n_feat      # model recovered
    assert [(r.day, r.pass_id) for r in r2.ckpt.records()] == \
        [("stream", 1)]                                 # nothing re-published
    # New traffic keeps flowing with continuous pass numbering.
    _write_event_file(log, "b.log", BS, rng)
    assert r2.poll_once(flush=True) == 1
    assert [(r.day, r.pass_id) for r in r2.ckpt.records()] == \
        [("stream", 1), ("stream", 2)]


def test_freshness_digest_and_day_rollover(tmp_path, flagset):
    """Per-pass event→servable latency lands in the registry digest
    (count == passes), computed against the injected clock; a day-label
    change publishes the previous day's base mid-stream."""
    rng = np.random.default_rng(17)
    log = str(tmp_path / "log")
    t0 = 1_000_000.0
    _write_event_file(log, "d0-a.log", BS, rng, mtime=t0)
    _write_event_file(log, "d1-a.log", BS, rng, mtime=t0 + 60)
    flagset(stream_pass_events=BS, stream_pass_window_s=0.0)
    base = monitor.GLOBAL.quantile_digest("stream/event_to_servable_ms")

    tr, feed = _make_trainer()
    clock = {"now": t0 + 100.0}
    runner = StreamRunner(
        tr, feed, str(tmp_path / "out"), log_dir=log, shuffle=False,
        num_reader_threads=2, clock=lambda: clock["now"],
        day_of=lambda p: os.path.basename(p).split("-")[0])
    assert runner.poll_once(flush=True) == 2
    runner.end_day()
    recs = [(r.day, r.pass_id) for r in runner.ckpt.records()]
    # d0 delta, d0 base (rolled over BEFORE d1 trained), d1 delta, d1 base.
    assert recs == [("d0", 1), ("d0", 0), ("d1", 1), ("d1", 0)]
    d = monitor.GLOBAL.quantile_digest("stream/event_to_servable_ms")
    assert d is not None
    win = d.delta(base) if base is not None else d
    assert win.count == 2
    # This run's two observations off the INJECTED clock: the d0 pass's
    # oldest event is 100s old at ack, the d1 pass's 40s (1% sketch
    # error on each).
    assert win.quantile(0.0) == pytest.approx(40e3, rel=0.02)
    assert win.quantile(1.0) == pytest.approx(100e3, rel=0.02)


# ---------------------------------------------------------------------------
# lifecycle: decay / TTL / min-show across the store variants
# ---------------------------------------------------------------------------

CFG = TableConfig(name="t", dim=4, learning_rate=0.1,
                  show_click_decay=0.9)


def _touch(store, keys):
    """Training write-back stand-in: pull rows, set show=1, push."""
    k = np.sort(np.asarray(keys, np.uint64))
    vals = store.pull_for_pass(k)
    vals["show"] = np.ones_like(vals["show"])
    store.push_from_pass(k, vals)


def _lifecycle_scenario(store):
    """Shared drill: day1 touches A∪B, day2 touches only B; with
    ttl=1, day3's shrink evicts exactly A (unseen 2 days)."""
    a = np.arange(2, 22, 2, dtype=np.uint64)       # evens
    b = np.arange(101, 111, dtype=np.uint64)
    _touch(store, np.concatenate([a, b]))
    store.shrink()                                  # day 1 boundary
    _touch(store, b)
    store.shrink()                                  # day 2: A at age 2
    surv_a = store.contains(a)
    surv_b = store.contains(b)
    return surv_a, surv_b, int(store.num_features)


@pytest.mark.parametrize("variant", [
    "flat", "sharded", "device", "tiered", "grouped", "multihost"])
def test_lifecycle_parity_across_variants(variant, tmp_path, flagset):
    """Unit parity of the unseen-days TTL across ALL six store
    variants: day1 touches A∪B, day2 touches only B, the day-2 shrink
    (ttl=1) evicts exactly A everywhere."""
    flagset(table_ttl_days=1, table_decay_rate=0.0, table_min_show=0.0)
    servers = None
    if variant == "grouped":
        # The dim-grouped facade: drive each width group's member store
        # through the same scenario, shrink ONCE through the facade —
        # a feasign ages independently per width group.
        from paddlebox_tpu.embedding.grouped import GroupedEngine
        a = np.arange(2, 22, 2, dtype=np.uint64)
        b = np.arange(101, 111, dtype=np.uint64)
        eng = GroupedEngine(CFG, {"a": 4, "b": 8})
        for g in eng.groups:
            _touch(g.engine.store, np.concatenate([a, b]))
        eng.store.shrink()
        for g in eng.groups:
            _touch(g.engine.store, b)
        eng.store.shrink()
        for g in eng.groups:
            assert not g.engine.store.contains(a).any()
            assert g.engine.store.contains(b).all()
        assert eng.store.num_features == 2 * 10
        return
    if variant == "flat":
        store = FeatureStore(CFG)
    elif variant == "sharded":
        from paddlebox_tpu.embedding.sharded_store import \
            ShardedFeatureStore
        store = ShardedFeatureStore(CFG, num_buckets=4, num_threads=2)
    elif variant == "device":
        from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
        store = DeviceFeatureStore(CFG)
    elif variant == "tiered":
        from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
        # RAM budget below the working set: rows MUST cross the disk
        # tier, proving ages ride the spill/stage-in path.
        store = TieredFeatureStore(CFG, str(tmp_path / "ssd"),
                                   max_ram_features=6)
    else:
        from paddlebox_tpu.multihost import (MultiHostStore,
                                             start_local_shards,
                                             stop_shards)
        servers, eps = start_local_shards(2, CFG)
        store = MultiHostStore(CFG, eps)
    try:
        surv_a, surv_b, n = _lifecycle_scenario(store)
    finally:
        if servers is not None:
            store.close()
            stop_shards(servers)
    assert not surv_a.any(), f"{variant}: TTL must evict unseen rows"
    assert surv_b.all(), f"{variant}: touched rows must survive"
    assert n == 10


def test_lifecycle_show_values_match_flat(flagset, tmp_path):
    """Decay parity: surviving rows' show values after the scenario are
    bit-identical between the flat store and each composed variant."""
    from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
    from paddlebox_tpu.embedding.sharded_store import ShardedFeatureStore
    from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
    flagset(table_ttl_days=1, table_decay_rate=0.0, table_min_show=0.0)
    b = np.arange(101, 111, dtype=np.uint64)

    def run(store):
        _lifecycle_scenario(store)
        return store.pull_for_pass(b)["show"]

    ref = run(FeatureStore(CFG))
    np.testing.assert_array_equal(
        ref, run(ShardedFeatureStore(CFG, num_buckets=4, num_threads=2)))
    np.testing.assert_array_equal(ref, run(DeviceFeatureStore(CFG)))
    np.testing.assert_array_equal(
        ref, run(TieredFeatureStore(CFG, str(tmp_path / "ssd"),
                                    max_ram_features=6)))
    # One decay after the touch: show == 0.9 exactly.
    np.testing.assert_allclose(ref, np.float32(0.9))


def test_decay_rate_flag_overrides_config(flagset):
    flagset(table_decay_rate=0.5)
    store = FeatureStore(CFG)      # config decay is 0.9
    k = np.arange(1, 5, dtype=np.uint64)
    _touch(store, k)
    store.shrink()
    np.testing.assert_allclose(store.pull_for_pass(k)["show"],
                               np.float32(0.5))


def test_min_show_flag_floor(flagset):
    flagset(table_min_show=0.6, table_decay_rate=0.0)
    store = FeatureStore(CFG)
    k = np.arange(1, 5, dtype=np.uint64)
    _touch(store, k)               # show 1.0 -> decays to 0.9
    assert store.shrink() == 0     # 0.9 >= 0.6 floor
    assert store.shrink(min_show=0.95) == 4  # caller above the floor wins


def test_ttl_age_survives_ssd_spill(flagset, tmp_path):
    """A row's unseen-days clock must not reset when it round-trips
    through the disk tier."""
    from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
    flagset(table_ttl_days=0)
    store = TieredFeatureStore(CFG, str(tmp_path / "ssd"),
                               max_ram_features=4)
    cold = np.arange(1, 5, dtype=np.uint64)
    _touch(store, cold)
    store.shrink()                 # cold at age 1
    hot = np.arange(100, 108, dtype=np.uint64)
    _touch(store, hot)             # evicts the cold (show-decayed) rows
    assert store.ram.num_features <= 4
    ages = store.unseen_for(cold)
    np.testing.assert_array_equal(ages, 1)   # tracked on disk
    # Stage back in (read pull) — age still 1, not reset to 0.
    store.pull_for_pass(cold)
    np.testing.assert_array_equal(store.unseen_for(cold), 1)


def test_ttl_bounds_store_under_churning_traffic(flagset):
    """The acceptance shape: 3 'days' of churning keys with TTL on —
    the resident row count stays bounded instead of growing linearly."""
    flagset(table_ttl_days=1)
    store = FeatureStore(CFG)
    per_day = 200
    day_rows = []
    for day in range(3):
        lo = 1 + day * per_day // 2          # half carries, half churns
        keys = np.arange(lo, lo + per_day, dtype=np.uint64)
        _touch(store, keys)
        store.shrink()
        day_rows.append(store.num_features)
    assert day_rows[2] <= day_rows[0] * 1.5, day_rows
    # And without lifecycle the same traffic grows monotonically.
    flags.set_flags({"table_ttl_days": 0})
    ref = FeatureStore(CFG)
    ref_rows = []
    for day in range(3):
        lo = 1 + day * per_day // 2
        _touch(ref, np.arange(lo, lo + per_day, dtype=np.uint64))
        ref.shrink()
        ref_rows.append(ref.num_features)
    assert ref_rows[2] > day_rows[2]


def test_shrink_still_guards_save_delta(tmp_path, flagset):
    store = FeatureStore(CFG)
    _touch(store, np.arange(1, 9, dtype=np.uint64))
    store.shrink()
    with pytest.raises(RuntimeError, match="save_delta after shrink"):
        store.save_delta(str(tmp_path / "d"))


# ---------------------------------------------------------------------------
# persisted TTL ages (the ages sidecar — ROADMAP item-2 follow-up)
# ---------------------------------------------------------------------------

def _store_variant(variant, tmp_path):
    if variant == "flat":
        return FeatureStore(CFG), None
    if variant == "sharded":
        from paddlebox_tpu.embedding.sharded_store import \
            ShardedFeatureStore
        return ShardedFeatureStore(CFG, num_buckets=4, num_threads=2), None
    if variant == "device":
        from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
        return DeviceFeatureStore(CFG), None
    if variant == "tiered":
        from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
        return TieredFeatureStore(CFG, str(tmp_path / "ssd"),
                                  max_ram_features=6), None
    from paddlebox_tpu.multihost import MultiHostStore, start_local_shards
    servers, eps = start_local_shards(2, CFG)
    return MultiHostStore(CFG, eps), servers


@pytest.mark.parametrize("variant", [
    "flat", "sharded", "device", "tiered", "multihost"])
def test_ttl_ages_persist_across_restart(variant, tmp_path, flagset):
    """The ages sidecar (ONLINE.md "persisted TTL ages"): a save_base →
    fresh-process load round-trip preserves every row's unseen-days
    age, so a restart no longer grants aged rows a fresh TTL lease —
    rows one shrink from eviction still evict one shrink after the
    restart. (The grouped facade delegates to these per-group stores.)"""
    from paddlebox_tpu.multihost import stop_shards
    flagset(table_ttl_days=2, table_decay_rate=0.0, table_min_show=0.0)
    a = np.arange(2, 22, 2, dtype=np.uint64)        # will be age 2
    b = np.arange(101, 111, dtype=np.uint64)        # will be age 0
    store, servers = _store_variant(variant, tmp_path)
    store2, servers2 = None, None
    try:
        _touch(store, a)
        store.shrink()                               # a at age 1
        store.shrink()                               # a at age 2
        _touch(store, b)                             # b at age 0
        path = str(tmp_path / "ck")
        store.save_base(path)
        np.testing.assert_array_equal(store.unseen_for(a), 2)

        # "Restart": a brand-new store loads the same checkpoint.
        store2, servers2 = _store_variant(variant, tmp_path / "re")
        store2.load(path, "base")
        np.testing.assert_array_equal(store2.unseen_for(a), 2)
        np.testing.assert_array_equal(store2.unseen_for(b), 0)
        # One more shrink pushes a PAST ttl=2 — evicted, b survives.
        evicted = store2.shrink()
        assert evicted == a.size
        assert not store2.contains(a).any()
        assert store2.contains(b).all()
    finally:
        for s, srv in ((store, servers), (store2, servers2)):
            if srv is not None:
                s.close()
                stop_shards(srv)


def test_ttl_ages_persist_through_delta_chain(tmp_path, flagset):
    """Delta checkpoints carry the sidecar too: base + delta reload
    restores the delta keys' saved ages instead of zeroing them."""
    flagset(table_ttl_days=0)
    store = FeatureStore(CFG)
    a = np.arange(1, 9, dtype=np.uint64)
    _touch(store, a)
    base = str(tmp_path / "base")
    store.save_base(base)
    b = np.arange(50, 58, dtype=np.uint64)
    _touch(store, b)
    delta = str(tmp_path / "delta")
    store.save_delta(delta)

    re = FeatureStore(CFG)
    re.load(base, "base")
    re.load(delta, "delta")
    np.testing.assert_array_equal(re.unseen_for(a), 0)
    np.testing.assert_array_equal(re.unseen_for(b), 0)
    # Pre-sidecar checkpoints (sidecar removed) still load — rows just
    # restart their lease, the documented legacy behavior.
    os.unlink(os.path.join(base, "t.base.ages.npz"))
    legacy = FeatureStore(CFG)
    legacy.load(base, "base")
    assert legacy.num_features == a.size


def test_tiered_disk_ages_persist_across_restart(tmp_path, flagset):
    """Disk-tier rows' ages persist too (the RowAges side table rides
    its own sidecar beside the copied buckets)."""
    from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
    flagset(table_ttl_days=0, table_decay_rate=0.0)
    store = TieredFeatureStore(CFG, str(tmp_path / "ssd"),
                               max_ram_features=4)
    cold = np.arange(1, 5, dtype=np.uint64)
    _touch(store, cold)
    store.shrink()                    # cold at age 1
    hot = np.arange(100, 108, dtype=np.uint64)
    _touch(store, hot)                # spills cold rows to disk
    np.testing.assert_array_equal(store.unseen_for(cold), 1)
    path = str(tmp_path / "ck")
    store.save_base(path)

    re = TieredFeatureStore(CFG, str(tmp_path / "ssd2"),
                            max_ram_features=4)
    re.load(path, "base")
    np.testing.assert_array_equal(re.unseen_for(cold), 1)
    np.testing.assert_array_equal(re.unseen_for(hot), 0)


# ---------------------------------------------------------------------------
# byte-offset tail cursor (FLAGS_stream_tail_bytes)
# ---------------------------------------------------------------------------

def _append_lines(path, rows, rng, partial=False):
    """Append complete event lines (plus optionally one UNTERMINATED
    partial line) to a growing log file."""
    with open(path, "a") as f:
        for _ in range(rows):
            toks = " ".join(f"{s}:{rng.integers(1, 200)}" for s in SLOTS)
            f.write(f"{int(rng.random() < 0.3)} {toks}\n")
        if partial:
            f.write("1 user:17 item")          # no newline: in flight


def test_tail_carves_byte_ranges_of_growing_file(tmp_path, flagset):
    from paddlebox_tpu.data.dataset import split_byte_range
    rng = np.random.default_rng(11)
    log = str(tmp_path / "log")
    os.makedirs(log)
    path = os.path.join(log, "live.log")
    _append_lines(path, 6, rng, partial=True)
    flagset(stream_tail_bytes=True, stream_pass_events=4,
            stream_pass_window_s=0.0)
    src = StreamSource(log, clock=lambda: 0.0)
    src.poll()
    protos = src.carve(flush=True)
    # 6 complete lines consumed; the partial trailing line stays with
    # the writer.
    assert len(protos) == 1
    _d, files, events, _t = protos[0]
    assert events == 6 and len(files) == 1
    base, start, end = split_byte_range(files[0])
    assert base == path and start == 0
    with open(path, "rb") as f:
        assert f.read(end)[-1:] == b"\n"

    # The writer finishes the partial line and appends more: the next
    # poll registers EXACTLY the new complete bytes.
    with open(path, "a") as f:
        f.write(":9\n")
    _append_lines(path, 3, rng)
    src.poll()
    protos = src.carve(flush=True)
    assert len(protos) == 1
    _d, files2, events2, _t = protos[0]
    b2, s2, e2 = split_byte_range(files2[0])
    assert (b2, s2) == (path, end) and events2 == 4
    sz = os.path.getsize(path)
    assert e2 == sz


def test_tail_mode_trains_ranges_and_matches_whole_file(tmp_path,
                                                        flagset):
    """A Dataset fed byte-range specs parses exactly the same rows as
    the whole file split into segments — the reader seam under the
    tail cursor."""
    from paddlebox_tpu.data.dataset import BYTE_RANGE_SEP, Dataset
    rng = np.random.default_rng(12)
    log = str(tmp_path / "log")
    path = _write_event_file(log, "seg.log", 12, rng)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        buf = f.read()
    cut = buf.find(b"\n", size // 2) + 1          # a mid-file line cut
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=4)

    def rows_of(files):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        out = [(c.num_rows) for c in ds._chunks]
        total = sum(out)
        ds.clear()
        return total

    whole = rows_of([path])
    ranged = rows_of([f"{path}{BYTE_RANGE_SEP}0-{cut}",
                      f"{path}{BYTE_RANGE_SEP}{cut}-{size}"])
    assert whole == ranged == 12


def test_tail_cursor_resume_mid_file(tmp_path, flagset):
    """Restart with a mid-file cursor: the re-built source resumes at
    the recorded byte offset — nothing lost, nothing re-consumed."""
    rng = np.random.default_rng(13)
    log = str(tmp_path / "log")
    os.makedirs(log)
    path = os.path.join(log, "live.log")
    _append_lines(path, 5, rng)
    flagset(stream_tail_bytes=True, stream_pass_events=1,
            stream_pass_window_s=0.0)
    cursor = StreamCursor(str(tmp_path / "cursor.json"))
    src = StreamSource(log, clock=lambda: 0.0,
                       consumed=cursor.consumed_files())
    src.poll()
    protos = src.carve(flush=True)
    assert len(protos) == 1 and protos[0][2] == 5
    m = cursor.append(protos[0][0], protos[0][1], protos[0][2],
                      protos[0][3])

    # "kill -9": a fresh source rebuilt from the durable cursor.
    _append_lines(path, 4, rng)
    cursor2 = StreamCursor(str(tmp_path / "cursor.json"))
    assert [x.to_dict() for x in cursor2.manifests] == [m.to_dict()]
    src2 = StreamSource(log, clock=lambda: 0.0,
                        consumed=cursor2.consumed_files())
    src2.poll()
    protos2 = src2.carve(flush=True)
    assert len(protos2) == 1 and protos2[0][2] == 4
    from paddlebox_tpu.data.dataset import split_byte_range
    _b, s, e = split_byte_range(protos2[0][1][0])
    _b0, s0, e0 = split_byte_range(m.files[0])
    assert s == e0 and e == os.path.getsize(path)
    # Event totals across both incarnations are exact: 5 + 4 = 9.
    assert protos[0][2] + protos2[0][2] == 9


def test_whole_segment_mode_skips_mid_file_cursor(tmp_path, flagset):
    """Flipping tail mode OFF with a mid-file cursor on record must
    NOT re-consume the file from byte 0 (that would duplicate
    events) — the file is skipped with a warning."""
    from paddlebox_tpu.data.dataset import BYTE_RANGE_SEP
    rng = np.random.default_rng(14)
    log = str(tmp_path / "log")
    os.makedirs(log)
    path = os.path.join(log, "live.log")
    _append_lines(path, 4, rng)
    flagset(stream_tail_bytes=False, stream_pass_events=1,
            stream_pass_window_s=0.0)
    src = StreamSource(log, clock=lambda: 0.0,
                       consumed={f"{path}{BYTE_RANGE_SEP}0-10"})
    src.poll()
    assert src.carve(flush=True) == []
