"""K-step scanned device megastep (FLAGS_trainer_steps_per_dispatch).

The megastep exists to amortize host dispatch + sync out of the CTR hot
loop: K steps run inside ONE lax.scan'd XLA program, so the pass loop
pays one dispatch and at most one host sync per K steps. Capacity is
padding and the scan is a pure re-staging of the same per-step body —
so K=4 must be BIT-identical to K=1 on CPU: params, opt_state, AUC
state, and every per-step loss, including a non-multiple-of-K step
count (masked tail block) and a kstep dense-sync boundary that falls
mid-block. The dispatch/sync-count pins are the acceptance criterion:
O(steps) -> O(steps/K).
"""

import numpy as np
import pytest

import jax

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("u", "i", "c")


def _shard(path, n, seed=7, n_keys=150):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, n_keys, rng.integers(1, 3))
                     for s in SLOTS}
            click = np.mean([(int(v) % 5 == 0)
                             for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * click)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shard_13(tmp_path_factory):
    # 13 batches of 32 -> K=4 gives blocks of 4,4,4,1: the tail block
    # exercises the masked partial-block path in every test below.
    return _shard(tmp_path_factory.mktemp("mega") / "part-0", 13 * 32)


def _dataset(p):
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    return feed, ds


def _run(p, k, cfg=None, passes=1, check_nan=False):
    """Train `passes` passes at steps_per_dispatch=k; returns (trainer,
    stats list, flat per-step losses across all passes)."""
    cfg = cfg or TrainerConfig(auc_num_buckets=1 << 10,
                               check_nan_inf=check_nan)
    feed, ds = _dataset(p)
    mesh = build_mesh(HybridTopology(dp=8))
    tr = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                    feed, TableConfig(dim=8, learning_rate=0.1),
                    mesh=mesh, config=cfg,
                    store_factory=lambda c: DeviceFeatureStore(
                        c, mesh=mesh))
    tr.init(seed=0)
    tr._debug_collect_losses = True
    prev = flagmod.flag("trainer_steps_per_dispatch")
    flagmod.set_flags({"trainer_steps_per_dispatch": k})
    try:
        stats = [tr.train_pass(ds) for _ in range(passes)]
    finally:
        flagmod.set_flags({"trainer_steps_per_dispatch": prev})
    losses = []
    for _base, blk, n_active in tr._debug_losses:
        arr = np.atleast_1d(np.asarray(blk))
        losses.extend(arr[:n_active].tolist())
    return tr, stats, np.asarray(losses)


def _assert_trees_bitwise(a, b, what):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb), what
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=what)


def test_k4_bitwise_matches_k1_with_partial_tail(shard_13):
    """Full-pass bit-parity at a non-multiple-of-K step count: params,
    opt_state, AUC state, and every per-step loss."""
    t1, s1, l1 = _run(shard_13, 1)
    t4, s4, l4 = _run(shard_13, 4)
    assert s1[0]["steps"] == 13 and s4[0]["steps"] == 13
    np.testing.assert_array_equal(l1, l4)
    _assert_trees_bitwise(t1.params, t4.params, "params")
    _assert_trees_bitwise(t1.opt_state, t4.opt_state, "opt_state")
    _assert_trees_bitwise(t1.auc_state, t4.auc_state, "auc_state")
    # Same tables too: the store's written-back rows must agree.
    np.testing.assert_allclose(s1[0]["auc"], s4[0]["auc"], rtol=0)


def test_k4_kstep_sync_boundary_mid_block(shard_13):
    """kstep local-SGD with interval 3 under K=4: the in-scan step
    counter must fire the pmean at global steps 3,6,9,12 — inside
    blocks, not at block edges — bit-identical to the host-computed
    per-step sync_flag."""
    cfg = dict(dense_optimizer="sgd", dense_learning_rate=0.05,
               auc_num_buckets=1 << 10, dense_sync_mode="kstep",
               dense_sync_interval=3)
    t1, _, l1 = _run(shard_13, 1, TrainerConfig(**cfg))
    t4, _, l4 = _run(shard_13, 4, TrainerConfig(**cfg))
    np.testing.assert_array_equal(l1, l4)
    _assert_trees_bitwise(t1.params, t4.params, "params (kstep)")
    _assert_trees_bitwise(t1.opt_state, t4.opt_state, "opt_state (kstep)")


def test_dispatch_and_sync_counts_drop_by_k(shard_13):
    """The acceptance pin: host dispatches AND host syncs drop from
    O(steps) to O(steps/K). check_nan_inf is ON so the sync counter
    counts the per-block finite-vector fetches."""
    _, s1, _ = _run(shard_13, 1, check_nan=True)
    _, s4, _ = _run(shard_13, 4, check_nan=True)
    assert s1[0]["steps_per_dispatch"] == 1
    assert s4[0]["steps_per_dispatch"] == 4
    assert s1[0]["dispatch_blocks"] == 13
    assert s4[0]["dispatch_blocks"] == 4        # ceil(13/4)
    assert s1[0]["host_syncs"] == 13            # one finite fetch/step
    assert s4[0]["host_syncs"] == 4             # one finite fetch/block
    # Without check_nan_inf the loop body never blocks at all.
    _, s0, _ = _run(shard_13, 4)
    assert s0[0]["host_syncs"] == 0


def test_check_nan_inf_reports_global_step_index(shard_13):
    """check_nan_inf raises from the per-block finite vector with the
    OFFENDING global step, not the block index."""
    tr, _, _ = _run(shard_13, 4, check_nan=True)  # warm + build mega fn
    orig = tr._mega_fn

    def poisoned(*args):
        out = orig(*args)
        tables, params, opt_state, auc, losses, overflows, finites = out
        # Poison in-block step 1 of the SECOND block -> global step 6
        # (1-based), leaving the first block clean.
        if int(np.asarray(args[4])) == 4:  # step0 of block 1
            import jax.numpy as jnp
            losses = losses.at[1].set(jnp.nan)
            finites = finites.at[1].set(False)
        return (tables, params, opt_state, auc, losses, overflows,
                finites)

    tr._mega_fn = poisoned
    feed, ds = _dataset(shard_13)
    prev = flagmod.flag("trainer_steps_per_dispatch")
    flagmod.set_flags({"trainer_steps_per_dispatch": 4})
    try:
        with pytest.raises(FloatingPointError, match="step 6"):
            tr.train_pass(ds)
    finally:
        flagmod.set_flags({"trainer_steps_per_dispatch": prev})
        tr._mega_fn = orig


def test_async_mode_forces_k1(shard_13):
    cfg = TrainerConfig(dense_learning_rate=3e-3,
                        auc_num_buckets=1 << 10, dense_sync_mode="async")
    tr, stats, _ = _run(shard_13, 4, cfg)
    try:
        assert stats[0]["steps_per_dispatch"] == 1
        assert stats[0]["dispatch_blocks"] == stats[0]["steps"] == 13
    finally:
        tr._async_dense.stop()


def test_eval_pass_megastep_matches_k1(shard_13):
    """Eval megastep: AUC/loss identical between K=1 and K=4 (read-only
    scan, masked tail)."""
    feed, ds = _dataset(shard_13)
    mesh = build_mesh(HybridTopology(dp=8))

    def build():
        tr = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                        feed, TableConfig(dim=8, learning_rate=0.1),
                        mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        return tr

    prev = flagmod.flag("trainer_steps_per_dispatch")
    try:
        flagmod.set_flags({"trainer_steps_per_dispatch": 1})
        e1 = build().eval_pass(ds)
        flagmod.set_flags({"trainer_steps_per_dispatch": 4})
        e4 = build().eval_pass(ds)
    finally:
        flagmod.set_flags({"trainer_steps_per_dispatch": prev})
    assert e1["steps"] == e4["steps"] == 13
    np.testing.assert_array_equal(e1["auc"], e4["auc"])
    np.testing.assert_allclose(e1["loss"], e4["loss"], rtol=1e-6)


def test_auto_capacity_ratchet_with_megastep(tmp_path):
    """Auto-capacity under K=4: pass 1 measures caps from the first
    STACKED block (before the scanned fn is built); a second pass over
    a hotter key mix may only ratchet caps UP (rebuild) — and results
    stay identical to the K=1 auto-capacity run throughout."""
    # Duplicate-heavy first day, wider key range second day.
    p_small = _shard(tmp_path / "d0", 8 * 32, seed=1, n_keys=12)
    p_big = _shard(tmp_path / "d1", 8 * 32, seed=2, n_keys=400)

    def run(k):
        feed = DataFeedConfig(
            slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
            batch_size=32)
        mesh = build_mesh(HybridTopology(dp=8))
        tr = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                        feed, TableConfig(dim=8, learning_rate=0.1),
                        mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        flagmod.set_flags({"trainer_steps_per_dispatch": k,
                           "embedding_auto_capacity": True})
        caps = []
        stats = []
        try:
            for p in (p_small, p_big):
                ds = Dataset(feed, num_reader_threads=1)
                ds.set_filelist([p])
                ds.load_into_memory()
                stats.append(tr.train_pass(ds))
                caps.append(tr._step_caps)
        finally:
            flagmod.set_flags({"trainer_steps_per_dispatch": 1,
                               "embedding_auto_capacity": False})
        return tr, stats, caps

    t1, s1, caps1 = run(1)
    t4, s4, caps4 = run(4)
    for s in s1 + s4:
        assert s["lookup_overflow"] == 0
    assert caps4[0] is not None
    # Ratchet semantics: caps never shrink across passes.
    for c0, c1 in zip(caps4[0], caps4[1]):
        if c0 is not None and c1 is not None:
            assert c1 >= c0
    # Capacity is padding, never math: K=4 matches K=1 even while the
    # two measured different caps from their first block vs first batch.
    for a, b in zip(s1, s4):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-6)
        np.testing.assert_allclose(a["auc"], b["auc"], rtol=1e-6)
    _assert_trees_bitwise(t1.params, t4.params, "params (auto-cap)")
