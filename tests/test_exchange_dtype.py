"""FLAGS_embedding_exchange_dtype: reduced-precision all_to_all wire.

The pull-reply and push-grad payloads may cross the ICI as bf16 or as
int8 with per-block f32 scales (EQuARX-style quantized exchange —
PAPERS.md; codec in multihost/quant.py) while every accumulation stays
f32: grads merge sender-side in f32 (the bucket scatter-add), ride the
wire reduced, and widen back before the owner-side accumulate. Pins:
(1) 'f32' is BIT-identical to the pre-flag behavior (the cast path
must be a no-op, not a f32->f32 convert), (2) 'bf16' matches within
bf16 tolerance and 'int8' within the per-block quantization bound,
(3) the exchange-bytes observable reflects the halved/quartered
payload (int8 counts its scale sidecar), (4) unknown values fail
loudly.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.embedding import (SparseAdagrad, TableConfig,
                                     make_pull_fn, make_push_fn)
from paddlebox_tpu.embedding.lookup import exchange_bytes
from paddlebox_tpu.embedding.table import (build_pass_table_host,
                                           extract_pass_values_host,
                                           map_keys_to_rows)
from paddlebox_tpu.parallel import HybridTopology, build_mesh

DIM = 8
CFG = TableConfig(dim=DIM, learning_rate=0.1, initial_g2sum=1.0)


def _setup(seed=3, n_keys=60, n_ids=128, nshards=8):
    rng = np.random.default_rng(seed)
    vals = {
        "emb": rng.normal(size=(n_keys, DIM)).astype(np.float32),
        "emb_state": np.zeros((n_keys, 1), np.float32),
        "w": rng.normal(size=(n_keys,)).astype(np.float32),
        "w_state": np.zeros((n_keys, 1), np.float32),
        "show": np.zeros((n_keys,), np.float32),
        "click": np.zeros((n_keys,), np.float32),
    }
    keys = np.sort(rng.choice(np.arange(1, 100_000, dtype=np.uint64),
                              n_keys, replace=False))
    batch_keys = rng.choice(keys, n_ids).astype(np.uint64)
    table = build_pass_table_host(vals, nshards, CFG)
    rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
    mesh = build_mesh(HybridTopology(dp=nshards))
    g_emb = rng.normal(size=(n_ids, DIM)).astype(np.float32)
    g_w = rng.normal(size=(n_ids,)).astype(np.float32)
    ones = np.ones((n_ids,), np.float32)
    return table, mesh, jnp.asarray(rows), (g_emb, g_w, ones), n_keys


def _pull_push(mode):
    table, mesh, rows, (g_emb, g_w, ones), n_keys = _setup()
    prev = flagmod.flag("embedding_exchange_dtype")
    flagmod.set_flags({"embedding_exchange_dtype": mode})
    try:
        pulled = make_pull_fn(mesh, "dp")(table, rows)
        new_table = make_push_fn(mesh, "dp", SparseAdagrad.from_config(
            CFG))(table, rows, jnp.asarray(g_emb), jnp.asarray(g_w),
                  jnp.asarray(ones), jnp.asarray(ones))
    finally:
        flagmod.set_flags({"embedding_exchange_dtype": prev})
    return (np.asarray(pulled["emb"]), np.asarray(pulled["w"]),
            extract_pass_values_host(new_table, n_keys))


def test_f32_wire_is_bit_identical_to_default():
    """Explicit 'f32' == the default path, bitwise — the flag code must
    not add so much as a convert on the exact path."""
    emb_d, w_d, pushed_d = _pull_push("f32")
    emb_2, w_2, pushed_2 = _pull_push("f32")
    np.testing.assert_array_equal(emb_d, emb_2)
    np.testing.assert_array_equal(w_d, w_2)
    for f in pushed_d:
        np.testing.assert_array_equal(pushed_d[f], pushed_2[f],
                                      err_msg=f"field {f}")


def test_bf16_wire_parity_within_tolerance():
    emb_f, w_f, pushed_f = _pull_push("f32")
    emb_b, w_b, pushed_b = _pull_push("bf16")
    # bf16 has ~8 mantissa bits: 2^-8 relative on the wire values.
    np.testing.assert_allclose(emb_b, emb_f, rtol=8e-3, atol=8e-3)
    np.testing.assert_allclose(w_b, w_f, rtol=8e-3, atol=8e-3)
    for f in pushed_f:
        np.testing.assert_allclose(
            pushed_b[f], pushed_f[f], rtol=2e-2, atol=2e-2,
            err_msg=f"field {f}")
    # ...and the quantization actually happened (values differ, so a
    # future refactor can't silently drop the cast and keep passing).
    assert not np.array_equal(emb_b, emb_f)


def test_exchange_bytes_tracks_wire_dtype():
    table, _, rows, _, _ = _setup()
    n = int(rows.shape[0])
    prev = flagmod.flag("embedding_exchange_dtype")
    try:
        flagmod.set_flags({"embedding_exchange_dtype": "f32"})
        b_f32 = exchange_bytes(table, n)
        flagmod.set_flags({"embedding_exchange_dtype": "bf16"})
        b_bf16 = exchange_bytes(table, n)
    finally:
        flagmod.set_flags({"embedding_exchange_dtype": prev})
    assert b_bf16 < b_f32
    # Only the payload halves — the two int32 row exchanges don't — so
    # the ratio sits strictly between 0.5 and 1, near 0.5 at this width.
    ratio = b_bf16 / b_f32
    assert 0.5 < ratio < 0.62, ratio


def test_int8_wire_parity_within_tolerance():
    """Per-block int8: error per value is bounded by the block's
    absmax / 254 on the wire; the pulled values and one pushed update
    stay within that envelope while the table/accumulation never leave
    f32."""
    emb_f, w_f, pushed_f = _pull_push("f32")
    emb_i, w_i, pushed_i = _pull_push("int8")
    np.testing.assert_allclose(emb_i, emb_f, rtol=4e-2, atol=4e-2)
    np.testing.assert_allclose(w_i, w_f, rtol=4e-2, atol=4e-2)
    for f in pushed_f:
        np.testing.assert_allclose(
            pushed_i[f], pushed_f[f], rtol=5e-2, atol=1.5e-1,
            err_msg=f"field {f}")
    # ...and the quantization actually happened.
    assert not np.array_equal(emb_i, emb_f)


def test_exchange_bytes_int8_below_bf16():
    """The byte accounting must reflect the quartered payload plus the
    f32 scale sidecar: int8 < bf16 < f32, and int8's payload half sits
    near a quarter of f32's (scales add < 1 f32 per `block` values)."""
    table, _, rows, _, _ = _setup()
    n = int(rows.shape[0])
    prev = flagmod.flag("embedding_exchange_dtype")
    try:
        sizes = {}
        for mode in ("f32", "bf16", "int8"):
            flagmod.set_flags({"embedding_exchange_dtype": mode})
            sizes[mode] = exchange_bytes(table, n)
    finally:
        flagmod.set_flags({"embedding_exchange_dtype": prev})
    assert sizes["int8"] < sizes["bf16"] < sizes["f32"]
    # Row exchanges stay int32, so the total ratio sits strictly above
    # the pure-payload 1/4 but below bf16's.
    assert 0.25 < sizes["int8"] / sizes["f32"] < 0.5


def test_int8_wire_bits_recorded():
    from paddlebox_tpu.core import monitor
    from paddlebox_tpu.embedding.lookup import record_exchange_stats
    table, _, rows, _, _ = _setup()
    prev = flagmod.flag("embedding_exchange_dtype")
    try:
        flagmod.set_flags({"embedding_exchange_dtype": "int8"})
        record_exchange_stats([table], [int(rows.shape[0])], [None])
    finally:
        flagmod.set_flags({"embedding_exchange_dtype": prev})
    assert monitor.GLOBAL.get_gauge("lookup/wire_bits") == 8.0


def test_unknown_exchange_dtype_raises():
    table, mesh, rows, _, _ = _setup()
    prev = flagmod.flag("embedding_exchange_dtype")
    flagmod.set_flags({"embedding_exchange_dtype": "fp8"})
    try:
        with pytest.raises(ValueError, match="embedding_exchange_dtype"):
            make_pull_fn(mesh, "dp")(table, rows)
    finally:
        flagmod.set_flags({"embedding_exchange_dtype": prev})
