"""Hybrid-parallel GPT tests: the reference's hybrid_parallel_pp_transformer
parity bar — hybrid (dp×pp×sp×mp) loss == single-device dense loss, and a
training step improves it."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlebox_tpu.models.gpt import (GPTConfig, gpt_loss_fn, init_gpt,
                                      make_gpt_train_step)
from paddlebox_tpu.parallel import HybridTopology, build_mesh

CFG = GPTConfig(vocab_size=128, d_model=32, n_heads=4, n_layers=4, d_ff=64,
                max_seq_len=64)


def _dense_reference_loss(params, tokens, targets, cfg):
    """Single-device numpy/jnp reference of the same architecture."""
    x = params["embed"][tokens] + params["pos"][jnp.arange(tokens.shape[1])]

    def ln(x, g, b, eps=1e-5):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + eps) * g + b

    # layers stacked [pp, lps, ...] -> iterate in order
    layers = params["layers"]
    n_pp = jax.tree.leaves(layers)[0].shape[0]
    lps = jax.tree.leaves(layers)[0].shape[1]
    hd = cfg.d_model // cfg.n_heads
    for s in range(n_pp):
        for l in range(lps):
            p = jax.tree.map(lambda a: a[s, l], layers)
            h = ln(x, p["ln1_g"], p["ln1_b"])
            b, t, d = h.shape
            # head-major column layout (see _layer_init)
            qkv = (h @ p["wqkv"]).reshape(b, t, cfg.n_heads, 3, hd)
            q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
            scores = jnp.einsum("bqhd,bkhd->bqhk", q, k) / np.sqrt(hd)
            mask = jnp.tril(jnp.ones((t, t), bool))
            scores = jnp.where(mask[None, :, None, :], scores, -jnp.inf)
            attn = jax.nn.softmax(scores, -1)
            o = jnp.einsum("bqhk,bkhd->bqhd", attn, v).reshape(b, t, d)
            x = x + o @ p["wo"]
            h2 = ln(x, p["ln2_g"], p["ln2_b"])
            x = x + jax.nn.gelu(h2 @ p["wi"] + p["bi"]) @ p["wo2"] + p["bo2"]
    x = ln(x, params["lnf_g"], params["lnf_b"])
    logits = x @ params["head"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - tgt)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, CFG.vocab_size, (8, 32)).astype(np.int32)
    targets = rng.integers(0, CFG.vocab_size, (8, 32)).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


@pytest.mark.parametrize("topo", [
    dict(dp=2, pp=2, sp=1, mp=2),
    dict(dp=1, pp=2, sp=2, mp=2),
    dict(dp=4, sp=2),
    dict(mp=4, sp=2),
])
def test_hybrid_loss_matches_dense(devices8, data, topo):
    mesh = build_mesh(HybridTopology(**topo), devices8)
    pp_stages = topo.get("pp", 1)
    params, specs = init_gpt(jax.random.PRNGKey(0), CFG,
                             pp_stages=pp_stages)
    tokens, targets = data
    loss_fn = gpt_loss_fn(CFG, mesh, specs, num_microbatches=2)
    loss = loss_fn(params, tokens, targets)
    ref = _dense_reference_loss(params, tokens, targets, CFG)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)


@pytest.mark.slow  # loss parity above is the tier-1 oracle; the
# 5-step learn loop compiles the full train step and rides tier-2
def test_hybrid_train_step_learns(devices8, data):
    mesh = build_mesh(HybridTopology(dp=2, pp=2, sp=1, mp=2), devices8)
    params, specs = init_gpt(jax.random.PRNGKey(1), CFG, pp_stages=2)
    tokens, targets = data
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_gpt_train_step(CFG, mesh, specs, opt, num_microbatches=2)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


@pytest.mark.slow  # three extra full-pipeline compiles; the plain
# 1f1b parity in test_1f1b_wired.py stays tier-1
def test_interleaved_1f1b_matches_tied_layer_loss(devices8, data):
    """Interleaved GPT wiring: with every layer's params TIED to the same
    values, the composed function is layer-order-invariant, so the
    interleaved schedule's loss must equal the plain 1F1B loss exactly —
    which isolates the schedule machinery from the (documented)
    layer-layout difference — and a training step must learn."""
    import optax

    mesh = build_mesh(HybridTopology(dp=1, pp=2, sp=1, mp=2),
                      devices8[:4])
    params, specs = init_gpt(jax.random.PRNGKey(2), CFG, pp_stages=2)
    # Tie all layer rows to layer 0's values.
    params = dict(params)
    params["layers"] = jax.tree.map(
        lambda a: jnp.broadcast_to(a[:1, :1], a.shape).copy(),
        params["layers"])
    tokens, targets = data
    opt = optax.adam(1e-3)

    from paddlebox_tpu.models.gpt import gpt_value_and_grad_1f1b
    vg_plain = gpt_value_and_grad_1f1b(CFG, mesh, specs,
                                       num_microbatches=4)
    vg_inter = gpt_value_and_grad_1f1b(CFG, mesh, specs,
                                       num_microbatches=4, num_chunks=2)
    loss_p, grads_p = jax.jit(vg_plain)(params, tokens, targets)
    loss_i, grads_i = jax.jit(vg_inter)(params, tokens, targets)
    np.testing.assert_allclose(float(loss_i), float(loss_p), rtol=1e-5)
    # Under tied layers the composed function is identical, so the
    # layout-independent leaves (embedding cotangent chain + loss_params
    # head channel) must agree — this gradient-checks the interleave's
    # dx0 and lgrads plumbing, not just the forward.
    for name in ("embed", "pos", "lnf_g", "lnf_b", "head"):
        np.testing.assert_allclose(
            np.asarray(grads_i[name]), np.asarray(grads_p[name]),
            rtol=5e-4, atol=1e-6, err_msg=name)

    # End-to-end: the wired step trains under the interleaved schedule.
    params2, specs2 = init_gpt(jax.random.PRNGKey(3), CFG, pp_stages=2)
    opt_state = opt.init(params2)
    step = make_gpt_train_step(CFG, mesh, specs2, opt,
                               num_microbatches=4,
                               schedule="interleaved_1f1b", num_chunks=2)
    losses = []
    for _ in range(5):
        params2, opt_state, loss = step(params2, opt_state, tokens,
                                        targets)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
