"""Interleaved (virtual-stage) 1F1B: gradient parity with direct
autodiff over the full virtual-stage composition, V=1 equivalence with
the plain schedule, and the m % p constraint."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.parallel.pp import (interleaved_one_f_one_b_value_and_grad,
                                       one_f_one_b_value_and_grad)

P_RANKS = 4
F = 6


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def _virtual_stages(v, seed=0):
    """[V*p] per-virtual-stage params, plus the per-rank chunk stacking
    (cyclic layout: virtual stage d -> rank d % p, chunk d // p)."""
    rng = np.random.default_rng(seed)
    stages = [{"w": jnp.asarray(rng.normal(0, 0.5, (F, F)), jnp.float32),
               "b": jnp.asarray(rng.normal(0, 0.1, (F,)), jnp.float32)}
              for _ in range(v * P_RANKS)]
    # chunked[rank] has leaves [V, ...]; stack ranks on a new axis for
    # the pp sharding: leaves become [p, V, ...].
    chunked = [jax.tree.map(lambda *xs: jnp.stack(xs),
                            *[stages[c * P_RANKS + r] for c in range(v)])
               for r in range(P_RANKS)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *chunked)
    return stages, stacked


def _direct_loss(stages, x, t):
    def per_mb(xj, tj):
        h = xj
        for s in stages:
            h = _stage_fn(s, h)
        return _loss_fn(h, tj)
    return jnp.mean(jax.vmap(per_mb)(x, t))


def _run_interleaved(mesh, stacked, x, t, v):
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P("pp")), check_vma=False)
    def run(stacked_, x_mb, t_mb):
        chunks = jax.tree.map(lambda a: a[0], stacked_)
        loss, grads = interleaved_one_f_one_b_value_and_grad(
            _stage_fn, _loss_fn, chunks, x_mb, t_mb,
            num_chunks=v, axis="pp")
        return loss, jax.tree.map(lambda g: g[None], grads)

    return jax.jit(run)(stacked, x, t)


@pytest.mark.parametrize("v,m", [(2, 8), (3, 4), (2, 12)])
def test_interleaved_matches_direct_autodiff(v, m):
    mesh = build_mesh(HybridTopology(pp=P_RANKS),
                      devices=jax.devices()[:P_RANKS])
    stages, stacked = _virtual_stages(v)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(m, 4, F)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(m, 4, F)), jnp.float32)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda ss: _direct_loss(ss, x, t))(stages)
    loss, grads = _run_interleaved(mesh, stacked, x, t, v)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for c in range(v):
        for r in range(P_RANKS):
            got = jax.tree.map(lambda a: np.asarray(a[r, c]), grads)
            ref = jax.tree.map(np.asarray, ref_grads[c * P_RANKS + r])
            np.testing.assert_allclose(got["w"], ref["w"], rtol=2e-4,
                                       atol=1e-6)
            np.testing.assert_allclose(got["b"], ref["b"], rtol=2e-4,
                                       atol=1e-6)


def test_v1_equals_plain_schedule():
    """num_chunks=1 must reproduce the wired 1F1B bit-for-bit — the
    interleave is a strict generalization."""
    mesh = build_mesh(HybridTopology(pp=P_RANKS),
                      devices=jax.devices()[:P_RANKS])
    stages, stacked = _virtual_stages(1)
    rng = np.random.default_rng(2)
    m = 8
    x = jnp.asarray(rng.normal(size=(m, 4, F)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(m, 4, F)), jnp.float32)

    loss_i, grads_i = _run_interleaved(mesh, stacked, x, t, 1)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P("pp")), check_vma=False)
    def run_plain(stacked_, x_mb, t_mb):
        params_local = jax.tree.map(lambda a: a[0, 0], stacked_)
        loss, grads = one_f_one_b_value_and_grad(
            _stage_fn, _loss_fn, params_local, x_mb, t_mb, axis="pp")
        return loss, jax.tree.map(lambda g: g[None, None], grads)

    loss_p, grads_p = jax.jit(run_plain)(stacked, x, t)
    np.testing.assert_allclose(float(loss_i), float(loss_p), rtol=1e-6)
    for leaf_i, leaf_p in zip(jax.tree.leaves(grads_i),
                              jax.tree.leaves(grads_p)):
        np.testing.assert_allclose(np.asarray(leaf_i), np.asarray(leaf_p),
                                   rtol=1e-6, atol=1e-7)


def test_interleaved_channels_match_direct_autodiff():
    """The loss_params (head) and return_input_grads (embedding
    cotangent) channels at V=3: every gradient surface — chunk params,
    head params, and dx0 — must match direct autodiff over the full
    virtual composition."""
    v, m = 3, 8
    mesh = build_mesh(HybridTopology(pp=P_RANKS),
                      devices=jax.devices()[:P_RANKS])
    stages, stacked = _virtual_stages(v, seed=4)
    rng = np.random.default_rng(5)
    head = {"w": jnp.asarray(rng.normal(0, 0.5, (F, F)), jnp.float32)}
    x = jnp.asarray(rng.normal(size=(m, 4, F)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(m, 4, F)), jnp.float32)

    def head_loss(lp, y, tt):
        return jnp.mean((y @ lp["w"] - tt) ** 2)

    def direct(ss, lp, xx):
        def per_mb(xj, tj):
            h = xj
            for s in ss:
                h = _stage_fn(s, h)
            return head_loss(lp, h, tj)
        return jnp.mean(jax.vmap(per_mb)(xx, t))

    ref_loss, (ref_sg, ref_lg, ref_dx) = jax.value_and_grad(
        direct, argnums=(0, 1, 2))(stages, head, x)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=(P(), P("pp"), P(), P()), check_vma=False)
    def run(stacked_, lp, x_mb, t_mb):
        chunks = jax.tree.map(lambda a: a[0], stacked_)
        loss, grads, lgrads, dx0 = \
            interleaved_one_f_one_b_value_and_grad(
                _stage_fn, head_loss, chunks, x_mb, t_mb,
                num_chunks=v, axis="pp", loss_params=lp,
                return_input_grads=True)
        # Documented contract: head grads live on the last rank, dx0 on
        # rank 0 (zero elsewhere) — psum to replicate for P() outputs.
        from jax import lax
        lgrads = jax.tree.map(lambda g: lax.psum(g, "pp"), lgrads)
        dx0 = lax.psum(dx0, "pp")
        return (loss, jax.tree.map(lambda g: g[None], grads),
                lgrads, dx0)

    loss, grads, lgrads, dx0 = jax.jit(run)(stacked, head, x, t)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lgrads["w"]),
                               np.asarray(ref_lg["w"]), rtol=2e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx0), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-6)
    for c in range(v):
        for r in range(P_RANKS):
            got = jax.tree.map(lambda a: np.asarray(a[r, c]), grads)
            ref = jax.tree.map(np.asarray, ref_sg[c * P_RANKS + r])
            np.testing.assert_allclose(got["w"], ref["w"], rtol=2e-4,
                                       atol=1e-6)


def test_rejects_indivisible_microbatches():
    mesh = build_mesh(HybridTopology(pp=P_RANKS),
                      devices=jax.devices()[:P_RANKS])
    stages, stacked = _virtual_stages(2)
    x = jnp.zeros((6, 4, F), jnp.float32)   # 6 % 4 != 0
    t = jnp.zeros((6, 4, F), jnp.float32)
    with pytest.raises(ValueError, match="microbatches % pp"):
        _run_interleaved(mesh, stacked, x, t, 2)
