"""Worker payload for the TRUE multi-process CTR test (spawned by
``python -m paddlebox_tpu.launch --nproc 2 tests/mp_ctr_worker.py``).

Role of the reference worker payloads spawned by _run_cluster
(``test_dist_base.py:1041``): join the cluster via the env contract,
train the tiny config on deterministic data, and report the loss
trajectory so the parent can assert parity with a single-process run.

Usage: mp_ctr_worker.py <data_dir> <out_json>
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    data_dir, out_json = sys.argv[1], sys.argv[2]
    from paddlebox_tpu.distributed import bootstrap
    bootstrap.initialize()   # PBX_* env from the launcher
    assert jax.process_count() == int(os.environ["PBX_NUM_PROCESSES"])

    import numpy as np
    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    ndev = len(jax.devices())        # global across processes
    mesh = build_mesh(HybridTopology(dp=ndev))
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(3))
    feed = DataFeedConfig(slots=slots, batch_size=32)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                   emb_dim=4, hidden=(16,))
    trainer = CTRTrainer(model, feed,
                         TableConfig(dim=4, learning_rate=0.1), mesh=mesh,
                         config=TrainerConfig(auc_num_buckets=1 << 10))
    trainer.init(seed=0)

    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir)
        if f.startswith("part-"))
    losses = []
    for _ in range(2):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        stats = trainer.train_pass(ds)
        losses.append(stats["loss"])

    if jax.process_index() == 0:
        with open(out_json, "w") as f:
            json.dump({"losses": losses,
                       "ndev": ndev,
                       "nproc": jax.process_count()}, f)


if __name__ == "__main__":
    main()
