"""AOT Mosaic-compile checks for every Pallas kernel.

The interpret-mode tests prove the kernels' math; they prove nothing
about whether Mosaic accepts their memory ops (alignment/tiling rules
only the real TPU pipeline enforces — r03 shipped two kernels that were
interpret-correct and Mosaic-rejected: the sorted scatter's unaligned
DMA offsets and the flash attention's (1, block_q) row-stat blocks).
jax's compile-only PJRT topology compiles for TPU with no TPU attached,
so the real pipeline runs in CI: these tests fail the suite if any
kernel stops compiling at the exact shapes the benchmarks use.

Skipped when libtpu's AOT topology is unavailable in the environment.
"""

import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _aot_device():
    from jax.experimental import topologies
    try:
        topo = topologies.get_topology_desc("v5e:2x2x1", "tpu")
        return topo.devices[0]
    except Exception as e:  # noqa: BLE001 - any failure means no libtpu
        pytest.skip(f"no TPU AOT topology available: {e!r}")


# (updates, payload width, rows incl. trash) — bench_deepfm push,
# bench_wide_deep push, and the tiny probe shape.
SHAPES = [
    (425_984, 20, 4_194_305),
    (163_840, 12, 1_048_577),
    (64, 8, 9000),
]


@pytest.mark.slow
@pytest.mark.parametrize("n,aw,rows_n", SHAPES)
def test_scatter_kernel_mosaic_compiles_at_bench_shapes(n, aw, rows_n):
    from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
        sorted_scatter_accumulate)
    dev = _aot_device()
    sh = NamedSharding(Mesh([dev], ("d",)), P())
    rows = jax.ShapeDtypeStruct((n,), jnp.int32, sharding=sh)
    pay = jax.ShapeDtypeStruct((n, aw), jnp.float32, sharding=sh)
    compiled = jax.jit(
        lambda r, p: sorted_scatter_accumulate(r, p, rows_n)
    ).lower(rows, pay).compile()
    assert compiled is not None


# (requests, pull width, table width, rows incl. trash) — bench_deepfm
# pull (426K ids from the [4M, W] fused table; rows NOT a multiple of
# the kernel BLOCK, so this also pins Mosaic's padded tail-block fetch)
# and the tiny probe shape.
GATHER_SHAPES = [
    (425_984, 16, 20, 4_194_305),
    (425_984, 40, 40, 4_194_305),
    (64, 8, 9, 9000),
]


@pytest.mark.slow
@pytest.mark.parametrize("n,pw,w,rows_n", GATHER_SHAPES)
def test_gather_kernel_mosaic_compiles_at_bench_shapes(n, pw, w, rows_n):
    from paddlebox_tpu.ops.pallas_kernels.sorted_gather import sorted_gather
    dev = _aot_device()
    sh = NamedSharding(Mesh([dev], ("d",)), P())
    rows = jax.ShapeDtypeStruct((n,), jnp.int32, sharding=sh)
    tbl = jax.ShapeDtypeStruct((rows_n, w), jnp.float32, sharding=sh)
    compiled = jax.jit(
        lambda r, t: sorted_gather(r, t, width=pw)
    ).lower(rows, tbl).compile()
    assert compiled is not None


@pytest.mark.slow
def test_flash_attention_mosaic_compiles_fwd_bwd():
    """bench_gpt's shape: [4, 1024, 16, 64], causal, with gradients."""
    from paddlebox_tpu.ops.pallas_kernels.flash_attention import (
        flash_attention)
    dev = _aot_device()
    sh = NamedSharding(Mesh([dev], ("d",)), P())
    q = jax.ShapeDtypeStruct((4, 1024, 16, 64), jnp.float32, sharding=sh)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True, use_pallas=True).sum()

    compiled = jax.jit(
        jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
    assert compiled is not None


@pytest.mark.slow
def test_seqpool_cvm_mosaic_compiles():
    from paddlebox_tpu.ops.pallas_kernels.seqpool_cvm import (
        seqpool_cvm_pallas)
    dev = _aot_device()
    sh = NamedSharding(Mesh([dev], ("d",)), P())
    n, d, rows = 65536, 16, 16384
    emb = jax.ShapeDtypeStruct((n, d), jnp.float32, sharding=sh)
    sc = jax.ShapeDtypeStruct((n,), jnp.float32, sharding=sh)
    seg = jax.ShapeDtypeStruct((n,), jnp.int32, sharding=sh)
    compiled = jax.jit(
        lambda e, s, c, g: seqpool_cvm_pallas(e, s, c, g, rows,
                                              use_pallas=True)
    ).lower(emb, sc, sc, seg).compile()
    assert compiled is not None
