"""The multi-window burn-rate alert engine (core/alerts.py).

Pins the objective semantics OBSERVABILITY.md documents: a breach in
the fast window alone parks a rule at PENDING (blip), fast AND slow
together fire it (sustained burn), resolution needs BOTH windows clean
for ``FLAGS_alerts_clear_windows`` consecutive evaluations (hysteresis
— one good sample never flaps a page), thresholds come from live flags
(a 0/unset flag gates the rule out entirely), the default rule pack
validates, a bad pack raises at construction, and ``evaluate_safe``
contains an evaluator crash (counted + retried next tick — the
ROBUSTNESS.md ``alerts/evaluate`` row).

All clocks injected, histories planted — no sampler thread, no wall
time, no jax.
"""

import pytest

from paddlebox_tpu.core import alerts, flags, monitor
from paddlebox_tpu.core.alerts import (AlertEngine, SLORule,
                                       default_rule_pack, validate_rules)
from paddlebox_tpu.core.timeseries import MetricHistory

STEP = 10.0


@pytest.fixture()
def aflags():
    """Short windows so planted rings cover them: fast = exactly the
    newest sample window, slow = the last three plus the current."""
    keys = ("alerts_fast_window_s", "alerts_slow_window_s",
            "alerts_clear_windows")
    prev = {k: flags.flag(k) for k in keys}
    flags.set_flags({"alerts_fast_window_s": STEP - 1.0,
                     "alerts_slow_window_s": 3 * STEP + 1.0,
                     "alerts_clear_windows": 2})
    yield
    flags.set_flags(prev)


class _Plant:
    """A registry + history a test feeds one window at a time."""

    def __init__(self):
        self.reg = monitor.Monitor()
        self.hist = MetricHistory(self.reg, points=64, label="plant",
                                  clock=lambda: 0.0)
        self.t = 1000.0
        self.hist.sample(now=self.t)  # delta base

    def window(self, *, lat_ms=None, n=20, counters=(), gauges=()):
        """One sample window: n latency observations + counter bumps."""
        if lat_ms is not None:
            for _ in range(n):
                self.reg.observe_quantile("serving/predict_ms", lat_ms)
        for name, v in counters:
            self.reg.add(name, v)
        for name, v in gauges:
            self.reg.set_gauge(name, v)
        self.t += STEP
        self.hist.sample(now=self.t)
        return self.t


def _engine(plant, rules, **kw):
    return AlertEngine(plant.hist, rules, clock=lambda: 0.0, **kw)


def _p99_rule(threshold=100.0):
    return SLORule(name="p99", metric="serving/predict_ms",
                   kind="quantile", q="p99", threshold=threshold,
                   severity="page")


# -- burn-rate math on planted histories --------------------------------------


def test_fast_breach_alone_is_pending_not_firing(aflags):
    """Three healthy windows then ONE slow window: the fast window
    breaches but the slow window's merged p99 stays under — blip, not
    burn."""
    p = _Plant()
    eng = _engine(p, [_p99_rule(100.0)], on_page=lambda t: None)
    for _ in range(3):
        t = p.window(lat_ms=5.0, n=400)
        assert eng.evaluate(now=t) == []
        assert eng.state("p99") == "ok"
    t = p.window(lat_ms=500.0, n=2)  # 2 slow among 1200 fast in slow win
    trans = eng.evaluate(now=t)
    assert [(x["from"], x["to"]) for x in trans] == [("ok", "pending")]
    st = eng.active()[0]
    assert st["state"] == "pending"
    assert st["value_fast"] > 100.0 > st["value_slow"]


def test_sustained_breach_fires_then_hysteresis_resolves(aflags):
    """The full PENDING→FIRING→RESOLVED ride: sustained degradation
    fires once both windows burn; recovery resolves only after
    clear_windows consecutive clean evaluations."""
    fired = []
    p = _Plant()
    eng = _engine(p, [_p99_rule(100.0)], on_page=fired.append)
    for _ in range(3):
        eng.evaluate(now=p.window(lat_ms=5.0, n=400))
    # Degrade: window 1's few slow samples breach the fast window only
    # (<1% of the slow window's tail) → pending; by window 2 the slow
    # window burns too.
    eng.evaluate(now=p.window(lat_ms=500.0, n=5))
    assert eng.state("p99") == "pending"
    eng.evaluate(now=p.window(lat_ms=500.0, n=50))
    eng.evaluate(now=p.window(lat_ms=500.0, n=50))
    assert eng.state("p99") == "firing"
    assert len(fired) == 1 and fired[0]["name"] == "p99"
    assert eng.firing_count() == 1
    assert monitor.GLOBAL.get("alert/p99") >= 1
    assert monitor.GLOBAL.get_gauge("alerts/firing") == 1.0
    # Recovery: windows turn clean, but the slow window still holds the
    # bad samples for a while — FIRING holds (no flap), then after the
    # slow window slides clean it takes clear_windows=2 clean evals.
    clean = 0
    states = []
    for _ in range(8):
        t = p.window(lat_ms=5.0, n=50)
        eng.evaluate(now=t)
        states.append(eng.state("p99"))
        if eng.state("p99") == "resolved":
            break
    assert states[-1] == "resolved"
    # No intermediate flap: once firing, only firing→resolved happens.
    assert set(states[:-1]) == {"firing"}
    assert len(fired) == 1  # resolution never pages


def test_clear_windows_hysteresis_counts_consecutive(aflags):
    """A breach DURING recovery resets the clean-eval counter: clean,
    breach, clean, clean → still needs the 2 consecutive cleans AFTER
    the breach."""
    p = _Plant()
    eng = _engine(p, [_p99_rule(100.0)], on_page=lambda t: None)
    eng.evaluate(now=p.window(lat_ms=500.0))
    eng.evaluate(now=p.window(lat_ms=500.0))
    assert eng.state("p99") == "firing"
    # 4 clean windows slide the slow window clean...
    for _ in range(4):
        eng.evaluate(now=p.window(lat_ms=5.0, n=200))
    # ...but a fresh burst mid-recovery resets the counter.
    eng.evaluate(now=p.window(lat_ms=500.0, n=200))
    assert eng.state("p99") == "firing"
    for _ in range(6):
        t = p.window(lat_ms=5.0, n=500)
        eng.evaluate(now=t)
        if eng.state("p99") == "resolved":
            break
    assert eng.state("p99") == "resolved"
    # resolved decays to a NEW cycle on the next breach (pending/firing)
    eng.evaluate(now=p.window(lat_ms=900.0, n=500))
    assert eng.state("p99") in ("pending", "firing")


def test_rate_rule_burn_multiplier_and_delta_prefix(aflags):
    """rate-kind rules gate on threshold*burn events/second; delta-kind
    rules with a trailing * sum the whole counter family and fire on
    ANY event when the threshold is 0."""
    p = _Plant()
    # rate/delta kinds diff CONSECUTIVE points, so their fast window
    # must span two samples (the first is the delta base).
    rules = [SLORule(name="burn", metric="slo/violations", kind="rate",
                     threshold=1.0, burn=2.0, severity="warn",
                     fast_window_s=STEP + 1.0),
             SLORule(name="alarms", metric="quality/alarms/*",
                     kind="delta", threshold=0.0, severity="warn",
                     gate_on_threshold=False,
                     fast_window_s=STEP + 1.0)]
    eng = _engine(p, rules)
    # 15 violations / 10s = 1.5/s: above threshold 1.0 but BELOW the
    # burn bar 1.0*2.0 — must not even go pending.
    for _ in range(4):
        t = p.window(counters=[("slo/violations", 15)])
        eng.evaluate(now=t)
    assert eng.state("burn") == "ok"
    # 30/10s = 3.0/s clears the burn bar in both windows.
    for _ in range(4):
        t = p.window(counters=[("slo/violations", 30)])
        eng.evaluate(now=t)
    assert eng.state("burn") == "firing"
    # One drift alarm anywhere in the family breaches the 0 threshold.
    assert eng.state("alarms") == "ok"
    for _ in range(2):
        t = p.window(counters=[("quality/alarms/auc_drop", 1)])
        eng.evaluate(now=t)
    assert eng.state("alarms") == "firing"


def test_gauge_rule_direction_below(aflags):
    p = _Plant()
    eng = _engine(p, [SLORule(
        name="overlap",
        metric="pass/train_boundary_exchange_overlap_frac",
        kind="gauge", direction="below", threshold=0.5,
        severity="warn")])
    for v in (0.9, 0.8):
        eng.evaluate(now=p.window(gauges=[(
            "pass/train_boundary_exchange_overlap_frac", v)]))
    assert eng.state("overlap") == "ok"
    for _ in range(4):
        t = p.window(gauges=[(
            "pass/train_boundary_exchange_overlap_frac", 0.2)])
        eng.evaluate(now=t)
    assert eng.state("overlap") == "firing"


# -- threshold flags gate rules ----------------------------------------------


def test_threshold_flag_gates_and_retunes_live(aflags):
    """An unset (0) threshold flag means the objective does not exist;
    setting it mid-run arms the rule at the NEXT evaluation — operator
    retunes a live fleet without restarts."""
    prev = flags.flag("serving_slo_p99_ms")
    p = _Plant()
    eng = _engine(p, [SLORule(name="slo", metric="serving/predict_ms",
                              kind="quantile", q="p99",
                              threshold_flag="serving_slo_p99_ms",
                              severity="warn")])
    try:
        flags.set_flags({"serving_slo_p99_ms": 0.0})
        for _ in range(4):
            t = p.window(lat_ms=500.0)
            assert eng.evaluate(now=t) == []
        assert eng.state("slo") == "ok"
        assert eng.active() == []  # gated rules are invisible
        flags.set_flags({"serving_slo_p99_ms": 100.0})
        eng.evaluate(now=p.window(lat_ms=500.0))
        assert eng.state("slo") == "firing"
        assert eng.active()[0]["threshold"] == 100.0
    finally:
        flags.set_flags({"serving_slo_p99_ms": prev})


# -- rule-pack validation -----------------------------------------------------


def test_default_rule_pack_validates():
    pack = default_rule_pack()
    assert validate_rules(pack) == []
    names = {r.name for r in pack}
    assert {"serving_predict_p99", "slo_violation_burn",
            "replica_lag_p99", "stream_freshness_p99",
            "quality_alarm_burst",
            "boundary_overlap_floor"} <= names
    # Engine construction over the default pack must succeed.
    AlertEngine(MetricHistory(monitor.Monitor(), points=4,
                              clock=lambda: 0.0))


def test_bad_rule_pack_rejected():
    bad = [SLORule(name="x", metric="m", kind="nope"),
           SLORule(name="x", metric="m", severity="loud"),
           SLORule(name="", metric=""),
           SLORule(name="w", metric="m", burn=0.0),
           SLORule(name="v", metric="m", fast_window_s=60.0,
                   slow_window_s=30.0)]
    errs = validate_rules(bad)
    assert len(errs) >= 6  # each defect + the duplicate name
    with pytest.raises(ValueError, match="invalid alert rule pack"):
        AlertEngine(None, bad)


# -- containment --------------------------------------------------------------


def test_evaluate_safe_contains_crashes():
    """The sampler-callback entry never raises: a crashing evaluation
    is counted and warned (ROBUSTNESS.md alerts/evaluate row)."""
    class Boom(MetricHistory):
        def points(self, window_s=None):
            raise RuntimeError("planted")

    p = _Plant()
    boom = Boom(p.reg, points=8, clock=lambda: 0.0)
    boom.sample(now=1.0)
    boom.__class__ = Boom  # keep the planted failure after sample()
    eng = AlertEngine(boom, [_p99_rule(1.0)], clock=lambda: 0.0)
    errs0 = monitor.GLOBAL.get("alerts/evaluate_errors")
    # len(history) raises through points()? __len__ reads the deque
    # directly — force the crash inside evaluate via rule evaluation.
    boom.sample(now=2.0)
    assert eng.evaluate_safe(now=3.0) == []
    assert monitor.GLOBAL.get("alerts/evaluate_errors") == errs0 + 1


def test_module_proxies_without_global_engine():
    assert alerts.GLOBAL is None or True  # other tests may have armed it
    prev = alerts.GLOBAL
    alerts.GLOBAL = None
    try:
        assert alerts.enabled() is False
        assert alerts.active_alerts() == []
        assert alerts.firing_count() == 0
    finally:
        alerts.GLOBAL = prev
