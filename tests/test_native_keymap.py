"""Native key-map parity + smoke perf tests (role of the PreBuildTask /
CopyKeys host path, SURVEY.md §7 hard part #1)."""

import time

import numpy as np
import pytest

from paddlebox_tpu.embedding.table import map_keys_to_rows
from paddlebox_tpu.native.build import native_available
from paddlebox_tpu.native.keymap_py import KeyMap, dedup_keys

needs_native = pytest.mark.skipif(not native_available(),
                                  reason="native lib unavailable")


def test_dedup_matches_numpy():
    # Full uint64 range so every range shard (top byte) is exercised and
    # the cross-shard sorted concatenation is verified.
    rng = np.random.default_rng(0)
    keys = rng.integers(0, np.iinfo(np.uint64).max, 100_000, dtype=np.uint64)
    keys[::7] = 0  # null feasigns dropped
    keys[1::3] = keys[::3][:keys[1::3].size]  # heavy duplication
    out = dedup_keys(keys)
    ref = np.unique(keys)
    ref = ref[ref != 0]
    np.testing.assert_array_equal(out, ref)


@needs_native
def test_native_dedup_full_range_all_shards():
    """Force the NATIVE path regardless of core count: full-range keys hit
    all 256 range shards of pbx_dedup_u64."""
    import ctypes
    from paddlebox_tpu.native.build import load_library
    lib = load_library()
    rng = np.random.default_rng(42)
    keys = rng.integers(0, np.iinfo(np.uint64).max, 50_000, dtype=np.uint64)
    keys = np.concatenate([keys, keys[:10_000], np.zeros(100, np.uint64)])
    h = lib.pbx_dedup_u64(
        np.ascontiguousarray(keys).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint64)), keys.size)
    try:
        n = lib.pbx_dedup_size(h)
        out = np.empty((n,), np.uint64)
        lib.pbx_dedup_fill(
            h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    finally:
        lib.pbx_dedup_free(h)
    ref = np.unique(keys)
    ref = ref[ref != 0]
    np.testing.assert_array_equal(out, ref)
    # sanity: keys really spanned many top-byte shards
    assert np.unique(keys >> np.uint64(56)).size > 200


def test_dedup_empty_and_tiny():
    assert dedup_keys(np.empty((0,), np.uint64)).size == 0
    np.testing.assert_array_equal(
        dedup_keys(np.array([5, 5, 0, 3], np.uint64)), [3, 5])


@pytest.mark.parametrize("num_shards", [1, 4])
def test_keymap_matches_numpy_map(num_shards):
    rng = np.random.default_rng(1)
    n_keys = 5000
    keys = np.unique(rng.integers(1, 1 << 50, n_keys, dtype=np.uint64))
    rps = -(-keys.size // num_shards)
    km = KeyMap(keys, rps, num_shards)
    batch = rng.choice(keys, 20_000).astype(np.uint64)
    batch[::11] = rng.integers(1 << 51, 1 << 52, batch[::11].size,
                               dtype=np.uint64)  # misses
    batch[::13] = 0  # null
    out = km.lookup(batch)
    ref = map_keys_to_rows(keys, batch, rps, num_shards)
    np.testing.assert_array_equal(out, ref)
    km.close()


def test_keymap_empty_batch():
    keys = np.array([7, 9], np.uint64)
    km = KeyMap(keys, 2, 1)
    assert km.lookup(np.empty((0,), np.uint64)).size == 0
    km.close()


@needs_native
def test_native_faster_than_numpy_on_large_batch():
    """Smoke perf: native path should beat np.searchsorted on a realistic
    pass (4M keys, 4M-id batch). Generous 1.0x bar to avoid CI flakes —
    locally it's typically 3-10x."""
    rng = np.random.default_rng(2)
    keys = np.unique(rng.integers(1, 1 << 52, 4_000_000, dtype=np.uint64))
    rps = -(-keys.size // 8)
    batch = rng.choice(keys, 4_000_000).astype(np.uint64)

    km = KeyMap(keys, rps, 8)
    km.lookup(batch[:1000])  # warm
    t0 = time.perf_counter()
    out = km.lookup(batch)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    ref = map_keys_to_rows(keys, batch, rps, 8)
    t_numpy = time.perf_counter() - t0
    km.close()

    np.testing.assert_array_equal(out, ref)
    assert t_native < t_numpy * 1.0, (t_native, t_numpy)


@needs_native
def test_native_dedup_perf_smoke():
    """dedup_keys picks native only with >=4 cores; either way the result
    must match numpy, and on multi-core boxes be competitive."""
    import os
    rng = np.random.default_rng(3)
    keys = rng.integers(1, 1 << 40, 8_000_000, dtype=np.uint64)
    t0 = time.perf_counter()
    out = dedup_keys(keys)
    t_chosen = time.perf_counter() - t0
    t0 = time.perf_counter()
    ref = np.unique(keys)
    t_numpy = time.perf_counter() - t0
    np.testing.assert_array_equal(out, ref[ref != 0])
    if (os.cpu_count() or 1) >= 4:
        assert t_chosen < t_numpy * 2.0, (t_chosen, t_numpy)
