"""Auto-parallel tests: ProcessMesh conversion, shard_tensor/reshard
placement, and planner spec completion rules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel.auto import (DistAttr, ProcessMesh, apply_plan,
                                         plan_params, plan_shardings,
                                         reshard, shard_tensor)
from paddlebox_tpu.parallel import HybridTopology, build_mesh


def test_process_mesh_to_jax(devices8):
    pm = ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
    mesh = pm.to_jax(devices8)
    assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 4
    with pytest.raises(ValueError):
        ProcessMesh(shape=(2, 4), dim_names=("dp",))
    with pytest.raises(ValueError):
        ProcessMesh(shape=(4, 4), dim_names=("a", "b")).to_jax(devices8)


def test_shard_tensor_and_reshard(devices8):
    pm = ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
    x = jnp.arange(64.0).reshape(8, 8)
    xs = shard_tensor(x, pm, ("dp", None), devices=devices8)
    assert xs.sharding.spec == P("dp", None)
    xr = reshard(xs, pm, (None, "mp"), devices=devices8)
    assert xr.sharding.spec == P(None, "mp")
    np.testing.assert_array_equal(np.asarray(xr), np.asarray(x))


def test_dist_attr_spec():
    pm = ProcessMesh(shape=(8,), dim_names=("dp",))
    assert DistAttr(pm, ("dp", None)).spec() == P("dp", None)


def test_plan_params_rules(devices8):
    mesh = build_mesh(HybridTopology(sharding=2, mp=4), devices8)
    params = {
        "embedding": {"table": jnp.zeros((4096, 64))},   # vocab hint -> mp@0
        "dense": {"w": jnp.zeros((256, 128)),            # largest dim / mp
                  "b": jnp.zeros((128,))},               # small -> replicate
        "odd": jnp.zeros((254, 254)),                    # 254 % 4 != 0; % 2 == 0
    }
    plan = plan_params(params, mesh)
    assert plan["embedding"]["table"] == P("mp", None)
    assert plan["dense"]["w"] == P("mp", None)
    assert plan["dense"]["b"] == P()
    assert plan["odd"] == P("sharding", None)


def test_plan_overrides_and_apply(devices8):
    mesh = build_mesh(HybridTopology(dp=2, mp=4), devices8)
    params = {"wte": jnp.ones((512, 32)), "head": jnp.ones((32, 512))}
    plan = plan_params(params, mesh, overrides={"head": P(None, "mp")})
    assert plan["head"] == P(None, "mp")
    placed = apply_plan(params, mesh, overrides={"head": P(None, "mp")})
    assert placed["wte"].sharding.spec == P("mp", None)
    assert placed["head"].sharding.spec == P(None, "mp")
    # compute under jit with planned shardings runs and matches
    shardings = plan_shardings(params, mesh,
                               overrides={"head": P(None, "mp")})
    f = jax.jit(lambda p: p["wte"] @ p["head"], in_shardings=(shardings,))
    np.testing.assert_allclose(np.asarray(f(placed)),
                               np.asarray(params["wte"] @ params["head"]))


def test_cost_planner_respects_budget():
    """Cost-based planner (planner_v2/cost-model role): ample budget →
    fully replicated (cheapest comm); tight budget → largest leaves
    sharded first until resident bytes fit; impossible budget raises."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.parallel.auto import estimate_plan, plan_params_cost

    mesh = build_mesh(HybridTopology(dp=2, sharding=2, mp=2))
    params = {
        "big": np.zeros((1024, 64), np.float32),     # 256 KiB
        "mid": np.zeros((256, 64), np.float32),      # 64 KiB
        "tiny": np.zeros((7,), np.float32),          # indivisible by 2
    }
    total = 256 * 1024 + 64 * 1024 + 28

    # Ample budget: everything replicated, comm = 2x bytes allreduce.
    specs, cost = plan_params_cost(params, mesh,
                                   bytes_budget_per_device=2 * total)
    assert specs["big"] == P() and specs["mid"] == P()
    assert cost.param_bytes_per_device == total
    assert cost.allgather_bytes == 0
    assert cost.allreduce_bytes == 2 * total

    # Tight budget: big must shard; mid may stay replicated.
    budget = 256 * 1024 // 2 + 64 * 1024 + 1024
    specs, cost = plan_params_cost(params, mesh,
                                   bytes_budget_per_device=budget)
    assert specs["big"] != P()
    assert cost.param_bytes_per_device <= budget
    assert cost.allgather_bytes > 0
    # estimate_plan consistency on the returned plan
    again = estimate_plan(params, specs, mesh)
    assert again == cost

    # Impossible budget raises (tiny is indivisible, floor exists).
    import pytest
    with pytest.raises(ValueError):
        plan_params_cost(params, mesh, bytes_budget_per_device=100)
