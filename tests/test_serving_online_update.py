"""Online serving updates (the reference's headline "real-time model
update", README.md:48): a live CTRPredictor absorbing per-pass delta
exports must serve exactly what a cold predictor rebuilt from the full
post-pass export serves."""

import numpy as np
import pytest

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.serving import (CTRPredictor, load_delta_update,
                                   load_xbox_model)
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("u", "i")


def _write(path, rng, n, lo, hi):
    with open(path, "w") as f:
        for _ in range(n):
            toks = " ".join(f"{s}:{rng.integers(lo, hi)}" for s in SLOTS)
            f.write(f"{int(rng.random() < 0.3)} {toks}\n")
    return path


def test_live_predictor_matches_cold_rebuild(tmp_path):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,))
    tr = CTRTrainer(model, feed, TableConfig(name="emb", dim=8,
                                             learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10))
    tr.init(seed=0)
    rng = np.random.default_rng(3)

    # Pass 1 over keys [1, 400); base xbox export; live predictor.
    p1 = _write(str(tmp_path / "p1"), rng, 256, 1, 400)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p1])
    ds.load_into_memory()
    tr.train_pass(ds)
    base_dir = str(tmp_path / "base")
    tr.engine.store.save_xbox(base_dir)
    # Clear the dirty set so the next delta covers only pass 2.
    tr.engine.store.save_base(str(tmp_path / "b0"))
    keys, emb, w = load_xbox_model(base_dir, table="emb")
    live = CTRPredictor(model, feed, keys, emb, w, tr.params,
                        compute_dtype="float32")

    # Pass 2 touches old keys AND brand-new ones [300, 700).
    p2 = _write(str(tmp_path / "p2"), rng, 256, 300, 700)
    ds2 = Dataset(feed, num_reader_threads=1)
    ds2.set_filelist([p2])
    ds2.load_into_memory()
    tr.train_pass(ds2)
    delta_dir = str(tmp_path / "delta")
    tr.engine.store.save_delta(delta_dir)

    # Live update vs cold rebuild from the post-pass full export.
    dk, de, dw = load_delta_update(delta_dir, table="emb")
    assert dk.size > 0
    n_new = live.apply_update(dk, de, dw, dense_params=tr.params)
    assert n_new > 0  # pass 2 introduced unseen keys

    full_dir = str(tmp_path / "full")
    tr.engine.store.save_xbox(full_dir)
    k2, e2, w2 = load_xbox_model(full_dir, table="emb")
    cold = CTRPredictor(model, feed, k2, e2, w2, tr.params,
                        compute_dtype="float32")

    ds3 = Dataset(feed, num_reader_threads=1)
    ds3.set_filelist([_write(str(tmp_path / "probe"), rng, 128, 1, 800)])
    ds3.load_into_memory()
    batch = next(ds3.batches_sharded(1))
    np.testing.assert_allclose(live.predict(batch), cold.predict(batch),
                               rtol=1e-6, atol=1e-6)


def test_apply_update_width_check_and_dups(tmp_path):
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=8)
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=(8,))
    params = model.init(__import__("jax").random.PRNGKey(0))
    keys = np.arange(1, 5, dtype=np.uint64)
    emb = np.ones((4, 4), np.float32)
    w = np.zeros((4,), np.float32)
    pred = CTRPredictor(model, feed, keys, emb, w, params,
                        compute_dtype="float32")
    with pytest.raises(ValueError, match="width"):
        pred.apply_update(keys, np.ones((4, 8), np.float32), w)
    # Duplicate keys: the LAST occurrence wins (stream order).
    upd_keys = np.asarray([7, 7], np.uint64)
    upd_emb = np.stack([np.full(4, 1.0), np.full(4, 2.0)]).astype(
        np.float32)
    pred.apply_update(upd_keys, upd_emb, np.zeros(2, np.float32))
    row = pred._index.lookup(np.asarray([7], np.uint64))[0]
    np.testing.assert_allclose(
        np.asarray(pred._table)[row, :4], 2.0)


def test_delta_loader_handles_sharded_layout(tmp_path):
    from paddlebox_tpu.embedding.sharded_store import ShardedFeatureStore

    cfg = TableConfig(name="emb", dim=4, learning_rate=0.1)
    store = ShardedFeatureStore(cfg, num_buckets=4)
    keys = np.arange(1, 200, dtype=np.uint64)
    vals = store.pull_for_pass(keys)
    store.push_from_pass(keys, vals)
    store.save_delta(str(tmp_path))
    k, e, w = load_delta_update(str(tmp_path), table="emb")
    assert np.array_equal(np.sort(k), keys)
    assert e.shape == (199, 4)


def test_apply_update_drops_null_feasign(tmp_path):
    import jax

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=8)
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=(8,))
    params = model.init(jax.random.PRNGKey(0))
    keys = np.arange(1, 5, dtype=np.uint64)
    pred = CTRPredictor(model, feed, keys, np.ones((4, 4), np.float32),
                        np.zeros((4,), np.float32), params,
                        compute_dtype="float32")
    trash_before = np.asarray(pred._table)[-1].copy()
    # Key 0 (the null feasign) must be dropped, NOT wrap onto the trash
    # row via KeyIndex's -1.
    pred.apply_update(np.asarray([0], np.uint64),
                      np.full((1, 4), 9.0, np.float32),
                      np.ones((1,), np.float32))
    np.testing.assert_array_equal(np.asarray(pred._table)[-1],
                                  trash_before)
    assert (trash_before == 0).all()


def test_concurrent_predict_during_updates(tmp_path):
    """Hammer predict() from one thread while another streams updates —
    no crash, and every served batch is finite (a consistent model
    version per batch)."""
    import threading

    import jax

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=16)
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=(8,))
    params = model.init(jax.random.PRNGKey(0))
    keys = np.arange(1, 100, dtype=np.uint64)
    rng = np.random.default_rng(0)
    pred = CTRPredictor(model, feed, keys,
                        rng.normal(size=(99, 4)).astype(np.float32),
                        np.zeros((99,), np.float32), params,
                        compute_dtype="float32")
    p = _write(str(tmp_path / "probe"), rng, 64, 1, 500)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    batch = next(ds.batches_sharded(1))

    stop = threading.Event()
    errors = []

    def updater():
        r = np.random.default_rng(1)
        while not stop.is_set():
            upd = r.choice(np.arange(1, 600, dtype=np.uint64), 50,
                           replace=False)
            try:
                pred.apply_update(upd,
                                  r.normal(size=(50, 4)).astype(
                                      np.float32),
                                  np.zeros((50,), np.float32))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=updater)
    t.start()
    try:
        for _ in range(15):
            probs = pred.predict(batch)
            assert np.isfinite(probs).all()
    finally:
        stop.set()
        t.join()
    assert not errors, errors


def test_from_dirs_loads_dense_checkpoint(tmp_path):
    """CTRPredictor.from_dirs over a DayRunner-style artifact pair
    (xbox export + dense.npz) — the load_pytree (tree, step) unpack was
    untested and broken."""
    import jax

    from paddlebox_tpu.checkpoint.dense import save_pytree

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=8)
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=(8,))
    params = model.init(jax.random.PRNGKey(1))
    from paddlebox_tpu.embedding.store import FeatureStore
    store = FeatureStore(TableConfig(name="embedding", dim=4,
                                     learning_rate=0.1))
    keys = np.arange(1, 50, dtype=np.uint64)
    vals = store.pull_for_pass(keys)
    store.push_from_pass(keys, vals)
    store.save_xbox(str(tmp_path))
    save_pytree(params, str(tmp_path / "dense.npz"))

    template = model.init(jax.random.PRNGKey(2))  # different weights
    pred = CTRPredictor.from_dirs(
        model, feed, str(tmp_path),
        dense_path=str(tmp_path / "dense.npz"),
        dense_template=template, compute_dtype="float32")
    # The restored dense params are the SAVED ones, not the template.
    for a, b in zip(jax.tree.leaves(pred._dense_params),
                    jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    rng = np.random.default_rng(0)
    p = _write(str(tmp_path / "probe"), rng, 8, 1, 60)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    probs = pred.predict(next(ds.batches_sharded(1)))
    assert np.isfinite(probs).all()


def test_recovery_skips_shape_mismatched_dense(tmp_path):
    """A dense checkpoint whose leaf shapes no longer match the model is
    rejected with a warning, not silently restored."""
    import jax

    from paddlebox_tpu.checkpoint.dense import save_pytree
    from paddlebox_tpu.train.day_runner import DayRunner

    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)

    def make(hidden):
        model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=hidden)
        tr = CTRTrainer(model, feed, TableConfig(name="emb", dim=4),
                        mesh=mesh, config=TrainerConfig())
        tr.init(seed=0)
        return tr

    tr_old = make((8,))
    runner = DayRunner(tr_old, feed, str(tmp_path / "out"),
                       data_root=str(tmp_path / "data"))
    mdir = str(tmp_path / "ckpt")
    import os
    os.makedirs(mdir, exist_ok=True)
    runner._save_dense(mdir)

    tr_new = make((16,))  # changed model shape
    runner_new = DayRunner(tr_new, feed, str(tmp_path / "out2"),
                           data_root=str(tmp_path / "data"))
    before = [np.asarray(x).copy()
              for x in jax.tree.leaves(tr_new.params)]
    assert runner_new._load_dense(mdir) is False
    for a, b in zip(jax.tree.leaves(tr_new.params), before):
        np.testing.assert_array_equal(np.asarray(a), b)
