"""Fused CTR op + metrics tests, numpy-parity style (role of the
reference's OpTest harness, test strategy SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.metrics import (auc_accumulate, auc_compute,
                                   auc_state_init, wuauc_compute)
from paddlebox_tpu.ops import (continuous_value_model, fused_seqpool_cvm,
                               rank_attention, seqpool)


def _auc_ref(preds, labels):
    """O(n log n) exact rank-sum AUC reference."""
    order = np.argsort(preds, kind="stable")
    ranks = np.empty(len(preds))
    ranks[order] = np.arange(1, len(preds) + 1)
    pos = labels > 0.5
    npos, nneg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def test_seqpool_modes():
    vals = jnp.asarray(np.arange(12, dtype=np.float32).reshape(6, 2))
    segs = jnp.asarray(np.array([0, 0, 1, 1, 1, 3], np.int32))  # 3 = pad
    out = seqpool(vals, segs, 3, mode="sum")
    np.testing.assert_allclose(out[0], [0 + 2, 1 + 3])
    np.testing.assert_allclose(out[1], [4 + 6 + 8, 5 + 7 + 9])
    np.testing.assert_allclose(out[2], [0, 0])  # empty row
    mean = seqpool(vals, segs, 3, mode="mean")
    np.testing.assert_allclose(mean[1], [6, 7])
    sq = seqpool(vals, segs, 3, mode="sqrtn")
    np.testing.assert_allclose(sq[1], np.array([18, 21]) / np.sqrt(3))


def test_cvm_transform():
    x = jnp.asarray([[7.0, 3.0, 1.5], [0.0, 0.0, -2.0]])
    y = continuous_value_model(x, use_cvm=True)
    np.testing.assert_allclose(
        y[0], [np.log(8.0), np.log(4.0) - np.log(8.0), 1.5], rtol=1e-6)
    y2 = continuous_value_model(x, use_cvm=False)
    assert y2.shape == (2, 1)
    np.testing.assert_allclose(y2[:, 0], [1.5, -2.0])


def test_fused_seqpool_cvm():
    emb = jnp.ones((4, 3), jnp.float32)
    show = jnp.asarray([1.0, 1.0, 1.0, 1.0])
    click = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    segs = jnp.asarray(np.array([0, 0, 1, 2], np.int32))  # 2 = pad row
    out = fused_seqpool_cvm(emb, show, click, segs, 2)
    assert out.shape == (2, 5)
    np.testing.assert_allclose(
        out[0], [np.log(3.0), np.log(2.0) - np.log(3.0), 2, 2, 2], rtol=1e-6)


def test_rank_attention_matches_loop():
    rng = np.random.default_rng(0)
    B, F, C, K = 6, 4, 3, 3
    x = rng.normal(size=(B, F)).astype(np.float32)
    param = rng.normal(size=(K * K, F, C)).astype(np.float32)
    rank_offset = np.zeros((B, 1 + 2 * K), np.int32)
    for b in range(B):
        rank_offset[b, 0] = rng.integers(0, K + 1)  # 0 = invalid
        for k in range(K):
            if rng.random() < 0.7:
                rank_offset[b, 1 + 2 * k] = rng.integers(1, K + 1)
                rank_offset[b, 2 + 2 * k] = rng.integers(0, B)

    out, ins_rank = rank_attention(jnp.asarray(x), jnp.asarray(rank_offset),
                                   jnp.asarray(param), max_rank=K)
    ref = np.zeros((B, C), np.float32)
    for b in range(B):
        lower = rank_offset[b, 0] - 1
        if lower < 0:
            continue
        for k in range(K):
            faster = rank_offset[b, 1 + 2 * k] - 1
            if faster < 0:
                continue
            idx = rank_offset[b, 2 + 2 * k]
            ref[b] += x[idx] @ param[lower * K + faster]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(ins_rank), rank_offset[:, 0])


def test_auc_exact_vs_ranksum():
    rng = np.random.default_rng(1)
    n = 5000
    preds = rng.random(n).astype(np.float32)
    labels = (rng.random(n) < preds * 0.7).astype(np.float32)  # correlated
    state = auc_state_init(1 << 16)
    # accumulate in 5 chunks like 5 train steps
    for i in range(0, n, 1000):
        state = auc_accumulate(state, jnp.asarray(preds[i:i+1000]),
                               jnp.asarray(labels[i:i+1000]))
    stats = auc_compute(state)
    ref = _auc_ref(preds, labels)
    assert abs(stats["auc"] - ref) < 1e-3  # bucketing error only
    np.testing.assert_allclose(stats["actual_ctr"], labels.mean(), rtol=1e-5)
    np.testing.assert_allclose(stats["predicted_ctr"], preds.mean(), rtol=1e-5)


def test_auc_valid_mask():
    state = auc_state_init(1 << 10)
    preds = jnp.asarray([0.9, 0.1, 0.5, 0.5])
    labels = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    valid = jnp.asarray([True, True, False, False])
    state = auc_accumulate(state, preds, labels, valid)
    stats = auc_compute(state)
    assert stats["count"] == 2.0
    assert stats["auc"] == 1.0  # perfect ordering on the 2 valid rows


def test_auc_distributed_psum(devices8):
    """AUC accumulated across 8 dp ranks == single-rank (exact distributed
    AUC, role of metrics.cc:286-292 allreduce)."""
    from jax.sharding import PartitionSpec as P
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    mesh = build_mesh(HybridTopology(dp=8))
    rng = np.random.default_rng(2)
    n = 1024
    preds = rng.random(n).astype(np.float32)
    labels = (rng.random(n) < 0.3).astype(np.float32)

    def body(state, p, l):
        return auc_accumulate(state, p, l, axis="dp")

    sm = jax.shard_map(body, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
                       out_specs=P(), check_vma=False)
    state = sm(auc_state_init(1 << 12), jnp.asarray(preds),
               jnp.asarray(labels))
    single = auc_accumulate(auc_state_init(1 << 12), jnp.asarray(preds),
                            jnp.asarray(labels))
    np.testing.assert_allclose(np.asarray(state.table),
                               np.asarray(single.table))
    assert abs(auc_compute(state)["auc"] - auc_compute(single)["auc"]) < 1e-9


def test_wuauc():
    users = np.array([1, 1, 1, 2, 2, 2, 3, 3], np.uint64)
    preds = np.array([0.9, 0.2, 0.6, 0.1, 0.8, 0.5, 0.3, 0.3], np.float32)
    labels = np.array([1, 0, 0, 0, 1, 0, 1, 1], np.float32)
    out = wuauc_compute(users, preds, labels)
    # user1: pos 0.9 vs negs {0.2, 0.6} -> auc 1.0; user2: pos 0.8 vs
    # {0.1, 0.5} -> 1.0; user3 all-pos -> skipped.
    assert out["wuauc_users"] == 2.0
    np.testing.assert_allclose(out["wuauc"], 1.0)
