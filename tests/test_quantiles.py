"""The streaming quantile sketch contract (core/quantiles.py).

The digest is the SLO layer's foundation: its relative-error bound must
hold on adversarial value distributions (six-decade lognormals, heavy
tails, constants, negatives/zeros), its merge must be associative (the
per-rank cluster aggregation is a fold), and the registry integration
(monitor.observe_quantile / snapshot_all / merge_snapshots / the
FileStore collector / the atexit final flush) must round-trip through
JSON without accuracy loss.
"""

import json
import math
import threading

import numpy as np
import pytest

from paddlebox_tpu.core import monitor
from paddlebox_tpu.core.quantiles import (DEFAULT_QS, LogQuantileDigest,
                                          merge_digests)

QS = (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 0.999)


def _check_rel_error(values, rel_error=0.01, qs=QS):
    d = LogQuantileDigest(rel_error)
    for v in values:
        d.observe(v)
    arr = np.asarray(values, dtype=np.float64)
    for q in qs:
        # The sketch's guarantee is per-VALUE: its estimate is within
        # rel_error of SOME value at that rank. Compare against the
        # nearest-rank exact quantile it targets.
        exact = float(np.quantile(arr, q, method="lower"))
        est = d.quantile(q)
        if exact == 0.0:
            assert abs(est) <= rel_error, (q, est)
        else:
            assert abs(est - exact) <= rel_error * abs(exact) + 1e-12, \
                (q, exact, est)


def test_rel_error_lognormal_six_decades():
    rng = np.random.default_rng(0)
    _check_rel_error(rng.lognormal(mean=0.0, sigma=3.0, size=50_000))


def test_rel_error_heavy_tail_pareto():
    rng = np.random.default_rng(1)
    _check_rel_error((rng.pareto(1.1, size=50_000) + 1.0) * 0.001)


def test_rel_error_mixture_with_negatives_and_zeros():
    rng = np.random.default_rng(2)
    vals = np.concatenate([
        -rng.lognormal(2.0, 2.0, 10_000),      # negative tail
        np.zeros(5_000),                        # exact zeros
        rng.lognormal(2.0, 2.0, 10_000),        # positive tail
    ])
    rng.shuffle(vals)
    _check_rel_error(vals)


def test_rel_error_constant_and_near_constant():
    _check_rel_error(np.full(1000, 42.5))
    rng = np.random.default_rng(3)
    _check_rel_error(42.5 + rng.normal(0, 1e-9, 1000))


def test_rel_error_configurable():
    rng = np.random.default_rng(4)
    _check_rel_error(rng.lognormal(1.0, 2.0, 20_000), rel_error=0.05)


def test_empty_and_single_value_edges():
    d = LogQuantileDigest()
    assert d.quantile(0.5) is None
    assert all(v is None for v in d.quantiles().values())
    assert d.to_dict()["count"] == 0
    assert d.to_dict()["min"] is None
    d.observe(7.0)
    for q in (0.0, 0.5, 1.0):
        assert abs(d.quantile(q) - 7.0) <= 0.01 * 7.0
    assert d.min == d.max == 7.0
    with pytest.raises(ValueError):
        d.quantile(1.5)
    with pytest.raises(ValueError):
        LogQuantileDigest(0.0)


def test_merge_associativity_and_exactness():
    rng = np.random.default_rng(5)
    chunks = [rng.lognormal(0, 2.0, 5_000) * s
              for s in (1.0, 100.0, 1e-3)]
    digs = []
    for c in chunks:
        d = LogQuantileDigest()
        for v in c:
            d.observe(v)
        digs.append(d)
    a, b, c = (d.copy() for d in digs)
    left = a.merge(b).merge(c)                       # (a+b)+c
    a2, b2, c2 = (d.copy() for d in digs)
    right = a2.merge(b2.merge(c2))                   # a+(b+c)
    assert left.counts == right.counts
    assert left.count == right.count
    # Merged digest == digest of the concatenated stream, bucket-exact.
    whole = LogQuantileDigest()
    for v in np.concatenate(chunks):
        whole.observe(v)
    assert left.counts == whole.counts
    assert left.zero_count == whole.zero_count
    for q in QS:
        assert left.quantile(q) == whole.quantile(q)
    # merge_digests fold helper
    folded = merge_digests(digs)
    assert folded.counts == whole.counts
    assert merge_digests([]) is None
    # Mixed rel_error digests must refuse to merge.
    with pytest.raises(ValueError):
        LogQuantileDigest(0.01).merge(LogQuantileDigest(0.02))


def test_delta_window():
    d = LogQuantileDigest()
    for v in (1.0, 2.0, 3.0):
        d.observe(v)
    base = d.copy()
    for v in (100.0, 200.0, 300.0):
        d.observe(v)
    w = d.delta(base)
    assert w.count == 3
    # The window sees ONLY the post-base observations.
    assert w.quantile(0.0) > 50.0
    assert abs(w.quantile(0.5) - 200.0) <= 0.01 * 200.0 + 1e-9
    # delta(None) == copy of the whole digest.
    assert d.delta(None).count == 6


def test_serialization_roundtrip():
    rng = np.random.default_rng(6)
    d = LogQuantileDigest()
    for v in rng.lognormal(0, 2, 2000):
        d.observe(v)
    d.observe(0.0)
    d.observe(-5.0)
    blob = json.dumps(d.to_dict())
    back = LogQuantileDigest.from_dict(json.loads(blob))
    assert back.count == d.count
    assert back.counts == d.counts
    assert back.neg_counts == d.neg_counts
    assert back.zero_count == d.zero_count
    for q in QS:
        assert back.quantile(q) == d.quantile(q)
    # to_dict carries the derived SLO fields directly.
    td = d.to_dict()
    for name in ("p50", "p90", "p99", "p999"):
        assert name in td


def test_monitor_quantile_registration():
    reg = monitor.Monitor()
    for v in (1.0, 10.0, 100.0):
        reg.observe_quantile("trainer/dispatch_ms", v)
    snap = reg.snapshot_all()
    q = snap["quantiles"]["trainer/dispatch_ms"]
    assert q["count"] == 3
    assert abs(q["p50"] - 10.0) <= 0.1 + 1e-9
    # quantile_digest returns a COPY (window-base safety).
    cp = reg.quantile_digest("trainer/dispatch_ms")
    reg.observe_quantile("trainer/dispatch_ms", 1000.0)
    assert cp.count == 3
    assert reg.quantile_digest("missing") is None
    reg.reset()
    assert reg.snapshot_all()["quantiles"] == {}


def test_merge_snapshots_cluster_semantics():
    regs = [monitor.Monitor() for _ in range(3)]
    for i, r in enumerate(regs):
        r.add("pass/train_samples", 100 * (i + 1))
        r.set_gauge("pass/train_samples_per_s", 1000.0 * (i + 1))
        r.observe("trainer/dispatch_ms", 10.0 * (i + 1))
        r.observe_quantile("trainer/dispatch_ms", 10.0 * (i + 1))
    merged = monitor.merge_snapshots([r.snapshot_all({"rank": i})
                                      for i, r in enumerate(regs)])
    assert merged["ranks"] == 3
    assert merged["counters"]["pass/train_samples"] == 600
    assert merged["gauges"]["pass/train_samples_per_s"] == 2000.0
    # The skew view: the mean hides the slow rank, __max names it.
    assert merged["gauges"]["pass/train_samples_per_s__max"] == 3000.0
    h = merged["histograms"]["trainer/dispatch_ms"]
    assert h["count"] == 3 and sum(h["counts"]) == 3
    assert h["min"] == 10.0 and h["max"] == 30.0
    q = merged["quantiles"]["trainer/dispatch_ms"]
    assert q["count"] == 3
    assert abs(q["p50"] - 20.0) <= 0.25
    # Mismatched histogram buckets across ranks must refuse to merge.
    a, b = monitor.Monitor(), monitor.Monitor()
    a.observe("h", 1.0, buckets=(1.0, 2.0))
    b.observe("h", 1.0, buckets=(1.0, 3.0))
    with pytest.raises(ValueError):
        monitor.merge_snapshots([a.snapshot_all(), b.snapshot_all()])
    assert monitor.merge_snapshots([])["ranks"] == 0


def test_filestore_cluster_collector(tmp_path):
    """Two ranks rendezvous through a FileStore; both get the SAME
    merged cluster snapshot (prep for multihost_scale)."""
    from paddlebox_tpu.distributed.transport import FileStore

    world = 2
    regs = []
    for i in range(world):
        r = monitor.Monitor()
        r.add("pass/train_steps", 10 + i)
        r.observe_quantile("trainer/dispatch_ms", float(10 ** (i + 1)))
        regs.append(r)
    results = [None] * world
    errors = []

    def rank_body(i):
        try:
            fs = FileStore(str(tmp_path / "fs"), rank=i, world=world)
            results[i] = monitor.collect_cluster_snapshot(
                fs, registry=regs[i], labels={"rank": i}, timeout=30.0)
        except BaseException as e:  # pragma: no cover - surfaced below
            errors.append(e)

    ts = [threading.Thread(target=rank_body, args=(i,))
          for i in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60.0)
    assert not errors, errors
    for res in results:
        assert res is not None
        assert res["ranks"] == world
        assert res["counters"]["pass/train_steps"] == 21
        assert res["quantiles"]["trainer/dispatch_ms"]["count"] == 2
    assert results[0]["counters"] == results[1]["counters"]


def test_atexit_final_flush_idempotent(tmp_path):
    """Arming the exporter registers a final flush that appends one last
    labeled snapshot at exit — and is safe to run alongside (or after)
    the periodic thread."""
    path = str(tmp_path / "m.jsonl")
    reg = monitor.Monitor()
    reg.add("tool/things", 3)
    # interval <= 0: no thread, but the path is armed and the atexit
    # hook registered — the short-lived-tool case the flush exists for.
    reg.start_flush_thread(path, interval_s=0.0)
    assert reg._atexit_registered
    reg._atexit_flush()
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert lines and lines[-1]["labels"] == {"event": "final_flush"}
    assert lines[-1]["counters"]["tool/things"] == 3
    # Idempotent: calling again appends another valid line, never raises.
    reg._atexit_flush()
    assert len(open(path).read().splitlines()) == 2
    # Fully de-configured exporter (stop_flush_thread) -> exit flush is
    # a no-op instead of resurrecting the file.
    reg.stop_flush_thread()
    before = open(path).read()
    reg._atexit_flush()
    assert open(path).read() == before


def test_bucket_midpoint_bound_math():
    """The bucket-estimate error bound is exactly rel_error at the
    bucket edges (the DDSketch midpoint property) — pin the math so a
    refactor of _bucket_value can't silently widen the guarantee."""
    a = 0.01
    d = LogQuantileDigest(a)
    gamma = (1 + a) / (1 - a)
    for v in (1e-6, 0.1, 1.0, 7.3, 1e4, 1e9):
        i = math.ceil(math.log(v) / math.log(gamma))
        est = 2.0 * gamma ** i / (gamma + 1.0)
        assert abs(est - v) <= a * v * (1 + 1e-9)
        d.observe(v)
