"""Distributed graph service (VERDICT r02 task 8): CSR shards served over
the typed wire on a localhost fake cluster; 2-shard sampling must be
bit-identical to the single-host table (shard-layout-invariant sampler)."""

import numpy as np
import pytest

from paddlebox_tpu.graph.service import (GraphClient, GraphServer,
                                         sample_neighbors_host)
from paddlebox_tpu.graph.table import build_csr

N_NODES = 200
N_EDGES = 2000


def _edges(seed=0):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, N_NODES, N_EDGES).astype(np.int64)
    dst = rng.integers(0, N_NODES, N_EDGES).astype(np.int64)
    return src, dst


def _cluster(n):
    servers = [GraphServer("127.0.0.1:0", i, n) for i in range(n)]
    client = GraphClient([s.endpoint for s in servers])
    return servers, client


@pytest.mark.parametrize("n_servers", [1, 2])
def test_sharded_sampling_matches_single_host(n_servers):
    src, dst = _edges()
    full = build_csr(src, dst, num_nodes=N_NODES)
    servers, cli = _cluster(n_servers)
    try:
        cli.upload_batch("e", src, dst, num_nodes=N_NODES)
        cli.build("e")
        nodes = np.random.default_rng(1).integers(
            0, N_NODES, 64).astype(np.int64)
        got = cli.sample_neighbors("e", nodes, k=5, seed=7)
        ref = sample_neighbors_host(full, nodes, 5, 7)
        np.testing.assert_array_equal(got, ref)
        # Degrees agree with the full CSR.
        deg_ref = full.indptr[nodes + 1] - full.indptr[nodes]
        np.testing.assert_array_equal(cli.degrees("e", nodes), deg_ref)
        # Samples are actual neighbors.
        for i, v in enumerate(nodes):
            nbrs = set(full.neighbors(int(v)).tolist())
            for s in got[i]:
                assert (int(s) in nbrs) if nbrs else s == -1
    finally:
        cli.stop_servers()
        cli.close()
        for s in servers:
            s.stop()


def test_two_shard_equals_one_shard_exactly():
    """The sampler is deterministic per (seed, node, slot), so the SAME
    queries through different cluster sizes give identical answers."""
    src, dst = _edges(seed=3)
    outs = {}
    for n in (1, 2):
        servers, cli = _cluster(n)
        try:
            cli.upload_batch("e", src, dst, num_nodes=N_NODES)
            cli.build("e")
            nodes = np.arange(0, N_NODES, 3, dtype=np.int64)
            outs[n] = cli.sample_neighbors("e", nodes, k=4, seed=11)
        finally:
            cli.stop_servers()
            cli.close()
            for s in servers:
                s.stop()
    np.testing.assert_array_equal(outs[1], outs[2])


def test_node_features_and_walks():
    src, dst = _edges(seed=5)
    servers, cli = _cluster(2)
    try:
        cli.upload_batch("e", src, dst, num_nodes=N_NODES)
        cli.build("e")
        nodes = np.arange(N_NODES, dtype=np.int64)
        feats = np.random.default_rng(2).normal(
            size=(N_NODES, 8)).astype(np.float32)
        cli.set_node_feat("x", nodes, feats)
        got = cli.get_node_feat("x", nodes[::7])
        np.testing.assert_array_equal(got, feats[::7])
        walks = cli.random_walk("e", nodes[:32], length=4, seed=9)
        assert walks.shape == (32, 5)
        full = build_csr(src, dst, num_nodes=N_NODES)
        for row in walks:
            for a, b in zip(row[:-1], row[1:]):
                nbrs = full.neighbors(int(a))
                assert b == a or int(b) in nbrs.tolist()
    finally:
        cli.stop_servers()
        cli.close()
        for s in servers:
            s.stop()


def test_isolated_and_out_of_range_nodes():
    servers, cli = _cluster(2)
    try:
        cli.upload_batch("e", np.array([0, 2], np.int64),
                         np.array([2, 0], np.int64), num_nodes=10)
        cli.build("e")
        nodes = np.array([0, 1, 2, 5], np.int64)  # 1 and 5 isolated
        got = cli.sample_neighbors("e", nodes, k=3, seed=0)
        assert (got[1] == -1).all() and (got[3] == -1).all()
        assert (got[0] == 2).all() and (got[2] == 0).all()
    finally:
        cli.stop_servers()
        cli.close()
        for s in servers:
            s.stop()


def test_metapath_walk_shard_invariant_with_features():
    """Metapath walks over two edge types + feature pulls on the walk
    frontier: 2-shard answers must be bit-identical to 1-shard, and
    every hop must respect its hop's edge type (bipartite layout)."""
    rng = np.random.default_rng(9)
    users = np.arange(0, 100, dtype=np.int64)
    items = np.arange(100, 200, dtype=np.int64)
    u2i = (np.repeat(users, 4), rng.choice(items, 400))
    i2u = (np.repeat(items, 4), rng.choice(users, 400))
    feats = rng.normal(size=(200, 3)).astype(np.float32)
    outs = {}
    for n in (1, 2):
        servers, cli = _cluster(n)
        try:
            cli.upload_batch("u2i", *u2i, num_nodes=200)
            cli.upload_batch("i2u", *i2u, num_nodes=200)
            cli.build("u2i")
            cli.build("i2u")
            nodes = np.arange(200, dtype=np.int64)
            cli.set_node_feat("x", nodes, feats)
            walks = cli.metapath_walk(["u2i", "i2u", "u2i", "i2u"],
                                      users[:32], seed=13)
            # feature pull on the walk's final frontier
            f = cli.get_node_feat("x", walks[:, -1])
            outs[n] = (walks, f)
        finally:
            cli.stop_servers()
            cli.close()
            for s in servers:
                s.stop()
    w1, f1 = outs[1]
    w2, f2 = outs[2]
    np.testing.assert_array_equal(w1, w2)
    np.testing.assert_array_equal(f1, f2)
    # typed hops: odd positions are items, even are users
    assert np.all(w2[:, [1, 3]] >= 100) and np.all(w2[:, [0, 2, 4]] < 100)
    np.testing.assert_allclose(f2, feats[w2[:, -1]])
