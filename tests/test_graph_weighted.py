"""Edge-weighted graph sampling (VERDICT-r04 #6).

The reference's graph store carries a weight per edge and samples
neighbors by it when ``is_weighted``
(common_graph_table.h:128-152 add_neighbor(id, dst, weight)); these tests
pin the TPU build's three surfaces of the same capability: the host CSR
(weights ride build/load), the padded device view (per-neighbor CDF +
compare-sum inverse-CDF draw in XLA), and the sharded service
(deterministic counter-hash draws -> shard-layout-invariant weighted
samples).
"""

import jax
import numpy as np
import pytest

from paddlebox_tpu.graph import (DeviceGraph, build_csr, device_arrays,
                                 device_cdf, load_edge_file,
                                 metapath_walk_weighted,
                                 random_walk_weighted,
                                 sample_neighbors_weighted,
                                 stack_device_cdfs, stack_device_graphs)
from paddlebox_tpu.graph.service import (GraphClient, GraphServer,
                                         sample_neighbors_host)


def _weighted_star():
    """Node 0 -> {1, 2, 3} with weights 1, 2, 7 (plus a spectator edge)."""
    src = np.asarray([0, 0, 0, 4], np.int64)
    dst = np.asarray([1, 2, 3, 0], np.int64)
    w = np.asarray([1.0, 2.0, 7.0, 5.0], np.float32)
    return build_csr(src, dst, num_nodes=5, weights=w)


def test_build_csr_carries_weights_through_permutation():
    src = np.asarray([2, 0, 2, 1], np.int64)
    dst = np.asarray([3, 1, 0, 2], np.int64)
    w = np.asarray([0.3, 0.1, 0.2, 0.4], np.float32)
    g = build_csr(src, dst, num_nodes=4, weights=w)
    assert g.is_weighted
    # Weight must stay glued to its (src, dst) edge across the sort.
    for s, d, wi in zip(src, dst, w):
        seg = slice(g.indptr[s], g.indptr[s + 1])
        j = np.flatnonzero(g.cols[seg] == d)[0]
        assert g.weights[seg][j] == np.float32(wi)


def test_load_edge_file_third_column(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("0 1 2.5\n1 2 0.5\n2 0 1.0\n")
    g = load_edge_file(str(p))
    assert g.is_weighted and g.num_edges == 3
    np.testing.assert_allclose(g.neighbor_weights(0), [2.5])
    # Two-column files stay unweighted.
    p2 = tmp_path / "plain.txt"
    p2.write_text("0 1\n1 2\n")
    assert not load_edge_file(str(p2)).is_weighted


def test_negative_weights_rejected():
    with pytest.raises(ValueError, match="negative"):
        build_csr(np.asarray([0]), np.asarray([1]), num_nodes=2,
                  weights=np.asarray([-1.0]))


def test_device_sampling_frequency_matches_weights():
    g = _weighted_star()
    dg = DeviceGraph.from_csr(g)
    nbrs, _ = device_arrays(dg)
    cdf = device_cdf(dg)
    nodes = np.zeros(512, np.int64)
    out = np.asarray(sample_neighbors_weighted(
        nbrs, cdf, nodes, jax.random.key(0), 16)).reshape(-1)
    freq = np.bincount(out, minlength=5) / out.size
    # weights 1:2:7 over neighbors {1,2,3}
    np.testing.assert_allclose(freq[[1, 2, 3]], [0.1, 0.2, 0.7], atol=0.02)
    assert freq[0] == 0 and freq[4] == 0  # non-neighbors never sampled


def test_zero_weight_edge_never_sampled_and_isolated_self_loops():
    src = np.asarray([0, 0], np.int64)
    dst = np.asarray([1, 2], np.int64)
    g = build_csr(src, dst, num_nodes=4,
                  weights=np.asarray([0.0, 3.0], np.float32))
    dg = DeviceGraph.from_csr(g)
    nbrs, _ = device_arrays(dg)
    cdf = device_cdf(dg)
    # node 0: only the weight-3 edge; node 3: isolated -> self.
    out = np.asarray(sample_neighbors_weighted(
        nbrs, cdf, np.asarray([0, 3], np.int64), jax.random.key(1), 64))
    assert set(out[0].tolist()) == {2}
    assert set(out[1].tolist()) == {3}


def test_weighted_walk_follows_heavy_path():
    # Chain 0->1->2 with heavy weights vs decoy edges of tiny weight:
    # a weighted walk follows the heavy chain essentially always.
    src = np.asarray([0, 0, 1, 1, 2], np.int64)
    dst = np.asarray([1, 3, 2, 3, 2], np.int64)
    w = np.asarray([1e4, 1e-4, 1e4, 1e-4, 1.0], np.float32)
    dg = DeviceGraph.from_csr(build_csr(src, dst, num_nodes=4, weights=w))
    nbrs, _ = device_arrays(dg)
    cdf = device_cdf(dg)
    walks = np.asarray(random_walk_weighted(
        nbrs, cdf, np.zeros(64, np.int64), jax.random.key(2), 2))
    heavy = (walks == np.asarray([0, 1, 2])).all(axis=1).mean()
    assert heavy > 0.95


def test_hub_truncation_keeps_heavy_edges():
    # Node 0 has 64 neighbors but max_degree=8; 8 edges carry weight 1,
    # the rest ~0 — the Efraimidis-Spirakis subsample must keep exactly
    # the heavy ones.
    n_nb = 64
    src = np.zeros(n_nb, np.int64)
    dst = np.arange(1, n_nb + 1, dtype=np.int64)
    w = np.full(n_nb, 1e-20, np.float32)
    heavy = np.asarray([3, 7, 11, 19, 23, 31, 47, 55])
    w[heavy - 1] = 1.0
    g = build_csr(src, dst, num_nodes=n_nb + 1, weights=w)
    dg = DeviceGraph.from_csr(g, max_degree=8, seed=5)
    assert set(dg.nbrs[0].tolist()) == set(heavy.tolist())


def test_weighted_metapath_stack():
    # Type 0: 0->{1,2} heavy to 1; type 1: from {1,2} heavy to 3 vs 4.
    g0 = build_csr(np.asarray([0, 0]), np.asarray([1, 2]), num_nodes=5,
                   weights=np.asarray([1e4, 1e-4], np.float32))
    g1 = build_csr(np.asarray([1, 1, 2]), np.asarray([3, 4, 4]),
                   num_nodes=5,
                   weights=np.asarray([1e4, 1e-4, 1.0], np.float32))
    dgs = [DeviceGraph.from_csr(g0), DeviceGraph.from_csr(g1)]
    nbrs_s, _ = stack_device_graphs(dgs)
    cdf_s = stack_device_cdfs(dgs)
    walks = np.asarray(metapath_walk_weighted(
        nbrs_s, cdf_s, np.zeros(64, np.int64), jax.random.key(3),
        (0, 1)))
    frac = (walks == np.asarray([0, 1, 3])).all(axis=1).mean()
    assert frac > 0.95


def test_service_weighted_layout_invariance():
    """The decisive service property: weighted samples are deterministic
    per (seed, node, slot), so a 2-shard cluster answers BIT-IDENTICALLY
    to the single-shard one — and both match the local host sampler on
    the full CSR. Integer-valued weights keep the prefix-sum float ops
    exact, so the equality is exact."""
    rng = np.random.default_rng(11)
    n_nodes, n_edges = 120, 1500
    src = rng.integers(0, n_nodes, n_edges).astype(np.int64)
    dst = rng.integers(0, n_nodes, n_edges).astype(np.int64)
    w = rng.integers(1, 9, n_edges).astype(np.float32)
    full = build_csr(src, dst, num_nodes=n_nodes, weights=w)
    nodes = rng.integers(0, n_nodes, 64).astype(np.int64)

    results = {}
    for n_servers in (1, 2):
        servers = [GraphServer("127.0.0.1:0", i, n_servers)
                   for i in range(n_servers)]
        cli = GraphClient([s.endpoint for s in servers])
        try:
            cli.upload_batch("e", src, dst, num_nodes=n_nodes, weights=w)
            cli.build("e")
            results[n_servers] = (
                cli.sample_neighbors("e", nodes, k=6, seed=9,
                                     weighted=True),
                cli.metapath_walk(["e", "e", "e"], nodes, seed=4,
                                  weighted=True))
        finally:
            cli.stop_servers()
            cli.close()
            for s in servers:
                s.stop()
    np.testing.assert_array_equal(results[1][0], results[2][0])
    np.testing.assert_array_equal(results[1][1], results[2][1])
    ref = sample_neighbors_host(full, nodes, 6, 9, weighted=True)
    np.testing.assert_array_equal(results[1][0], ref)

    # And the weighted draws actually tilt toward heavy edges: the host
    # sampler's empirical pick distribution on a 3-neighbor star. The
    # draw is deterministic per (seed, node, slot), so the SLOT axis is
    # what varies the randomness (identical rows repeat by design).
    star = _weighted_star()
    picks = sample_neighbors_host(star, np.zeros(1, np.int64), 8192, 123,
                                  weighted=True).reshape(-1)
    freq = np.bincount(picks, minlength=5) / picks.size
    np.testing.assert_allclose(freq[[1, 2, 3]], [0.1, 0.2, 0.7], atol=0.03)


def test_generator_weighted_walks():
    """GraphDataGenerator(weighted=True): walk hops follow edge weights
    (single-type and metapath), so skip-gram contexts concentrate on the
    heavy-edge path."""
    from paddlebox_tpu.graph import (GraphDataGenerator, GraphGenConfig,
                                     GraphTable)

    t = GraphTable()
    # 0 -> 1 heavy vs 0 -> 3 tiny; the first hop from 0 lands on 1.
    # Node 3 is a sink (its walks self-loop and mask out), so center-0
    # pairs come only from walks STARTING at 0 — no backward dilution.
    src = np.asarray([0, 0, 1, 2], np.int64)
    dst = np.asarray([1, 3, 2, 1], np.int64)
    w = np.asarray([1e4, 1e-4, 1.0, 1.0], np.float32)
    t.add_edges("e", src, dst, num_nodes=4, weights=w)
    gen = GraphDataGenerator(
        t, "e", GraphGenConfig(walk_len=1, window=1, batch_walks=64,
                               start_type=None, weighted=True))
    b = next(gen.batches())
    centers = np.asarray(b["centers"])
    contexts = np.asarray(b["contexts"])
    from_zero = contexts[(centers == 0) & np.asarray(b["mask"])]
    assert from_zero.size and (from_zero == 1).mean() > 0.9

    t.add_edges("f", dst, src, num_nodes=4, weights=w)
    gen2 = GraphDataGenerator(
        t, "e", GraphGenConfig(walk_len=2, batch_walks=8, weighted=True,
                               metapath=("e", "f")))
    assert next(gen2.batches())["centers"].size


def test_service_weighted_requires_weights():
    servers = [GraphServer("127.0.0.1:0", 0, 1)]
    cli = GraphClient([servers[0].endpoint])
    try:
        cli.upload_batch("e", np.asarray([0]), np.asarray([1]),
                         num_nodes=2)
        cli.build("e")
        with pytest.raises(RuntimeError, match="no weights"):
            cli.sample_neighbors("e", np.asarray([0]), 2, weighted=True)
    finally:
        cli.stop_servers()
        cli.close()
        servers[0].stop()
