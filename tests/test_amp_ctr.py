"""compute_dtype="bfloat16" (AMP role, paddle.amp / AMP meta-optimizer):
model fwd/bwd in bf16 with f32 master params, loss/AUC/sparse push f32."""

import numpy as np

from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

from tests.test_device_store import _FakeDataset


def _run(compute_dtype):
    mesh = build_mesh(HybridTopology(dp=8))
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(3))
    feed = DataFeedConfig(slots=slots, batch_size=64)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                   emb_dim=4, hidden=(16,))
    tr = CTRTrainer(model, feed,
                    TableConfig(dim=4, learning_rate=0.1), mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10,
                                         compute_dtype=compute_dtype))
    tr.init(seed=0)
    losses = []
    for p in range(3):
        ds = _FakeDataset(feed, seed=5 + p, nbatches=3, ndev=8)
        losses.append(tr.train_pass(ds)["loss"])
    return losses


def test_bf16_compute_trains_close_to_f32():
    l_bf16 = _run("bfloat16")
    l_f32 = _run("float32")
    assert all(np.isfinite(l_bf16))
    # Same trajectory within bf16 tolerance; still learning.
    np.testing.assert_allclose(l_bf16, l_f32, rtol=0.05, atol=0.02)
    assert l_bf16[-1] < l_bf16[0]
