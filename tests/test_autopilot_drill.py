"""The ISSUE-20 acceptance drill: chaos-under-load soak with the full
autopilot loop closed over REAL replica processes.

A seeded, hot-set-skewed trace (serving/traceload.py) replays against a
router over three replica subprocesses (``fleet_replica_worker.py``)
standing on a REPLICATED 2-host shard tier, with the FleetAutopilot
driving the actuators. The chaos script rides the trace:

- a 10x rate spike,
- a replica kill -9 (the autopilot must heal the fleet back over the
  FLAGS_autopilot_min_replicas floor by spawning a fresh worker
  process),
- a shard-host kill (replicated tier: every replica's miss reads fail
  over, no client sees it),
- a calibration-poisoned donefile BASE publish (the canary controller
  stages it on one replica, watches the REAL sampled-label COPC join
  collapse, and rolls the canary back to the incumbent base — the
  poisoned model never reaches full fanout).

Acceptance: ZERO failed client RPCs, merged predict p99 under the SLO
flag, the poisoned model confined + rolled back, and every autopilot
action visible in ONE telemetry_scrape sweep.
"""

import dataclasses
import os
import signal
import subprocess
import sys
import time

import numpy as np

from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import telemetry_scrape
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost.shard_service import (start_local_shards,
                                                   stop_shards)
from paddlebox_tpu.multihost.store import MultiHostStore
from paddlebox_tpu.serving import traceload
from paddlebox_tpu.serving.autopilot import FleetAutopilot
from paddlebox_tpu.serving.router import FleetRouter
from paddlebox_tpu.serving.service import PredictClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fleet_replica_worker.py")

DIM = 8
N_KEYS = 400           # shard tier holds all of these, clean
N_BASE = 360           # donefile base covers a prefix: the tail keys
#                        still exercise the shard-tier miss/failover path

_PROBE = ["0 u:5 i:9", "0 u:77 i:123", "0 u:200 i:350"]


def _spawn(elastic_root, host_id, shard_eps, ready_file, base_dir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PBX_FLEET_SHARD_REPLICAS"] = "2"
    env["PBX_FLEET_BASE_EXPORT"] = base_dir
    # The drill's labels flow through the router fan-out; every replica
    # samples every rid so the COPC join is dense enough for a verdict.
    env["FLAGS_quality_sample_rate"] = "1.0"
    env["FLAGS_quality_min_events"] = "8"
    env.pop("PBX_RANK", None)
    return subprocess.Popen(
        [sys.executable, WORKER, elastic_root, host_id,
         ",".join(shard_eps), ready_file],
        cwd=REPO, env=env, start_new_session=True)


def _wait_file(path, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        time.sleep(0.1)
    raise TimeoutError(f"worker never wrote {path}")


def _wait_healthy(router, want, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if router.fleet.size() >= want:
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"fleet never reached {want} healthy: {router.fleet.replicas()}")


def test_autopilot_chaos_soak_drill(tmp_path):
    # Replicated shard tier, populated with the deterministic model.
    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    shard_servers, shard_eps = start_local_shards(2, cfg, replicas=2)
    store = MultiHostStore(cfg, shard_eps, replicas=2)
    rng = np.random.default_rng(3)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * 0.02
    w = rng.normal(size=(N_KEYS,)).astype(np.float32) * 0.02
    rows = store.pull_for_pass(keys)
    rows["emb"] = emb.copy()
    rows["w"] = w.copy()
    store.push_from_pass(keys, rows)
    store.sync_replicas()

    # Donefile root: the clean incumbent base (published — the model
    # the workers stand up from) and the poisoned base (written now,
    # PUBLISHED mid-trace by the chaos event). The poison saturates
    # every prediction toward 1.0: served COPC collapses to ~0.5
    # against the alternating labels below.
    pub_root = str(tmp_path / "publish")
    proto = CheckpointProtocol(pub_root)

    def write_base(day, e, ww):
        d = proto.model_dir(day, 0)
        os.makedirs(d, exist_ok=True)
        np.savez(os.path.join(d, "embedding.xbox.npz"),
                 keys=keys[:N_BASE], emb=e[:N_BASE], w=ww[:N_BASE])
        return d

    base_dir = write_base("20260801", emb, w)
    proto.publish("20260801")
    write_base("20260802", emb + 5.0, w + 5.0)

    root = str(tmp_path / "elastic")
    procs = {}
    router = None
    cli = None
    autopilot = None
    prev = {k: flagmod.flag(k) for k in (
        "fleet_health_interval_s", "serving_slo_p99_ms",
        "autopilot_cooldown_s", "autopilot_min_replicas",
        "autopilot_max_replicas", "autopilot_poll_s",
        "autopilot_canary_replicas", "autopilot_canary_min_labels",
        "autopilot_canary_copc_margin", "autopilot_canary_timeout_s")}
    flagmod.set_flags({
        "fleet_health_interval_s": 0.2,
        "serving_slo_p99_ms": 2000.0,   # generous CPU bound; the drill
        # asserts p99 stays UNDER it through the spike and the kills
        "autopilot_cooldown_s": 8.0, "autopilot_min_replicas": 3,
        "autopilot_max_replicas": 5, "autopilot_poll_s": 0.25,
        "autopilot_canary_replicas": 1,
        "autopilot_canary_min_labels": 16,
        "autopilot_canary_copc_margin": 0.15,
        "autopilot_canary_timeout_s": 90.0})
    try:
        for hid in ("repA", "repB", "repC"):
            procs[hid] = _spawn(root, hid, shard_eps,
                                str(tmp_path / f"{hid}.ep"), base_dir)
        eps = {hid: _wait_file(str(tmp_path / f"{hid}.ep"))
               for hid in ("repA", "repB", "repC")}
        router = FleetRouter("127.0.0.1:0", elastic_root=root)
        _wait_healthy(router, 3)

        # The clean model's answers — identical on every replica (same
        # base export, same dense seed, same shard tier), and what the
        # whole fleet must serve again once the poisoned canary is
        # rolled back.
        clean_probs = None
        for ep in eps.values():
            c = PredictClient(ep)
            p = c.predict(_PROBE)
            c.close()
            if clean_probs is None:
                clean_probs = p
            else:
                np.testing.assert_array_equal(p, clean_probs)

        spawned = {}

        def spawn():
            # Idempotent actuator: asked again while the last joiner is
            # still importing jax, hand back the same rid instead of
            # forking another process.
            for rid, p in spawned.items():
                rep = router.fleet.get(rid)
                if p.poll() is None and (rep is None
                                         or rep.state != "healthy"):
                    return rid
            rid = f"auto-{len(spawned)}"
            spawned[rid] = procs[rid] = _spawn(
                root, rid, shard_eps, str(tmp_path / f"{rid}.ep"),
                base_dir)
            return rid

        def retire(rid):
            p = procs.pop(rid, None)
            spawned.pop(rid, None)
            if p is not None and p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    p.kill()

        autopilot = FleetAutopilot(
            router.fleet, lambda: router.handle_stats({}),
            donefile_root=pub_root, spawn=spawn, retire=retire,
            registry=router.metrics,
            state_path=str(tmp_path / "autopilot.json"))
        autopilot.start()

        dur = 8.0
        cfg_t = traceload.TraceConfig(
            seed=0, duration_s=dur, base_rps=25.0, n_keys=N_KEYS,
            slots=("u", "i"), rows_per_request=2,
            chaos=(
                traceload.ChaosEvent(at_s=0.30 * dur, kind="spike",
                                     duration_s=0.15 * dur, factor=10.0),
                traceload.ChaosEvent(at_s=0.45 * dur,
                                     kind="kill_replica", arg="repB"),
                traceload.ChaosEvent(at_s=0.60 * dur, kind="kill_shard",
                                     arg="0"),
                traceload.ChaosEvent(at_s=0.70 * dur,
                                     kind="poison_delta",
                                     arg="20260802"),
            ))
        gen = traceload.TraceGenerator(cfg_t)

        cli = PredictClient(router.endpoint)
        failures = []

        def send(req):
            seq = int(req.rid.rsplit("-", 1)[1])
            try:
                out = cli.predict(list(req.lines), rid=req.rid)
                assert out.shape == (len(req.lines),)
                cli.send_labels(req.rid,
                                [(seq + r) % 2
                                 for r in range(len(req.lines))])
            except Exception as e:  # noqa: BLE001 - the drill count
                failures.append((req.rid, repr(e)))

        def kill_replica(ev):
            p = procs[ev.arg]
            os.kill(p.pid, signal.SIGKILL)
            p.wait(timeout=30)

        def kill_shard(ev):
            shard_servers[int(ev.arg)].kill()

        def poison(ev):
            proto.publish(ev.arg)

        # Label-join sanity before the chaos starts: a broken sample/
        # fan-out path would otherwise surface as a canary timeout.
        warm = traceload.TraceGenerator(
            dataclasses.replace(cfg_t, seed=99, duration_s=1.0,
                                chaos=()))
        for req in warm.requests():
            send(req)
        snap = telemetry_scrape.scrape_endpoint(eps["repA"],
                                                with_stats=False)
        assert snap["counters"].get("quality/label_joined", 0) > 0, \
            "label join path is dead — canary verdict would starve"

        replayed = traceload.replay(
            gen, send, handlers={"kill_replica": kill_replica,
                                 "kill_shard": kill_shard,
                                 "poison_delta": poison})
        assert replayed["events_fired"] == 3

        # Drain: keep labeled traffic flowing until the canary verdict
        # lands and the fleet heals back over the floor.
        deadline = time.time() + 150.0
        extra = 1
        while time.time() < deadline:
            canary_open = autopilot.canary.state.data.get(
                "canary") is not None
            healed = router.fleet.size() >= 3
            if not canary_open and healed:
                break
            drain = traceload.TraceGenerator(dataclasses.replace(
                cfg_t, seed=1000 + extra, duration_s=1.5, chaos=()))
            extra += 1
            for req in drain.requests():
                send(req)
        reports = list(autopilot.canary.reports)
        st = router.handle_stats({})
        autopilot.stop()

        # -- acceptance ----------------------------------------------------
        assert failures == [], failures[:5]
        # The killed replica left; the autopilot healed the floor.
        assert router.fleet.size() >= 3, router.fleet.replicas()
        dead = router.fleet.get("repB")
        assert dead is None or dead.state == "ejected"
        assert any(a["kind"] == "scale_out"
                   for a in autopilot.scaler.actions), \
            autopilot.scaler.actions
        # Bounded tail through spike + kills.
        p99 = (st.get("latency_ms") or {}).get("p99")
        assert p99 is not None and p99 < 2000.0, st.get("latency_ms")
        # The poisoned base was staged, breached COPC, and rolled back
        # — never promoted, and the whole fleet serves the clean model.
        rollbacks = [r for r in reports if r["verdict"] == "rollback"]
        assert rollbacks, reports
        assert rollbacks[-1]["objective"] in ("copc", "timeout")
        assert not [r for r in reports if r["verdict"] == "promote"]
        for rep in router.fleet.healthy():
            c = PredictClient(rep.endpoint)
            try:
                np.testing.assert_array_equal(c.predict(_PROBE),
                                              clean_probs)
            finally:
                c.close()
        # Every action in ONE scrape sweep (the autopilot mirrors its
        # counters into the router's instance registry).
        sweep = telemetry_scrape.scrape_cluster(
            {"router": router.endpoint}, with_stats=False)
        acts = {k: v
                for k, v in (sweep["merged"]["counters"] or {}).items()
                if k.startswith("autopilot/actions/")}
        assert acts.get("autopilot/actions/scale_out", 0) >= 1, acts
        assert acts.get("autopilot/actions/canary_start", 0) >= 1, acts
        assert acts.get("autopilot/actions/canary_rollback", 0) >= 1, \
            acts
        router_snap = telemetry_scrape.scrape_endpoint(
            router.endpoint, with_stats=False)
        assert router_snap["gauges"].get("fleet/topology_epoch", 0) > 0
    finally:
        if autopilot is not None:
            autopilot.stop()
        flagmod.set_flags(prev)
        if cli is not None:
            cli.close()
        if router is not None:
            router.stop()
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    p.kill()
                p.wait(timeout=30)
        store.close()
        stop_shards(shard_servers)
