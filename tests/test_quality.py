"""Model-quality & data-health observatory (core/quality.py).

The planted-drift contract: a synthetic calibration shift and a slot
going dark each trip exactly the right ``quality/alarms/*`` within one
pass, a healthy multi-day stream run trips none, the label-join window
expiry is counted not crashed, one ``telemetry_scrape`` sweep shows a
trainer-side alarm fleet-wide, and the jaxpr pins prove the train step
and serving forward are unchanged with quality collection on."""

import json
import os

import numpy as np
import pytest

from paddlebox_tpu.core import flags, monitor, quality
from paddlebox_tpu.data import DataFeedConfig, SlotConf
from paddlebox_tpu.data.columnar import instances_to_chunk
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.stream import StreamRunner
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item")
BS = 32
ROWS = 96          # rows per event file = one carved pass


@pytest.fixture
def qflags():
    """Arm quality collection with test-friendly thresholds; restore +
    reset the global tracker/registry afterwards."""
    prev = {}

    def set_(**kw):
        for k in kw:
            prev.setdefault(k, flags.flag(k))
        flags.set_flags(kw)

    set_(quality_collect=True, quality_warmup_passes=2,
         quality_baseline_passes=6, quality_copc_tol=0.5,
         quality_coverage_drop=0.5)
    quality.GLOBAL.reset()
    monitor.reset()
    try:
        yield set_
    finally:
        flags.set_flags(prev)
        quality.GLOBAL.reset()
        monitor.reset()


def _feed():
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=BS)


def _trainer():
    mesh = build_mesh(HybridTopology(dp=8))
    tr = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), _feed(),
        TableConfig(name="emb", dim=8, learning_rate=0.05), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=1e-3,
                             auc_num_buckets=1 << 10))
    tr.init(seed=0)
    return tr


def _write_event_file(log_dir, name, rng, label_fn, *, rows=ROWS,
                      lo=1, hi=200, slots=SLOTS):
    """One atomically-appearing log segment; label_fn(rng) -> 0/1."""
    os.makedirs(log_dir, exist_ok=True)
    tmp = os.path.join(log_dir, "." + name + ".tmp")
    with open(tmp, "w") as f:
        for _ in range(rows):
            toks = " ".join(f"{s}:{rng.integers(lo, hi)}" for s in slots)
            f.write(f"{label_fn(rng)} {toks}\n")
    path = os.path.join(log_dir, name)
    os.replace(tmp, path)
    return path


def _stream_runner(tr, tmp_path):
    return StreamRunner(tr, _feed(), str(tmp_path / "out"),
                        log_dir=str(tmp_path / "events"),
                        day_of=lambda p: os.path.basename(p).split("-")[0],
                        shuffle=False, num_reader_threads=1)


# -- units --------------------------------------------------------------------


def test_log_bucket_rebin_and_offenders():
    nb = 1000
    table = np.zeros((2, nb))
    b = int(0.3 * nb)                 # 10K shows predicted at ~0.3
    table[0, b] = 7000.0
    table[1, b] = 3000.0              # actual ctr 0.3 -> calibrated
    buckets = quality.log_bucket_table(table)
    assert len(buckets) == 1
    assert abs(buckets[0]["copc"] - 1.0) < 0.05
    assert quality.offending_buckets(buckets, tol=0.2) == []
    # Flip the labels: actual 0.7 at predicted 0.3 -> the bucket must
    # be NAMED as offending, with its prediction range attached.
    table[0, b], table[1, b] = 3000.0, 7000.0
    bad = quality.offending_buckets(quality.log_bucket_table(table),
                                    tol=0.2)
    assert len(bad) == 1
    assert bad[0]["copc"] > 2.0
    assert bad[0]["lo"] < 0.3 <= bad[0]["hi"]
    # The calibration error reuses the registry sweep verbatim.
    from paddlebox_tpu.metrics.registry import bucket_error_sweep
    assert quality.calibration_error_from_table(table) == \
        pytest.approx(bucket_error_sweep(table))


def test_drift_detector_warmup_then_abrupt_alarm(qflags):
    d = quality.DriftDetector()
    # Warmup + gradual convergence: never alarms.
    for v in (0.6, 0.65, 0.7, 0.74, 0.78):
        assert d.check("m", v, rel_tol=0.25) is None
    # Abrupt excursion vs the EWMA baseline: alarms with context.
    a = d.check("m", 2.5, rel_tol=0.25)
    assert a is not None and a["value"] == 2.5
    assert 0.6 <= a["baseline"] <= 0.85
    # Direction filter: a coverage-style metric only alarms DOWN.
    for v in (0.9, 0.9, 0.9):
        d.check("cov", v, rel_tol=0.3, direction="down")
    assert d.check("cov", 2.0, rel_tol=0.3, direction="down") is None
    assert d.check("cov", 0.1, rel_tol=0.3, direction="down") is not None


def test_slot_health_collector_units():
    feed = _feed()
    lines = ([f"0 user:{i % 5 + 1} item:{i + 1}" for i in range(80)]
             + [f"0 user:{i % 5 + 1}" for i in range(20)])  # item gap
    chunk = instances_to_chunk(parse_lines(lines, feed), feed)
    # Zero keys never survive the svm parser — plant them chunk-side
    # (the collector watches the columnar path, wherever it came from).
    chunk.sparse_ids["user"][:20] = 0
    c = quality.SlotHealthCollector()
    c.observe_chunk(chunk)
    h = c.finalize()
    assert h["examples"] == 100
    u, it = h["slots"]["user"], h["slots"]["item"]
    assert u["coverage"] == 1.0
    assert it["coverage"] == pytest.approx(0.8)
    assert u["zero_frac"] == pytest.approx(0.2)
    assert u["unique_keys"] == 6       # 5 hot + the planted zero key
    # user draws from 6 keys -> its head-1% (1 key) owns a fat share;
    # item ids are all unique -> top share is ~1/n.
    assert u["top_share"] > it["top_share"]
    assert it["ids_per_example_p50"] in (0.0, 1.0)
    assert h["label_oob_frac"] == 0.0
    assert set(h["_keys"]) == {"user", "item"}


# -- the always-on pass_report satellite -------------------------------------


def test_pass_report_carries_copc_and_bucket_error(tmp_path):
    """Satellite pin: calibration lands in EVERY pass report + registry
    — computed-then-dropped no more — with quality_collect left OFF."""
    from paddlebox_tpu.data import Dataset

    assert not flags.flag("quality_collect")
    monitor.reset()
    rng = np.random.default_rng(0)
    path = _write_event_file(str(tmp_path), "p0.log", rng,
                             lambda r: int(r.random() < 0.3))
    ds = Dataset(_feed(), num_reader_threads=1)
    ds.set_filelist([path])
    ds.load_into_memory()
    tr = _trainer()
    stats = tr.train_pass(ds)
    rep = stats["pass_report"]
    for k in ("copc", "bucket_error", "actual_ctr", "predicted_ctr"):
        assert k in rep and np.isfinite(rep[k]), k
    assert rep["copc"] == pytest.approx(
        rep["actual_ctr"] / rep["predicted_ctr"], rel=1e-6)
    snap = monitor.snapshot()
    assert snap["pass/train_copc"] == pytest.approx(rep["copc"])
    assert snap["pass/train_bucket_error"] == rep["bucket_error"]
    # Off by default: no quality_report was emitted.
    assert "quality_report" not in stats
    assert monitor.get("quality/reports") == 0
    ev = tr.eval_pass(ds)
    assert monitor.get_gauge("pass/eval_copc") == pytest.approx(
        ev["copc"])
    monitor.reset()


# -- planted drift over a stream ---------------------------------------------


def test_planted_copc_shift_trips_alarm_within_one_pass(tmp_path,
                                                        qflags):
    """The acceptance drill: a streamed day with a planted mid-day
    calibration shift raises quality/alarms/copc within ONE carved
    pass, the quality_report names the offending prediction buckets,
    and one telemetry_scrape sweep shows the alarm fleet-wide."""
    qflags(stream_pass_events=ROWS, stream_pass_window_s=0.0)
    tr = _trainer()
    runner = _stream_runner(tr, tmp_path)
    log_dir = str(tmp_path / "events")
    rng = np.random.default_rng(1)
    healthy = lambda r: int(r.random() < 0.3)  # noqa: E731
    for i in range(5):
        _write_event_file(log_dir, f"day0-{i:03d}.log", rng, healthy)
        assert runner.poll_once(flush=True) == 1
    assert monitor.get("quality/alarms/copc") == 0
    base_reports = monitor.get("quality/reports")
    # Mid-day shift: every event converts — actual ctr ~1.0 against
    # predictions trained at 0.3.
    _write_event_file(log_dir, "day0-900.log", rng, lambda r: 1)
    assert runner.poll_once(flush=True) == 1
    assert monitor.get("quality/alarms/copc") >= 1
    assert monitor.get("quality/reports") == base_reports + 1
    rep = quality.GLOBAL.last_report
    assert rep["day"] == "day0" and rep["pass_id"] == 6
    assert rep["events"] == ROWS
    assert any(a["kind"] == "copc" for a in rep["alarms"])
    assert rep["offending_buckets"], "the shifted buckets must be named"
    assert all(b["copc"] > 1.0 for b in rep["offending_buckets"])

    # Fleet-wide: ANY framed server in this process answers the base
    # metrics_snapshot from the global registry — one scrape sweep
    # shows the trainer's alarm beside everything else.
    from paddlebox_tpu.core import telemetry_scrape as ts
    from paddlebox_tpu.distributed import rpc
    srv = rpc.FramedRPCServer("127.0.0.1:0")
    try:
        sweep = ts.scrape_cluster({"trainer": srv.endpoint},
                                  with_stats=False)
    finally:
        srv.stop()
    assert not sweep["errors"]
    merged = sweep["merged"]
    assert merged["counters"]["quality/alarms/copc"] >= 1
    assert sweep["summary"][0]["quality_alarms"] >= 1
    assert sweep["cluster"]["quality_alarms"] >= 1
    assert "copc" in sweep["cluster"]


def test_healthy_stream_trips_no_alarms(tmp_path, qflags):
    """Stationary multi-day traffic through day rollovers: gradual
    convergence and the sliding per-day key window must trip NOTHING
    (churn alarm armed; rollover suppression covers the day edge)."""
    qflags(stream_pass_events=ROWS, stream_pass_window_s=0.0,
           quality_churn_max=0.9)
    tr = _trainer()
    runner = _stream_runner(tr, tmp_path)
    log_dir = str(tmp_path / "events")
    rng = np.random.default_rng(2)
    healthy = lambda r: int(r.random() < 0.3)  # noqa: E731
    for day in range(3):
        for i in range(2):
            _write_event_file(log_dir, f"day{day}-{i:03d}.log", rng,
                              healthy, lo=1 + day * 50,
                              hi=200 + day * 50)
            assert runner.poll_once(flush=True) == 1
        runner.end_day()
    snap = monitor.snapshot()
    alarms = {k: v for k, v in snap.items()
              if k.startswith("quality/alarms/")}
    assert not alarms, alarms
    # The quality plane still observed every pass.
    assert monitor.get("quality/reports") == 6
    assert monitor.get_gauge("quality/copc") > 0.0
    assert "quality/slot_coverage/user" in snap


def test_slot_going_dark_trips_slot_dark(tmp_path, qflags):
    qflags(stream_pass_events=ROWS, stream_pass_window_s=0.0)
    tr = _trainer()
    runner = _stream_runner(tr, tmp_path)
    log_dir = str(tmp_path / "events")
    rng = np.random.default_rng(3)
    healthy = lambda r: int(r.random() < 0.3)  # noqa: E731
    for i in range(4):
        _write_event_file(log_dir, f"day0-{i:03d}.log", rng, healthy)
        assert runner.poll_once(flush=True) == 1
    assert monitor.get("quality/alarms/slot_dark") == 0
    # The item slot vanishes from the feed (an upstream join broke).
    _write_event_file(log_dir, "day0-900.log", rng, healthy,
                      slots=("user",))
    assert runner.poll_once(flush=True) == 1
    assert monitor.get("quality/alarms/slot_dark") >= 1
    rep = quality.GLOBAL.last_report
    dark = [a for a in rep["alarms"] if a["kind"] == "slot_dark"]
    assert dark and dark[0]["slot"] == "item"
    assert rep["slots"]["item"]["coverage"] == 0.0
    assert monitor.get_gauge("quality/slot_coverage/item") == 0.0


# -- serving label join -------------------------------------------------------


def test_label_join_window_expiry_counted_not_crashed(qflags):
    qflags(quality_sample_rate=1.0, quality_join_window_s=10.0,
           quality_join_pending=4, quality_min_events=10_000)
    now = [1000.0]
    reg = monitor.Monitor()
    q = quality.ServingQuality(registries=(reg,),
                               clock=lambda: now[0])
    preds = np.full(8, 0.25)
    assert q.sample("r1", preds)
    assert q.sample("r2", preds)
    now[0] += 60.0                     # both age out of the window
    assert not q.join("r1", np.ones(8))
    assert reg.get("quality/label_join_expired") >= 2
    assert reg.get("quality/label_join_miss") == 1
    # A fresh sample joins fine.
    assert q.sample("r3", preds)
    assert q.join("r3", np.ones(8))
    assert reg.get("quality/label_joined") == 8
    # Capacity bound: oldest entries expire counted, never unbounded.
    for i in range(10):
        q.sample(f"cap-{i}", preds)
    assert q.pending() <= 4
    # An unknown rid is a counted miss, not an error.
    assert not q.join("never-sampled", np.ones(8))


def test_serving_copc_band_alarm_reaches_instance_registry(qflags):
    qflags(quality_sample_rate=1.0, quality_min_events=32,
           quality_copc_band=0.3)
    reg = monitor.Monitor()
    q = quality.ServingQuality(registries=(reg,), clock=lambda: 0.0)
    preds = np.full(16, 0.25)
    for i in range(4):                 # 64 joined rows -> 2 windows
        rid = f"r{i}"
        assert q.sample(rid, preds)
        assert q.join(rid, np.ones(16))   # every impression clicked
    assert reg.get("quality/alarms/copc") >= 1
    assert monitor.get("quality/alarms/copc") >= 1
    assert reg.get_gauge("quality/copc") == pytest.approx(4.0, rel=0.1)


def test_predict_rid_sampling_and_labels_rpc(tmp_path, qflags):
    """End-to-end over the wire: rid-tagged predicts sample on the
    replica, send_labels joins, the alarm lands in the instance
    registry, and handle_stats/fleet summarize it."""
    import jax

    from paddlebox_tpu.serving import (CTRPredictor, PredictClient,
                                       PredictServer)
    qflags(quality_sample_rate=1.0, quality_min_events=32,
           quality_copc_band=0.3)
    feed = _feed()
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=())
    dense = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    keys = np.arange(1, 65, dtype=np.uint64)
    emb = rng.normal(size=(64, 8)).astype(np.float32) * 0.01
    w = rng.normal(size=(64,)).astype(np.float32) * 0.01
    pred = CTRPredictor(model, feed, keys, emb, w, dense,
                        compute_dtype="float32")
    srv = PredictServer("127.0.0.1:0", pred)
    cli = PredictClient(srv.endpoint)
    try:
        lines = [f"0 user:{i % 60 + 1} item:{(i * 7) % 60 + 1}"
                 for i in range(16)]
        for i in range(4):
            cli.predict(lines, rid=f"q{i}")
            out = cli.send_labels(f"q{i}", [1.0] * 16)
            assert out["joined"]
        st = cli.stats()
        assert st["quality_alarms"] >= 1
        snap = srv.handle_metrics_snapshot({})
        assert snap["counters"]["quality/alarms/copc"] >= 1
        from paddlebox_tpu.core.telemetry_scrape import summarize_target
        row = summarize_target("rep", srv.endpoint, snap)
        assert row["quality_alarms"] >= 1
        # A rid the window never saw: counted miss over the wire too.
        assert not cli.send_labels("ghost", [1.0])["joined"]
    finally:
        cli.stop_server()
        cli.close()
        srv.stop()


# -- slot-AUC satellite -------------------------------------------------------


def test_slot_auc_gauges(tmp_path):
    from paddlebox_tpu.data import Dataset
    from paddlebox_tpu.train.auc_runner import slot_replacement_eval

    monitor.reset()
    rng = np.random.default_rng(5)
    # user carries the label signal; item is noise — the drop ranking
    # must reflect it AND land in the registry.
    path = os.path.join(str(tmp_path), "p0")
    with open(path, "w") as f:
        for _ in range(BS * 8):
            u = int(rng.integers(1, 40))
            it = int(rng.integers(1, 40))
            label = int(rng.random() < (0.8 if u % 2 else 0.1))
            f.write(f"{label} user:{u} item:{it}\n")
    ds = Dataset(_feed(), num_reader_threads=1)
    ds.set_filelist([path])
    ds.load_into_memory()
    tr = _trainer()
    for _ in range(2):
        tr.train_pass(ds)
    out = slot_replacement_eval(tr, ds, seed=0)
    assert out["ranking"][0] == "user"
    snap = monitor.snapshot()
    assert snap["quality/base_auc"] == pytest.approx(out["base_auc"])
    for s in SLOTS:
        assert snap[f"quality/slot_auc/{s}"] == pytest.approx(
            out["slots"][s]["auc"])
        assert snap[f"quality/slot_auc_drop/{s}"] == pytest.approx(
            out["slots"][s]["auc_drop"])
    monitor.reset()


# -- zero-device-cost pins ----------------------------------------------------


def test_quality_on_leaves_step_and_serving_forward_unchanged(qflags):
    """The jaxpr pin: quality collection is host-side only — the train
    step and the serving forward trace to identical op counts with
    FLAGS_quality_collect (and serving sampling) on."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.slots import SlotBatch
    from paddlebox_tpu.embedding import DeviceFeatureStore
    from paddlebox_tpu.serving.batcher import pack_bucketed
    from paddlebox_tpu.serving.predictor import CTRPredictor
    from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
    from paddlebox_tpu.utils import inspect as pbx_inspect

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=8)
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=())

    def step_op_counts():
        mesh = build_mesh(HybridTopology(dp=4),
                          devices=jax.devices()[:4])
        tr = CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        lines = [f"{i % 2} user:{3 + i} item:{4 + i}" for i in range(8)]
        b = SlotBatch.pack_sharded(parse_lines(lines, feed), feed, 4)
        tr.engine.feed_pass([
            np.unique(np.concatenate([b.ids[n] for n in g.slots]))
            for g in tr.engine.groups])
        step = tr._build_step()
        tables = tr.engine.begin_pass()
        rows = tr._map_batch_rows(b)
        segs = {n: jnp.asarray(b.segments[n]) for n in b.ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows,
                segs, jnp.asarray(b.labels), jnp.asarray(b.valid),
                jnp.asarray(_concat_dense_host(b)),
                jnp.zeros((), jnp.int32))
        return pbx_inspect.jaxpr_summary(lambda *a: step(*a), *args)

    def fwd_op_counts():
        rng = np.random.default_rng(0)
        keys = np.arange(1, 33, dtype=np.uint64)
        emb = rng.normal(size=(32, 8)).astype(np.float32)
        w = rng.normal(size=(32,)).astype(np.float32)
        pred = CTRPredictor(model, feed, keys, emb, w,
                            model.init(jax.random.PRNGKey(0)),
                            compute_dtype="float32")
        batch = pack_bucketed(
            parse_lines(["0 user:3 item:4", "1 user:5 item:6"], feed),
            feed)
        caps = {n: batch.ids[n].shape[0] for n in pred._slot_names}
        all_ids = np.concatenate(
            [batch.ids[n] for n in pred._slot_names])
        looked = pred._index.lookup(all_ids)
        rows = np.where(looked < 0, pred._table.shape[0] - 1,
                        looked).astype(np.int32)
        fwd = pred._build_fwd(caps, batch.batch_size, 0)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in pred._slot_names}
        return pbx_inspect.jaxpr_summary(
            lambda *a: fwd(*a), pred._table, pred._zero_miss,
            pred._dense_params, rows, segs,
            jnp.asarray(_concat_dense_host(batch)))

    flags.set_flags({"quality_collect": False, "quality_sample_rate": 0.0})
    step_off, fwd_off = step_op_counts(), fwd_op_counts()
    flags.set_flags({"quality_collect": True, "quality_sample_rate": 1.0})
    step_on, fwd_on = step_op_counts(), fwd_op_counts()
    assert step_on == step_off, (step_on, step_off)
    assert fwd_on == fwd_off, (fwd_on, fwd_off)


def test_quality_report_jsonl_and_artifacts(tmp_path, qflags):
    """With the metrics sink armed, each quality_report appends one
    labeled snapshot — the scrape/JSONL surface of the quality plane."""
    from paddlebox_tpu.data import Dataset

    mpath = str(tmp_path / "m.jsonl")
    qflags(metrics_path=mpath, metrics_flush_interval_s=0.0)
    rng = np.random.default_rng(0)
    path = _write_event_file(str(tmp_path), "p0.log", rng,
                             lambda r: int(r.random() < 0.3))
    ds = Dataset(_feed(), num_reader_threads=1)
    ds.set_filelist([path])
    ds.load_into_memory()
    tr = _trainer()
    stats = tr.train_pass(ds)
    assert stats["quality_report"]["copc"] > 0
    assert "slots" in stats["quality_report"]
    lines = [json.loads(x) for x in open(mpath).read().splitlines()]
    q = [ln for ln in lines
         if ln["labels"].get("event") == "quality_report"]
    assert q, "quality_report must append a labeled JSONL snapshot"
    assert q[-1]["gauges"]["quality/copc"] > 0
    assert q[-1]["counters"]["quality/reports"] == 1
    monitor.stop_flush_thread()
