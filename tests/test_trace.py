"""Span tracer tests: nesting, thread safety, ring bound, Chrome-trace
validity, snapshot-on-exception, and the disabled-path contract (the
zero-hot-loop-cost requirement of the telemetry layer)."""

import json
import threading

import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import trace
from paddlebox_tpu.core.trace import Tracer


def test_span_nesting_records_both_levels():
    tr = Tracer(capacity=128)
    tr.enable()
    with tr.span("outer", k=4):
        with tr.span("inner"):
            pass
    evs = tr.snapshot()
    assert [e["name"] for e in evs] == ["inner", "outer"]  # close order
    inner, outer = evs
    assert outer["dur"] >= inner["dur"] >= 0.0
    assert outer["tid"] == inner["tid"] == threading.get_ident()
    assert outer["args"] == {"k": 4}
    assert all(e["ph"] == "X" for e in evs)


def test_thread_safety_all_events_land():
    tr = Tracer(capacity=100_000)
    tr.enable()
    n_threads, n_spans = 8, 200
    errors = []

    def worker(i):
        try:
            for j in range(n_spans):
                with tr.span(f"t{i}", j=j):
                    pass
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    evs = tr.snapshot()
    assert len(evs) == n_threads * n_spans
    # tids are OS thread idents (reused once a thread exits), so the
    # distinct count is >= 2, not necessarily n_threads.
    assert len({e["tid"] for e in evs}) >= 2


def test_ring_buffer_bound_and_drop_count():
    tr = Tracer(capacity=16)
    tr.enable()
    for i in range(100):
        tr.instant("e", i=i)
    evs = tr.snapshot()
    assert len(evs) == 16
    # Oldest dropped, newest kept.
    assert [e["args"]["i"] for e in evs] == list(range(84, 100))
    assert tr.trace_object()["otherData"]["dropped_events"] == 84


def test_export_valid_chrome_trace_json(tmp_path):
    tr = Tracer(capacity=64)
    tr.enable(str(tmp_path / "t.trace.json"))
    with tr.span("stage", table="emb"):
        pass
    tr.instant("marker")
    tr.counter("bytes", per_step=123.0)
    path = tr.export()
    obj = json.load(open(path))
    evs = obj["traceEvents"]
    assert isinstance(evs, list) and evs
    # Thread-name metadata + the three recorded events.
    phs = [e["ph"] for e in evs]
    assert "M" in phs and "X" in phs and "i" in phs and "C" in phs
    for e in evs:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] >= 0.0 and "ts" in e
    # Args must have been clamped to JSON scalars.
    json.dumps(obj)


def test_span_records_on_exception_with_error_arg():
    tr = Tracer(capacity=8)
    tr.enable()
    with pytest.raises(ValueError):
        with tr.span("doomed", step=3):
            raise ValueError("boom")
    (ev,) = tr.snapshot()
    assert ev["name"] == "doomed"
    assert ev["args"]["step"] == 3
    assert "ValueError" in ev["args"]["error"]
    # The ring IS the crash dump: snapshot() after the exception has it.


def test_disabled_path_is_shared_noop():
    tr = Tracer(capacity=8)
    s1 = tr.span("a", x=1)
    s2 = tr.span("b")
    assert s1 is s2  # one shared null context, zero allocation
    with s1:
        pass
    tr.instant("c")
    tr.counter("d", v=1.0)
    assert tr.snapshot() == []


def test_non_json_args_are_clamped():
    tr = Tracer(capacity=8)
    tr.enable()
    with tr.span("s", obj=object()):
        pass
    (ev,) = tr.snapshot()
    assert isinstance(ev["args"]["obj"], str)
    json.dumps(ev)


def test_global_init_from_flags(tmp_path):
    path = str(tmp_path / "flagged.trace.json")
    prev = flagmod.flag("trace_path")
    try:
        flagmod.set_flags({"trace_path": path, "trace_ring_events": 32})
        assert trace.init_from_flags() is True
        assert trace.enabled()
        with trace.span("flagged"):
            pass
        out = trace.export()
        assert out == path
        assert any(e["name"] == "flagged"
                   for e in json.load(open(out))["traceEvents"])
    finally:
        flagmod.set_flags({"trace_path": prev})
        trace.disable()
        trace.clear()


def test_init_from_flags_stays_off_without_path():
    prev = flagmod.flag("trace_path")
    try:
        flagmod.set_flags({"trace_path": ""})
        trace.disable()
        assert trace.init_from_flags() is False
        assert not trace.enabled()
    finally:
        flagmod.set_flags({"trace_path": prev})
