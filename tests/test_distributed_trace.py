"""Fleet-wide distributed tracing (ISSUE 14 acceptance suite).

Covers the four contract pillars:

- **propagation** — a trace context minted at the client edge rides the
  framed wire through router → replica batcher → shard tier (including
  a forced read-failover hop) and through the training write path
  (push → primary → synchronous backup forward): one trace id on every
  hop's spans.
- **merge** — per-process trace rings carry wall-clock anchors; the
  ``trace_report --merge`` stitch produces ONE Perfetto trace with
  per-process tracks and resolving cross-process flow arrows. The
  3-process drill (router + 2 replica processes over a replicated
  2-host shard tier) proves it against real processes, kill included.
- **one-scrape telemetry** — every framed server answers
  ``metrics_snapshot``; ShardServer's instance registry keeps per-host
  counters separable, the replication-lag gauges are computed at scrape
  time, and ``fleet_top --once --json`` reports per-replica p99 +
  worst-slot lag in one sweep.
- **zero cost** — tracing-on (context active) leaves the jitted train
  step and serving forward op counts unchanged, and the disabled path
  attaches nothing to the wire.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import monitor, telemetry_scrape, trace
from paddlebox_tpu.distributed import rpc
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost.shard_service import (start_local_shards,
                                                   stop_shards)
from paddlebox_tpu.multihost.store import MultiHostStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fleet_replica_worker.py")
DIM = 8


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    trace.clear()
    yield
    trace.disable()
    trace.clear()


class _EchoServer(rpc.FramedRPCServer):
    service_name = "echo"

    def handle_echo(self, req):
        with trace.span("echo/inner"):
            return {"x": req.get("x"),
                    "ctx": trace.current_context()}

    def handle_slow(self, req):
        time.sleep(float(req.get("sleep_s", 0.5)))
        return True


# -- context + wire units ----------------------------------------------------


def test_wire_context_off_is_none_and_attaches_nothing():
    """Disabled path: no context minted, nothing on the wire, handler
    sees no thread-local context."""
    assert trace.wire_context() is None
    srv = _EchoServer("127.0.0.1:0")
    conn = rpc.FramedRPCConn(srv.endpoint, service_name="echo")
    try:
        out = conn.call("echo", x=1)
        assert out["ctx"] is None
        assert trace.snapshot() == []
    finally:
        conn.close()
        srv.stop()


def test_context_scopes_and_ids():
    trace.enable()
    root = trace.wire_context()
    assert set(root) == {"tid", "sid", "origin"}
    assert ":" in root["origin"]
    with trace.use_context(root):
        child = trace.wire_context()
        assert child["tid"] == root["tid"]          # same trace
        assert child["sid"] != root["sid"]          # fresh span
        assert trace.current_context() is root
    assert trace.current_context() is None
    sctx = trace.server_context(child)
    assert sctx["tid"] == root["tid"]
    assert sctx["parent"] == child["sid"]


def test_rpc_propagation_server_ms_and_flow_linkage():
    """One traced RPC: client span + server span share the trace id,
    the server span's parent is the client span id (the flow-arrow
    key), and the reply's _server_ms decomposes the client's observed
    latency into server vs wire share."""
    srv = _EchoServer("127.0.0.1:0")
    trace.enable()
    conn = rpc.FramedRPCConn(srv.endpoint, service_name="echo")
    try:
        out = conn.call("echo", x=2)
        assert out["ctx"] is not None               # context crossed
        assert conn.last_server_ms is not None
        assert conn.last_wire_ms is not None and conn.last_wire_ms >= 0
        evs = trace.snapshot()
        by_name = {e["name"]: e for e in evs}
        cli = by_name["rpc/client/echo"]
        se = by_name["rpc/echo"]
        inner = by_name["echo/inner"]
        tid = cli["args"]["trace"]
        assert se["args"]["trace"] == tid
        assert inner["args"]["trace"] == tid        # nested span inherits
        assert se["args"]["parent"] == cli["args"]["span"]
    finally:
        conn.close()
        srv.stop()


def test_clock_offset_handshake_and_anchor():
    """Tracing-on connects run the clock handshake: a same-machine peer
    reports a near-zero offset, recorded per endpoint in the export's
    otherData beside the wall anchor."""
    srv = _EchoServer("127.0.0.1:0")
    trace.enable()
    conn = rpc.FramedRPCConn(srv.endpoint, service_name="echo")
    try:
        assert conn.clock_offset_ms is not None
        assert abs(conn.clock_offset_ms) < 1000.0   # same machine
        obj = trace.GLOBAL.trace_object()
        od = obj["otherData"]
        assert od["wall_anchor_ns"] > 0
        assert od["pid"] == os.getpid()
        assert srv.endpoint in od["peer_offsets_ms"]
        assert monitor.get_gauge("rpc/clock_offset_ms") == \
            conn.clock_offset_ms
    finally:
        conn.close()
        srv.stop()


def test_rpc_retry_counters_labeled_by_method():
    """The ride-along bugfix: reconnects/retries are counted per method
    beside the totals, and a server restart consumes exactly the
    budget the counters say."""
    srv = _EchoServer("127.0.0.1:0")
    ep = srv.endpoint
    conn = rpc.FramedRPCConn(ep, service_name="echo",
                             idempotent=("echo",))
    base_re = monitor.get("rpc/retries/echo")
    base_rc = monitor.get("rpc/reconnects/echo")
    try:
        conn.call("echo", x=1)
        # Kill-like teardown, then a fresh server on the same port.
        srv.stop()
        srv.close_connections()
        deadline = time.time() + 30
        srv2 = None
        while srv2 is None and time.time() < deadline:
            try:
                srv2 = _EchoServer(ep)
            except OSError:
                time.sleep(0.1)
        assert srv2 is not None
        out = conn.call("echo", x=2)    # retried through the reconnect
        assert out["x"] == 2
        assert monitor.get("rpc/retries/echo") > base_re
        assert monitor.get("rpc/reconnects/echo") > base_rc
    finally:
        conn.close()
        srv2.stop()


def test_inflight_rpc_table_reaches_stall_forensics():
    """The watchdog satellite: a call blocked on a slow peer shows up
    in stall_forensics' inflight_rpcs with its endpoint, method, and
    age — and unregisters on completion."""
    srv = _EchoServer("127.0.0.1:0")
    conn = rpc.FramedRPCConn(srv.endpoint, service_name="echo")
    seen = {}

    def run():
        conn.call("slow", sleep_s=1.0)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        tab = rpc.inflight_table()
        hit = [e for e in tab if e["method"] == "slow"]
        if hit:
            seen = hit[0]
            break
        time.sleep(0.02)
    assert seen, "slow call never appeared in the inflight table"
    assert seen["endpoint"] == srv.endpoint
    assert seen["service"] == "echo"
    fx = trace.stall_forensics()
    assert any(e.get("method") == "slow"
               for e in fx["inflight_rpcs"])
    t.join(timeout=10)
    assert not [e for e in rpc.inflight_table()
                if e["method"] == "slow"]
    conn.close()
    srv.stop()


# -- shard tier: instance metrics + replication lag ---------------------------


def _shard_cluster(replicas=2, n_keys=400):
    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    servers, eps = start_local_shards(2, cfg, replicas=replicas)
    store = MultiHostStore(cfg, eps, replicas=replicas)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    rows = store.pull_for_pass(keys)
    store.push_from_pass(keys, rows)
    if replicas > 1:
        store.sync_replicas()
    return cfg, servers, eps, store, keys


def test_shard_server_instance_metrics_separate_per_host():
    """Satellite 1: two in-process ShardServers no longer clobber each
    other's multihost/* counters — each instance registry carries its
    own served volume, and the scrape merge still totals them."""
    cfg, servers, eps, store, keys = _shard_cluster(replicas=1)
    try:
        snaps = [telemetry_scrape.scrape_endpoint(ep, with_stats=False)
                 for ep in eps]
        per_host = [s["counters"].get("multihost/served_push_keys", 0)
                    for s in snaps]
        assert all(v > 0 for v in per_host), per_host
        assert sum(per_host) == keys.size
        # Per-host labels identify the shard.
        assert {s["labels"]["shard"] for s in snaps} == {0, 1}
        merged = monitor.merge_snapshots(snaps)
        assert merged["counters"]["multihost/served_push_keys"] == \
            keys.size
    finally:
        store.close()
        stop_shards(servers)


def test_replication_lag_gauge_under_held_back_backup():
    """The journal-lag gauge: kill one host (its backup slots stop
    acking), push N more mutations, and the surviving primary's scrape
    reports worst lag >= N while a healthy pair reports 0."""
    cfg, servers, eps, store, keys = _shard_cluster(replicas=2)
    try:
        snap = telemetry_scrape.scrape_endpoint(eps[1], with_stats=False)
        assert snap["gauges"]["multihost/replica_lag_worst"] == 0.0
        servers[0].kill()
        owner = store.ranges.owner_of(keys)
        held = keys[owner == 1]
        rows = {f: v for f, v in store.pull_for_pass(held).items()}
        n_push = 3
        for _ in range(n_push):
            store.push_from_pass(held, rows)
        snap = telemetry_scrape.scrape_endpoint(eps[1], with_stats=False)
        lag = snap["gauges"]["multihost/replica_lag_worst"]
        assert lag >= n_push, lag
        assert snap["gauges"]["multihost/replica_lag_p99"] >= n_push
        # The lag rides the instance registry, scrapeable in one sweep.
        rec = telemetry_scrape.scrape_cluster({"shard1": eps[1]})
        row = rec["summary"][0]
        assert row["replica_lag_worst"] >= n_push
    finally:
        store.close()
        stop_shards(servers)


def test_training_write_path_one_trace_id():
    """Training writes: trainer push → primary → synchronous backup
    forward all carry ONE trace id (the fan-out threads and the
    server-side peer forward both propagate the context)."""
    cfg, servers, eps, store, keys = _shard_cluster(replicas=2)
    trace.enable()
    try:
        with trace.use_context(trace.wire_context()) as ctx:
            rows = store.pull_for_pass(keys)
            store.push_from_pass(keys, rows)
        evs = trace.snapshot()
        tid = ctx["tid"]
        traced = {e["name"] for e in evs
                  if (e.get("args") or {}).get("trace") == tid}
        assert "rpc/client/push" in traced, traced
        assert "rpc/push" in traced
        # The synchronous backup forward is a hop of the SAME trace.
        assert "rpc/client/replica_apply" in traced, traced
        assert "rpc/replica_apply" in traced
        assert "multihost/shard_push" in traced
    finally:
        store.close()
        stop_shards(servers)


# -- fleet_top / scrape -------------------------------------------------------


def test_fleet_top_once_json_smoke(capsys):
    """Tier-1 CLI smoke: fleet_top --once --json against any framed
    server prints one parseable scrape record with summary + merged
    sections and exits 0."""
    from tools import fleet_top
    srv = _EchoServer("127.0.0.1:0")
    monitor.add("echo/requests", 1)  # graftlint: allow-registry(test-only name)
    try:
        rcode = fleet_top.main(["--targets", f"echo={srv.endpoint}",
                                "--once", "--json"])
        out = capsys.readouterr().out.strip().splitlines()[-1]
        rec = json.loads(out)
        assert rcode == 0
        assert rec["summary"][0]["target"] == "echo"
        assert rec["cluster"]["scraped"] == 1
        assert "counters" in rec["merged"]
    finally:
        srv.stop()


def test_fleet_top_unreachable_target_exits_nonzero(capsys):
    from tools import fleet_top
    rcode = fleet_top.main(["--targets", "gone=127.0.0.1:1",
                            "--once", "--json", "--timeout", "2"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rcode == 1
    assert "gone" in rec["errors"]


# -- merge validity -----------------------------------------------------------


def _fake_ring(events, wall_anchor_ns, pid, host="h"):
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"wall_anchor_ns": wall_anchor_ns,
                          "host": host, "pid": pid,
                          "peer_offsets_ms": {}}}


def test_merge_traces_aligns_anchors_and_draws_flows(tmp_path):
    """Merge mechanics on fabricated rings: wall anchors shift each
    file onto one timeline, colliding pids are remapped to distinct
    tracks, and client→server span pairs produce resolving flow
    arrows."""
    from tools.trace_report import merge_files
    cli_ev = {"name": "rpc/client/echo", "ph": "X", "pid": 7, "tid": 1,
              "ts": 100.0, "dur": 900.0,
              "args": {"trace": "t1", "span": "a.1"}}
    srv_ev = {"name": "rpc/echo", "ph": "X", "pid": 7, "tid": 9,
              "ts": 50.0, "dur": 500.0,
              "args": {"trace": "t1", "span": "b.1", "parent": "a.1"}}
    t0 = 1_000_000_000_000_000_000
    p1 = tmp_path / "a.trace.json"
    p2 = tmp_path / "b.trace.json"
    p1.write_text(json.dumps(_fake_ring([cli_ev], t0, 7, "hostA")))
    # Second process: same pid (collision), anchor 1 ms later.
    p2.write_text(json.dumps(_fake_ring([srv_ev], t0 + 1_000_000, 7,
                                        "hostB")))
    out = tmp_path / "merged.json"
    merged = merge_files([str(p1), str(p2)], str(out))
    evs = merged["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    pids = {e["pid"] for e in xs}
    assert len(pids) == 2                       # collision remapped
    cli = next(e for e in xs if e["name"] == "rpc/client/echo")
    srv = next(e for e in xs if e["name"] == "rpc/echo")
    # Anchor alignment: file B's events shifted +1 ms.
    assert srv["ts"] == pytest.approx(50.0 + 1000.0)
    assert cli["ts"] == pytest.approx(100.0)
    # Flow arrows: one s->f pair, binding client start to server start.
    flows = [e for e in evs if e.get("ph") in ("s", "f")]
    assert merged["otherData"]["flow_arrows"] == 1
    starts = {e["id"]: e for e in flows if e["ph"] == "s"}
    finishes = [e for e in flows if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 1
    f = finishes[0]
    s = starts[f["id"]]
    assert (s["pid"], s["tid"]) == (cli["pid"], cli["tid"])
    assert (f["pid"], f["tid"]) == (srv["pid"], srv["tid"])
    assert s["ts"] <= f["ts"]
    # Per-process tracks are named.
    names = {e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert any("hostA" in n for n in names)
    assert any("hostB" in n for n in names)
    # The merged file is a valid Chrome trace (loadable JSON object).
    loaded = json.loads(out.read_text())
    assert isinstance(loaded["traceEvents"], list)


# -- zero-cost pin ------------------------------------------------------------


def test_tracing_on_leaves_serving_forward_and_step_unchanged():
    """The jaxpr pin: with tracing enabled AND a trace context active,
    the serving forward and the jitted train step trace to identical
    op counts — the context is host-side metadata, never a device op."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.serving.batcher import pack_bucketed
    from paddlebox_tpu.serving.predictor import CTRPredictor
    from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
    from paddlebox_tpu.utils import inspect as pbx_inspect

    slots = ("u", "i")
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in slots),
        batch_size=8)
    model = DeepFM(slot_names=slots, emb_dim=DIM, hidden=())
    dense = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    keys = np.arange(1, 33, dtype=np.uint64)
    emb = rng.normal(size=(32, DIM)).astype(np.float32)
    w = rng.normal(size=(32,)).astype(np.float32)
    pred = CTRPredictor(model, feed, keys, emb, w, dense,
                        compute_dtype="float32")
    batch = pack_bucketed(
        parse_lines(["0 u:3 i:4", "1 u:5 i:6"], feed), feed)

    def fwd_op_counts():
        caps = {n: batch.ids[n].shape[0] for n in pred._slot_names}
        all_ids = np.concatenate(
            [batch.ids[n] for n in pred._slot_names])
        looked = pred._index.lookup(all_ids)
        rows = np.where(looked < 0, pred._table.shape[0] - 1,
                        looked).astype(np.int32)
        fwd = pred._build_fwd(caps, batch.batch_size, 0)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in pred._slot_names}
        return pbx_inspect.jaxpr_summary(
            lambda *a: fwd(*a), pred._table, pred._zero_miss,
            pred._dense_params, rows, segs,
            jnp.asarray(_concat_dense_host(batch)))

    off = fwd_op_counts()
    trace.enable()
    with trace.use_context(trace.wire_context()):
        on = fwd_op_counts()
    assert on == off, (on, off)

    # Train step: same pin through the trainer build (the serving
    # forward covers the predict path; this covers the fleet's write
    # producer).
    from paddlebox_tpu.embedding import DeviceFeatureStore
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    def step_op_counts():
        mesh = build_mesh(HybridTopology(dp=4),
                          devices=jax.devices()[:4])
        tr = CTRTrainer(model, feed, TableConfig(dim=DIM),
                        mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        lines = [f"{i % 2} u:{3 + i} i:{4 + i}" for i in range(8)]
        b = SlotBatch.pack_sharded(parse_lines(lines, feed), feed, 4)
        tr.engine.feed_pass([
            np.unique(np.concatenate([b.ids[n] for n in g.slots]))
            for g in tr.engine.groups])
        step = tr._build_step()
        tables = tr.engine.begin_pass()
        rows = tr._map_batch_rows(b)
        segs = {n: jnp.asarray(b.segments[n]) for n in b.ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows,
                segs, jnp.asarray(b.labels), jnp.asarray(b.valid),
                jnp.asarray(_concat_dense_host(b)),
                jnp.zeros((), jnp.int32))
        return pbx_inspect.jaxpr_summary(lambda *a: step(*a), *args)

    trace.disable()
    step_off = step_op_counts()
    trace.enable()
    with trace.use_context(trace.wire_context()):
        step_on = step_op_counts()
    assert step_on == step_off, (step_on, step_off)


# -- the 3-process acceptance drill -------------------------------------------


def _spawn_replica(elastic_root, host_id, shard_eps, ready_file,
                   trace_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_trace_path"] = trace_path
    env["PBX_FLEET_SHARD_REPLICAS"] = "2"
    env.pop("PBX_RANK", None)
    return subprocess.Popen(
        [sys.executable, WORKER, elastic_root, host_id,
         ",".join(shard_eps), ready_file],
        cwd=REPO, env=env, start_new_session=True)


def _wait_file(path, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        time.sleep(0.1)
    raise TimeoutError(f"worker never wrote {path}")


def test_three_process_trace_drill(tmp_path, capsys):
    """The acceptance drill: router + 2 replica PROCESSES over a
    replicated 2-host shard tier. One predict's trace id spans client,
    router, replica, and shard hops — including a forced read-failover
    after a shard-host kill — across the MERGED per-process trace; and
    one fleet_top scrape reports per-replica p99 + worst-slot
    replication lag."""
    from paddlebox_tpu.serving.router import FleetRouter
    from paddlebox_tpu.serving.service import PredictClient
    from tools.trace_report import merge_files

    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    shard_servers, shard_eps = start_local_shards(2, cfg, replicas=2)
    store = MultiHostStore(cfg, shard_eps, replicas=2)
    keys = np.arange(1, 801, dtype=np.uint64)
    rows = store.pull_for_pass(keys)
    rng = np.random.default_rng(3)
    rows["emb"] = rng.normal(size=(keys.size, DIM)).astype(np.float32) * .02
    rows["w"] = rng.normal(size=(keys.size,)).astype(np.float32) * .02
    store.push_from_pass(keys, rows)
    store.sync_replicas()
    owner = store.ranges.owner_of(keys)
    slot0 = keys[owner == 0]
    assert slot0.size >= 8

    root = str(tmp_path / "elastic")
    procs = {}
    router = None
    cli = None
    prev_hb = flagmod.flag("fleet_health_interval_s")
    flagmod.set_flags({"fleet_health_interval_s": 0.2})
    traces = {h: str(tmp_path / f"{h}.trace.json")
              for h in ("repA", "repB")}
    try:
        for hid in ("repA", "repB"):
            procs[hid] = _spawn_replica(root, hid, shard_eps,
                                        str(tmp_path / f"{hid}.ep"),
                                        traces[hid])
        eps = {hid: _wait_file(str(tmp_path / f"{hid}.ep"))
               for hid in ("repA", "repB")}
        router = FleetRouter("127.0.0.1:0", elastic_root=root)
        deadline = time.time() + 120
        while router.fleet.size() < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert router.fleet.size() >= 2, router.fleet.replicas()

        trace.enable()
        cli = PredictClient(router.endpoint)
        # Warm hops (also warms each replica's conns).
        cli.predict([f"0 u:{slot0[0]} i:{slot0[1]}"])
        # Forced read-failover: kill shard host 0 (primary of slot 0),
        # then predict FRESH slot-0 keys — every replica's miss must
        # fail over to the surviving backup.
        shard_servers[0].kill()
        probe = [f"0 u:{slot0[-1]} i:{slot0[-2]}"]
        out = cli.predict(probe)
        assert out.shape == (1,)
        assert cli.last_hop is not None and "route_ms" in cli.last_hop
        tid = None
        for e in reversed(trace.snapshot()):
            if e["name"] == "rpc/client/predict":
                tid = e["args"]["trace"]
                break
        assert tid is not None

        # Collect every process's ring: workers via the trace_export
        # RPC, the parent (client + router + shard tier) directly.
        files = []
        for hid, ep in eps.items():
            c = rpc.FramedRPCConn(ep, service_name="collect")
            got = c.call("trace_export", path=traces[hid])
            c.close()
            assert got["events"] > 0
            files.append(traces[hid])
        parent_trace = str(tmp_path / "parent.trace.json")
        trace.GLOBAL.export(parent_trace)
        files.append(parent_trace)

        merged = merge_files(files, str(tmp_path / "fleet.trace.json"))
        evs = merged["traceEvents"]
        xs = [e for e in evs if e.get("ph") == "X"]
        hop_evs = [e for e in xs
                   if (e.get("args") or {}).get("trace") == tid]
        hop_pids = {e["pid"] for e in hop_evs}
        hop_names = {e["name"] for e in hop_evs}
        # ONE trace id across processes: the parent's client+router
        # spans AND a replica process's server-side spans.
        assert len(hop_pids) >= 2, (hop_pids, hop_names)
        assert "rpc/client/predict" in hop_names
        assert "rpc/predict" in hop_names
        assert "serving/predict" in hop_names
        # The shard hop (miss resolution) rides the same id.
        assert "rpc/client/pull_serving" in hop_names, hop_names
        # The forced failover hop is recorded under a trace id that the
        # parent's predicts minted (the batcher may coalesce, so match
        # any client-minted id).
        cli_tids = {e["args"]["trace"] for e in xs
                    if e["name"] == "rpc/client/predict"}
        fo = [e for e in evs
              if e.get("name") == "multihost/replica_failover"]
        assert fo, "no failover hop recorded"
        assert any((e.get("args") or {}).get("trace") in cli_tids
                   for e in fo)
        # Merged-trace validity: per-track timestamps are finite and
        # flow arrows resolve start-before-finish within clock-skew
        # tolerance (same machine).
        assert all(e["ts"] >= 0 for e in xs)
        flows = [e for e in evs if e.get("ph") in ("s", "f")]
        assert merged["otherData"]["flow_arrows"] > 0
        starts = {}
        for e in flows:
            if e["ph"] == "s":
                starts.setdefault(e["id"], e)
        for e in flows:
            if e["ph"] == "f":
                assert e["id"] in starts, e
                assert starts[e["id"]]["ts"] <= e["ts"] + 50_000, e

        # One-scrape cluster telemetry over the LIVE fleet — through
        # the fleet_top CLI itself: per-replica p99 + worst-slot
        # replication lag in ONE scrape. Push a held-back mutation
        # first so the lag is visible (shard host 0 is dead, so the
        # slot-1 primary's backup stops acking).
        from tools import fleet_top
        held = keys[owner == 1][:64]
        store.push_from_pass(held, store.pull_for_pass(held))
        rcode = fleet_top.main(["--router", router.endpoint,
                                "--shards", shard_eps[1],
                                "--once", "--json"])
        assert rcode == 0
        rec = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        rows_by_target = {r["target"]: r for r in rec["summary"]}
        rep_rows = [r for t, r in rows_by_target.items()
                    if t.startswith("replica:")]
        # Per-replica p99 for every replica that served traffic (hash
        # affinity may leave one replica idle — an idle digest has no
        # quantiles, correctly).
        assert len(rep_rows) == 2, rows_by_target
        assert any("predict_p99_ms" in r for r in rep_rows), \
            rows_by_target
        assert rows_by_target["shard0"]["replica_lag_worst"] >= 1
        assert rec["cluster"]["fleet_predict_p99_ms"] is not None
        assert rec["cluster"]["replica_lag_worst"] >= 1
    finally:
        flagmod.set_flags({"fleet_health_interval_s": prev_hb})
        if cli is not None:
            cli.close()
        if router is not None:
            router.stop()
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    p.kill()
                p.wait(timeout=30)
        store.close()
        stop_shards(shard_servers)
