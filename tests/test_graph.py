"""Graph engine tests: CSR build/load parity, padded device view
invariants, sampling validity (every sampled neighbor is a true
neighbor), walk validity, skip-gram batch generation, and an end-to-end
deepwalk-style embedding smoke train."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.graph import (DeviceGraph, GraphDataGenerator,
                                 GraphGenConfig, GraphTable, build_csr,
                                 device_arrays, load_edge_file, random_walk,
                                 sample_neighbors, skip_gram_pairs)
from paddlebox_tpu.graph import sampler


def ring_edges(n):
    src = np.arange(n)
    return src, (src + 1) % n


def test_build_csr_and_neighbors():
    src = np.asarray([0, 0, 1, 2, 2, 2])
    dst = np.asarray([1, 2, 2, 0, 1, 3])
    g = build_csr(src, dst)
    assert g.num_nodes == 4 and g.num_edges == 6
    np.testing.assert_array_equal(np.sort(g.neighbors(2)), [0, 1, 3])
    np.testing.assert_array_equal(g.degrees(), [2, 1, 3, 0])


def test_symmetrize_and_load_edge_file(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("0 1\n1 2\n2 3\n")
    g = load_edge_file(str(p), symmetrize=True)
    assert g.num_edges == 6
    np.testing.assert_array_equal(np.sort(g.neighbors(1)), [0, 2])


def test_device_graph_padding_invariants():
    src, dst = ring_edges(6)
    g = build_csr(src, dst)
    dg = DeviceGraph.from_csr(g, max_degree=4)
    # valid slots hold true neighbors; padding slots self-loop
    for i in range(6):
        np.testing.assert_array_equal(dg.nbrs[i, :dg.degree[i]],
                                      g.neighbors(i))
        np.testing.assert_array_equal(dg.nbrs[i, dg.degree[i]:], i)


def test_device_graph_truncates_high_degree():
    # star graph: node 0 connects to 1..9
    src = np.zeros(9, np.int64)
    dst = np.arange(1, 10)
    g = build_csr(src, dst)
    dg = DeviceGraph.from_csr(g, max_degree=4)
    assert dg.degree[0] == 4
    assert set(dg.nbrs[0].tolist()) <= set(range(1, 10))
    assert len(set(dg.nbrs[0].tolist())) == 4  # subsample w/o replacement


def test_build_csr_validates_ids():
    with pytest.raises(ValueError):
        build_csr(np.asarray([0]), np.asarray([5]), num_nodes=3)
    with pytest.raises(ValueError):
        build_csr(np.asarray([5]), np.asarray([0]), num_nodes=3)


def test_device_graph_truncation_many_hubs():
    """Vectorized hub subsample: several high-degree nodes at once, all
    slots valid, no duplicates within a node."""
    rng = np.random.default_rng(0)
    srcs, dsts = [], []
    for hub in range(5):
        nb = rng.choice(np.arange(5, 100), size=20, replace=False)
        srcs.append(np.full(20, hub))
        dsts.append(nb)
    g = build_csr(np.concatenate(srcs), np.concatenate(dsts))
    dg = DeviceGraph.from_csr(g, max_degree=8)
    for hub in range(5):
        row = dg.nbrs[hub]
        assert dg.degree[hub] == 8
        assert len(set(row.tolist())) == 8
        assert set(row.tolist()) <= set(g.neighbors(hub).tolist())


def test_sample_neighbors_validity():
    src, dst = ring_edges(8)
    g = build_csr(src, dst, symmetrize=True)
    nbrs, deg = device_arrays(DeviceGraph.from_csr(g))
    nodes = jnp.asarray([0, 3, 5], jnp.int32)
    out = sample_neighbors(nbrs, deg, nodes, jax.random.PRNGKey(0), k=16)
    assert out.shape == (3, 16)
    for row, node in zip(np.asarray(out), [0, 3, 5]):
        true = set(g.neighbors(node).tolist())
        assert set(row.tolist()) <= true
        assert len(set(row.tolist())) > 1  # both ring neighbors appear


def test_isolated_node_self_loops():
    g = build_csr(np.asarray([0]), np.asarray([1]), num_nodes=3)
    nbrs, deg = device_arrays(DeviceGraph.from_csr(g))
    out = sample_neighbors(nbrs, deg, jnp.asarray([2], jnp.int32),
                           jax.random.PRNGKey(1), k=4)
    np.testing.assert_array_equal(np.asarray(out), 2)


def test_random_walk_follows_edges():
    src, dst = ring_edges(10)
    g = build_csr(src, dst)  # directed ring: walk must be i, i+1, i+2...
    nbrs, deg = device_arrays(DeviceGraph.from_csr(g))
    starts = jnp.asarray([0, 4], jnp.int32)
    walks = np.asarray(random_walk(nbrs, deg, starts,
                                   jax.random.PRNGKey(0), walk_len=5))
    np.testing.assert_array_equal(walks[0], np.arange(6) % 10)
    np.testing.assert_array_equal(walks[1], (4 + np.arange(6)) % 10)


def test_skip_gram_pairs_window():
    walks = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    pairs = np.asarray(skip_gram_pairs(walks, window=1))
    mask = pairs[:, 0] != pairs[:, 1]
    real = {tuple(p) for p in pairs[mask].tolist()}
    want = {(0, 1), (1, 2), (2, 3), (1, 0), (2, 1), (3, 2)}
    assert real == want


def test_graph_table_facade_and_features():
    t = GraphTable(num_shards=4)
    src, dst = ring_edges(8)
    t.add_edges("follow", src, dst, symmetrize=True)
    assert t.graph("follow").num_edges == 16
    dg = t.device_graph("follow")
    assert dg.nbrs.shape[0] == 8
    t.set_node_feat("emb", np.arange(16, dtype=np.float32).reshape(8, 2))
    np.testing.assert_array_equal(t.get_node_feat("emb", [2, 0]),
                                  [[4, 5], [0, 1]])
    np.testing.assert_array_equal(t.shard_of([5, 8]), [1, 0])


def test_data_generator_shapes_and_coverage():
    t = GraphTable()
    src, dst = ring_edges(20)
    t.add_edges("e", src, dst, symmetrize=True)
    cfg = GraphGenConfig(walk_len=4, window=2, num_neg=3, batch_walks=8)
    gen = GraphDataGenerator(t, "e", cfg)
    batches = list(gen.batches(epochs=1))
    assert len(batches) == 3  # ceil(20/8)
    b = batches[0]
    num_pairs = 8 * 5 * 4  # batch_walks * (walk_len+1) * 2*window
    assert b["centers"].shape == (num_pairs,)
    assert b["negatives"].shape == (num_pairs, 3)
    assert b["mask"].dtype == jnp.bool_
    # masked-in pairs are real edges-or-near pairs within the ring
    c = np.asarray(b["centers"])[np.asarray(b["mask"])]
    x = np.asarray(b["contexts"])[np.asarray(b["mask"])]
    d = np.minimum((c - x) % 20, (x - c) % 20)
    assert (d <= cfg.window).all() and (d > 0).all()


def test_deepwalk_smoke_train():
    """Tiny deepwalk: two ring communities bridged by one edge; after a
    few epochs, intra-community similarity > inter-community."""
    rng = np.random.default_rng(0)
    s1, d1 = ring_edges(8)
    s2, d2 = ring_edges(8)
    src = np.concatenate([s1, s2 + 8, [0]])
    dst = np.concatenate([d1, d2 + 8, [8]])
    t = GraphTable()
    t.add_edges("e", src, dst, symmetrize=True)
    gen = GraphDataGenerator(
        t, "e", GraphGenConfig(walk_len=6, window=2, num_neg=2,
                               batch_walks=16))
    emb = jnp.asarray(rng.normal(0, 0.1, (16, 8)), jnp.float32)

    @jax.jit
    def step(emb, c, x, negs, mask):
        def loss_fn(emb):
            pos = jnp.sum(emb[c] * emb[x], -1)
            neg = jnp.einsum("pd,pnd->pn", emb[c], emb[negs])
            l_pos = jax.nn.softplus(-pos)
            l_neg = jax.nn.softplus(neg).sum(-1)
            return jnp.sum((l_pos + l_neg) * mask) / jnp.maximum(
                mask.sum(), 1)
        g = jax.grad(loss_fn)(emb)
        return emb - 0.5 * g

    for batch in gen.batches(epochs=120):
        emb = step(emb, batch["centers"], batch["contexts"],
                   batch["negatives"], batch["mask"])
    e = np.asarray(emb)
    e = e / np.linalg.norm(e, axis=1, keepdims=True)
    sims = e @ e.T
    intra = (sims[:8, :8].sum() - 8) / (8 * 7)
    inter = sims[:8, 8:].mean()
    assert intra > inter + 0.1


def test_metapath_walk_alternates_edge_types():
    """Bipartite u2i/i2u metapath: hop parity must land on the right
    side of the graph every time (users 0-3, items 4-7)."""
    users = np.arange(4)
    items = np.arange(4, 8)
    rng = np.random.default_rng(0)
    # every user connects to 2 items; every item back to 2 users
    u2i_src = np.repeat(users, 2)
    u2i_dst = rng.choice(items, 8)
    i2u_src = np.repeat(items, 2)
    i2u_dst = rng.choice(users, 8)
    table = GraphTable()
    table.add_edges("u2i", u2i_src, u2i_dst, num_nodes=8)
    table.add_edges("i2u", i2u_src, i2u_dst, num_nodes=8)
    views = [table.device_graph("u2i"), table.device_graph("i2u")]
    nbrs, deg = sampler.stack_device_graphs(views)
    walks = sampler.metapath_walk(
        nbrs, deg, jnp.asarray(users, jnp.int32),
        jax.random.PRNGKey(0), (0, 1, 0, 1))
    w = np.asarray(walks)
    assert w.shape == (4, 5)
    # hops 1,3 are items; hops 0,2,4 are users
    assert np.all(w[:, [1, 3]] >= 4)
    assert np.all(w[:, [0, 2, 4]] < 4)


def test_metapath_dead_end_stays_in_place():
    table = GraphTable()
    table.add_edges("a", np.array([0]), np.array([1]), num_nodes=3)
    table.add_edges("b", np.array([2]), np.array([0]), num_nodes=3)
    nbrs, deg = sampler.stack_device_graphs(
        [table.device_graph("a"), table.device_graph("b")])
    # node 1 has no 'b' edges: the b-hop must self-loop
    walks = sampler.metapath_walk(
        nbrs, deg, jnp.asarray([0], jnp.int32),
        jax.random.PRNGKey(1), (0, 1))
    w = np.asarray(walks)[0]
    assert w[1] == 1 and w[2] == 1


def test_degree_negative_sampling_tracks_degree():
    deg = np.array([0, 1, 1, 1, 100], np.int64)
    cdf = sampler.degree_neg_cdf(deg)
    negs = np.asarray(sampler.negative_samples_by_degree(
        jax.random.PRNGKey(0), cdf, 4096, 4)).ravel()
    counts = np.bincount(negs, minlength=5)
    # hub node ~ deg^0.75 weight: drawn far more often than unit nodes
    assert counts[4] > 5 * counts[1]
    assert counts.sum() == 4096 * 4
    assert (counts[:4] > 0).all()  # isolated node stays reachable


def test_node_types_and_typed_starts(tmp_path):
    table = GraphTable()
    p = tmp_path / "nodes.txt"
    p.write_text("user 0\nuser 1\nitem 2\nitem 3\n")
    table.load_node_file(str(p), {"user": 0, "item": 1}, num_nodes=5)
    np.testing.assert_array_equal(table.nodes_of_type(0), [0, 1])
    np.testing.assert_array_equal(table.nodes_of_type(1), [2, 3])
    np.testing.assert_array_equal(table.nodes_of_type(-1), [4])


def test_generator_metapath_feats_and_degree_negs():
    users = np.arange(6)
    items = np.arange(6, 12)
    rng = np.random.default_rng(3)
    table = GraphTable()
    table.add_edges("u2i", np.repeat(users, 2), rng.choice(items, 12),
                    num_nodes=12)
    table.add_edges("i2u", np.repeat(items, 2), rng.choice(users, 12),
                    num_nodes=12)
    feats = rng.normal(size=(12, 5)).astype(np.float32)
    table.set_node_feat("x", feats)
    gen = GraphDataGenerator(
        table, "u2i",
        GraphGenConfig(walk_len=4, window=2, num_neg=3, batch_walks=8,
                       metapath=("u2i", "i2u"), degree_negatives=True,
                       feat_name="x"))
    batch = next(iter(gen.batches()))
    assert batch["center_feats"].shape == (batch["centers"].shape[0], 5)
    np.testing.assert_allclose(
        np.asarray(batch["center_feats"]),
        feats[np.asarray(batch["centers"])])
    assert np.asarray(batch["negatives"]).max() < 12


def test_generator_typed_starts():
    """start_type restricts the walk start pool to the typed frontier
    (metapath semantics: a u2i...-path starts from user nodes) —
    asserted BEHAVIORALLY through emitted batches: with walk_len=1 over
    "u2i", a user start yields exactly two unmasked (user<->item) pairs
    per walk, while an item start dead-ends into fully-masked
    self-pairs, so any item leaking into the start pool shows up as a
    short or type-violating batch."""
    users = np.arange(4)
    items = np.arange(4, 8)
    rng = np.random.default_rng(1)
    table = GraphTable()
    table.add_edges("u2i", np.repeat(users, 2), rng.choice(items, 8),
                    num_nodes=8)
    table.add_edges("i2u", np.repeat(items, 2), rng.choice(users, 8),
                    num_nodes=8)
    table.set_node_types(np.array([0, 0, 0, 0, 1, 1, 1, 1], np.int32))
    gen = GraphDataGenerator(
        table, "u2i",
        GraphGenConfig(walk_len=1, window=1, batch_walks=4,
                       metapath=("u2i",), start_type=0))
    for batch in gen.batches(epochs=2):
        mask = np.asarray(batch["mask"])
        c = np.asarray(batch["centers"])[mask]
        x = np.asarray(batch["contexts"])[mask]
        # every walk contributes its 2 cross pairs — nothing masked away
        # by dead-end item starts
        assert mask.sum() == 2 * 4, mask.sum()
        assert np.all((c < 4) != (x < 4)), (c, x)  # user<->item only
    with pytest.raises(ValueError):
        GraphDataGenerator(table, "u2i",
                           GraphGenConfig(metapath=("u2i",), start_type=7))
    # Typed pool larger than the walk graph: loud failure, not a
    # silently clamped gather.
    table.set_node_types(np.array([0] * 4 + [1] * 4 + [0], np.int32))
    with pytest.raises(ValueError):
        GraphDataGenerator(table, "u2i",
                           GraphGenConfig(metapath=("u2i",), start_type=0))
