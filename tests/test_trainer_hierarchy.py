"""Trainer hierarchy, async dense table, and sanitizer tests."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.core import flags
from paddlebox_tpu.parallel import HybridTopology, build_mesh, pp
from paddlebox_tpu.train.async_dense import AsyncDenseTable
from paddlebox_tpu.train.trainer import (MultiTrainer, PipelineTrainer,
                                         TrainerDesc, create_trainer,
                                         register_trainer)
from paddlebox_tpu.utils import sanitizer


# ---------------------------------------------------------------------------
# MultiTrainer
# ---------------------------------------------------------------------------

def _linreg_batches(n_batches, bs=32, seed=0):
    rng = np.random.default_rng(seed)
    w = np.asarray([2.0, -1.0, 0.5, 3.0], np.float32)
    for _ in range(n_batches):
        x = rng.normal(size=(bs, 4)).astype(np.float32)
        yield {"x": x, "y": x @ w + 0.01 * rng.normal(size=bs).astype(
            np.float32)}


def test_multi_trainer_learns(devices8):
    mesh = build_mesh(HybridTopology(dp=8))

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    t = MultiTrainer(loss_fn, {"w": jnp.zeros(4), "b": jnp.zeros(())},
                     optax.sgd(0.1))
    out = t.fit(_linreg_batches(200), TrainerDesc(log_every=0), mesh)
    assert out["steps"] == 200
    assert out["loss_last"] < 0.01 < out["loss_first"]
    np.testing.assert_allclose(np.asarray(t.params["w"]),
                               [2, -1, 0.5, 3], atol=0.05)


def test_trainer_factory_registry():
    t = create_trainer("MultiTrainer",
                       lambda p, b: jnp.sum(p["w"] ** 2),
                       {"w": jnp.ones(2)}, optax.sgd(0.1))
    assert isinstance(t, MultiTrainer)
    with pytest.raises(KeyError):
        create_trainer("NoSuchTrainer")


def test_multi_trainer_max_steps_and_nan_check(devices8):
    mesh = build_mesh(HybridTopology(dp=8))

    def bad_loss(params, batch):
        # divergence by design: loss explodes to inf/nan quickly
        return jnp.exp(jnp.sum(params["w"] * 1e4)) * jnp.mean(batch["x"])

    t = MultiTrainer(bad_loss, {"w": jnp.ones(4)}, optax.sgd(1e6))
    with pytest.raises(FloatingPointError):
        t.fit(_linreg_batches(50),
              TrainerDesc(check_nan_inf=True, log_every=0), mesh)


# ---------------------------------------------------------------------------
# HeterTrainer
# ---------------------------------------------------------------------------

def test_heter_trainer_learns_with_host_stage(devices8):
    """Host normalization stage + device step pipelined through the
    interceptor runtime; parity with the plain trainer's convergence."""
    from paddlebox_tpu.train.trainer import HeterTrainer
    mesh = build_mesh(HybridTopology(dp=8))
    host_calls = []

    def host_fn(batch):
        # Fixed host-side transform (a per-batch normalization would make
        # the regression target batch-dependent and unlearnable).
        host_calls.append(1)
        return {"x": batch["x"] * 2.0, "y": batch["y"]}

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] + params["b"]
                         - batch["y"]) ** 2)

    t = HeterTrainer(loss_fn, {"w": jnp.zeros(4), "b": jnp.zeros(())},
                     optax.adam(0.05), host_fn=host_fn)
    out = t.fit(list(_linreg_batches(150)), TrainerDesc(log_every=0), mesh)
    assert out["steps"] == 150
    assert len(host_calls) == 150
    assert out["loss_last"] < 0.05 < out["loss_first"]


def test_heter_trainer_short_dataset_under_max_steps(devices8):
    """max_steps beyond the dataset must end cleanly at the data's end,
    not hang waiting for batches that never come."""
    from paddlebox_tpu.train.trainer import HeterTrainer
    mesh = build_mesh(HybridTopology(dp=8))
    t = HeterTrainer(
        lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2),
        {"w": jnp.zeros(4)}, optax.sgd(0.1), chunk_size=8)
    out = t.fit(_linreg_batches(10), TrainerDesc(max_steps=50, log_every=0),
                mesh)
    assert out["steps"] == 10


def test_heter_trainer_factory():
    from paddlebox_tpu.train.trainer import HeterTrainer
    t = create_trainer("HeterTrainer", lambda p, b: jnp.sum(p["w"] ** 2),
                       {"w": jnp.ones(2)}, optax.sgd(0.1))
    assert isinstance(t, HeterTrainer)


# ---------------------------------------------------------------------------
# PipelineTrainer
# ---------------------------------------------------------------------------

def test_pipeline_trainer_learns(devices8):
    mesh = build_mesh(HybridTopology(pp=8))
    rng = np.random.default_rng(0)
    dim = 8
    stage_params = [
        {"w": jnp.asarray(rng.normal(0, 0.5, (dim, dim)), jnp.float32)}
        for _ in range(8)]
    stacked = pp.stack_stage_params(stage_params)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def loss_head(y, batch):
        return jnp.mean((jnp.sum(y, -1) - batch["y"]) ** 2)

    t = PipelineTrainer(stage_fn, stacked, loss_head, optax.adam(3e-3))
    desc = TrainerDesc(num_micro_batches=8, log_every=0)

    def batches(n):
        r = np.random.default_rng(1)
        for _ in range(n):
            x = r.normal(size=(32, dim)).astype(np.float32)
            yield {"x": x, "y": np.tanh(x.sum(1)).astype(np.float32)}

    out = t.fit(batches(150), desc, mesh)
    assert out["loss_last"] < out["loss_first"] * 0.5


# ---------------------------------------------------------------------------
# AsyncDenseTable
# ---------------------------------------------------------------------------

def test_async_dense_applies_adam():
    params = {"w": np.ones((4,), np.float32)}
    table = AsyncDenseTable(params, learning_rate=0.1)
    for _ in range(10):
        table.push_dense({"w": np.ones((4,), np.float32)})
    table.flush()
    out = table.pull_dense()
    # positive grads -> params decreased
    assert (np.asarray(out["w"]) < 1.0).all()
    assert table.steps_applied >= 1
    table.stop()


def test_async_dense_converges_quadratic():
    """pull/push loop minimizes ||w - target||^2 through the async path."""
    target = np.asarray([1.0, -2.0, 0.5], np.float32)
    table = AsyncDenseTable({"w": np.zeros(3, np.float32)},
                            learning_rate=0.05, beta1=0.9, beta2=0.999)
    for _ in range(300):
        w = np.asarray(table.pull_dense()["w"])
        table.push_dense({"w": 2 * (w - target)})
        table.flush()
    w = np.asarray(table.pull_dense()["w"])
    np.testing.assert_allclose(w, target, atol=0.1)
    table.stop()


def test_async_dense_ring_drops_oldest_not_blocks():
    table = AsyncDenseTable({"w": np.zeros(2, np.float32)}, ring_capacity=2)
    # push far more than capacity quickly: must not block
    for i in range(100):
        table.push_dense({"w": np.full(2, float(i), np.float32)})
    table.stop()


def test_async_dense_shape_mismatch_raises():
    table = AsyncDenseTable({"w": np.zeros(2, np.float32)})
    with pytest.raises(ValueError):
        table.push_dense({"w": np.zeros(2), "extra": np.zeros(1)})
    # same leaf count, different structure -> refuse (would cross-apply)
    table2 = AsyncDenseTable({"a": np.zeros(2, np.float32),
                              "b": np.zeros(2, np.float32)})
    with pytest.raises(ValueError):
        table2.push_dense([np.zeros(2, np.float32),
                           np.zeros(2, np.float32)])
    # same structure, wrong leaf shape -> refuse
    with pytest.raises(ValueError):
        table2.push_dense({"a": np.zeros(3, np.float32),
                           "b": np.zeros(2, np.float32)})
    table.stop()
    table2.stop()


def test_dump_path_requires_eval_fn(devices8, tmp_path):
    t = MultiTrainer(lambda p, b: jnp.sum(p["w"] ** 2), {"w": jnp.ones(2)},
                     optax.sgd(0.1))
    with pytest.raises(ValueError):
        t.fit(iter([]), TrainerDesc(dump_path=str(tmp_path / "d.txt")))


def test_dump_path_writes_predictions(devices8, tmp_path):
    mesh = build_mesh(HybridTopology(dp=8))

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    def eval_fn(params, batch):
        return batch["x"] @ params["w"], batch["y"]

    path = str(tmp_path / "preds.txt")
    t = MultiTrainer(loss_fn, {"w": jnp.zeros(4)}, optax.sgd(0.05),
                     eval_fn=eval_fn)
    t.fit(_linreg_batches(3), TrainerDesc(dump_path=path, log_every=0),
          mesh)
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 3 * 32  # one line per instance


def test_pipeline_trainer_rejects_indivisible_batch(devices8):
    mesh = build_mesh(HybridTopology(pp=8))
    stacked = pp.stack_stage_params(
        [{"w": jnp.eye(4)} for _ in range(8)])
    t = PipelineTrainer(lambda p, x: x @ p["w"], stacked,
                        lambda y, b: jnp.mean(y ** 2), optax.sgd(0.1))
    desc = TrainerDesc(num_micro_batches=8, log_every=0)
    with pytest.raises(ValueError):
        t.fit(iter([{"x": np.ones((30, 4), np.float32)}]), desc, mesh)


# ---------------------------------------------------------------------------
# Sanitizer
# ---------------------------------------------------------------------------

def test_sanitizer_all_finite_and_report():
    clean = {"a": jnp.ones(3), "b": {"c": jnp.zeros((2, 2))}}
    assert bool(sanitizer.all_finite(clean))
    dirty = {"a": jnp.asarray([1.0, jnp.nan]),
             "b": {"c": jnp.asarray([jnp.inf, 1.0])}}
    assert not bool(sanitizer.all_finite(dirty))
    report = sanitizer.find_nonfinite(dirty)
    assert {k for _, k, _ in report} == {"nan", "inf"}
    assert all(count == 1 for _, _, count in report)
    assert any("a" in name for name, k, _ in report if k == "nan")
    assert any("c" in name for name, k, _ in report if k == "inf")


def test_sanitizer_check_batch_flag_gated():
    dirty = {"a": jnp.asarray([jnp.nan])}
    flags.set_flags({"check_nan_inf": False})
    assert sanitizer.check_batch(dirty) is True  # disabled -> no-op
    flags.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            sanitizer.check_batch(dirty, step=7)
        assert sanitizer.check_batch({"a": jnp.ones(2)}) is True
    finally:
        flags.set_flags({"check_nan_inf": False})


def test_sanitizer_ignores_integer_leaves():
    tree = {"ids": jnp.arange(5), "x": jnp.ones(2)}
    assert bool(sanitizer.all_finite(tree))
