"""Multi-tier CTR integration: the full PSGPUWrapper-style flow — pass
build pulls values from a backing tier (remote PS cluster / RAM+SSD
tiered store), the hot pass trains in device HBM, EndPass writes back.
Verifies learning continuity across passes through each tier."""

import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.distributed.ps import PSBackedStore, start_local_cluster
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
from paddlebox_tpu.models import WideDeep
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item")


def _shard(path, n, seed, num_feats=150):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, num_feats, rng.integers(1, 3))
                     for s in SLOTS}
            clickiness = np.mean([(int(v) % 5 == 0)
                                  for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * clickiness)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    d = tmp_path_factory.mktemp("tiered")
    return [_shard(d / f"p{i}", 384, seed=i) for i in range(2)]


def _train(store, shards, passes=3):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=64)
    table = TableConfig(name="emb", dim=8, learning_rate=0.1)
    model = WideDeep(slot_names=SLOTS, emb_dim=8, hidden=(32, 16))
    trainer = CTRTrainer(model, feed, table, mesh=mesh,
                         config=TrainerConfig(dense_learning_rate=3e-3,
                                              auc_num_buckets=1 << 12),
                         store=store)
    trainer.init(seed=0)
    ds = Dataset(feed, num_reader_threads=2)
    ds.set_filelist(shards)
    ds.load_into_memory()
    stats = []
    for p in range(passes):
        trainer.reset_metrics()
        ds.local_shuffle(seed=p)
        stats.append(trainer.train_pass(ds))
    return trainer, stats


def test_ctr_over_remote_ps(shards):
    """BuildPull from a 3-shard PS cluster; EndPass writes back; learning
    carries across passes through the remote tier."""
    cfg = TableConfig(name="emb", dim=8, learning_rate=0.1)
    servers, client = start_local_cluster(3, {"emb": cfg})
    try:
        store = PSBackedStore(client, "emb")
        trainer, stats = _train(store, shards)
        assert stats[-1]["auc"] > stats[0]["auc"]
        assert stats[-1]["auc"] > 0.6
        # values persisted on the PS shards, not just device HBM
        assert store.num_features > 100
        # show counters accumulated server-side through EndPass write-back
        keys = np.asarray([k for k in range(1, 150)], np.uint64)
        rows = client.pull_pass("emb", keys)
        assert rows["show"].sum() > 0
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.stop()


def test_ctr_over_tiered_store(shards, tmp_path):
    """RAM budget far below the feature count: every pass stages cold
    rows in from disk and evicts after write-back, and the model still
    learns (LoadSSD2Mem/CheckNeedLimitMem flow)."""
    cfg = TableConfig(name="emb", dim=8, learning_rate=0.1)
    store = TieredFeatureStore(cfg, str(tmp_path / "ssd"),
                               max_ram_features=64)
    trainer, stats = _train(store, shards)
    assert stats[-1]["auc"] > stats[0]["auc"]
    assert stats[-1]["auc"] > 0.6
    assert store.ram.num_features <= 64
    assert store.disk.num_features > 0
    # base+delta checkpoint through the tiered store still works
    store.save_base(str(tmp_path / "base"))
    fresh = TieredFeatureStore(cfg, str(tmp_path / "ssd2"))
    fresh.load(str(tmp_path / "base"))
    assert fresh.num_features == store.num_features
