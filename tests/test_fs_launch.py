"""Filesystem abstraction + elastic launch tests. HadoopFS is exercised
against a fake `hadoop` CLI shim (the reference's hdfs paths shell out the
same way, fs.cc:224), so no real cluster is needed — mirroring the
reference's localhost-fake-cluster test philosophy."""

import os
import stat
import sys
import textwrap

import pytest

from paddlebox_tpu.utils.fs import HadoopFS, LocalFS, fs_for

FAKE_HADOOP = textwrap.dedent("""\
    #!/bin/sh
    # fake 'hadoop' CLI: maps 'fs -<op> args...' onto a local root dir
    ROOT="$FAKE_HDFS_ROOT"
    shift  # drop 'fs'
    op="$1"; shift
    strip() { echo "$1" | sed 's|hdfs://fake||'; }
    case "$op" in
      -test) [ -e "$ROOT$(strip "$2")" ] ;;
      -mkdir) shift; mkdir -p "$ROOT$(strip "$1")" ;;
      -cat) cat "$ROOT$(strip "$1")" ;;
      -put)
        force="$1"; [ "$force" = "-f" ] && shift
        src="$1"; dst="$ROOT$(strip "$2")"
        mkdir -p "$(dirname "$dst")"
        if [ "$src" = "-" ]; then cat > "$dst"; else cp "$src" "$dst"; fi ;;
      -get) cp "$ROOT$(strip "$1")" "$2" ;;
      -rm) shift; shift; rm -rf "$ROOT$(strip "$1")" ;;
      -mv) mv "$ROOT$(strip "$1")" "$ROOT$(strip "$2")" ;;
      -ls)
        d="$ROOT$(strip "$1")"
        [ -d "$d" ] || { echo "ls: no such file: $1" >&2; exit 1; }
        for f in "$d"/*; do
          [ -e "$f" ] || continue
          echo "-rw-r--r-- 1 u g 0 2026-01-01 00:00 hdfs://fake${f#$ROOT}"
        done ;;
      *) echo "unknown op $op" >&2; exit 1 ;;
    esac
    """)


@pytest.fixture
def fake_hdfs(tmp_path, monkeypatch):
    shim = tmp_path / "hadoop"
    shim.write_text(FAKE_HADOOP)
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    root = tmp_path / "hdfs_root"
    root.mkdir()
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
    return root


def test_local_fs_roundtrip(tmp_path):
    fs = LocalFS()
    p = str(tmp_path / "a" / "b.txt")
    with fs.open_write(p) as f:
        f.write(b"hello")
    assert fs.exists(p)
    with fs.open_read(p) as f:
        assert f.read() == b"hello"
    fs.rename(p, str(tmp_path / "a" / "c.txt"))
    assert not fs.exists(p)
    assert [os.path.basename(x) for x in fs.ls(str(tmp_path / "a"))] \
        == ["c.txt"]
    fs.remove(str(tmp_path / "a"))
    assert not fs.exists(str(tmp_path / "a"))


def test_fs_for_scheme_routing():
    assert isinstance(fs_for("/tmp/x"), LocalFS)
    assert isinstance(fs_for("hdfs://ns1/user/x"), HadoopFS)
    assert isinstance(fs_for("afs://cluster/x"), HadoopFS)


def test_hadoop_fs_against_shim(fake_hdfs, tmp_path):
    fs = HadoopFS()
    base = "hdfs://fake/warehouse"
    fs.mkdir(base)
    assert fs.exists(base)
    # streaming write -> read roundtrip via pipes; close() is durable so
    # the file exists as soon as the with-block exits
    with fs.open_write(f"{base}/part-0") as f:
        f.write(b"line1\nline2\n")
    assert fs.exists(f"{base}/part-0")
    with fs.open_read(f"{base}/part-0") as f:
        assert f.read() == b"line1\nline2\n"
    # reading a missing path raises at close, not an empty stream
    with pytest.raises(IOError):
        s = fs.open_read(f"{base}/nonexistent")
        s.read()
        s.close()
    # put/get files
    local = tmp_path / "up.txt"
    local.write_text("payload")
    fs.put(str(local), f"{base}/up.txt")
    fs.get(f"{base}/up.txt", str(tmp_path / "down.txt"))
    assert (tmp_path / "down.txt").read_text() == "payload"
    # ls / mv / rm
    names = [p.rsplit("/", 1)[-1] for p in fs.ls(base)]
    assert set(names) == {"part-0", "up.txt"}
    fs.rename(f"{base}/up.txt", f"{base}/moved.txt")
    assert fs.exists(f"{base}/moved.txt")
    # a deliberate partial read must NOT raise (SIGPIPE on the CLI)
    with fs.open_write(f"{base}/big") as f:
        f.write(b"x" * (1 << 20))
    with fs.open_read(f"{base}/big") as f:
        assert f.read(10) == b"x" * 10
    fs.remove(base)
    assert not fs.exists(base)


def test_hadoop_fs_error_surfaces(fake_hdfs):
    fs = HadoopFS()
    with pytest.raises(IOError):
        fs.ls("hdfs://fake/definitely/missing/dir/x")


# ---------------------------------------------------------------------------
# elastic launch
# ---------------------------------------------------------------------------

def test_launch_elastic_single_host(tmp_path):
    """Elastic mode end-to-end on one host: ranks come from the lease
    table; the worker script records its env and exits."""
    from paddlebox_tpu.launch.main import main
    script = tmp_path / "worker.py"
    out = tmp_path / "out"
    out.mkdir()
    script.write_text(textwrap.dedent(f"""\
        import os
        rank = os.environ["PBX_PROCESS_ID"]
        with open(r"{out}" + "/r" + rank, "w") as f:
            f.write(os.environ["PBX_NUM_PROCESSES"] + ":" +
                    os.environ["PBX_ELASTIC_GENERATION"])
        """))
    rc = main(["--elastic-dir", str(tmp_path / "es"), "--host-id", "h0",
               "--nproc", "2", "--min-hosts", "1",
               "--elastic-timeout", "30", str(script)])
    assert rc == 0
    assert sorted(os.listdir(out)) == ["r0", "r1"]
    assert (out / "r0").read_text().startswith("2:")
