"""DayRunner tests: the production day/pass loop — per-pass deltas,
day-end shrink+base, done-file publication, and crash recovery
continuing training with preserved state."""

import os

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.day_runner import DayRunner

SLOTS = ("user", "item")


def _write_day(root, day, hours, rows_per_split=96, seed0=0):
    rng = np.random.default_rng(seed0 + int(day))
    for h in hours:
        d = os.path.join(root, day, f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-00000"), "w") as f:
            for _ in range(rows_per_split):
                feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                         for s in SLOTS}
                click = np.mean([(int(v) % 5 == 0)
                                 for vs in feats.values() for v in vs])
                label = int(rng.random() < 0.1 + 0.8 * click)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")


def _make_runner(data_root, out_root):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10))
    trainer.init(seed=0)
    return DayRunner(trainer, feed, out_root, data_root=data_root,
                     split_interval=60, split_per_pass=1,
                     hours=[0, 1, 2], num_reader_threads=2)


def test_day_loop_publishes_deltas_and_base(tmp_path):
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0, 1, 2])
    runner = _make_runner(data, out)
    stats = runner.train_day("20260728")
    assert len(stats) == 3  # one pass per hour
    recs = runner.ckpt.records()
    # 3 deltas (pass 1..3) + 1 day base (pass 0)
    assert [(r.day, r.pass_id) for r in recs] == \
        [("20260728", 1), ("20260728", 2), ("20260728", 3),
         ("20260728", 0)]
    assert os.path.exists(os.path.join(out, "20260728", "0",
                                       "emb.base.npz"))
    assert os.path.exists(os.path.join(out, "20260728", "2",
                                       "emb.delta.npz"))


def test_missing_splits_skipped(tmp_path):
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0, 2])  # hour 1 missing
    runner = _make_runner(data, out)
    stats = runner.train_day("20260728")
    assert len(stats) == 2


def test_xbox_serving_export(tmp_path):
    """save_xbox writes the serving payload (emb+w only) per pass and
    publishes to the separate xbox done-file."""
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0])
    runner = _make_runner(data, out)
    runner.save_xbox = True
    runner.train_day("20260728")
    xrecs = runner.ckpt.xbox_records()
    assert [(r.day, r.pass_id) for r in xrecs] == [("20260728", 1)]
    x = np.load(os.path.join(out, "20260728", "1", "emb.xbox.npz"))
    assert set(x.files) == {"keys", "emb", "w"}  # no optimizer state
    assert x["emb"].shape[1] == 8
    # training donefile unaffected by xbox publications
    recs = runner.ckpt.records()
    assert [(r.day, r.pass_id) for r in recs] == \
        [("20260728", 1), ("20260728", 0)]


def test_empty_day_publishes_nothing(tmp_path):
    """A day with no data must not shrink the model or publish a base
    (late-arriving data keeps the day trainable)."""
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    os.makedirs(data, exist_ok=True)
    runner = _make_runner(data, out)
    stats = runner.train_day("20260728")
    assert stats == []
    assert runner.ckpt.records() == []


def test_recovery_resumes_with_state(tmp_path):
    """Crash after day 1: a fresh runner recovers base+deltas and its
    store matches the original's feature count; finished days are
    skipped by run_days."""
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0, 1, 2])
    _write_day(data, "20260729", [0, 1, 2])
    r1 = _make_runner(data, out)
    r1.train_day("20260728")
    n_features = r1.trainer.engine.store.num_features
    assert n_features > 0

    # 'crash': new process = new runner; recover from donefile
    r2 = _make_runner(data, out)
    point = r2.recover()
    assert point == {"day": "20260728", "pass_id": 0}
    assert r2.trainer.engine.store.num_features == n_features
    out2 = r2.run_days(["20260728", "20260729"])
    assert list(out2) == ["20260729"]  # finished day skipped
    # day 2 published its own base
    base, deltas = r2.ckpt.recovery_chain()
    assert base.day == "20260729"


def test_recovery_applies_deltas_after_base(tmp_path):
    """Deltas published after the base must be part of recovery: train
    day1 (base), then one pass of day2 (delta only), crash, recover —
    the delta's updates survive and its pass is NOT re-trained."""
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0])
    _write_day(data, "20260729", [0])
    runner = _make_runner(data, out)
    runner.train_day("20260728")
    files = runner._default_filelist("20260729", ["00"])
    runner.train_pass("20260729", 1, files)  # delta beyond the base
    store1 = runner.trainer.engine.store
    n = store1.num_features
    show_total = float(store1.pull_for_pass(
        np.sort(store1.dirty_keys()))["show"].sum()) \
        if store1.dirty_keys().size else 0.0

    r2 = _make_runner(data, out)
    point = r2.recover()
    assert point == {"day": "20260729", "pass_id": 1}
    assert r2.trainer.engine.store.num_features == n
    # run_days must resume AFTER the recovered delta pass: day2 only has
    # hour 0 (= pass 1), so nothing re-trains; but day-end STILL runs
    # (shrink + base) because the day's passes are complete in the store.
    out2 = r2.run_days(["20260728", "20260729"])
    assert out2 == {"20260729": []}
    base, _ = r2.ckpt.recovery_chain()
    assert base.day == "20260729"  # day 2 got its base after resume
    store2 = r2.trainer.engine.store
    keys = np.sort(store1.dirty_keys())
    if keys.size:
        # show counts = originals * one day-end decay — NOT doubled
        # (re-training pass 1 would double-apply show/click/state)
        show2 = float(store2.pull_for_pass(keys)["show"].sum())
        assert show2 == pytest.approx(show_total * 0.98)


def test_recovery_restores_dense_state(tmp_path):
    """The recovered model must be CONSISTENT: sparse table AND dense
    towers (params + optimizer state) from the same checkpoint — a
    table-only recovery would pair trained embeddings with freshly
    initialized dense weights."""
    import jax

    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0, 1])
    r1 = _make_runner(data, out)
    r1.train_day("20260728")
    trained = jax.tree.map(lambda x: np.asarray(x).copy(),
                           r1.trainer.params)

    r2 = _make_runner(data, out)  # fresh init (different weights)
    fresh_leaf = np.asarray(jax.tree.leaves(r2.trainer.params)[0]).copy()
    r2.recover()
    for a, b in zip(jax.tree.leaves(r2.trainer.params),
                    jax.tree.leaves(trained)):
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-7)
    # And it genuinely changed something (the fresh init differed).
    restored_leaf = np.asarray(jax.tree.leaves(r2.trainer.params)[0])
    assert not np.allclose(restored_leaf, fresh_leaf) or \
        np.allclose(fresh_leaf, jax.tree.leaves(trained)[0])
    # Optimizer state restored too (adam moments non-zero post-recovery).
    moments = [np.abs(np.asarray(x)).sum()
               for x in jax.tree.leaves(r2.trainer.opt_state)
               if hasattr(x, "shape") and np.asarray(x).size > 1]
    assert any(m > 0 for m in moments)
