"""TRUE multi-process distributed test (VERDICT r02 task 4).

Spawns 2 REAL OS processes via the production launcher
(``python -m paddlebox_tpu.launch``), each owning one virtual CPU device,
joined through ``bootstrap.initialize`` (jax.distributed with a real
coordinator service and a real localhost socket between the processes),
trains the tiny CTR config, and asserts loss parity against the
single-process 2-virtual-device run of the exact same data — the
reference's _run_cluster mechanism (``test_dist_base.py:1041``).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_ctr_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_data(data_dir: str) -> None:
    rng = np.random.default_rng(7)
    os.makedirs(data_dir, exist_ok=True)
    for b in range(3):
        lines = []
        for _ in range(64):
            ids = rng.integers(1, 200, 3)
            feats = " ".join(f"s{j}:{ids[j]}" for j in range(3))
            lines.append(f"{rng.integers(0, 2)} {feats}")
        with open(os.path.join(data_dir, f"part-{b}"), "w") as f:
            f.write("\n".join(lines) + "\n")


def _single_process_reference(data_dir: str) -> list:
    """Same worker payload, run in ONE subprocess with 2 virtual devices
    (no jax.distributed) — the parity baseline."""
    out = os.path.join(data_dir, "ref.json")
    env = dict(os.environ)
    env.pop("PBX_COORDINATOR", None)
    env["PBX_NUM_PROCESSES"] = "1"
    env["PBX_PROCESS_ID"] = "0"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run([sys.executable, WORKER, data_dir, out], env=env,
                   cwd=REPO, check=True, timeout=420)
    with open(out) as f:
        return json.load(f)["losses"]


@pytest.mark.slow
def test_two_process_multislice_ctr_parity(tmp_path):
    """The slice (DCN) axis on a REAL process boundary (VERDICT-r04 #3):
    2 jax.distributed processes x 4 CPU devices, mesh slice=2 x dp=4.
    Inside the run the worker asserts the mesh puts each slice on one
    process and that hierarchical_psum_tree equals the flat psum across
    the boundary; here we assert the training trajectory matches the
    identical single-process 8-device slice=2 x dp=4 run — the hierarchy
    changes the transport, not the math (gather_multi_node_grad role,
    heter_comm.h:156-172)."""
    worker = os.path.join(REPO, "tests", "mp_slice_worker.py")
    data_dir = str(tmp_path / "data")
    _write_data(data_dir)
    out = str(tmp_path / "mp_slice.json")
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # worker pins its own 4-device flag
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddlebox_tpu.launch", "--nproc", "2",
         "--coordinator", f"127.0.0.1:{port}", worker, data_dir, out],
        env=env, cwd=REPO, timeout=420, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"launcher failed rc={proc.returncode}\n--- stdout\n"
        f"{proc.stdout[-3000:]}\n--- stderr\n{proc.stderr[-3000:]}")
    with open(out) as f:
        mp = json.load(f)
    assert mp["nproc"] == 2 and mp["ndev"] == 8
    assert mp["slice_on_boundary"], (
        f"slice axis not on the process boundary: {mp['slice_procs']}")
    assert mp["hier_err"] < 1e-5, (
        f"hierarchical psum diverged across processes: {mp['hier_err']}")

    # Single-process reference: SAME worker, same mesh shape, 8 local
    # virtual devices, no jax.distributed.
    ref_out = os.path.join(data_dir, "ref_slice.json")
    env_ref = dict(env)
    env_ref.pop("PBX_COORDINATOR", None)
    env_ref["PBX_NUM_PROCESSES"] = "1"
    env_ref["PBX_PROCESS_ID"] = "0"
    env_ref["PBX_TEST_LOCAL_DEVICES"] = "8"
    subprocess.run([sys.executable, worker, data_dir, ref_out],
                   env=env_ref, cwd=REPO, check=True, timeout=420)
    with open(ref_out) as f:
        ref = json.load(f)
    np.testing.assert_allclose(mp["losses"], ref["losses"], rtol=1e-5,
                               err_msg="2-process slice run diverged from "
                                       "the single-process slice run")
    assert mp["losses"][1] < mp["losses"][0]


@pytest.mark.slow
def test_two_process_ctr_loss_parity(tmp_path):
    data_dir = str(tmp_path / "data")
    _write_data(data_dir)
    out = str(tmp_path / "mp.json")
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # worker pins its own 1-device flag
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "paddlebox_tpu.launch", "--nproc", "2",
         "--coordinator", f"127.0.0.1:{port}", WORKER, data_dir, out],
        env=env, cwd=REPO, timeout=420, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"launcher failed rc={proc.returncode}\n--- stdout\n"
        f"{proc.stdout[-3000:]}\n--- stderr\n{proc.stderr[-3000:]}")
    with open(out) as f:
        mp = json.load(f)
    assert mp["nproc"] == 2 and mp["ndev"] == 2
    ref = _single_process_reference(data_dir)
    np.testing.assert_allclose(mp["losses"], ref, rtol=1e-5,
                               err_msg="2-process run diverged from the "
                                       "single-process 2-device run")
    assert mp["losses"][1] < mp["losses"][0]
