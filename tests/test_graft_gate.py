"""The multichip dryrun's in-process gate (VERDICT r05 "Next round #1").

Three rounds of driver MULTICHIP captures wedged because the capture
process's env *claimed* cpu (JAX_PLATFORMS=cpu) while still carrying the
axon PJRT bootstrap (PALLAS_AXON_POOL_IPS): the sitecustomize registers
the plugin at interpreter startup, and the in-process ``jax.devices()``
then dials the dead tunnel forever. The gate predicate must therefore
require BOTH cpu pinning AND the pool var's absence — provably, as a
pure function of the env — and a poisoned env must route through the
scrubbed-subprocess path end to end.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import __graft_entry__ as graft  # noqa: E402

sys.path.remove(REPO)


def test_gate_requires_cpu_pin():
    assert graft.inprocess_dryrun_allowed({"JAX_PLATFORMS": "cpu"})
    assert graft.inprocess_dryrun_allowed({"JAX_PLATFORMS": "CPU"})
    assert not graft.inprocess_dryrun_allowed({})
    assert not graft.inprocess_dryrun_allowed({"JAX_PLATFORMS": "axon"})
    assert not graft.inprocess_dryrun_allowed({"JAX_PLATFORMS": "cpu,tpu"})


def test_gate_blocks_axon_bootstrap():
    """The r05 wedge env: claims cpu, carries the pool var. The gate
    must refuse in-process execution — the sitecustomize has already
    registered the plugin by the time any python code can react."""
    assert not graft.inprocess_dryrun_allowed(
        {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "10.0.0.1"})
    # Empty string = bootstrap disabled: in-process is safe.
    assert graft.inprocess_dryrun_allowed(
        {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})


def test_gate_reads_process_env(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    assert graft.inprocess_dryrun_allowed()
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    assert not graft.inprocess_dryrun_allowed()


@pytest.mark.slow
def test_dryrun_completes_with_poisoned_env(tmp_path):
    """End to end: JAX_PLATFORMS=cpu + PALLAS_AXON_POOL_IPS injected
    (the exact driver-capture env of MULTICHIP r03-r05) must complete
    via the scrubbed subprocess — two entry beacons (parent + child)
    prove the subprocess path ran, and the sub-dryruns all pass."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"   # dead by construction
    # Decouple the test from the production 180 s child budget: a loaded
    # CI box may exceed it; the path under test is gate routing, not the
    # budget value.
    env["_PBT_DRYRUN_TIMEOUT_S"] = "540"
    env.pop("_PBT_DRYRUN_CHILD", None)
    proc = subprocess.run(
        [sys.executable, "-u", "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    beacons = out.count("dryrun_multichip: entered (pid=")
    assert beacons >= 2, out[-4000:]   # parent AND scrubbed child
    assert "dryrun ctr(2): OK" in out, out[-4000:]
