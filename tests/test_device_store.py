"""DeviceFeatureStore: the HBM-resident persistent tier (device_store.py).

Parity contract: behaves exactly like the host FeatureStore for the same
operation sequence — same init values (shared deterministic per-key init),
same pull/push semantics, same base/delta checkpoint artifacts — while
keeping values on device between passes (role of the GPU-resident BoxPS
tables, README.md:48 / heter_ps hashtables in HBM).
"""

import numpy as np
import pytest

from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import extract_pass_values_host
from paddlebox_tpu.parallel import HybridTopology, build_mesh

CFG = TableConfig(dim=4, optimizer="adagrad", learning_rate=0.1)
FIELDS = ("emb", "emb_state", "w", "w_state", "show", "click")


def keys_of(n, seed=0, lo=1, hi=10_000):
    return np.sort(np.random.default_rng(seed).choice(
        np.arange(lo, hi, dtype=np.uint64), n, replace=False))


def assert_vals_equal(a, b, **kw):
    for f in FIELDS:
        np.testing.assert_allclose(a[f], b[f], err_msg=f, **kw)


@pytest.mark.parametrize("mesh_shards", [1, 8])
def test_pull_push_parity_with_host_store(mesh_shards):
    mesh = (build_mesh(HybridTopology(dp=8)) if mesh_shards == 8 else None)
    dev = DeviceFeatureStore(CFG, mesh=mesh)
    host = FeatureStore(CFG)
    k1 = keys_of(257, seed=1)
    # Fresh pull: init parity.
    v_dev = dev.pull_for_pass(k1)
    v_host = host.pull_for_pass(k1)
    assert_vals_equal(v_dev, v_host, rtol=0, atol=0)
    # Mutate + push back through both, then re-pull.
    for v in (v_dev, v_host):
        v["emb"] = v["emb"] + 1.5
        v["show"] = v["show"] + 2.0
    dev.push_from_pass(k1, v_dev)
    host.push_from_pass(k1, v_host)
    assert dev.num_features == host.num_features == 257
    k2 = keys_of(301, seed=2)  # overlaps k1 partially + new keys
    assert_vals_equal(dev.pull_for_pass(k2), host.pull_for_pass(k2),
                      rtol=0, atol=1e-7)


@pytest.mark.parametrize("mesh_shards", [1, 8])
def test_pass_table_roundtrip_and_readonly(mesh_shards):
    mesh = (build_mesh(HybridTopology(dp=8)) if mesh_shards == 8 else None)
    s = mesh_shards
    dev = DeviceFeatureStore(CFG, mesh=mesh)
    k = keys_of(100, seed=3)
    table, rows = dev.pull_pass_table(k, s)
    assert dev.num_features == 100
    assert (rows >= 0).all()
    vals = extract_pass_values_host(table, 100)
    host = FeatureStore(CFG)
    assert_vals_equal(vals, host.pull_for_pass(k), rtol=0, atol=0)
    # Write back modified values; re-pull sees them.
    new_vals = table.with_emb(table.emb + 3.0)
    dev.push_pass_table(k, rows, new_vals)
    t2, _ = dev.pull_pass_table(k, s)
    got = extract_pass_values_host(t2, 100)
    np.testing.assert_allclose(got["emb"], vals["emb"] + 3.0, atol=1e-6)
    assert set(np.asarray(dev.dirty_keys()).tolist()) == \
        set(k.tolist())
    # Read-only pull with unseen keys: store NOT grown, init overlaid.
    k_new = keys_of(50, seed=4, lo=20_000, hi=30_000)
    k_mix = np.sort(np.concatenate([k[:25], k_new]))
    t3, rows3 = dev.pull_pass_table(k_mix, s, readonly=True)
    assert dev.num_features == 100          # unchanged
    got3 = extract_pass_values_host(t3, k_mix.shape[0])
    ref = host.pull_for_pass(k_mix)         # host never persists on pull
    known = np.isin(k_mix, k[:25])
    np.testing.assert_allclose(got3["emb"][known],
                               vals["emb"][np.isin(k, k_mix)] + 3.0,
                               atol=1e-6)
    np.testing.assert_allclose(got3["emb"][~known], ref["emb"][~known],
                               atol=0)
    assert (rows3[~known] == -1).all()


def test_growth_preserves_values():
    dev = DeviceFeatureStore(CFG, capacity_hint=0)  # starts at 1024/shard
    k1 = keys_of(900, seed=5)
    v1 = dev.pull_for_pass(k1)
    v1["emb"] += 0.25
    dev.push_from_pass(k1, v1)
    # Force growth past the initial capacity (ensure_rows inserts+inits;
    # pull_for_pass is read-only and must NOT grow the store).
    k2 = keys_of(3000, seed=6, lo=50_000, hi=90_000)
    dev.pull_for_pass(k2)
    assert dev.num_features == 900
    dev.ensure_rows(k2)
    assert dev.num_features == 900 + 3000
    back = dev.pull_for_pass(k1)
    np.testing.assert_allclose(back["emb"], v1["emb"], atol=1e-7)


@pytest.mark.parametrize("mesh_shards", [1, 8])
def test_checkpoint_roundtrip_and_host_interop(tmp_path, mesh_shards):
    mesh = (build_mesh(HybridTopology(dp=8)) if mesh_shards == 8 else None)
    dev = DeviceFeatureStore(CFG, mesh=mesh)
    k = keys_of(64, seed=7)
    v = dev.pull_for_pass(k)
    v["emb"] += 0.5
    dev.push_from_pass(k, v)
    dev.save_base(str(tmp_path / "base"))
    # Delta: touch a subset after base.
    sub = k[10:20]
    v2 = dev.pull_for_pass(sub)
    v2["click"] += 4.0
    dev.push_from_pass(sub, v2)
    assert dev.dirty_keys().shape[0] == 10
    dev.save_delta(str(tmp_path / "delta"))
    # Host store loads the device store's artifacts (same format).
    host = FeatureStore(CFG)
    host.load(str(tmp_path / "base"), "base")
    host.load(str(tmp_path / "delta"), "delta")
    # A fresh device store loads its own artifacts.
    dev2 = DeviceFeatureStore(CFG, mesh=mesh)
    dev2.load(str(tmp_path / "base"), "base")
    dev2.load(str(tmp_path / "delta"), "delta")
    assert_vals_equal(dev2.pull_for_pass(k), host.pull_for_pass(k),
                      rtol=0, atol=1e-7)
    # xbox export exists and carries emb+w only.
    n = dev.save_xbox(str(tmp_path / "xbox"))
    assert n == 64
    data = np.load(tmp_path / "xbox" / f"{CFG.name}.xbox.npz")
    assert set(data.files) == {"keys", "emb", "w"}


def test_shrink_decay_and_eviction():
    dev = DeviceFeatureStore(CFG)
    k = keys_of(40, seed=8)
    v = dev.pull_for_pass(k)
    v["show"][:] = np.where(np.arange(40) < 15, 0.05, 10.0)
    v["click"][:] = 1.0
    dev.push_from_pass(k, v)
    evicted = dev.shrink(min_show=0.1)
    assert evicted == 15
    assert dev.num_features == 25
    survivors = k[15:] if (v["show"][:15] < 0.1).all() else None
    kept = dev.contains(k)
    assert kept.sum() == 25
    back = dev.pull_for_pass(k[kept])
    np.testing.assert_allclose(back["show"],
                               10.0 * CFG.show_click_decay, atol=1e-5)
    np.testing.assert_allclose(back["click"],
                               1.0 * CFG.show_click_decay, atol=1e-6)
    with pytest.raises(RuntimeError):
        dev.save_delta("/tmp/should-not-exist")


@pytest.mark.parametrize("mesh_shards", [1, 8])
def test_ctr_trainer_with_device_store_matches_host_store(mesh_shards):
    """Same data, same seeds: a CTRTrainer over the device tier must train
    identically (loss trajectory) to one over the host tier."""
    import jax
    from jax.sharding import Mesh
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    mesh = (build_mesh(HybridTopology(dp=8)) if mesh_shards == 8
            else Mesh(np.array(jax.devices()[:1]), ("dp",)))
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(3))
    feed = DataFeedConfig(slots=slots, batch_size=32)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                   emb_dim=4, hidden=(16,))

    def run(store_factory):
        tr = CTRTrainer(model, feed, CFG, mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=store_factory)
        tr.init(seed=0)
        losses = []
        for p in range(2):
            ds = _FakeDataset(feed, seed=11 + p, nbatches=3, ndev=mesh_shards)
            losses.append(tr.train_pass(ds)["loss"])
        return losses

    l_dev = run(lambda cfg: DeviceFeatureStore(cfg, mesh=mesh))
    l_host = run(lambda cfg: FeatureStore(cfg))
    np.testing.assert_allclose(l_dev, l_host, rtol=2e-5)


class _FakeDataset:
    """Minimal Dataset stand-in: fixed random batches + pass_keys."""

    def __init__(self, feed, seed, nbatches, ndev):
        from paddlebox_tpu.data.slots import Instance
        self.feed = feed
        rng = np.random.default_rng(seed)
        self._instances = []
        for _ in range(nbatches):
            batch = []
            for _ in range(feed.batch_size):
                batch.append(Instance(
                    labels=np.asarray(
                        [float(rng.integers(0, 2))], np.float32),
                    sparse={s.name: rng.integers(1, 300, 1).astype(
                        np.uint64) for s in feed.sparse_slots},
                    dense={}))
            self._instances.append(batch)

    def pass_keys(self, slots=None):
        return np.concatenate([
            np.concatenate([ins.sparse[s] for s in ins.sparse])
            for batch in self._instances for ins in batch])

    def batches_sharded(self, ndev):
        from paddlebox_tpu.data.slots import SlotBatch
        for batch in self._instances:
            yield SlotBatch.pack_sharded(batch, self.feed, ndev)
