"""DataFeedDesc proto-text compatibility: reference-style configs load
into DataFeedConfig / GraphGenConfig without a protobuf runtime."""

import numpy as np
import pytest

from paddlebox_tpu.data import (Dataset, data_feed_config_from_desc,
                                graph_gen_config_from_desc,
                                parse_proto_text)

DESC = """
# reference-style reader config (data_feed.proto DataFeedDesc)
name: "MultiSlotDataFeed"
batch_size: 32
pipe_command: "cat"
thread_num: 4
multi_slot_desc {
  slots {
    name: "user"
    type: "uint64"
    is_dense: false
    is_used: true
  }
  slots {
    name: "item"
    type: "uint64"
    is_used: true
  }
  slots {
    name: "skip_me"
    type: "uint64"
    is_used: false
  }
  slots {
    name: "dense_f"
    type: "float"
    is_dense: true
    is_used: true
    shape: 13
  }
  slots {
    name: "dense_2d"
    type: "float"
    is_dense: true
    is_used: true
    shape: 2
    shape: 3
  }
}
"""


def test_parse_proto_text_structure():
    d = parse_proto_text(DESC)
    assert d["batch_size"] == 32
    assert d["pipe_command"] == "cat"
    slots = d["multi_slot_desc"]["slots"]
    assert [s["name"] for s in slots] == [
        "user", "item", "skip_me", "dense_f", "dense_2d"]
    assert slots[3]["is_dense"] is True
    assert d["multi_slot_desc"]["slots"][4]["shape"] == [2, 3]


def test_data_feed_config_from_desc_end_to_end(tmp_path):
    cfg, extras = data_feed_config_from_desc(DESC)
    assert cfg.batch_size == 32 and cfg.pipe_command == "cat"
    assert extras["thread_num"] == 4
    names = [s.name for s in cfg.sparse_slots]
    assert names == ["user", "item"]          # unused slot excluded
    dd = {s.name: s.dim for s in cfg.dense_slots}
    assert dd == {"dense_f": 13, "dense_2d": 6}

    # The parsed config actually READS data (a pipe_command of cat is a
    # no-op filter; the unused slot's tokens are dropped).
    p = str(tmp_path / "part")
    rng = np.random.default_rng(0)
    with open(p, "w") as f:
        for _ in range(64):
            dense = ",".join("0.5" for _ in range(13))
            d2 = ",".join("0.1" for _ in range(6))
            f.write(f"{rng.integers(0, 2)} user:{rng.integers(1, 50)} "
                    f"item:{rng.integers(1, 50)} skip_me:7 "
                    f"dense_f:{dense} dense_2d:{d2}\n")
    ds = Dataset(cfg, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    batch = next(ds.batches_sharded(1))
    assert batch.batch_size == 32
    assert "user" in batch.ids and "skip_me" not in batch.ids


def test_unknown_fields_flow_to_extras_and_errors_are_loud():
    # Not a DataFeedDesc at all -> loud.
    with pytest.raises(ValueError, match="no DataFeedDesc fields"):
        data_feed_config_from_desc('nonsense_field: 3')
    # A newer-reference field on a real desc rides along in extras.
    cfg, extras = data_feed_config_from_desc(
        'batch_size: 8\nfuture_knob: 7\n'
        'multi_slot_desc { slots { name: "a" type: "uint64" '
        'is_used: true } }')
    assert cfg.batch_size == 8 and extras["future_knob"] == 7
    with pytest.raises(ValueError, match="missing closing"):
        parse_proto_text("a { b: 1")
    with pytest.raises(ValueError, match="has no value"):
        parse_proto_text("a: ")
    # Non-ASCII strings survive; escapes still decode.
    d = parse_proto_text('cmd: "cat 数据/part-*"\nesc: "a\\tb"')
    assert d["cmd"] == "cat 数据/part-*" and d["esc"] == "a\tb"


def test_graph_desc_requires_graph_fields():
    # A graph-less CTR desc (batch_size alone is ambiguous — GraphConfig
    # has its own — so use unambiguous feed fields) must fail loudly
    # instead of returning all-default walk knobs.
    with pytest.raises(ValueError, match="no graph_config"):
        graph_gen_config_from_desc('pipe_command: "cat"\nthread_num: 2')
    # Bare graph block (no wrapper) accepted; repeated meta_path: last
    # value wins (proto2 optional semantics).
    g = graph_gen_config_from_desc(
        'walk_len: 3\nmeta_path: "a-b"\nmeta_path: "c-d"')
    assert g.walk_len == 3 and g.metapath == ("c", "d")


def test_graph_gen_config_from_desc():
    g = graph_gen_config_from_desc("""
graph_config {
  walk_len: 6
  window: 2
  batch_size: 16
  meta_path: "u2i-i2u;u2c-c2u"
}
""")
    assert g.walk_len == 6 and g.window == 2 and g.batch_walks == 16
    assert g.metapath == ("u2i", "i2u")      # first path of the mix


def test_table_config_from_desc():
    from paddlebox_tpu.data import table_config_from_desc

    cfg, extras = table_config_from_desc("""
table_id: 0
table_class: "MemorySparseTable"
shard_num: 1950
accessor {
  accessor_class: "CtrCommonAccessor"
  fea_dim: 11
  embedx_dim: 16
  embedx_threshold: 10
  ctr_accessor_param {
    nonclk_coeff: 0.1
    click_coeff: 1.0
    show_click_decay_rate: 0.96
  }
  embedx_sgd_param {
    name: "SparseAdaGradSGDRule"
    adagrad {
      learning_rate: 0.08
      initial_g2sum: 2.5
      weight_bounds: -12.0
      weight_bounds: 12.0
    }
  }
}
""")
    assert cfg.dim == 16 and cfg.optimizer == "adagrad"
    assert cfg.learning_rate == 0.08 and cfg.initial_g2sum == 2.5
    assert cfg.min_bound == -12.0 and cfg.max_bound == 12.0
    assert cfg.show_click_decay == 0.96
    # Placement stays mesh-derived; shard_num passes through.
    assert cfg.num_shards == 1 and extras["shard_num"] == 1950

    # Adam rule selects the adam optimizer; a built table honors it.
    cfg2, _ = table_config_from_desc("""
accessor {
  embedx_dim: 8
  embedx_sgd_param {
    name: "SparseAdamSGDRule"
    adam { learning_rate: 0.002 beta1_decay_rate: 0.85 }
  }
}
""")
    assert cfg2.optimizer == "adam" and cfg2.beta1 == 0.85
    from paddlebox_tpu.embedding import make_sparse_optimizer
    opt = make_sparse_optimizer(cfg2)
    assert type(opt).__name__ == "SparseAdam"

    # Shared-adam rule selects adam_shared, not plain adam (different
    # update semantics + state layout).
    cfg3, _ = table_config_from_desc("""
accessor {
  embedx_dim: 8
  embedx_sgd_param { name: "SparseSharedAdamSGDRule"
                     adam { learning_rate: 0.01 } }
}
""")
    assert cfg3.optimizer == "adam_shared"

    # Unmapped accessor subfields survive in extras (no silent drops).
    acc_extras = extras["accessor"]
    assert acc_extras["embedx_threshold"] == 10
    assert acc_extras["ctr_accessor_param"]["nonclk_coeff"] == 0.1
    assert "embedx_sgd_param" not in acc_extras  # consumed

    with pytest.raises(ValueError, match="no accessor"):
        table_config_from_desc("batch_size: 4")


def test_distributed_strategy_from_proto_text():
    from paddlebox_tpu.fleet.strategy import DistributedStrategy

    s = DistributedStrategy.from_proto_text("""
amp: true
recompute: true
sharding: true
amp_configs {
  dtype: "bfloat16"
  init_loss_scaling: 1024.0
  unknown_amp_knob: 3
}
sharding_configs { stage: 3 offload: true }
hybrid_configs {
  dp_degree: 2
  mp_degree: 2
  pp_degree: 2
  weird_degree: 9
}
future_switch: true
""")
    assert s.amp and s.recompute and s.sharding
    assert s.amp_configs.init_loss_scaling == 1024.0
    assert s.sharding_configs.stage == 3 and s.sharding_configs.offload
    assert s.hybrid_configs == {"dp_degree": 2, "mp_degree": 2,
                                "pp_degree": 2}
    topo = s.topology(world_size=8)
    assert topo.dp == 2 and topo.mp == 2 and topo.pp == 2


def test_strategy_proto_repeated_and_malformed_fields():
    from paddlebox_tpu.fleet.strategy import DistributedStrategy

    # Repeated fields: last value wins (proto2 singular semantics).
    s = DistributedStrategy.from_proto_text(
        "amp: true\namp: false\n"
        "hybrid_configs { dp_degree: 2 dp_degree: 4 }\n"
        "sharding_configs { stage: 2 stage: 3 }")
    assert s.amp is False
    assert s.hybrid_configs == {"dp_degree": 4}
    assert s.sharding_configs.stage == 3
    # A scalar where a config block belongs is refused (skipped), not
    # planted as a time bomb.
    s2 = DistributedStrategy.from_proto_text("amp_configs: true")
    assert s2.amp_configs.dtype == "bfloat16"
