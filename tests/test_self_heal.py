"""Self-healing day loop drills: deterministic faults injected mid-day
must cost ONE pass retry — with checkpoint rollback making the retried
day BIT-identical to an unfailed run — the stall watchdog must abort and
retry instead of hanging, and a kill -9 at publish/save sites must
resume through ``recover()`` with no double-applied deltas.

Role of the reference recovery story being proven: donefile
resume (fleet_util.py) + elastic restart's pass-exactly-once semantics,
now exercised by deliberate breakage instead of claimed."""

import importlib.util
import os
import time

import numpy as np
import pytest

from paddlebox_tpu.core import faults, flags as flagmod, monitor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "crash_drill", os.path.join(REPO, "tools", "crash_drill.py"))
crash_drill = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(crash_drill)

DAY = "20260728"
SLOTS = ("user", "item")
HOURS = [0, 1]


@pytest.fixture(autouse=True)
def _clean_fault_state():
    keep = ("fault_spec", "pass_max_retries", "pass_retry_backoff_s",
            "pass_retry_backoff_max_s", "stall_timeout_s")
    old = {k: flagmod.flag(k) for k in keep}
    faults.clear()
    flagmod.set_flags({"pass_retry_backoff_s": 0.01})
    try:
        yield
    finally:
        faults.clear()
        flagmod.set_flags(old)


def _write_day(root):
    crash_drill.write_day(root, DAY, HOURS, rows_per_split=96)


def _make_runner(data, out, *, device_store=False):
    from paddlebox_tpu.data import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig
    from paddlebox_tpu.train.day_runner import DayRunner

    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    store_factory = None
    if device_store:
        from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
        store_factory = lambda c: DeviceFeatureStore(c, mesh=mesh)  # noqa
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10),
        store_factory=store_factory)
    trainer.init(seed=0)
    return DayRunner(trainer, feed, out, data_root=data,
                     split_interval=60, split_per_pass=1,
                     hours=HOURS, num_reader_threads=2)


def _final_state(runner):
    import jax
    tr = runner.trainer
    store = tr.engine.store
    keys = np.sort(store.key_stats()[0])
    vals = store.pull_for_pass(keys)
    return {
        "params": [np.asarray(x).copy()
                   for x in jax.tree.leaves(tr.params)],
        "opt": [np.asarray(x).copy()
                for x in jax.tree.leaves(tr.opt_state)],
        "keys": keys,
        "vals": {f: np.asarray(v).copy() for f, v in vals.items()},
    }


def _assert_state_equal(got, want):
    for a, b in zip(got["params"], want["params"]):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(got["opt"], want["opt"]):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(got["keys"], want["keys"])
    for f in want["vals"]:
        np.testing.assert_array_equal(got["vals"][f], want["vals"][f])


@pytest.fixture(scope="module")
def day_data(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("heal_data"))
    _write_day(d)
    return d


@pytest.fixture(scope="module")
def reference(day_data, tmp_path_factory):
    """Unfailed host-store day: the bit-parity baseline."""
    out = str(tmp_path_factory.mktemp("ref_out"))
    runner = _make_runner(day_data, out)
    stats = runner.train_day(DAY)
    return {"stats": stats, "state": _final_state(runner)}


# ---------------------------------------------------------------------------
# transient-fault retry matrix: ~6 sites x {raise, delay}
# ---------------------------------------------------------------------------

# (site, hit) — hits are chosen to land in different pass phases:
# builds, write-backs, prefetch reads mid-pass-1 and mid-pass-2, and the
# post-train save/publish window (which exercises the
# no-double-applied-updates rollback: the store was already written back
# when the failure hit).
RETRY_SITES = [
    ("pass_engine/build", 2),
    ("pass_engine/write_back", 2),
    ("trainer/prefetch", 2),
    ("trainer/pack", 5),
    ("day_runner/save", 1),
    ("day_runner/publish", 2),
]


@pytest.mark.parametrize("action", ["raise=IOError", "delay_ms=120"])
@pytest.mark.parametrize("site,hit", RETRY_SITES,
                         ids=[s.replace("/", "_") for s, _ in RETRY_SITES])
def test_transient_fault_costs_one_retry_bit_parity(
        site, hit, action, day_data, reference, tmp_path):
    out = str(tmp_path / "out")
    retries0 = monitor.get("pass/retries")
    faults.configure(f"{site}:hit={hit}:{action}")
    runner = _make_runner(day_data, out)
    stats = runner.train_day(DAY)
    faults.clear()

    injected = monitor.get(f"fault/{site}_injected")
    assert injected >= 1, "fault site never traversed"
    if action.startswith("raise"):
        assert monitor.get("pass/retries") - retries0 >= 1
    else:
        # A pure delay is not a failure: no retry, just latency.
        assert monitor.get("pass/retries") - retries0 == 0

    ref = reference
    assert len(stats) == len(ref["stats"])
    for got, want in zip(stats, ref["stats"]):
        assert got["steps"] == want["steps"]
        assert got["loss"] == want["loss"], (site, got["loss"],
                                            want["loss"])
        assert got["auc"] == want["auc"]
    _assert_state_equal(_final_state(runner), ref["state"])
    # Recovery index is intact: 2 deltas + the day base, exactly once.
    recs = runner.ckpt.records()
    assert [(r.day, r.pass_id) for r in recs] == \
        [(DAY, 1), (DAY, 2), (DAY, 0)]


def test_fatal_fault_is_not_retried(day_data, tmp_path):
    """ValueError (bad data / code bug class) must raise immediately —
    blind retry would re-fail or mask the bug."""
    retries0 = monitor.get("pass/retries")
    faults.configure("day_runner/save:raise=ValueError")
    runner = _make_runner(day_data, str(tmp_path / "out"))
    with pytest.raises(ValueError):
        runner.train_day(DAY)
    assert monitor.get("pass/retries") - retries0 == 0


def test_retry_budget_exhaustion_raises_original(day_data, tmp_path):
    """A persistent transient fault raises after FLAGS_pass_max_retries
    attempts (times=0 keeps the site hot forever)."""
    flagmod.set_flags({"pass_max_retries": 1})
    retries0 = monitor.get("pass/retries")
    faults.configure("day_runner/save:times=0:raise=IOError")
    runner = _make_runner(day_data, str(tmp_path / "out"))
    with pytest.raises(OSError):
        runner.train_day(DAY)
    assert monitor.get("pass/retries") - retries0 == 1


def test_retry_disabled_with_zero_budget(day_data, tmp_path):
    flagmod.set_flags({"pass_max_retries": 0})
    faults.configure("day_runner/save:raise=IOError")
    runner = _make_runner(day_data, str(tmp_path / "out"))
    with pytest.raises(OSError):
        runner.train_day(DAY)


def test_device_store_retry_bit_parity(day_data, tmp_path):
    """The HBM-tier store heals the same way: a transient push failure
    mid-day retries to a bit-identical final state."""
    ref = _make_runner(day_data, str(tmp_path / "ref"),
                       device_store=True)
    ref_stats = ref.train_day(DAY)

    faults.configure("device_store/push:hit=2:raise=IOError")
    runner = _make_runner(day_data, str(tmp_path / "out"),
                          device_store=True)
    stats = runner.train_day(DAY)
    faults.clear()
    assert [s["loss"] for s in stats] == [s["loss"] for s in ref_stats]
    _assert_state_equal(_final_state(runner), _final_state(ref))


# ---------------------------------------------------------------------------
# watchdog: stall -> forensic abort -> retry
# ---------------------------------------------------------------------------

def test_watchdog_stall_aborts_then_retries_bit_parity(
        day_data, reference, tmp_path):
    """An 8s wedge in the prefetch path with a 5s stall budget: the
    watchdog dumps forensics, aborts the pass via StallError, and the
    retry completes the day bit-identically. (The generous timeout keeps
    the first-dispatch XLA compile from tripping it.)"""
    flagmod.set_flags({"stall_timeout_s": 5.0, "pass_max_retries": 3})
    stalls0 = monitor.get("watchdog/stalls")
    retries0 = monitor.get("pass/retries")
    faults.configure("trainer/prefetch:hit=6:delay_ms=8000")
    t0 = time.time()
    runner = _make_runner(day_data, str(tmp_path / "out"))
    stats = runner.train_day(DAY)
    faults.clear()
    assert monitor.get("watchdog/stalls") - stalls0 >= 1
    assert monitor.get("pass/retries") - retries0 >= 1
    # It aborted at the stall budget and retried — it did NOT hang.
    assert time.time() - t0 < 120
    ref = reference
    for got, want in zip(stats, ref["stats"]):
        assert got["loss"] == want["loss"]
    _assert_state_equal(_final_state(runner), ref["state"])


# ---------------------------------------------------------------------------
# kill -9 crash drills (subprocess; fast 2-site mode is tier-1)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def drill_env(tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("drill"))
    ref = crash_drill.run_reference(workdir)
    return workdir, ref


@pytest.mark.parametrize("site,hit", crash_drill.FAST_SITES,
                         ids=[s.replace("/", "_") + f"_h{h}"
                              for s, h in crash_drill.FAST_SITES])
def test_kill9_resumes_via_recover_fast(drill_env, site, hit):
    """SIGKILL the worker AT the site, restart with resume=True: the
    donefile chain must replay to the exact uninterrupted final state —
    same dense digest, same store digest, same records, losses a suffix
    of the reference's (no pass retrained twice = no double-applied
    deltas; the store digest would differ if show/click doubled)."""
    workdir, ref = drill_env
    r = crash_drill.run_drill(workdir, site, hit=hit, reference=ref)
    assert r["killed_rc"] == -9, r
    assert r["ok"], r["mismatch"]


@pytest.mark.slow
@pytest.mark.parametrize(
    "site,hit",
    [s for s in crash_drill.FULL_SITES if s not in crash_drill.FAST_SITES],
    ids=[s.replace("/", "_") + f"_h{h}"
         for s, h in crash_drill.FULL_SITES
         if (s, h) not in crash_drill.FAST_SITES])
def test_kill9_resumes_via_recover_full(drill_env, site, hit):
    workdir, ref = drill_env
    r = crash_drill.run_drill(workdir, site, hit=hit, reference=ref)
    assert r["killed_rc"] == -9, r
    assert r["ok"], r["mismatch"]


def test_killed_ingest_worker_retried_without_hanging_preload(tmp_path):
    """Round-13 ingest process boundary: SIGKILL an ingest worker
    MID-FILE — the pump must requeue the file on a fresh worker
    (FLAGS_ingest_file_retries) and wait_preload_done() must return the
    complete, non-duplicated dataset instead of hanging on the dead
    child; with the retry budget at 0 the death propagates as an error
    (tests/test_ingest.py covers that half)."""
    from paddlebox_tpu.data import DataFeedConfig, Dataset, SlotConf

    lines = [f"1 user:{i} item:{i + 1000}" for i in range(1, 61)]
    part = tmp_path / "part-0"
    part.write_text("\n".join(lines) + "\n")
    started = tmp_path / "started"
    feed = DataFeedConfig(
        slots=(SlotConf("user"), SlotConf("item")), batch_size=8,
        pipe_command=f"touch {started}; sleep 3; cat")
    old = flagmod.get_flags(["ingest_workers", "ingest_file_retries"])
    flagmod.set_flags({"ingest_workers": 1, "ingest_file_retries": 1})
    try:
        ds = Dataset(feed)
        ds.set_filelist([str(part)])
        ds.preload_into_memory()
        t0 = time.time()
        while not started.exists() and time.time() - t0 < 60:
            time.sleep(0.05)
        assert started.exists(), "worker never reached the file"
        time.sleep(0.2)
        assert ds._ingest_procs
        started.unlink()  # the RETRY recreates it through the same pipe
        victim = ds._ingest_procs[0]
        os.kill(victim.pid, 9)
        t0 = time.time()
        while not started.exists() and time.time() - t0 < 60:
            time.sleep(0.05)
        assert started.exists(), "no replacement worker took the file"
        ds.wait_preload_done()  # returns (pipe delay), never hangs
        assert ds.num_instances == 60  # complete, no duplicated rows
        assert monitor.get("ingest/worker_restarts") >= 1
        ds.clear()
    finally:
        flagmod.set_flags(old)
