"""Optimizer-state offload (VERDICT r02 task 7): state pinned to host
memory ("pinned_host" memory kind) around the update, with exact loss
parity vs the on-device optimizer — role of the reference's
ShardingOptimizer offload pass (sharding_optimizer.py:540-558)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.parallel.zero import OffloadedOptimizer, zero_specs


def _toy():
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(0, 0.1, (64, 64)), jnp.float32),
        "b1": jnp.asarray(np.zeros(64), jnp.float32),
        "w2": jnp.asarray(rng.normal(0, 0.1, (64, 8)), jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
    return params, x, y


def _loss(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return jnp.mean((h @ params["w2"] - y) ** 2)


def _state_kinds(state):
    """Memory kinds of non-scalar state leaves (scalar step counters stay
    on device by design — bytes, and XLA rejects host-pinned scalars)."""
    return {leaf.sharding.memory_kind
            for leaf in jax.tree_util.tree_leaves(state)
            if hasattr(leaf, "sharding") and np.ndim(leaf) > 0}


def _host_kind(mesh):
    """The backend's spelling of host memory: TPU advertises
    pinned_host, the CPU test backend only unpinned_host — the offload
    contract under test is 'state lives in HOST memory', whichever kind
    the backend names it."""
    from paddlebox_tpu.parallel.zero import _resolve_host_kind
    return _resolve_host_kind(mesh, "pinned_host")


def test_offloaded_state_lives_on_host_and_matches_device_run():
    mesh = build_mesh(HybridTopology(sharding=8))
    params, x, y = _toy()
    tx = optax.adam(1e-2)

    # Plain on-device run.
    p_dev = jax.tree.map(jnp.copy, params)
    s_dev = tx.init(p_dev)

    @jax.jit
    def step_dev(p, s):
        loss, g = jax.value_and_grad(_loss)(p, x, y)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    # Offloaded run: identical math, state pinned to host memory.
    off = OffloadedOptimizer(tx, mesh)
    p_off = jax.tree.map(jnp.copy, params)
    s_off = off.init(p_off)
    # HBM optimizer-state bytes ~ 0: every array leaf of the state lives
    # in the host memory space, not device HBM.
    assert _state_kinds(s_off) == {_host_kind(mesh)}

    grad_fn = jax.jit(jax.value_and_grad(_loss))
    losses_dev, losses_off = [], []
    for _ in range(5):
        p_dev, s_dev, l_dev = step_dev(p_dev, s_dev)
        losses_dev.append(float(l_dev))
        l_off, g = grad_fn(p_off, x, y)
        u, s_off = off.update(g, s_off, p_off)
        p_off = optax.apply_updates(p_off, u)
        losses_off.append(float(l_off))
        assert _state_kinds(s_off) == {_host_kind(mesh)}

    np.testing.assert_allclose(losses_off, losses_dev, rtol=1e-6)
    # atol covers one-ulp jitter from the sharded-vs-replicated program.
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), p_off, p_dev)


def test_offloaded_state_is_sharded_over_axis():
    mesh = build_mesh(HybridTopology(sharding=8))
    params, _, _ = _toy()
    off = OffloadedOptimizer(optax.adam(1e-2), mesh, min_size=0)
    s = off.init(params)
    # Adam's mu for w1 [64, 64]: divisible dim sharded over the axis.
    mu_w1 = s[0].mu["w1"]
    assert mu_w1.sharding.memory_kind == _host_kind(mesh)
    assert mu_w1.sharding.spec == zero_specs(
        {"w1": np.zeros((64, 64))}, mesh, min_size=0)["w1"]


def test_zero3_compiled_memory_shrinks_with_sharding():
    """ZeRO-3 placement is real memory, not annotation theater: the
    compiled train step's per-device argument bytes drop by ~the sharding
    factor when params+state are sharded (1F1B-style compiled-memory
    assertion, VERDICT r02 weak #6)."""
    import optax
    from paddlebox_tpu.parallel.zero import zero_shardings

    mesh = build_mesh(HybridTopology(sharding=8))
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(0, 0.1, (512, 512)), jnp.float32),
              "v": jnp.asarray(rng.normal(0, 0.1, (512, 512)), jnp.float32)}
    tx = optax.adam(1e-3)
    x = jnp.asarray(rng.normal(size=(16, 512)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(16, 512)), jnp.float32)

    def loss(p, x, y):
        return jnp.mean((jnp.tanh(x @ p["w"]) @ p["v"] - y) ** 2)

    def step(p, s, x, y):
        l, g = jax.value_and_grad(loss)(p, x, y)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    def arg_bytes(p, s):
        return jax.jit(step).lower(p, s, x, y).compile() \
            .memory_analysis().argument_size_in_bytes

    state = tx.init(params)
    replicated = arg_bytes(params, state)
    sh = zero_shardings(params, mesh, min_size=0)
    p3 = jax.tree.map(jax.device_put, params, sh)
    s3 = tx.init(p3)
    s3 = jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, zero_shardings(leaf, mesh, min_size=0))
        if np.ndim(leaf) > 0 else leaf, s3)
    sharded = arg_bytes(p3, s3)
    # params (2MB x2) + adam mu/nu (4MB) dominate; sharded 8x should cut
    # per-device argument bytes by >= 4x (x/y stay replicated).
    assert sharded * 4 <= replicated, (sharded, replicated)
