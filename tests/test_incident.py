"""The incident flight recorder (core/incident.py) + the fleet-health
acceptance drill.

Unit pins: every trigger in the incident matrix (page alert, watchdog
stall, replica eject, STALE_PRIMARY burst) writes one bundle; captures
are rate-limited on an injected clock (repeated firing → exactly one
bundle + ``incident/rate_limited``); bundles appear ONLY via atomic
rename so a torn ``.tmp`` is never listed; a capture crash is
contained (counted, returns None — the ROBUSTNESS.md
``incident/capture`` row); and ``tools/incident_report.py`` renders a
bundle naming the breached objective.

The acceptance drill runs the real thing: router + 2 replicas over a
shard tier, health plane armed with second-scale windows, a planted
predict-latency degradation → ``serving_predict_p99`` FIRING within
two fast windows, visible in ONE ``telemetry_scrape`` sweep AND in
``fleet_top --once --json``, exactly one incident bundle under
repeated firing, ``incident_report`` naming the objective, and
recovery → RESOLVED after the slow window slides clean.

The jaxpr pin proves the whole plane (sampler + evaluator + capture
armed) changes ZERO device ops in the train step and serving forward.
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from paddlebox_tpu.core import alerts, flags, incident, monitor, timeseries
from paddlebox_tpu.core.incident import IncidentRecorder, list_bundles

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def recorder(tmp_path):
    """A fresh recorder swapped in as the process-global one (the
    watchdog / fleet / alert paths all reach ``incident.GLOBAL``)."""
    rec = IncidentRecorder(str(tmp_path / "inc"), min_interval_s=3600.0)
    prev, incident.GLOBAL = incident.GLOBAL, rec
    yield rec
    incident.GLOBAL = prev


def _bundle_kinds(rec):
    return [json.load(open(p))["kind"]
            for p in list_bundles(rec._directory())]


# -- atomic bundles + rate limit ----------------------------------------------


def test_trigger_writes_atomic_bundle_and_tmp_never_listed(tmp_path):
    d = str(tmp_path / "inc")
    rec = IncidentRecorder(d, min_interval_s=0.0)
    rec.set_context(day="20260807", pass_id=3)
    path = rec.trigger("unit_test", context={"who": "test"})
    assert path and os.path.exists(path)
    b = json.load(open(path))
    assert b["schema"] == "incident/1"
    assert b["kind"] == "unit_test"
    assert b["context"] == {"day": "20260807", "pass_id": 3,
                            "who": "test"}
    assert "metrics" in b and "forensics" in b
    # A torn capture (the crash_drill kill window) is a dot-tmp file:
    # list_bundles must never mistake it for a complete bundle.
    torn = os.path.join(d, ".incident-0099-torn.tmp")
    open(torn, "w").write("{ half a bund")
    assert list_bundles(d) == [path]
    # set_context(None) clears keys.
    rec.set_context(day=None)
    p2 = rec.trigger("unit_test2")
    assert "day" not in json.load(open(p2))["context"]


def test_rate_limit_one_bundle_under_repeated_firing(tmp_path):
    clk = [100.0]
    rec = IncidentRecorder(str(tmp_path / "inc"), min_interval_s=60.0,
                           clock=lambda: clk[0])
    limited0 = monitor.GLOBAL.get("incident/rate_limited")
    assert rec.trigger("flap") is not None
    for _ in range(5):  # a flapping alert re-triggering in the window
        clk[0] += 1.0
        assert rec.trigger("flap") is None
    assert len(list_bundles(rec._directory())) == 1
    assert monitor.GLOBAL.get("incident/rate_limited") == limited0 + 5
    # force bypasses (operator-requested capture), clock expiry re-arms.
    assert rec.trigger("forced", force=True) is not None
    clk[0] += 61.0
    assert rec.trigger("later") is not None
    assert len(list_bundles(rec._directory())) == 3


def test_capture_crash_contained(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file where the incident dir should be")
    rec = IncidentRecorder(str(blocker), min_interval_s=0.0)
    errs0 = monitor.GLOBAL.get("incident/capture_errors")
    assert rec.trigger("doomed") is None  # contained, never raises
    assert monitor.GLOBAL.get("incident/capture_errors") == errs0 + 1
    # The failed capture released its rate-limit claim: a later trigger
    # (dir fixed) succeeds immediately.
    rec2 = IncidentRecorder(str(tmp_path / "ok"),
                            min_interval_s=3600.0)
    assert rec2.trigger("fine") is not None


def test_disabled_recorder_is_a_noop(tmp_path):
    rec = IncidentRecorder("", min_interval_s=0.0)
    assert rec.enabled is False
    assert rec.trigger("ignored") is None
    rec.note_stale_primary()  # cheap no-op when disabled


# -- the trigger matrix -------------------------------------------------------


def test_watchdog_stall_writes_bundle(recorder):
    from paddlebox_tpu.core.watchdog import Watchdog
    wd = Watchdog(timeout_s=0.01, name="drill-dog")
    wd._phase = "dispatch"
    wd._target = None  # nothing to abort: exercise the forensics path
    wd._fire(12.5)
    assert _bundle_kinds(recorder) == ["watchdog_stall"]
    b = json.load(open(list_bundles(recorder._directory())[0]))
    assert b["context"]["watchdog"] == "drill-dog"
    assert b["context"]["phase"] == "dispatch"
    assert "thread_stacks" in (b["forensics"] or {})


def test_replica_eject_writes_bundle(recorder):
    from paddlebox_tpu.serving.fleet import ServingFleet
    fleet = ServingFleet()
    fleet.add_replica("r9", "127.0.0.1:1")
    fleet._eject(fleet.get("r9"), reason="drill")
    assert _bundle_kinds(recorder) == ["replica_eject"]
    b = json.load(open(list_bundles(recorder._directory())[0]))
    assert b["context"]["replica"] == "r9"


def test_stale_primary_burst_threshold(recorder):
    clk = [0.0]
    rec = IncidentRecorder(recorder._directory(), min_interval_s=0.0,
                           clock=lambda: clk[0])
    rec.note_stale_primary()
    clk[0] = 1.0
    rec.note_stale_primary()
    assert list_bundles(rec._directory()) == []  # 2 < burst threshold
    clk[0] = 2.0
    rec.note_stale_primary()
    assert _bundle_kinds(rec) == ["stale_primary_burst"]
    # Spread wider than the window: never a burst.
    for dt in (100.0, 120.0, 140.0):
        clk[0] = dt
        rec.note_stale_primary()
    assert len(list_bundles(rec._directory())) == 1


def test_page_alert_firing_triggers_capture(recorder):
    """The alerts→incident seam: a page-severity FIRING transition with
    no on_page override reaches incident.trigger."""
    from paddlebox_tpu.core.alerts import AlertEngine, SLORule
    from paddlebox_tpu.core.timeseries import MetricHistory
    reg = monitor.Monitor()
    h = MetricHistory(reg, points=16, clock=lambda: 0.0)
    h.sample(now=0.0)
    eng = AlertEngine(h, [SLORule(name="gauge_page", metric="g",
                                  kind="gauge", threshold=1.0,
                                  severity="page")],
                      clock=lambda: 0.0)
    reg.set_gauge("g", 5.0)
    h.sample(now=10.0)
    eng.evaluate(now=10.0)
    assert eng.state("gauge_page") == "firing"
    assert _bundle_kinds(recorder) == ["alert:gauge_page"]
    b = json.load(open(list_bundles(recorder._directory())[0]))
    assert b["context"]["alert"]["name"] == "gauge_page"


# -- incident_report ----------------------------------------------------------


def test_incident_report_renders_and_lists(tmp_path, capsys):
    rec = IncidentRecorder(str(tmp_path / "inc"), min_interval_s=0.0)
    path = rec.trigger("unit_render", context={"day": "20260807"})
    irep = _tool("incident_report")
    assert irep.main([path]) == 0
    out = capsys.readouterr().out
    assert "INCIDENT  unit_render" in out
    assert "day=20260807" in out
    # Directory form resolves the NEWEST complete bundle; --list names
    # them all; --json re-dumps machine-readably.
    assert irep.main([str(tmp_path / "inc"), "--list"]) == 0
    assert path in capsys.readouterr().out
    assert irep.main([str(tmp_path / "inc"), "--json"]) == 0
    assert json.loads(
        capsys.readouterr().out)["kind"] == "unit_render"


# -- the acceptance drill -----------------------------------------------------

SLOTS = ("u", "i")
N_KEYS = 400
DIM = 8


def _drill_fleet(shard_eps):
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.serving import (CTRPredictor, FleetRouter,
                                       PredictClient, PredictServer,
                                       ShardBackedStore)
    import jax
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=16)
    model = DeepFM(slot_names=SLOTS, emb_dim=DIM, hidden=())
    rng = np.random.default_rng(3)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * 0.02
    w = rng.normal(size=(N_KEYS,)).astype(np.float32) * 0.02
    dense = model.init(jax.random.PRNGKey(0))
    preds = [CTRPredictor(model, feed, keys[:32], emb[:32], w[:32],
                          dense, compute_dtype="float32", hbm_rows=24,
                          shard_backing=ShardBackedStore(shard_eps, DIM))
             for _ in range(2)]
    servers = [PredictServer("127.0.0.1:0", p, replica_id=f"r{i}")
               for i, p in enumerate(preds)]
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint for s in servers],
                         start_health=False)
    return preds, servers, router, PredictClient(router.endpoint)


@pytest.fixture()
def shard_tier():
    from paddlebox_tpu.embedding.table import TableConfig
    from paddlebox_tpu.multihost.shard_service import (start_local_shards,
                                                       stop_shards)
    from paddlebox_tpu.multihost.store import MultiHostStore
    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    servers, eps = start_local_shards(2, cfg)
    store = MultiHostStore(cfg, eps)
    rng = np.random.default_rng(3)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    rows = store.pull_for_pass(keys)
    rows["emb"] = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * 0.02
    rows["w"] = rng.normal(size=(N_KEYS,)).astype(np.float32) * 0.02
    store.push_from_pass(keys, rows)
    yield eps
    store.close()
    stop_shards(servers)


def test_fleet_health_acceptance_drill(tmp_path, shard_tier, capsys):
    """ISSUE-18 acceptance: degrade → FIRING in ≤2 fast windows →
    visible in one scrape sweep and fleet_top → one bundle → report
    names the objective → recover → RESOLVED; re-fire stays
    rate-limited at exactly one bundle."""
    from paddlebox_tpu.core import telemetry_scrape as tscrape
    inc_dir = str(tmp_path / "inc")
    keys = ("serving_slo_p99_ms", "alerts_fast_window_s",
            "alerts_slow_window_s", "alerts_clear_windows")
    prev = {k: flags.flag(k) for k in keys}
    flags.set_flags({"serving_slo_p99_ms": 300.0,
                     "alerts_fast_window_s": 9.0,
                     "alerts_slow_window_s": 31.0,
                     "alerts_clear_windows": 2})
    hist = timeseries.MetricHistory(monitor.GLOBAL, points=64,
                                    label="global", clock=lambda: 0.0)
    eng = alerts.AlertEngine(hist, clock=lambda: 0.0)  # default pack
    rec = IncidentRecorder(inc_dir, min_interval_s=3600.0)
    prev_rec, incident.GLOBAL = incident.GLOBAL, rec
    prev_eng, alerts.GLOBAL = alerts.GLOBAL, eng
    preds, servers, router, cli = _drill_fleet(shard_tier)
    rng = np.random.default_rng(7)

    def lines(n=2):
        return [f"0 u:{rng.integers(1, N_KEYS)} i:{rng.integers(1, N_KEYS)}"
                for _ in range(n)]

    t = [1_000_000.0]

    def window(bad=False):
        """One sampler window: real fleet traffic, plus (bad) a planted
        latency degradation >1% of the slow window's observations."""
        for _ in range(6):
            cli.predict(lines())
        for _ in range(200):
            monitor.observe_quantile(
                "serving/predict_ms", 5000.0 if bad else 5.0)
        t[0] += 10.0
        hist.sample(now=t[0])
        return eng.evaluate(now=t[0])

    try:
        for _ in range(8):  # JIT warmup before the delta base
            cli.predict(lines())
        hist.sample(now=t[0])
        for _ in range(3):
            window()
        assert eng.state("serving_predict_p99") == "ok"

        window(bad=True)
        window(bad=True)
        assert eng.state("serving_predict_p99") == "firing"

        # ONE scrape sweep shows the firing objective fleet-wide.
        targets = {"router": router.endpoint,
                   **{f"r{i}": s.endpoint
                      for i, s in enumerate(servers)}}
        sweep = tscrape.scrape_cluster(targets, with_history=True)
        assert not sweep["errors"]
        assert sweep["cluster"]["alerts_firing"] >= 1
        st = {a["name"]: a["state"] for a in sweep["alerts"]}
        assert st["serving_predict_p99"] == "firing"
        assert (sweep["history"]["points"]
                or sweep["per_target"]["r0"]["history"]["points"]
                is not None)

        # ...and in fleet_top --once --json (capsys drains the render).
        ftop = _tool("fleet_top")
        rc = ftop.main(["--targets", f"router={router.endpoint}",
                        "--once", "--json", "--alerts"])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0
        assert any(a["name"] == "serving_predict_p99"
                   and a["state"] == "firing" for a in out["alerts"])

        # Exactly one bundle, and the report names the objective.
        bundles = list_bundles(inc_dir)
        assert len(bundles) == 1
        irep = _tool("incident_report")
        assert irep.main([bundles[0]]) == 0
        rep = capsys.readouterr().out
        assert "alert:serving_predict_p99" in rep
        assert "serving/predict_ms" in rep

        # Recovery: clean windows slide the slow window clean, then the
        # clear_windows hysteresis resolves.
        states = []
        for _ in range(10):
            window()
            states.append(eng.state("serving_predict_p99"))
            if states[-1] == "resolved":
                break
        assert states[-1] == "resolved", states

        # Re-fire: rate limit holds the bundle count at exactly one.
        limited0 = monitor.GLOBAL.get("incident/rate_limited")
        window(bad=True)
        window(bad=True)
        assert eng.state("serving_predict_p99") == "firing"
        assert len(list_bundles(inc_dir)) == 1
        assert monitor.GLOBAL.get("incident/rate_limited") == limited0 + 1
    finally:
        incident.GLOBAL = prev_rec
        alerts.GLOBAL = prev_eng
        flags.set_flags(prev)
        cli.close()
        router.stop()
        for s in servers:
            s.stop()
        for p in preds:
            p.close()


# -- zero-device-cost pin -----------------------------------------------------


def test_health_plane_leaves_step_and_serving_forward_unchanged(tmp_path):
    """The jaxpr pin: sampler thread ticking + alert engine evaluating
    + incident capture armed (and one forced capture taken) change
    ZERO ops in the train step and the serving forward — the whole
    plane is host-side."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.data.slots import (DataFeedConfig, SlotBatch,
                                          SlotConf)
    from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.serving.batcher import pack_bucketed
    from paddlebox_tpu.serving.predictor import CTRPredictor
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig
    from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
    from paddlebox_tpu.utils import inspect as pbx_inspect

    slots = ("user", "item")
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in slots),
        batch_size=8)
    model = DeepFM(slot_names=slots, emb_dim=8, hidden=())

    def step_op_counts():
        mesh = build_mesh(HybridTopology(dp=4),
                          devices=jax.devices()[:4])
        tr = CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        tlines = [f"{i % 2} user:{3 + i} item:{4 + i}"
                  for i in range(8)]
        b = SlotBatch.pack_sharded(parse_lines(tlines, feed), feed, 4)
        tr.engine.feed_pass([
            np.unique(np.concatenate([b.ids[n] for n in g.slots]))
            for g in tr.engine.groups])
        step = tr._build_step()
        tables = tr.engine.begin_pass()
        rows = tr._map_batch_rows(b)
        segs = {n: jnp.asarray(b.segments[n]) for n in b.ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows,
                segs, jnp.asarray(b.labels), jnp.asarray(b.valid),
                jnp.asarray(_concat_dense_host(b)),
                jnp.zeros((), jnp.int32))
        return pbx_inspect.jaxpr_summary(lambda *a: step(*a), *args)

    def fwd_op_counts():
        rng = np.random.default_rng(0)
        keys = np.arange(1, 33, dtype=np.uint64)
        emb = rng.normal(size=(32, 8)).astype(np.float32)
        w = rng.normal(size=(32,)).astype(np.float32)
        pred = CTRPredictor(model, feed, keys, emb, w,
                            model.init(jax.random.PRNGKey(0)),
                            compute_dtype="float32")
        batch = pack_bucketed(
            parse_lines(["0 user:3 item:4", "1 user:5 item:6"], feed),
            feed)
        caps = {n: batch.ids[n].shape[0] for n in pred._slot_names}
        all_ids = np.concatenate(
            [batch.ids[n] for n in pred._slot_names])
        looked = pred._index.lookup(all_ids)
        rows = np.where(looked < 0, pred._table.shape[0] - 1,
                        looked).astype(np.int32)
        fwd = pred._build_fwd(caps, batch.batch_size, 0)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in pred._slot_names}
        return pbx_inspect.jaxpr_summary(
            lambda *a: fwd(*a), pred._table, pred._zero_miss,
            pred._dense_params, rows, segs,
            jnp.asarray(_concat_dense_host(batch)))

    step_off, fwd_off = step_op_counts(), fwd_op_counts()
    keys = ("history_interval_s", "alerts_enable", "incident_dir")
    prev = {k: flags.flag(k) for k in keys}
    flags.set_flags({"history_interval_s": 0.02,
                     "alerts_enable": True,
                     "incident_dir": str(tmp_path / "inc")})
    try:
        timeseries.init_from_flags()
        alerts.init_from_flags()
        assert timeseries.GLOBAL_SAMPLER.running
        assert alerts.enabled()
        assert incident.enabled()
        time.sleep(0.06)  # let the sampler tick while armed
        step_on, fwd_on = step_op_counts(), fwd_op_counts()
        assert incident.trigger("jaxpr_pin_probe", force=True)
        assert step_on == step_off, (step_on, step_off)
        assert fwd_on == fwd_off, (fwd_on, fwd_off)
    finally:
        alerts.shutdown()
        timeseries.GLOBAL_SAMPLER.stop()
        flags.set_flags(prev)
