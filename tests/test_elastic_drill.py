"""REAL-PROCESS elastic fault drill (VERDICT r04 task 7).

Two "hosts" (OS process trees) launched through the production elastic
launcher share a lease directory; one is SIGKILL'd (whole process group)
mid-day. The survivor's manager detects the dead lease, publishes a new
rank-table generation, its watcher restarts the worker at world=1, the
worker recovers the donefile chain and finishes the day. Final model
state must match an uninterrupted run — pass-exactly-once semantics make
the kill cost at most the in-flight pass.

Role of the reference's elastic stack: etcd lease expiry + watch
(``fleet/elastic/manager.py:236,443``), fault-tolerant rank reassignment
(:`manager.py:516`), the launch watcher restart, and recovery from the
model donefile.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_drill_worker.py")
DAY = "20260728"
SLOTS = ("user", "item")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_day(root, day, hours, rows_per_split=96):
    rng = np.random.default_rng(int(day))
    for h in hours:
        d = os.path.join(root, day, f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-00000"), "w") as f:
            for _ in range(rows_per_split):
                feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                         for s in SLOTS}
                click = np.mean([(int(v) % 5 == 0)
                                 for vs in feats.values() for v in vs])
                label = int(rng.random() < 0.1 + 0.8 * click)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")


def _spawn_host(host_id, elastic_dir, port, data, out, result, log_path, *,
                min_hosts=1, max_hosts=2, extra_env=None):
    env = dict(os.environ)
    env.update(extra_env or {})
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)    # worker pins its own 1-device flag
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Output goes to a FILE, not a pipe: nobody drains a pipe during the
    # multi-minute wait, and a full pipe buffer would wedge the host into
    # a spurious timeout. start_new_session: the host is a process GROUP
    # (launcher+worker) so the drill's SIGKILL takes out both — a dead
    # host must not leave an orphan worker still heartbeating through
    # checkpoint writes.
    logf = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddlebox_tpu.launch",
         "--elastic-dir", elastic_dir, "--host-id", host_id,
         "--min-hosts", str(min_hosts), "--max-hosts", str(max_hosts),
         "--coordinator", f"127.0.0.1:{port}",
         WORKER, data, out, result],
        env=env, cwd=REPO, start_new_session=True,
        stdout=logf, stderr=subprocess.STDOUT, text=True)
    proc._drill_log = log_path  # type: ignore[attr-defined]
    logf.close()  # child holds the fd
    return proc


def _log_tail(proc, n=3000) -> str:
    try:
        with open(proc._drill_log) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def _records(out_dir):
    from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
    return CheckpointProtocol(out_dir).records()


def _uninterrupted_reference(data, tmp_path) -> dict:
    """Same worker, solo world-1 run on a fresh out dir — the parity
    baseline for the drilled run's final state."""
    out = str(tmp_path / "ref_out")
    result = str(tmp_path / "ref.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PBX_COORDINATOR", None)
    env["PBX_NUM_PROCESSES"] = "1"
    env["PBX_PROCESS_ID"] = "0"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run([sys.executable, WORKER, data, out, result], env=env,
                   cwd=REPO, check=True, timeout=420)
    with open(result) as f:
        return json.load(f)


@pytest.mark.slow
def test_join_host_mid_day_scales_out_and_finishes(tmp_path):
    """Scale-OUT drill (VERDICT-r04 #5), the mirror of the kill drill:
    host A starts the day ALONE (world=1); mid-day a second host joins
    the shared lease dir. The leader publishes a new rank-table
    generation, BOTH watchers restart their workers at world=2, the day
    finishes, and the final state matches an uninterrupted run — the
    other half of the reference's elastic manager (join -> rerank ->
    resume, fleet/elastic/manager.py:443-516)."""
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    elastic = str(tmp_path / "elastic")
    result = str(tmp_path / "result.json")
    # Fatter passes than the kill drill: the join must land while passes
    # REMAIN — host A solo-finishing a short day before the rerank takes
    # effect is a legitimate outcome for the manager but proves nothing
    # about scale-out. Post-compile passes run ~3 s at 15000 rows
    # (batch 32, ~470 steps), so ~5 remaining passes outlast join +
    # settle + restart (~3 s) with an order of magnitude to spare.
    _write_day(data, DAY, range(6), rows_per_split=15000)
    os.makedirs(out, exist_ok=True)

    port = _free_port()
    host_a = _spawn_host("hostA", elastic, port, data, out, result,
                         str(tmp_path / "hostA.log"))
    host_b = None
    try:
        # Wait until training is underway (first delta published) BEFORE
        # the second host exists — the join must land mid-day.
        deadline = time.time() + 240
        while time.time() < deadline and not _records(out):
            if host_a.poll() is not None:
                pytest.fail("hostA exited before training started:\n"
                            + _log_tail(host_a))
            time.sleep(0.25)
        assert _records(out), "no checkpoint published within 240s"
        host_b = _spawn_host("hostB", elastic, port, data, out, result,
                             str(tmp_path / "hostB.log"))

        # Both hosts must finish the day in the scaled-out generation.
        rc_a = host_a.wait(timeout=420)
        assert rc_a == 0, f"hostA failed rc={rc_a}\n{_log_tail(host_a, 4000)}"
        rc_b = host_b.wait(timeout=120)
        assert rc_b == 0, f"hostB failed rc={rc_b}\n{_log_tail(host_b, 4000)}"
    finally:
        for h in (host_a, host_b):
            if h is None:
                continue
            try:
                os.killpg(os.getpgid(h.pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass

    with open(result) as f:
        final = json.load(f)
    # The finishing generation ran at world=2 after the join rerank.
    assert final["world"] == 2
    assert final["generation"] >= 1
    recs = _records(out)
    assert [(r.day, r.pass_id) for r in recs] == \
        [(DAY, p) for p in range(1, 7)] + [(DAY, 0)]

    # Loss parity with an uninterrupted solo run: world 2 vs 1 is
    # numerically equivalent (test_multiprocess) and the scaled-out
    # generation resumes from the last published delta, so every pass it
    # trained must match the same-numbered pass of the solo run. The
    # result carries only the finishing generation's passes — compare
    # the overlap.
    ref = _uninterrupted_reference(data, tmp_path)
    assert ref["trained_passes"] == 6
    trained = final["losses"]
    assert len(trained) >= 1  # the join left at least one pass to train
    np.testing.assert_allclose(trained, ref["losses"][-len(trained):],
                               rtol=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("multihost", [False, True],
                         ids=["flat", "multihost"])
def test_kill_worker_mid_day_recovers_and_finishes(tmp_path, multihost):
    """``multihost``: the same kill drill with the trainer backed by
    the 2-shard multi-host tier (PBX_MULTIHOST_WORLD — every elastic
    generation rebuilds its loopback cluster and recovers it from the
    shared donefile chain); loss parity against the flat single-host
    reference run pins the tier end to end under real SIGKILL."""
    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    elastic = str(tmp_path / "elastic")
    result = str(tmp_path / "result.json")
    _write_day(data, DAY, range(6))
    os.makedirs(out, exist_ok=True)
    extra_env = {"PBX_MULTIHOST_WORLD": "2"} if multihost else None

    port = _free_port()
    host_a = _spawn_host("hostA", elastic, port, data, out, result,
                         str(tmp_path / "hostA.log"),
                         extra_env=extra_env)
    host_b = _spawn_host("hostB", elastic, port, data, out, result,
                         str(tmp_path / "hostB.log"),
                         extra_env=extra_env)
    killed = False
    try:
        # Wait until training is underway (first delta published), then
        # SIGKILL host B's whole process group mid-day.
        deadline = time.time() + 240
        while time.time() < deadline and not _records(out):
            if host_a.poll() is not None:
                pytest.fail("hostA exited before training started:\n"
                            + _log_tail(host_a))
            time.sleep(0.5)
        assert _records(out), "no checkpoint published within 240s"
        os.killpg(os.getpgid(host_b.pid), signal.SIGKILL)
        killed = True

        # Survivor must detect the dead lease, rerank to world=1,
        # restart its worker, recover, and finish the day.
        rc = host_a.wait(timeout=420)
        assert rc == 0, f"hostA failed rc={rc}\n{_log_tail(host_a, 4000)}"
    finally:
        for h in (host_a, host_b):
            try:
                if not (killed and h is host_b):
                    os.killpg(os.getpgid(h.pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass

    with open(result) as f:
        final = json.load(f)
    # The finishing generation ran solo after the rerank.
    assert final["world"] == 1
    assert final["generation"] >= 1
    # Donefile chain is complete: 6 per-pass deltas + the day base, each
    # pass exactly once (recovery skipped finished passes, re-trained
    # only the in-flight one).
    recs = _records(out)
    assert [(r.day, r.pass_id) for r in recs] == \
        [(DAY, p) for p in range(1, 7)] + [(DAY, 0)]

    # Loss parity with an uninterrupted run: pass state depends only on
    # (prior checkpoint, pass data), so the kill must not change the
    # final passes' losses (world 2 vs 1 is numerically equivalent —
    # proven by test_multiprocess — and the killed pass re-trains from
    # the last checkpoint).
    ref = _uninterrupted_reference(data, tmp_path)
    assert ref["trained_passes"] == 6
    np.testing.assert_allclose(final["losses"][-2:], ref["losses"][-2:],
                               rtol=1e-4)
