"""AUC-runner slot-replacement eval: an informative slot must rank above
a pure-noise slot, and eval passes must leave the store untouched.

Role of box_wrapper.h:900-989 (AUC-runner mode) + SlotsShuffle.
"""

import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import (CTRTrainer, TrainerConfig,
                                 slot_replacement_eval)

SLOTS = ("signal", "noise")


def _shard(path, n=512, seed=0):
    """Label driven ONLY by the 'signal' slot; 'noise' is random."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            sig = rng.integers(1, 100)
            noi = rng.integers(1, 100)
            label = int(rng.random() < (0.85 if sig % 3 == 0 else 0.1))
            f.write(f"{label} signal:{sig} noise:{noi}\n")
    return str(path)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    d = tmp_path_factory.mktemp("aucr")
    shard = _shard(d / "part-0")
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    t = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                   feed, TableConfig(dim=8, learning_rate=0.2), mesh=mesh,
                   config=TrainerConfig(dense_learning_rate=3e-3,
                                        auc_num_buckets=1 << 10))
    t.init(seed=0)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([shard])
    ds.load_into_memory()
    for p in range(4):
        t.reset_metrics()
        ds.local_shuffle(seed=p)
        t.train_pass(ds)
    return t, ds


def test_eval_pass_is_read_only(trained):
    t, ds = trained
    n = t.engine.store.num_features
    dirty_before = np.sort(t.engine.store.dirty_keys())
    stats = t.eval_pass(ds)
    assert np.isfinite(stats["loss"])
    assert stats["auc"] > 0.7  # trained model evaluates well
    assert t.engine.store.num_features == n
    np.testing.assert_array_equal(
        np.sort(t.engine.store.dirty_keys()), dirty_before)


def test_slot_importance_ranks_signal_over_noise(trained):
    t, ds = trained
    report = slot_replacement_eval(t, ds, seed=1)
    assert report["ranking"][0] == "signal", report
    drop_sig = report["slots"]["signal"]["auc_drop"]
    drop_noi = report["slots"]["noise"]["auc_drop"]
    assert drop_sig > 0.1, report  # shuffling signal destroys the model
    assert drop_sig > drop_noi + 0.05, report
    # dataset restored: baseline eval reproduces
    again = t.eval_pass(ds)
    assert np.isclose(again["auc"], report["base_auc"], rtol=1e-5)
