"""ShardedFeatureStore: parity with the flat store, bucket locality (no
whole-store re-sort per pass), checkpoint round-trip + flat migration.

Role of the reference's 16-way sharded pass build (PreBuildTask,
ps_gpu_wrapper.cc:114) and sharded CPU PS tables.
"""

import time

import numpy as np
import pytest

from paddlebox_tpu.embedding import (FeatureStore, ShardedFeatureStore,
                                     TableConfig)
from paddlebox_tpu.embedding.sharded_store import _bucket_of

CFG = TableConfig(name="emb", dim=4, learning_rate=0.1)


def _rand_vals(store, keys):
    """Pull (materializes deterministic inits) then perturb."""
    vals = store.pull_for_pass(keys)
    vals["emb"] = vals["emb"] + 1.0
    vals["show"] = vals["show"] + 2.0
    return vals


def test_parity_with_flat_store():
    rng = np.random.default_rng(0)
    flat = FeatureStore(CFG, seed=0)
    shard = ShardedFeatureStore(CFG, num_buckets=8, seed=0)

    for step in range(4):
        keys = np.unique(rng.choice(
            np.arange(1, 5000, dtype=np.uint64), 600))
        va = flat.pull_for_pass(keys)
        vb = shard.pull_for_pass(keys)
        for f in va:
            np.testing.assert_allclose(vb[f], va[f], rtol=1e-6,
                                       err_msg=f"{f} step {step}")
        upd = {f: v + (1.0 if v.dtype == np.float32 else 0) for f, v in
               va.items()}
        flat.push_from_pass(keys, upd)
        shard.push_from_pass(keys, upd)
        assert flat.num_features == shard.num_features

    assert np.array_equal(np.sort(flat.dirty_keys()),
                          np.sort(shard.dirty_keys()))
    assert flat.shrink(min_show=0.5) == shard.shrink(min_show=0.5)
    assert flat.num_features == shard.num_features


def test_push_touches_only_owning_buckets():
    """The point of sharding: a pass write-back must merge only the
    buckets its keys hash into — never re-sort the whole store."""
    shard = ShardedFeatureStore(CFG, num_buckets=16, seed=0)
    all_keys = np.arange(1, 20001, dtype=np.uint64)
    shard.push_from_pass(all_keys, shard.pull_for_pass(all_keys))

    # Choose keys from exactly one bucket.
    target = 5
    one_bucket = all_keys[_bucket_of(all_keys, 16) == target][:50]
    assert one_bucket.size == 50

    calls = []
    for i, b in enumerate(shard._buckets):
        orig = b.push_from_pass

        def spy(keys, values, _i=i, _orig=orig):
            calls.append(_i)
            return _orig(keys, values)

        b.push_from_pass = spy
    shard.push_from_pass(one_bucket, shard.pull_for_pass(one_bucket))
    assert set(calls) == {target}


def test_incremental_push_much_cheaper_than_rebuild():
    """Writing a small delta into a large store must not scale with the
    store size (the flat store's O(N log N) full re-sort). Generous 5x
    margin over the initial build per-key cost."""
    shard = ShardedFeatureStore(CFG, num_buckets=32, seed=0)
    n = 2_000_000
    keys = np.arange(1, n + 1, dtype=np.uint64)
    t0 = time.perf_counter()
    shard.push_from_pass(keys, shard.pull_for_pass(keys))
    t_build = time.perf_counter() - t0

    # Median of 3 distinct 10k-key deltas: a single GC pause or CI load
    # spike during one push must not fail the ratio.
    times = []
    for r in range(3):
        lo = n + 1 + r * 10_000
        small = np.arange(lo, lo + 10_000, dtype=np.uint64)
        vals = shard.pull_for_pass(small)
        t0 = time.perf_counter()
        shard.push_from_pass(small, vals)
        times.append(time.perf_counter() - t0)
    t_small = sorted(times)[1]
    # A 10k-key delta must cost far less than rebuilding the 2M-key
    # store (linear per-bucket merges, no store-wide re-sort). Generous
    # 10x margin keeps this stable on loaded CI hosts.
    assert t_small < t_build / 10 + 0.05, (
        f"small push {t_small:.3f}s vs build {t_build:.3f}s for {n} keys")


def test_checkpoint_roundtrip_and_flat_migration(tmp_path):
    rng = np.random.default_rng(1)
    shard = ShardedFeatureStore(CFG, num_buckets=8, seed=0)
    keys = np.unique(rng.choice(np.arange(1, 9999, dtype=np.uint64), 500))
    shard.push_from_pass(keys, _rand_vals(shard, keys))

    base = str(tmp_path / "base")
    shard.save_base(base)
    fresh = ShardedFeatureStore(CFG, num_buckets=8, seed=0)
    fresh.load(base, "base")
    assert fresh.num_features == shard.num_features
    va = shard.pull_for_pass(keys)
    vb = fresh.pull_for_pass(keys)
    np.testing.assert_allclose(vb["emb"], va["emb"], rtol=1e-6)

    #

    # delta applies on top
    more = np.arange(20000, 20050, dtype=np.uint64)
    shard.push_from_pass(more, _rand_vals(shard, more))
    delta = str(tmp_path / "delta")
    shard.save_delta(delta)
    fresh.load(delta, "delta")
    assert fresh.num_features == shard.num_features

    # flat FeatureStore base migrates into a sharded store
    flat = FeatureStore(CFG, seed=0)
    flat.push_from_pass(keys, _rand_vals(flat, keys))
    flat_base = str(tmp_path / "flat")
    flat.save_base(flat_base)
    migrated = ShardedFeatureStore(CFG, num_buckets=8, seed=0)
    migrated.load(flat_base, "base")
    assert migrated.num_features == flat.num_features
    vm = migrated.pull_for_pass(keys)
    vf = flat.pull_for_pass(keys)
    np.testing.assert_allclose(vm["emb"], vf["emb"], rtol=1e-6)
    # base-load semantics: migration leaves a clean delta set
    assert migrated.dirty_keys().size == 0


def test_bucket_count_mismatch_rejected(tmp_path):
    shard = ShardedFeatureStore(CFG, num_buckets=8, seed=0)
    keys = np.arange(1, 100, dtype=np.uint64)
    shard.push_from_pass(keys, shard.pull_for_pass(keys))
    base = str(tmp_path / "b")
    shard.save_base(base)
    other = ShardedFeatureStore(CFG, num_buckets=16, seed=0)
    with pytest.raises(ValueError, match="buckets"):
        other.load(base, "base")


def test_pop_rows_and_coldness():
    shard = ShardedFeatureStore(CFG, num_buckets=4, seed=0)
    keys = np.arange(1, 101, dtype=np.uint64)
    vals = shard.pull_for_pass(keys)
    vals["show"] = np.arange(100, dtype=np.float32)[::-1].copy()
    shard.push_from_pass(keys, vals)
    cold = shard.rows_by_coldness()
    # coldest-first: show values ascending along the returned keys
    shows = shard.pull_for_pass(np.sort(cold[:10]))["show"]
    assert shows.max() <= 9.5
    popped_keys, popped = shard.pop_rows(keys[:10])
    assert popped_keys.size == 10
    assert shard.num_features == 90
