"""Quantized xbox serving exports (FLAGS_xbox_quant_bits): artifact
shrinks, loader dequantizes transparently, error is bounded by the
per-row scale, predictor serves from it, and the tiered store exports
across both tiers."""

import os

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.serving import load_xbox_model


@pytest.fixture(autouse=True)
def _reset_flag():
    yield
    flagmod.set_flags({"xbox_quant_bits": 0})


def _filled_store(n=500, dim=8):
    store = FeatureStore(TableConfig(name="emb", dim=dim,
                                     learning_rate=0.1))
    keys = np.arange(1, n + 1, dtype=np.uint64)
    vals = store.pull_for_pass(keys)
    rng = np.random.default_rng(0)
    vals["emb"] = rng.normal(0, 0.3, vals["emb"].shape).astype(np.float32)
    store.push_from_pass(keys, vals)
    return store, keys, vals


@pytest.mark.parametrize("bits", [8, 16])
def test_quantized_export_roundtrip_bounded_error(tmp_path, bits):
    store, keys, vals = _filled_store()
    store.save_xbox(str(tmp_path / "f32"))
    flagmod.set_flags({"xbox_quant_bits": bits})
    store.save_xbox(str(tmp_path / "q"))

    k, e, w = load_xbox_model(str(tmp_path / "q"), table="emb")
    assert np.array_equal(k, keys)
    np.testing.assert_array_equal(w, vals["w"])
    # Per-row error bound: half a quantization step.
    qmax = (1 << (bits - 1)) - 1
    bound = np.abs(vals["emb"]).max(axis=1) / qmax / 2 + 1e-7
    err = np.abs(e - vals["emb"]).max(axis=1)
    assert (err <= bound).all()

    size_f = os.path.getsize(tmp_path / "f32" / "emb.xbox.npz")
    size_q = os.path.getsize(tmp_path / "q" / "emb.xbox.npz")
    assert size_q < size_f * (0.45 if bits == 8 else 0.75), \
        (size_q, size_f)


def test_quantized_export_serves(tmp_path):
    import jax

    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.serving import CTRPredictor

    store, keys, vals = _filled_store(dim=4)
    flagmod.set_flags({"xbox_quant_bits": 8})
    store.save_xbox(str(tmp_path))
    k, e, w = load_xbox_model(str(tmp_path), table="emb")
    feed = DataFeedConfig(slots=(SlotConf("u", avg_len=1.0),
                                 SlotConf("i", avg_len=1.0)),
                          batch_size=8)
    model = DeepFM(slot_names=("u", "i"), emb_dim=4, hidden=(8,))
    pred = CTRPredictor(model, feed, k, e, w,
                        model.init(jax.random.PRNGKey(0)),
                        compute_dtype="float32")
    from paddlebox_tpu.data.dataset import Dataset
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "part")
        rng = np.random.default_rng(1)
        with open(p, "w") as f:
            for _ in range(8):
                f.write(f"0 u:{rng.integers(1, 500)} "
                        f"i:{rng.integers(1, 500)}\n")
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        probs = pred.predict(next(ds.batches_sharded(1)))
    assert np.isfinite(probs).all()


def test_tiered_store_xbox_covers_both_tiers(tmp_path):
    cfg = TableConfig(name="emb", dim=4, learning_rate=0.1)
    store = TieredFeatureStore(cfg, str(tmp_path / "ssd"),
                               max_ram_features=100)
    keys = np.arange(1, 401, dtype=np.uint64)
    vals = store.pull_for_pass(keys)
    store.push_from_pass(keys, vals)      # evicts past 100
    assert store.disk.num_features > 0
    n = store.save_xbox(str(tmp_path / "out"))
    assert n == 400
    k, e, w = load_xbox_model(str(tmp_path / "out"), table="emb")
    assert np.array_equal(k, keys)        # sorted, both tiers
    # Values must match the store's own view regardless of tier.
    pulled = store.pull_for_pass(keys)
    np.testing.assert_allclose(e, pulled["emb"], atol=1e-6)
