"""1F1B wired into the production paths (VERDICT r02 task 5):
- make_gpt_train_step(schedule="1f1b") parity vs the GPipe path
- PipelineTrainer with TrainerDesc.pipeline_schedule="1f1b" parity
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.models.gpt import GPTConfig, init_gpt, make_gpt_train_step
from paddlebox_tpu.parallel import HybridTopology, build_mesh, pp
from paddlebox_tpu.train.trainer import PipelineTrainer, TrainerDesc

CFG = GPTConfig(vocab_size=64, d_model=16, n_heads=2, n_layers=4, d_ff=32,
                max_seq_len=64, attention="ring")


@pytest.fixture
def devices8():
    d = jax.devices()
    assert len(d) >= 8
    return d[:8]


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, CFG.vocab_size, (8, 32)).astype(np.int32)
    targets = rng.integers(0, CFG.vocab_size, (8, 32)).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


@pytest.mark.parametrize("topo", [
    dict(dp=2, pp=2, sp=1, mp=2),
    # The alternate topologies pin the same parity property; they live
    # in the slow tier so tier-1 carries one compile of each schedule.
    pytest.param(dict(dp=1, pp=2, sp=2, mp=2), marks=pytest.mark.slow),
    pytest.param(dict(pp=4, dp=2), marks=pytest.mark.slow),
])
def test_gpt_1f1b_matches_gpipe(devices8, data, topo):
    """Same params/data: one 1F1B step produces the same loss and the
    same updated params as one GPipe step (both are exact schedules of
    the identical objective)."""
    mesh = build_mesh(HybridTopology(**topo), devices8)
    pp_stages = topo.get("pp", 1)
    tokens, targets = data
    out = {}
    for schedule in ("gpipe", "1f1b"):
        params, specs = init_gpt(jax.random.PRNGKey(0), CFG,
                                 pp_stages=pp_stages)
        opt = optax.sgd(1e-2)
        opt_state = opt.init(params)
        step = make_gpt_train_step(CFG, mesh, specs, opt,
                                   num_microbatches=4, schedule=schedule)
        p2, _, loss = step(params, opt_state, tokens, targets)
        out[schedule] = (float(loss), jax.device_get(p2))
    np.testing.assert_allclose(out["1f1b"][0], out["gpipe"][0], rtol=2e-5)
    ga, gb = out["gpipe"][1], out["1f1b"][1]
    for path, a in jax.tree_util.tree_leaves_with_path(ga):
        b = a  # placeholder; compare via tree below
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4,
                                                atol=2e-6),
        ga, gb)


def test_gpt_1f1b_learns(devices8, data):
    mesh = build_mesh(HybridTopology(dp=2, pp=2, sp=1, mp=2), devices8)
    params, specs = init_gpt(jax.random.PRNGKey(1), CFG, pp_stages=2)
    tokens, targets = data
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = make_gpt_train_step(CFG, mesh, specs, opt, num_microbatches=4,
                               schedule="1f1b")
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def _make_pipeline_trainer(schedule):
    rng = np.random.default_rng(0)
    dim = 8
    stage_params = [
        {"w": jnp.asarray(rng.normal(0, 0.5, (dim, dim)), jnp.float32)}
        for _ in range(8)]
    stacked = pp.stack_stage_params(stage_params)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    def loss_head(y, batch):
        return jnp.mean((jnp.sum(y, -1) - batch["y"]) ** 2)

    t = PipelineTrainer(stage_fn, stacked, loss_head, optax.sgd(3e-3))
    t.initialize(TrainerDesc(num_micro_batches=8, log_every=0,
                             pipeline_schedule=schedule))
    return t


def test_pipeline_trainer_1f1b_matches_gpipe(devices8):
    mesh = build_mesh(HybridTopology(pp=8))
    rng = np.random.default_rng(1)
    batches = []
    for _ in range(4):
        x = rng.normal(0, 1, (16, 8)).astype(np.float32)
        batches.append({"x": jnp.asarray(x),
                        "y": jnp.asarray(np.sin(x.sum(-1)))})
    results = {}
    for schedule in ("gpipe", "1f1b"):
        t = _make_pipeline_trainer(schedule)
        t.init_trainer_env(mesh)
        stats = t.run(iter(batches))
        results[schedule] = (stats, jax.device_get(t.params))
    sa, sb = results["gpipe"][0], results["1f1b"][0]
    np.testing.assert_allclose(sb["loss_first"], sa["loss_first"],
                               rtol=2e-5)
    np.testing.assert_allclose(sb["loss_last"], sa["loss_last"], rtol=2e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4,
                                                atol=2e-6),
        results["gpipe"][1], results["1f1b"][1])
