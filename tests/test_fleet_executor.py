"""FleetExecutor interceptor-runtime tests: linear pipeline ordering,
diamond-join DAG, amplifier fan-out, cross-carrier routing over a shared
bus, backpressure bounds, and error propagation."""

import threading
import time

import pytest

from paddlebox_tpu.distributed.fleet_executor import (Carrier, MessageBus,
                                                      TaskNode,
                                                      linear_pipeline)


def test_linear_pipeline_order_and_values():
    nodes = linear_pipeline([lambda x: x + 1, lambda x: x * 2])
    c = Carrier(nodes)
    out = c.run(8, feeds=list(range(8)))
    assert out == [(i + 1) * 2 for i in range(8)]


def test_pipeline_overlaps_stages():
    """Stage threads run concurrently: total wall-time of N microbatches
    through two 10ms stages must be far below serial N*2*10ms."""
    def slow(x):
        time.sleep(0.01)
        return x

    nodes = linear_pipeline([slow, slow, slow])
    c = Carrier(nodes)
    t0 = time.time()
    out = c.run(20, feeds=list(range(20)))
    elapsed = time.time() - t0
    assert out == list(range(20))
    assert elapsed < 0.45  # serial would be 20*3*0.01 = 0.6s + overhead


def test_diamond_join():
    #        1 (x+1)
    # 0 src <         > 3 (sum) -> 4 sink
    #        2 (x*10)
    nodes = [
        TaskNode(0, role="source", downstream=(1, 2)),
        TaskNode(1, fn=lambda x: x + 1, upstream=(0,), downstream=(3,)),
        TaskNode(2, fn=lambda x: x * 10, upstream=(0,), downstream=(3,)),
        TaskNode(3, fn=lambda pair: pair[0] + pair[1], upstream=(1, 2),
                 downstream=(4,)),
        TaskNode(4, role="sink", upstream=(3,)),
    ]
    c = Carrier(nodes)
    out = c.run(5, feeds=[1, 2, 3, 4, 5])
    assert out == [x + 1 + 10 * x for x in [1, 2, 3, 4, 5]]


def test_amplifier_fanout():
    nodes = [
        TaskNode(0, role="source", downstream=(1,)),
        TaskNode(1, role="amplifier", factor=3, upstream=(0,),
                 downstream=(2,)),
        TaskNode(2, fn=lambda x: x, upstream=(1,), downstream=(3,)),
        TaskNode(3, role="sink", upstream=(2,)),
    ]
    c = Carrier(nodes)
    out = c.run(2, feeds=["a", "b"])
    assert out == ["a", "a", "a", "b", "b", "b"]


def test_cross_carrier_routing():
    """Middle stage lives on another carrier; messages hop 0 -> 1 -> 0
    through the shared bus (role of the brpc MessageBus crossing nodes)."""
    nodes = [
        TaskNode(0, role="source", downstream=(1,), rank=0),
        TaskNode(1, fn=lambda x: x * x, upstream=(0,), downstream=(2,),
                 rank=1),
        TaskNode(2, role="sink", upstream=(1,), rank=0),
    ]
    bus = MessageBus()
    c0 = Carrier(nodes, rank=0, bus=bus)
    c1 = Carrier(nodes, rank=1, bus=bus)
    out = c0.run(6, feeds=[1, 2, 3, 4, 5, 6])
    assert out == [1, 4, 9, 16, 25, 36]
    c1.shutdown()


def test_error_propagates():
    def boom(x):
        if x == 3:
            raise ValueError("bad microbatch")
        return x

    nodes = linear_pipeline([boom])
    c = Carrier(nodes)
    with pytest.raises(RuntimeError):
        c.run(8, feeds=list(range(8)))


def test_error_does_not_hang_with_deep_feed():
    """More microbatches than total queue capacity: after the first-stage
    error the feeder is blocked on a full inbox; abort must still unwedge
    run() promptly (regression for the feeder-join hang)."""
    def boom(x):
        raise ValueError("always")

    nodes = linear_pipeline([boom], buffer_size=2)
    c = Carrier(nodes)
    t0 = time.time()
    with pytest.raises(RuntimeError):
        c.run(64, feeds=list(range(64)), timeout=30.0)
    assert time.time() - t0 < 5.0


def test_many_back_to_back_runs_no_stop_straggler():
    """Rapid consecutive runs: a straggler STOP from run N must never
    leak into run N+1's fresh interceptors (run drains the STOP cascade
    before returning)."""
    nodes = linear_pipeline([lambda x: x + 1, lambda x: x * 2],
                            buffer_size=2)
    c = Carrier(nodes)
    for r in range(20):
        out = c.run(5, feeds=[r * 10 + i for i in range(5)])
        assert out == [(r * 10 + i + 1) * 2 for i in range(5)]


def test_carrier_reusable_across_runs():
    nodes = linear_pipeline([lambda x: x + 1])
    c = Carrier(nodes)
    assert c.run(4, feeds=[0, 1, 2, 3]) == [1, 2, 3, 4]
    assert c.run(4, feeds=[10, 11, 12, 13]) == [11, 12, 13, 14]
    # reusable after an error too
    def boom(x):
        raise ValueError()
    c2 = Carrier(linear_pipeline([boom]))
    with pytest.raises(RuntimeError):
        c2.run(4, feeds=list(range(4)))
    c2.nodes[1].fn = lambda x: x * 3
    c2.reset()
    assert c2.run(2, feeds=[1, 2]) == [3, 6]


def test_backpressure_bounded_inbox():
    """A slow consumer bounds the producer: the fast stage cannot run
    more than buffer_size ahead."""
    seen = []
    gate = threading.Event()

    def fast(x):
        seen.append(x)
        return x

    def slow(x):
        gate.wait(2.0)
        return x

    nodes = linear_pipeline([fast, slow], buffer_size=2)
    c = Carrier(nodes)
    t = threading.Thread(target=lambda: c.run(12, feeds=list(range(12))),
                         daemon=True)
    t.start()
    time.sleep(0.3)
    # fast stage blocked: at most buffer(2) in slow inbox + 1 in flight +
    # a couple queued at fast itself
    assert len(seen) <= 6
    gate.set()
    t.join(5.0)
    assert len(seen) == 12
