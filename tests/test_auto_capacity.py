"""Measured bucket auto-capacity (FLAGS_embedding_auto_capacity).

With dedup, a bucket cell holds a unique id — so the right capacity is
the data's actual per-shard unique-id maximum, not the occurrence-based
binomial bound. The flag measures it from each pass's first batch
(pow2-bucketed for compile stability). These tests pin: the exchange
shrinks on duplicate-heavy data, results are IDENTICAL to the default
capacity (capacity is padding, never math), nothing overflows, and
steady-state passes reuse the compiled step.
"""

import numpy as np

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = tuple(f"s{i}" for i in range(6))


def _write_data(tmp_path, n_lines=1024, n_keys=40):
    # Heavy duplication: 6 slots drawing from only 40 keys — every
    # batch's unique count is a small fraction of its occurrences.
    rng = np.random.default_rng(5)
    p = str(tmp_path / "part")
    with open(p, "w") as f:
        for _ in range(n_lines):
            ks = rng.integers(1, n_keys + 1, len(SLOTS))
            label = int((int(ks[0]) % 2) == (rng.random() < 0.8))
            f.write(f"{label} " + " ".join(
                f"{s}:{k}" for s, k in zip(SLOTS, ks)) + "\n")
    return p


def _run(tmp_path, p, auto):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=128)
    tr = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(auc_num_buckets=1 << 10),
        store_factory=lambda c: DeviceFeatureStore(c, mesh=mesh))
    tr.init(seed=0)
    prev = flagmod.flag("embedding_auto_capacity")
    flagmod.set_flags({"embedding_auto_capacity": auto})
    try:
        stats = []
        for _ in range(2):
            ds = Dataset(feed, num_reader_threads=1)
            ds.set_filelist([p])
            ds.load_into_memory()
            stats.append(tr.train_pass(ds))
        return tr, stats
    finally:
        flagmod.set_flags({"embedding_auto_capacity": prev})


def test_auto_capacity_shrinks_exchange_identically(tmp_path):
    p = _write_data(tmp_path)
    tr_def, stats_def = _run(tmp_path, p, auto=False)
    tr_auto, stats_auto = _run(tmp_path, p, auto=True)

    for s in stats_def + stats_auto:
        assert s["lookup_overflow"] == 0
    # The measured capacity strictly shrinks the all-to-all...
    assert (stats_auto[0]["lookup_exchange_bytes"]
            < stats_def[0]["lookup_exchange_bytes"])
    # ...while capacity stays pure padding: identical training results.
    for sd, sa in zip(stats_def, stats_auto):
        np.testing.assert_allclose(sa["loss"], sd["loss"], rtol=1e-6)
        np.testing.assert_allclose(sa["auc"], sd["auc"], rtol=1e-6)

    # Steady state: the second pass re-measures into the SAME pow2
    # bucket, so the compiled step is reused (no rebuild).
    assert tr_auto._step_caps is not None
    step_obj = tr_auto._step_fn
    ds = Dataset(DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=128), num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    prev = flagmod.flag("embedding_auto_capacity")
    flagmod.set_flags({"embedding_auto_capacity": True})
    try:
        tr_auto.train_pass(ds)
    finally:
        flagmod.set_flags({"embedding_auto_capacity": prev})
    assert tr_auto._step_fn is step_obj


def test_auto_capacity_sizes_occurrences_when_dedup_off(tmp_path):
    """With dedup off a bucket cell is consumed per OCCURRENCE — the
    measurement must count occurrences, or duplicate-heavy data would
    undersize every bucket by the duplication factor and silently drop
    grads (counted, but dropped)."""
    p = _write_data(tmp_path, n_lines=512)
    prev = flagmod.flag("embedding_dedup")
    flagmod.set_flags({"embedding_dedup": False})
    try:
        tr, stats = _run(tmp_path, p, auto=True)
        for s in stats:
            assert s["lookup_overflow"] == 0
        assert tr._step_caps is not None
    finally:
        flagmod.set_flags({"embedding_dedup": prev})


def test_auto_capacity_off_restores_default_step(tmp_path):
    p = _write_data(tmp_path, n_lines=256)
    tr, _ = _run(tmp_path, p, auto=True)
    assert tr._step_caps is not None
    # Next pass with the flag off must rebuild at default capacity.
    ds = Dataset(DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=128), num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    stats = tr.train_pass(ds)
    assert tr._step_caps is None
    assert stats["lookup_overflow"] == 0
