"""Mesh topology + collectives tests on the virtual 8-device CPU mesh.

Role of the reference's topology tests (HybridCommunicateGroup axis carving,
``fleet/base/topology.py``) and collective op tests, run single-process.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel import (HybridTopology, build_mesh, collective)


def test_world_size_mismatch_raises():
    with pytest.raises(ValueError):
        build_mesh(HybridTopology(dp=3))  # 8 devices available


def test_build_hybrid_mesh(devices8):
    topo = HybridTopology(dp=2, pp=1, sp=1, mp=4)
    mesh = build_mesh(topo, devices8)
    assert mesh.shape == {"slice": 1, "dp": 2, "sharding": 1, "pp": 1,
                          "sp": 1, "ep": 1, "mp": 4}
    assert mesh.devices.size == 8


def test_collectives_under_shard_map(devices8):
    mesh = build_mesh(HybridTopology(dp=4, mp=2), devices8)

    def f(x):
        s = collective.all_reduce_sum(x, "dp")
        g = collective.all_gather(x, "mp", gather_dim=0)
        return s, g

    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    fm = jax.shard_map(f, mesh=mesh, in_specs=P(("dp", "mp")),
                       out_specs=(P(("dp", "mp")), P("dp")),
                       check_vma=False)
    s, g = fm(x)
    # all_reduce over dp sums 4 shards; shape preserved.
    assert s.shape == (8, 4)
    # all_gather over mp rebuilds mp-dim: each dp shard has its 2 mp shards.
    assert g.shape == (8, 4)


def test_reduce_scatter_matches_allreduce_slice(devices8):
    # Each rank holds a full gradient (replicated input); reduce-scatter
    # sums across dp and leaves each rank owning a 1/8 slice — the ZeRO /
    # BoxPS dense-sync building block (boxps_worker.cc:584).
    mesh = build_mesh(HybridTopology(dp=8), devices8)
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)

    def rs(x):
        return collective.reduce_scatter_sum(x, "dp", scatter_dim=0)

    out = jax.shard_map(rs, mesh=mesh, in_specs=P(), out_specs=P("dp"),
                        check_vma=False)(x)
    # 8 identical copies summed, rank i keeps row-slice i → 8*x reassembled.
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 8, rtol=1e-6)


def test_ppermute_ring_shift(devices8):
    mesh = build_mesh(HybridTopology(pp=8), devices8)
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    def f(x):
        return collective.ppermute_shift(x, "pp", shift=1)

    out = jax.shard_map(f, mesh=mesh, in_specs=P("pp"), out_specs=P("pp"))(x)
    np.testing.assert_array_equal(
        np.asarray(out).ravel(), np.roll(np.arange(8), 1))


def test_all_to_all(devices8):
    mesh = build_mesh(HybridTopology(mp=8), devices8)
    # Each rank holds [8, 2]: row j goes to rank j.
    x = jnp.arange(8 * 8 * 2, dtype=jnp.float32).reshape(64, 2)

    def f(x):
        return collective.all_to_all(x, "mp", split_dim=0, concat_dim=0)

    out = jax.shard_map(f, mesh=mesh, in_specs=P("mp"), out_specs=P("mp"))(x)
    assert out.shape == (64, 2)
    ref = np.asarray(x).reshape(8, 8, 2).transpose(1, 0, 2).reshape(64, 2)
    np.testing.assert_array_equal(np.asarray(out), ref)
