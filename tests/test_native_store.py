"""Native store engine (native/store.cc via native/store_py.py): the
incremental key index + sorted-store primitives, vs their numpy twins.

Role parity target: the reference's C++ PreBuildTask/BuildPull host loops
(ps_gpu_wrapper.cc:114,362) — VERDICT r02 task 3 (store build throughput).
"""

import time

import numpy as np
import pytest

from paddlebox_tpu.embedding.store import _per_key_uniform
from paddlebox_tpu.native import store_py as sp
from paddlebox_tpu.native.build import native_available


def test_key_index_upsert_lookup_order():
    idx = sp.KeyIndex()
    rows, n_new = idx.upsert(np.array([5, 3, 5, 0, 9], np.uint64))
    assert rows.tolist() == [0, 1, 0, -1, 2]
    assert n_new == 3 and idx.size == 3
    # Existing keys keep their rows; new ones append in order.
    rows2, n_new2 = idx.upsert(np.array([9, 7, 3], np.uint64))
    assert rows2.tolist() == [2, 3, 1] and n_new2 == 1
    assert idx.lookup(np.array([7, 8, 0], np.uint64)).tolist() == [3, -1, -1]
    assert idx.keys_by_row().tolist() == [5, 3, 9, 7]
    idx.close()
    with pytest.raises(RuntimeError):
        idx.lookup(np.array([5], np.uint64))


def test_key_index_reserve_and_growth():
    idx = sp.KeyIndex()
    idx.reserve(300_000)
    keys = np.random.default_rng(0).permutation(
        np.arange(1, 300_001)).astype(np.uint64)
    rows, n_new = idx.upsert(keys)
    assert n_new == 300_000
    assert (rows == np.arange(300_000)).all()
    back = idx.lookup(keys[::7])
    assert (back == rows[::7]).all()
    assert (idx.keys_by_row() == keys).all()


def test_ss_locate_matches_numpy():
    rng = np.random.default_rng(1)
    s = np.sort(rng.choice(np.arange(1, 100_000, dtype=np.uint64),
                           10_000, replace=False))
    q = rng.integers(0, 100_000, 5_000).astype(np.uint64)
    f, p = sp.ss_locate(s, q)
    pos = np.searchsorted(s, q)
    pc = np.minimum(pos, s.size - 1)
    assert (p == pc).all()
    assert (f == (s[pc] == q)).all()
    # empty store
    f0, p0 = sp.ss_locate(np.empty((0,), np.uint64), q)
    assert not f0.any()


def test_merge_sorted_matches_fallback():
    rng = np.random.default_rng(2)
    old = np.sort(rng.choice(np.arange(1, 50_000, dtype=np.uint64),
                             5_000, replace=False))
    add = np.setdiff1d(
        rng.integers(1, 50_000, 2_000).astype(np.uint64), old)
    mk, src = sp.merge_sorted(old, add)
    assert (mk == np.sort(np.concatenate([old, add]))).all()
    allv = np.concatenate([old, add])
    assert (allv[src] == mk).all()
    # degenerate sides
    mk2, src2 = sp.merge_sorted(old, np.empty((0,), np.uint64))
    assert (mk2 == old).all() and (src2 == np.arange(old.size)).all()
    mk3, src3 = sp.merge_sorted(np.empty((0,), np.uint64), add)
    assert (mk3 == add).all() and (src3 == np.arange(add.size)).all()


def test_gather_scatter_rows_masked():
    rng = np.random.default_rng(3)
    src = rng.normal(size=(500, 6)).astype(np.float32)
    idx = rng.permutation(500)[:200].astype(np.int64)
    mask = rng.random(200) < 0.7
    out = sp.gather_rows(src, idx, mask=mask)
    assert np.array_equal(out[mask], src[idx[mask]])
    assert (out[~mask] == 0).all()  # fresh out zeros unmasked rows
    dst = np.zeros((500, 6), np.float32)
    sp.scatter_rows(dst, idx, out, mask=mask)
    assert np.array_equal(dst[idx[mask]], src[idx[mask]])
    # 1-D (scalar-per-row) fields
    src1 = rng.normal(size=(500,)).astype(np.float32)
    g1 = sp.gather_rows(src1, idx)
    assert np.array_equal(g1, src1[idx])


def test_init_uniform_bit_exact_twin():
    keys = np.random.default_rng(4).integers(
        1, 1 << 62, 1000).astype(np.uint64)
    a = sp.init_uniform(keys, 8, 42, 0.01)
    b = _per_key_uniform(keys, 8, np.uint64(42), 0.01)
    assert np.array_equal(a, b)


@pytest.mark.skipif(not native_available(), reason="native lib unavailable")
def test_index_build_throughput():
    """VERDICT r02 task 3 floor: native-grade store build. On the 1-core
    bench host the prefetch-pipelined insert sustains >~4M keys/s; the
    floor is a conservative 2M keys/s on the MEDIAN of three runs so a
    transient CI load spike (which stalls at most one run) stays green
    while a regression to the numpy-era 0.4M keys/s still fails all
    three."""
    n = 4_000_000
    rates = []
    for run in range(3):
        keys = np.random.default_rng(5 + run).permutation(
            np.arange(1, n + 1)).astype(np.uint64)
        idx = sp.KeyIndex()
        idx.reserve(n)
        t0 = time.perf_counter()
        _, n_new = idx.upsert(keys)
        dt = time.perf_counter() - t0
        assert n_new == n
        rates.append(n / dt)
    rate = sorted(rates)[1]
    assert rate >= 2e6, (f"index build median {rate/1e6:.2f}M keys/s "
                         f"< 2M floor (runs: "
                         f"{[round(r/1e6, 2) for r in rates]}M)")
