"""Native C++ parser: build, python-parity, and throughput tests
(role of the reference's C++ data_feed readers, SURVEY.md §2.4)."""

import time

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedConfig, SlotConf, parse_lines
from paddlebox_tpu.data.columnar import ColumnarChunk, instances_to_chunk
from paddlebox_tpu.native import native_available
from paddlebox_tpu.native.parser_py import parse_chunk_native

CFG = DataFeedConfig(
    slots=(SlotConf("user", avg_len=2.0), SlotConf("item"),
           SlotConf("dense0", is_dense=True, dim=3)),
    batch_size=8)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no native toolchain")


def _chunks_equal(a: ColumnarChunk, b: ColumnarChunk):
    np.testing.assert_allclose(a.labels, b.labels)
    for s in a.sparse_ids:
        np.testing.assert_array_equal(a.sparse_ids[s], b.sparse_ids[s])
        np.testing.assert_array_equal(a.sparse_offsets[s],
                                      b.sparse_offsets[s])
    for s in a.dense:
        np.testing.assert_allclose(a.dense[s], b.dense[s], rtol=1e-6)


def test_native_matches_python_parser():
    rng = np.random.default_rng(0)
    lines = []
    for i in range(500):
        toks = [f"user:{rng.integers(1, 1000)}"
                for _ in range(rng.integers(0, 4))]
        toks += [f"item:{rng.integers(1, 1000)}"]
        if i % 3 == 0:
            toks.append(f"dense0:{rng.random():.4f},{rng.random():.4f}")
        if i % 7 == 0:
            toks.append("unknown_slot:123")   # ignored
        lines.append(f"{i % 2} {' '.join(toks)}")
    # malformed + null-feasign lines
    lines.insert(5, "not-a-label user:3")
    lines.insert(9, "1 user:0 item:4")        # user:0 dropped, line kept
    lines.insert(12, "")
    lines.insert(20, "0 user:-7 item:2")      # negative -> line malformed?
    text = ("\n".join(lines) + "\n").encode()

    native = parse_chunk_native(text, CFG)
    ref = instances_to_chunk(parse_lines(
        text.decode().splitlines(), CFG), CFG)
    assert native.num_rows == ref.num_rows
    _chunks_equal(native, ref)


def test_native_parser_throughput():
    rng = np.random.default_rng(1)
    lines = [f"1 user:{rng.integers(1, 1<<40)} user:{rng.integers(1, 1<<40)} "
             f"item:{rng.integers(1, 1<<40)}" for _ in range(20000)]
    text = ("\n".join(lines) + "\n").encode()

    t0 = time.perf_counter()
    native = parse_chunk_native(text, CFG)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    ref = instances_to_chunk(parse_lines(text.decode().splitlines(), CFG),
                             CFG)
    t_py = time.perf_counter() - t0

    assert native.num_rows == ref.num_rows == 20000
    speedup = t_py / t_native
    print(f"\nnative parse: {len(text)/t_native/1e6:.0f} MB/s, "
          f"python: {len(text)/t_py/1e6:.1f} MB/s, speedup {speedup:.1f}x")
    assert speedup > 3, f"native only {speedup:.1f}x faster"


def test_dataset_uses_native_path(tmp_path):
    """End-to-end: Dataset load goes through the native parser and
    produces identical batches to the python path."""
    from paddlebox_tpu.data import Dataset
    rng = np.random.default_rng(2)
    lines = [f"{i%2} user:{rng.integers(1, 100)} item:{i+1}"
             for i in range(40)]
    p = tmp_path / "part0"
    p.write_text("\n".join(lines) + "\n")

    ds = Dataset(CFG)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert ds.num_instances == 40
    b = next(ds.batches())
    assert b.num_valid == 8
