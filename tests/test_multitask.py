"""Multi-task CTR end-to-end: SharedBottomMultiTask through CTRTrainer —
per-task BCE over num_labels columns, stacked per-task AUC states (the
MultiTaskMetricMsg role), eval twin, and single-task equivalence of the
stacked-AUC plumbing."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import MMoE, SharedBottomMultiTask
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("a", "b")


def _make(tmp_path, num_tasks=2, n_steps=6, arch="shared_bottom"):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64, num_labels=num_tasks)
    if arch == "mmoe":
        model = MMoE(slot_names=SLOTS, emb_dim=8, num_tasks=num_tasks,
                     num_experts=3, expert_hidden=(32, 16),
                     tower_hidden=(8,))
    else:
        model = SharedBottomMultiTask(
            slot_names=SLOTS, emb_dim=8, num_tasks=num_tasks,
            bottom_hidden=(32, 16), tower_hidden=(8,))
    tr = CTRTrainer(model, feed, TableConfig(dim=8, learning_rate=0.2),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10,
                                         dense_learning_rate=3e-3))
    tr.init(seed=0)
    rng = np.random.default_rng(5)
    p = str(tmp_path / "part-mt")
    with open(p, "w") as f:
        for _ in range(n_steps * 64):
            a, b = rng.integers(1, 300), rng.integers(1, 300)
            # Task 0 (click): signal on a; task 1 (conversion): rarer,
            # signal on b — distinct learnable targets.
            l0 = int(rng.random() < (0.6 if a % 3 == 0 else 0.1))
            l1 = int(l0 and rng.random() < (0.7 if b % 2 == 0 else 0.1))
            labels = " ".join(str(v) for v in (l0, l1)[:num_tasks])
            f.write(f"{labels} a:{a} b:{b}\n")
    return tr, feed, p


@pytest.mark.parametrize("arch", ["shared_bottom", "mmoe"])
def test_multitask_trains_and_reports_per_task_auc(tmp_path, arch):
    tr, feed, p = _make(tmp_path, arch=arch)
    losses = []
    for _ in range(3):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        stats = tr.train_pass(ds)
        losses.append(stats["loss"])
    assert losses[-1] < losses[0], losses
    # Per-task AUC keys present and sane; headline auc == task 0's.
    assert "auc_task0" in stats and "auc_task1" in stats
    assert stats["auc"] == stats["auc_task0"]
    assert 0.5 < stats["auc_task0"] <= 1.0
    assert 0.0 <= stats["auc_task1"] <= 1.0
    # The two tasks genuinely differ (separate label columns learned).
    assert stats["actual_ctr_task0"] > stats["actual_ctr_task1"] > 0


def test_multitask_eval_pass(tmp_path):
    tr, feed, p = _make(tmp_path)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    tr.train_pass(ds)
    ds2 = Dataset(feed, num_reader_threads=1)
    ds2.set_filelist([p])
    ds2.load_into_memory()
    stats = tr.eval_pass(ds2)
    assert "auc_task1" in stats and np.isfinite(stats["loss"])


def test_multitask_label_column_check(tmp_path):
    """Constructing the trainer already fails (covers train AND eval
    paths — an eval-only user must not hit a cryptic vmap error)."""
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64, num_labels=1)  # too few label columns
    model = SharedBottomMultiTask(slot_names=SLOTS, emb_dim=8,
                                  num_tasks=2)
    with pytest.raises(ValueError, match="label columns"):
        CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh)


def test_single_task_plumbing_unchanged(tmp_path):
    """num_tasks=1 through the same stacked-AUC helpers must behave as
    the classic single-task path: scalar-state AUC, no _task keys — and
    it must LEARN. (A [B,1]-vs-[B] broadcast in the single-task BCE
    yields a finite loss while training a constant predictor, so the
    learning assertion is the real guard.)"""
    tr, feed, p = _make(tmp_path, num_tasks=1)
    stats = None
    for _ in range(10):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        stats = tr.train_pass(ds)
    assert np.isfinite(stats["loss"])
    assert "auc" in stats and not any(k.endswith("_task0") for k in stats)
    # The broadcast bug converges to a CONSTANT predictor, whose best
    # possible logloss is the label entropy H(p~0.267) ~= 0.58 — beating
    # it requires per-sample discrimination (auc must move too).
    assert stats["loss"] < 0.575, stats["loss"]
    assert stats["auc"] > 0.52, stats["auc"]
    # State is the plain (unstacked) AucState.
    assert tr.auc_state.table.ndim == 2
