"""Distributed runtime tests: transport, file store, launcher watcher.

Mirrors the reference's localhost fake-cluster mechanism
(test_dist_base.py): everything runs on 127.0.0.1 with free ports.
"""

import os
import socket
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.data.columnar import ColumnarChunk
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.distributed import FileStore, TcpTransport
from paddlebox_tpu.distributed.transport import make_chunk_exchanger
from paddlebox_tpu.launch.main import Watcher, build_env


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_filestore_kv_barrier_allgather(tmp_path):
    stores = [FileStore(str(tmp_path), r, 3) for r in range(3)]
    results = [None] * 3

    def worker(r):
        stores[r].set(f"k{r}", f"v{r}".encode())
        stores[r].barrier("b0", timeout=10)
        results[r] = stores[r].all_gather("g0", f"rank{r}".encode(),
                                          timeout=10)

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for r in range(3):
        assert results[r] == [b"rank0", b"rank1", b"rank2"]
    assert stores[0].get("k2") == b"v2"


def test_filestore_chunked_large_value_roundtrip(tmp_path):
    """A set() payload above FLAGS_filestore_chunk_bytes must split
    into chunk files behind a manifest and reassemble bit-identical on
    get() — a multi-MB rank-table/gathered snapshot can't blow one
    framed message or rename window. Sub-cap values stay single-file."""
    from paddlebox_tpu.core import flags as flagmod
    store = FileStore(str(tmp_path), 0, 1)
    prev = flagmod.flag("filestore_chunk_bytes")
    flagmod.set_flags({"filestore_chunk_bytes": 1024})
    try:
        blob = bytes(bytearray(range(256))) * 37  # 9472 B > cap, odd tail
        store.set("big", blob)
        assert store.get("big") == blob
        # Manifest + ceil(9472/1024)=10 chunk files on disk.
        import glob
        assert len(glob.glob(str(tmp_path / "big.c*"))) == 10
        # Small values do NOT chunk.
        store.set("small", b"x" * 64)
        assert not glob.glob(str(tmp_path / "small.c*"))
        assert store.get("small") == b"x" * 64
        # Overwrite with a new size re-publishes consistently.
        blob2 = b"y" * 2000
        store.set("big", blob2)
        assert store.get("big") == blob2
        # A literal value that happens to start with the manifest magic
        # must round-trip (escaped through the chunked path).
        tricky = FileStore._CHUNK_MAGIC + b"not-a-manifest"
        store.set("tricky", tricky)
        assert store.get("tricky") == tricky
    finally:
        flagmod.set_flags({"filestore_chunk_bytes": prev})


def test_filestore_chunked_all_gather(tmp_path):
    """all_gather rides the same set/get, so >cap payloads gather
    transparently."""
    from paddlebox_tpu.core import flags as flagmod
    stores = [FileStore(str(tmp_path), r, 2) for r in range(2)]
    prev = flagmod.flag("filestore_chunk_bytes")
    flagmod.set_flags({"filestore_chunk_bytes": 512})
    try:
        blobs = [bytes([r]) * 1500 for r in range(2)]
        results = [None] * 2

        def worker(r):
            results[r] = stores[r].all_gather("gbig", blobs[r],
                                              timeout=10)

        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(2)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        for r in range(2):
            assert results[r] == blobs
    finally:
        flagmod.set_flags({"filestore_chunk_bytes": prev})


def test_tcp_transport_exchange():
    ports = _free_ports(3)
    eps = [f"127.0.0.1:{p}" for p in ports]
    transports = [TcpTransport(r, eps) for r in range(3)]
    results = [None] * 3

    def worker(r):
        bufs = [f"{r}->{d}".encode() for d in range(3)]
        results[r] = transports[r].exchange(bufs, timeout=30)

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for r in range(3):
        assert results[r] == [f"{s}->{r}".encode() for s in range(3)]
    for t in transports:
        t.close()


def test_tcp_transport_exchange_window_1():
    """Flow-controlled exchange (FLAGS_padbox_max_shuffle_wait_count=1:
    one in-flight send per rank) must still complete the full
    all-to-all — the window serializes sends, never drops them."""
    from paddlebox_tpu.core import flags as flagmod
    old = flagmod.flag("padbox_max_shuffle_wait_count")
    flagmod.set_flags({"padbox_max_shuffle_wait_count": 1})
    try:
        ports = _free_ports(3)
        eps = [f"127.0.0.1:{p}" for p in ports]
        transports = [TcpTransport(r, eps) for r in range(3)]
        results = [None] * 3

        def worker(r):
            bufs = [f"w{r}->{d}".encode() for d in range(3)]
            results[r] = transports[r].exchange(bufs, timeout=30)

        ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for r in range(3):
            assert results[r] == [f"w{s}->{r}".encode() for s in range(3)]
        for t in transports:
            t.close()
    finally:
        flagmod.set_flags({"padbox_max_shuffle_wait_count": old})


def test_global_shuffle_over_tcp(tmp_path):
    """Two-rank dataset global shuffle through the real TCP transport —
    the ShuffleData/ReceiveSuffleData round trip."""
    from paddlebox_tpu.data import Dataset
    cfg = DataFeedConfig(slots=(SlotConf("u"),), batch_size=4)
    ports = _free_ports(2)
    eps = [f"127.0.0.1:{p}" for p in ports]
    transports = [TcpTransport(r, eps) for r in range(2)]
    datasets = []
    for r in range(2):
        p = tmp_path / f"part-{r}"
        p.write_text("".join(f"1 u:{100 * (r + 1) + i}\n" for i in range(20)))
        ds = Dataset(cfg)
        ds.set_filelist([str(p)])
        ds.load_into_memory()
        datasets.append(ds)

    def worker(r):
        datasets[r].global_shuffle(
            num_ranks=2, rank=r, seed=7,
            exchange=make_chunk_exchanger(transports[r]))

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for t in transports:
        t.close()
    total = datasets[0].num_instances + datasets[1].num_instances
    assert total == 40  # nothing lost
    # Both ranks hold a mix of each other's id ranges (whp with 20 each).
    keys0 = datasets[0].pass_keys()
    assert (keys0 < 200).any() and (keys0 >= 200).any()


def test_watcher_restarts_failed_rank(tmp_path):
    script = tmp_path / "flaky.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        marker = os.environ["MARKER"]
        if not os.path.exists(marker):
            open(marker, "w").write("1")
            sys.exit(3)   # fail first run
        sys.exit(0)       # succeed on restart
    """))
    env = build_env(0, 1, "127.0.0.1:1")
    env["MARKER"] = str(tmp_path / "marker")
    w = Watcher([[sys.executable, str(script)]], [env], max_restarts=1,
                poll_sec=0.05)
    assert w.run() == 0
    assert w.restarts[0] == 1


def test_watcher_gives_up_after_budget(tmp_path):
    script = tmp_path / "dead.py"
    script.write_text("import sys; sys.exit(5)")
    env = build_env(0, 1, "127.0.0.1:1")
    w = Watcher([[sys.executable, str(script)]], [env], max_restarts=2,
                poll_sec=0.05)
    assert w.run() == 5
    assert w.restarts[0] == 2


def test_build_env_contract():
    env = build_env(3, 8, "10.0.0.1:1234", base={})
    assert env == {"PBX_COORDINATOR": "10.0.0.1:1234",
                   "PBX_NUM_PROCESSES": "8", "PBX_PROCESS_ID": "3"}
