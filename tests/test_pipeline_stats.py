"""Pipeline occupancy + bottleneck-verdict contract
(core/pipeline_stats.py).

Two layers: pure synthetic scenarios (a hand-built occupancy window
must yield the expected bounding-stage verdict — the deterministic
core), and the trainer integration (a tiny CPU train_pass emits a
pass_report carrying a schema-complete ``bottleneck`` verdict and
dispatch-latency quantiles, with the jitted step untouched — the
zero-hot-loop-cost pin rides test_pass_report's off/on jaxpr compare,
which now runs with pipeline stats wired in).
"""

import time

import numpy as np
import pytest

from paddlebox_tpu.core import monitor, pipeline_stats
from paddlebox_tpu.core.pipeline_stats import (PipelineStats,
                                               bottleneck_verdict)


def _window(stage_ms):
    """Synthetic window: {stage: (busy, blocked_up, blocked_down)} ms."""
    return {"stages": {n: {"busy_ms": b, "blocked_up_ms": u,
                           "blocked_down_ms": d, "count": 1}
                       for n, (b, u, d) in stage_ms.items()},
            "queues": {}}


def test_verdict_slow_host_reader_bounds_the_pipeline():
    """The r02 shape: the device starves while the reader grinds —
    verdict must name the reader, with a high device idle fraction and
    a high host critical-path share."""
    win = _window({
        "reader": (800.0, 50.0, 0.0),
        "packer": (100.0, 0.0, 0.0),
        "keymap": (60.0, 0.0, 0.0),
        "device": (150.0, 750.0, 0.0),   # starved: blocked_up >> busy
    })
    v = bottleneck_verdict(win, wall_ms=1000.0)
    assert v["stage"] == "reader"
    assert v["device_idle_frac"] == pytest.approx(0.75)
    assert v["host_critical_share"] == pytest.approx(0.85)
    assert v["stages"]["reader"]["busy_frac"] == pytest.approx(0.8)
    assert v["stages"]["device"]["blocked_up_frac"] == pytest.approx(0.75)


def test_verdict_device_bound_pipeline():
    """Healthy shape: producer blocked on a full queue, device busy
    wall-to-wall — verdict is the device, near-zero idle."""
    win = _window({
        "reader": (100.0, 0.0, 0.0),
        "packer": (80.0, 0.0, 700.0),    # waiting on the full queue
        "device": (900.0, 20.0, 0.0),
    })
    v = bottleneck_verdict(win, wall_ms=1000.0)
    assert v["stage"] == "device"
    assert v["device_idle_frac"] == pytest.approx(0.02)
    assert v["host_critical_share"] == pytest.approx(0.1)
    assert v["stages"]["packer"]["blocked_down_frac"] == pytest.approx(0.7)


def test_verdict_boundary_build_is_the_wall():
    """The 'store_build at 406K keys/s is the wall' scenario as a
    verdict line: the boundary stage's busy share tops everything."""
    win = _window({
        "reader": (100.0, 0.0, 0.0),
        "device": (300.0, 500.0, 0.0),
        "boundary": (850.0, 40.0, 0.0),
    })
    v = bottleneck_verdict(win, wall_ms=1000.0)
    assert v["stage"] == "boundary"
    assert v["device_idle_frac"] == pytest.approx(0.5)


def test_verdict_edges():
    assert bottleneck_verdict({"stages": {}, "queues": {}},
                              1000.0)["stage"] is None
    assert bottleneck_verdict(_window({"reader": (1.0, 0.0, 0.0)}),
                              0.0)["stage"] is None
    # No device stage in the window: fractions are None, verdict still
    # names the bounding stage.
    v = bottleneck_verdict(_window({"reader": (5.0, 0.0, 0.0)}), 10.0)
    assert v["stage"] == "reader"
    assert v["device_idle_frac"] is None
    assert v["host_critical_share"] is None


def test_recorder_scopes_and_window_delta():
    ps = PipelineStats()
    with ps.busy("reader"):
        time.sleep(0.01)
    with ps.blocked_up("device"):
        time.sleep(0.005)
    base = ps.snapshot()
    # Post-base activity only must land in the window.
    with ps.busy("packer"):
        time.sleep(0.002)
    ps.add("reader", "busy", 0.5)
    win = ps.window(base)
    assert set(win["stages"]) == {"reader", "packer"}
    assert win["stages"]["reader"]["busy_ms"] >= 500.0
    assert win["stages"]["packer"]["busy_ms"] >= 1.0
    # Full (base-less) window sees everything.
    full = ps.window()
    assert full["stages"]["device"]["blocked_up_ms"] >= 5.0
    with pytest.raises(ValueError):
        ps.add("reader", "bogus", 1.0)


def test_recorder_scope_records_on_exception():
    ps = PipelineStats()
    with pytest.raises(RuntimeError):
        with ps.busy("reader"):
            raise RuntimeError("boom")
    assert ps.window()["stages"]["reader"]["count"] == 1


def test_queue_depth_digest_percentiles():
    ps = PipelineStats()
    for d in [0] * 50 + [2] * 40 + [8] * 10:
        ps.sample_queue("producer_queue", d)
    v = bottleneck_verdict(ps.window(), wall_ms=1000.0)
    # wall>0 but no stages -> early return; add one stage.
    ps.add("device", "busy", 0.1)
    v = bottleneck_verdict(ps.window(), wall_ms=1000.0)
    q = v["queue_depth"]["producer_queue"]
    assert q["samples"] == 100
    assert q["p50"] == pytest.approx(0.0, abs=0.1)
    assert q["p90"] == pytest.approx(2.0, rel=0.05)
    assert q["max"] == pytest.approx(8.0, rel=0.05)
    # Window delta: later samples only.
    base = ps.snapshot()
    ps.sample_queue("producer_queue", 100)
    win = ps.window(base)
    assert win["queues"]["producer_queue"].count == 1


# -- trainer integration ----------------------------------------------------

SLOTS = ("u", "i", "c")
N_BATCHES = 6
BATCH = 32


def _make_trainer_and_dataset(tmp_path):
    from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    rng = np.random.default_rng(11)
    path = tmp_path / "part-0"
    with open(path, "w") as f:
        for _ in range(N_BATCHES * BATCH):
            feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                     for s in SLOTS}
            label = int(rng.random() < 0.3)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=BATCH)
    mesh = build_mesh(HybridTopology(dp=8))
    tr = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                    feed, TableConfig(dim=8, learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10),
                    store_factory=lambda c: DeviceFeatureStore(
                        c, mesh=mesh))
    tr.init(seed=0)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([str(path)])
    ds.load_into_memory()
    return tr, ds


def test_train_pass_emits_bottleneck_and_dispatch_quantiles(tmp_path):
    """The acceptance pin: a CPU tier-1 train_pass's pass_report carries
    a schema-complete bottleneck verdict (bounding stage + device idle
    fraction + per-stage busy/blocked shares + queue depths) and
    dispatch-latency quantiles consistent with the block count."""
    tr, ds = _make_trainer_and_dataset(tmp_path)
    stats = tr.train_pass(ds)
    rep = stats["pass_report"]

    bn = rep["bottleneck"]
    assert bn is stats["bottleneck"]
    assert bn["stage"] is not None
    assert 0.0 <= bn["device_idle_frac"] <= 1.0
    assert 0.0 <= bn["host_critical_share"] <= 1.0
    # The wired stages all observed something on a real pass.
    for stage in ("reader", "packer", "keymap", "device"):
        assert stage in bn["stages"], bn["stages"]
        sh = bn["stages"][stage]
        assert sh["busy_frac"] >= 0.0
        assert sh["blocked_up_frac"] >= 0.0
    # The bounding stage is the argmax busy share (definition pin).
    busiest = max(bn["stages"], key=lambda n:
                  bn["stages"][n]["busy_frac"])
    assert bn["stage"] == busiest
    q = bn["queue_depth"]["producer_queue"]
    assert q["samples"] >= stats["dispatch_blocks"]

    dq = rep["dispatch_ms_quantiles"]
    assert dq["count"] == stats["dispatch_blocks"]
    assert dq["p50"] is not None and dq["p50"] > 0.0
    assert dq["p50"] <= dq["p90"] <= dq["p99"] <= dq["p999"]

    # Registry gauges feed the occupancy table in trace_report.
    snap = monitor.snapshot()
    assert snap["pipeline/device_busy_frac"] >= 0.0
    assert snap["pass/train_device_idle_frac"] == bn["device_idle_frac"]
    assert snap["pass/train_dispatch_ms_p99"] == dq["p99"]


def test_eval_pass_emits_bottleneck(tmp_path):
    tr, ds = _make_trainer_and_dataset(tmp_path)
    stats = tr.eval_pass(ds)
    bn = stats["pass_report"]["bottleneck"]
    assert bn["stage"] is not None
    assert "device" in bn["stages"]
    dq = stats["pass_report"]["dispatch_ms_quantiles"]
    assert dq["count"] == stats["dispatch_blocks"]


def test_pass_windows_are_independent(tmp_path):
    """Two consecutive passes each get their OWN window: the second
    pass's dispatch quantile count must reflect only its blocks (the
    digest/occupancy state is cumulative; the per-pass delta isolates
    the window)."""
    tr, ds = _make_trainer_and_dataset(tmp_path)
    s1 = tr.train_pass(ds)
    ds2 = ds  # dataset is reusable (in-memory)
    s2 = tr.train_pass(ds2)
    assert s1["dispatch_ms_quantiles"]["count"] == s1["dispatch_blocks"]
    assert s2["dispatch_ms_quantiles"]["count"] == s2["dispatch_blocks"]
    # The global pipeline recorder kept accumulating across both passes.
    full = pipeline_stats.GLOBAL.window()
    assert full["stages"]["device"]["count"] >= (
        s1["dispatch_blocks"] + s2["dispatch_blocks"])
