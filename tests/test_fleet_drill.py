"""Real-process serving fleet drill (the ISSUE-11 acceptance drill):
replica PROCESSES over one shared ShardServer tier behind a FleetRouter
discovered through elastic heartbeat meta — kill -9 one replica under
concurrent client traffic with ZERO failed client RPCs, and join a
replica mid-traffic that serves bit-identical probabilities to the
incumbents (everyone resolves the same shard tier with the same init
seed, so the model IS the same model).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost.shard_service import (start_local_shards,
                                                   stop_shards)
from paddlebox_tpu.multihost.store import MultiHostStore
from paddlebox_tpu.serving import PredictClient
from paddlebox_tpu.serving.router import FleetRouter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fleet_replica_worker.py")

DIM = 8
N_KEYS = 400


def _spawn(elastic_root, host_id, shard_eps, ready_file):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PBX_RANK", None)
    return subprocess.Popen(
        [sys.executable, WORKER, elastic_root, host_id,
         ",".join(shard_eps), ready_file],
        cwd=REPO, env=env, start_new_session=True)


def _wait_file(path, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        time.sleep(0.1)
    raise TimeoutError(f"worker never wrote {path}")


def _wait_healthy(router, want, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if router.fleet.size() >= want:
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"fleet never reached {want} healthy replicas: "
        f"{router.fleet.replicas()}")


def test_fleet_kill9_and_join_drill(tmp_path):
    # Shared shard tier, populated with a deterministic trained-model
    # stand-in every replica resolves against.
    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    shard_servers, shard_eps = start_local_shards(2, cfg)
    store = MultiHostStore(cfg, shard_eps)
    rng = np.random.default_rng(3)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    rows = store.pull_for_pass(keys)
    rows["emb"] = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * .02
    rows["w"] = rng.normal(size=(N_KEYS,)).astype(np.float32) * .02
    store.push_from_pass(keys, rows)

    root = str(tmp_path / "elastic")
    procs = {}
    router = None
    clients = []
    prev_hb = flagmod.flag("fleet_health_interval_s")
    flagmod.set_flags({"fleet_health_interval_s": 0.2})
    try:
        # Two incumbents, spawned in parallel (jax import dominates).
        for hid in ("repA", "repB"):
            procs[hid] = _spawn(root, hid, shard_eps,
                                str(tmp_path / f"{hid}.ep"))
        eps = {hid: _wait_file(str(tmp_path / f"{hid}.ep"))
               for hid in ("repA", "repB")}
        router = FleetRouter("127.0.0.1:0", elastic_root=root)
        _wait_healthy(router, 2)

        # Concurrent clients through the router. EVERY RPC must
        # succeed across the kill and the join below.
        stop = threading.Event()
        failures = []
        done = [0] * 4
        crng = np.random.default_rng(77)
        lines_per_cli = [
            [[f"0 u:{crng.integers(1, N_KEYS)} "
              f"i:{crng.integers(1, N_KEYS)}" for _ in range(2)]
             for _ in range(8)]
            for _ in range(4)]

        def run(i):
            cli = PredictClient(router.endpoint)
            j = 0
            try:
                while not stop.is_set():
                    try:
                        out = cli.predict(
                            lines_per_cli[i][j % 8])
                        assert out.shape == (2,)
                        done[i] += 1
                    except Exception as e:  # noqa: BLE001 - the drill count
                        failures.append((i, repr(e)))
                    j += 1
            finally:
                cli.close()

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)

        # JOIN mid-traffic: the third replica registers through the
        # same elastic meta and is admitted by the health loop.
        procs["repC"] = _spawn(root, "repC", shard_eps,
                               str(tmp_path / "repC.ep"))
        eps["repC"] = _wait_file(str(tmp_path / "repC.ep"))
        _wait_healthy(router, 3)

        # Bit-identical: the joiner answers exactly what an incumbent
        # answers (direct clients, fixed lines).
        probe = [f"0 u:{k} i:{k + 5}" for k in (3, 77, 250, 390)]
        c_new = PredictClient(eps["repC"])
        c_old = PredictClient(eps["repB"])
        np.testing.assert_array_equal(c_new.predict(probe),
                                      c_old.predict(probe))
        c_new.close()
        c_old.close()

        # KILL -9 one incumbent under traffic.
        os.kill(procs["repA"].pid, signal.SIGKILL)
        procs["repA"].wait(timeout=30)
        deadline = time.time() + 60
        while time.time() < deadline:
            r = router.fleet.get("repA")
            if r is None or r.state == "ejected":
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                f"repA never left the fleet: {router.fleet.replicas()}")
        time.sleep(1.0)     # keep traffic flowing post-eject
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert failures == [], failures[:5]
        assert all(d > 0 for d in done), done
        # The survivors (incl. the joiner) carried the traffic.
        st_cli = PredictClient(router.endpoint)
        st = st_cli.stats()
        st_cli.close()
        assert st["fleet_size"] == 2
        assert st["predict_rpcs"] > 0
    finally:
        flagmod.set_flags({"fleet_health_interval_s": prev_hb})
        stop_evt = locals().get("stop")
        if stop_evt is not None:
            stop_evt.set()
        for c in clients:
            c.close()
        if router is not None:
            router.stop()
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    p.kill()
                p.wait(timeout=30)
        store.close()
        stop_shards(shard_servers)
