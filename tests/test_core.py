"""Core runtime tests: flags, monitor, timers (SURVEY.md §2.7 config core)."""

import os

import pytest

from paddlebox_tpu.core import flags, monitor, timers


def test_flag_define_get_set():
    flags.define_flag("test_flag_a", 7, "test int flag")
    assert flags.get_flags("test_flag_a") == {"test_flag_a": 7}
    flags.set_flags({"test_flag_a": 11})
    assert flags.flag("test_flag_a") == 11


def test_flag_env_override():
    os.environ["FLAGS_test_flag_env"] = "42"
    flags.define_flag("test_flag_env", 1, "env-overridable")
    assert flags.flag("test_flag_env") == 42
    # Explicit set wins over env after the fact.
    flags.set_flags({"test_flag_env": 5})
    assert flags.flag("test_flag_env") == 5


def test_flag_bool_parse():
    os.environ["FLAGS_test_flag_bool"] = "true"
    flags.define_flag("test_flag_bool", False, "bool flag")
    assert flags.flag("test_flag_bool") is True


def test_flag_type_check():
    flags.define_flag("test_flag_typed", 1.5)
    flags.set_flags({"test_flag_typed": 2})  # int coerced to float
    assert flags.flag("test_flag_typed") == 2.0
    with pytest.raises(flags.FlagError):
        flags.set_flags({"test_flag_typed": [1]})


def test_builtin_flags_present():
    vals = flags.get_flags(["check_nan_inf", "auc_num_buckets",
                            "padbox_max_shuffle_wait_count"])
    assert vals["auc_num_buckets"] == 1 << 20
    assert vals["check_nan_inf"] is False


def test_monitor_counters():
    monitor.reset()
    monitor.add("ins_num", 100)
    monitor.add("ins_num", 28)
    monitor.set_stat("epoch", 3)
    snap = monitor.snapshot()
    assert snap["ins_num"] == 128
    assert snap["epoch"] == 3


def test_timer_accumulates():
    t = timers.Timer()
    with t.scope():
        pass
    with t.scope():
        pass
    assert t.count == 2
    assert t.elapsed_sec >= 0.0


def test_timer_group_report():
    g = timers.TimerGroup()
    with g.scope("pull"):
        pass
    with g.scope("push"):
        pass
    rep = g.report()
    assert "pull=" in rep and "push=" in rep


def test_monitor_float_gauges_do_not_truncate():
    monitor.reset()
    monitor.set_gauge("ratio", 0.75)
    monitor.add("float_counter", 0.5)   # float deltas survive too
    monitor.add("float_counter", 0.25)
    assert monitor.get_gauge("ratio") == 0.75
    snap = monitor.snapshot()           # flat back-compat view
    assert snap["ratio"] == 0.75
    assert snap["float_counter"] == 0.75


def test_monitor_histogram_fixed_buckets():
    monitor.reset()
    monitor.define_histogram("lat_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        monitor.observe("lat_ms", v)
    h = monitor.snapshot_all()["histograms"]["lat_ms"]
    assert h["counts"] == [1, 1, 1, 1]  # one per bucket + overflow
    assert h["count"] == 4 and h["min"] == 0.5 and h["max"] == 500.0
    # Redefining with different buckets must fail loudly.
    with pytest.raises(ValueError):
        monitor.define_histogram("lat_ms", buckets=(2.0, 4.0))


def test_monitor_snapshot_all_labeled_and_jsonl(tmp_path):
    import json
    monitor.reset()
    monitor.add("c", 3)
    monitor.set_gauge("g", 1.25)
    monitor.observe("h", 7.0)
    snap = monitor.snapshot_all({"kind": "test"})
    assert snap["labels"] == {"kind": "test"}
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 1.25
    path = str(tmp_path / "m.jsonl")
    monitor.flush_jsonl(path, {"n": 1})
    monitor.flush_jsonl(path, {"n": 2})
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert len(lines) == 2
    assert lines[1]["labels"] == {"n": 2}
    assert lines[0]["histograms"]["h"]["count"] == 1


def test_monitor_flush_thread(tmp_path):
    import time as _time
    monitor.reset()
    monitor.add("tick", 1)
    path = str(tmp_path / "bg.jsonl")
    try:
        assert monitor.start_flush_thread(path, interval_s=0.05)
        _time.sleep(0.2)
    finally:
        monitor.stop_flush_thread()
    assert len(open(path).read().splitlines()) >= 1
    # Disarmed after stop: flush with no explicit path is a no-op.
    assert monitor.flush_jsonl() is None


def test_timer_group_publishes_into_registry():
    monitor.reset()
    g = timers.TimerGroup()
    with g.scope("train"):
        pass
    g["fwd_bwd"].add_elapsed(0.25)
    g.publish("day")
    snap = monitor.snapshot()
    assert snap["day/train_ms"] >= 0.0
    assert snap["day/train_count"] == 1
    assert abs(snap["day/fwd_bwd_ms"] - 250.0) < 1e-6
    assert g.report_dict()["fwd_bwd"]["count"] == 1
