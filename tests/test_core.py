"""Core runtime tests: flags, monitor, timers (SURVEY.md §2.7 config core)."""

import os

import pytest

from paddlebox_tpu.core import flags, monitor, timers


def test_flag_define_get_set():
    flags.define_flag("test_flag_a", 7, "test int flag")
    assert flags.get_flags("test_flag_a") == {"test_flag_a": 7}
    flags.set_flags({"test_flag_a": 11})
    assert flags.flag("test_flag_a") == 11


def test_flag_env_override():
    os.environ["FLAGS_test_flag_env"] = "42"
    flags.define_flag("test_flag_env", 1, "env-overridable")
    assert flags.flag("test_flag_env") == 42
    # Explicit set wins over env after the fact.
    flags.set_flags({"test_flag_env": 5})
    assert flags.flag("test_flag_env") == 5


def test_flag_bool_parse():
    os.environ["FLAGS_test_flag_bool"] = "true"
    flags.define_flag("test_flag_bool", False, "bool flag")
    assert flags.flag("test_flag_bool") is True


def test_flag_type_check():
    flags.define_flag("test_flag_typed", 1.5)
    flags.set_flags({"test_flag_typed": 2})  # int coerced to float
    assert flags.flag("test_flag_typed") == 2.0
    with pytest.raises(flags.FlagError):
        flags.set_flags({"test_flag_typed": [1]})


def test_builtin_flags_present():
    vals = flags.get_flags(["check_nan_inf", "auc_num_buckets",
                            "padbox_max_shuffle_wait_count"])
    assert vals["auc_num_buckets"] == 1 << 20
    assert vals["check_nan_inf"] is False


def test_monitor_counters():
    monitor.reset()
    monitor.add("ins_num", 100)
    monitor.add("ins_num", 28)
    monitor.set_stat("epoch", 3)
    snap = monitor.snapshot()
    assert snap["ins_num"] == 128
    assert snap["epoch"] == 3


def test_timer_accumulates():
    t = timers.Timer()
    with t.scope():
        pass
    with t.scope():
        pass
    assert t.count == 2
    assert t.elapsed_sec >= 0.0


def test_timer_group_report():
    g = timers.TimerGroup()
    with g.scope("pull"):
        pass
    with g.scope("push"):
        pass
    rep = g.report()
    assert "pull=" in rep and "push=" in rep
