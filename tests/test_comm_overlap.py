"""Overlapped DCN exchange, quantized dense allreduce, chunked COPY.

Pins for the "hide and shrink every DCN byte" round (MULTIHOST.md):

- overlapped boundary exchange: the async push + barrier-free boundary
  pull sequence is BIT-identical to the serial wire across shared-key
  fractions {0, 0.5, 1} x wire dtypes {f32, int8} — overlap changes
  when bytes move, never which bytes;
- exchange worker safety: queued jobs always run to completion (reads
  drain first, reset after an async push leaves no torn rows);
- one coalesced boundary pull + one owner-plan derivation per pass
  (multihost/boundary_pulls, multihost/plan_misses);
- quantized_psum: f32 wire bit-identical to lax.psum; int8 wire within
  the blocked-codec error bound derived from the np twin; trainer-level
  dense sync at int8 still learns and tracks the f32 loss;
- chunked COPY: paged pull_range walk is digest-identical to the
  whole-range move, kill -9 between chunk windows recovers through
  recovery_chain with no lost/double rows; chunked replica snapshot
  commits atomically (mid-stream crash leaves the sentinel epoch that
  forces a clean re-snapshot).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import monitor
from paddlebox_tpu.embedding.store import _FIELDS
from paddlebox_tpu.embedding.table import TableConfig, shared_key_mask
from paddlebox_tpu.multihost import (MultiHostStore, ShardRangeTable,
                                     execute_reshard, start_local_shards,
                                     stop_shards)
from paddlebox_tpu.multihost.quant import (dequantize_blocked_np,
                                           quantize_blocked_np)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = TableConfig(name="emb", dim=8, learning_rate=0.1)


def _rand_keys(n, seed=0, hi=1 << 50):
    rng = np.random.default_rng(seed)
    keys = np.unique(rng.integers(1, hi, size=n + 64, dtype=np.uint64))
    assert keys.size >= n
    return keys[:n]


def _two_pass_keys(share: float, n=1200, seed=21):
    """Two sorted pass key arrays where `share` of pass 2's keys also
    appear in pass 1 (the boundary's shared-key fraction)."""
    k1 = _rand_keys(n, seed=seed)
    n_sh = int(round(share * n))
    rng = np.random.default_rng(seed + 1)
    fresh = np.unique(rng.integers(1 << 51, 1 << 52, size=n - n_sh,
                                   dtype=np.uint64))
    k2 = np.sort(np.concatenate([
        rng.choice(k1, size=n_sh, replace=False), fresh]))
    assert np.unique(k2).size == k2.size
    return k1, k2


def _boundary_sequence(eps, k1, k2):
    """The pass-engine boundary wire sequence against one cluster:
    seed pass 1's rows, write them back split priority/bulk, then pull
    pass 2 as early (non-shared, barriered) + boundary (shared,
    barrier-free) windows. Returns pass 2's assembled rows."""
    store = MultiHostStore(CFG, eps)
    try:
        rows = store.pull_for_pass(k1, pass_id=1)
        rng = np.random.default_rng(5)
        rows["emb"] = rng.normal(size=rows["emb"].shape).astype(
            np.float32)
        rows["show"] += 1.0
        pri = shared_key_mask(k2, k1)     # prev ∩ next, over k1
        job = store.push_from_pass_async(k1, rows, priority_select=pri,
                                         pass_id=1)
        shared2 = shared_key_mask(k1, k2)  # prev ∩ next, over k2
        full = {}
        early = (store.pull_for_pass(k2, ~shared2, pass_id=2)
                 if (~shared2).any() else None)
        boundary = (store.pull_for_pass(k2, shared2, pass_id=2,
                                        barrier=False, boundary=True)
                    if shared2.any() else None)
        job.wait()
        for f in _FIELDS:
            ref = (early or boundary)[f]
            buf = np.zeros((k2.size,) + ref.shape[1:], ref.dtype)
            if early is not None:
                buf[~shared2] = early[f]
            if boundary is not None:
                buf[shared2] = boundary[f]
            full[f] = buf
        return full
    finally:
        store.close()


@pytest.mark.parametrize("wire", ["f32", "int8"])
@pytest.mark.parametrize("share", [0.0, 0.5, 1.0])
def test_overlap_bit_identical_to_serial(share, wire):
    """Overlap on vs off is a pure scheduling change: the assembled
    pass-2 rows are BIT-identical on every wire dtype at every
    shared-key fraction."""
    k1, k2 = _two_pass_keys(share)
    prev = flagmod.get_flags(["multihost_overlap_exchange",
                              "multihost_wire_dtype"])
    outs = {}
    try:
        for overlap in (True, False):
            flagmod.set_flags({"multihost_overlap_exchange": overlap,
                               "multihost_wire_dtype": wire})
            servers, eps = start_local_shards(2, CFG)
            try:
                outs[overlap] = _boundary_sequence(eps, k1, k2)
            finally:
                stop_shards(servers)
    finally:
        flagmod.set_flags(prev)
    for f in _FIELDS:
        np.testing.assert_array_equal(outs[True][f], outs[False][f],
                                      err_msg=f"{f} wire={wire}")


def test_exchange_jobs_complete_reads_drain_reset_not_torn():
    """The worker never leaves torn peer state: a queued bulk push is
    fully visible to the next read (reads drain), and an admin reset
    right behind an async push still lands on a quiesced cluster."""
    servers, eps = start_local_shards(2, CFG)
    store = MultiHostStore(CFG, eps)
    try:
        k1, k2 = _two_pass_keys(0.5, n=800, seed=33)
        rows = store.pull_for_pass(k1, pass_id=1)
        rows["click"] += 3.0
        pri = shared_key_mask(k2, k1)
        store.push_from_pass_async(k1, rows, priority_select=pri,
                                   pass_id=1)
        # contains() drains the queue before asking the owners.
        assert store.contains(k1).all()
        back = store.pull_for_pass(k1)
        np.testing.assert_array_equal(back["click"], rows["click"])
        s = store.exchange_stats()
        assert s["exchange_busy_ms"] >= 0.0
        assert 0.0 <= store.exchange_overlap_frac() <= 1.0
        # reset() behind another in-flight async push: quiesce, then
        # wipe — no half-applied push survives on any server.
        rows["click"] += 1.0
        store.push_from_pass_async(k1, rows, priority_select=pri,
                                   pass_id=2)
        store.reset()
        assert store.num_features == 0
    finally:
        store.close()
        stop_shards(servers)


def test_one_boundary_pull_one_plan_per_pass():
    """Satellites 1+2: the boundary shared pull is ONE coalesced fanout
    (multihost/boundary_pulls) and the whole pull/push cycle of a pass
    derives its owner plan ONCE (multihost/plan_misses keyed by
    pass id)."""
    servers, eps = start_local_shards(2, CFG)
    store = MultiHostStore(CFG, eps)
    try:
        k1, k2 = _two_pass_keys(0.5, n=600, seed=44)
        before = (monitor.GLOBAL.get("multihost/plan_misses"),
                  monitor.GLOBAL.get("multihost/boundary_pulls"))
        rows = store.pull_for_pass(k1, pass_id=1)          # plan(k1)
        shared2 = shared_key_mask(k1, k2)
        store.pull_for_pass(k2, ~shared2, pass_id=2)       # plan(k2)
        store.pull_for_pass(k2, shared2, pass_id=2, barrier=False,
                            boundary=True)                 # cached
        store.push_from_pass_async(
            k1, rows, priority_select=shared_key_mask(k2, k1),
            pass_id=1)                                     # cached
        store.contains(k1)  # drain
        misses = monitor.GLOBAL.get("multihost/plan_misses") - before[0]
        bpulls = (monitor.GLOBAL.get("multihost/boundary_pulls")
                  - before[1])
        assert misses == 2, misses  # exactly one plan per pass
        assert bpulls == 1, bpulls  # one coalesced boundary fanout
    finally:
        store.close()
        stop_shards(servers)


# ---------------------------------------------------------------------------
# quantized dense-grad allreduce
# ---------------------------------------------------------------------------

def test_quantized_psum_f32_bit_identical_int8_bounded(devices8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddlebox_tpu.parallel.collective import quantized_psum

    mesh = Mesh(np.array(devices8), ("dp",))
    rng = np.random.default_rng(9)
    n = 8
    tree = {"w": rng.normal(size=(n, 37, 5)).astype(np.float32) * 2.0,
            "b": rng.normal(size=(n, 11)).astype(np.float32)}
    block = 16

    def run(wire):
        fn = jax.jit(jax.shard_map(
            lambda t: quantized_psum(t, "dp", wire_dtype=wire,
                                     block=block),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))
        out = fn(tree)
        return {k: np.asarray(v)[0] for k, v in out.items()}

    exact = {k: v.sum(axis=0) for k, v in tree.items()}
    f32 = run("f32")
    for k in tree:
        np.testing.assert_array_equal(f32[k], np.asarray(
            jax.jit(jax.shard_map(lambda t: jax.lax.psum(t, "dp"),
                                  mesh=mesh, in_specs=P("dp"),
                                  out_specs=P("dp")))(tree)[k])[0],
            err_msg=k)

    q = run("int8")
    # Error bound from the np twin codec: each element crosses the
    # int8 codec twice (per-rank scatter + reduced-segment gather), so
    # |err| <= sum_r bound_r + bound_seg, with bound = absmax/254 + eps
    # per block. Derive it on the SAME fused-flat layout the op uses.
    flat = np.concatenate([tree["w"].reshape(n, -1),
                           tree["b"].reshape(n, -1)], axis=1)
    pad = (-flat.shape[1]) % n
    flat = np.pad(flat, ((0, 0), (0, pad)))
    seg_w = flat.shape[1] // n
    got = np.concatenate([q["w"].ravel(), q["b"].ravel()])
    want = np.concatenate([exact["w"].ravel(), exact["b"].ravel()])
    err = np.abs(got - want)
    # Per-rank scatter error (exact, from the twin) ...
    scatter = np.zeros((n, seg_w), np.float32)
    for r in range(n):
        rows = flat[r].reshape(n, seg_w)
        qr, sr = quantize_blocked_np(rows, block)
        scatter += np.abs(
            dequantize_blocked_np(qr, sr, seg_w, block) - rows)
    # ... plus the gather-hop bound on the reduced segment: half a
    # quant step for rounding, plus one FULL step of allowance — the
    # device accumulates the dequantized segments in its own order and
    # with its own scatter error, so its requantization can land one
    # bucket away from the twin's half-step envelope.
    seg_sum = flat.reshape(n, n, seg_w).sum(axis=0)
    nb = -(-seg_w // block)
    amax = np.abs(np.pad(seg_sum, ((0, 0), (0, nb * block - seg_w)))
                  .reshape(n, nb, block)).max(-1)
    step = np.repeat(amax / 127.0 + 1e-6, block, axis=1)[:, :seg_w]
    total = (scatter + 1.5 * step).reshape(-1)[:err.size]
    assert (err <= total + 1e-5).all(), float((err - total).max())
    assert not np.array_equal(got, want)  # int8 wire really engaged


def test_trainer_int8_dense_sync_learns(tmp_path):
    """_build_step wiring: FLAGS_dense_allreduce_dtype=int8 trains and
    tracks the f32 loss curve within quantization tolerance."""
    from paddlebox_tpu.data import DataFeedConfig, Dataset, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    slots = ("u", "i")
    rng = np.random.default_rng(3)
    path = str(tmp_path / "part-0")
    with open(path, "w") as f:
        for _ in range(256):
            feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                     for s in slots}
            click = np.mean([(int(v) % 5 == 0)
                             for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * click)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")

    def train(wire):
        prev = flagmod.get_flags(["dense_allreduce_dtype"])
        flagmod.set_flags({"dense_allreduce_dtype": wire})
        try:
            mesh = build_mesh(HybridTopology(dp=8))
            feed = DataFeedConfig(
                slots=tuple(SlotConf(s, avg_len=1.5) for s in slots),
                batch_size=32)
            t = CTRTrainer(
                DeepFM(slot_names=slots, emb_dim=8, hidden=(16,)),
                feed, TableConfig(dim=8, learning_rate=0.1),
                mesh=mesh, config=TrainerConfig(
                    dense_learning_rate=0.01,
                    auc_num_buckets=1 << 10))
            t.init(seed=0)
            ds = Dataset(feed, num_reader_threads=1)
            ds.set_filelist([path])
            ds.load_into_memory()
            return [t.train_pass(ds)["loss"] for _ in range(2)]
        finally:
            flagmod.set_flags(prev)

    lf = train("f32")
    li = train("int8")
    assert lf[1] < lf[0]  # learns
    for a, b in zip(lf, li):
        assert np.isclose(a, b, rtol=5e-2, atol=5e-2), (lf, li)
    assert monitor.GLOBAL.get_gauge("dense/allreduce_wire_bits") == 8


def test_dense_allreduce_dtype_validated(tmp_path):
    from paddlebox_tpu.data import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    prev = flagmod.get_flags(["dense_allreduce_dtype"])
    flagmod.set_flags({"dense_allreduce_dtype": "fp4"})
    try:
        mesh = build_mesh(HybridTopology(dp=8))
        feed = DataFeedConfig(slots=(SlotConf("u", avg_len=1.5),),
                              batch_size=32)
        t = CTRTrainer(DeepFM(slot_names=("u",), emb_dim=8,
                              hidden=(16,)),
                       feed, TableConfig(dim=8, learning_rate=0.1),
                       mesh=mesh, config=TrainerConfig())
        t.init(seed=0)
        with pytest.raises(ValueError, match="dense_allreduce_dtype"):
            t._build_step()
    finally:
        flagmod.set_flags(prev)


# ---------------------------------------------------------------------------
# bounded-memory chunked COPY
# ---------------------------------------------------------------------------

def _seeded_cluster(world=2, n=3000, seed=51):
    servers, eps = start_local_shards(world, CFG)
    store = MultiHostStore(CFG, eps)
    keys = _rand_keys(n, seed=seed)
    rows = store.pull_for_pass(keys)
    rows["emb"] += 0.75
    rows["show"] += 2.0
    store.push_from_pass(keys, rows)
    store.close()
    return servers, eps, keys, rows


@pytest.mark.parametrize("chunk", [0, 277])
def test_chunked_copy_digest_identical(chunk):
    """The paged COPY walk moves exactly the whole-range rows: final
    contents are bit-identical, and with a chunk window the walk really
    pages (multihost/reshard_chunks > segment count)."""
    from paddlebox_tpu.multihost import rows_moved_minimal

    prev = flagmod.get_flags(["reshard_chunk_rows"])
    flagmod.set_flags({"reshard_chunk_rows": chunk})
    servers, eps, keys, rows = _seeded_cluster()
    s3, e3 = start_local_shards(3, CFG)
    joiner, jep = s3[2], e3[2]
    stop_shards(s3[:2])
    try:
        before = monitor.GLOBAL.get("multihost/reshard_chunks")
        rec = execute_reshard(eps, eps + [jep])
        t2 = ShardRangeTable.for_world(2)
        t3 = ShardRangeTable.for_world(3)
        assert rec["moved_rows"] == rows_moved_minimal(t2, t3, keys)
        chunks = monitor.GLOBAL.get("multihost/reshard_chunks") - before
        if chunk:
            assert chunks > rec["segments"], (chunks, rec["segments"])
        store = MultiHostStore(CFG, eps + [jep], ranges=t3)
        got = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], rows[f], err_msg=f)
        store.close()
        for i, s in enumerate(servers + [joiner]):
            skeys, _ = s.store.key_stats()
            if skeys.size:
                assert (t3.owner_of(skeys) == i).all()
    finally:
        flagmod.set_flags(prev)
        stop_shards(servers + [joiner])


def test_kill9_between_chunk_windows_recovers(tmp_path):
    """SIGKILL between two chunk windows of one COPY segment (some
    windows applied, source not yet dropped): recovery through the
    checkpoint chain is digest-identical to the seed — per-window
    idempotence carries the drill."""
    root = str(tmp_path / "ck")
    os.makedirs(root, exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_reshard_chunk_rows"] = "400"
    worker = os.path.join(REPO, "tests", "multihost_reshard_worker.py")

    def run(mode, world=None, fault="", check=True):
        e = dict(env)
        if fault:
            e["FLAGS_fault_spec"] = fault
        cmd = [sys.executable, worker, root, mode]
        if world is not None:
            cmd.append(str(world))
        return subprocess.run(cmd, env=e, cwd=REPO, timeout=180,
                              check=check, capture_output=True)

    run("seed")
    with open(os.path.join(root, "digest_seed.json")) as f:
        seed = json.load(f)
    assert seed["rows"] > 0

    r = run("reshard", 3, fault="multihost/reshard_chunk:hit=2:kill",
            check=False)
    assert r.returncode in (-signal.SIGKILL, 137), (
        r.returncode, r.stdout[-500:], r.stderr[-500:])

    run("recover", 3)
    with open(os.path.join(root, "digest_recover.json")) as f:
        rec = json.load(f)
    assert rec == seed

    run("reshard", 3)
    with open(os.path.join(root, "digest_reshard.json")) as f:
        done = json.load(f)
    assert done == seed


def test_chunked_replica_snapshot_and_partial_sentinel():
    """Re-replication streams in chunk windows and commits atomically:
    the caught-up backup is digest-identical to the primary, and a
    snapshot that stops mid-stream leaves the sentinel epoch so the
    next catch-up re-snapshots instead of trusting a torn store."""
    import hashlib

    from paddlebox_tpu.multihost import ReplicaMap
    from paddlebox_tpu.multihost.shard_service import (_SNAPSHOT_PARTIAL,
                                                       ShardServer)

    def digest(fs):
        keys, _ = fs.key_stats()
        keys = np.sort(keys)
        vals = fs.pull_for_pass(keys)
        h = hashlib.sha256(keys.tobytes())
        for f in _FIELDS:
            h.update(np.ascontiguousarray(vals[f]).tobytes())
        return h.hexdigest()

    prev = flagmod.get_flags(["reshard_chunk_rows",
                              "multihost_journal_entries"])
    flagmod.set_flags({"reshard_chunk_rows": 200,
                       "multihost_journal_entries": 0})  # force snapshot
    servers, eps = start_local_shards(2, CFG, replicas=2)
    store = MultiHostStore(CFG, eps, replicas=2)
    fresh = None
    try:
        keys = _rand_keys(1500, seed=61)
        rows = store.pull_for_pass(keys)
        rows["w"] += 2.0
        store.push_from_pass(keys, rows)

        # Replace the backup of slot 0 with an empty server; the next
        # mutation triggers a CHUNKED snapshot catch-up.
        old = servers[1]
        old.kill()
        fresh = ShardServer(eps[1], 1, ShardRangeTable.for_world(2),
                            CFG)
        fresh.adopt_replica_map(ReplicaMap.ring(eps, 2))
        before = monitor.GLOBAL.get("multihost/replica_snapshot_chunks")
        rows["w"] += 1.0
        store.push_from_pass(keys, rows)
        chunks = (monitor.GLOBAL.get("multihost/replica_snapshot_chunks")
                  - before)
        assert chunks >= 2, chunks
        assert digest(servers[0]._slot_stores[0]) == digest(
            fresh._slot_stores[0])
        assert fresh._slot_epoch[0] == servers[0]._journals[0].epoch

        # Mid-stream crash simulation: a first chunk with no last chunk
        # leaves the sentinel epoch; the following sync re-snapshots.
        sub = keys[:100]
        fresh.handle_replica_snapshot(
            {"slot": 0, "seq": 999, "epoch": "next",
             "keys": sub, "values": store.pull_for_pass(sub),
             "unseen": np.zeros(sub.size, np.int32), "part": "first"})
        assert fresh._slot_epoch[0] == _SNAPSHOT_PARTIAL
        with pytest.raises(RuntimeError, match="SNAPSHOT_GAP"):
            servers[0].handle_replica_snapshot(
                {"slot": 1, "seq": 1, "epoch": "x", "keys": sub,
                 "values": store.pull_for_pass(sub),
                 "unseen": np.zeros(sub.size, np.int32), "part": "mid"})
        # The next mutation's forward hits the epoch mismatch, falls
        # into catch-up, sees the sentinel, and re-snapshots cleanly.
        rows["w"] += 1.0
        store.push_from_pass(keys, rows)
        assert digest(servers[0]._slot_stores[0]) == digest(
            fresh._slot_stores[0])
        assert fresh._slot_epoch[0] == servers[0]._journals[0].epoch
    finally:
        flagmod.set_flags(prev)
        store.close()
        stop_shards(servers + ([fresh] if fresh else []))
