"""Elastic manager tests: heartbeat membership, leader rank-table
publication, scale-out/in reassignment with callbacks, quorum hold
(mirrors the reference elastic manager scenarios, which CI tests by
killing subprocesses — here manager instances share a tmpdir)."""

import time

import pytest

from paddlebox_tpu.launch.elastic import ElasticManager, RankTable

FAST = dict(heartbeat_interval=0.05, timeout=0.4, settle=0.1)


def _mk(root, host, **kw):
    m = ElasticManager(str(root), host, **{**FAST, **kw})
    m.start()
    return m


def test_membership_and_ranktable(tmp_path):
    a = _mk(tmp_path, "host-a", min_hosts=2)
    b = _mk(tmp_path, "host-b", min_hosts=2)
    try:
        ta = a.wait_for_quorum(5.0)
        tb = b.wait_for_quorum(5.0)
        assert ta.hosts == tb.hosts == ["host-a", "host-b"]
        assert a.current_rank() == 0 and b.current_rank() == 1
        assert a.is_leader() and not b.is_leader()
    finally:
        a.stop()
        b.stop()


def test_scale_out_triggers_callback(tmp_path):
    events = []
    a = _mk(tmp_path, "host-a", on_change=lambda t: events.append(t.hosts))
    try:
        a.wait_for_quorum(5.0)
        c = _mk(tmp_path, "host-c")
        try:
            deadline = time.time() + 5
            while time.time() < deadline:
                t = a.current_table()
                if t and t.world_size == 2:
                    break
                time.sleep(0.05)
            assert a.current_table().hosts == ["host-a", "host-c"]
            assert events[-1] == ["host-a", "host-c"]
        finally:
            c.stop()
    finally:
        a.stop()


def test_scale_in_reassigns_ranks(tmp_path):
    a = _mk(tmp_path, "host-a", min_hosts=1)
    b = _mk(tmp_path, "host-b", min_hosts=1)
    try:
        a.wait_for_quorum(5.0)
        deadline = time.time() + 5
        while time.time() < deadline:
            t = a.current_table()
            if t and t.world_size == 2:
                break
            time.sleep(0.05)
        b.stop()  # lease removed -> scale-in
        deadline = time.time() + 5
        while time.time() < deadline:
            t = a.current_table()
            if t and t.world_size == 1:
                break
            time.sleep(0.05)
        assert a.current_table().hosts == ["host-a"]
        assert a.current_rank() == 0
    finally:
        a.stop()


def test_quorum_hold_below_min(tmp_path):
    """Below min_hosts no table is published (job holds, reference :443)."""
    a = _mk(tmp_path, "host-a", min_hosts=2)
    try:
        with pytest.raises(TimeoutError):
            a.wait_for_quorum(0.6)
        assert a.current_table() is None
    finally:
        a.stop()


def test_leader_failover(tmp_path):
    a = _mk(tmp_path, "host-a", min_hosts=1)
    b = _mk(tmp_path, "host-b", min_hosts=1)
    try:
        a.wait_for_quorum(5.0)
        assert a.is_leader()
        a.stop()  # leader dies; host-b takes over and republishes
        deadline = time.time() + 5
        while time.time() < deadline:
            t = b.current_table()
            if t and t.hosts == ["host-b"]:
                break
            time.sleep(0.05)
        assert b.is_leader()
        assert b.current_table().hosts == ["host-b"]
        assert b.current_rank() == 0
    finally:
        b.stop()


def test_ranktable_helpers():
    t = RankTable(generation=3, hosts=["x", "y"])
    assert t.rank_of("y") == 1
    assert t.rank_of("zz") is None
    assert t.world_size == 2
