"""Dense sync modes: k-step local-SGD and async host dense table.

Role of the BoxPSWorker dense-sync machinery: per-step allreduce vs
k-step SyncParam (boxps_worker.cc:584-645) vs BoxPSAsynDenseTable
(boxps_worker.cc:43-341).
"""

import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("u", "i")


def _shard(path, n=256, seed=3):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                     for s in SLOTS}
            click = np.mean([(int(v) % 5 == 0)
                             for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * click)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shard_file(tmp_path_factory):
    return _shard(tmp_path_factory.mktemp("sync") / "part-0")


def _train(shard_file, cfg: TrainerConfig, passes=2):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    t = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                   feed, TableConfig(dim=8, learning_rate=0.1),
                   mesh=mesh, config=cfg)
    t.init(seed=0)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([shard_file])
    ds.load_into_memory()
    stats = [t.train_pass(ds) for _ in range(passes)]
    return t, stats


def test_kstep_at_1_with_sgd_matches_per_step(shard_file):
    """k=1 local-SGD (grad x world, update, pmean) is algebraically the
    per-step psum path for SGD — exact parity modulo float order."""
    a, sa = _train(shard_file, TrainerConfig(
        dense_optimizer="sgd", dense_learning_rate=0.01,
        auc_num_buckets=1 << 10, dense_sync_mode="step"))
    b, sb = _train(shard_file, TrainerConfig(
        dense_optimizer="sgd", dense_learning_rate=0.01,
        auc_num_buckets=1 << 10, dense_sync_mode="kstep",
        dense_sync_interval=1))
    for x, y in zip(sa, sb):
        assert np.isclose(x["loss"], y["loss"], rtol=1e-4), (x, y)
    import jax
    pa = jax.device_get(a.params)
    pb = jax.device_get(b.params)
    for la, lb in zip(jax.tree_util.tree_leaves(pa),
                      jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(la, lb, rtol=1e-4, atol=1e-6)


def test_kstep_interval_learns(shard_file):
    """k=4: fewer dense collectives, model still learns."""
    _, stats = _train(shard_file, TrainerConfig(
        dense_learning_rate=3e-3, auc_num_buckets=1 << 10,
        dense_sync_mode="kstep", dense_sync_interval=4), passes=6)
    assert all(np.isfinite(s["loss"]) for s in stats)
    assert stats[-1]["auc"] > 0.54, [s["auc"] for s in stats]
    assert stats[-1]["auc"] > stats[0]["auc"] + 0.05


def test_async_dense_mode_learns(shard_file):
    """Async host dense table: decoupled Adam still converges."""
    t, stats = _train(shard_file, TrainerConfig(
        dense_learning_rate=3e-3, auc_num_buckets=1 << 10,
        dense_sync_mode="async"), passes=6)
    try:
        assert all(np.isfinite(s["loss"]) for s in stats)
        assert stats[-1]["auc"] > 0.52, [s["auc"] for s in stats]
        assert stats[-1]["auc"] > stats[0]["auc"] + 0.05
        assert t._async_dense.steps_applied > 0
    finally:
        t._async_dense.stop()
