"""Sparse FTRL-proximal parity tests (role of the reference's ftrl op,
operators/optimizers/ftrl_op.cc, at the standard lr_power = -1/2) plus
the sparsity contract the rule exists for and an end-to-end learn check
through the sharded push."""

import numpy as np
import jax.numpy as jnp

from paddlebox_tpu.embedding import (SparseFTRL, TableConfig,
                                     make_pull_fn, make_push_fn,
                                     make_sparse_optimizer)
from paddlebox_tpu.embedding.table import (build_pass_table_host,
                                           map_keys_to_rows)
from paddlebox_tpu.parallel import HybridTopology, build_mesh


def _ftrl_ref_step(v, z, n, g, alpha, l1, l2, beta, lo=-10, hi=10):
    nn = n + g * g
    sigma = (np.sqrt(nn) - np.sqrt(n)) / alpha
    zn = z + g - sigma * v
    denom = (beta + np.sqrt(nn)) / alpha + l2
    vn = np.where(np.abs(zn) <= l1, 0.0,
                  -(zn - np.sign(zn) * l1) / denom)
    return np.clip(vn, lo, hi).astype(np.float32), zn, nn


def test_ftrl_vector_matches_reference_math():
    opt = SparseFTRL(learning_rate=0.1, l1=0.05, l2=0.5, beta=1.0)
    n, d = 5, 3
    rng = np.random.default_rng(0)
    v = rng.normal(size=(n, d)).astype(np.float32)
    g = rng.normal(size=(n, d)).astype(np.float32)
    state = opt.init_emb_state(n, d)
    v1, s1 = opt.update_vector(jnp.asarray(v), jnp.asarray(state),
                               jnp.asarray(g))
    v2, s2 = opt.update_vector(v1, s1, jnp.asarray(g * 0.3))

    z = np.zeros((n, d)); acc = np.zeros((n, d))
    rv, z, acc = _ftrl_ref_step(v, z, acc, g, 0.1, 0.05, 0.5, 1.0)
    np.testing.assert_allclose(np.asarray(v1), rv, rtol=1e-5, atol=1e-6)
    rv, z, acc = _ftrl_ref_step(rv, z, acc, g * 0.3, 0.1, 0.05, 0.5, 1.0)
    np.testing.assert_allclose(np.asarray(v2), rv, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(s2[:, :d]), z, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(s2[:, d:]), acc, rtol=1e-5,
                               atol=1e-6)


def test_ftrl_scalar_and_factory():
    cfg = TableConfig(dim=4, optimizer="ftrl", learning_rate=0.2,
                      ftrl_l1=0.01, ftrl_l2=0.1, ftrl_beta=0.5)
    opt = make_sparse_optimizer(cfg)
    assert isinstance(opt, SparseFTRL)
    assert opt.l1 == 0.01 and opt.l2 == 0.1 and opt.beta == 0.5
    v = np.asarray([0.5, -0.5], np.float32)
    g = np.asarray([0.3, -0.2], np.float32)
    state = opt.init_w_state(2)
    v1, s1 = opt.update_scalar(jnp.asarray(v), jnp.asarray(state),
                               jnp.asarray(g))
    rv, z, acc = _ftrl_ref_step(v, np.zeros(2), np.zeros(2), g,
                                0.2, 0.01, 0.1, 0.5)
    np.testing.assert_allclose(np.asarray(v1), rv, rtol=1e-5, atol=1e-6)


def test_ftrl_l1_drives_small_signals_to_zero():
    """The sparsity contract: a coordinate whose accumulated signal
    stays inside the l1 ball is EXACTLY zero — not merely small."""
    opt = SparseFTRL(learning_rate=0.1, l1=1.0, l2=0.0, beta=1.0)
    v = jnp.asarray(np.zeros((1, 4), np.float32))
    state = jnp.asarray(opt.init_emb_state(1, 4))
    g = jnp.asarray(np.asarray([[0.3, -0.2, 0.1, 0.05]], np.float32))
    v1, s1 = opt.update_vector(v, state, g)
    assert np.all(np.asarray(v1) == 0.0)  # |z| <= l1 everywhere
    # A strong coordinate escapes the ball and moves.
    g2 = jnp.asarray(np.asarray([[5.0, 0.0, 0.0, 0.0]], np.float32))
    v2, _ = opt.update_vector(v1, s1, g2)
    out = np.asarray(v2)
    assert out[0, 0] != 0.0 and np.all(out[0, 1:] == 0.0)


def test_ftrl_through_sharded_push(devices8):
    """8-shard push with duplicates: the accumulated (merged) grad feeds
    one FTRL application per touched row — parity with single shard."""
    n_keys, n_ids, nshards = 48, 96, 8
    rng = np.random.default_rng(2)
    vals = {
        "emb": rng.normal(size=(n_keys, 4)).astype(np.float32),
        "emb_state": np.zeros((n_keys, 8), np.float32),
        "w": rng.normal(size=(n_keys,)).astype(np.float32),
        "w_state": np.zeros((n_keys, 2), np.float32),
        "show": np.zeros((n_keys,), np.float32),
        "click": np.zeros((n_keys,), np.float32),
    }
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    cfg = TableConfig(dim=4, optimizer="ftrl", learning_rate=0.1)
    opt = make_sparse_optimizer(cfg)
    batch_keys = rng.choice(keys, n_ids).astype(np.uint64)
    g_emb = rng.normal(size=(n_ids, 4)).astype(np.float32)
    g_w = rng.normal(size=(n_ids,)).astype(np.float32)
    ones = np.ones((n_ids,), np.float32)

    outs = {}
    for ns in (1, 8):
        table = build_pass_table_host(vals, ns, cfg)
        mesh = build_mesh(HybridTopology(dp=ns),
                          devices=devices8[:ns])
        rows = jnp.asarray(map_keys_to_rows(
            keys, batch_keys, table.rows_per_shard, num_shards=ns))
        pushed = make_push_fn(mesh, "dp", opt)(
            table, rows, jnp.asarray(g_emb), jnp.asarray(g_w),
            jnp.asarray(ones), jnp.asarray(ones * 0))
        pulled = make_pull_fn(mesh, "dp")(pushed, rows)
        outs[ns] = np.asarray(pulled["emb"])
    np.testing.assert_allclose(outs[1], outs[8], rtol=1e-5, atol=1e-6)