"""Predict service over the typed wire: served probabilities must equal
the local predictor's, partial batches pad/strip transparently, and the
live delta-update RPC refreshes the model in place."""

import numpy as np

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.serving import (CTRPredictor, PredictClient,
                                   PredictServer, load_xbox_model)
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("u", "i")


def _train_and_export(tmp_path, rng, passes=1):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,))
    tr = CTRTrainer(model, feed, TableConfig(name="emb", dim=8,
                                             learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10))
    tr.init(seed=0)
    for i in range(passes):
        p = str(tmp_path / f"p{i}")
        with open(p, "w") as f:
            for _ in range(256):
                toks = " ".join(f"{s}:{rng.integers(1, 400)}"
                                for s in SLOTS)
                f.write(f"{int(rng.random() < 0.3)} {toks}\n")
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        tr.train_pass(ds)
    return tr, model, feed


def test_served_predictions_match_local(tmp_path):
    rng = np.random.default_rng(5)
    tr, model, feed = _train_and_export(tmp_path, rng)
    base = str(tmp_path / "xbox")
    tr.engine.store.save_xbox(base)
    keys, emb, w = load_xbox_model(base, table="emb")
    pred = CTRPredictor(model, feed, keys, emb, w,
                        tr.params, compute_dtype="float32")

    server = PredictServer("127.0.0.1:0", pred)
    cli = PredictClient(server.endpoint)
    try:
        lines = [f"0 " + " ".join(f"{s}:{rng.integers(1, 500)}"
                                  for s in SLOTS)
                 for _ in range(feed.batch_size)]
        got = cli.predict(lines)
        ref = pred.predict(SlotBatch.pack(parse_lines(lines, feed), feed))
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        assert got.shape == (feed.batch_size,)

        # Partial batch: padded server-side, stripped in the reply.
        part = cli.predict(lines[:7])
        np.testing.assert_allclose(part, ref[:7], rtol=1e-6)

        # Oversized request is rejected loudly, not truncated.
        try:
            cli.predict(lines + lines[:1])
            assert False, "oversized request must raise"
        except RuntimeError as e:
            assert "split the request" in str(e)

        st = cli.stats()
        assert st["keys"] == keys.shape[0] and st["dim"] == 8
    finally:
        cli.stop_server()
        cli.close()
        server.stop()


def test_malformed_request_gets_error_reply(tmp_path):
    """A well-formed frame whose payload is not a {'method': str} dict
    must get an in-band error REPLY — not a silently-dead connection
    that strands the client until its socket timeout (the shared
    FramedRPCServer contract, distributed/rpc.py)."""
    import socket as socketmod

    import jax

    from paddlebox_tpu.distributed import wire
    from paddlebox_tpu.distributed.transport import _recv_exact

    rng = np.random.default_rng(1)
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=8)
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=())
    keys = np.arange(1, 10, dtype=np.uint64)
    pred = CTRPredictor(model, feed, keys,
                        rng.normal(size=(9, 4)).astype(np.float32),
                        rng.normal(size=(9,)).astype(np.float32),
                        model.init(jax.random.PRNGKey(0)),
                        compute_dtype="float32")
    server = PredictServer("127.0.0.1:0", pred)
    host, port = server.endpoint.rsplit(":", 1)
    s = socketmod.create_connection((host, int(port)), timeout=10)
    try:
        for bad in (["predict"], "predict", {"method": 7}):
            s.sendall(wire.pack_frame(bad))
            ln = wire.read_frame_header(_recv_exact(s, wire.HEADER.size))
            resp = wire.loads(_recv_exact(s, ln))
            assert resp["ok"] is False and "method" in resp["error"]
        # The SAME connection still serves real requests afterwards.
        s.sendall(wire.pack_frame({"method": "stats"}))
        ln = wire.read_frame_header(_recv_exact(s, wire.HEADER.size))
        resp = wire.loads(_recv_exact(s, ln))
        assert resp["ok"] and resp["result"]["keys"] == 9
    finally:
        s.close()
        server.stop()


def test_delta_rpc_refreshes_model(tmp_path):
    import jax

    rng = np.random.default_rng(9)
    tr, model, feed = _train_and_export(tmp_path, rng)
    base = str(tmp_path / "xbox")
    tr.engine.store.save_xbox(base)
    keys, emb, w = load_xbox_model(base, table="emb")
    # The serving process owns its own dense copy (from_dirs loads from
    # disk); sharing live trainer buffers would see them donated by the
    # next train_pass.
    dense_copy = jax.device_get(tr.params)
    pred = CTRPredictor(model, feed, keys, emb, w,
                        dense_copy, compute_dtype="float32")

    # Train a second pass (new keys too) and export its delta.
    p2 = str(tmp_path / "more")
    with open(p2, "w") as f:
        for _ in range(256):
            toks = " ".join(f"{s}:{rng.integers(300, 700)}"
                            for s in SLOTS)
            f.write(f"{int(rng.random() < 0.3)} {toks}\n")
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p2])
    ds.load_into_memory()
    tr.train_pass(ds)
    delta = str(tmp_path / "delta")
    tr.engine.store.save_delta(delta)

    server = PredictServer("127.0.0.1:0", pred)
    cli = PredictClient(server.endpoint)
    try:
        lines = [f"0 " + " ".join(f"{s}:{rng.integers(300, 700)}"
                                  for s in SLOTS)
                 for _ in range(feed.batch_size)]
        before = cli.predict(lines)
        n_new = cli.apply_delta(delta, table="emb")
        assert n_new > 0  # keys in [400, 700) are new to the base
        after = cli.predict(lines)
        # The refreshed model answers differently (trained rows moved)
        # and matches a LOCAL predictor rebuilt from the full sparse
        # export at the SAME dense snapshot (the delta RPC streams the
        # sparse half; dense refreshes ride the dense-checkpoint path).
        assert not np.allclose(before, after)
        full = str(tmp_path / "full")
        tr.engine.store.save_xbox(full)
        k2, e2, w2 = load_xbox_model(full, table="emb")
        cold = CTRPredictor(model, feed, k2, e2, w2, dense_copy,
                            compute_dtype="float32")
        ref = cold.predict(SlotBatch.pack(parse_lines(lines, feed), feed))
        np.testing.assert_allclose(after, ref, rtol=1e-5, atol=1e-6)
    finally:
        cli.stop_server()
        cli.close()
        server.stop()


def test_export_serving_round_trip(tmp_path):
    """CTRTrainer.export_serving -> load_serving_predictor: the one-call
    export serves exactly what a live-params predictor serves."""
    import jax

    from paddlebox_tpu.serving import load_serving_predictor

    rng = np.random.default_rng(21)
    tr, model, feed = _train_and_export(tmp_path, rng)
    out = tr.export_serving(str(tmp_path / "exp"))
    assert out["features"] > 0

    pred = load_serving_predictor(model, feed, str(tmp_path / "exp"),
                                  compute_dtype="float32")

    keys, emb, w = load_xbox_model(out["xbox"], table="emb")
    ref = CTRPredictor(model, feed, keys, emb, w,
                       jax.device_get(tr.params),
                       compute_dtype="float32")
    lines = [f"0 u:{rng.integers(1, 500)} i:{rng.integers(1, 500)}"
             for _ in range(feed.batch_size)]
    batch = SlotBatch.pack(parse_lines(lines, feed), feed)
    np.testing.assert_allclose(pred.predict(batch), ref.predict(batch),
                               rtol=1e-6)


def test_export_serving_preserves_data_norm(tmp_path):
    """The meta-driven load keeps the trainer-added data_norm stats — a
    plain model.init template would silently drop them (load_pytree
    ignores extra file keys) and serve un-normalized probabilities."""
    import jax

    from paddlebox_tpu.serving import load_serving_predictor

    rng = np.random.default_rng(23)
    mesh = build_mesh(HybridTopology(dp=8))
    slots = tuple(SlotConf(s, avg_len=1.0) for s in SLOTS)
    slots += (SlotConf("d", is_dense=True, dim=3),)
    feed = DataFeedConfig(slots=slots, batch_size=64)
    model = DeepFM(slot_names=SLOTS, emb_dim=8, dense_dim=3, hidden=(16,))
    tr = CTRTrainer(model, feed, TableConfig(name="emb", dim=8,
                                             learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10,
                                         data_norm=True))
    tr.init(seed=0)
    p = str(tmp_path / "p0")
    with open(p, "w") as f:
        for _ in range(256):
            toks = " ".join(f"{s}:{rng.integers(1, 300)}" for s in SLOTS)
            dv = ",".join(f"{rng.random() * 9:.3f}" for _ in range(3))
            f.write(f"{int(rng.random() < 0.3)} {toks} d:{dv}\n")
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    tr.train_pass(ds)

    out = tr.export_serving(str(tmp_path / "exp"))
    pred = load_serving_predictor(model, feed, str(tmp_path / "exp"),
                                  compute_dtype="float32")
    assert "data_norm" in pred._dense_params  # stats survived the load

    keys, emb, w = load_xbox_model(out["xbox"], table="emb")
    ref = CTRPredictor(model, feed, keys, emb, w,
                       jax.device_get(tr.params),
                       compute_dtype="float32")
    lines = []
    for _ in range(feed.batch_size):
        toks = " ".join(f"{s}:{rng.integers(1, 300)}" for s in SLOTS)
        dv = ",".join(f"{rng.random() * 9:.3f}" for _ in range(3))
        lines.append(f"0 {toks} d:{dv}")
    batch = SlotBatch.pack(parse_lines(lines, feed), feed)
    np.testing.assert_allclose(pred.predict(batch), ref.predict(batch),
                               rtol=1e-6)


def test_serving_slo_quantiles_and_client_latency(tmp_path):
    """The serving SLO layer: handle_stats returns server-side latency
    quantiles + uptime + throughput, a sub-ms SLO target counts every
    predict as a violation, and the client's end-to-end digest records
    wire-inclusive latencies >= nothing (separable from server time)."""
    import jax

    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.core import monitor

    rng = np.random.default_rng(21)
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=8)
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=())
    keys = np.arange(1, 50, dtype=np.uint64)
    pred = CTRPredictor(model, feed, keys,
                        rng.normal(size=(49, 4)).astype(np.float32),
                        rng.normal(size=(49,)).astype(np.float32),
                        model.init(jax.random.PRNGKey(0)),
                        compute_dtype="float32")
    monitor.reset()
    prev = flagmod.flag("serving_slo_p99_ms")
    flagmod.set_flags({"serving_slo_p99_ms": 1e-6})  # everything breaches
    server = PredictServer("127.0.0.1:0", pred)
    cli = PredictClient(server.endpoint)
    try:
        lines = ["0 " + " ".join(f"{s}:{rng.integers(1, 40)}"
                                 for s in SLOTS)
                 for _ in range(feed.batch_size)]
        n_rpcs = 5
        for _ in range(n_rpcs):
            cli.predict(lines)
        st = cli.stats()
        assert st["latency_count"] == n_rpcs
        lat = st["latency_ms"]
        assert lat["p50"] is not None and lat["p50"] > 0.0
        assert lat["p50"] <= lat["p99"] <= lat["p999"]
        assert st["uptime_s"] > 0.0
        assert st["throughput_rps"] > 0.0
        assert st["slo_p99_ms"] == 1e-6
        assert st["slo_violations"] == n_rpcs
        # Client-side end-to-end digest: wire-inclusive, so every
        # percentile is >= the corresponding server-side one.
        cq = cli.latency_quantiles()
        assert cq["count"] == n_rpcs
        assert cq["p50"] >= lat["p50"]
        # Registry carries the mergeable digest + throughput gauge.
        snap = monitor.snapshot_all()
        assert snap["quantiles"]["serving/predict_ms"]["count"] == n_rpcs
        assert snap["gauges"]["serving/throughput_rps"] > 0.0
        assert snap["counters"]["slo/violations"] == n_rpcs

        # SLO off (default): violations stop counting, quantiles remain.
        flagmod.set_flags({"serving_slo_p99_ms": 0.0})
        cli.predict(lines)
        st2 = cli.stats()
        assert st2["slo_violations"] == n_rpcs
        assert st2["latency_count"] == n_rpcs + 1
    finally:
        flagmod.set_flags({"serving_slo_p99_ms": prev})
        cli.stop_server()
        cli.close()
        server.stop()
        monitor.reset()
