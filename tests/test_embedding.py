"""Sparse embedding engine tests (SURVEY.md §2.2/2.3 roles).

The key correctness bar, mirroring the reference's HeterPS device test
(``heter_ps/test_comm.cu``): pull returns exactly the stored rows; push
applies one exact merged update per touched row; multi-shard (8-device
all-to-all) results equal single-shard results.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.embedding import (FeatureStore, PassEngine, SparseAdagrad,
                                     TableConfig, make_pull_fn, make_push_fn)
from paddlebox_tpu.embedding.table import (build_pass_table_host,
                                           extract_pass_values_host,
                                           map_keys_to_rows, plan_shards)
from paddlebox_tpu.parallel import HybridTopology, build_mesh

DIM = 4
CFG = TableConfig(dim=DIM, learning_rate=0.1, initial_g2sum=1.0)


def _host_values(n, dim, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "emb": rng.normal(size=(n, dim)).astype(np.float32),
        "emb_state": np.zeros((n, 1), np.float32),
        "w": rng.normal(size=(n,)).astype(np.float32),
        "w_state": np.zeros((n, 1), np.float32),
        "show": np.zeros((n,), np.float32),
        "click": np.zeros((n,), np.float32),
    }


def _adagrad_ref(v, g2, g, lr=0.1, ig=1.0, scalar=False):
    if scalar:
        g2n = g2 + g * g
        scale = np.sqrt(ig / (ig + g2n))
        return np.clip(v - lr * scale * g, -10, 10), g2n
    g2n = g2 + (g * g).mean(axis=-1)
    scale = np.sqrt(ig / (ig + g2n))
    return np.clip(v - lr * scale[:, None] * g, -10, 10), g2n


def test_map_keys_to_rows():
    keys = np.array([3, 7, 10, 15, 22, 30, 41, 55], np.uint64)
    rps = plan_shards(8, 2)  # 4 rows/shard
    rows = map_keys_to_rows(keys, np.array([3, 55, 99, 0, 22], np.uint64),
                            rps, num_shards=2)
    # Round-robin deal: rank g -> shard g % S, slot g // S (block rps+1).
    assert rows[0] == 0                    # key 3 -> g0 -> shard0 slot0
    assert rows[1] == 1 * (rps + 1) + 3    # 55 -> g7 -> shard1 slot3
    # Sentinels spread round-robin over shards' trash rows by position:
    assert rows[2] == 0 * (rps + 1) + rps  # pos 2 -> shard 0 trash
    assert rows[3] == 1 * (rps + 1) + rps  # pos 3 -> shard 1 trash
    assert rows[4] == 0 * (rps + 1) + 2    # 22 -> g4 -> shard0 slot2


def test_sentinels_spread_evenly():
    # Regression: padding concentrated on shard 0 would overflow its
    # all-to-all bucket; sentinels must hit every shard's trash row.
    rows = map_keys_to_rows(np.array([5], np.uint64),
                            np.zeros(64, np.uint64), 4, num_shards=8)
    shards = rows // 5  # block = rps+1 = 5
    np.testing.assert_array_equal(np.bincount(shards, minlength=8),
                                  [8] * 8)


def test_table_roundtrip_host():
    n = 13
    vals = _host_values(n, DIM)
    t = build_pass_table_host(vals, 4, CFG)
    assert t.num_shards == 4
    back = extract_pass_values_host(t, n)
    for f in vals:
        np.testing.assert_allclose(back[f], vals[f], rtol=1e-6)


@pytest.mark.parametrize("nshards", [1, 8])
def test_pull_matches_reference(devices8, nshards):
    n_keys, n_ids = 64, 128
    vals = _host_values(n_keys, DIM)
    keys = np.sort(np.random.default_rng(1).choice(
        np.arange(1, 10_000, dtype=np.uint64), n_keys, replace=False))
    table = build_pass_table_host(vals, nshards, CFG)
    mesh = build_mesh(HybridTopology(dp=nshards),
                      devices8[:nshards] if nshards > 1 else devices8[:1])
    pull = make_pull_fn(mesh, "dp")

    rng = np.random.default_rng(2)
    batch_keys = rng.choice(keys, n_ids).astype(np.uint64)
    batch_keys[5] = 9999  # unknown key
    rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
    out = pull(table, jnp.asarray(rows))

    g = np.searchsorted(keys, batch_keys)
    ref = np.zeros((n_ids, DIM), np.float32)
    known = batch_keys != 9999
    ref[known] = vals["emb"][g[known]]
    np.testing.assert_allclose(np.asarray(out["emb"]), ref, rtol=1e-5)
    ref_w = np.zeros((n_ids,), np.float32)
    ref_w[known] = vals["w"][g[known]]
    np.testing.assert_allclose(np.asarray(out["w"]), ref_w, rtol=1e-5)


@pytest.mark.parametrize("nshards", [1, 8])
def test_push_exact_dedup_update(devices8, nshards):
    n_keys, n_ids = 32, 64
    vals = _host_values(n_keys, DIM, seed=3)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    table = build_pass_table_host(vals, nshards, CFG)
    mesh = build_mesh(HybridTopology(dp=nshards),
                      devices8[:nshards] if nshards > 1 else devices8[:1])
    opt = SparseAdagrad(learning_rate=0.1, initial_g2sum=1.0)
    push = make_push_fn(mesh, "dp", opt)

    rng = np.random.default_rng(4)
    batch_keys = rng.choice(keys, n_ids).astype(np.uint64)  # duplicates!
    rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
    g_emb = rng.normal(size=(n_ids, DIM)).astype(np.float32)
    g_w = rng.normal(size=(n_ids,)).astype(np.float32)
    shows = np.ones((n_ids,), np.float32)
    clicks = (rng.random(n_ids) < 0.3).astype(np.float32)

    new_table = push(table, jnp.asarray(rows), jnp.asarray(g_emb),
                     jnp.asarray(g_w), jnp.asarray(shows),
                     jnp.asarray(clicks))
    back = extract_pass_values_host(new_table, n_keys)

    # numpy reference: merge grads per key, single update per key.
    ref_emb, ref_g2 = vals["emb"].copy(), vals["emb_state"].copy()
    ref_w_, ref_wg2 = vals["w"].copy(), vals["w_state"].copy()
    ref_show, ref_click = vals["show"].copy(), vals["click"].copy()
    for ki, key in enumerate(keys):
        m = batch_keys == key
        if not m.any():
            continue
        ge = g_emb[m].sum(axis=0)
        gw = g_w[m].sum()
        ref_emb[ki:ki+1], ref_g2[ki:ki+1, 0] = _adagrad_ref(
            ref_emb[ki:ki+1], ref_g2[ki:ki+1, 0], ge[None])
        ref_w_[ki:ki+1], ref_wg2[ki:ki+1, 0] = _adagrad_ref(
            ref_w_[ki:ki+1], ref_wg2[ki:ki+1, 0], np.array([gw]), scalar=True)
        ref_show[ki] += shows[m].sum()
        ref_click[ki] += clicks[m].sum()

    np.testing.assert_allclose(back["emb"], ref_emb, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(back["emb_state"], ref_g2, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(back["w"], ref_w_, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(back["show"], ref_show, rtol=1e-5)
    np.testing.assert_allclose(back["click"], ref_click, rtol=1e-5)


def test_multi_shard_equals_single_shard(devices8):
    """8-way all-to-all pull/push == single-device result (the test_comm.cu
    parity bar)."""
    n_keys, n_ids = 50, 96
    vals = _host_values(n_keys, DIM, seed=7)
    keys = np.sort(np.random.default_rng(8).choice(
        np.arange(1, 100_000, dtype=np.uint64), n_keys, replace=False))
    rng = np.random.default_rng(9)
    batch_keys = rng.choice(keys, n_ids).astype(np.uint64)
    g_emb = rng.normal(size=(n_ids, DIM)).astype(np.float32)
    g_w = rng.normal(size=(n_ids,)).astype(np.float32)
    shows = np.ones((n_ids,), np.float32)
    clicks = np.zeros((n_ids,), np.float32)

    results = {}
    for nshards in (1, 8):
        table = build_pass_table_host(vals, nshards, CFG)
        mesh = build_mesh(HybridTopology(dp=nshards),
                          devices8[:nshards] if nshards > 1 else devices8[:1])
        rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
        pull = make_pull_fn(mesh, "dp")
        push = make_push_fn(mesh, "dp", SparseAdagrad.from_config(CFG))
        pulled = pull(table, jnp.asarray(rows))
        new_table = push(table, jnp.asarray(rows), jnp.asarray(g_emb),
                         jnp.asarray(g_w), jnp.asarray(shows),
                         jnp.asarray(clicks))
        results[nshards] = (np.asarray(pulled["emb"]),
                            extract_pass_values_host(new_table, n_keys))

    np.testing.assert_allclose(results[1][0], results[8][0], rtol=1e-5)
    for f in results[1][1]:
        np.testing.assert_allclose(results[1][1][f], results[8][1][f],
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"field {f}")


def test_store_pass_cycle(tmp_path):
    store = FeatureStore(CFG, seed=0)
    keys1 = np.array([5, 9, 14], np.uint64)
    v1 = store.pull_for_pass(keys1)
    assert v1["emb"].shape == (3, DIM)
    v1["w"][:] = [1.0, 2.0, 3.0]
    store.push_from_pass(keys1, v1)
    assert store.num_features == 3

    # Second pass: overlap {9, 14} + new {20}; existing values persist.
    keys2 = np.array([9, 14, 20], np.uint64)
    v2 = store.pull_for_pass(keys2)
    np.testing.assert_allclose(v2["w"][:2], [2.0, 3.0])
    v2["w"][:] = [4.0, 5.0, 6.0]
    store.push_from_pass(keys2, v2)
    assert store.num_features == 4

    # base+delta checkpoint round trip.
    store.save_base(str(tmp_path / "base"))
    keys3 = np.array([5], np.uint64)
    v3 = store.pull_for_pass(keys3)
    v3["w"][:] = [7.0]
    store.push_from_pass(keys3, v3)
    store.save_delta(str(tmp_path / "delta"))

    restored = FeatureStore(CFG)
    restored.load(str(tmp_path / "base"), "base")
    assert restored.num_features == 4
    np.testing.assert_allclose(
        restored.pull_for_pass(np.array([5], np.uint64))["w"], [1.0])
    restored.load(str(tmp_path / "delta"), "delta")
    np.testing.assert_allclose(
        restored.pull_for_pass(np.array([5], np.uint64))["w"], [7.0])


def test_store_shrink():
    store = FeatureStore(TableConfig(dim=DIM, show_click_decay=0.5))
    keys = np.array([1, 2, 3], np.uint64)
    v = store.pull_for_pass(keys)
    v["show"][:] = [10.0, 0.1, 5.0]
    store.push_from_pass(keys, v)
    evicted = store.shrink(min_show=1.0)
    assert evicted == 1  # key 2 (0.05 after decay) evicted
    assert store.num_features == 2


def test_pass_engine_lifecycle(devices8):
    mesh = build_mesh(HybridTopology(dp=8), devices8)
    eng = PassEngine(CFG, mesh=mesh, table_axis="dp")
    batch_keys = np.array([11, 22, 33, 44, 11, 22, 33, 44], np.uint64)

    eng.feed_pass(batch_keys, async_build=True)
    table = eng.begin_pass()
    assert table.num_shards == 8
    rows = eng.lookup_rows(batch_keys)
    assert rows.shape == (8,)  # sharded pull needs len % ndev == 0
    pull = make_pull_fn(mesh, "dp")
    out = pull(table, jnp.asarray(rows))
    # same key -> same embedding row
    np.testing.assert_allclose(np.asarray(out["emb"])[0],
                               np.asarray(out["emb"])[4])
    eng.end_pass()
    assert eng.store.num_features == 4

    with pytest.raises(RuntimeError):
        eng.end_pass()


def test_map_keys_empty_pass():
    rows = map_keys_to_rows(np.empty((0,), np.uint64),
                            np.array([1, 2], np.uint64), 4)
    np.testing.assert_array_equal(rows, [4, 4])  # all sentinel


def test_save_delta_refuses_after_shrink(tmp_path):
    store = FeatureStore(CFG)
    keys = np.array([1, 2], np.uint64)
    store.push_from_pass(keys, store.pull_for_pass(keys))
    store.save_base(str(tmp_path / "b"))
    store.shrink()
    with pytest.raises(RuntimeError, match="save_base first"):
        store.save_delta(str(tmp_path / "d"))
    store.save_base(str(tmp_path / "b2"))
    store.save_delta(str(tmp_path / "d"))  # ok again after new base


def test_overflow_counter_on_skewed_keys(devices8):
    """Adversarial skew: every batch id targets ONE shard with DISTINCT
    keys (a hot shard — the one skew dedup cannot absorb), overflowing
    its fixed-capacity bucket. The overflow counter must surface exactly
    the dropped lookups (which degrade to zeros) instead of failing
    silently — the accuracy contract of FLAGS_embedding_shard_slack."""
    from paddlebox_tpu.embedding.lookup import bucket_capacity

    n_keys, n_ids, nshards = 1024, 64, 8
    vals = _host_values(n_keys, DIM)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    table = build_pass_table_host(vals, nshards, CFG)
    mesh = build_mesh(HybridTopology(dp=nshards), devices8)
    pull = make_pull_fn(mesh, "dp")

    # Distinct keys whose ranks are all ≡ 0 (mod nshards) -> all land in
    # shard 0's bucket on every device (round-robin deal: shard = rank %
    # nshards), so dedup cannot absorb the skew.
    batch_keys = np.tile(
        1 + nshards * np.arange(n_ids, dtype=np.uint64), nshards)
    rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
    out = pull(table, jnp.asarray(rows))

    cap = bucket_capacity(n_ids, nshards)
    expected_drop_per_dev = max(0, n_ids - cap)
    assert expected_drop_per_dev > 0, "test needs actual overflow"
    overflow = np.asarray(out["overflow"])
    assert overflow.shape == (nshards,)
    assert overflow.sum() == expected_drop_per_dev * nshards
    # Dropped lookups return zeros; the in-capacity prefix returns the row.
    per_dev_emb = np.asarray(out["emb"]).reshape(nshards, n_ids, DIM)
    n_zero = (np.abs(per_dev_emb).sum(-1) == 0).sum(axis=1)
    assert (n_zero == expected_drop_per_dev).all()


def test_hot_key_dedup_no_overflow(devices8):
    """The VERDICT-r04 contract: a hot key making up 30% of a device's
    ids (the realistic CTR skew) must NOT overflow at default slack —
    dedup collapses every repetition into one bucket cell
    (dedup_keys_and_fillidx role, heter_comm.h:192) — and every
    occurrence must still pull the exact stored row."""
    n_keys, n_ids, nshards = 1024, 160, 8
    vals = _host_values(n_keys, DIM)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    table = build_pass_table_host(vals, nshards, CFG)
    mesh = build_mesh(HybridTopology(dp=nshards), devices8)
    pull = make_pull_fn(mesh, "dp")

    rng = np.random.default_rng(7)
    hot = int(0.3 * n_ids)
    per_dev = []
    for d in range(nshards):
        # One hot key (different per device) at 30%, rest uniform draws
        # WITH repetition — duplicates everywhere, like real CTR data.
        hot_key = np.uint64(1 + rng.integers(0, n_keys))
        rest = rng.integers(1, n_keys + 1, size=n_ids - hot).astype(
            np.uint64)
        ids = np.concatenate([np.full((hot,), hot_key, np.uint64), rest])
        per_dev.append(rng.permutation(ids))
    batch_keys = np.concatenate(per_dev)
    rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
    out = pull(table, jnp.asarray(rows))

    assert np.asarray(out["overflow"]).sum() == 0
    np.testing.assert_allclose(np.asarray(out["emb"]),
                               vals["emb"][batch_keys - 1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               vals["w"][batch_keys - 1], rtol=1e-6)


def test_dedup_parity_with_nondedup(devices8):
    """Dedup is a layout change, not a math change: pull values and the
    pushed table must be bit-identical with the flag on and off (when
    neither path overflows) — sender-side duplicate-grad merging
    (dynamic_merge_grad role) commutes with the owner-side accumulate."""
    from paddlebox_tpu.core import flags as flagmod

    n_keys, n_ids, nshards = 512, 64, 8
    vals = _host_values(n_keys, DIM)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    mesh = build_mesh(HybridTopology(dp=nshards), devices8)
    rng = np.random.default_rng(11)
    batch_keys = rng.integers(1, n_keys + 1,
                              size=n_ids * nshards).astype(np.uint64)
    g_emb = rng.normal(size=(n_ids * nshards, DIM)).astype(np.float32)
    g_w = rng.normal(size=(n_ids * nshards,)).astype(np.float32)
    shows = np.ones((n_ids * nshards,), np.float32)
    clicks = rng.integers(0, 2, n_ids * nshards).astype(np.float32)

    results = {}
    prev = flagmod.flag("embedding_dedup")
    for dedup in (True, False):
        flagmod.set_flags({"embedding_dedup": dedup})
        try:
            table = build_pass_table_host(vals, nshards, CFG)
            rows = jnp.asarray(map_keys_to_rows(
                keys, batch_keys, table.rows_per_shard,
                num_shards=nshards))
            pulled = make_pull_fn(mesh, "dp")(table, rows)
            assert np.asarray(pulled["overflow"]).sum() == 0
            pushed = make_push_fn(mesh, "dp")(
                table, rows, jnp.asarray(g_emb), jnp.asarray(g_w),
                jnp.asarray(shows), jnp.asarray(clicks))
            results[dedup] = (np.asarray(pulled["emb"]),
                              np.asarray(pushed.vals))
        finally:
            flagmod.set_flags({"embedding_dedup": prev})
    np.testing.assert_array_equal(results[True][0], results[False][0])
    np.testing.assert_allclose(results[True][1], results[False][1],
                               rtol=1e-6, atol=1e-7)


def test_unique_frac_shrinks_exchange_bytes(devices8):
    """FLAGS_embedding_unique_frac turns dedup into an all-to-all byte
    reduction: capacity (and so exchange_bytes) shrinks, and a
    duplicate-heavy batch still overflows nothing at the smaller cap."""
    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.embedding.lookup import (bucket_capacity,
                                                exchange_bytes)

    n_keys, n_ids, nshards = 1024, 256, 8
    vals = _host_values(n_keys, DIM)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    table = build_pass_table_host(vals, nshards, CFG)
    mesh = build_mesh(HybridTopology(dp=nshards), devices8)

    bytes_full = exchange_bytes(table, n_ids)
    prev = flagmod.flag("embedding_unique_frac")
    flagmod.set_flags({"embedding_unique_frac": 0.5})
    try:
        assert bucket_capacity(n_ids, nshards) < bucket_capacity(
            n_ids, nshards, unique_frac=1.0)
        bytes_half = exchange_bytes(table, n_ids)
        assert bytes_half < bytes_full

        # Each id appears ~4x (256 draws from 64 distinct keys): unique
        # count per device is <= 64, well inside the halved capacity.
        rng = np.random.default_rng(13)
        batch_keys = rng.choice(
            np.arange(1, n_keys + 1, dtype=np.uint64), 64,
            replace=False)[rng.integers(0, 64, size=n_ids * nshards)]
        rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                                num_shards=nshards)
        out = make_pull_fn(mesh, "dp")(table, jnp.asarray(rows))
        assert np.asarray(out["overflow"]).sum() == 0
        np.testing.assert_allclose(np.asarray(out["emb"]),
                                   vals["emb"][batch_keys - 1], rtol=1e-6)
    finally:
        flagmod.set_flags({"embedding_unique_frac": prev})


def test_no_overflow_under_uniform_keys(devices8):
    """Uniformly-hashed ids stay within capacity (the 4-sigma headroom
    contract) — counter reads zero."""
    n_keys, n_ids, nshards = 1024, 256, 8
    vals = _host_values(n_keys, DIM)
    keys = np.sort(np.random.default_rng(3).choice(
        np.arange(1, 1 << 20, dtype=np.uint64), n_keys, replace=False))
    table = build_pass_table_host(vals, nshards, CFG)
    mesh = build_mesh(HybridTopology(dp=nshards), devices8)
    pull = make_pull_fn(mesh, "dp")
    rng = np.random.default_rng(4)
    batch_keys = rng.choice(keys, n_ids * nshards).astype(np.uint64)
    rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                            num_shards=nshards)
    out = pull(table, jnp.asarray(rows))
    assert np.asarray(out["overflow"]).sum() == 0
