"""Fleet facade tests: DistributedStrategy resolution, fleet.init mesh
wiring, distributed_optimizer (gradient merge / DGC / AMP), and
fleet.metrics distributed reductions (parity vs brute-force references,
mirroring the reference's metric.py unit tests)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import paddlebox_tpu.fleet as fleet
from paddlebox_tpu.fleet import metrics as fmetrics
from paddlebox_tpu.fleet.strategy import DistributedStrategy
from paddlebox_tpu.parallel.dgc import dgc_transform


# ---------------------------------------------------------------------------
# DistributedStrategy
# ---------------------------------------------------------------------------

def test_strategy_topology_resolution():
    st = DistributedStrategy(hybrid_configs={"dp_degree": 2, "mp_degree": 2,
                                             "pp_degree": 2})
    topo = st.topology(world_size=8)
    assert topo.dp == 2 and topo.mp == 2 and topo.pp == 2
    assert topo.world_size == 8


def test_strategy_dp_fill_rest():
    st = DistributedStrategy(hybrid_configs={"dp_degree": -1, "mp_degree": 4})
    topo = st.topology(world_size=8)
    assert topo.dp == 2 and topo.mp == 4


def test_strategy_validation_errors():
    with pytest.raises(ValueError):
        DistributedStrategy(hybrid_configs={"bogus_degree": 2}).topology()
    with pytest.raises(ValueError):
        DistributedStrategy(hybrid_configs={"mp_degree": 3}).topology(
            world_size=8)
    with pytest.raises(ValueError):  # pipeline=True but pp_degree==1
        DistributedStrategy(pipeline=True).topology(world_size=8)


def test_strategy_dict_roundtrip():
    st = DistributedStrategy(amp=True, gradient_merge=True)
    st.gradient_merge_configs.k_steps = 4
    st2 = DistributedStrategy.from_dict(st.to_dict())
    assert st2.amp and st2.gradient_merge_configs.k_steps == 4
    assert dataclasses.asdict(st) == dataclasses.asdict(st2)


# ---------------------------------------------------------------------------
# fleet.init + distributed_optimizer
# ---------------------------------------------------------------------------

def test_fleet_init_builds_mesh(devices8):
    st = DistributedStrategy(hybrid_configs={"dp_degree": 4, "mp_degree": 2})
    mesh = fleet.init(strategy=st, devices=devices8)
    assert mesh.shape["dp"] == 4 and mesh.shape["mp"] == 2
    assert fleet.worker_num() >= 1
    assert fleet.is_first_worker() == (fleet.worker_index() == 0)
    fleet.barrier_worker()  # single-process: no-op


def test_distributed_optimizer_gradient_merge(devices8):
    fleet.init(strategy=DistributedStrategy(), devices=devices8)
    st = DistributedStrategy(gradient_merge=True)
    st.gradient_merge_configs.k_steps = 4
    dopt = fleet.distributed_optimizer(optax.sgd(1.0), strategy=st)
    params = {"w": jnp.ones((4,))}
    state = dopt.init(params)
    g = {"w": jnp.full((4,), 2.0)}
    for i in range(4):
        updates, state = dopt.update(g, state, params)
        params = optax.apply_updates(params, updates)
        if i < 3:  # accumulating: no update applied yet
            np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
    # after k=4 steps the mean grad (2.0) is applied once: 1 - 2 = -1
    np.testing.assert_allclose(np.asarray(params["w"]), -1.0, rtol=1e-6)


def test_distributed_optimizer_amp_and_clip(devices8):
    fleet.init(strategy=DistributedStrategy(), devices=devices8)
    st = DistributedStrategy(amp=True, clip_norm=1.0)
    st.amp_configs.use_dynamic_loss_scaling = True
    dopt = fleet.distributed_optimizer("adam", strategy=st,
                                       learning_rate=1e-3)
    assert dopt.amp_policy is not None
    assert dopt.loss_scale is not None
    params = {"w": jnp.ones((3,))}
    state = dopt.init(params)
    updates, _ = dopt.update({"w": jnp.full((3,), 100.0)}, state, params)
    # clip_norm bounds the grad seen by adam; update magnitude stays sane
    assert float(jnp.max(jnp.abs(updates["w"]))) < 1.0


def test_distributed_model_recompute(devices8):
    fleet.init(strategy=DistributedStrategy(), devices=devices8)
    st = DistributedStrategy(recompute=True)

    def f(x):
        return jnp.sum(jnp.tanh(x) ** 2)

    g = fleet.distributed_model(f, strategy=st)
    x = jnp.linspace(-1, 1, 8)
    np.testing.assert_allclose(np.asarray(jax.grad(g)(x)),
                               np.asarray(jax.grad(f)(x)), rtol=1e-6)


def test_fleet_init_validates_strategy_without_hybrid_configs(devices8):
    with pytest.raises(ValueError):
        fleet.init(strategy=DistributedStrategy(pipeline=True),
                   devices=devices8)


def test_distributed_optimizer_lars_lamb_wiring(devices8):
    fleet.init(strategy=DistributedStrategy(), devices=devices8)
    # by-name base is replaced by the large-batch rule
    dopt = fleet.distributed_optimizer(
        "momentum", strategy=DistributedStrategy(lars=True),
        learning_rate=0.1)
    assert dopt.tx is not None
    # optax-object base + lars is an error, not a silent no-op
    with pytest.raises(ValueError):
        fleet.distributed_optimizer(optax.sgd(0.1),
                                    strategy=DistributedStrategy(lars=True))
    # name without learning_rate is an error, not a silent 1e-3
    with pytest.raises(ValueError):
        fleet.distributed_optimizer("adam",
                                    strategy=DistributedStrategy())
    with pytest.raises(ValueError):
        fleet.distributed_optimizer(
            "sgd", strategy=DistributedStrategy(lars=True, lamb=True),
            learning_rate=0.1)


def test_distributed_optimizer_amp_dtype_validation(devices8):
    fleet.init(strategy=DistributedStrategy(), devices=devices8)
    st = DistributedStrategy(amp=True)
    st.amp_configs.dtype = "bf16"  # alias accepted
    assert fleet.distributed_optimizer(optax.sgd(0.1), strategy=st) \
        .amp_policy.compute_dtype == jnp.bfloat16
    st.amp_configs.dtype = "float32"
    with pytest.raises(ValueError):
        fleet.distributed_optimizer(optax.sgd(0.1), strategy=st)


def test_loss_scale_backoff_interval():
    from paddlebox_tpu import amp
    state = amp.loss_scale_init(1024.0, backoff_interval=2)
    bad = {"w": jnp.asarray([jnp.inf])}
    # first non-finite step: update skipped but scale held (interval=2)
    _, finite, state = amp.unscale_and_check(state, bad)
    assert not bool(finite)
    assert float(state.scale) == 1024.0
    # second consecutive non-finite: back off
    _, _, state = amp.unscale_and_check(state, bad)
    assert float(state.scale) == 512.0
    # counter reset after backoff
    assert int(state.nonfinite_tracker) == 0


# ---------------------------------------------------------------------------
# DGC
# ---------------------------------------------------------------------------

def test_dgc_tuple_pytree_structure():
    """Grads whose pytree contains tuples as containers must not be
    scrambled by the out/residual split."""
    tx = dgc_transform(sparsity=0.75, rampup_begin_step=0)
    g = (jnp.asarray([1.0, 2.0, 3.0, 4.0]),
         jnp.asarray([10.0, 20.0, 30.0, 40.0]))
    state = tx.init(g)
    out, state = tx.update(g, state)
    np.testing.assert_allclose(np.asarray(out[0]), [0, 0, 0, 4.0])
    np.testing.assert_allclose(np.asarray(out[1]), [0, 0, 0, 40.0])
    np.testing.assert_allclose(np.asarray(state.residual[0]),
                               [1.0, 2.0, 3.0, 0.0])
    np.testing.assert_allclose(np.asarray(state.residual[1]),
                               [10.0, 20.0, 30.0, 0.0])

def test_dgc_sparsifies_and_feeds_back_error():
    tx = dgc_transform(sparsity=0.75, rampup_begin_step=0)
    g = {"w": jnp.asarray([1.0, 2.0, 3.0, 4.0])}
    state = tx.init(g)
    out, state = tx.update(g, state)
    # keep top 25% -> only the largest entry survives
    np.testing.assert_allclose(np.asarray(out["w"]), [0, 0, 0, 4.0])
    # residual carries the dropped mass
    np.testing.assert_allclose(np.asarray(state.residual["w"]),
                               [1.0, 2.0, 3.0, 0.0])
    # next step: residual + new grad competes for top-k
    out2, state2 = tx.update({"w": jnp.asarray([0.1, 0.1, 2.0, 0.1])}, state)
    np.testing.assert_allclose(np.asarray(out2["w"]), [0, 0, 5.0, 0])
    # conservation: emitted + residual == total injected
    total = np.asarray(out["w"]) + np.asarray(out2["w"]) \
        + np.asarray(state2.residual["w"])
    np.testing.assert_allclose(total, [1.1, 2.1, 5.0, 4.1], rtol=1e-6)


def test_dgc_rampup_passthrough():
    tx = dgc_transform(sparsity=0.99, rampup_begin_step=10)
    g = {"w": jnp.arange(8.0)}
    state = tx.init(g)
    out, state = tx.update(g, state)
    np.testing.assert_allclose(np.asarray(out["w"]), np.arange(8.0))
    np.testing.assert_allclose(np.asarray(state.residual["w"]), 0.0)


# ---------------------------------------------------------------------------
# fleet.metrics
# ---------------------------------------------------------------------------

def _brute_auc(preds, labels):
    """O(P*N) exact AUC."""
    pos = preds[labels == 1]
    neg = preds[labels == 0]
    wins = (pos[:, None] > neg[None, :]).sum() \
        + 0.5 * (pos[:, None] == neg[None, :]).sum()
    return wins / (len(pos) * len(neg))


def test_fleet_metrics_auc_parity():
    rng = np.random.default_rng(0)
    nb = 1000
    preds = rng.integers(0, nb, 5000) / nb  # quantized -> bucketing is exact
    labels = (rng.random(5000) < preds).astype(np.int64)
    stat_pos = np.bincount((preds[labels == 1] * nb).astype(int),
                           minlength=nb)
    stat_neg = np.bincount((preds[labels == 0] * nb).astype(int),
                           minlength=nb)
    got = fmetrics.auc(stat_pos, stat_neg)
    want = _brute_auc(preds, labels)
    np.testing.assert_allclose(got, want, atol=1e-9)


def test_fleet_metrics_distributed_via_store(tmp_path):
    from paddlebox_tpu.distributed.transport import FileStore
    s0 = FileStore(str(tmp_path), 0, 2)
    s1 = FileStore(str(tmp_path), 1, 2)
    import threading
    results = {}

    def worker(store, rank):
        red = fmetrics.make_store_reduce(store)
        # each rank holds half the error mass
        results[rank] = fmetrics.mae(abserr=10.0 * (rank + 1),
                                     total_ins_num=50.0, reduce=red)

    ts = [threading.Thread(target=worker, args=(s, r))
          for r, s in ((0, s0), (1, s1))]
    [t.start() for t in ts]
    [t.join() for t in ts]
    # global mae = (10+20)/(50+50) = 0.3 on both ranks
    assert results[0] == pytest.approx(0.3)
    assert results[1] == pytest.approx(0.3)


def test_fleet_metrics_scalar_helpers():
    assert fmetrics.acc(correct=30, total=40) == pytest.approx(0.75)
    assert fmetrics.rmse(sqrerr=4.0, total_ins_num=1.0) == pytest.approx(2.0)
    assert fmetrics.mse(sqrerr=4.0, total_ins_num=2.0) == pytest.approx(2.0)
    np.testing.assert_allclose(fmetrics.sum(np.ones(3)), np.ones(3))
