"""End-to-end CTR training on the multi-host shard tier.

The 2-host loopback drill from the acceptance bar (MULTIHOST.md):

- a full DayRunner day with the trainer backed by a 2-host
  MultiHostStore is BIT-identical to the single-host FeatureStore run
  on the f32 wire — per-pass losses, final dense params, and final
  store contents;
- a mid-day elastic reshard (2 → 3 after pass 1's boundary, 3 → 2
  after pass 2's) driven through the pass-boundary hook leaves the
  final state bit-identical to an unresized run at the same data
  order, with per-row move counts matching the minimal-transfer plan.
"""

import os

import jax
import numpy as np

from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
from paddlebox_tpu.data import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.embedding.store import _FIELDS
from paddlebox_tpu.launch.elastic import RankTable
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.multihost import (MultiHostStore, ShardRangeTable,
                                     rows_moved_minimal,
                                     start_local_shards, stop_shards)
from paddlebox_tpu.multihost.reshard import ElasticReshardController
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.day_runner import DayRunner

SLOTS = ("user", "item")
DAY = "20260801"


def _write_day(root, rows_per_split=96):
    rng = np.random.default_rng(int(DAY))
    for h in range(3):
        d = os.path.join(root, DAY, f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-00000"), "w") as f:
            for _ in range(rows_per_split):
                feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                         for s in SLOTS}
                click = np.mean([(int(v) % 5 == 0)
                                 for vs in feats.values() for v in vs])
                label = int(rng.random() < 0.1 + 0.8 * click)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")


def _make_runner(data, out, store=None, hook=None):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10),
        store=store)
    trainer.init(seed=0)
    # pipeline_passes=False: the reshard hook mutates shard placement at
    # the boundary, so the next pass's build must not be pulling
    # concurrently (MULTIHOST.md "boundary quiescence").
    return DayRunner(trainer, feed, out, data_root=data,
                     split_interval=60, split_per_pass=1,
                     hours=[0, 1, 2], num_reader_threads=1,
                     pipeline_passes=False, pass_boundary_hook=hook)


def _store_rows(store, keys):
    return store.pull_for_pass(np.sort(np.asarray(keys, np.uint64)))


def _assert_same_run(stats_a, stats_b, runner_a, runner_b, keys):
    assert len(stats_a) == len(stats_b) == 3
    for sa, sb in zip(stats_a, stats_b):
        np.testing.assert_array_equal(sa["loss"], sb["loss"])
        np.testing.assert_array_equal(sa["auc"], sb["auc"])
    for la, lb in zip(
            jax.tree_util.tree_leaves(runner_a.trainer.params),
            jax.tree_util.tree_leaves(runner_b.trainer.params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    rows_a = _store_rows(runner_a.trainer.engine.store, keys)
    rows_b = _store_rows(runner_b.trainer.engine.store, keys)
    for f in _FIELDS:
        np.testing.assert_array_equal(rows_a[f], rows_b[f], err_msg=f)


def test_two_host_day_bit_identical_to_single_host(tmp_path):
    data = str(tmp_path / "data")
    _write_day(data)

    flat_runner = _make_runner(data, str(tmp_path / "out_flat"))
    flat_stats = flat_runner.train_day(DAY)

    servers, eps = start_local_shards(2, TableConfig(
        name="emb", dim=8, learning_rate=0.1))
    try:
        mh_store = MultiHostStore(TableConfig(
            name="emb", dim=8, learning_rate=0.1), eps)
        mh_runner = _make_runner(data, str(tmp_path / "out_mh"),
                                 store=mh_store)
        mh_stats = mh_runner.train_day(DAY)
        keys, _ = flat_runner.trainer.engine.store.key_stats()
        assert keys.size > 0
        assert mh_store.num_features == keys.size
        _assert_same_run(flat_stats, mh_stats, flat_runner, mh_runner,
                         keys)
    finally:
        stop_shards(servers)


def test_two_host_day_int8_wire_auc_parity(tmp_path):
    """The quantized DCN wire (documented tolerance, MULTIHOST.md):
    a 2-host day at multihost_wire_dtype=int8 must track the exact-run
    losses closely and land the same AUC within quantization noise —
    the EQuARX negligible-quality-loss claim at training level."""
    from paddlebox_tpu.core import flags as flagmod

    data = str(tmp_path / "data")
    _write_day(data, rows_per_split=192)

    flat_runner = _make_runner(data, str(tmp_path / "out_flat"))
    flat_stats = flat_runner.train_day(DAY)

    servers, eps = start_local_shards(2, TableConfig(
        name="emb", dim=8, learning_rate=0.1))
    prev = flagmod.flag("multihost_wire_dtype")
    flagmod.set_flags({"multihost_wire_dtype": "int8"})
    try:
        store = MultiHostStore(TableConfig(
            name="emb", dim=8, learning_rate=0.1), eps)
        runner = _make_runner(data, str(tmp_path / "out_i8"),
                              store=store)
        stats = runner.train_day(DAY)
    finally:
        flagmod.set_flags({"multihost_wire_dtype": prev})
        stop_shards(servers)
    assert len(stats) == len(flat_stats) == 3
    for sa, sb in zip(stats, flat_stats):
        np.testing.assert_allclose(sa["loss"], sb["loss"],
                                   rtol=2e-2, atol=2e-2)
        assert abs(sa["auc"] - sb["auc"]) < 2e-2
    # ...and the wire really quantized (states diverge somewhere).
    keys, _ = flat_runner.trainer.engine.store.key_stats()
    ra = _store_rows(runner.trainer.engine.store, keys)
    rb = _store_rows(flat_runner.trainer.engine.store, keys)
    assert not np.array_equal(ra["emb"], rb["emb"])
    np.testing.assert_allclose(ra["emb"], rb["emb"], rtol=5e-2,
                               atol=5e-2)


def test_two_host_day_int8_dense_sync_auc_parity(tmp_path):
    """The quantized dense-grad allreduce (FLAGS_dense_allreduce_dtype,
    MULTIHOST.md): a 2-host day with the dp=8 dense sync on the int8
    wire must track the exact-run losses closely and land AUC within
    the documented 2e-2 — the DCN-byte win costs no training quality.
    The shard wire stays f32 here so ONLY the dense sync quantizes."""
    from paddlebox_tpu.core import flags as flagmod, monitor

    data = str(tmp_path / "data")
    _write_day(data, rows_per_split=192)

    flat_runner = _make_runner(data, str(tmp_path / "out_flat"))
    flat_stats = flat_runner.train_day(DAY)

    servers, eps = start_local_shards(2, TableConfig(
        name="emb", dim=8, learning_rate=0.1))
    prev = flagmod.flag("dense_allreduce_dtype")
    flagmod.set_flags({"dense_allreduce_dtype": "int8"})
    try:
        store = MultiHostStore(TableConfig(
            name="emb", dim=8, learning_rate=0.1), eps)
        runner = _make_runner(data, str(tmp_path / "out_i8d"),
                              store=store)
        stats = runner.train_day(DAY)
        assert monitor.GLOBAL.get_gauge("dense/allreduce_wire_bits") == 8
    finally:
        flagmod.set_flags({"dense_allreduce_dtype": prev})
        stop_shards(servers)
    assert len(stats) == len(flat_stats) == 3
    for sa, sb in zip(stats, flat_stats):
        np.testing.assert_allclose(sa["loss"], sb["loss"],
                                   rtol=2e-2, atol=2e-2)
        assert abs(sa["auc"] - sb["auc"]) < 2e-2
    # ...and the dense wire really quantized (params diverge, closely).
    la = jax.tree_util.tree_leaves(runner.trainer.params)
    lb = jax.tree_util.tree_leaves(flat_runner.trainer.params)
    assert any(not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(la, lb))
    for a, b in zip(la, lb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-2)


def test_mid_day_reshard_bit_identical_to_unresized(tmp_path):
    data = str(tmp_path / "data")
    _write_day(data)
    cfg = TableConfig(name="emb", dim=8, learning_rate=0.1)

    # Baseline: 2-host day, never resharded.
    base_servers, base_eps = start_local_shards(2, cfg)
    try:
        base_store = MultiHostStore(cfg, base_eps)
        base_runner = _make_runner(data, str(tmp_path / "out_base"),
                                   store=base_store)
        base_stats = base_runner.train_day(DAY)
    finally:
        stop_shards(base_servers)

    # Resharding run: join after pass 1's boundary, leave after pass 2's.
    servers, eps = start_local_shards(2, cfg)
    j3, je3 = start_local_shards(3, cfg)
    joiner, jep = j3[2], je3[2]
    stop_shards([j3[0], j3[1]])
    try:
        store = MultiHostStore(cfg, eps)
        out = str(tmp_path / "out_rs")
        meta2 = {"a": {"shard_endpoint": eps[0]},
                 "b": {"shard_endpoint": eps[1]}}
        meta3 = dict(meta2, c={"shard_endpoint": jep})
        tables = {"t": RankTable(generation=0, hosts=["a", "b"],
                                 meta=meta2)}
        ctl = ElasticReshardController(store, CheckpointProtocol(out),
                                       table_fn=lambda: tables["t"])
        moved = []

        def resident_keys():
            ks = [s.store.key_stats()[0] for s in servers + [joiner]]
            ks = [k for k in ks if k.size]
            return (np.concatenate(ks) if ks
                    else np.empty((0,), np.uint64))

        def hook(day, pass_id):
            rk = resident_keys()
            rec = ctl.maybe_apply(day, pass_id)
            if rec is not None:
                # Per-row move count == the minimal-transfer bound for
                # the keys resident at THIS boundary.
                expect = rows_moved_minimal(
                    ShardRangeTable.for_world(rec["old_world"]),
                    ShardRangeTable.for_world(rec["new_world"]), rk)
                assert rec["moved_rows"] == expect
                moved.append(rec)
            # Script the NEXT boundary's membership: grow after pass 1,
            # shrink back after pass 2.
            if pass_id == 1:
                tables["t"] = RankTable(generation=1,
                                        hosts=["a", "b", "c"],
                                        meta=meta3)
            elif pass_id == 2:
                tables["t"] = RankTable(generation=2, hosts=["a", "b"],
                                        meta=meta2)

        runner = _make_runner(data, out, store=store, hook=hook)
        stats = runner.train_day(DAY)

        # Both resizes ran (audited per-row inside the hook).
        assert [m["new_world"] for m in moved] == [3, 2]
        for m in moved:
            assert m["moved_rows"] == sum(m["segment_rows"]) > 0
        # After the final 3->2, the joiner is fully drained and every
        # surviving server holds only its world-2 range.
        t2 = ShardRangeTable.for_world(2)
        jk, _ = joiner.store.key_stats()
        assert jk.size == 0
        all_keys = []
        for i, s in enumerate(servers):
            sk, _ = s.store.key_stats()
            assert (t2.owner_of(sk) == i).all()
            all_keys.append(sk)
        keys = np.sort(np.concatenate(all_keys))

        _assert_same_run(base_stats, stats, base_runner, runner, keys)
    finally:
        stop_shards(servers)
        joiner.stop()
