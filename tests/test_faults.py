"""Fault-injection subsystem: spec parsing, hit-count trigger semantics,
registry counters, the zero-cost-when-disabled pin, the stall watchdog,
and the hardened failure surfaces it drives (DumpWriter error surfacing,
FileStore timeout diagnostics, FramedRPCConn reconnect/retry,
crash-consistent dense checkpoints)."""

import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.core import faults, flags as flagmod, monitor
from paddlebox_tpu.core.faults import (FaultError, InjectedFault,
                                       parse_fault_spec)
from paddlebox_tpu.core.watchdog import StallError, Watchdog


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    try:
        yield
    finally:
        faults.clear()
        flagmod.set_flags({"fault_spec": ""})


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------

def test_parse_spec_grammar():
    specs = parse_fault_spec(
        "pass_engine/build:hit=2:raise=IOError;"
        "transport/get:delay_ms=500;"
        "day_runner/publish:kill;"
        "x/y:hit=3:times=0:raise=ConnectionResetError")
    assert len(specs) == 4
    s0, s1, s2, s3 = specs
    assert (s0.site, s0.hit, s0.raise_name) == \
        ("pass_engine/build", 2, "IOError")
    assert (s1.site, s1.delay_ms) == ("transport/get", 500.0)
    assert s2.site == "day_runner/publish" and s2.kill_sig is not None
    assert (s3.hit, s3.times) == (3, 0)


def test_parse_spec_empty_and_errors():
    assert parse_fault_spec("") == []
    assert parse_fault_spec("  ;  ") == []
    with pytest.raises(FaultError):
        parse_fault_spec("site_without_action")
    with pytest.raises(FaultError):
        parse_fault_spec("s:hit=0:raise=IOError")  # hit is 1-based
    with pytest.raises(FaultError):
        parse_fault_spec("s:frobnicate=1")
    with pytest.raises(FaultError):
        parse_fault_spec(":raise=IOError")  # no site


def test_unknown_exception_name_falls_back_to_injected_fault():
    faults.configure("s:raise=NoSuchException")
    with pytest.raises(InjectedFault):
        faults.faultpoint("s")


# ---------------------------------------------------------------------------
# trigger semantics + counters
# ---------------------------------------------------------------------------

def test_hit_count_triggers_exactly_once_by_default():
    base = monitor.get("fault/s_injected")
    faults.configure("s:hit=3:raise=IOError")
    faults.faultpoint("s")
    faults.faultpoint("s")
    with pytest.raises(OSError):
        faults.faultpoint("s")          # 3rd traversal fires
    faults.faultpoint("s")              # 4th passes (times=1)
    assert faults.hits("s") == 4
    assert monitor.get("fault/s_injected") - base == 1


def test_times_window_and_forever():
    faults.configure("s:hit=2:times=2:raise=IOError")
    faults.faultpoint("s")
    for _ in range(2):
        with pytest.raises(OSError):
            faults.faultpoint("s")
    faults.faultpoint("s")  # window [2, 3] closed

    faults.configure("t:times=0:raise=IOError")
    for _ in range(3):
        with pytest.raises(OSError):
            faults.faultpoint("t")


def test_delay_injection_and_counter():
    base = monitor.get("fault/d_injected")
    faults.configure("d:delay_ms=80")
    t0 = time.perf_counter()
    faults.faultpoint("d")
    assert time.perf_counter() - t0 >= 0.07
    assert monitor.get("fault/d_injected") - base == 1


def test_other_sites_untouched():
    faults.configure("only/this:raise=IOError")
    faults.faultpoint("some/other")     # never raises
    assert faults.hits("some/other") == 0


def test_init_from_flags_arms_once():
    flagmod.set_flags({"fault_spec": "f:raise=IOError"})
    assert faults.init_from_flags()
    with pytest.raises(OSError):
        faults.faultpoint("f")
    faults.clear()
    flagmod.set_flags({"fault_spec": ""})
    assert not faults.init_from_flags()
    faults.faultpoint("f")  # disarmed: no-op


def test_is_transient_classification():
    assert faults.is_transient(OSError())
    assert faults.is_transient(TimeoutError())
    assert faults.is_transient(ConnectionResetError())
    assert faults.is_transient(StallError())
    assert faults.is_transient(InjectedFault("x"))
    assert not faults.is_transient(ValueError())
    assert not faults.is_transient(KeyError())
    assert not faults.is_transient(FloatingPointError())
    assert not faults.is_transient(KeyboardInterrupt())
    # Explicit attribute wins in both directions.
    e = RuntimeError()
    e.transient = True
    assert faults.is_transient(e)
    e2 = OSError()
    e2.transient = False
    assert not faults.is_transient(e2)


# ---------------------------------------------------------------------------
# zero-cost-when-disabled pin
# ---------------------------------------------------------------------------

def test_disabled_faultpoint_is_cheap():
    """Disabled path = ONE cached bool; a generous wall bound (~µs/call
    scale) pins that nobody reintroduces a flag read or lock there."""
    assert not faults.armed()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        faults.faultpoint("hot/site")
    dt = time.perf_counter() - t0
    assert dt < 0.25, f"{n} disabled faultpoints took {dt:.3f}s"


def test_faultpoints_leave_step_op_structure_unchanged():
    """Faultpoints are host-side only: arming the registry (at a site
    with an unreachable hit count) must not change the jitted train
    step's op counts — the same pin the telemetry layer carries."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data import DataFeedConfig, SlotConf
    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.data.slots import SlotBatch
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig
    from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
    from paddlebox_tpu.utils import inspect as pbx_inspect

    def op_counts():
        mesh = build_mesh(HybridTopology(dp=4),
                          devices=jax.devices()[:4])
        slots = tuple(SlotConf(f"s{i}", avg_len=2.0) for i in range(3))
        feed = DataFeedConfig(slots=slots, batch_size=16)
        model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                       emb_dim=8, hidden=(16, 8))
        tr = CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        rng = np.random.default_rng(0)
        lines = [f"{rng.integers(0, 2)} "
                 + " ".join(f"s{i}:{rng.integers(1, 40)}"
                            for i in range(3))
                 for _ in range(feed.batch_size)]
        batch = SlotBatch.pack_sharded(parse_lines(lines, feed), feed, 4)
        tr.engine.feed_pass([
            np.unique(np.concatenate([batch.ids[n] for n in g.slots]))
            for g in tr.engine.groups])
        step = tr._build_step()
        tables = tr.engine.begin_pass()
        rows = tr._map_batch_rows(batch)
        segs = {n: jnp.asarray(batch.segments[n]) for n in batch.ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows,
                segs, jnp.asarray(batch.labels),
                jnp.asarray(batch.valid),
                jnp.asarray(_concat_dense_host(batch)),
                jnp.zeros((), jnp.int32))
        return pbx_inspect.jaxpr_summary(lambda *a: step(*a), *args)

    off = op_counts()
    faults.configure("device_store/pull:hit=1000000:raise=IOError;"
                     "pass_engine/build:hit=1000000:raise=IOError")
    on = op_counts()
    assert on == off, (on, off)


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_stall_raises_in_armed_thread():
    base = monitor.get("watchdog/stalls")
    wd = Watchdog(0.25, poll_s=0.05)
    got = {}

    def work():
        wd.arm(phase="drill")
        try:
            for _ in range(200):
                time.sleep(0.05)  # no beats
        except StallError as e:
            got["err"] = e
        finally:
            wd.disarm()

    t = threading.Thread(target=work)
    t.start()
    t.join(timeout=10)
    wd.close()
    assert isinstance(got.get("err"), StallError)
    assert monitor.get("watchdog/stalls") - base == 1


def test_watchdog_beats_keep_alive_and_disarm_is_noop():
    wd = Watchdog(0.4, poll_s=0.05)
    done = {}

    def work():
        wd.arm(phase="ok")
        try:
            for _ in range(10):
                time.sleep(0.1)
                wd.beat()
            done["ok"] = True
        finally:
            wd.disarm()

    t = threading.Thread(target=work)
    t.start()
    t.join(timeout=10)
    # Disarmed: idle time accrues but nothing fires, and beat is a no-op.
    time.sleep(0.6)
    wd.beat()
    wd.close()
    assert done.get("ok") is True


def test_global_watchdog_arm_from_flags():
    from paddlebox_tpu.core import watchdog as wdmod
    assert not wdmod.arm_from_flags()  # default flag 0.0 -> off
    flagmod.set_flags({"stall_timeout_s": 60.0})
    try:
        assert wdmod.arm_from_flags(phase="t")
        assert wdmod.GLOBAL.armed
    finally:
        wdmod.disarm()
        flagmod.set_flags({"stall_timeout_s": 0.0})


# ---------------------------------------------------------------------------
# DumpWriter: writer-thread failure surfaces on the NEXT write
# ---------------------------------------------------------------------------

def test_dump_writer_error_surfaces_on_next_write(tmp_path):
    from paddlebox_tpu.utils.dump import DumpWriter

    base = monitor.get("fault/dump_errors")
    faults.configure("dump/write:raise=IOError")  # 'disk full' on line 1
    w = DumpWriter(str(tmp_path / "dump.txt"), capacity=4)
    preds = np.array([0.5, 0.25])
    labels = np.array([1.0, 0.0])
    w.write_batch(preds, labels)  # queued; writer dies consuming it
    deadline = time.time() + 5
    while w._error is None and time.time() < deadline:
        time.sleep(0.01)
    with pytest.raises(OSError):
        w.write_batch(preds, labels)
    assert monitor.get("fault/dump_errors") - base == 1
    with pytest.raises(OSError):
        w.close()


def test_dump_writer_clean_close_still_works(tmp_path):
    from paddlebox_tpu.utils.dump import DumpWriter

    w = DumpWriter(str(tmp_path / "dump.txt"))
    w.write_batch(np.array([0.5]), np.array([1.0]))
    w.close()
    assert open(tmp_path / "dump.txt").read().strip() == "0\t0.500000\t1"


# ---------------------------------------------------------------------------
# FileStore: named missing ranks + poll backoff
# ---------------------------------------------------------------------------

def test_filestore_timeout_names_missing_ranks(tmp_path):
    from paddlebox_tpu.distributed.transport import FileStore

    fs = FileStore(str(tmp_path), rank=0, world=3)
    with pytest.raises(TimeoutError) as ei:
        fs.barrier("sync", timeout=0.3)
    msg = str(ei.value)
    # Rank 0 arrived; 1 and 2 never did — the error says exactly that.
    assert "barrier('sync')" in msg
    assert "[1, 2]" in msg and "rank 0" in msg

    with pytest.raises(TimeoutError) as ei2:
        fs.all_gather("ag", b"x", timeout=0.3)
    assert "[1, 2]" in str(ei2.value)


def test_filestore_get_backoff_still_finds_late_keys(tmp_path):
    from paddlebox_tpu.distributed.transport import FileStore

    fs = FileStore(str(tmp_path), rank=0, world=1)

    def late_set():
        time.sleep(0.4)
        fs.set("k", b"v")

    t = threading.Thread(target=late_set)
    t.start()
    assert fs.get("k", timeout=5.0) == b"v"  # poll backed off to 250ms max
    t.join()


def test_fleet_executor_drain_timeout_names_missing(tmp_path):
    """'did not drain' must say WHICH scopes are missing and which
    stages are still alive, not just that it timed out."""
    from paddlebox_tpu.distributed.fleet_executor import (Carrier,
                                                          linear_pipeline)

    def wedge(x):
        time.sleep(60)
        return x

    c = Carrier(linear_pipeline([wedge]))
    with pytest.raises(TimeoutError) as ei:
        c.run(2, feeds=[0, 1], timeout=0.5)
    msg = str(ei.value)
    assert "0/2 sink scopes" in msg
    assert "missing scopes [0, 1]" in msg


# ---------------------------------------------------------------------------
# FramedRPCConn: reconnect + idempotent retry
# ---------------------------------------------------------------------------

class _EchoServer:
    def __init__(self):
        from paddlebox_tpu.distributed.rpc import FramedRPCServer

        class Srv(FramedRPCServer):
            service_name = "echo"
            calls = 0

            def handle_ping(self, req):
                Srv.calls += 1
                return {"pong": req.get("x", 0)}

            def handle_write(self, req):
                return True

        self.cls = Srv
        self.srv = Srv("127.0.0.1:0")
        self.endpoint = self.srv.endpoint


def test_rpc_idempotent_retry_through_injected_blip():
    from paddlebox_tpu.distributed.rpc import FramedRPCConn

    es = _EchoServer()
    try:
        conn = FramedRPCConn(es.endpoint, service_name="echo",
                             idempotent=("ping",))
        assert conn.call("ping", x=1) == {"pong": 1}
        # Next rpc/call traversal dies with a connection error; the
        # idempotent method reconnects and retries transparently.
        faults.configure("rpc/call:raise=ConnectionResetError")
        base = monitor.get("rpc/retries")
        assert conn.call("ping", x=2) == {"pong": 2}
        assert monitor.get("rpc/retries") - base >= 1
        # Non-idempotent: the same blip surfaces to the caller.
        faults.configure("rpc/call:raise=ConnectionResetError")
        with pytest.raises(ConnectionResetError):
            conn.call("write")
        faults.clear()
        # ...but the NEXT call reconnects instead of being stranded.
        assert conn.call("ping", x=3) == {"pong": 3}
        conn.close()
    finally:
        es.srv.stop()


def test_rpc_reconnects_after_server_restart():
    from paddlebox_tpu.distributed.rpc import FramedRPCConn, FramedRPCServer

    class Srv(FramedRPCServer):
        service_name = "echo"

        def handle_ping(self, req):
            return 42

    srv = Srv("127.0.0.1:0")
    endpoint = srv.endpoint
    conn = FramedRPCConn(endpoint, service_name="echo",
                         idempotent=("ping",))
    assert conn.call("ping") == 42
    srv.stop()
    time.sleep(0.05)
    # Restart on the SAME port (a PS coming back after a blip).
    srv2 = Srv(endpoint)
    try:
        assert conn.call("ping") == 42  # retried onto the new server
    finally:
        conn.close()
        srv2.stop()


# ---------------------------------------------------------------------------
# crash-consistent dense checkpoints
# ---------------------------------------------------------------------------

def _tree():
    return {"params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                       "b": np.ones((4,), np.float32)},
            "opt_state": {"m": np.zeros((3, 4), np.float32)}}


def test_dense_checkpoint_roundtrip_with_crc(tmp_path):
    from paddlebox_tpu.checkpoint.dense import load_pytree, save_pytree

    p = str(tmp_path / "dense.npz")
    t = _tree()
    save_pytree(t, p, step=7)
    data = np.load(p)
    assert "__crc32__" in data.files
    out, step = load_pytree(_tree(), p)
    assert step == 7
    np.testing.assert_array_equal(out["params"]["w"], t["params"]["w"])


def test_dense_checkpoint_truncated_raises_corrupt(tmp_path):
    from paddlebox_tpu.checkpoint.dense import (CheckpointCorruptError,
                                                load_pytree, save_pytree)

    p = str(tmp_path / "dense.npz")
    save_pytree(_tree(), p)
    full = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(full[:len(full) // 2])   # torn write
    with pytest.raises(CheckpointCorruptError):
        load_pytree(_tree(), p)


def test_dense_checkpoint_bitflip_fails_crc(tmp_path):
    from paddlebox_tpu.checkpoint.dense import (CheckpointCorruptError,
                                                load_pytree, save_pytree)

    p = str(tmp_path / "dense.npz")
    save_pytree(_tree(), p)
    blob = bytearray(open(p, "rb").read())
    # Flip one byte inside the stored (uncompressed) array payload.
    blob[len(blob) // 2] ^= 0xFF
    with open(p, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises((CheckpointCorruptError, KeyError)):
        load_pytree(_tree(), p)


def test_recover_skips_corrupt_dense_to_older_record(tmp_path):
    """A torn dense.npz in the NEWEST record must not kill recover():
    the sparse chain still loads and dense falls back to the next-newest
    record that verifies."""
    from tests.test_day_runner import _make_runner, _write_day

    data = str(tmp_path / "data")
    out = str(tmp_path / "out")
    _write_day(data, "20260728", [0, 1])
    r1 = _make_runner(data, out)
    r1.train_day("20260728")
    import jax
    trained = jax.tree.map(lambda x: np.asarray(x).copy(),
                           r1.trainer.params)

    # Corrupt the newest record's dense checkpoint (the day base).
    base_dense = os.path.join(out, "20260728", "0", "dense.npz")
    blob = open(base_dense, "rb").read()
    with open(base_dense, "wb") as f:
        f.write(blob[:100])

    r2 = _make_runner(data, out)
    point = r2.recover()          # must not raise
    assert point == {"day": "20260728", "pass_id": 0}
    assert r2.trainer.engine.store.num_features == \
        r1.trainer.engine.store.num_features
    # Dense restored from an OLDER record (pass 2's delta) — trained
    # state, not fresh init... the older record predates the day-end
    # decay, but it must load without error and differ from fresh init.
    leaves = [np.asarray(x) for x in jax.tree.leaves(r2.trainer.params)]
    assert any(l.size for l in leaves)
