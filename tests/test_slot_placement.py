"""FLAGS_table_slot_placement parity: slot-column split/offload of the
device feature store vs the fused baseline.

Role of the reference's value/slot layout split: a feature row is
[emb D | show click day | emb_state Ke | w_state Kw], but only the
first D+3 columns are touched by pull/serving — the optimizer slot
columns ride along every HBM byte only because the fused layout stores
values x slots together. 'split' carves the slot columns into a sibling
array (hot part becomes exactly [rows, D+3]); 'host' additionally pins
the slot part to host memory with transient HBM crossings around the
push. Both must be PLACEMENT, not format: identical key sets, bitwise
identical pulled values, identical lifecycle (decay/TTL/eviction)
results, and checkpoints that round-trip across placements unchanged.
"""

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
from paddlebox_tpu.parallel import HybridTopology, build_mesh

CFG = dict(name="t", dim=8, optimizer="adagrad", show_click_decay=0.98)

PLACEMENTS = ("fused", "split", "host")


@pytest.fixture(autouse=True)
def _restore_placement_flags():
    old = {k: flagmod.flag(k) for k in
           ("table_slot_placement", "table_ttl_days")}
    try:
        yield
    finally:
        flagmod.set_flags(old)


def _keys(seed=0, n=600):
    rng = np.random.default_rng(seed)
    return np.unique(rng.integers(1, 1 << 40, n, dtype=np.uint64))


def _lifecycle_run(placement, sharded):
    """Two pull/push cycles + decay/TTL shrink + min_show eviction,
    ending in a full-store snapshot digest."""
    flagmod.set_flags({"table_slot_placement": placement,
                       "table_ttl_days": 2})
    mesh = build_mesh(HybridTopology(dp=8)) if sharded else None
    st = DeviceFeatureStore(TableConfig(**CFG), mesh=mesh)
    keys = _keys()
    vals = st.pull_for_pass(keys)
    upd = {f: np.asarray(v) for f, v in vals.items()}
    upd["emb"] = upd["emb"] + 0.5
    upd["show"] = upd["show"] + 1.0
    st.push_from_pass(keys, upd)
    st.shrink(min_show=0.0)          # decay + TTL aging, no eviction
    k2 = np.unique(np.concatenate(
        [keys[::2], keys.max() + np.arange(1, 100, dtype=np.uint64)]))
    v2 = st.pull_for_pass(k2)
    st.push_from_pass(k2, {f: np.asarray(v) for f, v in v2.items()})
    st.shrink(min_show=0.5)          # evicts the cold half
    allk = np.sort(st._index.keys_by_row())
    snap = st.pull_for_pass(allk)
    digest = {f: np.asarray(v).tobytes() for f, v in snap.items()}
    return allk, digest, st.memory_stats(), st


def test_six_variants_bitwise_vs_fused_local():
    """All six store variants (fused/split/host x local/dp-sharded):
    identical surviving key sets and bitwise-identical value digests
    through pull -> push -> decay/TTL -> eviction. Split placements
    must also carve the exact shapes: hot [rows, D+3], slot
    [rows, Ke+Kw]."""
    base_k = base_dig = None
    for sharded in (False, True):
        for placement in PLACEMENTS:
            k, dig, mem, st = _lifecycle_run(placement, sharded)
            assert mem["placement"] == placement
            if placement != "fused":
                rows_tot = st.num_shards * (st._cap + 1)
                assert st._parts[0].shape == (rows_tot, st.dim + 3)
                assert st._parts[1].shape == (rows_tot, st.ke + st.kw)
            if base_k is None:
                base_k, base_dig = k.tobytes(), dig
                continue
            tag = f"{placement}/{'sharded' if sharded else 'local'}"
            assert k.tobytes() == base_k, f"{tag}: key set diverged"
            for f in dig:
                assert dig[f] == base_dig[f], f"{tag}: {f} diverged"


def test_memory_stats_hot_bytes_per_row_exact():
    """The acceptance arithmetic: under split/host the HOT array holds
    exactly (D+3) f32 columns per row — the slot columns contribute
    zero bytes to it. Fused reports the same TOTAL, attributed
    proportionally."""
    for placement in ("split", "host"):
        flagmod.set_flags({"table_slot_placement": placement})
        st = DeviceFeatureStore(TableConfig(**CFG))
        st.pull_for_pass(_keys())
        rows_tot = st.num_shards * (st._cap + 1)
        mem = st.memory_stats()
        width = st.dim + 3 + st.ke + st.kw
        hot_plus_slot = rows_tot * width * 4
        assert mem["hot_hbm_bytes"] == rows_tot * (st.dim + 3) * 4
        assert (mem["hot_hbm_bytes"] + mem["slot_hbm_bytes"]
                == hot_plus_slot)
    flagmod.set_flags({"table_slot_placement": "fused"})
    st = DeviceFeatureStore(TableConfig(**CFG))
    st.pull_for_pass(_keys())
    mem = st.memory_stats()
    width = st.dim + 3 + st.ke + st.kw
    total = st.num_shards * (st._cap + 1) * width * 4
    assert mem["hot_hbm_bytes"] + mem["slot_hbm_bytes"] == total


def test_checkpoint_roundtrip_across_placements(tmp_path):
    """save_base under one placement, load under another: checkpoints
    carry the LOGICAL row (placement is not format) — pulls after
    fused->split and split->host round-trips are bitwise identical."""
    flagmod.set_flags({"table_slot_placement": "fused"})
    keys = _keys()
    a = DeviceFeatureStore(TableConfig(**CFG))
    va = a.pull_for_pass(keys)
    a.push_from_pass(keys,
                     {f: np.asarray(v) + 0.25 for f, v in va.items()})
    d1 = str(tmp_path / "ck_fused")
    a.save_base(d1)
    ref = a.pull_for_pass(keys)

    flagmod.set_flags({"table_slot_placement": "split"})
    b = DeviceFeatureStore(TableConfig(**CFG))
    b.load(d1, "base")
    got = b.pull_for_pass(keys)
    for f in ref:
        np.testing.assert_array_equal(np.asarray(ref[f]),
                                      np.asarray(got[f]),
                                      err_msg=f"fused->split {f}")

    d2 = str(tmp_path / "ck_split")
    b.save_base(d2)
    flagmod.set_flags({"table_slot_placement": "host"})
    c = DeviceFeatureStore(TableConfig(**CFG))
    c.load(d2, "base")
    got2 = c.pull_for_pass(keys)
    for f in ref:
        np.testing.assert_array_equal(np.asarray(ref[f]),
                                      np.asarray(got2[f]),
                                      err_msg=f"split->host {f}")


def test_pass_table_block_identical_across_placements():
    """The PassTable stays FUSED under every placement (the trainer's
    jitted pull/push signature never changes): the [rows, width] block
    handed to the pass is bitwise identical, fused vs split."""
    blocks = {}
    keys = _keys(seed=7, n=200)
    for placement in ("fused", "split"):
        flagmod.set_flags({"table_slot_placement": placement})
        st = DeviceFeatureStore(TableConfig(**CFG))
        vals = st.pull_for_pass(keys)
        st.push_from_pass(
            keys, {f: np.asarray(v) + 1.0 for f, v in vals.items()})
        table, rows = st.pull_pass_table(keys, st.num_shards)
        blocks[placement] = (np.asarray(table.vals).tobytes(),
                             np.asarray(rows).tobytes())
    assert blocks["fused"] == blocks["split"]


def test_invalid_placement_raises():
    flagmod.set_flags({"table_slot_placement": "hbm3"})
    with pytest.raises(ValueError, match="table_slot_placement"):
        DeviceFeatureStore(TableConfig(**CFG))
