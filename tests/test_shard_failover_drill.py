"""Shard-host kill -9 drill: survive host loss under live traffic.

The acceptance bar of the replicated tier (MULTIHOST.md "replicated
tier"): two REAL shard-host processes hold a replicas=2 world; a
DayRunner trains against them while serving-style readers hammer the
``pull_serving`` miss path. One host is SIGKILL'd between passes:

- every concurrent serving read keeps succeeding (reads fail over to
  the surviving replica — ZERO failed client RPCs);
- the interrupted training pass costs one self-heal retry: the
  pass-retry hook PROMOTES the surviving backup to primary, the
  rollback reloads the published chain from live servers only, and the
  replay is bit-identical — final losses, dense params, and store
  contents equal a never-killed single-host reference;
- a fresh host joins through the elastic rank table and the
  pass-boundary hook RE-REPLICATES the thinned slots to it, restoring
  the replication factor, with content digests matching the survivor.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

from paddlebox_tpu.embedding.store import _FIELDS
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.launch.elastic import read_rank_table
from paddlebox_tpu.multihost import MultiHostStore, ReplicaMap, ShardClient
from paddlebox_tpu.multihost.reshard import ElasticReshardController
from paddlebox_tpu.serving.fleet import ShardBackedStore
from tests.test_multihost_ctr import (DAY, _make_runner, _store_rows,
                                      _write_day)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = TableConfig(name="emb", dim=8, learning_rate=0.1)


def _spawn_host(root: str, host_id: str, index: int, world: int = 2):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "shard_host_worker.py"),
         root, host_id, str(index), str(world)],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    ep_file = os.path.join(root, f"{host_id}.ep")
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(ep_file):
            with open(ep_file) as f:
                return proc, json.load(f)["endpoint"]
        if proc.poll() is not None:
            raise RuntimeError(f"worker {host_id} died rc={proc.returncode}")
        time.sleep(0.05)
    raise TimeoutError(f"worker {host_id} never advertised an endpoint")


class _ServingReaders:
    """Concurrent pull_serving traffic: the fleet's shard-miss path.
    Counts every failed read — the drill pins the count at ZERO."""

    def __init__(self, backed: ShardBackedStore, keys: np.ndarray,
                 threads: int = 3):
        self._backed = backed
        self._keys = keys
        self._stop = threading.Event()
        self.failures = []
        self.reads = 0
        self._lock = threading.Lock()
        self._threads = [threading.Thread(target=self._loop, daemon=True)
                         for _ in range(threads)]

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                found, vals = self._backed.read(self._keys)
                assert vals.shape == (self._keys.size,
                                      self._backed.dim + 1)
                with self._lock:
                    self.reads += 1
            except Exception as e:  # noqa: BLE001 — the drill records all
                with self._lock:
                    self.failures.append(repr(e))
            time.sleep(0.01)

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)


def _digest(arrs) -> str:
    h = hashlib.sha256()
    for a in arrs:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def test_shard_host_kill9_under_train_and_predict_traffic(tmp_path):
    data = str(tmp_path / "data")
    _write_day(data, rows_per_split=96)

    # Never-killed reference: the flat single-host run (bit-identical
    # to the multihost f32 wire by the PR-10 parity pins).
    ref = _make_runner(data, str(tmp_path / "out_ref"))
    ref_stats = ref.train_day(DAY)
    ref_keys, _ = ref.trainer.engine.store.key_stats()

    root = str(tmp_path / "hosts")
    os.makedirs(root, exist_ok=True)
    elroot = os.path.join(root, "elastic")
    proc_a, ep_a = _spawn_host(root, "hostA", 0)
    proc_b, ep_b = _spawn_host(root, "hostB", 1)
    proc_c = None
    try:
        rmap = ReplicaMap.ring([ep_a, ep_b], 2)
        for ep in (ep_a, ep_b):
            c = ShardClient(ep)
            c.call("set_replication", map=rmap.to_dict())
            c.close()

        store = MultiHostStore(CFG, [ep_a, ep_b], replica_map=rmap)
        ctl = ElasticReshardController(
            store, None, table_fn=lambda: read_rank_table(elroot))
        runner = _make_runner(
            data, str(tmp_path / "out_drill"), store=store,
            hook=lambda day, pid: ctl.maybe_apply(day, pid))
        ctl.ckpt = runner.ckpt
        runner.pass_retry_hook = (
            lambda day, pid, e: ctl.repair(reason=repr(e)))

        traffic_keys = np.sort(np.unique(np.random.default_rng(7)
                               .integers(1, 120, 64, dtype=np.uint64)))
        backed = ShardBackedStore([ep_a, ep_b], CFG.dim,
                                  replica_map=store.replica_map)
        files = [runner.filelist_fn(DAY, s) for s in runner.pass_splits]
        stats = []
        with _ServingReaders(backed, traffic_keys) as readers:
            stats.append(runner.train_pass(DAY, 1, files[0]))

            # kill -9 one host of the replicated pair, mid-traffic.
            proc_b.send_signal(signal.SIGKILL)
            proc_b.wait(timeout=30)
            proc_c, ep_c = _spawn_host(root, "hostC", 0)

            # The interrupted pass: push hits the dead primary → loud
            # transient → retry hook PROMOTES → rollback+replay.
            stats.append(runner.train_pass(DAY, 2, files[1]))
            # The dead host is out of the map (promotion); pass 2's own
            # boundary hook may ALREADY have re-replicated to hostC if
            # the rank table settled that fast — both are legal here.
            assert ep_b not in store.replica_map.all_endpoints()
            backed.set_replica_map(store.replica_map)

            # Boundary repair: once the rank table settles on
            # {hostA, hostC}, the hook re-replicates to the fresh host.
            stats.append(runner.train_pass(DAY, 3, files[2]))
            deadline = time.time() + 30
            while (store.replica_map.replication < 2
                   and time.time() < deadline):
                ctl.maybe_apply(DAY, 3)       # the boundary-hook path
                time.sleep(0.25)
            assert store.replica_map.replication == 2, \
                "boundary repair never restored the replication factor"
            backed.set_replica_map(store.replica_map)
            found, _ = backed.read(traffic_keys)   # reads span old+new

        assert not readers.failures, readers.failures[:5]
        assert readers.reads > 0
        # Close the day the same way the reference's train_day did
        # (lifecycle shrink + base dump — forwarded to the new backup).
        runner.day_end(DAY)

        # Zero lost updates: the drilled run equals the reference.
        assert len(stats) == 3
        for sa, sb in zip(stats, ref_stats):
            np.testing.assert_array_equal(sa["loss"], sb["loss"])
            np.testing.assert_array_equal(sa["auc"], sb["auc"])
        import jax
        assert _digest(jax.tree_util.tree_leaves(
            jax.device_get(runner.trainer.params))) == _digest(
            jax.tree_util.tree_leaves(jax.device_get(ref.trainer.params)))
        rows_d = _store_rows(store, ref_keys)
        rows_r = _store_rows(ref.trainer.engine.store, ref_keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(rows_d[f], rows_r[f],
                                          err_msg=f)

        # Replication factor restored WITH matching bytes: the fresh
        # host's replica stores mirror the survivor's primaries.
        ca, cc = ShardClient(ep_a), ShardClient(ep_c)
        try:
            st_a = ca.call("replica_status")
            st_c = cc.call("replica_status")
            assert st_a["replication"] == 2
            assert {s: d["role"] for s, d in st_a["slots"].items()} == \
                {"0": "primary", "1": "primary"}
            assert {s: d["role"] for s, d in st_c["slots"].items()} == \
                {"0": "backup", "1": "backup"}
            for slot in ("0", "1"):
                assert st_c["slots"][slot]["rows"] == \
                    st_a["slots"][slot]["rows"]
                assert st_c["slots"][slot]["seq"] == \
                    st_a["slots"][slot]["seq"]
        finally:
            ca.close()
            cc.close()
        backed.close()
        store.close()
    finally:
        for p in (proc_a, proc_b, proc_c):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)
