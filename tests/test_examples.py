"""The examples/ scripts are executable documentation — run each in a
subprocess on the virtual CPU mesh and require a clean exit. A broken
example is a broken promise to the first user."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    "ctr_deepfm_end_to_end.py",
    "day_production_loop.py",
    "gpt_hybrid_parallel.py",
    "remote_ps_tiered.py",
    "graph_deepwalk.py",
    "multislice_ctr.py",
    "online_serving.py",
    "migrate_reference_configs.py",
]


@pytest.mark.slow
@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs_clean(script):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        env=env, capture_output=True, text=True, timeout=900, cwd=REPO)
    assert proc.returncode == 0, (
        f"{script} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
