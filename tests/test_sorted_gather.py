"""sorted_gather (CopyForPull-class Pallas kernel) vs the XLA gather
reference — interpret mode on CPU; the same code compiles for TPU
(Mosaic AOT check in tests/test_pallas_aot.py). Covers the ISSUE's
parity matrix: uniform keys, skewed/hot-row fallback, trash rows, empty
blocks, widths 8/16/40, non-BLOCK-multiple row counts (the production
pow2+trash shape), the shared pull+push sort layout, and the lookup
wiring (pull_local single- and multi-shard) under the
``sparse_gather_kernel`` flag."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.ops.pallas_kernels.sorted_gather import (
    sorted_gather, sorted_stream_layout)
from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
    BLOCK, UCAP, sorted_scatter_accumulate)


def _ref(rows, table, pw):
    keep = rows < table.shape[0]
    safe = np.where(keep, rows, 0)
    return np.where(keep[:, None], table[safe, :pw], 0.0).astype(np.float32)


@pytest.mark.parametrize("num_rows,n,w,pw", [
    (BLOCK, 1000, 16, 16),            # one block, full width
    (3 * BLOCK + 17, 20_000, 20, 16),  # non-multiple rows: tail block
    (BLOCK + 1, 9_000, 8, 8),          # the rows_per_shard+1 real shape
    (2 * BLOCK, 4_000, 40, 40),        # pull width 40 (wide mf)
])
def test_matches_xla_gather(num_rows, n, w, pw):
    rng = np.random.default_rng(0)
    rows = rng.integers(0, num_rows, n).astype(np.int32)
    table = rng.normal(size=(num_rows, w)).astype(np.float32)
    got = sorted_gather(jnp.asarray(rows), jnp.asarray(table), width=pw,
                        interpret=True)
    np.testing.assert_array_equal(np.asarray(got), _ref(rows, table, pw))


def test_trash_rows_dropped_to_zeros():
    rng = np.random.default_rng(1)
    num_rows = BLOCK + 1
    n = 6000
    rows = rng.integers(0, num_rows, n).astype(np.int32)
    # A third of entries carry the drop sentinel (padding/overflow), and
    # they CONCENTRATE — must count toward no block's run (else the
    # hot-row fallback would fire on every call).
    rows[::3] = num_rows
    table = rng.normal(size=(num_rows, 12)).astype(np.float32)
    got = sorted_gather(jnp.asarray(rows), jnp.asarray(table), width=12,
                        interpret=True)
    ref = _ref(rows, table, 12)
    assert (np.asarray(got)[::3] == 0.0).all()
    np.testing.assert_array_equal(np.asarray(got), ref)


def test_hot_row_falls_back_to_xla_gather():
    """More than UCAP requests for one row: the kernel budget would
    overflow, so the cond must take the exact XLA path."""
    rng = np.random.default_rng(2)
    num_rows = BLOCK
    n = UCAP + 2048
    rows = np.full((n,), 7, np.int32)
    rows[-5:] = num_rows              # plus a few dropped sentinels
    table = rng.normal(size=(num_rows, 16)).astype(np.float32)
    got = sorted_gather(jnp.asarray(rows), jnp.asarray(table), width=16,
                        interpret=True)
    np.testing.assert_array_equal(np.asarray(got), _ref(rows, table, 16))


def test_empty_blocks_and_tail_rows():
    """All requests inside block 0 plus a handful in the tail partial
    block: interior blocks have zero-length runs (the kernel loop body
    must not execute), and tail rows past the last full block boundary
    are still served exactly."""
    rng = np.random.default_rng(3)
    num_rows = 3 * BLOCK + 5
    rows = np.concatenate([
        rng.integers(0, 64, 500),                    # block 0 only
        rng.integers(3 * BLOCK, num_rows, 40),        # tail block rows
    ]).astype(np.int32)
    table = rng.normal(size=(num_rows, 8)).astype(np.float32)
    got = sorted_gather(jnp.asarray(rows), jnp.asarray(table), width=8,
                        interpret=True)
    np.testing.assert_array_equal(np.asarray(got), _ref(rows, table, 8))


def test_width_slice_of_wider_record():
    """width < table width gathers the leading pull slice only — the
    lookup serves [emb | w | show | click] out of the fused record."""
    rng = np.random.default_rng(4)
    num_rows = BLOCK
    rows = rng.integers(0, num_rows, 300).astype(np.int32)
    table = rng.normal(size=(num_rows, 21)).astype(np.float32)
    got = sorted_gather(jnp.asarray(rows), jnp.asarray(table), width=11,
                        interpret=True)
    assert got.shape == (300, 11)
    np.testing.assert_array_equal(np.asarray(got), _ref(rows, table, 11))


def test_shared_layout_serves_gather_and_scatter():
    """ONE sorted_stream_layout drives both kernels (the step's shared
    argsort): results must be identical to each kernel computing its own
    sort."""
    rng = np.random.default_rng(5)
    num_rows = BLOCK + 1
    n = 4000
    rows = rng.integers(0, num_rows, n).astype(np.int32)
    rows[::6] = num_rows
    table = rng.normal(size=(num_rows, 12)).astype(np.float32)
    payload = rng.normal(size=(n, 12)).astype(np.float32)
    layout = sorted_stream_layout(jnp.asarray(rows), num_rows)

    g_shared = sorted_gather(jnp.asarray(rows), jnp.asarray(table),
                             width=12, interpret=True, layout=layout)
    g_own = sorted_gather(jnp.asarray(rows), jnp.asarray(table),
                          width=12, interpret=True)
    np.testing.assert_array_equal(np.asarray(g_shared), np.asarray(g_own))

    s_shared = sorted_scatter_accumulate(jnp.asarray(rows),
                                         jnp.asarray(payload), num_rows,
                                         interpret=True, layout=layout)
    s_own = sorted_scatter_accumulate(jnp.asarray(rows),
                                      jnp.asarray(payload), num_rows,
                                      interpret=True)
    np.testing.assert_array_equal(np.asarray(s_shared), np.asarray(s_own))


def test_layout_shape_mismatch_raises():
    rng = np.random.default_rng(6)
    rows = rng.integers(0, BLOCK, 100).astype(np.int32)
    table = rng.normal(size=(BLOCK, 8)).astype(np.float32)
    layout = sorted_stream_layout(jnp.asarray(rows), BLOCK)
    with pytest.raises(ValueError, match="shared layout"):
        sorted_gather(jnp.asarray(rows[:50]), jnp.asarray(table),
                      width=8, interpret=True, layout=layout)


def test_width_guards():
    rng = np.random.default_rng(7)
    rows = jnp.asarray(rng.integers(0, 64, 16).astype(np.int32))
    wide = jnp.asarray(rng.normal(size=(64, 130)).astype(np.float32))
    with pytest.raises(ValueError, match="table width"):
        sorted_gather(rows, wide, width=16, interpret=True)
    tbl = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
    with pytest.raises(ValueError, match="width"):
        sorted_gather(rows, tbl, width=9, interpret=True)


def test_pull_local_kernel_path_matches_xla():
    """Full single-shard pull_local through the Pallas (interpret)
    gather equals the XLA-gather path — emb, w, show, click — with
    padding (trash-row) requests in the batch."""
    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.embedding.lookup import pull_local
    from paddlebox_tpu.embedding.table import PassTable

    rng = np.random.default_rng(8)
    rps, d = 300, 4
    ke, kw = 1, 1
    w_width = d + 3 + ke + kw
    vals = rng.normal(size=(rps + 1, w_width)).astype(np.float32)
    vals[rps, :d + 3] = 0.0          # trash row pull columns zero
    n = 256
    rows = rng.integers(0, rps, n).astype(np.int32)
    rows[::5] = rps                  # padding entries -> trash row

    def run(mode):
        flagmod.set_flags({"sparse_gather_kernel": mode})
        try:
            table = PassTable(vals=jnp.asarray(vals), rows_per_shard=rps,
                              num_shards=1, dim=d, ke=ke, kw=kw)
            out = pull_local(table, jnp.asarray(rows), axis="dp")
            return {k: np.asarray(v) for k, v in out.items()}
        finally:
            flagmod.set_flags({"sparse_gather_kernel": "auto"})

    a = run("xla")
    b = run("interpret")
    for k in ("emb", "w", "show", "click", "overflow"):
        np.testing.assert_array_equal(b[k], a[k], err_msg=k)


def test_sharded_pull_push_kernel_parity(devices8):
    """Multi-shard pull + push through compute_bucketing's SHARED
    layout (one rows exchange + one argsort) in interpret mode equal
    the XLA paths bit-for-bit — the serve-side gather and the owner-side
    scatter both consume the same sort."""
    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.embedding.lookup import (compute_bucketing,
                                                pull_local, push_local)
    from paddlebox_tpu.embedding.optimizers import SparseAdagrad
    from paddlebox_tpu.embedding.table import PassTable
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from jax.sharding import PartitionSpec as P
    import functools

    ndev = 4
    mesh = build_mesh(HybridTopology(dp=ndev), devices=devices8[:ndev])
    rng = np.random.default_rng(9)
    rps, d = 64, 4
    ke, kw = 1, 1
    block = rps + 1
    w_width = d + 3 + ke + kw
    vals = rng.normal(size=(ndev * block, w_width)).astype(np.float32)
    for s in range(ndev):
        vals[s * block + rps, :d + 3] = 0.0
    n_local = 40
    rows = rng.integers(0, ndev * block, ndev * n_local).astype(np.int32)
    rows[::7] = (rows[::7] // block) * block + rps     # padding -> trash
    g_emb = rng.normal(size=(ndev * n_local, d)).astype(np.float32)
    g_w = rng.normal(size=(ndev * n_local,)).astype(np.float32)
    shows = np.ones((ndev * n_local,), np.float32)
    clicks = (rng.random(ndev * n_local) < 0.4).astype(np.float32)

    def run(gmode, smode):
        flagmod.set_flags({"sparse_gather_kernel": gmode,
                           "sparse_scatter_kernel": smode})
        try:
            @jax.jit
            @functools.partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P("dp"),
                          P("dp")),
                out_specs=(P("dp"), P("dp")),
                check_vma=False)
            def both(table, dev_rows, ge, gw, sh, ck):
                bk = compute_bucketing(table, dev_rows, axis="dp")
                pulled = pull_local(table, dev_rows, axis="dp",
                                    bucketing=bk)
                new = push_local(table, dev_rows, ge, gw, sh, ck,
                                 axis="dp", opt=SparseAdagrad(),
                                 bucketing=bk)
                return pulled["emb"], new.vals

            table = PassTable(vals=jnp.asarray(vals), rows_per_shard=rps,
                              num_shards=ndev, dim=d, ke=ke, kw=kw)
            emb, new_vals = both(table, jnp.asarray(rows),
                                 jnp.asarray(g_emb), jnp.asarray(g_w),
                                 jnp.asarray(shows), jnp.asarray(clicks))
            return np.asarray(emb), np.asarray(new_vals)
        finally:
            flagmod.set_flags({"sparse_gather_kernel": "auto",
                               "sparse_scatter_kernel": "auto"})

    emb_x, vals_x = run("xla", "xla")
    emb_k, vals_k = run("interpret", "interpret")
    np.testing.assert_allclose(emb_k, emb_x, rtol=1e-6, atol=1e-6)
    # Trash-row optimizer state may differ (kernel drops trash updates);
    # everything consumable must match.
    for s in range(ndev):
        np.testing.assert_allclose(
            vals_k[s * block:s * block + rps],
            vals_x[s * block:s * block + rps], rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(
            vals_k[s * block + rps, :d + 3],
            vals_x[s * block + rps, :d + 3])
