"""AutoInt through CTRTrainer end-to-end + a numpy attention oracle."""

import numpy as np
import pytest

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import AutoInt
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("a", "b")


def test_autoint_learns_interaction(tmp_path):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    model = AutoInt(slot_names=SLOTS, emb_dim=8, att_dim=16, num_heads=2,
                    num_layers=2, hidden=(32,))
    tr = CTRTrainer(model, feed, TableConfig(dim=8, learning_rate=0.2),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10,
                                         dense_learning_rate=3e-3))
    tr.init(seed=0)
    rng = np.random.default_rng(9)
    p = str(tmp_path / "part")
    with open(p, "w") as f:
        for _ in range(512):
            a, b = rng.integers(1, 60), rng.integers(1, 60)
            # Pure interaction signal (same planting as the DCN/CIN
            # tests): neither field alone predicts the label.
            label = int(((a % 2) == (b % 2)) == (rng.random() < 0.85))
            f.write(f"{label} a:{a} b:{b}\n")
    losses = []
    for _ in range(7):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        stats = tr.train_pass(ds)
        losses.append(stats["loss"])
    assert losses[-1] < losses[0]
    assert stats["auc"] > 0.62, stats["auc"]


def test_autoint_matches_numpy_oracle():
    """apply() against an independently written numpy attention tower
    with TWO layers and att_dim != emb_dim, so any head/field axis mixup
    or residual-projection slip changes the answer."""
    import jax
    import jax.numpy as jnp

    model = AutoInt(slot_names=SLOTS, emb_dim=4, att_dim=6, num_heads=3,
                    num_layers=2, hidden=())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    bs = 3
    emb = {s: jnp.asarray(rng.normal(size=(bs, 4)), jnp.float32)
           for s in SLOTS}
    w = {s: jnp.asarray(rng.normal(size=(bs,)), jnp.float32)
         for s in SLOTS}
    segs = {s: jnp.arange(bs, dtype=jnp.int32) for s in SLOTS}
    got = np.asarray(model.apply(params, emb, w, segs, batch_size=bs))

    def softmax(z):
        e = np.exp(z - z.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    x = np.stack([np.asarray(emb[s]) for s in SLOTS], axis=1)  # [B,2,4]
    dh = 2
    for layer in params["att"]:
        wq, wk, wv = (np.asarray(layer[k]) for k in ("wq", "wk", "wv"))
        heads = []
        for hh in range(3):
            q = x @ wq[hh]                                  # [B,m,dh]
            k = x @ wk[hh]
            v = x @ wv[hh]
            s = q @ np.swapaxes(k, 1, 2) / np.sqrt(dh)
            heads.append(softmax(s) @ v)
        o = np.concatenate(heads, axis=-1)                  # [B,m,6]
        x = np.maximum(o + x @ np.asarray(layer["wr"]), 0.0)
    head = params["head"]
    logits = (x.reshape(bs, -1) @ np.asarray(head["w"])
              )[:, 0] + np.asarray(head["b"])[0]
    wide = sum(np.asarray(w[s]) for s in SLOTS)
    ref = logits + wide + float(params["bias"])
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_autoint_rejects_mixed_widths():
    with pytest.raises(ValueError, match="uniform emb_dim"):
        AutoInt(slot_names=SLOTS, emb_dim={"a": 4, "b": 8}).init(
            __import__("jax").random.PRNGKey(0))
    with pytest.raises(ValueError, match="must divide"):
        AutoInt(slot_names=SLOTS, emb_dim=4, att_dim=5,
                num_heads=2).init(__import__("jax").random.PRNGKey(0))
    with pytest.raises(ValueError, match="num_layers"):
        AutoInt(slot_names=SLOTS, emb_dim=4, num_layers=0).init(
            __import__("jax").random.PRNGKey(0))
