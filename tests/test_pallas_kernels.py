"""Pallas kernel parity tests: interpreter-mode kernels vs XLA oracles.

Mirrors the reference's OpTest pattern (SURVEY.md §4: per-op numeric
parity harness, ``tests/unittests/op_test.py``) for the hand-written
kernels: forward values and grads must match the XLA reference
implementations that define the op semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ops.pallas_kernels import (
    flash_attention,
    flash_attention_reference,
    seqpool_cvm_pallas,
)


def _qkv(rng, b, s, h, d, sk=None):
    sk = s if sk is None else sk
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, sk, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, sk, h, d)).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 16, 2, 8), (1, 24, 1, 4)])
def test_flash_attention_forward(causal, shape):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, *shape)
    got = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    want = flash_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_unpadded_vs_padded():
    # Sq not a multiple of the block: wrapper pads and slices.
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 1, 13, 2, 8)
    got = flash_attention(q, k, v, causal=True, block_q=8, block_k=8,
                          interpret=True)
    want = flash_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, 2, 16, 2, 8)

    def loss_pallas(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=8,
                              block_k=8, interpret=True)
        return jnp.sum(out * out)

    def loss_ref(q, k, v):
        out = flash_attention_reference(q, k, v, causal=causal)
        return jnp.sum(out * out)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_attention_offsets_match_global():
    # Ring-attention contract: per-block kernel with k_offset equals the
    # corresponding slice of full causal attention... exercised by
    # comparing a shifted-k block vs the reference with same offsets.
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, 1, 8, 1, 4, sk=8)
    got = flash_attention(q, k, v, causal=True, q_offset=8, k_offset=0,
                          block_q=8, block_k=8, interpret=True)
    want = flash_attention_reference(q, k, v, causal=True, q_offset=8,
                                     k_offset=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_flash_attention_fallback_backend():
    # use_pallas=False returns the XLA path (non-TPU production default).
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, 1, 8, 1, 4)
    got = flash_attention(q, k, v, causal=False, use_pallas=False)
    want = flash_attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def _seqpool_case(rng, n, num_rows, dim):
    emb = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    show = jnp.asarray(
        rng.integers(0, 5, size=(n,)).astype(np.float32))
    click = jnp.asarray(
        rng.integers(0, 3, size=(n,)).astype(np.float32))
    # Sorted CSR segments, with some rows empty and trailing padding.
    seg = np.sort(rng.integers(0, num_rows, size=(n - 2,)))
    seg = np.concatenate([seg, [num_rows, num_rows]]).astype(np.int32)
    return emb, show, click, jnp.asarray(seg)


@pytest.mark.parametrize("use_cvm", [True, False])
def test_seqpool_cvm_pallas_forward(use_cvm):
    rng = np.random.default_rng(5)
    emb, show, click, seg = _seqpool_case(rng, 30, 7, 6)
    got = seqpool_cvm_pallas(emb, show, click, seg, 7, use_cvm=use_cvm,
                             block_b=8, block_n=8, interpret=True)
    want = fused_seqpool_cvm(emb, show, click, seg, 7, use_cvm=use_cvm)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("use_cvm", [True, False])
def test_seqpool_cvm_pallas_grads(use_cvm):
    rng = np.random.default_rng(6)
    emb, show, click, seg = _seqpool_case(rng, 20, 5, 4)

    def loss_pallas(emb):
        out = seqpool_cvm_pallas(emb, show, click, seg, 5,
                                 use_cvm=use_cvm, block_b=8, block_n=8,
                                 interpret=True)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    def loss_ref(emb):
        out = fused_seqpool_cvm(emb, show, click, seg, 5,
                                use_cvm=use_cvm)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    gp = jax.grad(loss_pallas)(emb)
    gr = jax.grad(loss_ref)(emb)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=1e-4, atol=1e-4)


def test_seqpool_cvm_clip():
    rng = np.random.default_rng(7)
    emb, show, click, seg = _seqpool_case(rng, 12, 3, 4)
    emb = emb * 100.0
    got = seqpool_cvm_pallas(emb, show, click, seg, 3, clip_value=5.0,
                             block_b=8, block_n=8, interpret=True)
    want = fused_seqpool_cvm(emb, show, click, seg, 3, clip_value=5.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
