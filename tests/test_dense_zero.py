"""FLAGS_dense_zero bit-parity: ZeRO-1/2 sharded and host-offloaded
dense optimizer state vs the replicated baseline.

Role of the reference's sharding optimizer-state partition/offload
(fleet/meta_optimizers/sharding_optimizer.py + sharding/offload_helper):
the SAME model trajectory with 1/dp (or ~zero) of the optimizer bytes
resident per device. Parity here is BITWISE in f32, not allclose — the
shard path decomposes the update into psum -> zero_slice -> elementwise
update on shards -> all-gather, which is element-for-element the
replicated math; the offload path fuses update+apply in one jitted
program so FMA rounding matches the in-step fused update. Any drift
means the decomposition reordered float math and would silently fork
training from the replicated baseline.
"""


import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.parallel import zero as zero_lib
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("u", "i")


def _shard(path, n=256, seed=3):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, 120, rng.integers(1, 3))
                     for s in SLOTS}
            click = np.mean([(int(v) % 5 == 0)
                             for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * click)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shard_file(tmp_path_factory):
    return _shard(tmp_path_factory.mktemp("zero") / "part-0")


@pytest.fixture(autouse=True)
def _restore_zero_flags():
    old = {k: flagmod.flag(k) for k in
           ("dense_zero", "dense_zero_min_size",
            "trainer_steps_per_dispatch")}
    try:
        yield
    finally:
        flagmod.set_flags(old)


def _train(shard_file, dense_zero, *, sync_mode="step", k=1,
           optimizer="adam", clip=1.0, passes=2, megastep=1):
    flagmod.set_flags({"dense_zero": dense_zero,
                       "dense_zero_min_size": 0,
                       "trainer_steps_per_dispatch": megastep})
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    cfg = TrainerConfig(dense_optimizer=optimizer,
                        dense_learning_rate=0.01,
                        auc_num_buckets=1 << 10,
                        dense_sync_mode=sync_mode,
                        dense_sync_interval=k,
                        grad_clip_norm=clip)
    t = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                   feed, TableConfig(dim=8, learning_rate=0.1),
                   mesh=mesh, config=cfg)
    t.init(seed=0)
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([shard_file])
    ds.load_into_memory()
    stats = [t.train_pass(ds) for _ in range(passes)]
    return t, stats, t.dense_memory_stats()


def _assert_bitwise(a, b, what):
    la = jax.tree.leaves(jax.device_get(a))
    lb = jax.tree.leaves(jax.device_get(b))
    assert len(la) == len(lb), what
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"{what}: leaf {i} diverged")


def test_shard_bitwise_parity_and_memory(shard_file):
    """dense_zero='shard' on a dp=8 mesh: every param/opt_state leaf
    and every pass loss bit-identical to replicated, while the resident
    opt_state bytes drop toward 1/dp (acceptance: <= replicated/2 with
    slack for the handful of tiny non-divisible leaves)."""
    t0, s0, m0 = _train(shard_file, "off")
    t1, s1, m1 = _train(shard_file, "shard")
    assert m1["dense_zero"] == "shard"
    _assert_bitwise(t0.params, t1.params, "params")
    _assert_bitwise(t0.opt_state, t1.opt_state, "opt_state")
    assert [s["loss"] for s in s0] == [s["loss"] for s in s1]
    assert m0["opt_state_hbm_bytes"] > 0
    assert (m1["opt_state_hbm_bytes"]
            <= m0["opt_state_hbm_bytes"] / 2 + 1024)
    # Params are NOT sharded (ZeRO-1/2, not ZeRO-3).
    assert m1["params_hbm_bytes"] == m0["params_hbm_bytes"]


def test_offload_bitwise_parity(shard_file):
    """dense_zero='offload': the host-resident state path must stay
    bit-identical too — the update+apply runs as ONE jitted program
    precisely so FMA fusion rounds like the in-step fused update."""
    t0, s0, _ = _train(shard_file, "off")
    t2, s2, m2 = _train(shard_file, "offload")
    assert m2["dense_zero"] == "offload"
    _assert_bitwise(t0.params, t2.params, "params")
    _assert_bitwise(t0.opt_state, t2.opt_state, "opt_state")
    assert [s["loss"] for s in s0] == [s["loss"] for s in s2]


def test_shard_parity_under_megastep(shard_file):
    """K=4 steps per dispatch (the megastep lax.scan body) consumes the
    sharded state across scan iterations — parity must hold there too,
    not just in the K=1 program."""
    t0, s0, _ = _train(shard_file, "off", megastep=4)
    t1, s1, _ = _train(shard_file, "shard", megastep=4)
    _assert_bitwise(t0.params, t1.params, "params")
    _assert_bitwise(t0.opt_state, t1.opt_state, "opt_state")
    assert [s["loss"] for s in s0] == [s["loss"] for s in s1]


def test_shard_under_async_dense_places_and_trains(shard_file):
    """dense_sync_mode='async' (host dense table) with sharded state:
    async is inherently nondeterministic run-to-run (the host updater
    races the steps — two IDENTICAL 'off' runs already differ in low
    bits), so bitwise parity is the wrong assertion here. What must
    hold: the ZeRO placement engages (opt bytes drop toward 1/dp),
    and the async pass still trains to a finite loss on the same step
    count. async owns its own clip policy, so no grad_clip here."""
    t0, s0, m0 = _train(shard_file, "off", sync_mode="async", clip=0.0)
    t1, s1, m1 = _train(shard_file, "shard", sync_mode="async", clip=0.0)
    assert m1["dense_zero"] == "shard"
    assert m0["opt_state_hbm_bytes"] > 0
    assert (m1["opt_state_hbm_bytes"]
            <= m0["opt_state_hbm_bytes"] / 2 + 1024)
    assert [s["steps"] for s in s0] == [s["steps"] for s in s1]
    assert all(np.isfinite(s["loss"]) for s in s1)


def test_shard_under_kstep_degrades_with_warning(shard_file):
    """'shard' + 'kstep' has no replicated copy to shard (k-step state
    is intentionally worker-local): it must degrade to 'off' with a
    warning (the once-latch), bit-identical to the plain kstep run —
    NOT raise, NOT silently mix per-device trajectories through an
    all-gather."""
    t0, s0, _ = _train(shard_file, "off", sync_mode="kstep", k=2,
                       optimizer="sgd", clip=0.0)
    t1, s1, m1 = _train(shard_file, "shard", sync_mode="kstep", k=2,
                        optimizer="sgd", clip=0.0)
    assert m1["dense_zero"] == "off"
    assert t1._zero_warned  # the degrade warning actually fired
    _assert_bitwise(t0.params, t1.params, "params")
    assert [s["loss"] for s in s0] == [s["loss"] for s in s1]


def test_offload_requires_step_mode(shard_file):
    with pytest.raises(ValueError, match="offload.*requires"):
        _train(shard_file, "offload", sync_mode="kstep", k=2,
               optimizer="sgd", clip=0.0, passes=1)


def test_checkpoint_roundtrip_across_placements(shard_file):
    """Save under 'shard', reload under 'off' and under 'shard':
    checkpoints are layout-agnostic (global shapes mode-invariant;
    place_dense re-shards on load) — both reloads bit-match the
    source trainer's host-format state."""
    t1, _, _ = _train(shard_file, "shard")
    host_p = jax.device_get(t1.params)
    host_s = jax.device_get(t1.opt_state)
    for mode in ("off", "shard"):
        t2, _, _ = _train(shard_file, mode, passes=1)
        p2, s2 = t2.place_dense(host_p, host_s)
        _assert_bitwise(host_p, p2, f"params via {mode}")
        _assert_bitwise(host_s, s2, f"opt_state via {mode}")


# ---------------------------------------------------------------------------
# OffloadedOptimizer unit surface (no trainer, pure optax trees)
# ---------------------------------------------------------------------------


def _mesh():
    return build_mesh(HybridTopology(dp=8))


def test_offloaded_optimizer_cache_refreshes_on_shape_change():
    """The jit/shardings cache keys on treedef AND leaf shapes: a
    same-structure state whose leaves changed shape (param growth)
    must rebuild — replaying stale shardings would place the grown
    leaves with the old layout (or throw mid-step)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _mesh()
    rep = NamedSharding(mesh, P())
    tx = zero_lib.OffloadedOptimizer(optax.adam(1e-2), mesh, axis="dp",
                                     min_size=0)
    p1 = jax.device_put({"w": jnp.ones((16, 8), jnp.float32)}, rep)
    s1 = tx.init(p1)
    p1, s1 = tx.update_apply(jax.tree.map(jnp.ones_like, p1), s1, p1)
    fn1 = tx._jit_update_apply
    # Same structure + shapes: cache must be reused (one live program).
    p1, s1 = tx.update_apply(jax.tree.map(jnp.ones_like, p1), s1, p1)
    assert tx._jit_update_apply is fn1
    # Same structure, grown leaf: must rebuild.
    p2 = jax.device_put({"w": jnp.ones((32, 8), jnp.float32)}, rep)
    s2 = tx.init(p2)
    p2, s2 = tx.update_apply(jax.tree.map(jnp.ones_like, p2), s2, p2)
    assert tx._jit_update_apply is not fn1
    fn2 = tx._jit_update_apply
    # New structure (extra leaf): must rebuild again.
    p3 = jax.device_put({"w": jnp.ones((32, 8), jnp.float32),
                         "b": jnp.ones((32,), jnp.float32)}, rep)
    s3 = tx.init(p3)
    p3, s3 = tx.update_apply(jax.tree.map(jnp.ones_like, p3), s3, p3)
    assert tx._jit_update_apply is not fn2
    assert np.isfinite(np.asarray(p3["w"])).all()


def test_offloaded_update_apply_bitwise_vs_fused_jit():
    """update_apply == the one-program fused update+apply, bit-for-bit
    (params output pinned to the input placement, state round-trips
    through host pinning unchanged)."""
    mesh = _mesh()
    base = optax.adam(1e-2)
    tx = zero_lib.OffloadedOptimizer(base, mesh, axis="dp", min_size=0)
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())
    p = jax.device_put({"w": jnp.arange(64., dtype=jnp.float32)
                        .reshape(8, 8) / 7.0}, rep)
    g = jax.tree.map(lambda x: jnp.cos(x), p)

    s_ref = base.init(p)

    @jax.jit
    def fused(gg, ss, pp):
        u, s2 = base.update(gg, ss, pp)
        return optax.apply_updates(pp, u), s2

    p_ref, s_ref = p, s_ref
    p_off, s_off = p, tx.init(p)
    for _ in range(3):
        p_ref, s_ref = fused(g, s_ref, p_ref)
        p_off, s_off = tx.update_apply(g, s_off, p_off)
    _assert_bitwise(p_ref, p_off, "params")
    _assert_bitwise(s_ref, s_off, "opt_state")
    # The offload contract: new params keep the caller's (replicated)
    # placement — the sharded state must not leak into them.
    for leaf in jax.tree.leaves(p_off):
        assert leaf.sharding.is_fully_replicated
