"""Structural regression net over the fused CTR train step.

The r02→r03 rework collapsed the push path from six scatter-adds +
three argsorts + six gathers per step to ONE owner-side
scatter-accumulate + a dense optimizer sweep (PROFILE.md: XLA TPU
scatter costs ~7 ns/element, so scatter COUNT is the step's cost
model). These tests pin the op-level shape of the compiled program so a
refactor that quietly reintroduces per-field scatters (or a second
all_to_all round) fails loudly here instead of as a silent 3x
throughput regression the CPU tests can't see.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
from paddlebox_tpu.utils import inspect as pbx_inspect


def _trainer_and_batch(ndev=4):
    mesh = build_mesh(HybridTopology(dp=ndev),
                      devices=jax.devices()[:ndev])
    slots = tuple(SlotConf(f"s{i}", avg_len=2.0) for i in range(3))
    feed = DataFeedConfig(slots=slots, batch_size=4 * ndev)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                   emb_dim=8, hidden=(16, 8))
    tr = CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10),
                    store_factory=lambda c: DeviceFeatureStore(
                        c, mesh=mesh))
    tr.init(seed=0)
    rng = np.random.default_rng(0)
    lines = [f"{rng.integers(0, 2)} "
             + " ".join(f"s{i}:{rng.integers(1, 40)}" for i in range(3))
             for _ in range(feed.batch_size)]
    batch = SlotBatch.pack_sharded(parse_lines(lines, feed), feed, ndev)
    tr.engine.feed_pass([
        np.unique(np.concatenate([batch.ids[n] for n in g.slots]))
        for g in tr.engine.groups])
    return tr, batch


def _step_op_counts(ndev=4):
    tr, batch = _trainer_and_batch(ndev)
    step = tr._build_step()
    tables = tr.engine.begin_pass()
    rows = tr._map_batch_rows(batch)
    segs = {n: jnp.asarray(batch.segments[n]) for n in batch.ids}
    args = (tables, tr.params, tr.opt_state, tr.auc_state, rows, segs,
            jnp.asarray(batch.labels), jnp.asarray(batch.valid),
            jnp.asarray(_concat_dense_host(batch)),
            jnp.zeros((), jnp.int32))
    return pbx_inspect.jaxpr_summary(lambda *a: step(*a), *args)


def test_ctr_step_collective_and_scatter_budget():
    c = _step_op_counts()
    # Exactly THREE all_to_alls for a single width group: the SHARED
    # rows exchange (compute_bucketing moves send_rows once for the
    # pull's requests AND the push's destinations — same array), the
    # pull reply, and the push payload. A fourth means the pull/push
    # stopped sharing the rows exchange (or a new collective round
    # crept into the hot path).
    assert c.get("all_to_all", 0) == 3, c
    # Scatter budget: ONE shared bucket-set (pull+push share the
    # bucket-by-shard layout), payload add, owner-side accumulate, AUC
    # histograms, and the gather-VJP scatter-adds from autodiff. The
    # six-field push layout this replaced would blow past the ceiling
    # (+5 per width group).
    assert (c.get("scatter-add", 0) + c.get("scatter", 0)) <= 12, c
    # Dedup-before-exchange (r05): representatives come from ONE
    # scatter-min over the row space per width group — a second one
    # means the layout stopped being shared between pull and push.
    assert c.get("scatter-min", 0) <= 1, c
    # ...and its routing costs at most two extra [n] gathers (first_idx,
    # representative cell) on top of the r04 budget of 10.
    assert c.get("gather", 0) <= 12, c
    # SORT-FREE bucketing, dedup included: positions come from a one-hot
    # cumsum and representatives from a scatter-min, so the step carries
    # ZERO sorts (the r02 layout carried 3 argsorts in the push alone;
    # the reference's dedup itself is 2x cub radix sort,
    # heter_comm.h:196-205; the Pallas accumulate's internal sort lives
    # behind the TPU-only flag and is not part of this CPU lowering).
    assert c.get("sort", 0) == 0, c
    assert c.get("cumsum", 0) >= 1, c


def test_ctr_megastep_one_scan_unchanged_per_step_budget():
    """The K-step megastep (FLAGS_trainer_steps_per_dispatch) must be
    ONE lax.scan wrapping the SAME per-step body: exactly one scan in
    the program, and the per-step collective / scatter / sort budgets
    of the K=1 pins above unchanged — jaxpr_summary counts the scan
    body ONCE, so any number here growing with K means ops leaked out
    of the scan (paid per block) or multiplied inside it."""
    K = 4
    tr, batch = _trainer_and_batch()
    mega = tr._build_step(k_steps=K)
    tables = tr.engine.begin_pass()
    rows = tuple(jnp.stack([r] * K) for r in tr._map_batch_rows(batch))
    segs = {n: jnp.stack([jnp.asarray(batch.segments[n])] * K)
            for n in batch.ids}
    stack = lambda x: jnp.stack([jnp.asarray(x)] * K)  # noqa: E731
    args = (tables, tr.params, tr.opt_state, tr.auc_state,
            jnp.zeros((), jnp.int32), jnp.asarray(K, jnp.int32),
            rows, segs, stack(batch.labels), stack(batch.valid),
            stack(_concat_dense_host(batch)))
    c = pbx_inspect.jaxpr_summary(lambda *a: mega(*a), *args)
    assert c.get("scan", 0) == 1, c
    # Per-step budgets identical to test_ctr_step_collective_and_
    # scatter_budget — the scan re-stages the body, it must not reshape
    # it (an extra all_to_all or scatter here costs K× per block).
    assert c.get("all_to_all", 0) == 3, c
    assert (c.get("scatter-add", 0) + c.get("scatter", 0)) <= 12, c
    assert c.get("scatter-min", 0) <= 1, c
    assert c.get("gather", 0) <= 12, c
    assert c.get("sort", 0) == 0, c
    assert c.get("cumsum", 0) >= 1, c


def _walk_eqns(jaxpr, in_cond=False):
    """Yield (primitive_name, eqn, inside_cond_branch) over the whole
    program. ``inside_cond_branch`` marks ops that exist only in a
    lax.cond arm — the sorted-stream kernels keep their exact XLA
    fallback there (the hot-row guard), and the budget below must
    distinguish the fallback's table-sized gather/scatter from one on
    the hot path."""
    for eqn in jaxpr.eqns:
        yield eqn.primitive.name, eqn, in_cond
        inner_cond = in_cond or eqn.primitive.name == "cond"
        for p in eqn.params.values():
            items = p if isinstance(p, (tuple, list)) else (p,)
            for item in items:
                if hasattr(item, "eqns"):
                    yield from _walk_eqns(item, inner_cond)


def test_ctr_step_pallas_mode_no_table_gather_scatter_one_sort():
    """The Pallas sorted-stream pair (sparse_gather_kernel +
    sparse_scatter_kernel = pallas) must leave ZERO XLA gathers reading
    the table and ZERO XLA scatters building the [block, aw] grad
    accumulator on the hot path (the exact fallbacks live inside the
    hot-row lax.cond arms only), and the shared pull+push layout must
    pay exactly ONE argsort per width group — the whole point of
    sharing compute_bucketing's stream layout."""
    import jax.tree_util as jtu

    from paddlebox_tpu.core import flags as flagmod
    from paddlebox_tpu.embedding.table import PassTable

    flagmod.set_flags({"sparse_gather_kernel": "pallas",
                       "sparse_scatter_kernel": "pallas"})
    try:
        mesh = build_mesh(HybridTopology(dp=4), devices=jax.devices()[:4])
        slots = tuple(SlotConf(f"s{i}", avg_len=2.0) for i in range(3))
        feed = DataFeedConfig(slots=slots, batch_size=16)
        model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                       emb_dim=8, hidden=(16, 8))
        tr = CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        rng = np.random.default_rng(0)
        lines = [f"{rng.integers(0, 2)} "
                 + " ".join(f"s{i}:{rng.integers(1, 40)}" for i in range(3))
                 for _ in range(feed.batch_size)]
        batch = SlotBatch.pack_sharded(parse_lines(lines, feed), feed, 4)
        tr.engine.feed_pass([
            np.unique(np.concatenate([batch.ids[n] for n in g.slots]))
            for g in tr.engine.groups])
        step = tr._build_step()
        tables = tr.engine.begin_pass()
        rows = tr._map_batch_rows(batch)
        segs = {n: jnp.asarray(batch.segments[n]) for n in batch.ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows, segs,
                jnp.asarray(batch.labels), jnp.asarray(batch.valid),
                jnp.asarray(_concat_dense_host(batch)),
                jnp.zeros((), jnp.int32))
        jaxpr = jax.make_jaxpr(lambda *a: step(*a))(*args)

        # Per-shard table/accumulator shapes as the shard_map body sees
        # them (gathers/scatters against these are the ~6-7 ns/element
        # ops the kernels exist to kill).
        t = tables[0]
        block, w = t.rows_per_shard + 1, t.vals.shape[-1]
        aw = t.dim + 4
        table_gathers, acc_scatters, sorts = [], [], 0
        for prim, eqn, in_cond in _walk_eqns(jaxpr.jaxpr):
            if prim == "sort":
                sorts += 1
            if in_cond or not eqn.invars:
                continue  # the hot-row fallback arm, by design
            shp = tuple(getattr(eqn.invars[0], "aval", None).shape
                        if hasattr(eqn.invars[0], "aval") else ())
            if prim == "gather" and shp == (block, w):
                table_gathers.append(eqn)
            if prim in ("scatter-add", "scatter") and shp == (block, aw):
                acc_scatters.append(eqn)
        assert not table_gathers, table_gathers
        assert not acc_scatters, acc_scatters
        # One width group -> exactly one argsort, shared by the pull
        # gather and the push scatter via compute_bucketing's layout.
        n_groups = len(tr.engine.groups)
        assert sorts == n_groups, (sorts, n_groups)
        assert jtu.tree_structure(args) is not None  # keep args alive
    finally:
        flagmod.set_flags({"sparse_gather_kernel": "auto",
                           "sparse_scatter_kernel": "auto"})


def test_jaxpr_summary_sees_inside_shard_map():
    """Guard for the introspection fix: shard_map carries a PLAIN Jaxpr
    param; the summary must recurse into it (a regression here silently
    turns the budget test above into {'jit': 1})."""
    from jax.sharding import PartitionSpec as P
    mesh = build_mesh(HybridTopology(dp=4), devices=jax.devices()[:4])

    def body(x):
        return jnp.zeros((8, 4)).at[jnp.array([1, 2])].add(x[:2])

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(),
                              out_specs=P(), check_vma=False))
    c = pbx_inspect.jaxpr_summary(f, jnp.ones((4, 4)))
    assert c.get("scatter-add", 0) >= 1, c
