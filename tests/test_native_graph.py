"""Native parallel CSR build: bit-parity with the numpy stable argsort
path (the contract that lets build_csr switch between them by size), and
a throughput sanity check at the auto-switch scale."""

import time

import numpy as np
import pytest

from paddlebox_tpu.graph.table import build_csr
from paddlebox_tpu.native.graph_py import build_csr_native


def _rand_edges(n, n_nodes, seed=0, weighted=True):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_nodes, n).astype(np.int64)
    dst = rng.integers(0, n_nodes, n).astype(np.int64)
    w = (rng.integers(1, 100, n).astype(np.float32) if weighted else None)
    return src, dst, w


@pytest.mark.parametrize("weighted", [True, False])
@pytest.mark.parametrize("n,n_nodes", [(1, 1), (97, 5), (20_000, 317),
                                       (200_000, 10_000)])
def test_native_matches_numpy_bit_exact(n, n_nodes, weighted):
    src, dst, w = _rand_edges(n, n_nodes, seed=n, weighted=weighted)
    built = build_csr_native(src, dst, w, n_nodes)
    if built is None:
        pytest.skip("native lib unavailable")
    indptr_n, cols_n, w_n = built

    order = np.argsort(src, kind="stable")
    counts = np.bincount(src, minlength=n_nodes)
    indptr = np.zeros(n_nodes + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    np.testing.assert_array_equal(indptr_n, indptr)
    np.testing.assert_array_equal(cols_n, dst[order])
    if weighted:
        np.testing.assert_array_equal(w_n, w[order])
    else:
        assert w_n is None


def test_build_csr_auto_switch_consistency():
    """Above the size threshold build_csr must return the same graph the
    numpy path would (sampling correctness rides on the layout)."""
    n, n_nodes = 150_000, 4_096
    src, dst, w = _rand_edges(n, n_nodes, seed=3)
    g = build_csr(src, dst, num_nodes=n_nodes, weights=w)
    order = np.argsort(src, kind="stable")
    np.testing.assert_array_equal(g.cols, dst[order])
    np.testing.assert_array_equal(g.weights, w[order])
    assert g.indptr[-1] == n


def test_native_build_faster_than_argsort():
    built = build_csr_native(*(_rand_edges(8, 4)[:2]), None, 4)
    if built is None:
        pytest.skip("native lib unavailable")
    n, n_nodes = 2_000_000, 200_000
    src, dst, w = _rand_edges(n, n_nodes, seed=7)
    t0 = time.perf_counter()
    build_csr_native(src, dst, w, n_nodes)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    order = np.argsort(src, kind="stable")
    _ = dst[order]
    _ = w[order]
    t_numpy = time.perf_counter() - t0
    # Gross-pathology canary only (a tight ratio flakes on a loaded CI
    # box): the O(E) counting sort must not be an order of magnitude
    # behind the O(E log E) argsort — that would mean the threading or
    # scatter path broke. In isolation it measures several times FASTER
    # (36M vs 3.4M edges/s on the bench host).
    assert t_native < t_numpy * 10, (t_native, t_numpy)
