"""DayRunner over the DEVICE-resident store tier: the pipelined day loop
(async feed_pass thread racing end_pass on the store lock) must produce
the same checkpoint protocol artifacts and keep training sane — the
production configuration (GPU-resident PS thesis) end to end."""

import os

import numpy as np

from paddlebox_tpu.data import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.day_runner import DayRunner

from tests.test_day_runner import SLOTS, _write_day


def _make_runner(data_root, out_root, mesh):
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10),
        store_factory=lambda cfg: DeviceFeatureStore(cfg, mesh=mesh))
    trainer.init(seed=0)
    return trainer, DayRunner(
        trainer, feed, out_root, data_root=data_root,
        split_interval=60, split_per_pass=1, hours=[0, 1, 2],
        num_reader_threads=2, pipeline_passes=True, save_xbox=True)


def test_pipelined_day_over_device_store(tmp_path):
    data_root = str(tmp_path / "data")
    out_root = str(tmp_path / "out")
    _write_day(data_root, "20260701", [0, 1, 2])
    mesh = build_mesh(HybridTopology(dp=8))
    trainer, runner = _make_runner(data_root, out_root, mesh)
    out = runner.run_days(["20260701"], resume=False)
    assert len(out["20260701"]) == 3
    assert trainer.engine.store.num_features > 0
    # Checkpoint protocol artifacts: per-pass deltas + xbox, day base in
    # the pass-0 dir (reference day/pass-addressed layout).
    day_dir = os.path.join(out_root, "20260701")
    recs = runner.ckpt.records()
    assert [(r.day, r.pass_id) for r in recs] == \
        [("20260701", 1), ("20260701", 2), ("20260701", 3),
         ("20260701", 0)]
    assert os.path.exists(os.path.join(day_dir, "0", "emb.base.npz"))
    assert os.path.exists(os.path.join(day_dir, "2", "emb.delta.npz"))
    assert os.path.exists(os.path.join(day_dir, "1", "emb.xbox.npz"))

    # The day base reloads into a FRESH device store with equal contents.
    mesh2 = build_mesh(HybridTopology(dp=8))
    fresh = DeviceFeatureStore(TableConfig(name="emb", dim=8,
                                           learning_rate=0.1), mesh=mesh2)
    fresh.load(os.path.join(day_dir, "0"), "base")
    assert fresh.num_features == trainer.engine.store.num_features
    keys = np.sort(
        trainer.engine.store._index.keys_by_row())
    a = trainer.engine.store.pull_for_pass(keys)
    b = fresh.pull_for_pass(keys)
    np.testing.assert_allclose(b["emb"], a["emb"], atol=1e-7)


def test_eval_pass_does_not_grow_device_store(tmp_path):
    data_root = str(tmp_path / "data")
    _write_day(data_root, "20260701", [0])
    mesh = build_mesh(HybridTopology(dp=8))
    trainer, _ = _make_runner(data_root, str(tmp_path / "out"), mesh)
    from paddlebox_tpu.data.dataset import Dataset
    feed = trainer.feed_config
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([os.path.join(data_root, "20260701", "00",
                                  "part-00000")])
    ds.load_into_memory()
    trainer.train_pass(ds)
    n_after_train = trainer.engine.store.num_features
    # Eval over data containing UNSEEN keys must not insert them.
    _write_day(data_root, "20260702", [0], seed0=999)
    ds2 = Dataset(feed, num_reader_threads=1)
    ds2.set_filelist([os.path.join(data_root, "20260702", "00",
                                   "part-00000")])
    ds2.load_into_memory()
    stats = trainer.eval_pass(ds2)
    assert np.isfinite(stats["loss"])
    assert trainer.engine.store.num_features == n_after_train
