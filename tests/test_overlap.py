"""Host/device overlap machinery: split-pull async pass builds, the
train-pass prefetch thread, and the pipelined day loop must produce
EXACTLY the results of the serial path (same batch order, same sequencing
of store reads vs write-backs).

Role of the reference's overlap: PreLoadIntoMemory/WaitFeedPassDone
(box_wrapper.h:1140,1161), double-buffered build threads
(ps_gpu_wrapper.cc:907), MiniBatchGpuPack pipelined packing
(data_feed.cc:4611).
"""

import os

import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import PassEngine, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.day_runner import DayRunner

SLOTS = ("u", "i")


def _write_day(root, day, hours, n=96, seed=7):
    rng = np.random.default_rng(seed)
    for h in hours:
        d = os.path.join(root, day, f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-0"), "w") as f:
            for _ in range(n):
                feats = {s: rng.integers(1, 150, rng.integers(1, 3))
                         for s in SLOTS}
                label = int(rng.random() < 0.3)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")


def _make_runner(data, out, pipeline):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=32)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 10))
    trainer.init(seed=0)
    return DayRunner(trainer, feed, out, data_root=data,
                     split_interval=60, split_per_pass=1,
                     hours=[0, 1, 2], num_reader_threads=2,
                     pipeline_passes=pipeline)


def test_pipelined_day_matches_serial(tmp_path):
    data = str(tmp_path / "data")
    _write_day(data, "20260728", [0, 1, 2])

    r_serial = _make_runner(data, str(tmp_path / "out_s"), pipeline=False)
    s_serial = r_serial.train_day("20260728")
    r_pipe = _make_runner(data, str(tmp_path / "out_p"), pipeline=True)
    s_pipe = r_pipe.train_day("20260728")

    assert len(s_serial) == len(s_pipe) == 3
    for a, b in zip(s_serial, s_pipe):
        assert a["steps"] == b["steps"]
        assert np.isclose(a["loss"], b["loss"], rtol=1e-5), (a, b)
        assert np.isclose(a["auc"], b["auc"], rtol=1e-5)

    st_a = r_serial.trainer.engine.store
    st_b = r_pipe.trainer.engine.store
    assert st_a.num_features == st_b.num_features
    keys = np.sort(st_a.dirty_keys())
    va = st_a.pull_for_pass(keys)
    vb = st_b.pull_for_pass(keys)
    np.testing.assert_allclose(va["emb"], vb["emb"], rtol=1e-5)
    np.testing.assert_allclose(va["show"], vb["show"], rtol=1e-5)


def test_split_pull_reads_writeback_for_shared_keys():
    """A pending build that starts during an active pass must see the
    active pass's end_pass write-back for SHARED keys, and may prefetch
    the rest early. Simulate the interleaving explicitly."""
    import jax

    mesh = build_mesh(HybridTopology(dp=8))
    cfg = TableConfig(dim=4, learning_rate=0.1)
    eng = PassEngine(cfg, mesh=mesh, table_axis="dp")

    keys_a = np.arange(1, 65, dtype=np.uint64)
    eng.feed_pass(keys_a)
    table = eng.begin_pass()

    # Mutate pass A's table (simulating training): bump every emb by 1.
    import jax.numpy as jnp
    table = table.with_emb(table.emb + 1.0)
    eng.update_table(table)

    # Async-build pass B while A is still active: B shares keys 33..64
    # and adds 65..96.
    keys_b = np.arange(33, 97, dtype=np.uint64)
    eng.feed_pass(keys_b, async_build=True)
    # The build must be blocked on A's end_pass (only the non-shared
    # prefix may have been pulled).
    eng.end_pass()
    table_b = eng.begin_pass()

    vals = eng.store.pull_for_pass(np.arange(33, 65, dtype=np.uint64))
    # Shared keys carry A's +1 update in both the store and B's table.
    rows = eng.lookup_rows(np.arange(33, 65, dtype=np.uint64))
    emb_b = np.asarray(table_b.emb)[rows]
    np.testing.assert_allclose(emb_b, vals["emb"], rtol=1e-6)
    eng.end_pass()


def test_prefetch_pass_matches_direct_iteration(tmp_path):
    """Two fresh trainers over identical data: prefetch (default) run
    equals a run with depth-1 queue — order and results deterministic."""
    from paddlebox_tpu.core import flags as flagmod

    data = str(tmp_path / "d")
    _write_day(data, "20260728", [0])
    files = [os.path.join(data, "20260728", "00", "part-0")]

    def run(depth):
        old = flagmod.flag("trainer_prefetch_depth")
        flagmod.set_flags({"trainer_prefetch_depth": depth})
        try:
            mesh = build_mesh(HybridTopology(dp=8))
            feed = DataFeedConfig(
                slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
                batch_size=32)
            t = CTRTrainer(
                DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
                TableConfig(dim=8, learning_rate=0.1), mesh=mesh,
                config=TrainerConfig(auc_num_buckets=1 << 10))
            t.init(seed=0)
            ds = Dataset(feed, num_reader_threads=1)
            ds.set_filelist(files)
            ds.load_into_memory()
            return t.train_pass(ds)
        finally:
            flagmod.set_flags({"trainer_prefetch_depth": old})

    a, b = run(1), run(4)
    assert a["steps"] == b["steps"]
    assert np.isclose(a["loss"], b["loss"], rtol=1e-6)
    assert np.isclose(a["auc"], b["auc"], rtol=1e-6)
