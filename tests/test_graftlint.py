"""graftlint (tools/graftlint) as a tier-1 gate.

Two halves:

1. **Planted-violation fixtures** — tiny synthetic projects, one per
   pass, each asserting: the violation is caught, the matching
   ``# graftlint: allow-*`` pragma suppresses it, and a clean variant
   produces nothing. Plus baseline suppression / ``--fail-on new``
   semantics and the near-miss metric-name warning.
2. **The real tree** — ``run_passes(default_config(REPO))`` over
   ``paddlebox_tpu/``, ``tools/`` and ``bench.py`` must produce ZERO
   non-baselined error findings: a PR that introduces a hot-path sync,
   an undocumented flag/metric, a faultpoint/doc drift, an unlocked
   cross-thread write, or replay-path wall-clock FAILS this suite.

No jax import needed by the suite itself — graftlint is stdlib-only.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftlint import (Baseline, DEFAULT_BASELINE,  # noqa: E402
                             RunResult, default_config, fixture_config,
                             run_passes)
from tools.graftlint.passes import registry_drift  # noqa: E402


def _write(root, rel, text):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(textwrap.dedent(text))
    return path


def _by_code(findings, code):
    return [f for f in findings if f.code == code]


def _active(result, code=None):
    out = [f for f in result.active]
    if code is not None:
        out = [f for f in out if f.code == code]
    return out


# ---------------------------------------------------------------------------
# pass 1: hot-path sync detector
# ---------------------------------------------------------------------------

HOT_FIXTURE = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def hot_root(x):
        y = jnp.sum(x)
        helper(y)
        bad = float(y)                      # HS001
        if y > 0:                           # HS005
            pass
        np.asarray(y)                       # HS003
        y.item()                            # HS002
        jax.device_get(y)                   # HS004
        return bad

    def helper(v):
        w = v + jnp.ones(3)
        return int(w)                       # HS001 (reached via root)

    def allowed_root(x):
        y = jnp.sum(x)
        # graftlint: allow-sync(fixture says this one is fine)
        return float(y)

    def clean_root(x):
        y = jnp.sum(x)
        z = y + 1
        if x is not None:                   # identity check: no finding
            z = z * 2
        return z

    def cold(x):
        return float(jnp.sum(x))            # unreachable: no finding
"""


def test_hot_sync_fixture(tmp_path):
    _write(str(tmp_path), "hot.py", HOT_FIXTURE)
    cfg = fixture_config(str(tmp_path), hot_roots=(
        "hot:hot_root", "hot:allowed_root", "hot:clean_root"))
    res = run_passes(cfg, ["hot_sync"])
    codes = sorted(f.code for f in res.active)
    assert codes == ["HS001", "HS001", "HS002", "HS003", "HS004",
                     "HS005"], [f.message for f in res.findings]
    # the helper finding proves call-graph reachability
    assert any("helper" in f.key for f in res.active)
    # the pragma'd float() is recorded as allowed, not active
    allowed = [f for f in res.findings if f.suppressed_by is not None]
    assert len(allowed) == 1
    assert "fixture says" in allowed[0].suppressed_by
    # nothing anchored in clean_root or the unreachable cold()
    assert not any("clean_root" in f.key or ":cold" in f.key
                   for f in res.active)


def test_hot_sync_traced_body_params_are_tracers(tmp_path):
    _write(str(tmp_path), "hot.py", """
        def _build_step(self):
            def body(tables, n):
                if n:                       # tracer truth-test
                    return tables
                return tables
            return body
    """)
    cfg = fixture_config(str(tmp_path), hot_roots=("hot:_build_step",))
    res = run_passes(cfg, ["hot_sync"])
    assert [f.code for f in res.active] == ["HS005"]


# ---------------------------------------------------------------------------
# pass 2: flag hygiene
# ---------------------------------------------------------------------------

FLAGS_FIXTURE = """
    def define_flag(name, default, help="", type=None):
        pass

    def validate_all():
        return ["bad_default does not parse"]

    define_flag("used_documented", 1)
    define_flag("orphan_flag", 2)                 # FH002: never referenced
    define_flag("undocumented_flag", 3)           # FH003: not in DOCS.md
    define_flag("bad_default", "nope", type=int)  # FH005 (static)
"""

FLAG_CODE_FIXTURE = """
    def flag(name):
        return name

    def f():
        flag("used_documented")
        flag("undocumented_flag")
        flag("bad_default")
        flag("missing_flag")                      # FH001
"""

FLAG_DOCS = """
    # Docs
    `FLAGS_used_documented` does things. `FLAGS_orphan_flag` too, and
    `FLAGS_bad_default`. But `FLAGS_ghost_flag` was renamed away.  <!-- FH004 -->
"""


def test_flag_hygiene_fixture(tmp_path):
    _write(str(tmp_path), "flags.py", FLAGS_FIXTURE)
    _write(str(tmp_path), "code.py", FLAG_CODE_FIXTURE)
    _write(str(tmp_path), "DOCS.md", FLAG_DOCS)
    cfg = fixture_config(str(tmp_path))
    res = run_passes(cfg, ["flag_hygiene"])
    assert [f.key for f in _active(res, "FH001")] == ["missing_flag"]
    assert [f.key for f in _active(res, "FH002")] == ["orphan_flag"]
    assert [f.key for f in _active(res, "FH003")] == ["undocumented_flag"]
    assert [f.key for f in _active(res, "FH004")] == ["ghost_flag"]
    # FH005 twice: the static type/default mismatch AND the module's own
    # validate_all() report
    fh5 = _active(res, "FH005")
    assert any(f.key == "bad_default" for f in fh5)
    assert any("bad_default does not parse" in f.message for f in fh5)


def test_flag_hygiene_pragma_on_define(tmp_path):
    _write(str(tmp_path), "flags.py", """
        def define_flag(name, default, help="", type=None): pass
        def validate_all(): return []
        # graftlint: allow-flag(kept for operator compat)
        define_flag("deliberate_orphan", 1)
    """)
    _write(str(tmp_path), "DOCS.md", "`FLAGS_deliberate_orphan`\n")
    cfg = fixture_config(str(tmp_path))
    res = run_passes(cfg, ["flag_hygiene"])
    assert not res.active
    assert any(f.suppressed_by for f in res.findings)


# ---------------------------------------------------------------------------
# pass 3: registry drift (+ near-miss warning)
# ---------------------------------------------------------------------------

REGISTRY_CODE = """
    from x import monitor, faults

    def f(site):
        faults.faultpoint("eng/build")
        faults.faultpoint("eng/missing_from_doc")   # RD001
        monitor.add("ns/good_metric", 1)
        monitor.add("ns/typo_metrc", 1)             # RD004 near-miss
        monitor.add("ns/very_undocumented", 1)      # RD003
        monitor.add(f"dyn/{site}_done", 1)          # pattern: doc has dyn/<s>_done
"""

REGISTRY_DOCS = """
    # Docs

    metrics: `ns/good_metric`, `ns/typo_metric`, `dyn/<site>_done`,
    and `ns/stale_gone` (RD005).

    ## Faultpoint site table

    | Site | Where |
    |---|---|
    | `eng/build` | the build |
    | `eng/stale_site` | removed long ago |
"""


def test_registry_drift_fixture(tmp_path):
    _write(str(tmp_path), "code.py", REGISTRY_CODE)
    _write(str(tmp_path), "DOCS.md", REGISTRY_DOCS)
    cfg = fixture_config(str(tmp_path))
    res = run_passes(cfg, ["registry_drift"])
    assert [f.key for f in _active(res, "RD001")] == ["eng/missing_from_doc"]
    assert [f.key for f in _active(res, "RD002")] == ["eng/stale_site"]
    assert [f.key for f in _active(res, "RD003")] == ["ns/very_undocumented"]
    near = _active(res, "RD004")
    assert [f.key for f in near] == ["ns/typo_metrc"]
    assert near[0].severity == "warn"
    assert "ns/typo_metric" in near[0].message     # the did-you-mean
    assert [f.key for f in _active(res, "RD005")] == ["ns/stale_gone"]
    # the f-string pattern matched the <site> doc form: no finding for it
    assert not any("dyn/" in f.key for f in res.active)


def test_registry_transient_contract(tmp_path):
    _write(str(tmp_path), "faults_mod.py", """
        _TRANSIENT_TYPES = (OSError,)
        class InjectedFault(RuntimeError):
            pass
        def is_transient(e):
            return isinstance(e, _TRANSIENT_TYPES)
    """)
    _write(str(tmp_path), "DOCS.md", "## Faultpoint site table\n")
    cfg = fixture_config(str(tmp_path))
    res = run_passes(cfg, ["registry_drift"])
    assert [f.code for f in res.active] == ["RD006"]


def test_globs_intersect():
    gi = registry_drift.globs_intersect
    assert gi("pass/*_steps", "pass/train_*")
    assert gi("a/b", "a/b")
    assert not gi("a/b", "a/c")
    assert gi("fault/*_injected", "fault/eng/build_injected")
    assert not gi("pass/*_steps", "day/*")
    assert gi("*", "anything/at/all")


# ---------------------------------------------------------------------------
# pass 4: lock discipline
# ---------------------------------------------------------------------------

LOCK_FIXTURE = """
    import threading

    class Racy:
        def __init__(self):
            self._lock = threading.Lock()
            self._evt = threading.Event()
            self.counter = 0
            self._t = threading.Thread(target=self._work)

        def _work(self):
            self.counter += 1          # LD001: unlocked thread write
            self._evt.wait()           # LD003: untimed wait off main

        def read(self):
            return self.counter

    class Clean:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            self._t = threading.Thread(target=self._work)

        def _work(self):
            with self._lock:
                self.n += 1

        def read(self):
            with self._lock:
                return self.n

    class Pragmad:
        def __init__(self):
            self.flagv = False
            self._t = threading.Thread(target=self._work)

        def _work(self):
            # graftlint: allow-lock(monotonic latch, torn read fine)
            self.flagv = True

        def read(self):
            return self.flagv

    class DeadlockA:
        def __init__(self):
            self.la = threading.Lock()
            self.lb = threading.Lock()
            self._t = threading.Thread(target=self.one)

        def one(self):
            with self.la:
                with self.lb:
                    pass

        def two(self):
            with self.lb:
                with self.la:      # LD002: cycle la->lb->la
                    pass
"""


def test_lock_discipline_fixture(tmp_path):
    _write(str(tmp_path), "locks.py", LOCK_FIXTURE)
    cfg = fixture_config(str(tmp_path))
    res = run_passes(cfg, ["lock_discipline"])
    ld1 = _active(res, "LD001")
    assert [f.key for f in ld1] == ["Racy.counter"], \
        [f.message for f in res.findings]
    assert _active(res, "LD002"), "lock-order cycle not detected"
    ld3 = _active(res, "LD003")
    assert len(ld3) == 1 and ld3[0].severity == "warn"
    assert "_evt.wait" in ld3[0].key
    # the pragma'd latch is suppressed, the clean class silent
    assert any(f.suppressed_by and "Pragmad.flagv" in f.key
               for f in res.findings)
    assert not any("Clean." in f.key for f in res.active)


# ---------------------------------------------------------------------------
# pass 5: replay purity
# ---------------------------------------------------------------------------

REPLAY_FIXTURE = """
    import time
    import random
    import numpy as np

    def replay_root():
        t = time.time()                  # RP001
        r = random.random()              # RP002
        z = np.random.shuffle([1, 2])    # RP002
        s = {1, 2, 3}
        for x in s:                      # RP003 (warn)
            pass
        time.sleep(0.001)                # allowed
        ok = time.monotonic()            # allowed
        rng = np.random.default_rng(42)  # allowed (seeded)
        return sorted(s)                 # allowed

    def pragma_root():
        # graftlint: allow-replay(timestamp metadata only)
        return time.time()

    def cold():
        return time.time()               # unreachable: no finding
"""


def test_replay_purity_fixture(tmp_path):
    _write(str(tmp_path), "replay.py", REPLAY_FIXTURE)
    cfg = fixture_config(str(tmp_path), replay_roots=(
        "replay:replay_root", "replay:pragma_root"))
    res = run_passes(cfg, ["replay_purity"])
    assert [f.code for f in _active(res, "RP001")] == ["RP001"]
    assert len(_active(res, "RP002")) == 2
    rp3 = _active(res, "RP003")
    assert len(rp3) == 1 and rp3[0].severity == "warn"
    assert any(f.suppressed_by == "timestamp metadata only"
               for f in res.findings)
    assert not any(":cold" in f.key for f in res.active)


# ---------------------------------------------------------------------------
# baseline + fail-on semantics
# ---------------------------------------------------------------------------

def _flag_fixture_result(tmp_path) -> RunResult:
    _write(str(tmp_path), "flags.py", FLAGS_FIXTURE)
    _write(str(tmp_path), "code.py", FLAG_CODE_FIXTURE)
    _write(str(tmp_path), "DOCS.md", FLAG_DOCS)
    return run_passes(fixture_config(str(tmp_path)), ["flag_hygiene"])


def test_baseline_suppression_and_fail_on(tmp_path):
    res = _flag_fixture_result(tmp_path)
    assert res.failures("new"), "fixture must fail with no baseline"
    # baseline every current finding -> fail-on new passes, any fails
    bl = Baseline({f.fingerprint(res.root): "reviewed: fixture"
                   for f in res.active})
    res.apply_baseline(bl)
    assert res.failures("new") == []
    assert res.failures("any"), "--fail-on any ignores the baseline"
    assert res.failures("none") == []
    s = res.summary()
    assert s["new"] == 0 and s["baselined"] == len(res.active)


def test_baseline_is_line_number_stable(tmp_path):
    res1 = _flag_fixture_result(tmp_path)
    bl = Baseline({f.fingerprint(res1.root): "ok" for f in res1.active})
    # shift every line down; fingerprints must not move
    for rel in ("flags.py", "code.py"):
        p = os.path.join(str(tmp_path), rel)
        with open(p) as f:
            src = f.read()
        with open(p, "w") as f:
            f.write("# shifted\n# shifted\n" + src)
    res2 = run_passes(fixture_config(str(tmp_path)), ["flag_hygiene"])
    res2.apply_baseline(bl)
    assert res2.failures("new") == []


def test_baseline_save_load_roundtrip(tmp_path):
    path = os.path.join(str(tmp_path), "bl.json")
    bl = Baseline({"a:b:c:d": "why"})
    bl.save(path)
    assert Baseline.load(path).entries == {"a:b:c:d": "why"}
    assert Baseline.load(os.path.join(str(tmp_path), "nope.json")).entries \
        == {}


# ---------------------------------------------------------------------------
# the real tree: the adoption gate
# ---------------------------------------------------------------------------

def test_real_tree_has_no_new_findings():
    """The tier-1 contract: graftlint over paddlebox_tpu/, tools/ and
    bench.py yields zero non-baselined errors. If this fails, either fix
    the finding, add an inline pragma with a reason, or (for a reviewed
    intentional case) add a baseline entry with a reason."""
    cfg = default_config(REPO)
    res = run_passes(cfg)
    res.apply_baseline(Baseline.load(DEFAULT_BASELINE))
    failures = res.failures("new")
    msg = "\n".join(
        f"{os.path.relpath(f.path, REPO)}:{f.lineno} [{f.pass_id}/"
        f"{f.code}] {f.message}" for f in failures)
    assert not failures, f"new graftlint findings:\n{msg}"
    assert res.files_scanned > 100  # the walker really saw the tree


def test_real_tree_every_pragma_has_a_reason():
    """Pragmas are the inline escape hatch; an empty reason defeats the
    review trail."""
    res = run_passes(default_config(REPO))
    for f in res.findings:
        if f.suppressed_by is not None:
            assert f.suppressed_by.strip() not in ("", "allowed by pragma"), \
                f"{f.path}:{f.lineno} pragma without a reason"


def test_cli_end_to_end(tmp_path):
    """python -m tools.graftlint over the real tree: exit 0, JSON and
    summary artifacts parse, planted regression exits 1."""
    summary_path = os.path.join(str(tmp_path), "s.json")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json",
         "--summary", summary_path],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["summary"]["new"] == 0
    with open(summary_path) as f:
        summary = json.load(f)
    assert summary["findings_total"] >= summary["baselined"]
    assert set(summary["per_pass"]) == {
        "hot_sync", "flag_hygiene", "registry_drift",
        "lock_discipline", "replay_purity"}


def test_cli_fails_on_planted_violation(tmp_path):
    """A fixture tree with a violation + the CLI --fail-on new exits
    nonzero; --write-baseline then adopts it and the rerun exits 0."""
    root = str(tmp_path)
    _write(root, "flags.py",
           "def define_flag(n, d, help='', type=None): pass\n"
           "def validate_all(): return []\n")
    _write(root, "DOCS.md", "nothing\n")
    _write(root, "code.py",
           "def flag(n): return n\n"
           "def f(): flag('nonexistent_flag')\n")
    bl = os.path.join(root, "bl.json")
    args = [sys.executable, "-m", "tools.graftlint", "--root", root,
            "--baseline", bl, "--passes", "flag_hygiene", ""]
    proc = subprocess.run(args, cwd=REPO, capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "nonexistent_flag" in proc.stdout
    adopt = subprocess.run(
        args[:-1] + ["--write-baseline", ""],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert adopt.returncode == 0, adopt.stdout + adopt.stderr
    proc2 = subprocess.run(args, cwd=REPO, capture_output=True,
                           text=True, timeout=120)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr


# ---------------------------------------------------------------------------
# flags.validate_all (the small-fix satellite)
# ---------------------------------------------------------------------------

def test_validate_all_clean_and_dirty():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_flags_probe_test", os.path.join(
            REPO, "paddlebox_tpu", "core", "flags.py"))
    flags = importlib.util.module_from_spec(spec)
    sys.modules["_flags_probe_test"] = flags
    try:
        spec.loader.exec_module(flags)
    finally:
        sys.modules.pop("_flags_probe_test", None)
    # the live registry's defaults all round-trip
    assert flags.validate_all() == []
    # a planted bad default is caught
    reg = flags.FlagRegistry()
    reg.define("fine", 3)
    reg.define("bad", "xyz", type=int)
    errs = reg.validate_all()
    assert len(errs) == 1 and "bad" in errs[0]
    # bool/int confusion is caught (True is an int at isinstance level)
    reg2 = flags.FlagRegistry()
    reg2.define("sneaky", True, type=int)
    assert any("sneaky" in e for e in reg2.validate_all())
