"""Subprocess shard host for the replicated-tier failover drill
(tests/test_shard_failover_drill.py): one ShardServer on an ephemeral
loopback port, heartbeating its endpoint into the elastic root
(``shard_endpoint`` meta — the discovery path the repair controller
reads), then idling until the harness SIGKILLs it. The process IS the
failure domain: kill -9 takes the socket, the slot stores, and the
journal with it, exactly like a dead production host."""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(root: str, host_id: str, index: int, world: int) -> None:
    from paddlebox_tpu.embedding.table import TableConfig
    from paddlebox_tpu.launch.elastic import ElasticManager
    from paddlebox_tpu.multihost.keyrange import ShardRangeTable
    from paddlebox_tpu.multihost.shard_service import ShardServer

    cfg = TableConfig(name="emb", dim=8, learning_rate=0.1)
    server = ShardServer("127.0.0.1:0", index,
                         ShardRangeTable.for_world(world), cfg)
    mgr = ElasticManager(os.path.join(root, "elastic"), host_id,
                         heartbeat_interval=0.1, timeout=1.0,
                         settle=0.2,
                         meta={"shard_endpoint": server.endpoint})
    mgr.start()
    # Atomic endpoint advertisement for the harness (the rank table is
    # the controller's discovery path; this file is the test's).
    tmp = os.path.join(root, f".{host_id}.ep.tmp")
    with open(tmp, "w") as f:
        json.dump({"endpoint": server.endpoint, "pid": os.getpid()}, f)
    os.replace(tmp, os.path.join(root, f"{host_id}.ep"))
    while True:
        time.sleep(0.2)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
