"""Every suite run produces the round's multichip artifact.

VERDICT-r04 #4: three rounds of driver MULTICHIP captures died upstream
of ``dryrun_multichip`` (dead accelerator tunnel wedging backend init in
the capture process), leaving opaque rc=124 records for work that was
green all along. This test runs the REAL ``dryrun_multichip`` in-process
on the suite's 8-virtual-device CPU mesh — the same code path the driver
invokes — and pins that it (a) prints its pre-entry beacon and (b) writes
``MULTICHIP_LOCAL.json`` with every sub-dryrun OK, so each round carries
a self-produced, attributable multichip record regardless of what
happens to the driver's capture window.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_writes_local_artifact(devices8, capsys):
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as graft

        graft.dryrun_multichip(8)
    finally:
        sys.path.remove(REPO)

    out = capsys.readouterr().out
    assert "dryrun_multichip: entered (pid=" in out

    path = os.path.join(REPO, "MULTICHIP_LOCAL.json")
    assert os.path.exists(path)
    with open(path) as f:
        rec = json.load(f)
    assert rec["ok"] is True
    assert rec["n_devices"] == 8
    names = [s["name"] for s in rec["subs"]]
    assert names == ["ctr", "gpt-hybrid", "moe", "multislice", "remote-ps"]
    assert all(s["ok"] for s in rec["subs"])
    head = subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO,
                          capture_output=True, text=True).stdout.strip()
    # Commit may trail HEAD when run from a dirty tree mid-development,
    # but must be a real hash so the artifact is attributable.
    assert rec["commit"] is None or len(rec["commit"]) == 40
    assert head  # repo is a git checkout in CI and dev alike
