"""Event-loop multiplexed RPC plane tests (PR 16, RPC.md): wire v2
frames (request ids, scatter/gather zero-copy array segments, shm
shortcut), the single-poller server, N-outstanding connection
multiplexing, server-side pull coalescing, and the drill half —
out-of-order soak on ONE socket, kill -9 mid-flight with
idempotent-retry + resolve failover, and v1 interop both ways."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.core import flags, monitor
from paddlebox_tpu.distributed import rpc, wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class EchoServer(rpc.FramedRPCServer):
    service_name = "mux-test"

    def handle_echo(self, req):
        sleep_ms = float(req.get("sleep_ms", 0.0))
        if sleep_ms > 0:
            time.sleep(sleep_ms / 1e3)
        return {"a": np.asarray(req["a"], np.float32) * 2.0,
                "i": int(req.get("i", -1))}

    def handle_boom(self, req):
        raise ValueError("in-band boom")


def _conn(ep, **kw):
    kw.setdefault("service_name", "mux-test")
    kw.setdefault("idempotent", ("echo",))
    return rpc.FramedRPCConn(ep, timeout=30.0, **kw)


@pytest.fixture
def flag_reset():
    keep = {k: flags.flag(k) for k in
            ("rpc_mux", "rpc_sg_min_bytes", "rpc_shm",
             "multihost_coalesce_window_ms")}
    yield
    flags.set_flags(keep)


# -- wire v1: memoryview-segment encode stays bit-identical ----------------

def test_v1_ndarray_frames_bit_identical_and_roundtrip():
    """The v1 LEGACY-tag ndarray encode now feeds memoryview segments
    to the frame join instead of materializing ``tobytes()`` copies —
    the bytes on the wire must be IDENTICAL (v1 peers parse them), and
    a non-contiguous input must normalize exactly like
    ``ascontiguousarray`` always did."""
    rng = np.random.default_rng(0)
    dtypes = (np.float32, np.float64, np.float16, np.int8, np.uint8,
              np.int16, np.int32, np.int64, np.uint16, np.uint32,
              np.uint64, np.bool_)
    obj = {f"a{i}": rng.integers(0, 2, size=(3, 5)).astype(dt)
           for i, dt in enumerate(dtypes)}
    obj["nested"] = {"x": [np.arange(7, dtype=np.float32), "s", 3, None],
                     "empty": np.empty((0, 4), np.float64)}
    frame = wire.pack_frame(obj)
    # Reference layout: header + payload; v1, flags 0.
    assert frame[:2] == b"PB"
    ln = wire.read_frame_header(frame[:wire.HEADER.size])
    payload = frame[wire.HEADER.size:]
    assert len(payload) == ln
    back = wire.loads(payload)
    for i, dt in enumerate(dtypes):
        got = back[f"a{i}"]
        assert got.dtype == dt and np.array_equal(got, obj[f"a{i}"])
    assert np.array_equal(back["nested"]["x"][0], obj["nested"]["x"][0])
    assert back["nested"]["empty"].shape == (0, 4)
    # Deterministic bytes (same object -> same frame), and a strided
    # view encodes exactly like its contiguous copy — the
    # ascontiguousarray normalization the tobytes path performed.
    assert wire.pack_frame(obj) == frame
    big = rng.standard_normal((8, 6)).astype(np.float32)
    assert (wire.pack_frame({"v": big[::2, ::3]})
            == wire.pack_frame({"v": np.ascontiguousarray(big[::2, ::3])}))


# -- wire v2: plain, sg, shm ------------------------------------------------

def test_v2_plain_frame_roundtrip():
    obj = {"method": "echo", "x": [1, 2.5, "s"], "b": b"\x00\x01"}
    frame = wire.pack_frame_v2(obj, 41)
    ver, fl, ln = wire.read_any_header(frame[:wire.HEADER.size])
    assert (ver, fl) == (wire.WIRE_VERSION_MUX, 0)
    rid, back = wire.loads_v2(frame[wire.HEADER.size:])
    assert rid == 41 and back == obj


def test_sg_frame_roundtrip_zero_copy_and_edges():
    rng = np.random.default_rng(1)
    obj = {"ok": True,
           "result": {"emb": rng.standard_normal((64, 16)).astype(
                          np.float32),
                      "keys": np.arange(64, dtype=np.uint64),
                      "empty": np.empty((0, 3), np.float32),
                      "note": "mixed tree"}}
    bufs = wire.sg_frame_buffers(obj, 7)
    frame = b"".join(bytes(b) for b in bufs)
    ver, fl, ln = wire.read_any_header(frame[:wire.HEADER.size])
    assert ver == wire.WIRE_VERSION_MUX and fl & wire.FLAG_SG
    payload = memoryview(frame)[wire.HEADER.size:]
    assert len(payload) == ln
    rid, back = wire.loads_sg(payload)
    assert rid == 7
    assert np.array_equal(back["result"]["emb"], obj["result"]["emb"])
    assert back["result"]["keys"].dtype == np.uint64
    assert back["result"]["empty"].shape == (0, 3)
    assert back["result"]["note"] == "mixed tree"
    # Zero-copy: decoded arrays are VIEWS over the receive buffer.
    assert back["result"]["emb"].base is not None
    # Segments are 64-byte aligned in the payload.
    arrs = wire.dumps_sg(obj)[1]
    offs, _total = wire.sg_plan(arrs)
    assert all(o % 64 == 0 for o in offs)
    # No-array and 0-d edges: a frame with no segments round-trips, and
    # a 0-d array promotes to shape (1,) exactly like the v1 path.
    bufs2 = wire.sg_frame_buffers({"just": "tree"}, 9)
    f2 = b"".join(bytes(b) for b in bufs2)
    rid2, b2 = wire.loads_sg(memoryview(f2)[wire.HEADER.size:])
    assert (rid2, b2) == (9, {"just": "tree"})
    v1_back = wire.loads(wire.pack_frame(
        {"z": np.asarray(3.0, np.float32)})[wire.HEADER.size:])
    bufs3 = wire.sg_frame_buffers({"z": np.asarray(3.0, np.float32)}, 1)
    f3 = b"".join(bytes(b) for b in bufs3)
    _, b3 = wire.loads_sg(memoryview(f3)[wire.HEADER.size:])
    assert b3["z"].shape == v1_back["z"].shape == (1,)


def test_v1_reader_rejects_v2_and_flags():
    v2 = wire.pack_frame_v2({"m": 1}, 1)
    with pytest.raises(wire.WireError):
        wire.read_frame_header(v2[:wire.HEADER.size])
    # read_any_header refuses a v1 frame carrying v2 flags (corruption).
    hdr = bytearray(wire.pack_frame({"m": 1})[:wire.HEADER.size])
    hdr[3] |= wire.FLAG_SG
    with pytest.raises(wire.WireError):
        wire.read_any_header(bytes(hdr))


# -- mux dispatch: soak, ordering, inline handlers -------------------------

def test_mux_soak_out_of_order_bit_identical(flag_reset):
    """8 threads x 16 outstanding on ONE connection: replies arrive out
    of order (the server sleeps longer on even request ids) yet every
    future resolves to ITS request's payload, bit-identical to a serial
    reference run."""
    flags.set_flags({"rpc_mux": True})
    srv = EchoServer("127.0.0.1:0")
    conn = _conn(srv.endpoint)
    fb0 = monitor.get("rpc/mux_fallbacks")
    try:
        serial = {}
        for i in range(8):
            a = np.full((32,), float(i), np.float32)
            serial[i] = conn.call("echo", a=a, i=i)["a"]
        failures = []

        def worker(t):
            try:
                for _round in range(4):
                    futs = []
                    for j in range(16):
                        i = (t * 16 + j) % 8
                        a = np.full((32,), float(i), np.float32)
                        futs.append((i, conn.call_async(
                            "echo", a=a, i=i,
                            sleep_ms=2.0 if i % 2 == 0 else 0.0)))
                    for i, f in futs:
                        out = f.result()
                        if out["i"] != i or not np.array_equal(
                                out["a"], serial[i]):
                            failures.append((t, i))
            except BaseException as e:  # noqa: BLE001 - surface in test
                failures.append((t, repr(e)))

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(8)]
        [t.start() for t in ts]
        [t.join(60) for t in ts]
        assert not failures, failures[:5]
        # One socket did all of it: no fallback, no reconnect churn.
        assert monitor.get("rpc/mux_fallbacks") == fb0
    finally:
        conn.close()
        srv.stop()
        srv.close_connections()


def test_mux_inband_errors_and_sg_arrays_server_side(flag_reset):
    """In-band handler errors cross the mux wire as error replies (not
    stream teardown), and large array payloads ride SG frames in both
    directions when enabled."""
    flags.set_flags({"rpc_mux": True, "rpc_sg_min_bytes": 1024})
    srv = EchoServer("127.0.0.1:0")
    conn = _conn(srv.endpoint)
    try:
        sg0 = monitor.get("rpc/sg_frames")
        big = np.arange(4096, dtype=np.float32)
        out = conn.call("echo", a=big)
        assert np.array_equal(out["a"], big * 2.0)
        assert monitor.get("rpc/sg_frames") >= sg0 + 2  # request + reply
        with pytest.raises(RuntimeError, match="in-band boom"):
            conn.call("boom")
        # The conn survives an in-band error: same socket keeps working.
        assert conn.call("echo", a=np.ones(4, np.float32))["i"] == -1
    finally:
        conn.close()
        srv.stop()
        srv.close_connections()


def test_v1_interop_both_directions(flag_reset):
    """Version negotiation: a v1-pinned client (``--norpc_mux``) speaks
    legacy frames to the new server; a mux client against a pre-mux
    server (wire_caps answered with an in-band error) falls back to v1
    and counts ``rpc/mux_fallbacks`` — mixed-version clusters
    interoperate instead of desyncing."""
    srv = EchoServer("127.0.0.1:0")
    try:
        flags.set_flags({"rpc_mux": False})
        legacy = _conn(srv.endpoint)
        out = legacy.call("echo", a=np.arange(4, dtype=np.float32))
        assert np.array_equal(out["a"],
                              np.arange(4, dtype=np.float32) * 2.0)
        legacy.close()
    finally:
        srv.stop()
        srv.close_connections()

    class OldServer(EchoServer):
        def _wire_caps(self, cs, req):
            return {"max_version": 1}  # a pre-mux peer's best answer

    old = OldServer("127.0.0.1:0")
    try:
        flags.set_flags({"rpc_mux": True})
        fb0 = monitor.get("rpc/mux_fallbacks")
        conn = _conn(old.endpoint)
        out = conn.call("echo", a=np.ones(8, np.float32))
        assert np.array_equal(out["a"], np.full(8, 2.0, np.float32))
        assert monitor.get("rpc/mux_fallbacks") == fb0 + 1
        # call_async still works on the fallback conn (helper thread).
        f = conn.call_async("echo", a=np.ones(2, np.float32), i=5)
        assert f.result()["i"] == 5
        conn.close()
    finally:
        old.stop()
        old.close_connections()


# -- forensics tables -------------------------------------------------------

def test_inflight_and_poller_tables(flag_reset):
    flags.set_flags({"rpc_mux": True})
    srv = EchoServer("127.0.0.1:0")
    conn = _conn(srv.endpoint)
    try:
        futs = [conn.call_async("echo", a=np.ones(4, np.float32),
                                sleep_ms=300.0) for _ in range(3)]
        time.sleep(0.1)
        rows = rpc.inflight_table()
        mine = [r for r in rows if r["endpoint"] == srv.endpoint]
        assert mine and mine[0]["outstanding"] >= 3
        assert mine[0]["method"] == "echo"
        pol = rpc.poller_table()
        me = [p for p in pol if p["endpoint"] == srv.endpoint]
        assert me and me[0]["service"] == "mux-test"
        assert "poller" in me[0]["thread"]
        assert me[0]["conns"] >= 1 and me[0]["running"]
        for f in futs:
            f.result()
        assert not [r for r in rpc.inflight_table()
                    if r["endpoint"] == srv.endpoint]
    finally:
        conn.close()
        srv.stop()
        srv.close_connections()


# -- server-side pull coalescing -------------------------------------------

def test_pull_coalescing_bit_identical_and_counted(flag_reset):
    from paddlebox_tpu.embedding.table import TableConfig
    from paddlebox_tpu.multihost.keyrange import ShardRangeTable
    from paddlebox_tpu.multihost.shard_service import (ShardClient,
                                                       ShardServer)
    cfg = TableConfig(name="emb", dim=8, learning_rate=0.1)
    srv = ShardServer("127.0.0.1:0", 0, ShardRangeTable.for_world(1),
                      cfg)
    rng = np.random.default_rng(3)
    universe = np.unique(rng.integers(1, 1 << 40, 512, dtype=np.uint64))
    try:
        # Reference: direct (coalescing disabled) pulls per key set.
        flags.set_flags({"multihost_coalesce_window_ms": -1.0})
        sets = [np.unique(rng.choice(universe, 64)) for _ in range(16)]
        c0 = ShardClient(srv.endpoint)
        ref = [c0.call("pull", keys=k) for k in sets]
        base_rounds = srv.metrics.get("multihost/coalesce_rounds")
        assert base_rounds == 0  # disabled path never coalesces
        # Coalesced: concurrent pulls inside a window fold into fewer
        # store lookups; every slice stays bit-identical.
        flags.set_flags({"multihost_coalesce_window_ms": 5.0})
        got = [None] * len(sets)
        errs = []

        def puller(i):
            try:
                c = ShardClient(srv.endpoint)
                got[i] = c.call("pull", keys=sets[i])
                c.close()
            except BaseException as e:  # noqa: BLE001
                errs.append(repr(e))

        ts = [threading.Thread(target=puller, args=(i,))
              for i in range(len(sets))]
        [t.start() for t in ts]
        [t.join(30) for t in ts]
        assert not errs, errs[:3]
        for i in range(len(sets)):
            for f in ref[i]:
                assert np.array_equal(got[i][f], ref[i][f]), f
        assert srv.metrics.get("multihost/coalesced_pulls") > 0
        assert (srv.metrics.get("multihost/coalesce_rounds")
                < len(sets))  # fewer lookups than requests
        c0.close()
    finally:
        srv.stop()
        srv.close_connections()


# -- kill -9 drill ----------------------------------------------------------

def _spawn_echo(root, name):
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "rpc_echo_worker.py"),
         str(root), name],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    ep_file = os.path.join(root, f"{name}.ep")
    for _ in range(200):
        if os.path.exists(ep_file):
            with open(ep_file) as f:
                meta = json.load(f)
            return proc, meta["endpoint"]
        time.sleep(0.05)
    proc.kill()
    raise RuntimeError(f"echo worker {name} never advertised")


def test_mux_kill9_idempotent_retry_and_resolve_failover(
        tmp_path, flag_reset):
    """kill -9 the server while mux calls are provably in flight: the
    idempotent ``echo`` futures re-issue through the conn's
    retry/reconnect machinery, the reconnect-time ``resolve`` hook
    re-points at the surviving replica, and every call completes with
    correct bytes — the PR-5/PR-11 drill contract, unchanged on the
    mux plane."""
    flags.set_flags({"rpc_mux": True})
    proc_a, ep_a = _spawn_echo(tmp_path, "a")
    proc_b, ep_b = _spawn_echo(tmp_path, "b")
    live = {"ep": ep_a}
    conn = rpc.FramedRPCConn(
        ep_a, timeout=30.0, service_name="rpc-drill",
        idempotent=("echo",), resolve=lambda cur: live["ep"])
    try:
        re0 = monitor.get("rpc/retries")
        a = np.arange(16, dtype=np.float32)
        assert conn.call("echo", a=a)["who"] == "a"
        futs = [conn.call_async("echo", a=a, sleep_ms=400.0)
                for _ in range(8)]
        time.sleep(0.1)          # calls are mid-handler on A
        live["ep"] = ep_b
        proc_a.send_signal(signal.SIGKILL)
        outs = [f.result() for f in futs]
        for out in outs:
            assert np.array_equal(out["a"], a * 2.0)
            assert out["who"] == "b"  # failover actually moved hosts
        assert monitor.get("rpc/retries") > re0
        # The conn is settled on B: a plain call works, no new retry.
        assert conn.call("echo", a=a)["who"] == "b"
    finally:
        conn.close()
        for p in (proc_a, proc_b):
            p.kill()
            p.wait(timeout=10)


# -- shm shortcut (flag-gated off by default) ------------------------------

def test_shm_frames_roundtrip_same_host(flag_reset):
    if not os.path.isdir("/dev/shm"):
        pytest.skip("no /dev/shm")
    flags.set_flags({"rpc_mux": True, "rpc_shm": True,
                     "rpc_shm_min_bytes": 1024,
                     "rpc_sg_min_bytes": 1024})
    srv = EchoServer("127.0.0.1:0")
    conn = _conn(srv.endpoint)
    try:
        s0 = monitor.get("rpc/shm_frames")
        big = np.arange(65536, dtype=np.float32)
        out = conn.call("echo", a=big)
        assert np.array_equal(out["a"], big * 2.0)
        assert monitor.get("rpc/shm_frames") > s0
        # One-shot segments: nothing pbx-rpc-* leaks in /dev/shm.
        time.sleep(0.1)
        assert not [e for e in os.listdir("/dev/shm")
                    if e.startswith(f"pbx-rpc-{os.getpid()}")]
    finally:
        conn.close()
        srv.stop()
        srv.close_connections()
