"""xDeepFM through CTRTrainer end-to-end + a numpy CIN oracle."""

import numpy as np

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import XDeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("a", "b")


def test_xdeepfm_learns_interaction(tmp_path):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    model = XDeepFM(slot_names=SLOTS, emb_dim=8, cin_layers=(8, 8),
                    hidden=(32,))
    tr = CTRTrainer(model, feed, TableConfig(dim=8, learning_rate=0.2),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10,
                                         dense_learning_rate=3e-3))
    tr.init(seed=0)
    rng = np.random.default_rng(9)
    p = str(tmp_path / "part")
    with open(p, "w") as f:
        for _ in range(512):
            a, b = rng.integers(1, 60), rng.integers(1, 60)
            # Pure interaction signal (same planting as the DCN test).
            label = int(((a % 2) == (b % 2)) == (rng.random() < 0.85))
            f.write(f"{label} a:{a} b:{b}\n")
    losses = []
    for _ in range(7):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        stats = tr.train_pass(ds)
        losses.append(stats["loss"])
    assert losses[-1] < losses[0]
    assert stats["auc"] > 0.62, stats["auc"]


def test_xdeepfm_cin_matches_numpy_oracle():
    """apply() against an independently written numpy CIN with TWO
    layers and H_k != m: the layer-2 outer product is between DIFFERENT
    tensors (x1 vs x0), so a map/field axis swap in the recursion or
    reshape cannot cancel by symmetry (a single-layer oracle — x0 outer
    x0 — would pass with the axes swapped)."""
    import jax
    import jax.numpy as jnp

    model = XDeepFM(slot_names=SLOTS, emb_dim=4, cin_layers=(3, 5),
                    hidden=())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    bs = 3
    emb = {s: jnp.asarray(rng.normal(size=(bs, 4)), jnp.float32)
           for s in SLOTS}
    w = {s: jnp.asarray(rng.normal(size=(bs,)), jnp.float32)
         for s in SLOTS}
    segs = {s: jnp.arange(bs, dtype=jnp.int32) for s in SLOTS}
    got = np.asarray(model.apply(params, emb, w, segs, batch_size=bs))

    x0 = np.stack([np.asarray(emb[s]) for s in SLOTS], axis=1)  # [B,2,4]
    m, d = 2, 4
    xk = x0
    pooled = []
    for layer in params["cin"]:
        W = np.asarray(layer["w"])                 # [H_{k-1}*m, H_k]
        bvec = np.asarray(layer["b"])              # [H_k]
        z = (xk[:, :, None, :] * x0[:, None, :, :]).reshape(
            bs, xk.shape[1] * m, d)
        xk = np.maximum(np.einsum("bnd,nh->bhd", z, W)
                        + bvec[None, :, None], 0.0)
        pooled.append(xk.sum(axis=-1))
    cin_out = np.concatenate(pooled, axis=-1)      # [B, 3+5]
    flat = x0.reshape(bs, m * d)
    h = np.concatenate([cin_out, flat], axis=-1)
    Wh = np.asarray(params["head"]["w"])
    bh = np.asarray(params["head"]["b"])
    wide = sum(np.asarray(w[s]) for s in SLOTS)
    ref = h @ Wh[:, 0] + bh[0] + wide + float(params["bias"])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_xdeepfm_rejects_mixed_widths():
    import pytest
    model = XDeepFM(slot_names=SLOTS, emb_dim={"a": 4, "b": 8})
    import jax
    with pytest.raises(ValueError):
        model.init(jax.random.PRNGKey(0))
