"""Sparse Adam / AdamShared optimizer parity tests (role of the reference
optimizer kernels, heter_ps/optimizer.cuh.h:148,330)."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddlebox_tpu.embedding import (SparseAdam, SparseAdamShared,
                                     TableConfig, make_sparse_optimizer,
                                     make_push_fn)
from paddlebox_tpu.embedding.table import (build_pass_table_host,
                                           extract_pass_values_host,
                                           map_keys_to_rows)
from paddlebox_tpu.parallel import HybridTopology, build_mesh

EPS = 1e-8


def _adam_ref_step(v, m1, m2, b1p, b2p, g, lr, b1, b2, lo=-10, hi=10):
    ratio = lr * np.sqrt(1.0 - b2p) / (1.0 - b1p)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    vn = np.clip(v - ratio * (m1n / (np.sqrt(m2n) + EPS)), lo, hi)
    return vn, m1n, m2n, b1p * b1, b2p * b2


def test_adam_vector_matches_reference_math():
    opt = SparseAdam(learning_rate=0.01, beta1=0.9, beta2=0.999)
    n, d = 5, 3
    rng = np.random.default_rng(0)
    v = rng.normal(size=(n, d)).astype(np.float32)
    g = rng.normal(size=(n, d)).astype(np.float32)
    state = opt.init_emb_state(n, d)
    # two consecutive steps to exercise beta-pow decay
    v1, s1 = opt.update_vector(jnp.asarray(v), jnp.asarray(state),
                               jnp.asarray(g))
    v2, s2 = opt.update_vector(v1, s1, jnp.asarray(g * 0.5))

    m1 = np.zeros((n, d)); m2 = np.zeros((n, d))
    b1p = np.full((n, 1), 0.9); b2p = np.full((n, 1), 0.999)
    rv, m1, m2, b1p, b2p = _adam_ref_step(v, m1, m2, b1p, b2p, g, 0.01,
                                          0.9, 0.999)
    np.testing.assert_allclose(np.asarray(v1), rv, rtol=1e-5, atol=1e-6)
    rv, m1, m2, b1p, b2p = _adam_ref_step(rv, m1, m2, b1p, b2p, g * 0.5,
                                          0.01, 0.9, 0.999)
    np.testing.assert_allclose(np.asarray(v2), rv, rtol=1e-5, atol=1e-6)
    # state layout [m1, m2, b1p, b2p]
    np.testing.assert_allclose(np.asarray(s2[:, :d]), m1, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(s2[:, 2 * d]), b1p[:, 0],
                               rtol=1e-6)


def test_adam_shared_moments_are_means():
    opt = SparseAdamShared(learning_rate=0.01)
    n, d = 4, 6
    rng = np.random.default_rng(1)
    v = rng.normal(size=(n, d)).astype(np.float32)
    g = rng.normal(size=(n, d)).astype(np.float32)
    state = opt.init_emb_state(n, d)
    v1, s1 = opt.update_vector(jnp.asarray(v), jnp.asarray(state),
                               jnp.asarray(g))
    # per-dim new moments from shared old (0), stored as means
    m1n = (1 - 0.9) * g
    m2n = (1 - 0.999) * g * g
    np.testing.assert_allclose(np.asarray(s1[:, 0]), m1n.mean(-1), rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(s1[:, 1]), m2n.mean(-1), rtol=1e-4,
                               atol=1e-8)
    ratio = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expect = np.clip(v - ratio * m1n / (np.sqrt(m2n) + EPS), -10, 10)
    np.testing.assert_allclose(np.asarray(v1), expect, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("optname", ["adam", "adam_shared"])
def test_push_with_adam_multi_shard_parity(devices8, optname):
    """Push through the 8-way all-to-all path with adam == single shard."""
    cfg = TableConfig(dim=4, optimizer=optname, learning_rate=0.01)
    opt = make_sparse_optimizer(cfg)
    n_keys, n_ids = 40, 64
    rng = np.random.default_rng(2)
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    vals = {
        "emb": rng.normal(size=(n_keys, 4)).astype(np.float32),
        "emb_state": opt.init_emb_state(n_keys, 4),
        "w": rng.normal(size=(n_keys,)).astype(np.float32),
        "w_state": opt.init_w_state(n_keys),
        "show": np.zeros((n_keys,), np.float32),
        "click": np.zeros((n_keys,), np.float32),
    }
    batch_keys = rng.choice(keys, n_ids).astype(np.uint64)
    g_emb = rng.normal(size=(n_ids, 4)).astype(np.float32)
    g_w = rng.normal(size=(n_ids,)).astype(np.float32)
    ones = np.ones((n_ids,), np.float32)

    results = {}
    for nshards in (1, 8):
        table = build_pass_table_host(vals, nshards, cfg)
        mesh = build_mesh(HybridTopology(dp=nshards),
                          devices8[:nshards] if nshards > 1 else devices8[:1])
        rows = map_keys_to_rows(keys, batch_keys, table.rows_per_shard,
                                nshards)
        push = make_push_fn(mesh, "dp", opt)
        new_table = push(table, jnp.asarray(rows), jnp.asarray(g_emb),
                         jnp.asarray(g_w), jnp.asarray(ones),
                         jnp.asarray(ones * 0))
        results[nshards] = extract_pass_values_host(new_table, n_keys)

    for f in results[1]:
        np.testing.assert_allclose(results[1][f], results[8][f],
                                   rtol=1e-4, atol=1e-5, err_msg=f)
    # updated rows actually moved
    touched = np.isin(keys, batch_keys)
    assert not np.allclose(results[1]["emb"][touched], vals["emb"][touched])


def test_store_roundtrip_adam(tmp_path):
    from paddlebox_tpu.embedding import FeatureStore
    cfg = TableConfig(dim=4, optimizer="adam")
    store = FeatureStore(cfg)
    keys = np.array([3, 9], np.uint64)
    v = store.pull_for_pass(keys)
    assert v["emb_state"].shape == (2, 2 * 4 + 2)
    # new-key beta pows initialized to the decay rates
    np.testing.assert_allclose(v["emb_state"][:, -2], 0.9)
    np.testing.assert_allclose(v["w_state"][:, -1], 0.999)
    store.push_from_pass(keys, v)
    store.save_base(str(tmp_path / "b"))
    r = FeatureStore(cfg)
    r.load(str(tmp_path / "b"), "base")
    np.testing.assert_allclose(
        r.pull_for_pass(keys)["emb_state"], v["emb_state"])


def test_make_sparse_optimizer_unknown():
    with pytest.raises(ValueError, match="unknown sparse optimizer"):
        make_sparse_optimizer(TableConfig(optimizer="adamax"))
