"""Multi-slice (DCN) topology: hierarchical collectives + slice-parallel
training parity.

The slice axis models the reference's inner/inter-node comm split
(heter_comm.h:156-172 gather_one_node_grad / gather_multi_node_grad;
SyncParam's ReduceScatter + inter-node sync + AllGather,
boxps_worker.cc:584-645). These tests pin the TPU-side contract on the
virtual CPU mesh: a 2-slice x k-dp run must be numerically equivalent to
the flat 2k-dp run — the hierarchy changes the transport, not the math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.parallel.collective import hierarchical_psum_tree


def _mesh(slice_=1, dp=1, **kw):
    topo = HybridTopology(slice=slice_, dp=dp, **kw)
    return build_mesh(topo, devices=jax.devices()[:topo.world_size])


def test_topology_has_slice_axis():
    mesh = _mesh(slice_=2, dp=4)
    assert mesh.shape["slice"] == 2 and mesh.shape["dp"] == 4
    # slice is outermost: the first mesh dim.
    assert mesh.axis_names[0] == "slice"


def test_hierarchical_psum_tree_matches_flat():
    mesh = _mesh(slice_=2, dp=4)
    rng = np.random.default_rng(0)
    # Ragged leaf sizes (incl. one not divisible by dp=4) exercise the
    # fused-flatten + pad path.
    tree = {"a": jnp.asarray(rng.normal(size=(3, 5)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32),
            "c": jnp.asarray(rng.normal(size=(2, 2, 2)), jnp.float32)}

    def hier(t):
        return hierarchical_psum_tree(t, inner_axis="dp",
                                      outer_axis="slice")

    def flat(t):
        return jax.tree.map(lambda x: lax.psum(x, ("slice", "dp")), t)

    out_h = jax.jit(jax.shard_map(hier, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))(tree)
    out_f = jax.jit(jax.shard_map(flat, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))(tree)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out_h[k]),
                                   np.asarray(out_f[k]), rtol=1e-6)


def _make_ctr_trainer(mesh, n_slots=3, batch=16, **config_kw):
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    slots = tuple(SlotConf(f"s{i}", avg_len=2.0) for i in range(n_slots))
    feed = DataFeedConfig(slots=slots, batch_size=batch)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(n_slots)),
                   emb_dim=8, hidden=(16, 8))
    trainer = CTRTrainer(
        model, feed, TableConfig(dim=8), mesh=mesh,
        config=TrainerConfig(auc_num_buckets=1 << 10, **config_kw),
        store_factory=lambda cfg: DeviceFeatureStore(cfg, mesh=mesh))
    trainer.init(seed=0)
    return trainer, feed


def _synth_batch(feed, ndev, seed=0):
    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.data.slots import SlotBatch

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(feed.batch_size):
        toks = " ".join(f"s{i}:{rng.integers(1, 40)}" for i in range(3)
                        for _ in range(rng.integers(1, 3)))
        lines.append(f"{rng.integers(0, 2)} {toks}")
    return SlotBatch.pack_sharded(parse_lines(lines, feed), feed, ndev)


def _run_steps(trainer, feed, n_steps=3):
    """Drive n_steps of the jitted train step on deterministic batches
    with the SAME sync-flag schedule train_pass uses (kstep mode fires
    the periodic param average and the pass-end sync — otherwise the
    slice-spanning pmean would be dead code in these tests); return
    (loss trace, final dense params)."""
    eng = trainer.engine
    mode = trainer.config.dense_sync_mode
    k = max(1, trainer.config.dense_sync_interval)
    losses = []
    for step_i in range(n_steps):
        batch = _synth_batch(feed, trainer.ndev, seed=100 + step_i)
        eng.feed_pass([
            np.unique(np.concatenate([batch.ids[n] for n in g.slots]))
            for g in eng.groups])
        tables = eng.begin_pass()
        if trainer._step_fn is None:
            trainer._step_fn = trainer._build_step()
        rows = trainer._map_batch_rows(batch)
        segs = {n: jnp.asarray(batch.segments[n]) for n in batch.ids}
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        sync = 1 if (mode == "kstep" and (step_i + 1) % k == 0) else 0
        tables, trainer.params, trainer.opt_state, trainer.auc_state, \
            loss, _of = trainer._step_fn(
                tables, trainer.params, trainer.opt_state,
                trainer.auc_state, rows, segs, jnp.asarray(batch.labels),
                jnp.asarray(batch.valid),
                jnp.asarray(_concat_dense_host(batch)),
                jnp.asarray(sync, jnp.int32))
        losses.append(float(loss))
        eng.update_tables(tables)
        eng.end_pass()
    if mode == "kstep" and n_steps % k != 0:
        # Pass-boundary sync, as train_pass does — also makes the
        # returned params well-defined (replica-identical).
        trainer.params = trainer._sync_params_fn()(trainer.params)
    return losses, jax.device_get(trainer.params)


@pytest.mark.slow
def test_ctr_multislice_parity_vs_flat():
    """2-slice x 2-dp == flat 4-dp: same data, same loss trajectory, same
    dense params — the slice axis only re-routes the collectives."""
    mesh_flat = _mesh(dp=4)
    mesh_sl = _mesh(slice_=2, dp=2)

    tr_flat, feed = _make_ctr_trainer(mesh_flat)
    tr_sl, _ = _make_ctr_trainer(mesh_sl)
    assert tr_flat.ndev == tr_sl.ndev == 4
    assert tr_sl.dcn_axis == "slice" and tr_flat.dcn_axis is None

    losses_f, params_f = _run_steps(tr_flat, feed)
    losses_s, params_s = _run_steps(tr_sl, feed)
    np.testing.assert_allclose(losses_f, losses_s, rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        params_f, params_s)
    # Sparse side: same feature count persisted after the pass.
    assert (tr_flat.engine.store.num_features
            == tr_sl.engine.store.num_features)


@pytest.mark.slow
def test_gpt_multislice_step():
    """Hybrid GPT step on a slice=2 x pp=2 x mp=2 mesh: compiles, runs,
    loss matches the flat dp=2 x pp=2 x mp=2 mesh on the same data."""
    import optax

    from paddlebox_tpu.models.gpt import (GPTConfig, init_gpt,
                                          make_gpt_train_step)

    cfg = GPTConfig(vocab_size=64, d_model=16, n_heads=2, n_layers=4,
                    d_ff=32, max_seq_len=16)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)),
                         jnp.int32)
    targets = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)),
                          jnp.int32)

    def run(mesh):
        params, specs = init_gpt(jax.random.PRNGKey(0), cfg, pp_stages=2)
        opt = optax.sgd(1e-2)
        step = make_gpt_train_step(cfg, mesh, specs, opt,
                                   num_microbatches=2, schedule="1f1b")
        params, _, loss = step(params, opt.init(params), tokens, targets)
        jax.block_until_ready(loss)
        return float(loss)

    loss_sl = run(_mesh(slice_=2, dp=1, pp=2, mp=2))
    loss_flat = run(_mesh(dp=2, pp=2, mp=2))
    assert np.isfinite(loss_sl)
    np.testing.assert_allclose(loss_sl, loss_flat, rtol=2e-5)


@pytest.mark.slow
def test_ctr_multislice_kstep_parity_vs_flat():
    """kstep (local-SGD) under a slice mesh: the periodic param average
    spans slice x dp — 2-slice x 2-dp must equal flat 4-dp exactly (sgd
    optimizer so kstep's local trajectories are deterministic). With
    interval=2 over 3 steps the in-step sync fires at step 2 AND the
    pass-end sync covers the trailing local step."""
    kw = dict(dense_optimizer="sgd", dense_sync_mode="kstep",
              dense_sync_interval=2)
    tr_flat, feed = _make_ctr_trainer(_mesh(dp=4), **kw)
    tr_sl, _ = _make_ctr_trainer(_mesh(slice_=2, dp=2), **kw)
    losses_f, params_f = _run_steps(tr_flat, feed)
    losses_s, params_s = _run_steps(tr_sl, feed)
    np.testing.assert_allclose(losses_f, losses_s, rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
        params_f, params_s)


def test_hierarchical_psum_tree_mixed_dtypes_and_empty():
    """The fused buffer promotes to the widest leaf dtype and casts back
    per-leaf; an empty tree is a no-op, not an error. Per-rank
    contributions DIFFER (scaled by a global rank index) so the sum is
    non-trivial — summing 8 identical copies would be an exact power-of-
    two shift even in raw bf16 and could not detect a dropped
    promotion."""
    mesh = _mesh(slice_=2, dp=4)
    rng = np.random.default_rng(1)
    tree = {"h": jnp.asarray(rng.normal(size=(6,)), jnp.bfloat16),
            "f": jnp.asarray(rng.normal(size=(5,)), jnp.float32)}

    def hier(t):
        r = (lax.axis_index("slice") * lax.axis_size("dp")
             + lax.axis_index("dp") + 1).astype(jnp.float32)
        t = jax.tree.map(lambda x: (x.astype(jnp.float32)
                                    * r).astype(x.dtype), t)
        return hierarchical_psum_tree(t, inner_axis="dp",
                                      outer_axis="slice")

    out = jax.jit(jax.shard_map(hier, mesh=mesh, in_specs=P(),
                                out_specs=P(), check_vma=False))(tree)
    assert out["h"].dtype == jnp.bfloat16
    assert out["f"].dtype == jnp.float32
    scale = float(sum(range(1, 9)))   # ranks 1..8
    np.testing.assert_allclose(np.asarray(out["f"]),
                               np.asarray(tree["f"]) * scale, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out["h"], np.float32),
        np.asarray(tree["h"], np.float32) * scale, rtol=3e-2)

    out_e = jax.jit(jax.shard_map(hier, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))({})
    assert out_e == {}
