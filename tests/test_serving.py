"""Serving predictor over xbox exports (SURVEY L12 inference role): a
trained CTR model exported per-pass must serve predictions that match the
trainer's own eval forward."""

import numpy as np
import pytest

from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.serving import CTRPredictor, load_xbox_model
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

from tests.test_device_store import _FakeDataset


@pytest.mark.parametrize("store_kind", ["host", "device"])
def test_xbox_export_serves_trainer_predictions(tmp_path, store_kind):
    mesh = build_mesh(HybridTopology(dp=8))
    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(3))
    feed = DataFeedConfig(slots=slots, batch_size=32)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                   emb_dim=4, hidden=(16,))
    factory = (None if store_kind == "host"
               else (lambda cfg: DeviceFeatureStore(cfg, mesh=mesh)))
    tr = CTRTrainer(model, feed, TableConfig(dim=4, learning_rate=0.1),
                    mesh=mesh, config=TrainerConfig(
                        auc_num_buckets=1 << 10,
                        compute_dtype="float32"),
                    store_factory=factory)
    tr.init(seed=0)
    ds = _FakeDataset(feed, seed=1, nbatches=3, ndev=8)
    tr.train_pass(ds)

    # Per-pass online export: xbox (emb+w only) — the serving artifact.
    n = tr.engine.store.save_xbox(str(tmp_path))
    assert n == tr.engine.store.num_features
    keys, emb, w = load_xbox_model(str(tmp_path))
    assert keys.shape[0] == n and emb.shape == (n, 4)

    pred = CTRPredictor(model, feed, keys, emb, w, tr.params,
                        compute_dtype="float32")
    batch = next(_FakeDataset(feed, seed=1, nbatches=1,
                              ndev=1).batches_sharded(1))
    probs = pred.predict(batch)
    assert probs.shape == (32,)
    assert np.isfinite(probs).all() and (0 <= probs).all() \
        and (probs <= 1).all()

    # Parity with the trainer's own forward on the same batch: serve-side
    # sigmoid(logits) == sigmoid of eval logits. Build the reference from
    # the store's values directly.
    import jax.numpy as jnp
    vals = tr.engine.store.pull_for_pass(np.sort(keys))
    key_sorted = np.sort(keys)
    lut = {int(k): i for i, k in enumerate(key_sorted)}
    emb_ref = {}
    w_ref = {}
    for s in ("s0", "s1", "s2"):
        idx = np.array([lut.get(int(k), -1) for k in batch.ids[s]])
        e = np.zeros((idx.size, 4), np.float32)
        ww = np.zeros((idx.size,), np.float32)
        m = idx >= 0
        e[m] = vals["emb"][idx[m]]
        ww[m] = vals["w"][idx[m]]
        emb_ref[s] = jnp.asarray(e)
        w_ref[s] = jnp.asarray(ww)
    segs = {s: jnp.asarray(batch.segments[s]) for s in emb_ref}
    logits = model.apply(tr.params, emb_ref, w_ref, segs, batch_size=32,
                         dense_feats=None)
    ref_probs = np.asarray(jnp.asarray(1 / (1 + np.exp(-np.asarray(logits)))))
    np.testing.assert_allclose(probs, ref_probs, rtol=1e-5, atol=1e-6)


def test_unknown_keys_serve_zero_embeddings():
    feed = DataFeedConfig(
        slots=(SlotConf("s0", avg_len=1.0),), batch_size=4)
    model = DeepFM(slot_names=("s0",), emb_dim=2, hidden=(4,))
    params = model.init(__import__("jax").random.PRNGKey(0))
    keys = np.array([10, 20], np.uint64)
    emb = np.ones((2, 2), np.float32)
    w = np.ones((2,), np.float32)
    pred = CTRPredictor(model, feed, keys, emb, w, params,
                        compute_dtype="float32")
    from paddlebox_tpu.data.slots import Instance, SlotBatch
    ins = [Instance(labels=np.zeros(1, np.float32),
                    sparse={"s0": np.array([k], np.uint64)}, dense={})
           for k in (10, 999, 20, 777)]
    batch = SlotBatch.pack(ins, feed)
    probs = pred.predict(batch)
    # Unknown keys (999, 777) see zero emb+w -> identical outputs.
    assert probs[1] == probs[3]
    assert probs[0] != probs[1]
