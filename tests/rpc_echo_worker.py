"""Subprocess echo server for the RPC mux kill -9 drill
(tests/test_rpc_mux.py): one FramedRPCServer with an ``echo`` handler
(optional server-side sleep so the harness can land a SIGKILL while
calls are provably in flight) on an ephemeral loopback port. The
endpoint is advertised through an atomic file rename; the process then
idles until the harness kills it — the process IS the failure domain,
exactly like the shard-host drill worker."""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(root: str, name: str) -> None:
    import numpy as np

    from paddlebox_tpu.distributed import rpc

    class EchoServer(rpc.FramedRPCServer):
        service_name = "rpc-drill"

        def handle_echo(self, req):
            sleep_ms = float(req.get("sleep_ms", 0.0))
            if sleep_ms > 0:
                time.sleep(sleep_ms / 1e3)
            return {"a": np.asarray(req["a"], np.float32) * 2.0,
                    "who": name}

    server = EchoServer("127.0.0.1:0")
    tmp = os.path.join(root, f".{name}.ep.tmp")
    with open(tmp, "w") as f:
        json.dump({"endpoint": server.endpoint, "pid": os.getpid()}, f)
    os.replace(tmp, os.path.join(root, f"{name}.ep"))
    while True:
        time.sleep(0.2)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
