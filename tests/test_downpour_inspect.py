"""Downpour async-PS trainer + program introspection tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.distributed.ps import start_local_cluster
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.train.downpour import DownpourTrainer, PullDenseWorker
from paddlebox_tpu.utils import inspect as pbx_inspect


@pytest.fixture
def ps():
    cfg = TableConfig(name="emb", dim=4, optimizer="adagrad",
                      learning_rate=0.2)
    servers, client = start_local_cluster(2, {"emb": cfg})
    yield client
    client.stop_servers()
    client.close()
    for s in servers:
        s.stop()


def _make_batches(n_batches, cap=32, seed=0):
    """Synthetic CTR-ish data: label depends on whether any 'positive'
    feasign (odd id) is present."""
    rng = np.random.default_rng(seed)
    for _ in range(n_batches):
        ids = rng.integers(1, 200, cap).astype(np.uint64)
        label = (np.mean(ids % 2) > 0.5).astype(np.float32)
        yield {"ids": ids, "label": jnp.asarray([label])}


def test_downpour_learns_sparse_and_dense(ps):
    def loss_fn(dense, emb, w, batch):
        # score = mean(emb @ v) + sum(w)/cap + b
        s = jnp.mean(emb @ dense["v"]) + jnp.mean(w) + dense["b"][0]
        p = jax.nn.sigmoid(s)
        y = batch["label"][0]
        return -(y * jnp.log(p + 1e-7) + (1 - y) * jnp.log(1 - p + 1e-7))

    t = DownpourTrainer(ps, "emb", loss_fn,
                        {"v": np.zeros((4,), np.float32),
                         "b": np.zeros((1,), np.float32)},
                        pull_interval=0.01)
    try:
        out = t.fit(_make_batches(150), log_every=0)
        assert out["steps"] == 150
        assert out["loss_last"] < out["loss_first"]
        # sparse table actually trained: show counters accumulated
        stats = ps.stats()
        assert sum(s["emb"] for s in stats) > 0
        # dense was updated server-side (pushes applied by DenseTable)
        v = ps.pull_dense("b")
        assert np.abs(v).sum() > 0
    finally:
        t.stop()


def test_downpour_padding_rows_not_trained(ps):
    def loss_fn(dense, emb, w, batch):
        return jnp.sum(emb ** 2) + jnp.sum(w ** 2) + 0.0 * dense["z"][0]

    t = DownpourTrainer(ps, "emb", loss_fn,
                        {"z": np.zeros((1,), np.float32)})
    try:
        before = sum(s["emb"] for s in ps.stats())
        ids = np.asarray([5, 0, 7, 0], np.uint64)  # 0 = padding
        t.train_step({"ids": ids})
        # exactly the two real feasigns were created — a feasign-0 row
        # would make this 3 (padding keys must never touch the table)
        after = sum(s["emb"] for s in ps.stats())
        assert after - before == 2
    finally:
        t.stop()


def test_pull_dense_worker_versions(ps):
    ps.set_dense("w0", np.zeros(3, np.float32))
    pw = PullDenseWorker(ps, ["w0"], interval=0.01)
    pw.start()
    try:
        v0 = pw.version
        ps.set_dense("w0", np.ones(3, np.float32))
        import time
        deadline = time.time() + 5
        while time.time() < deadline:
            if pw.version > v0 and np.allclose(pw.latest()["w0"], 1.0):
                break
            time.sleep(0.01)
        np.testing.assert_allclose(pw.latest()["w0"], 1.0)
    finally:
        pw.stop()


# ---------------------------------------------------------------------------
# inspect
# ---------------------------------------------------------------------------

def test_jaxpr_summary_counts():
    def f(x):
        return jnp.sin(x) + jnp.cos(x) @ jnp.ones((4, 4))

    c = pbx_inspect.jaxpr_summary(f, jnp.ones((4, 4)))
    assert c.get("sin") == 1 and c.get("cos") == 1
    assert c.get("dot_general", 0) >= 1


def test_jaxpr_summary_recurses_into_cond_branches():
    def f(x):
        return jax.lax.cond(x.sum() > 0, jnp.sin, jnp.cos, x)

    c = pbx_inspect.jaxpr_summary(f, jnp.ones(3))
    assert c.get("sin", 0) >= 1 and c.get("cos", 0) >= 1


def test_jaxpr_summary_recurses_into_scan():
    def f(x):
        return jax.lax.scan(lambda c, t: (c + jnp.tanh(t), None), x,
                            jnp.arange(3.0))[0]

    c = pbx_inspect.jaxpr_summary(f, jnp.zeros(()))
    assert c.get("tanh", 0) >= 1  # found inside the scan body


def test_hlo_text_and_compiled_stats():
    def f(x):
        return (x @ x).sum()

    txt = pbx_inspect.hlo_text(f, jnp.ones((8, 8)))
    assert "dot" in txt.lower()
    stats = pbx_inspect.compiled_stats(f, jnp.ones((8, 8)))
    assert isinstance(stats, dict)  # backend-dependent contents


def test_print_tensor_summary():
    line = pbx_inspect.print_tensor(np.asarray([1.0, np.nan, 3.0]), "t")
    assert "nonfinite=1" in line and "shape=(3,)" in line
    assert "t:" in line
    assert "<empty>" in pbx_inspect.print_tensor(np.empty((0,)), "e")
    assert "dtype" in pbx_inspect.print_tensor(np.asarray(["a"]), "s")
