"""Dataset extras tests: disk spill roundtrip + streaming batches,
pv/ins grouped batching, and the extended (base+expand) embedding
lookup."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.data.columnar import ColumnarChunk
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import extended
from paddlebox_tpu.embedding.table import (TableConfig,
                                           build_pass_table_host)
from paddlebox_tpu.parallel import HybridTopology, build_mesh


def _config():
    return DataFeedConfig(
        slots=(SlotConf("sid"),
               SlotConf("feat", avg_len=4.0),
               SlotConf("d0", is_dense=True, dim=2)),
        batch_size=8)


def _write_files(tmp_path, n_files=3, rows_per_file=10):
    """svm format: label slot:feasign ... slot:v1,v2 (data/parser.py)."""
    paths = []
    rng = np.random.default_rng(0)
    rid = 0
    for f in range(n_files):
        lines = []
        for _ in range(rows_per_file):
            label = rng.integers(0, 2)
            sid = 1000 + rid // 3  # ~3 rows share a search id
            feats = " ".join(f"feat:{int(x)}"
                             for x in rng.integers(1, 500, 4))
            lines.append(f"{label} sid:{sid} {feats} d0:0.5,1.5")
            rid += 1
        p = tmp_path / f"part-{f:03d}.txt"
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return paths


def test_disk_spill_roundtrip(tmp_path):
    cfg = _config()
    files = _write_files(tmp_path)
    ds = Dataset(cfg, num_reader_threads=2)
    ds.set_filelist(files)
    spill = str(tmp_path / "spill")
    n_chunks = ds.dump_into_disk(spill)
    assert n_chunks >= 1
    assert ds.num_instances == 0  # nothing held in RAM

    ds.load_from_disk(spill)
    assert ds.num_instances == 30

    # parity with direct in-memory load
    ds2 = Dataset(cfg, num_reader_threads=2)
    ds2.set_filelist(files)
    ds2.load_into_memory()
    k1, k2 = ds.pass_keys(), ds2.pass_keys()
    np.testing.assert_array_equal(k1, k2)


def test_batches_from_disk_streams(tmp_path):
    cfg = _config()
    files = _write_files(tmp_path)
    ds = Dataset(cfg, num_reader_threads=2)
    ds.set_filelist(files)
    spill = str(tmp_path / "spill")
    ds.dump_into_disk(spill)
    batches = list(ds.batches_from_disk(spill, batch_size=8))
    assert sum(int(b.valid.sum()) for b in batches) == 30
    for b in batches:
        assert b.labels.shape == (8, 1)  # static shape incl. final pad


def test_chunk_save_load_roundtrip(tmp_path):
    cfg = _config()
    files = _write_files(tmp_path, n_files=1)
    ds = Dataset(cfg, num_reader_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    chunk = ds._merge()
    p = str(tmp_path / "c.npz")
    chunk.save(p)
    back = ColumnarChunk.load(p)
    np.testing.assert_array_equal(back.labels, chunk.labels)
    for s in chunk.sparse_ids:
        np.testing.assert_array_equal(back.sparse_ids[s],
                                      chunk.sparse_ids[s])
        np.testing.assert_array_equal(back.sparse_offsets[s],
                                      chunk.sparse_offsets[s])
    np.testing.assert_array_equal(back.dense["d0"], chunk.dense["d0"])


def test_batches_grouped_keeps_pvs_whole(tmp_path):
    cfg = _config()
    files = _write_files(tmp_path)
    ds = Dataset(cfg, num_reader_threads=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.local_shuffle(seed=1)  # grouping must undo interleaving
    seen_groups = {}
    total = 0
    for batch, gids in ds.batches_grouped("sid", batch_size=8):
        valid = batch.valid
        gv = gids[valid]
        total += int(valid.sum())
        # groups are contiguous within the batch
        changes = (gv[1:] != gv[:-1]).sum()
        assert changes == len(np.unique(gv)) - 1
        # no group spans two batches
        for g in np.unique(gv):
            assert g not in seen_groups, f"group {g} split across batches"
            seen_groups[g] = True
    assert total == 30


def test_batches_grouped_respects_shuffle_order(tmp_path):
    """Shuffling between epochs must change pv batch composition (groups
    ordered by first occurrence, not sorted key)."""
    cfg = _config()
    files = _write_files(tmp_path, n_files=2, rows_per_file=12)
    ds = Dataset(cfg, num_reader_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()

    def first_batch_groups(seed):
        ds.local_shuffle(seed=seed)
        batch, gids = next(ds.batches_grouped("sid", batch_size=8))
        return tuple(gids[batch.valid].tolist())

    orders = {first_batch_groups(s) for s in range(5)}
    assert len(orders) > 1, "epoch shuffles produced identical pv batches"


def test_dump_into_disk_clears_stale_chunks(tmp_path):
    cfg = _config()
    files = _write_files(tmp_path, n_files=3)
    spill = str(tmp_path / "spill")
    ds = Dataset(cfg, num_reader_threads=1)
    ds.set_filelist(files)
    ds.dump_into_disk(spill)
    # re-dump with a smaller filelist: old chunks must not survive
    ds2 = Dataset(cfg, num_reader_threads=1)
    ds2.set_filelist(files[:1])
    ds2.dump_into_disk(spill)
    ds2.load_from_disk(spill)
    assert ds2.num_instances == 10


def test_load_from_disk_missing_dir_raises(tmp_path):
    ds = Dataset(_config())
    with pytest.raises(FileNotFoundError):
        ds.load_from_disk(str(tmp_path / "nope"))


def test_batches_grouped_truncates_oversized_group(tmp_path):
    cfg = _config()
    # one giant pv: all 12 rows share sid
    lines = [f"1 sid:7 feat:{i+1} d0:0,0" for i in range(12)]
    p = tmp_path / "big.txt"
    p.write_text("\n".join(lines) + "\n")
    ds = Dataset(cfg, num_reader_threads=1)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    out = list(ds.batches_grouped("sid", batch_size=8))
    assert len(out) == 1  # truncated to one batch, remainder dropped
    assert int(out[0][0].valid.sum()) == 8


# ---------------------------------------------------------------------------
# extended lookup
# ---------------------------------------------------------------------------

def test_extended_pull_push(devices8):
    d_base, d_exp = 4, 2
    base_cfg = TableConfig(dim=d_base, learning_rate=0.1, initial_g2sum=1.0)
    cfg = extended.extended_table_config(base_cfg, d_exp)
    assert cfg.dim == 6
    n = 16
    rng = np.random.default_rng(0)
    vals = {
        "emb": rng.normal(size=(n, 6)).astype(np.float32),
        "emb_state": np.zeros((n, 1), np.float32),
        "w": rng.normal(size=(n,)).astype(np.float32),
        "w_state": np.zeros((n, 1), np.float32),
        "show": np.zeros((n,), np.float32),
        "click": np.zeros((n,), np.float32),
    }
    mesh = build_mesh(HybridTopology(dp=8))
    table = build_pass_table_host(vals, 8, cfg)

    rows = jnp.asarray(rng.integers(0, n, 32), jnp.int32)
    # map global ranks to device-row space: round-robin deal (rank g ->
    # shard g % S at slot g // S, table.py module docstring)
    block = table.rows_per_shard + 1
    nsh = table.num_shards
    dev_rows = (rows % nsh) * block + rows // nsh

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")),
                       out_specs=P("dp"), check_vma=False)
    def pull(table, dev_rows):
        return extended.pull_local_extended(table, dev_rows, d_base=d_base,
                                            axis="dp")

    out = pull(table, dev_rows)
    assert out["emb"].shape == (32, d_base)
    assert out["emb_expand"].shape == (32, d_exp)
    want = vals["emb"][np.asarray(rows)]
    np.testing.assert_allclose(np.asarray(out["emb"]), want[:, :d_base],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["emb_expand"]),
                               want[:, d_base:], rtol=1e-6)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P("dp"),) * 7, out_specs=P("dp"),
                       check_vma=False)
    def push(table, dev_rows, gb, ge, gw, s, c):
        return extended.push_local_extended(table, dev_rows, gb, ge, gw,
                                            s, c, axis="dp")

    gb = jnp.ones((32, d_base))
    ge = jnp.full((32, d_exp), 2.0)
    new_table = jax.jit(push)(table, dev_rows, gb, ge,
                              jnp.zeros(32), jnp.ones(32), jnp.zeros(32))
    out2 = pull(new_table, dev_rows)
    # both halves moved (base by grad 1, expand by grad 2 -> more)
    db = np.abs(np.asarray(out2["emb"]) - np.asarray(out["emb"])).mean()
    de = np.abs(np.asarray(out2["emb_expand"])
                - np.asarray(out["emb_expand"])).mean()
    assert de > db > 0


def test_extended_validation():
    base_cfg = TableConfig(dim=4)
    with pytest.raises(ValueError):
        # table dim == d_base -> no expand part
        vals = {
            "emb": np.zeros((4, 4), np.float32),
            "emb_state": np.zeros((4, 1), np.float32),
            "w": np.zeros((4,), np.float32),
            "w_state": np.zeros((4, 1), np.float32),
            "show": np.zeros((4,), np.float32),
            "click": np.zeros((4,), np.float32),
        }
        t = build_pass_table_host(vals, 1, base_cfg)
        extended.pull_local_extended(t, jnp.zeros((2,), jnp.int32),
                                     d_base=4, axis="dp")