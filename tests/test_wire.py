"""Typed PS wire protocol (distributed/wire.py — VERDICT r02 task 9):
round-trips, version/magic rejection, malformed-frame robustness, and the
live PS service over the typed frames."""

import os
import struct

import numpy as np
import pytest

from paddlebox_tpu.distributed import wire


def test_roundtrip_value_tree():
    obj = {
        "method": "push_pass",
        "table": "emb",
        "count": 7,
        "lr": 0.05,
        "flag": True,
        "nothing": None,
        "blob": b"\x00\xff raw",
        "keys": np.arange(10, dtype=np.uint64),
        "values": {
            "emb": np.random.default_rng(0).normal(
                size=(10, 4)).astype(np.float32),
            "show": np.zeros((10,), np.float32),
        },
        "list": [1, "two", 3.0, np.arange(3, dtype=np.int32)],
    }
    back = wire.loads(wire.dumps(obj))
    assert back["method"] == "push_pass" and back["count"] == 7
    assert back["flag"] is True and back["nothing"] is None
    assert back["blob"] == obj["blob"]
    np.testing.assert_array_equal(back["keys"], obj["keys"])
    np.testing.assert_array_equal(back["values"]["emb"],
                                  obj["values"]["emb"])
    assert back["list"][1] == "two"
    np.testing.assert_array_equal(back["list"][3], obj["list"][3])


def test_frame_header_roundtrip_and_rejections():
    frame = wire.pack_frame({"a": 1})
    n = wire.read_frame_header(frame[:wire.HEADER.size])
    assert wire.loads(frame[wire.HEADER.size:wire.HEADER.size + n]) == \
        {"a": 1}
    # Bad magic.
    bad = b"XX" + frame[2:]
    with pytest.raises(wire.WireError, match="magic"):
        wire.read_frame_header(bad[:wire.HEADER.size])
    # Version mismatch must be rejected, not guessed at.
    bumped = frame[:2] + bytes([wire.WIRE_VERSION + 1]) + frame[3:]
    with pytest.raises(wire.WireError, match="version"):
        wire.read_frame_header(bumped[:wire.HEADER.size])
    # Oversized length field.
    huge = wire.HEADER.pack(b"PB", wire.WIRE_VERSION, 0, wire.MAX_PAYLOAD + 1)
    with pytest.raises(wire.WireError, match="cap"):
        wire.read_frame_header(huge)


def test_unsupported_types_rejected():
    with pytest.raises(wire.WireError):
        wire.dumps({"x": object()})
    with pytest.raises(wire.WireError):
        wire.dumps({1: "non-str key"})
    with pytest.raises(wire.WireError):
        wire.dumps(np.zeros(3, dtype=np.complex64))


def test_malformed_payloads_raise_not_crash():
    good = wire.dumps({"k": np.arange(5, dtype=np.int64)})
    # Truncations at every boundary.
    for cut in range(len(good)):
        with pytest.raises(wire.WireError):
            wire.loads(good[:cut])
    # Unknown tag.
    with pytest.raises(wire.WireError):
        wire.loads(b"\x7f")
    # Array with absurd shape (would allocate TBs without the check).
    bad = (b"\x06" + struct.pack("<BB", 0, 2)
           + struct.pack("<QQ", 1 << 40, 1 << 40))
    with pytest.raises(wire.WireError):
        wire.loads(bad)
    # Trailing garbage after a valid value.
    with pytest.raises(wire.WireError, match="trailing"):
        wire.loads(good + b"\x00")


def test_fuzz_random_bytes_never_crash():
    rng = np.random.default_rng(0)
    for _ in range(300):
        n = int(rng.integers(0, 200))
        blob = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        try:
            wire.loads(blob)
        except wire.WireError:
            pass  # the only acceptable failure mode


def test_ps_service_over_typed_frames():
    """The PS round-trips real traffic over the typed wire, and a raw
    malformed frame only drops that connection, not the server."""
    import socket
    from paddlebox_tpu.distributed.ps import PSClient, PSServer
    from paddlebox_tpu.embedding.table import TableConfig

    cfg = TableConfig(dim=4, learning_rate=0.1)
    srv = PSServer("127.0.0.1:0", 0, 1, {"emb": cfg})
    try:
        cli = PSClient([srv.endpoint])
        keys = np.array([2, 4, 8], np.uint64)
        out = cli.pull_sparse("emb", keys)
        assert out["emb"].shape == (3, 4)
        cli.push_sparse("emb", keys,
                        emb_grad=np.ones((3, 4), np.float32),
                        w_grad=np.ones((3,), np.float32))
        out2 = cli.pull_sparse("emb", keys)
        assert not np.allclose(out2["emb"], out["emb"])

        # Malformed frame from a hostile/broken peer: connection dropped,
        # server keeps serving existing clients.
        host, port = srv.endpoint.rsplit(":", 1)
        with socket.create_connection((host, int(port))) as s:
            s.sendall(b"GARBAGE NOT A FRAME" * 3)
        out3 = cli.pull_sparse("emb", keys)
        np.testing.assert_allclose(out3["emb"], out2["emb"])
    finally:
        srv.stop()
