"""Overlapped pass boundary (round 8): the device-tier split-key early
build, the fused end/begin boundary program, and the off-critical-path
host keymap must be BIT-identical to the serial path on CPU — same
store state, same tables, same params/opt-state/AUC — across shared-key
fractions, eval (readonly) builds, aborts, cancellation, and a threaded
pipelined stress loop.

Role of the reference overlap being mirrored: PreLoadIntoMemory /
WaitFeedPassDone (box_wrapper.h:1140,1161) and the double-buffered
BuildPull threads (ps_gpu_wrapper.cc:907), extended to the HBM-resident
store tier where the build is an on-device gather.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import monitor
from paddlebox_tpu.embedding import PassEngine, TableConfig
from paddlebox_tpu.embedding.device_store import DeviceFeatureStore
from paddlebox_tpu.parallel import HybridTopology, build_mesh

SLOTS = ("u", "i")


@pytest.fixture(autouse=True)
def _restore_boundary_flags():
    old = {k: flagmod.flag(k) for k in
           ("pass_split_build", "pass_boundary_fuse",
            "keymap_lookup_threads", "trainer_map_ahead")}
    try:
        yield
    finally:
        flagmod.set_flags(old)


def _engine(dim=4):
    mesh = build_mesh(HybridTopology(dp=8))
    cfg = TableConfig(dim=dim, learning_rate=0.1)
    store = DeviceFeatureStore(cfg, mesh=mesh)
    return PassEngine(cfg, store, mesh=mesh, table_axis="dp"), store


def _keys_with_share(frac, n=64):
    """Pass-B key set sharing ``frac`` of pass A's keys (A = 1..64)."""
    n_sh = int(n * frac)
    return np.unique(np.concatenate([
        np.arange(n + 1 - n_sh, n + 1, dtype=np.uint64),
        np.arange(100, 100 + n - n_sh, dtype=np.uint64)]))


def _one_boundary(split, fuse, frac, *, readonly=False, settle=0.25):
    """Pass A trains (emb += 1), pass B feeds async mid-pass, boundary,
    begin B. Returns (B's rows in key order, store values for B's keys,
    boundary device-program count, store growth during B's build)."""
    flagmod.set_flags({"pass_split_build": split,
                       "pass_boundary_fuse": fuse})
    eng, store = _engine()
    keys_a = np.arange(1, 65, dtype=np.uint64)
    eng.feed_pass(keys_a)
    table = eng.begin_pass()
    table = table.with_emb(table.emb + 1.0)
    eng.update_table(table)
    keys_b = _keys_with_share(frac)
    nf0 = store.num_features
    c0 = monitor.get("device_store/boundary_progs")
    eng.feed_pass(keys_b, async_build=True, readonly=readonly)
    time.sleep(settle)  # let the early half run DURING the active pass
    eng.end_pass()
    tb = eng.begin_pass()
    c1 = monitor.get("device_store/boundary_progs")
    rows = eng.lookup_rows(keys_b)
    out = np.asarray(tb.vals)[rows]
    eng.abort_pass() if readonly else eng.end_pass()
    vals = store.pull_for_pass(keys_b)
    return out, vals, c1 - c0, store.num_features - nf0


@pytest.mark.parametrize("frac", [0.0, 0.5, 1.0])
@pytest.mark.parametrize("fuse", ["off", "auto"])
def test_split_build_bit_identical_to_serial(frac, fuse):
    """Overlapped build == serial build, bit for bit: shared keys
    observe pass A's write-back, not-shared keys gathered early carry
    exactly the values the serial (post-write-back) gather would read,
    and the post-B store state matches."""
    base_tbl, base_vals, _, _ = _one_boundary(False, "off", frac)
    got_tbl, got_vals, _, _ = _one_boundary(True, fuse, frac)
    np.testing.assert_array_equal(base_tbl, got_tbl)
    for f in base_vals:
        np.testing.assert_array_equal(base_vals[f], got_vals[f])


def test_fused_boundary_single_dispatch_pin():
    """The boundary's device-program count: fused = ONE jitted dispatch
    (scatter + remainder gather in one program); unfused split = two;
    and a fully-disjoint pass needs only the end_pass scatter."""
    _, _, n_fused, _ = _one_boundary(True, "auto", 0.5)
    assert n_fused == 1, n_fused
    _, _, n_split, _ = _one_boundary(True, "off", 0.5)
    assert n_split == 2, n_split
    _, _, n_disjoint, _ = _one_boundary(True, "auto", 0.0)
    assert n_disjoint == 1, n_disjoint  # scatter only; build fully early
    _, _, n_serial, _ = _one_boundary(False, "off", 0.5)
    assert n_serial == 2, n_serial      # scatter + serial full gather


def test_readonly_eval_build_never_inserts():
    """An overlapped eval (readonly) build must not grow the store —
    missing keys ride the init-record overlay in the EARLY half (a
    missing key is never shared) and the store stays untouched."""
    for split, fuse in ((False, "off"), (True, "off"), (True, "auto")):
        tbl, vals, _, grew = _one_boundary(split, fuse, 0.5,
                                           readonly=True)
        assert grew == 0
    # And parity: readonly overlapped == readonly serial, bit for bit.
    base_tbl, base_vals, _, _ = _one_boundary(False, "off", 0.5,
                                              readonly=True)
    got_tbl, got_vals, _, _ = _one_boundary(True, "auto", 0.5,
                                            readonly=True)
    np.testing.assert_array_equal(base_tbl, got_tbl)
    for f in base_vals:
        np.testing.assert_array_equal(base_vals[f], got_vals[f])


def test_abort_mid_overlap_reads_pre_pass_state():
    """abort_pass (eval/test mode) while a split build is parked: no
    write-back happens, so the merged remainder must read the PRE-pass
    values — identical to a serial build after the abort."""
    flagmod.set_flags({"pass_split_build": True,
                       "pass_boundary_fuse": "auto"})
    eng, store = _engine()
    keys_a = np.arange(1, 65, dtype=np.uint64)
    eng.feed_pass(keys_a)
    table = eng.begin_pass()
    baseline = store.pull_for_pass(keys_a)  # pre-mutation store state
    table = table.with_emb(table.emb + 7.0)  # would dirty if written back
    eng.update_table(table)
    keys_b = _keys_with_share(0.5)
    eng.feed_pass(keys_b, async_build=True)
    time.sleep(0.25)
    eng.abort_pass()                         # NOT end_pass
    tb = eng.begin_pass()
    rows = eng.lookup_rows(keys_a[32:])      # the shared half
    got = np.asarray(tb.vals)[rows][:, :4]
    np.testing.assert_array_equal(got, baseline["emb"][32:])
    eng.abort_pass()


def test_cancel_pending_while_parked_does_not_deadlock():
    """cancel_pending against a builder parked at the boundary wait
    (its pass failed mid-training and will never run end_pass) must
    return promptly and leave the engine reusable — pre-r08 this join
    hung forever."""
    flagmod.set_flags({"pass_split_build": True,
                       "pass_boundary_fuse": "auto"})
    eng, store = _engine()
    eng.feed_pass(np.arange(1, 65, dtype=np.uint64))
    eng.begin_pass()
    # All-shared next pass => the builder parks awaiting the boundary.
    eng.feed_pass(np.arange(1, 65, dtype=np.uint64), async_build=True)
    time.sleep(0.2)
    t0 = time.perf_counter()
    eng.cancel_pending()
    assert time.perf_counter() - t0 < 5.0
    # Engine remains fully usable: finish the pass and run another.
    eng.end_pass()
    eng.feed_pass(np.arange(200, 264, dtype=np.uint64))
    eng.begin_pass()
    eng.end_pass()
    assert store.num_features == 64 + 64


def test_threaded_stress_50_passes_matches_serial():
    """Pipelined day-loop shape, 50 passes: pass k+1 feeds from a loader
    thread while pass k 'trains' (table mutation), with jittered timing
    so the boundary lands at different points of the build. Final store
    must be bit-identical to the fully-serial run."""
    def run(split, fuse):
        flagmod.set_flags({"pass_split_build": split,
                           "pass_boundary_fuse": fuse})
        eng, store = _engine()
        rng = np.random.default_rng(42)
        keysets = [np.unique(rng.choice(
            np.arange(1, 257, dtype=np.uint64), 64))
            for _ in range(50)]
        eng.feed_pass(keysets[0])
        table = eng.begin_pass()
        for i in range(50):
            feeder = None
            if i + 1 < len(keysets):
                feeder = threading.Thread(
                    target=eng.feed_pass, args=(keysets[i + 1],),
                    kwargs={"async_build": True}, daemon=True)
                feeder.start()
            table = table.with_emb(table.emb + 1.0)
            eng.update_table(table)
            if i % 7 == 0:
                time.sleep(0.01)  # jitter where the boundary lands
            if feeder is not None:
                feeder.join()
            eng.end_pass()
            if i + 1 < len(keysets):
                table = eng.begin_pass()
        keys = np.sort(store.dirty_keys())
        return keys, store.pull_for_pass(keys)

    keys_s, vals_s = run(False, "off")
    keys_o, vals_o = run(True, "auto")
    np.testing.assert_array_equal(keys_s, keys_o)
    for f in vals_s:
        np.testing.assert_array_equal(vals_s[f], vals_o[f])


def test_trainer_pipelined_day_bit_identical_device_store(tmp_path):
    """End-to-end acceptance pin: a pipelined day over the device store
    (split build + fused boundary + map-ahead keymap) produces
    BIT-identical params, opt state, per-pass loss/AUC, and store
    values vs the serial path — and the pass reports carry the boundary
    breakdown."""
    import jax

    from paddlebox_tpu.data import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig
    from paddlebox_tpu.train.day_runner import DayRunner

    data = str(tmp_path / "data")
    rng = np.random.default_rng(7)
    for h in (0, 1, 2):
        d = os.path.join(data, "20260801", f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-0"), "w") as f:
            for _ in range(96):
                feats = {s: rng.integers(1, 150, rng.integers(1, 3))
                         for s in SLOTS}
                label = int(rng.random() < 0.3)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")

    def run(out, pipeline, split, fuse, map_ahead):
        flagmod.set_flags({"pass_split_build": split,
                           "pass_boundary_fuse": fuse,
                           "trainer_map_ahead": map_ahead})
        mesh = build_mesh(HybridTopology(dp=8))
        feed = DataFeedConfig(
            slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
            batch_size=32)
        trainer = CTRTrainer(
            DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)), feed,
            TableConfig(name="emb", dim=8, learning_rate=0.1),
            mesh=mesh,
            config=TrainerConfig(dense_learning_rate=3e-3,
                                 auc_num_buckets=1 << 10),
            store_factory=lambda cfg: DeviceFeatureStore(cfg, mesh=mesh))
        trainer.init(seed=0)
        runner = DayRunner(trainer, feed, out, data_root=data,
                           split_interval=60, split_per_pass=1,
                           hours=[0, 1, 2], num_reader_threads=2,
                           pipeline_passes=pipeline)
        stats = runner.train_day("20260801")
        return trainer, stats

    tr_s, st_s = run(str(tmp_path / "o_s"), False, False, "off", False)
    tr_o, st_o = run(str(tmp_path / "o_o"), True, True, "auto", True)

    assert len(st_s) == len(st_o) == 3
    for a, b in zip(st_s, st_o):
        assert a["steps"] == b["steps"]
        assert a["loss"] == b["loss"], (a["loss"], b["loss"])
        assert a["auc"] == b["auc"]
        for k in ("end_ms", "build_ms", "feed_wait_ms", "overlap_frac"):
            assert k in b["boundary"]
    of = st_o[1]["boundary"]["overlap_frac"]
    assert of is None or 0.0 <= of <= 1.0

    for a, b in zip(jax.tree.leaves(tr_s.params),
                    jax.tree.leaves(tr_o.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(tr_s.opt_state),
                    jax.tree.leaves(tr_o.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    store_s, store_o = tr_s.engine.store, tr_o.engine.store
    assert store_s.num_features == store_o.num_features
    keys = np.sort(store_s.dirty_keys())
    va, vb = store_s.pull_for_pass(keys), store_o.pull_for_pass(keys)
    for f in va:
        np.testing.assert_array_equal(va[f], vb[f])


def test_keymap_sharded_fallback_bit_identical():
    """The numpy-fallback lookup sharded across the worker pool must be
    bit-identical to the single-threaded lookup — including the
    position-dependent round-robin trash rows for missing/zero keys
    (the offset-aware map_keys_to_rows contract)."""
    from paddlebox_tpu.native.keymap_py import KeyMap

    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 40, 5000).astype(np.uint64))
    km = KeyMap(keys, rows_per_shard=1024, num_shards=8)
    km.close()
    km._handle = None  # force the numpy fallback path
    m = (1 << 16) + 777  # above the auto-shard threshold, odd tail
    batch = rng.choice(keys, m).astype(np.uint64)
    batch[rng.choice(m, m // 10, replace=False)] = 0          # pads
    batch[rng.choice(m, m // 10, replace=False)] = (1 << 41)  # missing
    flagmod.set_flags({"keymap_lookup_threads": 1})
    single = km.lookup(batch).copy()
    flagmod.set_flags({"keymap_lookup_threads": 5})
    out = np.empty((m,), np.int32)
    sharded = km.lookup(batch, out=out)
    assert sharded is out
    np.testing.assert_array_equal(single, sharded)
    # auto mode engages sharding at this size and stays identical too
    flagmod.set_flags({"keymap_lookup_threads": 0})
    np.testing.assert_array_equal(single, km.lookup(batch))
