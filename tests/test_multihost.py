"""Multi-host embedding exchange tier (MULTIHOST.md).

Pins, tier-1 (CPU, loopback sockets — the wire is real, the hosts are
in-process):

- hash-range placement: partition coverage, plan_moves minimality
  (segments cover EXACTLY the changed-owner keys, 2→3→2 returns home);
- int8 per-block codec: np/jnp twins bit-identical, round-trip error
  bound, exact zeros;
- the host-sharded parameter service: 2-host MultiHostStore is
  BIT-identical to a flat FeatureStore on the f32 wire (pulls, pushes,
  unseen-key init, num_features), int8 wire within tolerance with the
  byte accounting shrinking;
- a full 2-host training day (DayRunner + CTRTrainer backed by the
  shard tier) bit-identical to the single-host run — losses AND final
  store contents;
- elastic reshard: live 2→3→2 mid-day through the pass-boundary hook,
  final state bit-identical to an unresized run at the same data
  order; per-row move counts equal to the minimal-transfer bound; a
  failed reshard rolls back via recovery_chain and retries cleanly;
  kill -9 mid-move recovers with no lost/double-applied rows
  (subprocess drill, tests/multihost_reshard_worker.py);
- the elastic rank table carries per-host shard endpoints (meta) end
  to end through two live ElasticManagers.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddlebox_tpu.core import faults
from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.embedding.store import _FIELDS, FeatureStore
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost import (MultiHostStore, ShardRangeTable,
                                     execute_reshard, mix_keys, plan_moves,
                                     rows_moved_minimal, start_local_shards,
                                     stop_shards)
from paddlebox_tpu.multihost.keyrange import range_bounds
from paddlebox_tpu.multihost.quant import (dequantize_blocked,
                                           dequantize_blocked_np,
                                           quantize_blocked,
                                           quantize_blocked_np)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = TableConfig(name="emb", dim=8, learning_rate=0.1)


def _rand_keys(n, seed=0, hi=1 << 50):
    rng = np.random.default_rng(seed)
    keys = np.unique(rng.integers(1, hi, size=n + 64, dtype=np.uint64))
    assert keys.size >= n  # collisions are ~impossible at this range
    return keys[:n]


# ---------------------------------------------------------------------------
# keyrange
# ---------------------------------------------------------------------------

def test_range_partition_covers_and_balances():
    for world in (1, 2, 3, 7):
        b = range_bounds(world)
        assert b[0] == 0 and b[-1] == 1 << 64
        assert all(b[i] < b[i + 1] for i in range(world))
        t = ShardRangeTable.for_world(world)
        keys = _rand_keys(20000, seed=1)
        owner = t.owner_of(keys)
        assert owner.min() >= 0 and owner.max() < world
        if world > 1:
            counts = np.bincount(owner, minlength=world)
            # The mix spreads uniformly: no shard takes > 2x its share.
            assert counts.max() < 2 * keys.size / world


def test_owner_matches_mask_in_range():
    t = ShardRangeTable.for_world(3)
    keys = _rand_keys(5000, seed=2)
    owner = t.owner_of(keys)
    for h in range(3):
        lo, hi = t.range_of(h)
        np.testing.assert_array_equal(t.mask_in_range(keys, lo, hi),
                                      owner == h)


def test_plan_moves_is_minimal_and_exact():
    keys = _rand_keys(30000, seed=3)
    for w_old, w_new in ((2, 3), (3, 2), (2, 5), (4, 3), (1, 4)):
        old = ShardRangeTable.for_world(w_old)
        new = ShardRangeTable.for_world(w_new)
        plan = plan_moves(old, new)
        o, n = old.owner_of(keys), new.owner_of(keys)
        covered = np.zeros(keys.size, bool)
        for seg in plan:
            m = old.mask_in_range(keys, seg.lo, seg.hi)
            assert not (covered & m).any(), "overlapping segments"
            covered |= m
            # Every key in the segment really moves src -> dst.
            assert (o[m] == seg.src).all() and (n[m] == seg.dst).all()
        # Exactly the changed-owner keys are covered: minimal transfer.
        np.testing.assert_array_equal(covered, o != n)
        assert int(covered.sum()) == rows_moved_minimal(old, new, keys)


def test_same_world_plan_is_empty_and_dict_roundtrip():
    t = ShardRangeTable.for_world(4)
    assert plan_moves(t, ShardRangeTable.for_world(4)) == []
    assert ShardRangeTable.from_dict(t.to_dict()) == t
    assert mix_keys(np.array([5], np.uint64)).dtype == np.uint64


# ---------------------------------------------------------------------------
# int8 per-block codec
# ---------------------------------------------------------------------------

def test_quant_np_jnp_twins_bit_identical():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(33, 21)).astype(np.float32) * 3.0
    for block in (4, 8, 21, 128):
        qn, sn = quantize_blocked_np(x, block)
        qj, sj = quantize_blocked(x, block)
        np.testing.assert_array_equal(qn, np.asarray(qj),
                                      err_msg=f"block {block}")
        np.testing.assert_array_equal(sn, np.asarray(sj))
        dn = dequantize_blocked_np(qn, sn, x.shape[1], block)
        dj = np.asarray(dequantize_blocked(qj, sj, x.shape[1], block))
        np.testing.assert_array_equal(dn, dj)


def test_quant_roundtrip_error_bound_and_zeros():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(64, 24)).astype(np.float32) * 10.0
    x[7] = 0.0  # all-zero row must round-trip EXACTLY (scale 1)
    for block in (6, 24):
        q, s = quantize_blocked_np(x, block)
        assert q.shape == x.shape  # unpadded wire
        d = dequantize_blocked_np(q, s, x.shape[1], block)
        nb = -(-x.shape[1] // block)
        amax = np.abs(
            np.pad(x, ((0, 0), (0, nb * block - x.shape[1])))
            .reshape(64, nb, block)).max(-1)
        bound = np.repeat(amax / 254.0 + 1e-6, block, axis=1)[:, :24]
        assert (np.abs(d - x) <= bound).all()
        np.testing.assert_array_equal(d[7], 0.0)


# ---------------------------------------------------------------------------
# host-sharded parameter service
# ---------------------------------------------------------------------------

@pytest.fixture
def cluster2():
    servers, eps = start_local_shards(2, CFG)
    yield servers, eps
    stop_shards(servers)


def test_two_host_store_bit_identical_to_flat(cluster2):
    servers, eps = cluster2
    store = MultiHostStore(CFG, eps)
    flat = FeatureStore(CFG, seed=0)
    keys = _rand_keys(3000, seed=6)
    a, b = store.pull_for_pass(keys), flat.pull_for_pass(keys)
    for f in _FIELDS:
        np.testing.assert_array_equal(a[f], b[f], err_msg=f)
    a["emb"] += 0.25
    a["show"] += 1.0
    store.push_from_pass(keys, a)
    flat.push_from_pass(keys, a)
    assert store.num_features == flat.num_features == keys.size
    # Second pull serves the written rows identically (and the plan
    # cache reused the owner argsort between push and this pull).
    sub = keys[::3]
    a2, b2 = store.pull_for_pass(sub), flat.pull_for_pass(sub)
    for f in _FIELDS:
        np.testing.assert_array_equal(a2[f], b2[f], err_msg=f)


def test_int8_dcn_wire_tolerance_and_bytes(cluster2):
    from paddlebox_tpu.core import monitor
    servers, eps = cluster2
    store = MultiHostStore(CFG, eps)
    keys = _rand_keys(2000, seed=7)
    rows = store.pull_for_pass(keys)
    rng = np.random.default_rng(8)
    rows["emb"] = rng.normal(size=rows["emb"].shape).astype(np.float32)
    store.push_from_pass(keys, rows)

    def pull_bytes():
        before = monitor.GLOBAL.get("multihost/pull_bytes")
        out = store.pull_for_pass(keys)
        return out, monitor.GLOBAL.get("multihost/pull_bytes") - before

    prev = flagmod.flag("multihost_wire_dtype")
    try:
        flagmod.set_flags({"multihost_wire_dtype": "f32"})
        exact, b_f32 = pull_bytes()
        np.testing.assert_array_equal(exact["emb"], rows["emb"])
        flagmod.set_flags({"multihost_wire_dtype": "int8"})
        quant, b_int8 = pull_bytes()
        flagmod.set_flags({"multihost_wire_dtype": "f16"})
        half, b_f16 = pull_bytes()
    finally:
        flagmod.set_flags({"multihost_wire_dtype": prev})
    # Tolerance: per-block absmax/254; these are ~N(0,1) values.
    np.testing.assert_allclose(quant["emb"], rows["emb"],
                               rtol=4e-2, atol=4e-2)
    np.testing.assert_allclose(half["emb"], rows["emb"],
                               rtol=1e-3, atol=1e-3)
    assert not np.array_equal(quant["emb"], rows["emb"])
    # Non-emb fields stay exact on every wire.
    for f in ("w", "emb_state", "w_state", "show", "click"):
        np.testing.assert_array_equal(quant[f], rows[f], err_msg=f)
    # Byte accounting: int8 < f16 < f32 on the emb payload share.
    assert b_int8 < b_f16 < b_f32


def test_stale_range_table_fails_loudly(cluster2):
    servers, eps = cluster2
    # A client that thinks the world is 3 routes keys the 2-server
    # cluster does not own — the ownership check must name the drift,
    # not serve garbage.
    store = MultiHostStore(CFG, [eps[0], eps[1], eps[0]],
                           ranges=ShardRangeTable.for_world(3))
    keys = _rand_keys(500, seed=9)
    with pytest.raises(RuntimeError, match="not owned"):
        store.pull_for_pass(keys)


def test_checkpoint_world_agnostic_reload(cluster2, tmp_path):
    """A checkpoint written at world 2 reloads bit-identical into
    world 3 and world 1 (hostshard files are range-filtered on load) —
    the property every reshard rollback and elastic recovery rides."""
    servers, eps = cluster2
    store = MultiHostStore(CFG, eps)
    keys = _rand_keys(2500, seed=10)
    rows = store.pull_for_pass(keys)
    rows["click"] += 2.0
    store.push_from_pass(keys, rows)
    path = str(tmp_path / "ck")
    store.save_base(path)
    for world in (3, 1):
        s2, e2 = start_local_shards(world, CFG)
        try:
            other = MultiHostStore(CFG, e2)
            other.load(path, "base")
            assert other.num_features == keys.size
            got = other.pull_for_pass(keys)
            for f in _FIELDS:
                np.testing.assert_array_equal(got[f], rows[f],
                                              err_msg=f)
        finally:
            stop_shards(s2)


# ---------------------------------------------------------------------------
# live reshard
# ---------------------------------------------------------------------------

def _start_joiner(world, index):
    """One server of a world-`world` partition (a joining host)."""
    servers, eps = start_local_shards(world, CFG)
    for j, s in enumerate(servers):
        if j != index:
            s.stop()
    return servers[index], eps[index]


def test_reshard_2_3_2_minimal_moves_and_parity(cluster2):
    servers, eps = cluster2
    store = MultiHostStore(CFG, eps)
    keys = _rand_keys(4000, seed=11)
    rows = store.pull_for_pass(keys)
    rows["emb"] += 0.5
    store.push_from_pass(keys, rows)

    t2, t3 = ShardRangeTable.for_world(2), ShardRangeTable.for_world(3)
    joiner, jep = _start_joiner(3, 2)
    try:
        rec = execute_reshard(eps, eps + [jep])
        # Per-row move counts match the minimal-transfer plan exactly.
        assert rec["moved_rows"] == rows_moved_minimal(t2, t3, keys)
        assert rec["moved_rows"] == sum(rec["segment_rows"])
        assert rec["new_world"] == 3
        store.set_topology(eps + [jep], t3)
        got = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], rows[f], err_msg=f)
        # Every server now holds ONLY its world-3 range.
        for i, s in enumerate(servers + [joiner]):
            skeys, _ = s.store.key_stats()
            if skeys.size:
                assert (t3.owner_of(skeys) == i).all()
        # ...and back: 3 -> 2 drains the joiner completely.
        rec2 = execute_reshard(eps + [jep], eps)
        assert rec2["moved_rows"] == rows_moved_minimal(t3, t2, keys)
        store.set_topology(eps, t2)
        got2 = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got2[f], rows[f], err_msg=f)
        jk, _ = joiner.store.key_stats()
        assert jk.size == 0
    finally:
        joiner.stop()


def test_reshard_failure_rolls_back_and_retries(cluster2, tmp_path):
    """A transient fault mid-move: the controller rolls the shard tier
    back through recovery_chain() (published state), reports the resize
    not-applied, and the retry at the next boundary lands it."""
    from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
    from paddlebox_tpu.launch.elastic import RankTable
    from paddlebox_tpu.multihost.reshard import ElasticReshardController

    servers, eps = cluster2
    store = MultiHostStore(CFG, eps)
    keys = _rand_keys(2000, seed=12)
    rows = store.pull_for_pass(keys)
    rows["w"] += 3.0
    store.push_from_pass(keys, rows)
    ckpt = CheckpointProtocol(str(tmp_path / "out"))
    store.save_delta(ckpt.model_dir("20260801", 1))
    ckpt.publish("20260801", 1)

    joiner, jep = _start_joiner(3, 2)
    tables = {"t": RankTable(generation=0, hosts=["a", "b"])}
    ctl = ElasticReshardController(store, ckpt,
                                   table_fn=lambda: tables["t"])
    try:
        assert ctl.maybe_apply("20260801", 1) is None  # anchors gen 0
        meta = {"a": {"shard_endpoint": eps[0]},
                "b": {"shard_endpoint": eps[1]},
                "c": {"shard_endpoint": jep}}
        tables["t"] = RankTable(generation=1, hosts=["a", "b", "c"],
                                meta=meta)
        faults.configure("multihost/reshard_move:hit=2:raise=IOError")
        try:
            assert ctl.maybe_apply("20260801", 2) is None  # failed
        finally:
            faults.clear()
        # Rolled back: still world 2, contents intact.
        assert store.world == 2
        got = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], rows[f], err_msg=f)
        # Next boundary retries the SAME pending generation and lands.
        rec = ctl.maybe_apply("20260801", 3)
        assert rec is not None and rec["new_world"] == 3
        assert store.world == 3
        got = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], rows[f], err_msg=f)
    finally:
        joiner.stop()


def test_kill9_mid_reshard_recovers_via_recovery_chain(tmp_path):
    """Subprocess drill: SIGKILL inside the reshard COPY phase, then a
    fresh cluster recovers through recovery_chain() — the content
    digest (layout-independent) must equal the seeded state: no lost
    rows, no double-applied rows."""
    root = str(tmp_path / "ck")
    os.makedirs(root, exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(REPO, "tests", "multihost_reshard_worker.py")

    def run(mode, world=None, fault="", check=True):
        e = dict(env)
        if fault:
            e["FLAGS_fault_spec"] = fault
        cmd = [sys.executable, worker, root, mode]
        if world is not None:
            cmd.append(str(world))
        return subprocess.run(cmd, env=e, cwd=REPO, timeout=180,
                              check=check, capture_output=True)

    run("seed")
    with open(os.path.join(root, "digest_seed.json")) as f:
        seed = json.load(f)
    assert seed["rows"] > 0

    # Kill -9 on the SECOND move segment: segment 1's rows are already
    # applied to their dest but not yet dropped from their source — the
    # worst crash window for double-apply.
    r = run("reshard", 3, fault="multihost/reshard_move:hit=2:kill",
            check=False)
    assert r.returncode in (-signal.SIGKILL, 137), (
        r.returncode, r.stdout[-500:], r.stderr[-500:])
    assert not os.path.exists(os.path.join(root, "digest_reshard.json"))

    # Recover into the NEW layout (the elastic restart path): reset +
    # recovery_chain reload, range-filtered per server.
    run("recover", 3)
    with open(os.path.join(root, "digest_recover.json")) as f:
        rec = json.load(f)
    assert rec == seed

    # And a clean reshard replay from the same chain also matches.
    run("reshard", 3)
    with open(os.path.join(root, "digest_reshard.json")) as f:
        done = json.load(f)
    assert done == seed


# ---------------------------------------------------------------------------
# elastic rank-table meta plumbing
# ---------------------------------------------------------------------------

def test_elastic_meta_carries_shard_endpoints(tmp_path):
    from paddlebox_tpu.launch.elastic import ElasticManager
    from paddlebox_tpu.multihost.reshard import ElasticReshardController

    root = str(tmp_path / "el")
    mgrs = [ElasticManager(root, f"host{r}", heartbeat_interval=0.05,
                           timeout=1.0, settle=0.1,
                           meta={"shard_endpoint": f"127.0.0.1:90{r}0"})
            for r in range(2)]
    try:
        for m in mgrs:
            m.start()
        t = mgrs[0].wait_for_quorum(timeout=20)
        deadline = time.time() + 20
        while time.time() < deadline:
            t = mgrs[1].current_table() or t
            eps = ElasticReshardController.endpoints_of(t)
            if t.world_size == 2 and eps is not None:
                break
            time.sleep(0.05)
        assert t.world_size == 2
        assert eps == ["127.0.0.1:9000", "127.0.0.1:9010"]
    finally:
        for m in mgrs:
            m.stop()
