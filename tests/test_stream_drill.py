"""Streaming kill -9 crash drill (ONLINE.md crash-window table): a real
training process dies at each ``stream/*`` faultpoint, restarts, and
must converge to BYTE-identical state with a never-killed reference —
resume-from-cursor loses no event and trains none twice."""

import json
import os
import subprocess
import sys

import pytest

import tests.stream_drill_worker as worker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SITES = [("stream/source_poll", 1),
         ("stream/cursor_commit", 2),
         ("stream/delta_publish", 1)]


def _run_worker(log, out, result, *, fault_spec="", timeout=240.0,
                log_path="", mode="segments"):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_fault_spec"] = fault_spec
    logf = open(log_path, "ab") if log_path else subprocess.DEVNULL
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tests", "stream_drill_worker.py"),
             log, out, result, mode],
            env=env, cwd=REPO, timeout=timeout,
            stdout=logf, stderr=subprocess.STDOUT)
    finally:
        if log_path:
            logf.close()
    return proc.returncode


@pytest.fixture(scope="module")
def drill_env(tmp_path_factory):
    """Fixed event log + the uninterrupted reference run."""
    workdir = tmp_path_factory.mktemp("stream_drill")
    log = str(workdir / "events")
    worker.write_events(log)
    result = str(workdir / "ref.json")
    rc = _run_worker(log, str(workdir / "ref_out"), result,
                     log_path=str(workdir / "ref.log"))
    assert rc == 0, f"reference run failed rc={rc} (see {workdir}/ref.log)"
    with open(result) as f:
        return workdir, log, json.load(f)


@pytest.mark.parametrize(
    "site,hit", SITES,
    ids=[f"{s.replace('/', '_')}_h{h}" for s, h in SITES])
def test_kill9_stream_resumes_exactly_once(drill_env, site, hit):
    workdir, log, ref = drill_env
    tag = site.replace("/", "_") + f"_h{hit}"
    out = str(workdir / f"out_{tag}")
    result = str(workdir / f"result_{tag}.json")
    logp = str(workdir / f"{tag}.log")

    rc = _run_worker(log, out, result,
                     fault_spec=f"{site}:hit={hit}:kill", log_path=logp)
    assert rc == -9, f"faultpoint {site} hit={hit} never killed (rc={rc})"
    assert not os.path.exists(result)  # died before finishing

    rc2 = _run_worker(log, out, result, log_path=logp)
    assert rc2 == 0, f"resume run failed rc={rc2} (see {logp})"
    with open(result) as f:
        drilled = json.load(f)

    # Byte-identical final model: a lost event would change params, a
    # double-trained one would change optimizer state/show counts.
    for k in ("num_features", "store_digest", "dense_digest", "records"):
        assert drilled[k] == ref[k], (site, hit, k)
    # Exactly-once event accounting from the durable cursor: every log
    # file in exactly one manifest, total events == the written log.
    files = [f for m in drilled["manifests"] for f in m["files"]]
    assert len(files) == len(set(files)) == worker.FILES
    assert sum(m["events"] for m in drilled["manifests"]) == \
        worker.FILES * worker.BS
    assert drilled["manifests"] == ref["manifests"]


@pytest.mark.parametrize("site,hit",
                         [("stream/cursor_commit", 2),
                          ("stream/delta_publish", 1)],
                         ids=["cursor_commit_h2", "delta_publish_h1"])
def test_kill9_tail_mode_mid_file_cut(tmp_path, site, hit):
    """Byte-offset cursor drill (FLAGS_stream_tail_bytes): ONE growing
    file consumed in mid-file byte ranges; kill -9 at a cut, resume —
    no event lost or duplicated at the cut, final state byte-identical
    to a never-killed run over the same append schedule."""
    from paddlebox_tpu.data.dataset import split_byte_range

    log = str(tmp_path / "events")
    ref_result = str(tmp_path / "ref.json")
    rc = _run_worker(log, str(tmp_path / "ref_out"), ref_result,
                     mode="tail", log_path=str(tmp_path / "ref.log"))
    assert rc == 0
    with open(ref_result) as f:
        ref = json.load(f)

    log2 = str(tmp_path / "events2")
    out = str(tmp_path / "out")
    result = str(tmp_path / "result.json")
    logp = str(tmp_path / "drill.log")
    rc = _run_worker(log2, out, result, mode="tail",
                     fault_spec=f"{site}:hit={hit}:kill", log_path=logp)
    assert rc == -9, f"{site} hit={hit} never killed (rc={rc})"
    rc2 = _run_worker(log2, out, result, mode="tail", log_path=logp)
    assert rc2 == 0, f"resume failed rc={rc2} (see {logp})"
    with open(result) as f:
        drilled = json.load(f)

    for k in ("num_features", "store_digest", "dense_digest", "records"):
        assert drilled[k] == ref[k], (site, hit, k)
    # The manifests tile the file's bytes EXACTLY once: contiguous
    # disjoint [start, end) ranges from 0 to the final size, and the
    # event totals are exact — nothing lost or duplicated at the cut.
    ranges = sorted(split_byte_range(f)[1:]
                    for m in drilled["manifests"] for f in m["files"])
    assert ranges[0][0] == 0
    for (s0, e0), (s1, _e1) in zip(ranges, ranges[1:]):
        assert e0 == s1, f"gap/overlap at byte {e0}->{s1}"
    assert ranges[-1][1] == os.path.getsize(
        os.path.join(log2, "live.log"))
    assert sum(m["events"] for m in drilled["manifests"]) == \
        worker.TAIL_STAGES * worker.BS
