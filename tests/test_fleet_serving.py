"""Serving fleet tier: router over replicas sharing one shard tier.

Pins the contracts SERVING_FLEET.md documents: consistent-hash routing
is deterministic (same key → same healthy replica), least-loaded
spillover engages under skew, a dead replica is struck/ejected and its
traffic re-routes INSIDE the client RPC, SLO admission sheds overflow
to the degraded (HBM-hot-rows-only, ``degraded=true``) path, replicas
resolving misses against the shared ShardServer tier serve values
bit-identical to a flat full-table predictor (f32 wire), the router's
stats fan-out merges per-replica registries into one cluster view, and
a dim-grouped export serves through one replica (mixed-width slots).
"""

import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch, SlotConf
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.multihost.shard_service import (start_local_shards,
                                                   stop_shards)
from paddlebox_tpu.multihost.store import MultiHostStore
from paddlebox_tpu.serving import (CTRPredictor, FleetRouter,
                                   PredictClient, PredictServer,
                                   ServingFleet, ShardBackedStore)
from paddlebox_tpu.serving.fleet import HashRing, route_key_hash

SLOTS = ("u", "i")
N_KEYS = 400
DIM = 8


def _feed(bs=16):
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=bs)


def _model():
    return DeepFM(slot_names=SLOTS, emb_dim=DIM, hidden=())


def _model_arrays(seed=3):
    rng = np.random.default_rng(seed)
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(N_KEYS, DIM)).astype(np.float32) * 0.02
    w = rng.normal(size=(N_KEYS,)).astype(np.float32) * 0.02
    return keys, emb, w


def _dense(model):
    import jax
    return model.init(jax.random.PRNGKey(0))


def _lines(rng, n, lo=1, hi=N_KEYS):
    return [f"0 u:{rng.integers(lo, hi)} i:{rng.integers(lo, hi)}"
            for _ in range(n)]


@pytest.fixture()
def shard_tier():
    """A 2-host shared shard tier populated with the deterministic
    model arrays (the trained-model stand-in every replica resolves
    misses against)."""
    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    servers, eps = start_local_shards(2, cfg)
    store = MultiHostStore(cfg, eps)
    keys, emb, w = _model_arrays()
    rows = store.pull_for_pass(keys)
    rows["emb"] = emb.copy()
    rows["w"] = w.copy()
    store.push_from_pass(keys, rows)
    yield eps
    store.close()
    stop_shards(servers)


def _flat_predictor():
    model = _model()
    keys, emb, w = _model_arrays()
    return CTRPredictor(model, _feed(), keys, emb, w, _dense(model),
                        compute_dtype="float32")


def _backed_predictor(eps, *, warm=32, hbm=24):
    """A shard-backed replica predictor warm with only the first
    ``warm`` keys — everything else resolves from the shared tier."""
    model = _model()
    keys, emb, w = _model_arrays()
    return CTRPredictor(model, _feed(), keys[:warm], emb[:warm], w[:warm],
                        _dense(model), compute_dtype="float32",
                        hbm_rows=hbm,
                        shard_backing=ShardBackedStore(eps, DIM))


def test_ring_deterministic_and_minimal_remap():
    ring3 = HashRing(["a", "b", "c"], 64)
    ring3b = HashRing(["c", "a", "b"], 64)  # order-independent
    hashes = [route_key_hash([f"0 u:{k} i:9"]) for k in range(1, 400)]
    owners3 = [ring3.lookup(h) for h in hashes]
    assert owners3 == [ring3b.lookup(h) for h in hashes]
    # Removing one replica remaps ONLY the removed replica's keys —
    # the consistent-hash property that preserves the survivors' warm
    # tiers on eject.
    ring2 = HashRing(["a", "b"], 64)
    for h, o3 in zip(hashes, owners3):
        o2 = ring2.lookup(h)
        if o3 != "c":
            assert o2 == o3


def test_same_key_routes_to_same_replica(shard_tier):
    preds = [_backed_predictor(shard_tier) for _ in range(3)]
    servers = [PredictServer("127.0.0.1:0", p, replica_id=f"r{i}")
               for i, p in enumerate(preds)]
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint for s in servers],
                         start_health=False)
    try:
        rng = np.random.default_rng(11)
        # Ten requests per distinct user key, interleaved: every repeat
        # of a key must land on the same replica.
        by_key = {}
        for _ in range(10):
            for uk in (7, 99, 250, 381):
                out = router.handle_predict(
                    {"lines": [f"0 u:{uk} i:{rng.integers(1, 300)}"]})
                by_key.setdefault(uk, set()).add(out["replica"])
                assert out["degraded"] is False
        for uk, reps in by_key.items():
            assert len(reps) == 1, (uk, reps)
        # Distinct keys spread over more than one replica (64 vnodes ×
        # 3 replicas: 4 keys landing on one replica has p ~ (1/3)^3).
        assert len(set().union(*by_key.values())) >= 2
    finally:
        router.stop()
        for s in servers:
            s.stop()
        for p in preds:
            p.close()


def test_spillover_under_skew_and_degraded_admission():
    fleet = ServingFleet()
    a = fleet.add_replica("a", "127.0.0.1:1", ready=True)
    b = fleet.add_replica("b", "127.0.0.1:2", ready=True)
    prev = flagmod.flag("fleet_spillover_inflight")
    flagmod.set_flags({"fleet_spillover_inflight": 2})
    try:
        h = route_key_hash(["0 u:5 i:5"])
        home = fleet.pick(h)[0]
        other = b if home is a else a
        # Fill the home replica to the ceiling: the next pick for the
        # SAME key spills to the least-loaded healthy replica.
        r2, mode2, deg2 = fleet.pick(h)
        assert r2 is home and mode2 == "affinity"
        r3, mode3, deg3 = fleet.pick(h)
        assert r3 is other and mode3 == "spillover" and not deg3
        snap = monitor.snapshot()
        assert snap.get("fleet/spillover", 0) >= 1
        # Saturate BOTH replicas: with the home replica's SLO admission
        # tripped, its overflow is shed to the degraded path instead of
        # queueing; with admission ok it queues (backpressure).
        fleet.pick(h); fleet.pick(h)
        assert home.inflight >= 2 and other.inflight >= 2
        r, _m, deg = fleet.pick(h)
        assert not deg            # admission ok -> queue, not shed
        fleet.release(r)
        home.admission = "degraded"
        r, _m, deg = fleet.pick(h)
        assert deg is True
        assert monitor.snapshot().get("fleet/degraded", 0) >= 1
    finally:
        flagmod.set_flags({"fleet_spillover_inflight": prev})
        fleet.stop()


def test_slo_admission_window_trips_and_recovers():
    fleet = ServingFleet(stats_call=lambda r: next(stats_iter))
    r = fleet.add_replica("a", "127.0.0.1:1", ready=True)
    prev = {k: flagmod.flag(k) for k in ("fleet_slo_window_s",
                                         "fleet_slo_trip")}
    flagmod.set_flags({"fleet_slo_window_s": 0.05, "fleet_slo_trip": 3})
    try:
        # Baseline read, then +5 violations in one window: trips.
        stats_iter = iter([{"slo_violations": 10},
                           {"slo_violations": 15}])
        fleet.health_check_once()
        assert r.admission == "ok"      # first read only sets baseline
        fleet.health_check_once()
        assert r.admission == "degraded"
        # One clean (zero-delta) full window restores.
        time.sleep(0.06)
        stats_iter = iter([{"slo_violations": 15}])
        fleet.health_check_once()
        assert r.admission == "ok"
    finally:
        flagmod.set_flags(prev)
        fleet.stop()


def test_kill_replica_reroutes_in_rpc_and_ejects(shard_tier):
    """Hard-stop one replica under traffic: the routed predict that
    hits the dead socket re-routes to a live replica inside the SAME
    client RPC (zero failed RPCs), the dead replica is struck to
    ejection, and the epoch bumps so clients re-resolve."""
    preds = [_backed_predictor(shard_tier) for _ in range(3)]
    servers = [PredictServer("127.0.0.1:0", p, replica_id=f"r{i}")
               for i, p in enumerate(preds)]
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint for s in servers],
                         start_health=False)
    cli = PredictClient(router.endpoint)
    try:
        rng = np.random.default_rng(5)
        lines_by_key = {uk: [f"0 u:{uk} i:{rng.integers(1, 300)}"]
                        for uk in range(1, 40)}
        owners = {uk: router.handle_predict({"lines": ln})["replica"]
                  for uk, ln in lines_by_key.items()}
        # Kill the replica that owns at least one key: stop its
        # listener AND drop the router's pooled connections to it — the
        # next forward meets a refused connect, exactly what a pooled
        # conn to a kill -9'd process meets (the REAL SIGKILL drill is
        # tests/test_fleet_drill.py).
        victim_id = owners[1]
        vic_i = int(victim_id.split("-")[1])
        servers[vic_i].stop()
        router.fleet.get(victim_id).pool.close()
        epoch_before = router.fleet.epoch
        failures = 0
        rerouted = []
        for uk, ln in lines_by_key.items():
            try:
                out = cli.predict(ln)
                assert out.shape == (1,)
                if owners[uk] == victim_id:
                    rerouted.append((uk, cli.last_replica))
            except Exception:
                failures += 1
        assert failures == 0
        assert rerouted, "victim owned no keys — test is vacuous"
        assert all(rep != victim_id for _uk, rep in rerouted)
        vic = router.fleet.get(victim_id)
        assert vic.state == "ejected"
        assert router.fleet.epoch > epoch_before
        # Routing to the survivors stays deterministic post-eject.
        for uk, ln in lines_by_key.items():
            if owners[uk] != victim_id:
                cli.predict(ln)
                assert cli.last_replica == owners[uk]
    finally:
        cli.close()
        router.stop()
        for s in servers:
            s.stop()
        for p in preds:
            p.close()


def test_shard_backed_matches_flat_f32_and_int8(shard_tier):
    """A replica warm with 8% of the model, resolving misses from the
    shared shard tier, serves BIT-identical probabilities to a flat
    full-table predictor at the f32 wire; the int8 wire stays within
    quantization tolerance and moves fewer bytes per key."""
    flat = _flat_predictor()
    backed = _backed_predictor(shard_tier)
    feed = backed.feed
    rng = np.random.default_rng(17)
    lines = _lines(rng, 16)
    batch = SlotBatch.pack(parse_lines(lines, feed), feed)
    monitor.reset()
    ref = np.asarray(flat.predict(batch))
    got = np.asarray(backed.predict(batch))
    np.testing.assert_array_equal(got, ref)
    snap = monitor.snapshot()
    assert snap.get("serving/shard_miss_keys", 0) > 0
    f32_bytes = snap.get("serving/shard_miss_bytes", 0)
    assert f32_bytes > 0
    # Unknown keys (never trained) still serve the zero row.
    unk = [f"0 u:{N_KEYS + 50} i:{N_KEYS + 60}"]
    ub = SlotBatch.pack(parse_lines(unk, feed), feed)
    np.testing.assert_array_equal(
        np.asarray(backed.predict(ub))[:1],
        np.asarray(flat.predict(ub))[:1])
    # int8 wire: tolerance parity, fewer bytes per resolved key.
    prev = flagmod.flag("multihost_wire_dtype")
    flagmod.set_flags({"multihost_wire_dtype": "int8"})
    try:
        backed8 = _backed_predictor(shard_tier)
        monitor.reset()
        got8 = np.asarray(backed8.predict(batch))
        np.testing.assert_allclose(got8, ref, atol=5e-3)
        snap8 = monitor.snapshot()
        keys8 = snap8.get("serving/shard_miss_keys", 0)
        assert keys8 > 0
        assert (snap8["serving/shard_miss_bytes"] / keys8
                < f32_bytes / snap["serving/shard_miss_keys"])
        backed8.close()
    finally:
        flagmod.set_flags({"multihost_wire_dtype": prev})
    flat.close()
    backed.close()


def test_shard_backed_promotion_and_delta_routing(shard_tier):
    """Promotion admits hot missed keys by COPY (the shared tier is
    never mutated), and a delta lands only on locally materialized rows
    — the rest is bypassed (the tier already has the training push)."""
    backed = _backed_predictor(shard_tier, warm=16, hbm=8)
    feed = backed.feed
    tiers = backed._tiers
    rng = np.random.default_rng(23)
    hot_key = 300   # beyond the warm set: resolves via the tier
    for _ in range(6):
        lines = [f"0 u:{hot_key} i:{rng.integers(1, 200)}"]
        backed.predict(SlotBatch.pack(parse_lines(lines, feed), feed))
    assert tiers._miss_counts.get(hot_key, 0) >= 6
    n = backed.promote_now()
    assert n >= 1
    assert hot_key in tiers._hot_keys
    # The shared tier still owns the row (copy, not take).
    bfound, _ = tiers.backing.read(
        np.asarray([hot_key], np.uint64))
    assert bfound[0]
    # Delta: hot row updated in place, unmaterialized keys bypassed.
    monitor.reset()
    keys = np.asarray([hot_key, 399], np.uint64)  # 399 never touched
    emb = np.full((2, DIM), 0.5, np.float32)
    w = np.asarray([0.25, 0.25], np.float32)
    n_new = backed.apply_update(keys, emb, w)
    assert n_new == 0
    assert monitor.snapshot().get("serving/delta_bypassed", 0) == 1
    row = np.asarray(
        tiers.table[int(tiers._hot_rows[
            np.searchsorted(tiers._hot_keys, hot_key)])])
    np.testing.assert_allclose(row[:DIM], 0.5)
    backed.close()


def test_degraded_predict_serves_hot_rows_only(shard_tier):
    """The degraded path: misses read the default (zero) row with no
    warm/cold/backing resolution — the reply a router flags
    degraded=true — and the wire carries the flag end to end."""
    backed = _backed_predictor(shard_tier, warm=16, hbm=16)
    feed = backed.feed
    # A key outside the warm/hot set: normal predict resolves it from
    # the tier; degraded predict serves the zero row instead, which
    # must equal what an all-unknown flat predictor answers.
    lines = ["0 u:350 i:360"]
    batch = SlotBatch.pack(parse_lines(lines, feed), feed)
    monitor.reset()
    normal = np.asarray(backed.predict(batch))
    deg = np.asarray(backed.predict(batch, degraded=True))
    assert monitor.snapshot().get("serving/degraded_rows", 0) > 0
    model = _model()
    keys, emb, w = _model_arrays()
    empty = CTRPredictor(model, feed, keys[:1], emb[:1], w[:1],
                         _dense(model), compute_dtype="float32")
    want = np.asarray(empty.predict(batch))
    np.testing.assert_array_equal(deg[:1], want[:1])
    assert not np.array_equal(normal[:1], deg[:1])
    # End to end through router + wire: force the degraded decision.
    server = PredictServer("127.0.0.1:0", backed, replica_id="r0")
    router = FleetRouter("127.0.0.1:0", replicas=[server.endpoint],
                         start_health=False)
    cli = PredictClient(router.endpoint)
    prev = flagmod.flag("fleet_spillover_inflight")
    flagmod.set_flags({"fleet_spillover_inflight": 1})
    try:
        rep = router.fleet.get("replica-0")
        rep.admission = "degraded"
        rep.inflight = 5           # past the ceiling: overflow -> shed
        out = cli.predict(lines)
        assert cli.last_degraded is True
        np.testing.assert_array_equal(out, deg[:1])
        rep.inflight = 0
        rep.admission = "ok"
        out2 = cli.predict(lines)
        assert cli.last_degraded is False
        np.testing.assert_array_equal(out2, normal[:1])
    finally:
        flagmod.set_flags({"fleet_spillover_inflight": prev})
        cli.close()
        router.stop()
        server.stop()
        backed.close()
        empty.close()


def test_join_mid_traffic_bit_identical(shard_tier):
    """A replica joining a live fleet (register -> health admit) serves
    bit-identical probabilities to the incumbents and starts taking its
    ring share; incumbents keep their keys (minimal remap)."""
    preds = [_backed_predictor(shard_tier) for _ in range(2)]
    servers = [PredictServer("127.0.0.1:0", p, replica_id=f"r{i}")
               for i, p in enumerate(preds)]
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint for s in servers],
                         start_health=False)
    try:
        rng = np.random.default_rng(31)
        test_lines = [_lines(rng, 4) for _ in range(6)]
        before = {i: router.handle_predict({"lines": ln})
                  for i, ln in enumerate(test_lines)}
        # Join: a third replica registers (joining) and is admitted by
        # the health sweep once its stats answer.
        p3 = _backed_predictor(shard_tier)
        s3 = PredictServer("127.0.0.1:0", p3, replica_id="r2")
        epoch_before = router.fleet.epoch
        router.fleet.add_replica("replica-2", s3.endpoint)
        assert router.fleet.get("replica-2").state == "joining"
        router.fleet.health_check_once()
        assert router.fleet.get("replica-2").state == "healthy"
        assert router.fleet.epoch > epoch_before
        # Bit-identical: the joiner answers exactly what an incumbent
        # answered for the same lines (direct, no router).
        c_new = PredictClient(s3.endpoint)
        c_old = PredictClient(servers[0].endpoint)
        for ln in test_lines:
            np.testing.assert_array_equal(c_new.predict(ln),
                                          c_old.predict(ln))
        c_new.close()
        c_old.close()
        # Keys NOT remapped to the joiner stay on their old replica.
        after = {i: router.handle_predict({"lines": ln})
                 for i, ln in enumerate(test_lines)}
        moved = 0
        for i in before:
            np.testing.assert_array_equal(before[i]["probs"],
                                          after[i]["probs"])
            if after[i]["replica"] != before[i]["replica"]:
                moved += 1
                assert after[i]["replica"] == "replica-2"
        # The joiner eventually serves (drive enough keys through).
        hit = any(router.handle_predict(
            {"lines": [f"0 u:{k} i:1"]})["replica"] == "replica-2"
            for k in range(1, 200))
        assert hit
        s3.stop()
        p3.close()
    finally:
        router.stop()
        for s in servers:
            s.stop()
        for p in preds:
            p.close()


def test_cluster_stats_merge(shard_tier):
    """Router handle_stats = one merge_snapshots view: per-replica
    predict counts SUM, latency digests MERGE, and slo violations are
    fleet-wide — while per-replica briefs expose the skew."""
    prev = flagmod.flag("serving_slo_p99_ms")
    preds = [_backed_predictor(shard_tier) for _ in range(2)]
    servers = [PredictServer("127.0.0.1:0", p, replica_id=f"r{i}")
               for i, p in enumerate(preds)]
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint for s in servers],
                         start_health=False)
    cli = PredictClient(router.endpoint)
    try:
        rng = np.random.default_rng(41)
        n = 12
        flagmod.set_flags({"serving_slo_p99_ms": 1e-6})  # all violate
        for _ in range(n):
            cli.predict(_lines(rng, 2))
        st = cli.stats()
        assert st["fleet_size"] == 2
        assert st["predict_rpcs"] == n
        assert st["slo_violations"] == n
        assert st["latency_ms"]["p50"] and st["latency_ms"]["p50"] > 0
        assert st["route_ms"]["p50"] and st["route_ms"]["p50"] > 0
        merged = st["merged"]
        assert merged["ranks"] == 2
        assert merged["counters"]["serving/predict_rpcs"] == n
        assert merged["quantiles"]["serving/predict_ms"]["count"] == n
        per_rep = sum(b["stats"]["predict_rpcs"]
                      for b in st["replicas"].values())
        assert per_rep == n
    finally:
        flagmod.set_flags({"serving_slo_p99_ms": prev})
        cli.close()
        router.stop()
        for s in servers:
            s.stop()
        for p in preds:
            p.close()


def test_elastic_discovery_and_leave(tmp_path, shard_tier):
    """Replicas advertise serving_endpoint through the elastic
    heartbeat meta; the fleet adopts the published table (join), and a
    host leaving the table is removed (clean leave)."""
    from paddlebox_tpu.launch.elastic import ElasticManager
    root = str(tmp_path / "elastic")
    pred = _backed_predictor(shard_tier)
    server = PredictServer("127.0.0.1:0", pred, replica_id="hostA")
    m = ElasticManager(root, "hostA", heartbeat_interval=0.05,
                       timeout=1.0, settle=0.05,
                       meta={"serving_endpoint": server.endpoint})
    m.start()
    fleet = ServingFleet(elastic_root=root)
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if fleet.discover_once():
                break
            time.sleep(0.05)
        r = fleet.get("hostA")
        assert r is not None and r.state == "joining"
        assert r.endpoint == server.endpoint
        fleet.health_check_once()
        assert fleet.get("hostA").state == "healthy"
        # Clean leave: lease removed -> host drops from the table ->
        # discovery removes the replica.
        m.stop(remove_lease=True)
        # hostA was also the leader; with no hosts left nobody
        # publishes a new table, so simulate the next generation the
        # way a surviving leader would: another member publishes a
        # table without hostA.
        m2 = ElasticManager(root, "hostB", heartbeat_interval=0.05,
                            timeout=0.4, settle=0.05)
        m2.start()
        deadline = time.time() + 10
        left = False
        while time.time() < deadline:
            fleet.discover_once()
            if fleet.get("hostA") is None:
                left = True
                break
            time.sleep(0.05)
        assert left
        m2.stop()
    finally:
        fleet.stop()
        server.stop()
        pred.close()


def test_client_reresolves_through_router_topology(shard_tier):
    """The PR-5 retry fix-up: a direct-to-replica client whose replica
    was ejected re-resolves through the router's topology on reconnect
    and lands the retried predict on a live replica — instead of
    burning the whole retry deadline on the dead endpoint."""
    preds = [_backed_predictor(shard_tier) for _ in range(2)]
    servers = [PredictServer("127.0.0.1:0", p, replica_id=f"r{i}")
               for i, p in enumerate(preds)]
    router = FleetRouter("127.0.0.1:0",
                         replicas=[s.endpoint for s in servers],
                         start_health=False)
    cli = PredictClient(servers[0].endpoint, router=router.endpoint)
    try:
        rng = np.random.default_rng(7)
        lines = _lines(rng, 3)
        want = cli.predict(lines)
        # Kill replica 0 (listener down + this client's established
        # conn dropped, as a SIGKILL would) and eject it from the fleet
        # (as the health thread would); the client's NEXT predict must
        # succeed via re-resolution to replica 1.
        servers[0].stop()
        cli._conn.close()
        vic = router.fleet.get("replica-0")
        router.fleet.strike(vic)
        router.fleet.strike(vic)
        assert vic.state == "ejected"
        got = cli.predict(lines)   # idempotent retry + re-resolve
        np.testing.assert_array_equal(got, want)
        assert cli._conn.endpoint == servers[1].endpoint
    finally:
        cli.close()
        router.stop()
        for s in servers:
            s.stop()
        for p in preds:
            p.close()


def test_grouped_export_serves_mixed_dims(tmp_path):
    """Satellite: a dim-grouped (dynamic-mf) xbox export serves through
    ONE predictor — per-slot widths routed to their group tables, bit-
    equal to a hand-gathered model.apply, and grouped deltas land on
    the right group."""
    from paddlebox_tpu.serving.predictor import GroupedCTRPredictor
    import jax

    gslots = ("narrow_a", "narrow_b", "wide")
    dims = {"narrow_a": 8, "narrow_b": 8, "wide": 32}
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0,
                             emb_dim=(32 if s == "wide" else None))
                    for s in gslots),
        batch_size=8)
    model = DeepFM(slot_names=gslots, emb_dim=dims, hidden=())
    dense = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(9)
    groups = {}
    for d in (8, 32):
        n = 60
        keys = np.arange(1, n + 1, dtype=np.uint64)
        emb = rng.normal(size=(n, d)).astype(np.float32) * 0.05
        w = rng.normal(size=(n,)).astype(np.float32) * 0.05
        groups[d] = (keys, emb, w)
    pred = GroupedCTRPredictor(model, feed, groups, dense,
                               compute_dtype="float32")
    assert pred.dims == [8, 32]
    assert pred.num_keys == 120
    lines = [f"0 narrow_a:{rng.integers(1, 80)} "
             f"narrow_b:{rng.integers(1, 80)} wide:{rng.integers(1, 80)}"
             for _ in range(7)]
    # One crafted row hits key 1 in BOTH width groups, so the grouped
    # delta below provably changes the served output.
    lines.append("0 narrow_a:1 narrow_b:2 wide:1")
    batch = SlotBatch.pack(parse_lines(lines, feed), feed)
    got = np.asarray(pred.predict(batch))
    # Hand-gathered reference: per-slot rows from that slot's group
    # arrays (unknown keys -> zero rows), straight through model.apply.
    emb_ref, w_ref = {}, {}
    for s in gslots:
        d = dims[s]
        k, e, w = groups[d]
        ids = batch.ids[s]
        rows = np.zeros((ids.shape[0], d), np.float32)
        wv = np.zeros((ids.shape[0],), np.float32)
        for i, fid in enumerate(ids):
            j = np.searchsorted(k, fid)
            if j < k.shape[0] and k[j] == fid and fid != 0:
                rows[i] = e[j]
                wv[i] = w[j]
        emb_ref[s] = rows
        w_ref[s] = wv
    import jax.numpy as jnp
    segs = {s: jnp.asarray(batch.segments[s]) for s in gslots}
    from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
    logits = model.apply(dense, {s: jnp.asarray(v)
                                 for s, v in emb_ref.items()},
                         {s: jnp.asarray(v) for s, v in w_ref.items()},
                         segs, batch_size=batch.batch_size,
                         dense_feats=jnp.asarray(
                             _concat_dense_host(batch)))
    want = np.asarray(jax.nn.sigmoid(logits.astype(jnp.float32)))
    np.testing.assert_array_equal(got, want)
    # Grouped delta export round-trip: write dimD subdirs the way
    # GroupedStore does and hot-swap them through apply_update_export.
    delta_root = str(tmp_path / "delta")
    for d in (8, 32):
        sub = os.path.join(delta_root, f"dim{d}")
        os.makedirs(sub, exist_ok=True)
        dk = np.asarray([1, 200], np.uint64)      # 200 is new
        de = np.full((2, d), 0.25, np.float32)
        dw = np.asarray([0.5, 0.5], np.float32)
        np.savez(os.path.join(sub, f"embedding_dim{d}.delta.npz"),
                 keys=dk, emb=de, w=dw)
    n_new = pred.apply_update_export(delta_root, "embedding", "delta")
    assert n_new == 2                      # one new key per group
    assert pred.num_keys == 122
    got2 = np.asarray(pred.predict(batch))
    assert not np.array_equal(got, got2)   # key 1 moved in both groups
    # A single-width update routes by its column count.
    n3 = pred.apply_update(np.asarray([2], np.uint64),
                           np.full((1, 32), 0.1, np.float32),
                           np.asarray([0.1], np.float32))
    assert n3 == 0
    # from_dirs auto-detects the grouped layout (what
    # load_serving_predictor hits on a dynamic-mf export_serving dir).
    xbox_root = str(tmp_path / "xbox")
    for d in (8, 32):
        sub = os.path.join(xbox_root, f"dim{d}")
        os.makedirs(sub, exist_ok=True)
        k, e, w = groups[d]
        np.savez(os.path.join(sub, f"embedding_dim{d}.xbox.npz"),
                 keys=k, emb=e, w=w)
    loaded = CTRPredictor.from_dirs(model, feed, xbox_root,
                                    dense_params=dense,
                                    compute_dtype="float32")
    assert isinstance(loaded, GroupedCTRPredictor)
    np.testing.assert_array_equal(np.asarray(loaded.predict(batch)),
                                  got)
    loaded.close()
    pred.close()


def test_start_replica_helper(tmp_path, shard_tier):
    """start_replica: base export + shard backing + warm-up + elastic
    registration in one call (what the drill worker and a real replica
    process run)."""
    from paddlebox_tpu.serving import start_replica
    model = _model()
    keys, emb, w = _model_arrays()
    base = str(tmp_path / "xbox")
    os.makedirs(base, exist_ok=True)
    np.savez(os.path.join(base, "embedding.xbox.npz"),
             keys=keys[:32], emb=emb[:32], w=w[:32])
    server, mgr = start_replica(
        model, _feed(), base_export=base, dense_params=_dense(model),
        shard_endpoints=shard_tier, hbm_rows=16,
        elastic_root=str(tmp_path / "el"), host_id="repA",
        warm_lines=["0 u:1 i:2"], compute_dtype="float32")
    try:
        assert mgr is not None
        flat = _flat_predictor()
        cli = PredictClient(server.endpoint)
        rng = np.random.default_rng(2)
        lines = _lines(rng, 4)
        got = cli.predict(lines)
        want = flat.predict(SlotBatch.pack(parse_lines(lines, _feed()),
                                           _feed()))[:4]
        np.testing.assert_array_equal(got, np.asarray(want))
        cli.close()
        flat.close()
        # The heartbeat advertises the serving endpoint.
        deadline = time.time() + 10
        fleet = ServingFleet(elastic_root=str(tmp_path / "el"))
        seen = False
        while time.time() < deadline:
            fleet.discover_once()
            r = fleet.get("repA")
            if r is not None:
                assert r.endpoint == server.endpoint
                seen = True
                break
            time.sleep(0.05)
        assert seen
        fleet.stop()
    finally:
        if mgr is not None:
            mgr.stop()
        server.stop()
        server.predictor.close()
