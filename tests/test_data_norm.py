"""DataNorm parity vs a direct transcription of the reference op's CPU
semantics (data_norm_op.cc), incl. the slot_dim show-skip path, the
decayed summary update, dp-synced stats, and gradient behavior."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.data_norm import data_norm_apply, data_norm_init


def ref_forward(stats, x, slot_dim=-1):
    means = stats["batch_sum"] / stats["batch_size"]
    scales = np.sqrt(stats["batch_size"] / stats["batch_square_sum"])
    y = (x - means) * scales
    if slot_dim > 0:
        n, c = x.shape
        for k in range(n):
            for i in range(0, c, slot_dim):
                if abs(x[k, i]) < 1e-7:
                    y[k, i:i + slot_dim] = 0.0
    return y


def ref_deltas(stats, x, slot_dim, eps):
    n, c = x.shape
    means = stats["batch_sum"] / stats["batch_size"]
    d_size = np.zeros(c)
    d_sum = np.zeros(c)
    d_sq = np.zeros(c)
    if slot_dim > 0:
        for k in range(n):
            for i in range(0, c, slot_dim):
                if abs(x[k, i]) >= 1e-7:
                    for j in range(i, i + slot_dim):
                        d_size[j] += 1
                        d_sum[j] += x[k, j]
                        d_sq[j] += (x[k, j] - means[j]) ** 2
        for j in range(c):
            if d_size[j] >= 1:
                d_sum[j] /= d_size[j]
                d_sq[j] = d_sq[j] / d_size[j] + d_size[j] * eps
                d_size[j] = 1
    else:
        d_size[:] = n
        d_sum = x.sum(0)
        d_sq = ((x - means) ** 2).sum(0) + n * eps
    return d_size, d_sum, d_sq


def np_stats(stats):
    return {k: np.asarray(v) for k, v in stats.items()}


def test_identity_at_init():
    stats = data_norm_init(6)
    x = np.random.default_rng(0).normal(size=(32, 6)).astype(np.float32)
    y, _ = data_norm_apply(stats, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), x, rtol=1e-6)


@pytest.mark.parametrize("slot_dim", [-1, 4])
def test_forward_and_update_parity(slot_dim):
    rng = np.random.default_rng(1)
    c = 8
    stats = data_norm_init(c)
    # Non-trivial stats state.
    stats["batch_sum"] = jnp.asarray(
        rng.normal(size=c).astype(np.float32) * 100)
    stats["batch_square_sum"] = jnp.asarray(
        (rng.random(c).astype(np.float32) + 0.5) * 1e4)
    x = rng.normal(size=(16, c)).astype(np.float32)
    if slot_dim > 0:
        # Zero the "show" column of some chunks.
        x[::3, 0] = 0.0
        x[1::4, 4] = 0.0
    eps, dr = 1e-4, 0.999

    y, new = data_norm_apply(stats, jnp.asarray(x), slot_dim=slot_dim,
                             epsilon=eps, summary_decay_rate=dr)
    np.testing.assert_allclose(np.asarray(y),
                               ref_forward(np_stats(stats), x, slot_dim),
                               rtol=1e-5, atol=1e-5)
    d_size, d_sum, d_sq = ref_deltas(np_stats(stats), x, slot_dim, eps)
    s = np_stats(stats)
    np.testing.assert_allclose(np.asarray(new["batch_size"]),
                               s["batch_size"] * dr + d_size, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(new["batch_sum"]),
                               s["batch_sum"] * dr + d_sum,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(new["batch_square_sum"]),
                               s["batch_square_sum"] * dr + d_sq,
                               rtol=1e-4, atol=1e-4)


def test_scale_and_shift():
    stats = data_norm_init(4, enable_scale_and_shift=True)
    stats["scale_w"] = jnp.asarray([2.0, 1.0, 0.5, 1.0], jnp.float32)
    stats["bias"] = jnp.asarray([0.0, 1.0, 0.0, -1.0], jnp.float32)
    x = np.random.default_rng(2).normal(size=(8, 4)).astype(np.float32)
    y, _ = data_norm_apply(stats, jnp.asarray(x), train=False)
    np.testing.assert_allclose(
        np.asarray(y), x * np.asarray(stats["scale_w"])
        + np.asarray(stats["bias"]), rtol=1e-5)


def test_eval_does_not_update():
    stats = data_norm_init(4)
    x = jnp.ones((8, 4))
    _, new = data_norm_apply(stats, x, train=False)
    assert new is stats


def test_grads_flow_through_y_not_stats():
    stats = data_norm_init(4)
    stats["batch_square_sum"] = jnp.full((4,), 4e4, jnp.float32)  # scale .5
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, 4)),
                    jnp.float32)

    def loss(x):
        y, _ = data_norm_apply(stats, x)
        return jnp.sum(y)

    g = jax.grad(loss)(x)
    # d/dx (x - m) * s = s = 0.5 everywhere; stats path stop_gradient'd.
    np.testing.assert_allclose(np.asarray(g), 0.5, rtol=1e-6)


def test_synced_stats_match_global_batch():
    """psum'd deltas over dp must equal a single-host update on the
    concatenated batch (non-slot path)."""
    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
    c = 4
    stats = data_norm_init(c)
    rng = np.random.default_rng(4)
    x = rng.normal(size=(32, c)).astype(np.float32)

    def shard_fn(x):
        _, new = data_norm_apply(stats, x, axis_name="dp")
        return new

    from jax.sharding import PartitionSpec as P
    new_sharded = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh, in_specs=P("dp"), out_specs=P()))(x)
    _, new_single = data_norm_apply(stats, jnp.asarray(x))
    for k in new_single:
        np.testing.assert_allclose(np.asarray(new_sharded[k]),
                                   np.asarray(new_single[k]),
                                   rtol=1e-4, atol=1e-4)


# -- trainer integration ----------------------------------------------------

def _train_once(data_norm, tmp_path, n_steps=4, slot_dim=-1):
    import os
    import tempfile

    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    mesh = build_mesh(HybridTopology(dp=8))
    slots = (SlotConf("a", avg_len=1.0), SlotConf("b", avg_len=1.0),
             SlotConf("d", is_dense=True, dim=4))
    feed = DataFeedConfig(slots=slots, batch_size=64)
    model = DeepFM(slot_names=("a", "b"), emb_dim=4, dense_dim=4,
                   hidden=(16,))
    tr = CTRTrainer(model, feed, TableConfig(dim=4, learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(data_norm=data_norm,
                                         data_norm_slot_dim=slot_dim))
    tr.init(seed=0)
    rng = np.random.default_rng(7)
    p = str(tmp_path / f"part-dn-{data_norm}")
    with open(p, "w") as f:
        for _ in range(n_steps * 64):
            feats = f"a:{rng.integers(1, 200)} b:{rng.integers(1, 200)}"
            dense = ",".join(f"{v:.3f}" for v in
                             rng.normal(3.0, 2.0, 4))  # non-unit stats
            f.write(f"{rng.integers(0, 2)} {feats} d:{dense}\n")
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    stats = tr.train_pass(ds)
    return tr, stats


def test_trainer_data_norm_learns_stats(tmp_path):
    tr, stats = _train_once(True, tmp_path)
    assert np.isfinite(stats["loss"])
    dn = tr.params["data_norm"]
    # Stats moved off their init values toward the data's (mean 3).
    assert not np.allclose(np.asarray(dn["batch_sum"]), 0.0)
    # batch_size grew by ~the global sample count (4 steps x 64), and
    # the sums pull the means toward the data's mean (3.0) from 0.
    assert np.asarray(dn["batch_size"]).mean() > 1e4 + 200
    means = np.asarray(dn["batch_sum"]) / np.asarray(dn["batch_size"])
    assert (means > 0.0).all()
    # Optimizer state exists for the stats leaves but never moved them:
    # their only writer is the decayed summary path.
    tr2, stats2 = _train_once(True, tmp_path)
    np.testing.assert_allclose(np.asarray(dn["batch_size"]),
                               np.asarray(tr2.params["data_norm"]
                                          ["batch_size"]), rtol=1e-6)


def test_trainer_data_norm_identity_at_first_step(tmp_path):
    """Initial stats are the identity transform, so the FIRST step's
    loss must match the data_norm=False trainer exactly."""
    import jax.numpy as jnp

    tr_on, _ = _train_once(True, tmp_path, n_steps=1)
    tr_off, _ = _train_once(False, tmp_path, n_steps=1)
    # Compare a dense-tower weight after one identical step.
    wa = jax.tree_util.tree_leaves(tr_on.params["mlp"])[0]
    wb = jax.tree_util.tree_leaves(tr_off.params["mlp"])[0]
    np.testing.assert_allclose(np.asarray(wa), np.asarray(wb),
                               rtol=1e-5, atol=1e-6)


def test_trainer_data_norm_eval_does_not_touch_stats(tmp_path):
    from paddlebox_tpu.data.dataset import Dataset

    tr, _ = _train_once(True, tmp_path)
    before = {k: np.asarray(v).copy()
              for k, v in tr.params["data_norm"].items()}
    import os
    p = [f for f in os.listdir(tmp_path) if f.startswith("part-dn-True")]
    ds = Dataset(tr.feed_config, num_reader_threads=1)
    ds.set_filelist([str(tmp_path / p[0])])
    ds.load_into_memory()
    tr.eval_pass(ds)
    for k, v in tr.params["data_norm"].items():
        np.testing.assert_array_equal(np.asarray(v), before[k])


@pytest.mark.parametrize("slot_dim", [-1, 2])
def test_serving_parity_with_data_norm(tmp_path, slot_dim):
    """The predictor must normalize dense features by the trained stats
    exactly as the trainer forward does (PARITY serving row) — incl. the
    slot_dim show-skip zeroing."""
    import dataclasses

    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.serving import CTRPredictor, load_xbox_model

    tr, _ = _train_once(True, tmp_path, slot_dim=slot_dim)
    n = tr.engine.store.save_xbox(str(tmp_path))
    keys, emb, w = load_xbox_model(str(tmp_path), table="embedding")
    assert keys.shape[0] == n

    import os
    part = [f for f in os.listdir(tmp_path) if f.startswith("part-dn-True")]
    ds = Dataset(tr.feed_config, num_reader_threads=1)
    ds.set_filelist([str(tmp_path / part[0])])
    ds.load_into_memory()
    batch = next(ds.batches_sharded(1))

    if slot_dim > 0:
        # Zero some show channels so the skip path actually fires.
        dense0 = {k: v.copy() for k, v in batch.dense.items()}
        for v in dense0.values():
            v[::3, 0] = 0.0
            v[1::4, 2] = 0.0
        batch = dataclasses.replace(batch, dense=dense0)

    pred = CTRPredictor(tr.model, tr.feed_config, keys, emb, w, tr.params,
                        compute_dtype="float32",
                        data_norm_slot_dim=slot_dim)
    probs = pred.predict(batch)

    # Reference: strip the stats and hand the predictor pre-normalized
    # dense features — must match exactly.
    from paddlebox_tpu.ops.data_norm import data_norm_apply
    import jax.numpy as jnp
    stripped = {k: v for k, v in tr.params.items() if k != "data_norm"}
    dense_norm = {
        k: np.asarray(data_norm_apply(tr.params["data_norm"],
                                      jnp.asarray(v), train=False,
                                      slot_dim=slot_dim)[0])
        for k, v in batch.dense.items()}
    batch2 = dataclasses.replace(batch, dense=dense_norm)
    pred2 = CTRPredictor(tr.model, tr.feed_config, keys, emb, w, stripped,
                         compute_dtype="float32")
    probs2 = pred2.predict(batch2)
    np.testing.assert_allclose(probs, probs2, rtol=1e-6, atol=1e-6)
    # And the stats are genuinely non-identity by now (else this test
    # proves nothing).
    y, _ = data_norm_apply(tr.params["data_norm"],
                           jnp.asarray(list(batch.dense.values())[0]),
                           train=False)
    assert not np.allclose(np.asarray(y),
                           list(batch.dense.values())[0], atol=1e-4)
