"""SSD-tier-backed PS shards: the remote twin of the local RAM/disk tier.

Role of the reference's SSD table serving under the PS plane
(``box_wrapper.h:635`` LoadSSD2Mem on a served shard): each PS server
bounds its RAM-resident rows and overflows the coldest to per-shard disk
buckets, transparently to clients — pulls stage disk rows back in, and
save/load round-trips the union of both tiers.
"""

import numpy as np
import pytest

from paddlebox_tpu.distributed.ps import start_local_cluster
from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
from paddlebox_tpu.embedding.table import TableConfig

RAM_BUDGET = 40


@pytest.fixture
def tiered_cluster(tmp_path):
    cfg = TableConfig(name="emb", dim=4, optimizer="adagrad",
                      learning_rate=0.1)

    def factory(c, idx):
        return TieredFeatureStore(c, str(tmp_path / f"shard{idx}"),
                                  max_ram_features=RAM_BUDGET, seed=idx)

    servers, client = start_local_cluster(2, {"emb": cfg},
                                          store_factory=factory)
    yield servers, client, cfg
    client.stop_servers()
    client.close()
    for s in servers:
        s.stop()


def _plain_cluster(cfg):
    return start_local_cluster(2, {"emb": cfg})


def test_remote_tier_parity_with_plain_store(tiered_cluster):
    """Same pull/push traffic against tiered and plain clusters must give
    identical values even when the tiered shards evict past budget —
    tier movement is a placement detail, not a semantics change."""
    servers, client, cfg = tiered_cluster
    plain_servers, plain_client = _plain_cluster(cfg)
    try:
        rng = np.random.default_rng(0)
        # 4x the per-shard RAM budget so eviction must happen.
        all_keys = np.arange(1, 4 * 2 * RAM_BUDGET + 1, dtype=np.uint64)
        for step in range(4):
            keys = rng.choice(all_keys, size=64, replace=False)
            a = client.pull_sparse("emb", keys)
            b = plain_client.pull_sparse("emb", keys)
            np.testing.assert_allclose(a["emb"], b["emb"], atol=1e-6)
            g = rng.standard_normal((64, 4)).astype(np.float32)
            kw = dict(emb_grad=g,
                      w_grad=np.ones((64,), np.float32),
                      show=np.ones((64,), np.float32),
                      click=np.zeros((64,), np.float32))
            client.push_sparse("emb", keys, **kw)
            plain_client.push_sparse("emb", keys, **kw)
        # After the churn: every key must still read back identically.
        a = client.pull_sparse("emb", all_keys)
        b = plain_client.pull_sparse("emb", all_keys)
        np.testing.assert_allclose(a["emb"], b["emb"], atol=1e-6)
        np.testing.assert_allclose(a["w"], b["w"], atol=1e-6)
    finally:
        plain_client.stop_servers()
        plain_client.close()
        for s in plain_servers:
            s.stop()


def test_remote_tier_actually_evicts(tiered_cluster):
    servers, client, _ = tiered_cluster
    all_keys = np.arange(1, 4 * 2 * RAM_BUDGET + 1, dtype=np.uint64)
    client.pull_sparse("emb", all_keys)  # persists init rows
    g = np.ones((all_keys.size, 4), np.float32)
    client.push_sparse("emb", all_keys, emb_grad=g,
                       w_grad=np.ones((all_keys.size,), np.float32))
    for s in servers:
        store = s.tables["emb"]
        assert isinstance(store, TieredFeatureStore)
        assert store.ram.num_features <= RAM_BUDGET
        assert store.disk.num_features > 0
    # stats() reports the union (RAM + disk), not just resident rows.
    total = sum(st["emb"] for st in client.stats())
    assert total == all_keys.size


def test_remote_tier_save_load_roundtrip(tiered_cluster, tmp_path):
    servers, client, cfg = tiered_cluster
    keys = np.arange(1, 3 * 2 * RAM_BUDGET + 1, dtype=np.uint64)
    before = client.pull_sparse("emb", keys)
    client.push_sparse("emb", keys,
                       emb_grad=np.ones((keys.size, 4), np.float32),
                       w_grad=np.ones((keys.size,), np.float32))
    after = client.pull_sparse("emb", keys)
    ckpt = str(tmp_path / "ckpt")
    client.save(ckpt, "base")

    # Fresh tiered cluster, same shard count: load must restore every
    # row — including the ones that lived on disk at save time.
    def factory(c, idx):
        return TieredFeatureStore(c, str(tmp_path / f"re{idx}"),
                                  max_ram_features=RAM_BUDGET, seed=idx)

    servers2, client2 = start_local_cluster(2, {"emb": cfg},
                                            store_factory=factory)
    try:
        client2.load(ckpt, "base")
        out = client2.pull_sparse("emb", keys)
        np.testing.assert_allclose(out["emb"], after["emb"], atol=1e-6)
        assert not np.allclose(out["emb"], before["emb"])
    finally:
        client2.stop_servers()
        client2.close()
        for s in servers2:
            s.stop()
