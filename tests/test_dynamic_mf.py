"""Dynamic mf (per-slot embedding widths) end-to-end.

Role of the reference's per-slot mf dims: ``CtrDymfAccessor``
(``paddle/fluid/distributed/ps/table/ctr_dymf_accessor.h``) and ``mf_dim``
in the HBM value record (``heter_ps/feature_value.h:44-120``) — production
CTR models mix narrow and wide slots in one model. Here: 8- and 32-wide
slots train together through feed -> pull -> push -> store -> checkpoint
via the dim-grouped engine.
"""

import os

import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import GroupedEngine, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("narrow_a", "narrow_b", "wide")
DIMS = {"narrow_a": 8, "narrow_b": 8, "wide": 32}


def _feed(bs=64):
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=2.0,
                             emb_dim=(32 if s == "wide" else None))
                    for s in SLOTS),
        batch_size=bs)


def _shard(path, n, seed, num_feats=200):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, num_feats, rng.integers(1, 4))
                     for s in SLOTS}
            clickiness = np.mean([(int(v) % 5 == 0)
                                  for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * clickiness)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    d = tmp_path_factory.mktemp("dymf")
    return [_shard(d / f"part-{i}", 512, seed=i) for i in range(2)]


def _make_trainer():
    mesh = build_mesh(HybridTopology(dp=8))
    feed = _feed()
    model = DeepFM(slot_names=SLOTS, emb_dim=DIMS, hidden=(32, 16))
    trainer = CTRTrainer(model, feed, TableConfig(dim=8, learning_rate=0.1),
                         mesh=mesh,
                         config=TrainerConfig(dense_learning_rate=3e-3,
                                              auc_num_buckets=1 << 12))
    trainer.init(seed=0)
    return trainer, feed


def test_mixed_width_training_learns(shards):
    trainer, feed = _make_trainer()
    # Two width groups: dim 8 (narrow_a, narrow_b) and dim 32 (wide).
    assert trainer.engine.dims == [8, 32]
    assert trainer.engine.groups[0].slots == ("narrow_a", "narrow_b")
    assert trainer.engine.groups[1].slots == ("wide",)

    ds = Dataset(feed, num_reader_threads=2)
    ds.set_filelist(shards)
    ds.load_into_memory()
    stats = []
    for p in range(3):
        trainer.reset_metrics()
        ds.local_shuffle(seed=p)
        stats.append(trainer.train_pass(ds))
    for s in stats:
        assert np.isfinite(s["loss"])
    assert stats[-1]["auc"] > 0.65, [s["auc"] for s in stats]

    # Each width group persisted its own features at its own width.
    g8, g32 = trainer.engine.groups
    assert g8.engine.store.config.dim == 8
    assert g32.engine.store.config.dim == 32
    assert g8.engine.store.num_features > 50
    assert g32.engine.store.num_features > 50


def test_mixed_width_checkpoint_roundtrip(shards, tmp_path):
    trainer, feed = _make_trainer()
    ds = Dataset(feed, num_reader_threads=2)
    ds.set_filelist(shards)
    ds.load_into_memory()
    trainer.train_pass(ds)
    base = str(tmp_path / "base")
    trainer.engine.store.save_base(base)
    # Per-group subdirs so widths stay separate on disk.
    assert os.path.isdir(os.path.join(base, "dim8"))
    assert os.path.isdir(os.path.join(base, "dim32"))

    t2, _ = _make_trainer()
    t2.engine.store.load(base, "base")
    assert (t2.engine.store.num_features
            == trainer.engine.store.num_features)
    # Restored widths intact end-to-end: another pass trains fine.
    stats = t2.train_pass(ds)
    assert np.isfinite(stats["loss"])


def test_grouped_engine_rejects_store_instance_for_multi_width():
    feed = _feed()
    mesh = build_mesh(HybridTopology(dp=8))
    model = DeepFM(slot_names=SLOTS, emb_dim=DIMS, hidden=(32, 16))
    from paddlebox_tpu.embedding import FeatureStore
    store = FeatureStore(TableConfig(dim=8))
    with pytest.raises(ValueError, match="store_factory"):
        CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh, store=store)


def test_grouped_store_shrink_and_stats(shards):
    trainer, feed = _make_trainer()
    ds = Dataset(feed, num_reader_threads=2)
    ds.set_filelist(shards)
    ds.load_into_memory()
    trainer.train_pass(ds)
    store = trainer.engine.store
    n = store.num_features
    assert n > 0
    evicted = store.shrink(min_show=1e9)  # evict everything
    assert evicted == n
    assert store.num_features == 0
