"""The production serving tier (r14): ragged micro-batched predict must
be bit-identical per request to one-at-a-time dispatch, every batch must
see exactly one model version under concurrent hot-swaps, the
hierarchical HBM/host/ssd cache must serve values identical to an
uncached predictor, and the donefile publisher must land a delta under
live load with zero failed RPCs."""

import os
import threading
import time

import numpy as np
import pytest

import jax

from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
from paddlebox_tpu.core import faults, flags as flagmod, monitor
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch, SlotConf
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.serving import (CTRPredictor, DonefilePublisher,
                                   MicroBatcher, PredictClient,
                                   PredictServer, pack_bucketed)
from paddlebox_tpu.serving.batcher import bucket_capacities, pow2_bucket

SLOTS = ("u", "i")
N_KEYS = 500


def _feed(bs=64):
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=2.0) for s in SLOTS),
        batch_size=bs)


def _predictor(rng, feed, scale=0.01, **kw):
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,))
    keys = np.arange(1, N_KEYS + 1, dtype=np.uint64)
    emb = rng.normal(size=(N_KEYS, 8)).astype(np.float32) * scale
    w = rng.normal(size=(N_KEYS,)).astype(np.float32) * scale
    dense = model.init(jax.random.PRNGKey(0))
    pred = CTRPredictor(model, feed, keys, emb, w, dense,
                        compute_dtype="float32", **kw)
    return pred, (keys, emb, w, dense, model)


def _lines(rng, n, lo=1, hi=N_KEYS + 100):
    # hi past N_KEYS: some unknown feasigns ride along (zero rows).
    return ["0 " + " ".join(f"{s}:{rng.integers(lo, hi)}" for s in SLOTS)
            for _ in range(n)]


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset()
    faults.clear()
    try:
        yield
    finally:
        faults.clear()
        flagmod.set_flags({"fault_spec": ""})
        monitor.reset()


# ---------------------------------------------------------------------------
# micro-batch parity
# ---------------------------------------------------------------------------

def test_microbatch_parity_mixed_sizes_and_buckets():
    """Coalescing requests of mixed sizes into one packed forward gives
    BIT-identical per-request probabilities to dispatching each request
    alone — across row buckets (1..31 rows span three pow2 buckets) and
    the capacity buckets they imply."""
    rng = np.random.default_rng(3)
    feed = _feed()
    pred, _ = _predictor(rng, feed)
    try:
        sizes = (1, 2, 3, 7, 8, 9, 15, 16, 31)
        reqs = [parse_lines(_lines(rng, m), feed) for m in sizes]
        serial = [np.asarray(pred.predict(pack_bucketed(r, feed))[:len(r)])
                  for r in reqs]
        flat = [i for r in reqs for i in r]
        coalesced = np.asarray(pred.predict(pack_bucketed(flat, feed)))
        off = 0
        for r, want in zip(reqs, serial):
            got = coalesced[off:off + len(r)]
            off += len(r)
            np.testing.assert_array_equal(got, want)
    finally:
        pred.close()


def test_pack_bucketed_masks_padding_no_fake_lines():
    """Padding is masked rows, not synthesized '0' svm lines: the
    packed batch has exactly n valid rows, pads carry the discard
    segment, and shapes are pow2 buckets."""
    rng = np.random.default_rng(5)
    feed = _feed()
    ins = parse_lines(_lines(rng, 5), feed)
    batch = pack_bucketed(ins, feed)
    assert batch.batch_size == 8                 # pow2 row bucket
    assert batch.num_valid == 5                  # no fake label-0 rows
    assert not batch.valid[5:].any()
    for s in SLOTS:
        cap = batch.ids[s].shape[0]
        assert cap == pow2_bucket(feed.sparse_capacity(
            [c for c in feed.sparse_slots if c.name == s][0], 8))
        # pad cells point at the discard row (batch_size), never a real
        # row
        used = int(batch.lengths[s].sum())
        assert (batch.segments[s][used:] == batch.batch_size).all()
    caps = bucket_capacities(feed, 8)
    assert all(caps[s] == batch.ids[s].shape[0] for s in SLOTS)


def test_fwd_trace_cache_stays_bounded():
    """The pow2 ladder bounds the jitted-forward cache: many distinct
    request sizes collapse onto <= log2(max rows) traces (the exact-
    shape cache grew one entry per distinct request mix)."""
    rng = np.random.default_rng(7)
    feed = _feed()
    pred, _ = _predictor(rng, feed)
    try:
        for m in range(1, 40):
            pred.predict(pack_bucketed(parse_lines(
                _lines(rng, m), feed), feed))
        # rows buckets hit: 8, 16, 32, 64 -> at most 4 traces
        assert len(pred._fwd_cache) <= 4
    finally:
        pred.close()


def test_batcher_coalesces_concurrent_requests():
    """Concurrent submitters coalesce: N threads blocked on the window
    land in fewer dispatches than requests, with per-request results
    identical to solo dispatch."""
    rng = np.random.default_rng(9)
    feed = _feed()
    pred, _ = _predictor(rng, feed)
    prev = flagmod.flag("serving_batch_window_ms")
    flagmod.set_flags({"serving_batch_window_ms": 50.0})
    batcher = MicroBatcher(pred)
    try:
        reqs = [parse_lines(_lines(rng, m), feed)
                for m in (3, 5, 7, 9, 11, 2, 4, 6)]
        want = [np.asarray(pred.predict(
            pack_bucketed(r, feed))[:len(r)]) for r in reqs]
        got = [None] * len(reqs)

        def run(i):
            got[i] = batcher.predict(reqs[i])

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)
        snap = monitor.snapshot()
        assert snap["serving/batch_requests"] == len(reqs)
        assert snap["serving/batches"] < len(reqs)  # real coalescing
        assert monitor.snapshot_all()["gauges"][
            "serving/batch_fill_frac"] > 0.0
    finally:
        flagmod.set_flags({"serving_batch_window_ms": prev})
        batcher.close()
        pred.close()


def test_batch_dispatch_fault_fails_batch_not_batcher():
    """A fault inside one dispatch surfaces to that batch's callers and
    the batcher keeps serving the next request (error containment for
    the shared dispatcher thread)."""
    rng = np.random.default_rng(11)
    feed = _feed()
    pred, _ = _predictor(rng, feed)
    batcher = MicroBatcher(pred)
    try:
        ins = parse_lines(_lines(rng, 4), feed)
        faults.configure("serving/batch_dispatch:times=1:raise=RuntimeError")
        with pytest.raises(RuntimeError):
            batcher.predict(ins)
        out = batcher.predict(ins)  # the batcher thread survived
        assert out.shape == (4,)
    finally:
        batcher.close()
        pred.close()


# ---------------------------------------------------------------------------
# model-version consistency under hot-swap
# ---------------------------------------------------------------------------

def test_every_batch_sees_exactly_one_model_version():
    """Threaded predict vs apply_update: all embedding rows carry one
    constant per model version and every request row holds one id per
    slot, so a request mixing versions would return mixed
    probabilities. Every returned request must be pure v1 or pure v2."""
    rng = np.random.default_rng(13)
    feed = _feed()
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=())
    keys = np.arange(1, 101, dtype=np.uint64)

    def version_arrays(c):
        return (np.full((100, 4), c, np.float32),
                np.full((100,), c, np.float32))

    e1, w1 = version_arrays(0.01)
    e2, w2 = version_arrays(0.03)
    dense = model.init(jax.random.PRNGKey(1))
    pred = CTRPredictor(model, feed, keys, e1, w1, dense,
                        compute_dtype="float32")
    batcher = MicroBatcher(pred)
    lines = ["0 " + " ".join(f"{s}:{rng.integers(1, 100)}"
                             for s in SLOTS) for _ in range(8)]
    ins = parse_lines(lines, feed)
    p1 = np.asarray(pred.predict(pack_bucketed(ins, feed))[:8])
    pred.apply_update(keys, e2, w2)
    p2 = np.asarray(pred.predict(pack_bucketed(ins, feed))[:8])
    # constant-per-version by construction
    assert np.unique(p1).size == 1 and np.unique(p2).size == 1
    assert p1[0] != p2[0]
    pred.apply_update(keys, e1, w1)

    stop = threading.Event()
    torn = []
    errors = []

    def reader():
        try:
            while not stop.is_set():
                out = np.asarray(batcher.predict(ins))
                if not (np.array_equal(out, p1)
                        or np.array_equal(out, p2)):
                    torn.append(out.copy())
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        for flip in range(6):
            if flip % 2 == 0:
                pred.apply_update(keys, e2, w2)
            else:
                pred.apply_update(keys, e1, w1)
            time.sleep(0.02)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        batcher.close()
        pred.close()
    assert not errors
    assert not torn  # no request ever saw two versions


# ---------------------------------------------------------------------------
# hierarchical cache tiers
# ---------------------------------------------------------------------------

def test_cache_tiers_serve_identical_values(tmp_path):
    """A table larger than FLAGS_serving_hbm_rows serves THROUGH the
    host and ssd tiers with probabilities bit-identical to a predictor
    holding everything in HBM — and the batch actually exercised every
    tier (hit counters)."""
    rng = np.random.default_rng(17)
    feed = _feed()
    flat, (keys, emb, w, dense, model) = _predictor(rng, feed)
    tiered = CTRPredictor(model, feed, keys, emb, w, dense,
                          compute_dtype="float32", hbm_rows=64,
                          host_cache_rows=128,
                          cache_dir=str(tmp_path / "cold"))
    try:
        batch = pack_bucketed(parse_lines(_lines(rng, 48), feed), feed)
        want = np.asarray(flat.predict(batch))
        got = np.asarray(tiered.predict(batch))
        np.testing.assert_array_equal(got, want)
        snap = monitor.snapshot()
        assert snap["serving/cache_hbm_hits"] > 0
        assert snap["serving/cache_host_hits"] > 0
        assert snap["serving/cache_ssd_hits"] > 0  # 500-64-128 on disk
        # Promotion moves the observed hot set HBM-ward and changes no
        # served value.
        for _ in range(3):
            tiered.predict(batch)
        assert tiered.promote_now() > 0
        assert monitor.snapshot()["serving/cache_promoted"] > 0
        np.testing.assert_array_equal(
            np.asarray(tiered.predict(batch)), want)
    finally:
        tiered.close()
        flat.close()


def test_tiered_apply_update_routes_every_tier(tmp_path):
    """A delta spanning hot, warm, cold, and NEW keys lands correctly in
    the tiered table: post-update predictions equal a flat predictor
    given the same delta, and the new-key count matches."""
    rng = np.random.default_rng(19)
    feed = _feed()
    flat, (keys, emb, w, dense, model) = _predictor(rng, feed)
    tiered = CTRPredictor(model, feed, keys, emb, w, dense,
                          compute_dtype="float32", hbm_rows=64,
                          host_cache_rows=128,
                          cache_dir=str(tmp_path / "cold"))
    try:
        # touch some rows so the hot tier is exercised before updating
        warm_batch = pack_bucketed(parse_lines(_lines(rng, 32), feed),
                                   feed)
        tiered.predict(warm_batch)
        ku = np.concatenate([
            np.arange(1, 33, dtype=np.uint64),        # hot tier
            np.arange(100, 150, dtype=np.uint64),     # warm/cold mix
            np.arange(400, 480, dtype=np.uint64),     # cold tier
            np.arange(600, 620, dtype=np.uint64),     # new keys
        ])
        eu = rng.normal(size=(ku.shape[0], 8)).astype(np.float32) * 0.02
        wu = rng.normal(size=(ku.shape[0],)).astype(np.float32) * 0.02
        n_flat = flat.apply_update(ku, eu, wu)
        n_tier = tiered.apply_update(ku, eu, wu)
        assert n_flat == n_tier == 20
        assert tiered.num_keys == flat.num_keys == N_KEYS + 20
        q = pack_bucketed(parse_lines(_lines(rng, 48, 1, 650), feed),
                          feed)
        np.testing.assert_array_equal(np.asarray(tiered.predict(q)),
                                      np.asarray(flat.predict(q)))
    finally:
        tiered.close()
        flat.close()


# ---------------------------------------------------------------------------
# hot-swap drill: publisher under live wire load
# ---------------------------------------------------------------------------

def _write_delta(proto, day, pass_id, table, keys, emb, w):
    mdir = proto.model_dir(day, pass_id)
    with open(os.path.join(mdir, f"{table}.delta.npz"), "wb") as f:
        np.savez(f, keys=keys, emb=emb, w=w)
    assert proto.publish(day, pass_id)


def test_hotswap_drill_publisher_under_live_load(tmp_path):
    """The zero-downtime drill: a donefile publisher applies per-pass
    deltas while 8 client threads predict over the wire — zero failed
    RPCs, no torn reads (every reply is a pure model version), and the
    final state matches the last delta."""
    rng = np.random.default_rng(23)
    feed = _feed()
    model = DeepFM(slot_names=SLOTS, emb_dim=4, hidden=())
    keys = np.arange(1, 101, dtype=np.uint64)
    consts = [0.01, 0.02, 0.03, 0.04]

    def version_arrays(c):
        return (np.full((100, 4), c, np.float32),
                np.full((100,), c, np.float32))

    dense = model.init(jax.random.PRNGKey(2))
    e0, w0 = version_arrays(consts[0])
    pred = CTRPredictor(model, feed, keys, e0, w0, dense,
                        compute_dtype="float32")
    lines = ["0 " + " ".join(f"{s}:{rng.integers(1, 100)}"
                             for s in SLOTS) for _ in range(8)]
    ins = parse_lines(lines, feed)
    version_probs = []
    for c in consts:
        e, w = version_arrays(c)
        pred.apply_update(keys, e, w)
        p = np.asarray(pred.predict(pack_bucketed(ins, feed))[:8])
        assert np.unique(p).size == 1
        version_probs.append(p)
    e, w = version_arrays(consts[0])
    pred.apply_update(keys, e, w)  # back to v0

    root = str(tmp_path / "ckpt")
    proto = CheckpointProtocol(root)
    server = PredictServer("127.0.0.1:0", pred, watch_root=root,
                           watch_table="emb")
    stop = threading.Event()
    failures = []
    torn = []

    def client():
        cli = PredictClient(server.endpoint)
        try:
            while not stop.is_set():
                out = np.asarray(cli.predict(lines))
                if out.shape != (8,):
                    failures.append(("shape", out.shape))
                if not any(np.array_equal(out, vp)
                           for vp in version_probs):
                    torn.append(out.copy())
        except Exception as e:
            failures.append(("rpc", repr(e)))
        finally:
            cli.close()

    threads = [threading.Thread(target=client) for _ in range(8)]
    try:
        for t in threads:
            t.start()
        # publish three deltas while the fleet predicts
        for i, c in enumerate(consts[1:], start=1):
            e, w = version_arrays(c)
            _write_delta(proto, "20260804", i, "emb", keys, e, w)
            time.sleep(0.05)
        deadline = time.time() + 20
        while time.time() < deadline:
            if server._publisher.applied >= 3:
                break
            time.sleep(0.05)
        assert server._publisher.applied == 3
        time.sleep(0.1)  # a few more predicts on the final version
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        server.stop()
        pred.close()
    assert not failures  # zero failed/dropped RPCs
    assert not torn      # every reply was a pure version
    assert monitor.snapshot().get("serving/hotswap_applied", 0) == 3
    # final state serves the LAST delta
    final = np.asarray(pred.predict(pack_bucketed(ins, feed))[:8])
    np.testing.assert_array_equal(final, version_probs[-1])


def test_publisher_skips_bad_delta_and_continues(tmp_path):
    """A torn/unreadable published delta is counted, skipped forward,
    and does not stop later deltas from applying (no retry spin)."""
    rng = np.random.default_rng(29)
    feed = _feed()
    pred, (keys, emb, w, dense, model) = _predictor(rng, feed)
    root = str(tmp_path / "ckpt")
    proto = CheckpointProtocol(root)
    pub = DonefilePublisher(pred, root, table="emb")
    try:
        # pass 1: published record whose delta file is missing
        proto.model_dir("d", 1)
        assert proto.publish("d", 1)
        # pass 2: a well-formed delta
        ku = np.arange(600, 650, dtype=np.uint64)
        _write_delta(proto, "d", 2, "emb", ku,
                     rng.normal(size=(50, 8)).astype(np.float32),
                     rng.normal(size=(50,)).astype(np.float32))
        assert pub.poll_once() == 1
        assert pub.errors == 1 and pub.applied == 1
        assert pred.num_keys == N_KEYS + 50
        assert pub.poll_once() == 0  # both records consumed, no respin
    finally:
        pub.stop()
        pred.close()


# ---------------------------------------------------------------------------
# sliding-window throughput
# ---------------------------------------------------------------------------

def test_throughput_rps_sliding_window_decays_to_zero():
    """The stats-RPC throughput gauge is a sliding window
    (LogQuantileDigest.delta counts), not lifetime count / lifetime
    uptime: an idle replica reads 0 within two windows instead of a
    forever-decaying stale rate."""
    rng = np.random.default_rng(31)
    feed = _feed(bs=8)
    pred, _ = _predictor(rng, feed)
    prev = flagmod.flag("serving_rps_window_s")
    flagmod.set_flags({"serving_rps_window_s": 0.2})
    server = PredictServer("127.0.0.1:0", pred)
    cli = PredictClient(server.endpoint)
    try:
        lines = _lines(rng, 8)
        for _ in range(5):
            cli.predict(lines)
        st = cli.stats()
        assert st["throughput_rps"] > 0.0
        assert st["latency_count"] == 5
        time.sleep(0.25)
        cli.stats()          # rotates the window once
        time.sleep(0.25)
        st3 = cli.stats()    # second rotation: idle window
        assert st3["throughput_rps"] == 0.0
        # the lifetime-average bug would still report > 0 here
        assert st3["latency_count"] == 5
    finally:
        flagmod.set_flags({"serving_rps_window_s": prev})
        cli.stop_server()
        cli.close()
        server.stop()
        pred.close()
