"""Replicated shard tier (MULTIHOST.md "replicated tier").

Pins, tier-1 (CPU, loopback sockets — the wire is real, the hosts are
in-process):

- replica placement: ring map invariants (distinct hosts, promotion
  drop, repair add), dict round-trip;
- DeltaJournal: seq assignment, since() windows, cap eviction → None
  (snapshot required), reset;
- replica consistency: a replicas=2 cluster's pulls/pushes are
  BIT-identical to replicas=1 AND to a flat FeatureStore, and every
  backup's slot store is byte-identical to its primary's after
  synchronous forwarding;
- journal catch-up vs full-COPY equivalence: a rebuilt backup caught up
  by journal replay has the same content digest as one caught up by
  full snapshot (journal disabled);
- stale-primary loud failure: a write reaching a backup raises a LOUD
  StalePrimaryError that the pass-retry loop classifies TRANSIENT;
- read failover: kill a primary — pulls (trainer) and pull_serving
  (ShardBackedStore) fail over to the surviving backup with identical
  bytes and zero failed calls;
- promote + repair 2→2: kill one host of a replicated pair, promote
  the survivor, re-replicate to a fresh host — content digests equal
  the pre-kill state and the replication factor is restored;
- checkpoint round-trip at R=2: save writes one hostshard dir per
  PRIMARY slot (no double rows), load restores a fully replicated
  cluster, and the ages sidecar survives.
"""

import hashlib

import numpy as np
import pytest

from paddlebox_tpu.core import faults
from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.embedding.store import _FIELDS, FeatureStore
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost import (DeltaJournal, MultiHostStore,
                                     ReplicaMap, ShardClient,
                                     ShardRangeTable, StalePrimaryError,
                                     start_local_shards, stop_shards)
from paddlebox_tpu.multihost.shard_service import ShardServer

CFG = TableConfig(name="emb", dim=8, learning_rate=0.1)


def _rand_keys(n, seed=0, hi=1 << 50):
    rng = np.random.default_rng(seed)
    keys = np.unique(rng.integers(1, hi, size=n + 64, dtype=np.uint64))
    assert keys.size >= n
    return keys[:n]


def _store_digest(store: FeatureStore) -> str:
    keys, _ = store.key_stats()
    keys = np.sort(keys)
    vals = store.pull_for_pass(keys)
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(keys).tobytes())
    for f in _FIELDS:
        h.update(np.ascontiguousarray(vals[f]).tobytes())
    return h.hexdigest()


@pytest.fixture
def pair():
    """2-host replicas=2 loopback cluster + its client store."""
    servers, eps = start_local_shards(2, CFG, replicas=2)
    store = MultiHostStore(CFG, eps, replicas=2)
    yield servers, eps, store
    store.close()
    stop_shards(servers)


# ---------------------------------------------------------------------------
# ReplicaMap / DeltaJournal units
# ---------------------------------------------------------------------------

def test_ring_map_invariants_and_roundtrip():
    eps = ["h0:1", "h1:1", "h2:1"]
    m = ReplicaMap.ring(eps, 2)
    assert m.world == 3 and m.replication == 2
    assert m.primaries() == eps
    assert m.replicas_of(0) == ("h0:1", "h1:1")
    assert m.replicas_of(2) == ("h2:1", "h0:1")
    assert m.slots_of("h1:1") == {1: "primary", 0: "backup"}
    # R is clamped to the world: 2 hosts cannot hold 3 distinct copies.
    assert ReplicaMap.ring(eps[:2], 3).replication == 2
    assert ReplicaMap.from_dict(m.to_dict()) == m
    # Promotion: dropping h1 everywhere promotes slot 1 to its backup.
    d = m.drop_endpoint("h1:1")
    assert d.primaries() == ["h0:1", "h2:1", "h2:1"]
    assert d.replication == 1
    # Repair: a fresh host restores the factor slot by slot.
    r = d.add_backup(0, "h3:1")
    assert r.replicas_of(0) == ("h0:1", "h3:1")
    assert r.add_backup(0, "h3:1") is r        # idempotent
    with pytest.raises(ValueError, match="no surviving replica"):
        ReplicaMap.ring(["a:1"], 1).drop_endpoint("a:1")


def test_delta_journal_windows_and_cap():
    j = DeltaJournal(cap=4)
    seqs = [j.append("push", {"i": i}) for i in range(3)]
    assert seqs == [1, 2, 3] and j.seq == 3
    assert j.since(3) == []
    assert [e.seq for e in j.since(1)] == [2, 3]
    assert [e.seq for e in j.since(0)] == [1, 2, 3]
    for i in range(3):
        j.append("push", {"i": 3 + i})          # seqs 4..6, cap 4
    assert [e.seq for e in j.since(2)] == [3, 4, 5, 6]
    assert j.since(1) is None                   # past the window: snapshot
    j2 = DeltaJournal(cap=0, start_seq=7)       # journaling disabled
    assert j2.append("push", {}) == 8
    assert j2.since(7) is None and len(j2) == 0
    j.reset(start_seq=5)
    assert j.seq == 5 and j.since(5) == []


# ---------------------------------------------------------------------------
# replica consistency
# ---------------------------------------------------------------------------

def test_replicated_pulls_bit_identical_to_flat_and_r1(pair):
    servers, eps, store = pair
    s1, e1 = start_local_shards(2, CFG)          # replicas=1 reference
    r1 = MultiHostStore(CFG, e1)
    flat = FeatureStore(CFG, seed=0)
    try:
        keys = _rand_keys(3000, seed=1)
        a = store.pull_for_pass(keys)
        b = r1.pull_for_pass(keys)
        c = flat.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(a[f], b[f], err_msg=f)
            np.testing.assert_array_equal(a[f], c[f], err_msg=f)
        a["emb"] += 0.5
        a["show"] += 1.0
        for tgt in (store, r1, flat):
            tgt.push_from_pass(keys, a)
        assert store.num_features == r1.num_features == keys.size
        sub = keys[::3]
        g = store.pull_for_pass(sub)
        g2 = flat.pull_for_pass(sub)
        for f in _FIELDS:
            np.testing.assert_array_equal(g[f], g2[f], err_msg=f)
    finally:
        r1.close()
        stop_shards(s1)


def test_backups_byte_identical_after_sync_forwarding(pair):
    servers, eps, store = pair
    keys = _rand_keys(2000, seed=2)
    rows = store.pull_for_pass(keys)
    rows["emb"] += 1.25
    store.push_from_pass(keys, rows)
    store.push_from_pass(keys, rows)             # second pass (seq 2)
    # Each server is primary of its own slot and backup of the other:
    # the backup's slot store must hold the primary's exact bytes.
    for slot in (0, 1):
        prim = servers[slot]._slot_stores[slot]
        back = servers[1 - slot]._slot_stores[slot]
        assert _store_digest(prim) == _store_digest(back)
        np.testing.assert_array_equal(
            prim.unseen_for(keys), back.unseen_for(keys))
    st = servers[0].handle_replica_status({})
    assert st["replication"] == 2
    assert st["slots"]["0"]["role"] == "primary"
    assert st["slots"]["1"]["role"] == "backup"
    assert st["slots"]["0"]["backups"][eps[1]] == st["slots"]["0"]["seq"]


def test_slot_columns_ride_replication_like_values(pair):
    """FLAGS_table_slot_placement is device-side PLACEMENT only: the
    replication wire and journal carry the full LOGICAL row, so the
    optimizer slot columns (emb_state/w_state) forward to backups
    bit-identically with the values — never re-derived, never dropped.
    (That's what lets a device store under split/host rehydrate exact
    slot state from any replica after failover.)"""
    servers, eps, store = pair
    keys = _rand_keys(800, seed=11)
    rows = store.pull_for_pass(keys)
    rng = np.random.default_rng(11)
    rows["emb_state"] = rng.normal(
        size=rows["emb_state"].shape).astype(np.float32)
    rows["w_state"] = rng.normal(
        size=rows["w_state"].shape).astype(np.float32)
    store.push_from_pass(keys, rows)
    for slot in (0, 1):
        prim = servers[slot]._slot_stores[slot]
        back = servers[1 - slot]._slot_stores[slot]
        pk, _ = prim.key_stats()
        pk = np.sort(pk)
        if not pk.size:
            continue
        pv, bv = prim.pull_for_pass(pk), back.pull_for_pass(pk)
        for f in ("emb_state", "w_state"):
            assert np.asarray(pv[f]).any(), f"{f} all-zero: vacuous"
            np.testing.assert_array_equal(np.asarray(pv[f]),
                                          np.asarray(bv[f]), err_msg=f)


def test_replicated_shrink_forwards_resolved_policy(pair):
    servers, eps, store = pair
    keys = _rand_keys(500, seed=3)
    rows = store.pull_for_pass(keys)
    rows["show"] += 4.0
    store.push_from_pass(keys, rows)
    prev = flagmod.get_flags(["table_ttl_days"])
    try:
        flagmod.set_flags({"table_ttl_days": 2})
        store.shrink()
    finally:
        flagmod.set_flags(prev)
    for slot in (0, 1):
        prim = servers[slot]._slot_stores[slot]
        back = servers[1 - slot]._slot_stores[slot]
        assert _store_digest(prim) == _store_digest(back)
        # Ages bumped identically on both replicas.
        pk, _ = prim.key_stats()
        if pk.size:
            np.testing.assert_array_equal(prim.unseen_for(pk),
                                          back.unseen_for(pk))
            assert (prim.unseen_for(pk) == 1).all()


# ---------------------------------------------------------------------------
# journal catch-up vs full-COPY equivalence
# ---------------------------------------------------------------------------

def _rebind_backup(servers, eps, slot_of_backup: int):
    """Kill the backup host and stand an EMPTY server up on the same
    endpoint (the 'briefly disconnected backup returns' scenario)."""
    old = servers[slot_of_backup]
    ep = eps[slot_of_backup]
    old.kill()
    fresh = ShardServer(ep, slot_of_backup,
                        ShardRangeTable.for_world(len(eps)), CFG)
    assert fresh.endpoint == ep
    return fresh


@pytest.mark.parametrize("journal_entries", [256, 0],
                         ids=["journal", "snapshot"])
def test_backup_catchup_journal_vs_snapshot(journal_entries):
    """A returned-empty backup is caught up by journal replay (cap
    covers the gap... except a fresh store needs the snapshot) and by
    forced snapshot (cap=0) — both land the primary's exact bytes, and
    a SECOND push after a small lag exercises the pure journal-delta
    path when enabled."""
    prev = flagmod.get_flags(["multihost_journal_entries"])
    flagmod.set_flags({"multihost_journal_entries": journal_entries})
    servers, eps = start_local_shards(2, CFG, replicas=2)
    store = MultiHostStore(CFG, eps, replicas=2)
    fresh = None
    try:
        keys = _rand_keys(1500, seed=4)
        rows = store.pull_for_pass(keys)
        rows["w"] += 2.0
        store.push_from_pass(keys, rows)

        # Backup of slot 0 is host 1: replace it with an empty process.
        fresh = _rebind_backup(servers, eps, 1)
        rmap = ReplicaMap.ring(eps, 2)
        fresh.adopt_replica_map(rmap)

        # Next mutation triggers catch-up (snapshot: the fresh store's
        # seq 0 is past any journal window), then applies the new seq.
        rows["w"] += 1.0
        store.push_from_pass(keys, rows)
        prim0 = servers[0]._slot_stores[0]
        assert _store_digest(prim0) == _store_digest(
            fresh._slot_stores[0])

        # Lag the backup by ONE entry while reachable-again: with a
        # journal this catches up by delta replay, without one by
        # another snapshot — equivalence is the digest.
        before = (fresh._applied_seq[0],
                  len(servers[0]._journals[0]))
        rows["w"] += 1.0
        store.push_from_pass(keys, rows)
        assert _store_digest(prim0) == _store_digest(
            fresh._slot_stores[0])
        assert fresh._applied_seq[0] > before[0]
        if journal_entries:
            assert len(servers[0]._journals[0]) > 0
        else:
            assert len(servers[0]._journals[0]) == 0
    finally:
        flagmod.set_flags(prev)
        store.close()
        stop_shards(servers + ([fresh] if fresh else []))


def test_brief_disconnect_catches_up_with_journal_deltas(pair):
    """The canonical journal story: a backup whose CONNECTION bounced
    (host alive, socket dropped) misses one forward and is caught up by
    delta replay on the same mutation — never a full snapshot."""
    from paddlebox_tpu.core import monitor
    servers, eps, store = pair
    keys = _rand_keys(900, seed=9)
    rows = store.pull_for_pass(keys)
    rows["click"] += 1.0
    store.push_from_pass(keys, rows)
    snaps0 = monitor.GLOBAL.get("multihost/replica_snapshots")
    # Sever host 1's established conns (it keeps listening): the next
    # forward's direct send bounces, the in-line catch-up reconnects
    # and replays the journal gap.
    servers[1].close_connections()
    rows["click"] += 1.0
    store.push_from_pass(keys, rows)
    assert monitor.GLOBAL.get("multihost/replica_snapshots") == snaps0
    for slot in (0, 1):
        prim = servers[slot]._slot_stores[slot]
        back = servers[1 - slot]._slot_stores[slot]
        assert _store_digest(prim) == _store_digest(back)


# ---------------------------------------------------------------------------
# stale-primary loud failure
# ---------------------------------------------------------------------------

def test_write_to_backup_is_loud_and_transient(pair):
    servers, eps, store = pair
    keys = _rand_keys(400, seed=5)
    rows = store.pull_for_pass(keys)
    owner = store.ranges.owner_of(keys)
    slot0 = keys[owner == 0]
    vals0 = {f: v[owner == 0] for f, v in rows.items()}
    # Raw push of slot-0 keys to host 1 (its BACKUP): loud in-band.
    c = ShardClient(eps[1])
    try:
        with pytest.raises(RuntimeError, match="STALE_PRIMARY"):
            c.call("push", keys=slot0, values=vals0)
    finally:
        c.close()
    # Through the client store with a stale (swapped-primary) map: the
    # typed transient error the pass-retry loop understands.
    stale = ReplicaMap(table=store.ranges,
                       assignment=((eps[1], eps[0]), (eps[1], eps[0])))
    bad = MultiHostStore(CFG, eps, replica_map=stale)
    try:
        with pytest.raises(StalePrimaryError) as ei:
            bad.push_from_pass(keys, rows)
        assert faults.is_transient(ei.value)
    finally:
        bad.close()


# ---------------------------------------------------------------------------
# read failover + promote/repair
# ---------------------------------------------------------------------------

def test_read_failover_and_promote_repair_restores_r(pair):
    from paddlebox_tpu.serving.fleet import ShardBackedStore
    servers, eps, store = pair
    keys = _rand_keys(2500, seed=6)
    rows = store.pull_for_pass(keys)
    rows["emb"] += 0.75
    store.push_from_pass(keys, rows)
    ref = {f: rows[f].copy() for f in _FIELDS}

    backed = ShardBackedStore(eps, CFG.dim,
                              replica_map=store.replica_map)
    found, fused = backed.read(keys)
    assert found.all()
    np.testing.assert_array_equal(fused[:, :CFG.dim], ref["emb"])

    # Kill host 1 (primary of slot 1, backup of slot 0).
    servers[1].kill()

    # Pure reads fail over to the survivor's replica store — identical
    # bytes, zero failed calls.
    got = store.pull_for_pass(keys)
    for f in _FIELDS:
        np.testing.assert_array_equal(got[f], ref[f], err_msg=f)
    found2, fused2 = backed.read(keys)
    assert found2.all()
    np.testing.assert_array_equal(fused2, fused)

    # PROMOTE: drop the dead endpoint; the survivor leads both slots.
    rmap = store.replica_map.drop_endpoint(eps[1])
    servers[0].adopt_replica_map(rmap)
    store.set_replica_map(rmap)
    backed.set_replica_map(rmap)
    assert rmap.replication == 1
    got = store.pull_for_pass(keys)
    for f in _FIELDS:
        np.testing.assert_array_equal(got[f], ref[f], err_msg=f)
    # Writes land on the promoted primary (no stale error).
    got["click"] += 1.0
    store.push_from_pass(keys, got)
    ref = got

    # REPAIR: fresh host re-replicates both slots — factor restored.
    fresh = ShardServer("127.0.0.1:0", 0, store.ranges, CFG)
    try:
        r2 = rmap.add_backup(0, fresh.endpoint).add_backup(
            1, fresh.endpoint)
        assert r2.replication == 2
        for s in (servers[0], fresh):
            s.adopt_replica_map(r2)
        store.set_replica_map(r2)
        synced = store.sync_replicas()
        assert set(synced) == {0, 1}
        for slot in (0, 1):
            assert synced[slot][fresh.endpoint] >= 0
            assert _store_digest(servers[0]._slot_stores[slot]) == \
                _store_digest(fresh._slot_stores[slot])
        # The re-replicated backup now serves reads after the promoted
        # host dies too — the 2→2 repair kept every byte.
        servers[0].kill()
        got = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], ref[f], err_msg=f)
    finally:
        backed.close()
        fresh.stop()


def test_controller_repair_probe_promotes_and_reraises_factor(tmp_path):
    """ElasticReshardController.repair() (the pass-retry hook) probes
    endpoints and promotes off the dead one; _maybe_repair (the
    boundary hook) folds a fresh advertised host back in."""
    from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
    from paddlebox_tpu.launch.elastic import RankTable
    from paddlebox_tpu.multihost.reshard import ElasticReshardController

    servers, eps = start_local_shards(2, CFG, replicas=2)
    store = MultiHostStore(CFG, eps, replicas=2)
    fresh = None
    try:
        keys = _rand_keys(1200, seed=7)
        rows = store.pull_for_pass(keys)
        rows["show"] += 1.0
        store.push_from_pass(keys, rows)
        ckpt = CheckpointProtocol(str(tmp_path / "out"))
        tables = {"t": RankTable(generation=0, hosts=["a", "b"],
                                 meta={"a": {"shard_endpoint": eps[0]},
                                       "b": {"shard_endpoint": eps[1]}})}
        ctl = ElasticReshardController(store, ckpt,
                                       table_fn=lambda: tables["t"])
        assert ctl.maybe_apply("d", 1) is None       # anchors gen 0
        assert ctl.repair() is None                  # everyone alive

        servers[1].kill()
        rec = ctl.repair(reason="drill")
        assert rec is not None and rec["kind"] == "promote"
        assert rec["replication"] == 1 and rec["promoted"] == [1]
        got = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], rows[f], err_msg=f)

        # Boundary: the rank table drops the dead host and advertises a
        # fresh one — re-replication restores the factor.
        fresh = ShardServer("127.0.0.1:0", 0, store.ranges, CFG)
        tables["t"] = RankTable(
            generation=1, hosts=["a", "c"],
            meta={"a": {"shard_endpoint": eps[0]},
                  "c": {"shard_endpoint": fresh.endpoint}})
        rec2 = ctl.maybe_apply("d", 2)
        assert rec2 is not None and rec2["kind"] == "repair"
        assert rec2["replication"] == 2
        assert store.replica_map.replication == 2
        for slot in (0, 1):
            assert _store_digest(servers[0]._slot_stores[slot]) == \
                _store_digest(fresh._slot_stores[slot])
        # Idempotent: same generation does nothing more.
        assert ctl.maybe_apply("d", 3) is None
    finally:
        store.close()
        stop_shards(servers + ([fresh] if fresh else []))


# ---------------------------------------------------------------------------
# replicated checkpoints + ages sidecar
# ---------------------------------------------------------------------------

def test_replicated_checkpoint_no_double_rows_and_ages(tmp_path, pair):
    servers, eps, store = pair
    keys = _rand_keys(1800, seed=8)
    rows = store.pull_for_pass(keys)
    rows["show"] += 3.0
    store.push_from_pass(keys, rows)
    prev = flagmod.get_flags(["table_ttl_days"])
    try:
        flagmod.set_flags({"table_ttl_days": 10})
        store.shrink()                    # every row now at age 1
    finally:
        flagmod.set_flags(prev)
    path = str(tmp_path / "ck")
    store.save_base(path)

    # Exactly one hostshard dir per slot; their key sets are disjoint
    # (each server saved only its PRIMARY slot — no replica doubles).
    import glob
    import os
    dirs = sorted(glob.glob(os.path.join(path, "hostshard-*")))
    assert len(dirs) == 2
    saved = [np.load(os.path.join(d, "emb.base.npz"))["keys"]
             for d in dirs]
    assert sum(k.size for k in saved) == keys.size
    assert np.intersect1d(saved[0], saved[1]).size == 0
    # Ages sidecar rides beside each dump.
    for d in dirs:
        assert os.path.exists(os.path.join(d, "emb.base.ages.npz"))

    # Reload into a FRESH replicated pair: contents bit-identical,
    # backups populated straight from the checkpoint, ages restored.
    s2, e2 = start_local_shards(2, CFG, replicas=2)
    other = MultiHostStore(CFG, e2, replicas=2)
    try:
        other.load(path, "base")
        assert other.num_features == store.num_features
        got = other.pull_for_pass(keys)
        want = store.pull_for_pass(keys)
        for f in _FIELDS:
            np.testing.assert_array_equal(got[f], want[f], err_msg=f)
        for slot in (0, 1):
            prim = s2[slot]._slot_stores[slot]
            back = s2[1 - slot]._slot_stores[slot]
            assert _store_digest(prim) == _store_digest(back)
            pk, _ = prim.key_stats()
            assert (prim.unseen_for(pk) == 1).all()   # lease survived
            assert (back.unseen_for(pk) == 1).all()
    finally:
        other.close()
        stop_shards(s2)
