"""The full CTR train/eval step must compile through the real XLA:TPU +
Mosaic pipeline (compile-only PJRT topology) — program-level insurance
the per-kernel AOT tests can't give (shard_map + donation + Pallas
custom-call interactions). Runs tools/aot_check_step.py in a subprocess
because it re-pins platforms at import time."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(name, timeout, *args):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", name), *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    # The tools print this sentinel (and exit cleanly) when libtpu's AOT
    # topology cannot initialize, whatever the underlying error text —
    # substring-matching a specific jax message would rot.
    if "TPU-AOT-TOPOLOGY-UNAVAILABLE" in proc.stdout:
        pytest.skip("no TPU AOT topology available")
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


@pytest.mark.slow
def test_full_ctr_step_aot_compiles_for_tpu():
    out = _run_tool("aot_check_step.py", 900)
    assert "FULL-STEP TPU AOT COMPILE: OK" in out
    assert "EVAL-STEP TPU AOT COMPILE: OK" in out
    # K-step scanned megastep (train + eval), Pallas kernels inside the
    # scan body, through the same Mosaic pipeline.
    assert "MEGASTEP(K=4) TPU AOT COMPILE: OK" in out
    assert "MEGASTEP-EVAL(K=4) TPU AOT COMPILE: OK" in out
    # Fused end/begin pass-boundary program (FLAGS_pass_boundary_fuse):
    # one dispatch per boundary must keep compiling for TPU, single-chip
    # and sharded-all_to_all variants both.
    assert "FUSED-BOUNDARY(local) TPU AOT COMPILE: OK" in out
    assert "FUSED-BOUNDARY(sharded S=" in out
    # Slot-column split store (FLAGS_table_slot_placement=split|host):
    # the two-part scatter/boundary programs are distinct from the
    # fused 1-tuple layout and must lower for TPU on their own.
    assert "SPLIT-SLOT-PUSH(sharded S=" in out
    # ZeRO-sharded dense update (FLAGS_dense_zero=shard): psum ->
    # zero_slice -> shard update -> all-gather inside the full dp=4
    # shard_map'd step, clip-decomposed adam included.
    assert "ZERO-STEP(dp=4, adam+clip) TPU AOT COMPILE: OK" in out


@pytest.mark.slow
def test_multichip_steps_aot_compile_for_tpu():
    """GPT hybrid (pp x sp, 1F1B, ring attention) and CTR dp=4 (sharded
    table all-to-all) through the real TPU pipeline on a 4-device
    compile-only topology — ICI collective lowering included."""
    out = _run_tool("aot_check_multichip.py", 900)
    assert "MULTICHIP TPU AOT COMPILE: OK" in out


@pytest.mark.slow
def test_dense_bench_steps_aot_compile_for_tpu():
    """resnet50 (bf16 conv fwd+transpose under autodiff) and BERT-base
    train steps at their bench shapes."""
    out = _run_tool("aot_check_dense.py", 900)
    assert "DENSE BENCH TPU AOT COMPILE: OK" in out


@pytest.mark.slow
def test_scale_steps_aot_compile_for_tpu_256_chips():
    """The 8->256-chip scaling evidence (BASELINE.md metric 3) the bench
    chip can't give: the multislice CTR step (slice=4 x dp=64) and the
    hybrid GPT step (slice x dp x pp x sp x mp) lower + compile against
    a real 16x16 v5e compile-only topology — XLA schedules the full
    256-chip collective program (slice axis logical on the single-slice
    compile topology; DCN semantics pinned by test_multislice)."""
    out = _run_tool("aot_check_scale.py", 1500, "--chips", "256")
    assert "SCALE TPU AOT COMPILE (256 chips): OK" in out
