"""Data pipeline tests (SURVEY.md §2.4 roles): channel, parse, pack, dataset.

Mirrors the reference's test_dataset.py coverage (load/shuffle/batch) in
single-process form.
"""

import os
import threading

import numpy as np
import pytest

from paddlebox_tpu.data import (Channel, ClosedChannelError, DataFeedConfig,
                                Dataset, SlotBatch, SlotConf, parse_lines)

CFG = DataFeedConfig(
    slots=(
        SlotConf("user", avg_len=2.0),
        SlotConf("item", avg_len=1.0),
        SlotConf("dense0", is_dense=True, dim=3),
    ),
    batch_size=4,
    num_labels=1,
)


def _write_shard(tmp_path, name, lines):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_channel_mpmc_close():
    ch = Channel(capacity=8)
    results = []

    def consumer():
        try:
            while True:
                results.append(ch.get(timeout=5))
        except ClosedChannelError:
            pass

    ts = [threading.Thread(target=consumer) for _ in range(3)]
    for t in ts:
        t.start()
    for i in range(100):
        ch.put(i)
    ch.close()
    for t in ts:
        t.join()
    assert sorted(results) == list(range(100))
    with pytest.raises(ClosedChannelError):
        ch.put(1)


def test_parse_svm_line():
    ins = parse_lines(["1 user:11 user:12 item:7 dense0:0.5,1.5,2.5"], CFG)
    assert len(ins) == 1
    np.testing.assert_array_equal(ins[0].sparse["user"], [11, 12])
    np.testing.assert_array_equal(ins[0].sparse["item"], [7])
    np.testing.assert_allclose(ins[0].dense["dense0"], [0.5, 1.5, 2.5])
    assert ins[0].labels[0] == 1.0


def test_parse_skips_malformed():
    ins = parse_lines(["", "1 user:1", "garbage-no-colon token", "0 item:5"],
                      CFG)
    # "garbage-no-colon token": first tok parses as label? no — "garbage..."
    # is not a float → the whole line errors. Current parser: float() raises.
    assert len(ins) >= 2


def test_pack_static_shapes():
    ins = parse_lines(["1 user:11 user:12 item:7 dense0:1,2,3",
                       "0 user:13 item:9"], CFG)
    b = SlotBatch.pack(ins, CFG)
    cap_user = CFG.sparse_capacity(CFG.slots[0])
    assert b.ids["user"].shape == (cap_user,)
    assert b.segments["user"].shape == (cap_user,)
    assert b.lengths["user"].shape == (4,)
    assert b.labels.shape == (4, 1)
    assert b.dense["dense0"].shape == (4, 3)
    assert b.num_valid == 2
    # Padding segments point to the discard row (batch_size).
    assert b.segments["user"][3:].max() == 4
    np.testing.assert_array_equal(b.lengths["user"], [2, 1, 0, 0])
    np.testing.assert_array_equal(np.sort(b.all_sparse_ids()), [7, 9, 11, 12, 13])


def test_dataset_load_shuffle_batches(tmp_path):
    lines = [f"{i % 2} user:{100 + i} user:{200 + i} item:{i + 1} dense0:{i},{i},{i}"
             for i in range(37)]
    shards = [_write_shard(tmp_path, f"part-{j}", lines[j::3]) for j in range(3)]
    ds = Dataset(CFG, num_reader_threads=3)
    ds.set_filelist(shards)
    ds.load_into_memory()
    assert ds.num_instances == 37
    keys = ds.pass_keys()
    assert keys.size == 37 * 3  # all user/item ids unique
    ds.local_shuffle(seed=0)
    batches = list(ds.batches())
    assert len(batches) == 10  # ceil(37/4)
    assert sum(b.num_valid for b in batches) == 37
    # drop_last drops the short batch
    assert len(list(ds.batches(drop_last=True))) == 9


def test_dataset_preload_and_key_sink(tmp_path):
    lines = [f"1 user:{i} item:{i}" for i in range(1, 11)]
    shard = _write_shard(tmp_path, "p0", lines)
    seen = []
    ds = Dataset(CFG)
    ds.key_sink = lambda keys: seen.append(keys)
    ds.set_filelist([shard])
    ds.preload_into_memory()
    ds.wait_preload_done()
    assert ds.num_instances == 10
    assert np.unique(np.concatenate(seen)).size == 10  # user i == item i
    ds.clear()
    assert ds.num_instances == 0


def test_dataset_pipe_command(tmp_path):
    import gzip
    p = tmp_path / "part.gz"
    with gzip.open(p, "wt") as f:
        f.write("1 user:5 item:6\n0 user:7 item:8\n")
    cfg = DataFeedConfig(slots=CFG.slots, batch_size=4, pipe_command="zcat")
    ds = Dataset(cfg)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert ds.num_instances == 2


def test_global_shuffle_loopback(tmp_path):
    lines = [f"1 user:{i} item:{i}" for i in range(1, 21)]
    shard = _write_shard(tmp_path, "p0", lines)
    ds = Dataset(CFG)
    ds.set_filelist([shard])
    ds.load_into_memory()
    # Loopback: rank 0 of 2 keeps ~half the records.
    ds.global_shuffle(num_ranks=2, rank=0, seed=42, allow_partition=True)
    assert 0 < ds.num_instances < 20

    # Exchange-callback path: both buckets come back (identity cluster).
    ds2 = Dataset(CFG)
    ds2.set_filelist([shard])
    ds2.load_into_memory()
    from paddlebox_tpu.data.columnar import ColumnarChunk
    ds2.global_shuffle(num_ranks=2, rank=0, seed=42,
                       exchange=ColumnarChunk.concat)
    assert ds2.num_instances == 20


def test_slot_overflow_truncates(tmp_path):
    from paddlebox_tpu.core import monitor
    monitor.reset()
    cfg = DataFeedConfig(
        slots=(SlotConf("user", avg_len=1.0),), batch_size=2,
        slot_capacity_slack=1.0)
    many = " ".join(f"user:{i}" for i in range(100))
    ins = parse_lines([f"1 {many}", "0 user:1"], cfg)
    b = SlotBatch.pack(ins, cfg)
    cap = cfg.sparse_capacity(cfg.slots[0])
    assert b.ids["user"].shape == (cap,)
    assert monitor.get("slot_overflow/user") > 0


def test_failing_pipe_command_raises(tmp_path):
    p = _write_shard(tmp_path, "p0", ["1 user:1 item:2"])
    cfg = DataFeedConfig(slots=CFG.slots, batch_size=4,
                         pipe_command="nonexistent-cmd-xyz")
    ds = Dataset(cfg)
    ds.set_filelist([p])
    with pytest.raises(RuntimeError, match="pipe_command"):
        ds.load_into_memory()


def test_parser_negative_and_zero_feasign_dropped():
    from paddlebox_tpu.data import parse_lines as pl
    # Out-of-range/null feasign tokens are dropped (counted), line kept.
    ins = pl(["1 user:-5 item:3", "0 user:0 item:5"], CFG)
    assert len(ins) == 2
    assert "user" not in ins[0].sparse  # -5 dropped
    np.testing.assert_array_equal(ins[0].sparse["item"], [3])
    assert "user" not in ins[1].sparse  # 0 is the null sentinel
    np.testing.assert_array_equal(ins[1].sparse["item"], [5])


def test_global_shuffle_requires_transport(tmp_path):
    p = _write_shard(tmp_path, "p0", ["1 user:1 item:2"])
    ds = Dataset(CFG)
    ds.set_filelist([p])
    ds.load_into_memory()
    with pytest.raises(ValueError, match="transport"):
        ds.global_shuffle(num_ranks=2, rank=0)


def test_batches_sharded_divisibility_guard(tmp_path):
    p = _write_shard(tmp_path, "p0", [f"1 user:{i}" for i in range(1, 11)])
    cfg = DataFeedConfig(slots=(SlotConf("user"),), batch_size=10)
    ds = Dataset(cfg)
    ds.set_filelist([p])
    ds.load_into_memory()
    with pytest.raises(ValueError, match="not divisible"):
        next(ds.batches_sharded(4))


def test_shuffle_during_preload_raises(tmp_path):
    import time
    # a slow pipe keeps the preload alive while we try to shuffle
    p = _write_shard(tmp_path, "p0", ["1 user:1 item:2"] * 100)
    cfg = DataFeedConfig(slots=CFG.slots, batch_size=4,
                         pipe_command="sleep 0.5; cat")
    ds = Dataset(cfg)
    ds.set_filelist([p])
    ds.preload_into_memory()
    with pytest.raises(RuntimeError, match="preload"):
        ds.local_shuffle(0)
    ds.wait_preload_done()
    ds.local_shuffle(0)  # fine after wait
    assert ds.num_instances == 100
