"""Aux subsystem tests: profiler, dump writer, slots_shuffle, cache tables."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding.cache import InputTable, ReplicaCache
from paddlebox_tpu.utils import DumpWriter, Profiler, profile_pass


@pytest.mark.slow  # XPlane start/stop collection dominates; the cheap
# profile_pass context test below keeps the API in tier-1
def test_profiler_trace_and_timers(tmp_path):
    prof = Profiler(str(tmp_path / "trace"))
    prof.start()
    with prof.step(0):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    with prof.annotate("extra_region"):
        pass
    prof.stop()
    # XPlane trace files land under the logdir.
    found = []
    for root, _, files in os.walk(tmp_path / "trace"):
        found.extend(files)
    assert found, "no trace files written"
    rep = prof.report()
    assert "step=" in rep and "extra_region=" in rep


def test_profile_pass_context(tmp_path):
    with profile_pass(str(tmp_path / "t2")) as prof:
        with prof.annotate("work"):
            pass
    with profile_pass(str(tmp_path / "t3"), enabled=False) as prof:
        assert prof is None


def test_dump_writer_roundtrip(tmp_path):
    path = str(tmp_path / "dump" / "part-0")
    w = DumpWriter(path)
    preds = np.array([0.25, 0.5, 0.75])
    labels = np.array([0.0, 1.0, 1.0])
    valid = np.array([True, True, False])
    w.write_batch(preds, labels, valid, ins_ids=["a", "b", "c"],
                  extra={"bucket": np.array([1, 2, 3])})
    w.write_batch(np.array([0.9]), np.array([1.0]))
    w.close()
    lines = open(path).read().strip().split("\n")
    assert lines[0] == "a\t0.250000\t0\t1"
    assert lines[1] == "b\t0.500000\t1\t2"
    assert len(lines) == 3  # invalid row dropped


def test_slots_shuffle_decorrelates(tmp_path):
    cfg = DataFeedConfig(slots=(SlotConf("u", avg_len=2.0), SlotConf("i")),
                         batch_size=4)
    p = tmp_path / "part"
    rng = np.random.default_rng(0)
    with open(p, "w") as f:
        for k in range(50):
            us = " ".join(f"u:{k * 10 + j + 1}" for j in range(1 + k % 3))
            f.write(f"{k % 2} {us} i:{k + 1}\n")
    ds = Dataset(cfg)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    before = ds._merge()
    before_u = before.sparse_ids["u"].copy()
    before_i = before.sparse_ids["i"].copy()
    before_lens_sorted = np.sort(np.diff(before.sparse_offsets["u"]))

    ds.slots_shuffle(["u"], seed=1)
    after = ds._merge()
    # 'i' and labels untouched; 'u' multiset preserved but reordered.
    np.testing.assert_array_equal(after.sparse_ids["i"], before_i)
    np.testing.assert_array_equal(np.sort(after.sparse_ids["u"]),
                                  np.sort(before_u))
    assert not np.array_equal(after.sparse_ids["u"], before_u)
    np.testing.assert_array_equal(
        np.sort(np.diff(after.sparse_offsets["u"])), before_lens_sorted)
    assert ds.num_instances == 50


def test_replica_cache_pull():
    vals = np.arange(12, dtype=np.float32).reshape(4, 3)
    cache = ReplicaCache(vals)
    out = cache.pull(jnp.asarray([2, 0, 99, -1]))
    np.testing.assert_allclose(np.asarray(out)[0], vals[2])
    np.testing.assert_allclose(np.asarray(out)[1], vals[0])
    np.testing.assert_allclose(np.asarray(out)[2], 0.0)  # out of range
    np.testing.assert_allclose(np.asarray(out)[3], 0.0)


def test_input_table():
    t = InputTable()
    idx = t.add_many(["url_a", "url_b", "url_a", "url_c"])
    np.testing.assert_array_equal(idx, [0, 1, 0, 2])
    assert t.size == 3
    assert t.lookup("url_b") == 1
    assert t.lookup("missing") == -1
    assert t.key_at(2) == "url_c"
