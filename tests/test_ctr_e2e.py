"""End-to-end CTR training: the SURVEY.md §7 'minimum slice' bar —
DeepFM / Wide&Deep on synthetic slot data, multi-pass, with learning
verified by AUC lift, on the 8-device virtual mesh."""

import numpy as np
import pytest

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM, WideDeep
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item", "ctx")


def _synthetic_shard(path, n, seed, num_feats=200):
    """Clickiness is driven by feature identity so the model can learn:
    features with id % 5 == 0 are 'clicky'."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, num_feats, rng.integers(1, 4))
                     for s in SLOTS}
            clickiness = np.mean([(int(v) % 5 == 0)
                                  for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * clickiness)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items() for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shard_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("ctr")
    return [_synthetic_shard(d / f"part-{i}", 512, seed=i) for i in range(2)]


def _feed_config(bs=64):
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=2.0) for s in SLOTS),
        batch_size=bs)


def _run_training(model_cls, shard_files, passes=3):
    mesh = build_mesh(HybridTopology(dp=8))
    feed = _feed_config()
    table = TableConfig(dim=8, learning_rate=0.1)
    model = model_cls(slot_names=SLOTS, emb_dim=8, hidden=(32, 16))
    trainer = CTRTrainer(model, feed, table, mesh=mesh,
                         config=TrainerConfig(dense_learning_rate=3e-3,
                                              auc_num_buckets=1 << 12))
    trainer.init(seed=0)
    ds = Dataset(feed, num_reader_threads=2)
    ds.set_filelist(shard_files)
    ds.load_into_memory()
    stats_by_pass = []
    for p in range(passes):
        trainer.reset_metrics()
        ds.local_shuffle(seed=p)
        stats_by_pass.append(trainer.train_pass(ds))
    return trainer, stats_by_pass


def test_deepfm_learns(shard_files):
    trainer, stats = _run_training(DeepFM, shard_files)
    assert stats[0]["steps"] == 16  # 1024 instances / 64
    for s in stats:
        assert np.isfinite(s["loss"])
    # AUC improves materially over passes on learnable synthetic data.
    assert stats[-1]["auc"] > 0.65, [s["auc"] for s in stats]
    assert stats[-1]["auc"] > stats[0]["auc"] - 0.02
    # Store persisted features across passes.
    assert trainer.engine.store.num_features > 100


def test_widedeep_learns(shard_files):
    _, stats = _run_training(WideDeep, shard_files)
    assert stats[-1]["auc"] > 0.6, [s["auc"] for s in stats]


def test_checkpoint_roundtrip_continues(shard_files, tmp_path):
    trainer, stats = _run_training(DeepFM, shard_files, passes=2)
    trainer.engine.store.save_base(str(tmp_path / "base"))

    # New trainer, restored store: first pass starts from trained features.
    mesh = build_mesh(HybridTopology(dp=8))
    feed = _feed_config()
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(32, 16))
    t2 = CTRTrainer(model, feed, TableConfig(dim=8, learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 12))
    t2.init(seed=0)
    t2.engine.store.load(str(tmp_path / "base"), "base")
    assert t2.engine.store.num_features == trainer.engine.store.num_features


def test_grad_clip_bounds_update(tmp_path):
    """grad_clip_norm must cap the dense update: with a tiny clip the
    first-step parameter movement is strictly smaller than unclipped
    (clip sees the post-psum global grad)."""
    import jax

    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    def run(clip):
        mesh = build_mesh(HybridTopology(dp=8))
        feed = DataFeedConfig(slots=(SlotConf("a", avg_len=1.0),),
                              batch_size=64)
        model = DeepFM(slot_names=("a",), emb_dim=4, hidden=(16,))
        tr = CTRTrainer(
            model, feed, TableConfig(dim=4, learning_rate=0.1),
            mesh=mesh,
            config=TrainerConfig(dense_optimizer="sgd",
                                 dense_learning_rate=1.0,
                                 grad_clip_norm=clip))
        tr.init(seed=0)
        before = jax.tree.map(lambda x: np.asarray(x).copy(), tr.params)
        rng = np.random.default_rng(0)
        p = str(tmp_path / f"part-clip-{clip}")
        with open(p, "w") as f:
            for _ in range(64):
                f.write(f"{rng.integers(0, 2)} a:{rng.integers(1, 50)}\n")
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist([p])
        ds.load_into_memory()
        tr.train_pass(ds)
        delta = jax.tree.map(lambda a, b: np.abs(np.asarray(a) - b).max(),
                             tr.params, before)
        return max(jax.tree.leaves(delta))

    unclipped = run(0.0)
    clipped = run(1e-3)
    assert clipped < unclipped
    # SGD with lr 1 and global-norm clip c: max |update| <= c.
    assert clipped <= 1e-3 + 1e-6
