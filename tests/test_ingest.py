"""Round-13 host-ingest suite: bulk-parse bit-parity, multi-process
shared-memory ingest vs the thread reader, sorted-run store build vs the
incremental walk, worker-death surfacing, and shm leak hygiene.

Every comparison here is exact (np.array_equal) — the new ingest path is
an ACCELERATION of the old one, never an approximation.
"""

import gc
import os
import time

import numpy as np
import pytest

from paddlebox_tpu.core import faults, flags
from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf, parse_lines
from paddlebox_tpu.data.columnar import instances_to_chunk
from paddlebox_tpu.data.parser import parse_block_numpy

CFG = DataFeedConfig(
    slots=(
        SlotConf("user", avg_len=2.0),
        SlotConf("item", avg_len=1.0),
        SlotConf("dense0", is_dense=True, dim=3),
    ),
    batch_size=4,
    num_labels=1,
)


def _shm_leftovers():
    d = "/dev/shm"
    if not os.path.isdir(d):
        return []
    return [e for e in os.listdir(d) if e.startswith("pbx-ing-")]


def _assert_chunks_equal(a, b):
    np.testing.assert_array_equal(a.labels, b.labels)
    assert set(a.sparse_ids) == set(b.sparse_ids)
    for s in a.sparse_ids:
        np.testing.assert_array_equal(a.sparse_ids[s], b.sparse_ids[s])
        np.testing.assert_array_equal(a.sparse_offsets[s],
                                      b.sparse_offsets[s])
    assert set(a.dense) == set(b.dense)
    for s in a.dense:
        np.testing.assert_array_equal(a.dense[s], b.dense[s])


@pytest.fixture(autouse=True)
def _reset_flags():
    prev = flags.get_flags(["ingest_workers", "ingest_file_retries",
                            "ingest_key_runs"])
    yield
    flags.set_flags(prev)
    faults.clear()


def _write_files(tmp_path, n_files=3, n_rows=40, seed=0):
    rng = np.random.default_rng(seed)
    files = []
    for j in range(n_files):
        lines = []
        for i in range(n_rows):
            uids = rng.integers(1, 1 << 40, rng.integers(1, 4))
            user = " ".join(f"user:{u}" for u in uids)
            lines.append(f"{i % 2} {user} item:{j * n_rows + i + 1} "
                         f"dense0:{i}.5,{i},{i}")
        p = tmp_path / f"part-{j}"
        p.write_text("\n".join(lines) + "\n")
        files.append(str(p))
    return files


# -- bulk parser bit-parity -------------------------------------------------

def test_bulk_parse_matches_per_line_parser():
    blocks = [
        b"1 user:11 user:12 item:7 dense0:0.5,1.5,2.5\n0 user:13 item:9\n",
        b"1 user:5\n",
        b"1 user:0 item:3\n",              # null feasign -> dropped token
        b"\n\n1 user:1\n",                 # empty lines skipped
        b"1 unknown:9 user:2\n",           # unused slot ignored
        b"1 dense0:1,2,3 dense0:4,5,6\n",  # dup dense -> last wins
        b"0.5 user:3",                     # no trailing newline
        b"1\n",                            # labels only
    ]
    for blk in blocks:
        got = parse_block_numpy(blk, CFG)
        assert got is not None, blk
        want = instances_to_chunk(
            parse_lines(blk.decode("utf-8", "replace").split("\n"), CFG),
            CFG)
        _assert_chunks_equal(got, want)


def test_bulk_parse_defers_exotic_input_to_exact_path():
    # Inputs whose handling depends on per-token error semantics must
    # go to the exact parser (None), never be approximated.
    for blk in (b"1 user:-5\n", b"garbage nolabel\n", b"1 user:abc\n",
                b"1  user:3\n", b"1 user:3 \n", b"1\tuser:3\n",
                b"1 user:99999999999999999999\n", b"1 user\n",
                b"1 user:\n", "1 user:é\n".encode()):
        assert parse_block_numpy(blk, CFG) is None, blk


def test_bulk_parse_large_random_block_parity():
    rng = np.random.default_rng(3)
    lines = []
    for i in range(2000):
        n_u = rng.integers(0, 5)
        toks = [str(i % 2)]
        toks += [f"user:{rng.integers(1, 1 << 60)}" for _ in range(n_u)]
        if rng.random() < 0.7:
            toks.append(f"item:{rng.integers(1, 1 << 30)}")
        if rng.random() < 0.5:
            toks.append(f"dense0:{rng.random():.4f},{rng.random():.4f},1")
        lines.append(" ".join(toks))
    blk = ("\n".join(lines) + "\n").encode()
    got = parse_block_numpy(blk, CFG)
    assert got is not None
    want = instances_to_chunk(parse_lines(blk.decode().split("\n"), CFG),
                              CFG)
    _assert_chunks_equal(got, want)


# -- multi-process ingest vs thread reader ----------------------------------

def test_mp_ingest_bit_parity_across_worker_counts(tmp_path):
    files = _write_files(tmp_path)
    ds_ref = Dataset(CFG, num_reader_threads=2)
    ds_ref.set_filelist(files)
    ds_ref.load_into_memory()
    ref_keys = ds_ref.pass_keys()
    ref_user = ds_ref.pass_keys(slots=["user"])
    ref_batches = list(ds_ref.batches())

    from paddlebox_tpu.embedding.table import map_keys_to_rows
    probe = ref_keys[:: max(1, ref_keys.size // 64)]
    ref_rows = map_keys_to_rows(ref_keys, probe, 1 << 12, 2)

    for workers in (1, 4):
        flags.set_flags({"ingest_workers": workers})
        seen = []
        ds = Dataset(CFG)
        ds.key_sink = lambda k: seen.append(k)
        ds.set_filelist(files)
        ds.load_into_memory()
        assert ds.num_instances == ds_ref.num_instances
        # Identical pass keys (and per-slot key sets) regardless of
        # which process parsed what in which order.
        np.testing.assert_array_equal(ds.pass_keys(), ref_keys)
        np.testing.assert_array_equal(ds.pass_keys(slots=["user"]),
                                      ref_user)
        # key_sink saw the same key multiset the thread path feeds.
        np.testing.assert_array_equal(
            np.unique(np.concatenate(seen)), ref_keys)
        # Identical row maps: same sorted keys -> same sharded layout.
        np.testing.assert_array_equal(
            map_keys_to_rows(ds.pass_keys(), probe, 1 << 12, 2), ref_rows)
        # Identical chunk CONTENTS: rows in a canonical order.
        got = _sorted_rows(ds)
        want = _sorted_rows(ds_ref)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)
        assert len(list(ds.batches())) == len(ref_batches)
        ds.clear()
    gc.collect()
    assert not _shm_leftovers()


def _sorted_rows(ds):
    """Canonical (order-insensitive) view of the loaded records: rows
    sorted by (item key) — unique per row in _write_files — so thread
    and process loads compare content-equal despite arrival order."""
    merged = ds._merge()
    item = merged.sparse_ids["item"][merged.sparse_offsets["item"][:-1]]
    order = np.argsort(item, kind="stable")
    m = merged.take(order)
    return [m.labels, m.sparse_ids["user"], m.sparse_offsets["user"],
            m.sparse_ids["item"], m.dense["dense0"]]


def test_mp_ingest_worker_error_surfaces(tmp_path):
    files = _write_files(tmp_path, n_files=2)
    cfg = DataFeedConfig(slots=CFG.slots, batch_size=4,
                         pipe_command="nonexistent-cmd-xyz")
    flags.set_flags({"ingest_workers": 2})
    ds = Dataset(cfg)
    ds.set_filelist(files)
    with pytest.raises(RuntimeError, match="pipe_command"):
        ds.load_into_memory()
    gc.collect()
    assert not _shm_leftovers()


def test_mp_ingest_faultpoints_surface(tmp_path):
    files = _write_files(tmp_path, n_files=1)
    flags.set_flags({"ingest_workers": 1})
    for site, exc in (("ingest/worker_spawn", OSError),
                      ("ingest/shm_attach", OSError)):
        faults.configure(f"{site}:raise=IOError")
        ds = Dataset(CFG)
        ds.set_filelist(files)
        with pytest.raises(exc):
            ds.load_into_memory()
        faults.clear()
        gc.collect()
        assert not _shm_leftovers(), site


def test_mp_ingest_custom_parser_falls_back_to_threads(tmp_path):
    # parser_fn closures cannot cross a process boundary; the flag must
    # not break instance-scoped parsers.
    files = _write_files(tmp_path, n_files=1)
    flags.set_flags({"ingest_workers": 4})
    calls = []

    def pf(lines, config):
        calls.append(1)
        return parse_lines(lines, config)

    ds = Dataset(CFG, parser_fn=pf)
    ds.set_filelist(files)
    ds.load_into_memory()
    assert calls, "custom parser_fn was bypassed"
    assert ds.num_instances == 40


def test_mp_ingest_dump_into_disk(tmp_path):
    files = _write_files(tmp_path)
    spill = tmp_path / "spill"
    flags.set_flags({"ingest_workers": 2})
    ds = Dataset(CFG)
    ds.set_filelist(files)
    n = ds.dump_into_disk(str(spill))
    assert n >= 1
    ds2 = Dataset(CFG)
    ds2.load_from_disk(str(spill))
    assert ds2.num_instances == 120
    gc.collect()
    assert not _shm_leftovers()


# -- sorted-run pass keys ----------------------------------------------------

def test_pass_keys_runs_vs_fallback_parity(tmp_path):
    files = _write_files(tmp_path)
    # ONE reader thread, in-process: the global_shuffle partition below
    # drops rows BY POSITION, so this parity needs the two datasets
    # loaded in the same row order — multi-threaded (or mp-ingest)
    # chunk arrival order is scheduling-dependent and flaked this test.
    flags.set_flags({"ingest_workers": 0, "ingest_key_runs": True})
    ds_runs = Dataset(CFG, num_reader_threads=1)
    ds_runs.set_filelist(files)
    ds_runs.load_into_memory()
    assert ds_runs._key_runs_valid

    flags.set_flags({"ingest_key_runs": False})
    ds_flat = Dataset(CFG, num_reader_threads=1)
    ds_flat.set_filelist(files)
    ds_flat.load_into_memory()
    assert not ds_flat._key_runs_valid

    np.testing.assert_array_equal(ds_runs.pass_keys(), ds_flat.pass_keys())
    for slots in (["user"], ["item"], ["user", "item"], ["nosuch"]):
        np.testing.assert_array_equal(ds_runs.pass_keys(slots=slots),
                                      ds_flat.pass_keys(slots=slots))
    # local_shuffle preserves the key set -> runs stay valid and exact.
    ds_runs.local_shuffle(7)
    ds_flat.local_shuffle(7)
    np.testing.assert_array_equal(ds_runs.pass_keys(), ds_flat.pass_keys())
    # global_shuffle with a partition DROPS rows -> must fall back.
    ds_runs.global_shuffle(num_ranks=2, rank=0, seed=1,
                           allow_partition=True)
    ds_flat.global_shuffle(num_ranks=2, rank=0, seed=1,
                           allow_partition=True)
    assert not ds_runs._key_runs_valid
    np.testing.assert_array_equal(ds_runs.pass_keys(), ds_flat.pass_keys())


def test_pass_keys_runs_preserve_zero_key():
    # A custom parser may emit the 0 sentinel; pass_keys always reported
    # it and the run path must too (dedup_keys drops it by design).
    from paddlebox_tpu.data.slots import Instance

    def pf(lines, config):
        out = []
        for line in lines:
            if not line:
                continue
            out.append(Instance(
                labels=np.zeros((1,), np.float32),
                sparse={"user": np.array([0, 5], np.uint64)},
                dense={}))
        return out

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f")
        with open(p, "w") as f:
            f.write("x\nx\n")
        ds = Dataset(CFG, parser_fn=pf)
        ds.set_filelist([p])
        ds.load_into_memory()
        assert ds._key_runs_valid
        np.testing.assert_array_equal(ds.pass_keys(),
                                      np.array([0, 5], np.uint64))


# -- sorted-run store build vs incremental upsert ---------------------------

def test_bulk_build_matches_upsert_rows_and_keys():
    from paddlebox_tpu.native.store_py import KeyIndex, SortedRunMerger
    from paddlebox_tpu.native.keymap_py import dedup_keys
    rng = np.random.default_rng(11)
    chunks = [rng.integers(1, 1 << 48, 20_000, dtype=np.uint64)
              for _ in range(5)]
    # Sorted-run build: dedup each chunk as it "arrives", merge, bulk.
    merger = SortedRunMerger()
    for c in chunks:
        merger.add_run(dedup_keys(c))
    keys = merger.merge()
    np.testing.assert_array_equal(
        keys, np.unique(np.concatenate(chunks)))
    bulk, inc = KeyIndex(), KeyIndex()
    rows_bulk = bulk.bulk_build(keys)
    rows_inc, n_new = inc.upsert(keys)
    assert n_new == keys.size
    np.testing.assert_array_equal(rows_bulk, rows_inc)
    np.testing.assert_array_equal(bulk.keys_by_row(), inc.keys_by_row())
    q = rng.integers(1, 1 << 48, 5_000, dtype=np.uint64)
    np.testing.assert_array_equal(bulk.lookup(q), inc.lookup(q))
    bulk.close()
    inc.close()


def test_keyindex_fallback_matches_native():
    """The vectorized numpy fallback must be bit-identical to the native
    index on every surface (lookup/upsert/bulk_build/keys_by_row),
    including first-appearance row order and intra-batch duplicates."""
    import paddlebox_tpu.native.store_py as sp
    rng = np.random.default_rng(4)
    b1 = rng.integers(0, 500, 2_000, dtype=np.uint64)     # dups + zeros
    b2 = rng.integers(0, 1_000, 1_500, dtype=np.uint64)
    native = sp.KeyIndex()
    if native._h is None:
        pytest.skip("native library unavailable — nothing to compare")
    orig = sp.load_library
    sp.load_library = lambda: None
    try:
        fb = sp.KeyIndex()
        fb.reserve(2_000)  # honored as a pre-size hint, not a no-op
        assert fb._fb_by_row.shape[0] >= 2_000
    finally:
        sp.load_library = orig
    for idx in (native, fb):
        r1, n1 = idx.upsert(b1)
        r2, n2 = idx.upsert(b2)
        idx._res = (r1, n1, r2, n2)
    for a, b in zip(native._res, fb._res):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(native.keys_by_row(), fb.keys_by_row())
    q = rng.integers(0, 1_200, 3_000, dtype=np.uint64)
    np.testing.assert_array_equal(native.lookup(q), fb.lookup(q))
    assert native.size == fb.size
    native.close()
    fb.close()


def test_device_store_bulk_build_bit_parity(devices8, monkeypatch):
    """Fresh-build bypass vs incremental upsert on the HBM-tier store,
    SAME sorted input: same rows, same on-device values."""
    from paddlebox_tpu.core import monitor
    from paddlebox_tpu.embedding import TableConfig, device_store
    rng = np.random.default_rng(5)
    keys = np.unique(rng.integers(1, 1 << 40, 3_000, dtype=np.uint64))
    cfg = TableConfig(dim=8)
    before = monitor.get("device_store/bulk_builds")
    fresh = device_store.DeviceFeatureStore(cfg)  # sorted -> bulk path
    r_fresh = fresh.ensure_rows(keys)
    assert monitor.get("device_store/bulk_builds") == before + 1
    # Same input through the incremental walk (bypass disabled).
    monkeypatch.setattr(device_store.native_store,
                        "is_sorted_unique_nonzero", lambda k: False)
    incr = device_store.DeviceFeatureStore(cfg)
    r_incr = incr.ensure_rows(keys)
    np.testing.assert_array_equal(r_fresh, r_incr)
    for a, b in zip(fresh._parts, incr._parts):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Later batches through the normal upsert path still line up.
    more = np.unique(rng.integers(1, 1 << 40, 500, dtype=np.uint64))
    np.testing.assert_array_equal(fresh.ensure_rows(more),
                                  incr.ensure_rows(more))


def test_bench_index_build_modes_agree():
    from paddlebox_tpu.native.store_py import bench_index_build
    for mode in ("upsert", "bulk", "dict"):
        rate = bench_index_build(50_000, chunk=20_000, mode=mode)
        assert rate > 0
    with pytest.raises(ValueError):
        bench_index_build(1000, mode="nope")


# -- worker death ------------------------------------------------------------

@pytest.mark.slow
def test_mp_ingest_worker_death_exhausted_retries_raises(tmp_path):
    files = _write_files(tmp_path, n_files=1)
    started = tmp_path / "started"
    cfg = DataFeedConfig(slots=CFG.slots, batch_size=4,
                         pipe_command=f"touch {started}; sleep 30; cat")
    flags.set_flags({"ingest_workers": 1, "ingest_file_retries": 0})
    ds = Dataset(cfg)
    ds.set_filelist(files)
    ds.preload_into_memory()
    t0 = time.time()
    # The sentinel proves the worker is INSIDE the file (file_start
    # sent) — killing earlier would be an idle death, which respawns.
    while not started.exists() and time.time() - t0 < 60:
        time.sleep(0.05)
    assert started.exists()
    time.sleep(0.2)
    assert ds._ingest_procs
    os.kill(ds._ingest_procs[0].pid, 9)
    with pytest.raises(RuntimeError, match="ingest worker died"):
        ds.wait_preload_done()
    gc.collect()
    assert not _shm_leftovers()
