"""Pass-report + telemetry integration over the CTR trainer.

The acceptance contract of the telemetry layer: a tiny CPU train_pass
with FLAGS_trace_path / FLAGS_metrics_path set produces a
Perfetto-loadable trace JSON, a parseable metrics JSONL, and one
structured per-pass summary covering every PrintSyncTimer stage
(read/pack/pull/fwd-bwd/push/dispatch/sync) — consistent with the K>1
megastep counters — while tracing adds ZERO ops to the jitted step
(the op-structure pins of test_step_structure must hold with telemetry
on)."""

import json
import math

import numpy as np
import pytest

from paddlebox_tpu.core import flags as flagmod
from paddlebox_tpu.core import monitor, report, trace
from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("u", "i", "c")
N_BATCHES = 13          # K=4 -> blocks of 4,4,4,1 (tail block covered)
BATCH = 32


def _shard(path, n, seed=7, n_keys=150):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            feats = {s: rng.integers(1, n_keys, rng.integers(1, 3))
                     for s in SLOTS}
            click = np.mean([(int(v) % 5 == 0)
                             for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.1 + 0.8 * click)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return str(path)


@pytest.fixture(scope="module")
def shard_13(tmp_path_factory):
    return _shard(tmp_path_factory.mktemp("preport") / "part-0",
                  N_BATCHES * BATCH)


def _feed():
    return DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=BATCH)


def _dataset(p):
    feed = _feed()
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([p])
    ds.load_into_memory()
    return ds


def _trainer():
    mesh = build_mesh(HybridTopology(dp=8))
    tr = CTRTrainer(DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,)),
                    _feed(), TableConfig(dim=8, learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10),
                    store_factory=lambda c: DeviceFeatureStore(
                        c, mesh=mesh))
    tr.init(seed=0)
    return tr


@pytest.fixture()
def telemetry_paths(tmp_path):
    """Arm both sinks via flags; fully disarm afterwards so the rest of
    the suite runs with telemetry default-off."""
    tpath = str(tmp_path / "run.trace.json")
    mpath = str(tmp_path / "run.metrics.jsonl")
    flagmod.set_flags({"trace_path": tpath, "metrics_path": mpath,
                       "metrics_flush_interval_s": 0.0})
    trace.clear()
    monitor.reset()
    try:
        yield tpath, mpath
    finally:
        flagmod.set_flags({"trace_path": "", "metrics_path": "",
                           "metrics_flush_interval_s": 30.0})
        trace.disable()
        trace.clear()
        monitor.stop_flush_thread()
        monitor.reset()


def test_train_pass_report_with_megastep_and_artifacts(shard_13,
                                                       telemetry_paths):
    tpath, mpath = telemetry_paths
    tr = _trainer()
    prev = flagmod.flag("trainer_steps_per_dispatch")
    flagmod.set_flags({"trainer_steps_per_dispatch": 4})
    try:
        stats = tr.train_pass(_dataset(shard_13))
    finally:
        flagmod.set_flags({"trainer_steps_per_dispatch": prev})

    # -- the structured per-pass summary ------------------------------
    rep = stats["pass_report"]
    assert rep["kind"] == "train"
    assert set(rep["stage_ms"]) == set(report.STAGES)
    for s in report.STAGES:
        assert rep["stage_ms"][s] >= 0.0
    # Host stages actually observed something on this pass.
    assert rep["stage_ms"]["read"] > 0.0
    assert rep["stage_ms"]["pull"] > 0.0
    assert rep["stage_ms"]["dispatch"] > 0.0
    # Consistency with the K=4 megastep: 13 steps -> ceil(13/4) blocks,
    # zero in-loop host syncs, global sample count.
    assert rep["steps"] == stats["steps"] == N_BATCHES
    assert rep["samples"] == N_BATCHES * BATCH
    assert rep["samples_per_s"] > 0
    assert stats["dispatch_blocks"] == math.ceil(N_BATCHES / 4)
    assert rep["dispatch_blocks"] == stats["dispatch_blocks"]
    assert rep["host_syncs"] == 0
    assert rep["steps_per_dispatch"] == 4
    assert rep["lookup_exchange_bytes"] == stats["lookup_exchange_bytes"]
    assert rep["lookup_exchange_bytes"] > 0
    assert "seg_cache_hit_rate" in rep
    # -- critical-path attribution (round 11) -------------------------
    bn = rep["bottleneck"]
    assert bn["stage"] is not None
    assert 0.0 <= bn["device_idle_frac"] <= 1.0
    assert 0.0 <= bn["host_critical_share"] <= 1.0
    for stage in ("reader", "packer", "keymap", "device"):
        assert stage in bn["stages"]
    dq = rep["dispatch_ms_quantiles"]
    assert dq["count"] == stats["dispatch_blocks"]
    assert dq["p50"] <= dq["p99"]

    # -- trace artifact: Perfetto/chrome-loadable ---------------------
    out = trace.export()
    assert out == tpath
    obj = json.load(open(tpath))
    names = {e["name"] for e in obj["traceEvents"]}
    assert "pass/dispatch" in names
    assert "prefetch/host_map" in names
    assert "pass_report/train" in names
    dispatches = [e for e in obj["traceEvents"]
                  if e["name"] == "pass/dispatch" and e["ph"] == "X"]
    assert len(dispatches) == stats["dispatch_blocks"]
    # Producer spans come from the prefetch thread, dispatch from the
    # consumer: at least two distinct tids in the timeline.
    assert len({e["tid"] for e in obj["traceEvents"]}) >= 2

    # -- metrics artifact: every line parses, registry is fed ---------
    lines = [json.loads(x) for x in open(mpath).read().splitlines()]
    assert lines, "pass report must append at least one snapshot"
    last = lines[-1]
    assert last["labels"] == {"event": "pass_report", "kind": "train"}
    h = last["histograms"]["trainer/dispatch_ms"]
    assert h["count"] == stats["dispatch_blocks"]
    assert sum(h["counts"]) == h["count"]
    assert last["counters"]["pass/train_passes"] == 1
    assert last["counters"]["pass/train_steps"] == N_BATCHES
    assert last["gauges"]["pass/train_samples_per_s"] > 0
    assert last["counters"]["lookup/exchange_bytes_per_step"] == \
        stats["lookup_exchange_bytes"]
    # Quantile digests ride the snapshot (mergeable across ranks), and
    # the occupancy gauges feed trace_report's pipeline table.
    q = last["quantiles"]["trainer/dispatch_ms"]
    assert q["count"] == stats["dispatch_blocks"]
    assert q["p50"] is not None
    assert last["gauges"]["pass/train_device_idle_frac"] == \
        rep["bottleneck"]["device_idle_frac"]
    assert "pipeline/device_busy_frac" in last["gauges"]


def test_eval_pass_report(shard_13, telemetry_paths):
    tr = _trainer()
    prev = flagmod.flag("trainer_steps_per_dispatch")
    flagmod.set_flags({"trainer_steps_per_dispatch": 4})
    try:
        stats = tr.eval_pass(_dataset(shard_13))
    finally:
        flagmod.set_flags({"trainer_steps_per_dispatch": prev})
    rep = stats["pass_report"]
    assert rep["kind"] == "eval"
    assert set(rep["stage_ms"]) == set(report.STAGES)
    assert stats["dispatch_blocks"] == math.ceil(N_BATCHES / 4)
    assert rep["steps"] == N_BATCHES
    # Eval pushes nothing: the push stage must be (near) zero.
    assert rep["stage_ms"]["push"] == 0.0


def test_telemetry_off_no_artifacts(shard_13, tmp_path):
    """Default-off contract: with the flags unset, a pass writes no
    files and records no trace events."""
    trace.disable()
    trace.clear()
    tr = _trainer()
    stats = tr.train_pass(_dataset(shard_13))
    assert stats["pass_report"]["steps"] == N_BATCHES  # report still built
    assert trace.snapshot() == []
    assert list(tmp_path.iterdir()) == []


def test_tracing_leaves_step_op_structure_unchanged(telemetry_paths):
    """The zero-hot-loop-cost pin: enabling telemetry must not change
    the jitted train step's op counts (host spans only — no device
    ops, no syncs)."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.parser import parse_lines
    from paddlebox_tpu.data.slots import SlotBatch
    from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
    from paddlebox_tpu.utils import inspect as pbx_inspect

    def op_counts():
        mesh = build_mesh(HybridTopology(dp=4),
                          devices=jax.devices()[:4])
        slots = tuple(SlotConf(f"s{i}", avg_len=2.0) for i in range(3))
        feed = DataFeedConfig(slots=slots, batch_size=16)
        model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                       emb_dim=8, hidden=(16, 8))
        tr = CTRTrainer(model, feed, TableConfig(dim=8), mesh=mesh,
                        config=TrainerConfig(auc_num_buckets=1 << 10),
                        store_factory=lambda c: DeviceFeatureStore(
                            c, mesh=mesh))
        tr.init(seed=0)
        rng = np.random.default_rng(0)
        lines = [f"{rng.integers(0, 2)} "
                 + " ".join(f"s{i}:{rng.integers(1, 40)}"
                            for i in range(3))
                 for _ in range(feed.batch_size)]
        batch = SlotBatch.pack_sharded(parse_lines(lines, feed), feed, 4)
        tr.engine.feed_pass([
            np.unique(np.concatenate([batch.ids[n] for n in g.slots]))
            for g in tr.engine.groups])
        step = tr._build_step()
        tables = tr.engine.begin_pass()
        rows = tr._map_batch_rows(batch)
        segs = {n: jnp.asarray(batch.segments[n]) for n in batch.ids}
        args = (tables, tr.params, tr.opt_state, tr.auc_state, rows,
                segs, jnp.asarray(batch.labels),
                jnp.asarray(batch.valid),
                jnp.asarray(_concat_dense_host(batch)),
                jnp.zeros((), jnp.int32))
        return pbx_inspect.jaxpr_summary(lambda *a: step(*a), *args)

    trace.disable()
    off = op_counts()
    assert trace.init_from_flags()  # telemetry ON via the fixture flags
    on = op_counts()
    assert on == off, (on, off)


def test_day_runner_timers_reach_registry(shard_13, tmp_path,
                                          telemetry_paths):
    """Satellite pin: the day loop publishes through the ONE report
    path (registry gauges), not a private print."""
    from paddlebox_tpu.train.day_runner import DayRunner

    tr = _trainer()
    runner = DayRunner(tr, _feed(), str(tmp_path / "out"),
                       data_root=str(tmp_path), pipeline_passes=False)
    runner.train_pass("20260804", 1, [shard_13])
    snap = monitor.snapshot()
    assert snap["day_runner/train_ms"] > 0.0
    assert snap["day_runner/passes"] == 1
    assert snap["pass/train_passes"] >= 1
