"""Worker payload for the multi-process MULTI-SLICE test (spawned by
``python -m paddlebox_tpu.launch --nproc 2 tests/mp_slice_worker.py``).

The r04 multislice suite proved the slice hierarchy's math on a
single-process mesh; this worker puts the ``slice`` axis on a REAL
process boundary — 2 jax.distributed processes x 4 CPU devices each,
mesh ``slice=2 x dp=4`` — the closest this environment gets to the
reference's inter-node path (gather_multi_node_grad over a second comm
set, heter_comm.h:156-172). It checks, inside the distributed run:

- the mesh actually lays ``slice`` on the process boundary;
- ``hierarchical_psum_tree`` (RS-ICI -> psum-DCN -> AG-ICI) equals the
  flat psum ACROSS processes;
- a 2-pass CTR training trajectory, for the parent to compare against
  the identical single-process 8-device ``slice=2 x dp=4`` run.

Usage: mp_slice_worker.py <data_dir> <out_json>
(env PBX_TEST_LOCAL_DEVICES overrides the per-process device count — the
parent's single-process reference run uses 8.)
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                           + os.environ.get("PBX_TEST_LOCAL_DEVICES", "4"))
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    data_dir, out_json = sys.argv[1], sys.argv[2]
    from paddlebox_tpu.distributed import bootstrap
    bootstrap.initialize()   # PBX_* env from the launcher
    nproc = jax.process_count()
    assert nproc == int(os.environ["PBX_NUM_PROCESSES"])

    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddlebox_tpu.data.dataset import Dataset
    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.parallel.collective import hierarchical_psum_tree
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    ndev = len(jax.devices())        # global across processes
    n_slices = 2
    mesh = build_mesh(HybridTopology(slice=n_slices, dp=ndev // n_slices))

    # The whole point of this worker: each slice must be owned by ONE
    # process, so the slice axis (DCN role) crosses the process boundary
    # and nothing else does.
    slice_procs = [sorted({d.process_index for d in
                           mesh.devices[s].flatten()})
                   for s in range(n_slices)]
    slice_on_boundary = (nproc == n_slices
                         and slice_procs == [[0], [1]])

    # Hierarchical DCN tree vs flat psum, ACROSS the process boundary.
    rng = np.random.default_rng(3)
    tree = {"a": np.asarray(rng.normal(size=(5, 3)), np.float32),
            "b": np.asarray(rng.normal(size=(7,)), np.float32)}

    def hier(t):
        return hierarchical_psum_tree(t, inner_axis="dp",
                                      outer_axis="slice")

    def flat(t):
        return jax.tree.map(lambda x: lax.psum(x, ("slice", "dp")), t)

    out_h = jax.jit(jax.shard_map(hier, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))(tree)
    out_f = jax.jit(jax.shard_map(flat, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))(tree)
    hier_err = max(float(np.max(np.abs(np.asarray(out_h[k])
                                       - np.asarray(out_f[k]))))
                   for k in tree)

    slots = tuple(SlotConf(f"s{i}", avg_len=1.0) for i in range(3))
    feed = DataFeedConfig(slots=slots, batch_size=32)
    model = DeepFM(slot_names=tuple(f"s{i}" for i in range(3)),
                   emb_dim=4, hidden=(16,))
    trainer = CTRTrainer(model, feed,
                         TableConfig(dim=4, learning_rate=0.1), mesh=mesh,
                         config=TrainerConfig(auc_num_buckets=1 << 10))
    assert trainer.dcn_axis == "slice", trainer.dcn_axis
    trainer.init(seed=0)

    files = sorted(
        os.path.join(data_dir, f) for f in os.listdir(data_dir)
        if f.startswith("part-"))
    losses = []
    for _ in range(2):
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        stats = trainer.train_pass(ds)
        losses.append(stats["loss"])
        assert stats["lookup_overflow"] == 0

    if jax.process_index() == 0:
        with open(out_json, "w") as f:
            json.dump({"losses": losses,
                       "ndev": ndev,
                       "nproc": nproc,
                       "slice_on_boundary": slice_on_boundary,
                       "slice_procs": slice_procs,
                       "hier_err": hier_err}, f)


if __name__ == "__main__":
    main()
