"""Subprocess payload for the kill -9 mid-reshard drill
(tests/test_multihost.py).

Every invocation is one crash window of the reshard state machine
(MULTIHOST.md): the cluster's ONLY durable state is the checkpoint
chain, so a SIGKILL at any point must recover through
``recovery_chain()`` with no lost and no double-applied rows — the
layout-independent content digest this worker emits is the proof.

Usage: multihost_reshard_worker.py <ckpt_root> <mode> [world]
  seed          world-2 cluster, deterministic rows, save_base+publish,
                digest -> <ckpt_root>/digest_seed.json
  reshard W     load the chain into a world-2 cluster, reshard 2 -> W
                (FLAGS_fault_spec may kill us mid-move), then digest ->
                digest_reshard.json and save_base+publish the resharded
                state as the next record
  recover W     fresh world-W cluster, reset + recovery_chain reload,
                digest -> digest_recover.json
"""

import json
import os
import sys
import zlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

DAY = "20260801"
N_KEYS = 4000
DIM = 8


def _digest(servers) -> dict:
    """Layout-independent content digest: the union of every server's
    rows, sorted by key — identical digests mean identical logical
    table contents regardless of world size/placement (a duplicated or
    lost row changes `rows` or a crc)."""
    all_keys, all_emb, all_w = [], [], []
    for s in servers:
        keys, _ = s.store.key_stats()
        if keys.size:
            vals = s.store.pull_for_pass(np.sort(keys))
            all_keys.append(np.sort(keys))
            all_emb.append(vals["emb"])
            all_w.append(vals["w"])
    keys = (np.concatenate(all_keys) if all_keys
            else np.empty((0,), np.uint64))
    order = np.argsort(keys, kind="stable")
    keys = keys[order]
    emb = (np.concatenate(all_emb)[order] if all_keys
           else np.empty((0, DIM), np.float32))
    w = (np.concatenate(all_w)[order] if all_keys
         else np.empty((0,), np.float32))
    assert np.unique(keys).size == keys.size, "duplicated rows!"
    return {"rows": int(keys.size),
            "keys_crc": zlib.crc32(keys.tobytes()),
            "emb_crc": zlib.crc32(emb.tobytes()),
            "w_crc": zlib.crc32(w.tobytes())}


def main() -> None:
    root, mode = sys.argv[1], sys.argv[2]
    world = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
    from paddlebox_tpu.core import faults
    from paddlebox_tpu.embedding.table import TableConfig
    from paddlebox_tpu.multihost import (MultiHostStore, execute_reshard,
                                         start_local_shards, stop_shards)
    from paddlebox_tpu.multihost.keyrange import ShardRangeTable

    faults.init_from_flags()
    cfg = TableConfig(name="emb", dim=DIM, learning_rate=0.1)
    ckpt = CheckpointProtocol(root)

    if mode == "seed":
        servers, eps = start_local_shards(2, cfg)
        store = MultiHostStore(cfg, eps)
        rng = np.random.default_rng(7)
        keys = np.unique(rng.integers(1, 1 << 50, size=N_KEYS + 64,
                                      dtype=np.uint64))[:N_KEYS]
        rows = store.pull_for_pass(keys)
        rows["show"] += 1.0
        store.push_from_pass(keys, rows)
        mdir = ckpt.model_dir(DAY, 1)
        store.save_delta(mdir)
        ckpt.publish(DAY, 1)
        out = _digest(servers)
        stop_shards(servers)
    elif mode == "reshard":
        servers, eps = start_local_shards(2, cfg)
        store = MultiHostStore(cfg, eps)
        base, deltas = ckpt.recovery_chain()
        if base is not None:
            store.load(base.path, "base")
        for d in deltas:
            store.load(d.path, "delta")
        joiners, jeps = [], []
        for i in range(2, world):
            s, e = start_local_shards(world, cfg)
            joiners.append(s[i])
            jeps.append(e[i])
            stop_shards([srv for j, srv in enumerate(s) if j != i])
        # The fault spec may SIGKILL us inside this call — that is the
        # drill's crash window.
        execute_reshard(eps, eps + jeps,
                        old_ranges=ShardRangeTable.for_world(2),
                        new_ranges=ShardRangeTable.for_world(world))
        store.set_topology(eps + jeps, ShardRangeTable.for_world(world))
        mdir = ckpt.model_dir(DAY, 2)
        store.save_delta(mdir)
        ckpt.publish(DAY, 2)
        out = _digest(servers + joiners)
        stop_shards(servers + joiners)
    elif mode == "recover":
        servers, eps = start_local_shards(world, cfg)
        store = MultiHostStore(cfg, eps)
        store.reset()
        base, deltas = ckpt.recovery_chain()
        if base is not None:
            store.load(base.path, "base")
        for d in deltas:
            store.load(d.path, "delta")
        out = _digest(servers)
        stop_shards(servers)
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    path = os.path.join(root, f"digest_{mode}.json")
    with open(path + ".tmp", "w") as f:
        json.dump(out, f)
    os.replace(path + ".tmp", path)


if __name__ == "__main__":
    main()
